"""Custom pallas kernel tests (interpret mode on CPU; the TPU path shares
the exact same kernel body)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from relora_tpu.ops.pallas_quant_matmul import dequant_matmul
from relora_tpu.ops.quant import dequantize_int8, quantize_int8


def test_dequant_matmul_matches_reference():
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (256, 192))
    w = jax.random.normal(jax.random.fold_in(key, 1), (192, 256)) * 0.1
    q, s = quantize_int8(w)
    want = x @ dequantize_int8(q, s)
    got = dequant_matmul(x, q, s, block_m=128, block_n=128, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_dequant_matmul_batched_and_blocks():
    key = jax.random.PRNGKey(2)
    x = jax.random.normal(key, (2, 4, 128, 64))  # leading batch dims
    w = jax.random.normal(jax.random.fold_in(key, 1), (64, 128)) * 0.05
    q, s = quantize_int8(w)
    want = jnp.einsum("...mk,kn->...mn", x, dequantize_int8(q, s))
    got = dequant_matmul(x, q, s, block_m=256, block_n=128, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_dequant_matmul_grad_matches_reference():
    """jax.grad through the kernel (custom VJP) == grad of dequant-then-matmul."""
    key = jax.random.PRNGKey(3)
    x = jax.random.normal(key, (128, 64))
    w = jax.random.normal(jax.random.fold_in(key, 1), (64, 128)) * 0.1
    q, s = quantize_int8(w)

    def loss_kernel(x, s):
        return jnp.sum(dequant_matmul(x, q, s, block_m=128, block_n=128, interpret=True) ** 2)

    def loss_ref(x, s):
        return jnp.sum((x @ (q.astype(jnp.float32) * s)) ** 2)

    gx, gs = jax.grad(loss_kernel, argnums=(0, 1))(x, s)
    gx_ref, gs_ref = jax.grad(loss_ref, argnums=(0, 1))(x, s)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(gx_ref), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(gs), np.asarray(gs_ref), rtol=1e-4, atol=1e-4)


def test_pallas_quant_train_step_traces(monkeypatch):
    """RELORA_TPU_PALLAS_QUANT=1 must survive jax.grad at trace time (the
    advertised opt-in crashed int8 ReLoRA training before the custom VJP)."""
    monkeypatch.setenv("RELORA_TPU_PALLAS_QUANT", "1")
    from relora_tpu.core.relora import LoraSpec
    from relora_tpu.models.lora import LoRALinear

    import flax.linen as nn

    model = LoRALinear(features=128, lora=LoraSpec(r=4, alpha=8), quantize="int8")
    x = jax.random.normal(jax.random.PRNGKey(0), (16, 64))
    params = nn.meta.unbox(model.init(jax.random.PRNGKey(1), x, deterministic=True))

    frozen = dict(params["params"])
    lora = {k: frozen.pop(k) for k in ("lora_a", "lora_b")}

    def loss(lora_p):
        return jnp.sum(model.apply({"params": {**frozen, **lora_p}}, x, deterministic=True) ** 2)

    g = jax.jit(jax.grad(loss))(lora)
    assert jnp.isfinite(jnp.sum(g["lora_a"]))


def test_dequant_matmul_validation():
    x = jnp.zeros((100, 64))
    q = jnp.zeros((64, 128), jnp.int8)
    s = jnp.ones((1, 128))
    with pytest.raises(ValueError, match="tile"):
        dequant_matmul(x, q, s, block_m=64, block_n=128, interpret=True)
    with pytest.raises(ValueError, match="mismatch"):
        dequant_matmul(jnp.zeros((128, 32)), q, s, interpret=True)
