"""Checkpoint / resume: Orbax-backed sharded state + reference-schema JSON.

Reference checkpoint dir ``model_{update_step}`` holds the HF model files,
``optimizer.pt``, ``relora_config.json`` and ``training_state.json``
(torchrun_main.py:192-225, 256-273).  Here each ``model_{step}`` dir holds:

- ``state/``               — Orbax checkpoint of the full TrainState
  (params + optimizer state + step counters), saved **sharded**: every host
  writes its own shards (the reference funnels everything through rank 0 and
  notes it as a limitation, torchrun_main.py:508).
- ``training_state.json``  — the reference's counter schema, unchanged
  (global_step, update_step, tokens_seen, tokens_seen_before,
  n_lora_restarts, n_optimizer_resets, update_time, wandb_id).
- ``relora_config.json``   — LoraSpec (parity: relora.py:149-152).

Resume modes (parity: §3.5 of SURVEY.md):
- ``autoresume``    — find latest ``model_*`` in save_dir
  (training_utils.py:248-264).
- ``resume_from``   — explicit dir: full state restore.
- ``warmed_up_model`` — weights + counters only, fresh optimizer
  (torchrun_main.py:505-527).
Retention: ``delete_old_checkpoints`` keeps the newest N
(training_utils.py:406-418).

Integrity: each committed checkpoint gets a ``manifest.json`` with per-array
shapes/dtypes (from the in-memory tree at save time) and per-file
size+crc32 (computed at the next fence, once the async write has landed).
``get_last_checkpoint`` verifies the manifest and silently falls back to the
previous committed checkpoint when a dir is truncated or bit-flipped —
a torn write on a preempted host must never poison autoresume.  Save
initiation failures (flaky NFS/GCS mounts) are retried with exponential
backoff before giving up.
"""

from __future__ import annotations

import dataclasses
import json
import os
import shutil
import time
from typing import Any, Mapping, Optional, Tuple

import jax

from relora_tpu.core.relora import LoraSpec
from relora_tpu.utils import faults, integrity
from relora_tpu.utils.logging import get_logger

logger = get_logger(__name__)

PyTree = Any

STATE_SUBDIR = "state"
TRAINING_STATE_FILE = "training_state.json"
RELORA_CONFIG_FILE = "relora_config.json"
MANIFEST_FILE = "manifest.json"


_CKPTR = None


def _checkpointer():
    # one process-wide async checkpointer: StandardCheckpointer is an
    # AsyncCheckpointer — save() returns after the (blocking) device->host
    # copy and writes to disk in a background thread, so the train loop only
    # stalls for the copy, not the serialize+write (SURVEY.md §7: Orbax
    # async).  A singleton keeps one background thread pool and lets
    # wait_for_save() fence all pending writes.
    global _CKPTR
    if _CKPTR is None:
        import orbax.checkpoint as ocp

        _CKPTR = ocp.StandardCheckpointer()
    return _CKPTR


# checkpoint dirs whose async write has been initiated but whose manifest
# (size+crc32 per committed file) cannot be computed until the write lands;
# entries are (path, array_manifest, metadata) finalized at the next fence.
_PENDING_MANIFESTS: list = []


def wait_for_save() -> None:
    """Block until every initiated async checkpoint write has committed."""
    if _CKPTR is not None:
        _CKPTR.wait_until_finished()
    _finalize_pending_manifests()


def checkpoint_dir(save_dir: str, update_step: int) -> str:
    return os.path.join(save_dir, f"model_{update_step}")


def _array_manifest(state: PyTree) -> dict:
    """Per-leaf {shape, dtype} of the in-memory tree being saved — recorded
    *before* serialization so restore-side shape drift is detectable even
    when the files themselves are intact."""
    out = {}
    for keypath, leaf in jax.tree_util.tree_flatten_with_path(state)[0]:
        shape = getattr(leaf, "shape", ())
        dtype = getattr(leaf, "dtype", None)
        out[jax.tree_util.keystr(keypath)] = {
            "shape": list(shape),
            "dtype": str(dtype) if dtype is not None else type(leaf).__name__,
        }
    return out


# file-level crc lives in utils/integrity.py (jax-free) so the deployment
# watcher can verify dirs without an accelerator runtime; re-exported here
# for the manifest writer and existing callers.
_file_crc32 = integrity.file_crc32


def _walk_state_files(path: str) -> dict:
    """{relpath: {size, crc32}} for every file under ``path/state/`` plus the
    sibling JSON files the resume path depends on (and the prune-mask
    sidecar pair, when the checkpoint carries one — pruned zeros are load-
    bearing, so the mask is integrity-checked like the weights)."""
    from relora_tpu.compress.prune import PRUNE_MASK_FILE, PRUNE_META_FILE

    files = {}
    state_path = os.path.join(path, STATE_SUBDIR)
    for root, _, names in os.walk(state_path):
        for name in names:
            full = os.path.join(root, name)
            rel = os.path.relpath(full, path)
            files[rel] = {"size": os.path.getsize(full), "crc32": _file_crc32(full)}
    for name in (TRAINING_STATE_FILE, RELORA_CONFIG_FILE, PRUNE_MASK_FILE, PRUNE_META_FILE):
        full = os.path.join(path, name)
        if os.path.exists(full):
            files[name] = {"size": os.path.getsize(full), "crc32": _file_crc32(full)}
    return files


def _finalize_pending_manifests() -> None:
    """Compute and atomically write ``manifest.json`` for every checkpoint
    whose async write has now committed.  Runs at fences only, so it never
    races the background writer; process 0 writes, matching the JSON files."""
    global _PENDING_MANIFESTS
    if not _PENDING_MANIFESTS:
        return
    pending, _PENDING_MANIFESTS = _PENDING_MANIFESTS, []
    if jax.process_index() != 0:
        return
    for path, arrays, metadata in pending:
        if not os.path.isdir(os.path.join(path, STATE_SUBDIR)):
            logger.warning(f"checkpoint {path} never committed; no manifest written")
            continue
        manifest = {"arrays": arrays, "files": _walk_state_files(path),
                    "metadata": metadata}
        tmp = os.path.join(path, MANIFEST_FILE + ".tmp")
        with open(tmp, "w") as f:
            json.dump(manifest, f, indent=2)
        os.replace(tmp, os.path.join(path, MANIFEST_FILE))
        logger.info(f"checkpoint manifest committed for {path}")
        # deployment hook: only manifest-committed checkpoints are eligible
        # for fleet hot-swap, so the `latest` pointer moves here and nowhere
        # earlier — a watcher that trusts it never sees a torn dir.
        from relora_tpu.serve import deploy

        deploy.publish_latest(os.path.dirname(path) or ".", path)


def verify_checkpoint(path: str, check_arrays: bool = False) -> Tuple[bool, str]:
    """Integrity-check a committed checkpoint dir against its manifest.

    Returns ``(ok, reason)``.  A dir without a manifest is accepted as a
    legacy checkpoint (pre-manifest saves, or a run killed before the
    finalizing fence) — commit-detection via ``state/`` still applies.
    ``check_arrays`` additionally cross-checks recorded shapes/dtypes against
    the Orbax metadata (slower; used by tests and offline tools)."""
    ok, reason = integrity.verify_checkpoint_files(path)
    if not ok:
        return ok, reason
    if check_arrays:
        state_path = os.path.join(path, STATE_SUBDIR)
        manifest_path = os.path.join(path, MANIFEST_FILE)
        try:
            with open(manifest_path) as f:
                manifest = json.load(f)
        except (OSError, json.JSONDecodeError):
            manifest = {}
        import orbax.checkpoint as ocp

        try:
            meta = _metadata_tree(ocp.PyTreeCheckpointer(), os.path.abspath(state_path))
        except Exception as e:  # orbax raises various internal types here
            return False, f"unreadable array metadata: {e}"
        recorded = manifest.get("arrays", {})
        for keypath, leaf in jax.tree_util.tree_flatten_with_path(meta)[0]:
            rec = recorded.get(jax.tree_util.keystr(keypath))
            if rec is None:
                continue  # manifest from an older schema; file checks carried it
            shape = list(getattr(leaf, "shape", ()) or ())
            if rec["shape"] != shape:
                return False, (
                    f"shape mismatch at {jax.tree_util.keystr(keypath)}: "
                    f"{shape} != {rec['shape']}"
                )
    return True, reason


def save_checkpoint(
    save_dir: str,
    update_step: int,
    state: PyTree,
    training_state: dict,
    lora_spec: Optional[LoraSpec] = None,
    retries: int = 3,
    retry_backoff: float = 0.5,
    manifest_metadata: Optional[dict] = None,
) -> str:
    """Write one checkpoint dir; returns its path.  Safe to call from every
    process — Orbax coordinates the multi-host write; JSON goes from
    process 0 only.

    Save *initiation* (clearing a stale dir, the device->host copy, the JSON
    sidecars) is retried ``retries`` times with exponential backoff — these
    are the synchronous touchpoints where a flaky filesystem surfaces.  A
    failure of the *background* write is caught downstream instead: the dir
    never gains a committed ``state/`` (or fails manifest verification) and
    autoresume skips it.

    ``manifest_metadata`` lands under the manifest's ``metadata`` key.  When
    not given it is derived from the current mesh (mesh shape, chip count,
    partition-rule version) so ``train/elastic.py`` can validate a reshard
    target and ``restore_serving_params`` can reject a rule-mismatched dir."""
    path = checkpoint_dir(save_dir, update_step)
    ckptr = _checkpointer()
    # fence the previous in-flight save (usually a no-op: saves are far
    # apart), then initiate this one — save() returns after the d2h copy,
    # the disk write proceeds in the background.  Orbax writes to a tmp dir
    # and renames on commit, so ``state/`` appears atomically.
    ckptr.wait_until_finished()
    _finalize_pending_manifests()
    state_path = os.path.abspath(os.path.join(path, STATE_SUBDIR))
    for attempt in range(retries + 1):
        try:
            faults.maybe_fail("ckpt_save")
            os.makedirs(path, exist_ok=True)
            if os.path.exists(state_path):
                shutil.rmtree(state_path)
            ckptr.save(state_path, state)
            if jax.process_index() == 0:
                with open(os.path.join(path, TRAINING_STATE_FILE), "w") as f:
                    json.dump(training_state, f, indent=2)
                if lora_spec is not None:
                    with open(os.path.join(path, RELORA_CONFIG_FILE), "w") as f:
                        json.dump(dataclasses.asdict(lora_spec), f, indent=2)
            break
        except (OSError, ValueError) as e:
            # don't leave a background write racing the retry's rmtree
            ckptr.wait_until_finished()
            if attempt >= retries:
                logger.error(
                    f"checkpoint save at step {update_step} failed after "
                    f"{retries + 1} attempts: {e}"
                )
                raise
            delay = retry_backoff * (2**attempt)
            logger.warning(
                f"checkpoint save attempt {attempt + 1}/{retries + 1} failed "
                f"({e}); retrying in {delay:.1f}s"
            )
            time.sleep(delay)
    if manifest_metadata is None:
        from relora_tpu.parallel.mesh import current_mesh, mesh_metadata

        manifest_metadata = mesh_metadata(current_mesh())
    _PENDING_MANIFESTS.append((path, _array_manifest(state), manifest_metadata))
    logger.info(f"Saving checkpoint to {path} (async)")
    return path


def restore_checkpoint(path: str, abstract_state: PyTree) -> PyTree:
    """Restore a TrainState saved by ``save_checkpoint``.

    ``abstract_state`` — e.g. ``jax.eval_shape(lambda: state)`` with sharding
    annotations — tells Orbax the target shapes/shardings, so restore places
    shards directly on the mesh."""
    ckptr = _checkpointer()
    ckptr.wait_until_finished()  # same-process restore right after a save
    _finalize_pending_manifests()
    return ckptr.restore(os.path.abspath(os.path.join(path, STATE_SUBDIR)), abstract_state)


def restore_state_host(path: str) -> PyTree:
    """Template-free restore of the full saved state as host numpy arrays.

    Works regardless of the current device topology (every leaf is forced to
    numpy instead of the recorded shardings) — for warm starts and offline
    tools."""
    import numpy as np
    import orbax.checkpoint as ocp

    wait_for_save()  # same-process restore right after a save
    state_path = os.path.abspath(os.path.join(path, STATE_SUBDIR))
    if not os.path.isdir(state_path):
        raise FileNotFoundError(f"no checkpoint state at {state_path}")
    ckptr = ocp.PyTreeCheckpointer()
    meta_tree = _metadata_tree(ckptr, state_path)
    restore_args = jax.tree_util.tree_map(
        lambda _: ocp.RestoreArgs(restore_type=np.ndarray), meta_tree
    )
    return ckptr.restore(state_path, restore_args=restore_args)


def _metadata_tree(ckptr, state_path: str) -> PyTree:
    """Per-leaf metadata pytree of a saved checkpoint, across orbax versions
    (newer orbax wraps it in ``.item_metadata.tree``; 0.7.x returns the tree
    directly)."""
    meta = ckptr.metadata(state_path)
    item_metadata = getattr(meta, "item_metadata", None)
    if item_metadata is not None:
        tree = getattr(item_metadata, "tree", item_metadata)
        if tree is None:
            raise FileNotFoundError(f"checkpoint at {state_path} has no readable metadata")
        return tree
    if meta is None:
        raise FileNotFoundError(f"checkpoint at {state_path} has no readable metadata")
    return meta


def restore_params_host(path: str) -> PyTree:
    """Just the params subtree of ``restore_state_host`` (the saved tree —
    e.g. full-rank with its own optimizer — may deliberately differ from the
    new run's state shape)."""
    restored = restore_state_host(path)
    if isinstance(restored, Mapping) and "params" in restored:
        return restored["params"]
    return restored


def restore_serving_params(path: str) -> PyTree:
    """Params ready for inference: the checkpoint's param tree with LoRA
    factors merged into the base kernels when (and only when) they are
    present.

    Handles all three checkpoint flavors the serve path meets: a full-rank
    run (no ``relora_config.json``), a live ReLoRA run (factors present —
    merge via the saved spec), and an exported/already-merged tree that still
    carries its ``relora_config.json`` sidecar (no ``lora_a`` leaves — the
    merge walk passes it through unchanged instead of KeyError-ing).

    Every call — serve startup and every in-place reload — verifies the
    size+crc32 manifest first, so a torn or bit-flipped checkpoint is
    rejected (with the failing file named) before any device write.  A
    manifest recorded under a *different partition-rule version* is rejected
    too (reason ``partition_rule_mismatch``): the serving merge walks the
    tree by logical-axis names, so a rule-table drift means the arrays may
    not mean what the walk assumes.  Chip count and mesh shape are allowed
    to differ — serving restores host-side and replaces the layout anyway."""
    ok, reason = verify_checkpoint(path)
    if not ok:
        raise ValueError(f"refusing to serve corrupt checkpoint {path}: {reason}")
    meta = load_manifest_metadata(path)
    if meta is not None and "partition_rule_version" in meta:
        from relora_tpu.parallel.mesh import partition_rule_version

        want = partition_rule_version()
        got = meta["partition_rule_version"]
        if got != want:
            raise ValueError(
                f"refusing to serve checkpoint {path}: partition_rule_mismatch "
                f"(checkpoint rules {got}, runtime rules {want})"
            )
    params = restore_params_host(path)
    spec = load_lora_spec(path)
    if spec is None:
        return params
    from relora_tpu.core.relora import merged_params

    return merged_params(params, spec)


def load_manifest_metadata(path: str) -> Optional[dict]:
    """The manifest's ``metadata`` block (mesh shape, chip count,
    partition-rule version) for a checkpoint dir.  ``None`` for legacy
    checkpoints whose manifest predates the key — callers must treat those
    as unverifiable rather than mismatched."""
    manifest_path = os.path.join(path, MANIFEST_FILE)
    try:
        with open(manifest_path) as f:
            manifest = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    meta = manifest.get("metadata")
    return meta if isinstance(meta, dict) else None


def load_training_state(path: str) -> dict:
    with open(os.path.join(path, TRAINING_STATE_FILE)) as f:
        return json.load(f)


def load_lora_spec(path: str) -> Optional[LoraSpec]:
    p = os.path.join(path, RELORA_CONFIG_FILE)
    if not os.path.exists(p):
        return None
    with open(p) as f:
        return LoraSpec(**json.load(f))


def get_last_checkpoint(
    save_dir: str, before_step: Optional[int] = None
) -> Tuple[Optional[dict], Optional[str]]:
    """Find the newest *verified* ``model_{step}`` dir and its
    training_state.json (parity: training_utils.get_last_training_state
    :248-264).

    Candidates are tried newest-first; a dir that fails manifest
    verification or has an unreadable ``training_state.json`` is skipped
    with a warning and the previous committed checkpoint is used instead —
    a half-written or bit-flipped checkpoint must degrade resume, not break
    it.  ``before_step`` restricts the search to checkpoints with step
    strictly below it (the spike-rollback path: the spike's own checkpoint
    is not a valid rollback target)."""
    if not os.path.isdir(save_dir):
        return None, None
    dirs = _committed_checkpoints(save_dir)
    if before_step is not None:
        dirs = [d for d in dirs if int(d.split("_")[-1]) < before_step]
    if not dirs:
        logger.warning(f"Save directory {save_dir} exists but has no checkpoints; starting fresh")
        return None, None
    for d in reversed(dirs):
        path = os.path.join(save_dir, d)
        ok, reason = verify_checkpoint(path)
        if not ok:
            logger.warning(f"Skipping corrupt checkpoint {path}: {reason}")
            continue
        try:
            return load_training_state(path), path
        except (OSError, json.JSONDecodeError, KeyError) as e:
            logger.warning(f"Skipping checkpoint {path} with unreadable training state: {e}")
    logger.warning(
        f"Save directory {save_dir} has checkpoints but none passed verification; starting fresh"
    )
    return None, None


def _committed_checkpoints(save_dir: str) -> list:
    """``model_*`` dirs with a committed ``state/`` (Orbax renames the tmp dir
    into place on commit), sorted by step.  An async save that died mid-write
    leaves the JSON but no ``state/`` — those are invisible to both the
    autoresume probe and retention."""
    dirs = [
        d
        for d in os.listdir(save_dir)
        if d.startswith("model_")
        and os.path.isdir(os.path.join(save_dir, d, STATE_SUBDIR))
    ]
    dirs.sort(key=lambda d: int(d.split("_")[-1]))
    return dirs


def delete_old_checkpoints(save_dir: str, keep: Optional[int]) -> None:
    """Keep the newest N checkpoint dirs (parity: training_utils.py:406-418).

    Only *committed* checkpoints (renamed ``state/`` present) count toward
    the keep budget and are eligible for deletion — with async saves the
    newest dir may still be in flight, and pruning the last committed one
    against it would leave nothing restorable if the process dies before
    the write commits."""
    if keep is None or jax.process_index() != 0:
        return
    dirs = _committed_checkpoints(save_dir)
    if len(dirs) <= keep:
        return
    for d in dirs[:-keep]:
        full = os.path.join(save_dir, d)
        logger.info(f"Deleting old checkpoint {full}")
        shutil.rmtree(full)
