"""SLO-driven elastic scaling of the serving fleet.

The serving tier runs N replica processes behind the rendezvous router
(supervisor.py).  N is a cost/latency dial: too few replicas and TTFT
burns through its SLO budget under load; too many and idle chips bill for
nothing.  This module closes the loop — the same fleet observability plane
that pages a human (obs/fleet.py's SeriesStore) drives replica count.

Split, like deploy.py, into a *policy* and an *executor*:

- :class:`AutoscalerPolicy` is pure decision logic over a SeriesStore: it
  reads the collector's derived per-replica series (TTFT p95 from the
  scraped histogram, ``healthz_queue_depth``, active-slot utilization from
  ``healthz_active_slots / healthz_max_batch``) and returns a
  :class:`Decision` — scale up, scale down, or hold, always with a named
  reason.  Flap resistance is structural, not tuned: a scale-up needs the
  *whole* burn window saturated on every replica, a scale-down needs the
  whole (longer) idle window quiet on every replica, and any action starts
  a cooldown during which the policy holds.
- :class:`Autoscaler` is the executor thread: every ``interval_s`` it asks
  the policy, then acts through the supervisor's scale levers
  (``scale_up`` / ``scale_down`` — serialized with the rolling drain
  behind the supervisor's scale lock).  It additionally refuses to stack
  scale-ups while the newest replica is still warming (``up == 0`` in the
  store: a cold replica answers ``healthz`` 503 "warming" until its
  compile buckets are paid), because capacity that cannot be routed to
  yet must not count as capacity.

Every decision that acts — and every hold for a *new* reason — lands in
the SeriesStore as an ``autoscale_decision`` event next to the
supervisor's ``autoscale_up``/``autoscale_down_complete`` lifecycle
events, so ``fleet_report`` renders the whole elastic history.  The
executor also samples ``replicas_live`` under the ``autoscaler`` source:
the replica-count-over-time series the report and bench plot.

Tuning guidance and the flapping/stuck-at-max runbooks live in
docs/operations.md.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

from relora_tpu.utils.logging import get_logger

logger = get_logger(__name__)

#: the collector's derived series this policy reads (per replica source)
TTFT_P95_SERIES = "relora_serve_ttft_seconds_p95"
QUEUE_DEPTH_SERIES = "healthz_queue_depth"
ACTIVE_SLOTS_SERIES = "healthz_active_slots"
MAX_BATCH_SERIES = "healthz_max_batch"
UP_SERIES = "up"


@dataclasses.dataclass
class Decision:
    """One policy evaluation: ``action`` is ``"up"``, ``"down"``, or
    ``"hold"``; ``reason`` is a named, greppable cause; ``metrics`` carries
    the numbers the decision was made on (for the event detail)."""

    action: str
    reason: str
    metrics: Dict[str, float] = dataclasses.field(default_factory=dict)


class AutoscalerPolicy:
    """Hysteresis-banded scaling policy over the fleet SeriesStore.

    A replica is **burning** when, for at least one pressure signal, every
    sample in the last ``burn_window_s`` breaches its high-water mark
    (TTFT p95 over ``ttft_p95_target_s``, queue depth over
    ``queue_depth_high``, or slot utilization over ``slot_util_high``) —
    with at least ``min_samples`` samples, so a single hot scrape never
    scales the fleet.  The fleet scales up only when *every* live replica
    is burning: one hot tenant pinned to one replica is a routing story,
    uniform saturation is a capacity story.

    A replica is **idle** when every sample in the last ``idle_window_s``
    sits under the low-water marks (``queue_depth_low``,
    ``slot_util_low``).  The fleet scales down only when every replica is
    idle for the whole window — the idle window is deliberately longer
    than the burn window so capacity leaves slower than it arrives.

    Any action arms a ``cooldown_s`` hold, so consecutive decisions see
    the *effect* of the previous one instead of re-firing on the same
    stale pressure.
    """

    def __init__(
        self,
        *,
        min_replicas: int = 1,
        max_replicas: int = 4,
        ttft_p95_target_s: float = 2.0,
        queue_depth_high: float = 4.0,
        slot_util_high: float = 0.9,
        queue_depth_low: float = 0.5,
        slot_util_low: float = 0.5,
        burn_window_s: float = 5.0,
        idle_window_s: float = 15.0,
        cooldown_s: float = 10.0,
        min_samples: int = 3,
    ):
        if min_replicas < 1:
            raise ValueError(f"min_replicas must be >= 1, got {min_replicas}")
        if max_replicas < min_replicas:
            raise ValueError(
                f"max_replicas ({max_replicas}) < min_replicas ({min_replicas})"
            )
        self.min_replicas = min_replicas
        self.max_replicas = max_replicas
        self.ttft_p95_target_s = ttft_p95_target_s
        self.queue_depth_high = queue_depth_high
        self.slot_util_high = slot_util_high
        self.queue_depth_low = queue_depth_low
        self.slot_util_low = slot_util_low
        self.burn_window_s = burn_window_s
        self.idle_window_s = idle_window_s
        self.cooldown_s = cooldown_s
        self.min_samples = min_samples
        self._last_scale_t: Optional[float] = None

    # -- cooldown ------------------------------------------------------------

    def note_scaled(self, now: Optional[float] = None) -> None:
        """The executor applied an action; start the cooldown clock."""
        self._last_scale_t = time.time() if now is None else now

    def in_cooldown(self, now: Optional[float] = None) -> bool:
        if self._last_scale_t is None:
            return False
        now = time.time() if now is None else now
        return (now - self._last_scale_t) < self.cooldown_s

    # -- signal extraction ---------------------------------------------------

    def _slot_util(self, store, source: str, window_s: float, now: float) -> List[float]:
        active = store.window_values(source, ACTIVE_SLOTS_SERIES, window_s, now=now)
        latest_mb = store.latest(source, MAX_BATCH_SERIES)
        if not active or latest_mb is None or latest_mb[1] <= 0:
            return []
        max_batch = latest_mb[1]
        return [a / max_batch for a in active]

    def _burning(self, store, source: str, now: float) -> Optional[str]:
        """The signal name sustaining a burn on ``source``, else None."""
        w = self.burn_window_s
        ttft = store.window_values(source, TTFT_P95_SERIES, w, now=now)
        if len(ttft) >= self.min_samples and all(v > self.ttft_p95_target_s for v in ttft):
            return "ttft_p95"
        queue = store.window_values(source, QUEUE_DEPTH_SERIES, w, now=now)
        if len(queue) >= self.min_samples and all(v > self.queue_depth_high for v in queue):
            return "queue_depth"
        util = self._slot_util(store, source, w, now)
        if len(util) >= self.min_samples and all(v > self.slot_util_high for v in util):
            return "slot_utilization"
        return None

    def _idle(self, store, source: str, now: float) -> bool:
        w = self.idle_window_s
        queue = store.window_values(source, QUEUE_DEPTH_SERIES, w, now=now)
        if len(queue) < self.min_samples or any(v > self.queue_depth_low for v in queue):
            return False
        util = self._slot_util(store, source, w, now)
        # no slot data yet → not provably idle; short data is fine for util
        # (queue depth already proved the window), but a breach is not
        return not any(v > self.slot_util_low for v in util)

    # -- the decision --------------------------------------------------------

    def decide(
        self,
        store,
        sources: Sequence[str],
        n_live: int,
        now: Optional[float] = None,
    ) -> Decision:
        """Evaluate the fleet: ``sources`` are the replica rids to read,
        ``n_live`` the capacity-bearing replica count (the supervisor's
        view, which includes a replica mid-backoff the store has marked
        down)."""
        now = time.time() if now is None else now
        if self.in_cooldown(now):
            return Decision("hold", "cooldown", {"n_live": n_live})
        if not sources:
            return Decision("hold", "no_replicas", {"n_live": n_live})

        burning = {s: self._burning(store, s, now) for s in sources}
        signals = {s: b for s, b in burning.items() if b is not None}
        if signals and len(signals) == len(sources):
            if n_live >= self.max_replicas:
                return Decision(
                    "hold",
                    "at_max_replicas",
                    {"n_live": n_live, "max_replicas": self.max_replicas},
                )
            return Decision(
                "up",
                f"sustained_burn ({'/'.join(sorted(set(signals.values())))})",
                {"n_live": n_live, "burning_replicas": len(signals)},
            )

        if all(self._idle(store, s, now) for s in sources):
            if n_live <= self.min_replicas:
                return Decision(
                    "hold",
                    "at_min_replicas",
                    {"n_live": n_live, "min_replicas": self.min_replicas},
                )
            return Decision("down", "sustained_idle", {"n_live": n_live})

        reason = "partial_burn" if signals else "steady"
        return Decision(
            "hold", reason, {"n_live": n_live, "burning_replicas": len(signals)}
        )


class Autoscaler:
    """Executor thread: policy decisions become supervisor scale actions.

    ``supervisor`` needs the ReplicaSupervisor surface (``endpoints``,
    ``n_live``, ``scale_up``, ``scale_down``); ``store`` is the collector's
    SeriesStore.  Tests drive :meth:`step` directly with a scripted policy
    — the thread is just ``step`` on a cadence."""

    def __init__(
        self,
        policy: AutoscalerPolicy,
        supervisor,
        store,
        *,
        interval_s: float = 1.0,
        emit: Optional[Callable[[str, Optional[int], Dict], None]] = None,
    ):
        self.policy = policy
        self.supervisor = supervisor
        self.store = store
        self.interval_s = interval_s
        self.emit = emit  # (event, replica_idx, detail) — the supervisor CLI's sink
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_hold_reason: Optional[str] = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "Autoscaler":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(
            target=self._loop, name="autoscaler", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.step()
            except Exception as e:  # the fleet outlives a bad evaluation
                logger.warning(f"autoscaler step failed: {e}")

    # -- one evaluation ------------------------------------------------------

    def _event(self, decision: Decision) -> None:
        detail = {"action": decision.action, "reason": decision.reason}
        detail.update(decision.metrics)
        self.store.add_event("autoscale_decision", "autoscaler", **detail)
        if self.emit is not None:
            try:
                self.emit("autoscale_decision", None, detail)
            except Exception:
                pass

    def _warming_replica(self, sources: Sequence[str]) -> Optional[str]:
        """A replica the router cannot use yet (``up == 0`` in the store —
        cold warmup, rebinding after restart, or mid-backoff)."""
        for source in sources:
            latest = self.store.latest(source, UP_SERIES)
            if latest is not None and latest[1] < 1.0:
                return source
        return None

    def step(self, now: Optional[float] = None) -> Decision:
        now = time.time() if now is None else now
        sources = sorted(self.supervisor.endpoints().keys())
        n_live = self.supervisor.n_live()
        self.store.add_sample("autoscaler", "replicas_live", float(n_live), t=now)
        decision = self.policy.decide(self.store, sources, n_live, now=now)

        if decision.action == "up":
            warming = self._warming_replica(sources)
            if warming is not None:
                # the last scale-up has not finished warming: adding another
                # replica now would double-provision for one burn
                decision = Decision(
                    "hold",
                    "replica_warming",
                    {**decision.metrics, "warming": warming},
                )

        if decision.action == "hold":
            if decision.reason != self._last_hold_reason:
                self._event(decision)
            self._last_hold_reason = decision.reason
            return decision
        self._last_hold_reason = None
        self._event(decision)

        if decision.action == "up":
            rid = self.supervisor.scale_up()
            if rid is None:
                return Decision("hold", "scale_up_cancelled", decision.metrics)
            self.policy.note_scaled(now)
            logger.info(f"autoscale: {decision.reason} -> added {rid}")
        elif decision.action == "down":
            rid = self.supervisor.scale_down()
            if rid is None:
                return Decision("hold", "scale_down_refused", decision.metrics)
            self.policy.note_scaled(now)
            logger.info(f"autoscale: {decision.reason} -> drained {rid}")
        return decision
