"""GLUE harness tests on synthetic data (no network): metrics correctness,
classification model pooling, end-to-end fine-tune learns a separable task,
pretrained-backbone grafting."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from relora_tpu.config.model import ModelConfig
from relora_tpu.eval.glue import (
    GlueConfig,
    accuracy,
    classification_loss,
    f1_binary,
    finetune,
    matthews_corr,
    pearson_corr,
    spearman_corr,
    task_metrics,
)
from relora_tpu.models.llama import LlamaForSequenceClassification
from relora_tpu.models.params_util import init_params

TINY = ModelConfig(
    vocab_size=64,
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=2,
    num_attention_heads=2,
    max_sequence_length=32,
)


def test_metrics():
    p = np.asarray([1, 0, 1, 1, 0, 1])
    l = np.asarray([1, 0, 0, 1, 0, 1])
    assert accuracy(p, l) == pytest.approx(5 / 6)
    assert 0 < f1_binary(p, l) <= 1
    assert -1 <= matthews_corr(p, l) <= 1
    a = np.asarray([1.0, 2.0, 3.0, 4.0])
    assert pearson_corr(a, 2 * a + 1) == pytest.approx(1.0)
    assert spearman_corr(a, a**3) == pytest.approx(1.0)  # monotone
    m = task_metrics("cola", p, l)
    assert "matthews_correlation" in m
    m = task_metrics("mrpc", p, l)
    assert set(m) == {"accuracy", "f1"}
    m = task_metrics("stsb", a, 2 * a)
    assert m["pearson"] == pytest.approx(1.0)


def test_classification_pooling_ignores_padding():
    model = LlamaForSequenceClassification(TINY, num_labels=2, pad_token_id=0, dtype=jnp.float32)
    params = init_params(model, jax.random.PRNGKey(0), jnp.zeros((2, 8), jnp.int32))
    # same content, different padding amounts -> same logits
    a = jnp.asarray([[5, 6, 7, 0, 0, 0, 0, 0]], jnp.int32)
    b = jnp.asarray([[5, 6, 7, 0, 0]], jnp.int32)
    la = model.apply({"params": params}, a)
    lb = model.apply({"params": params}, b)
    np.testing.assert_allclose(np.asarray(la), np.asarray(lb), atol=1e-5)


def test_classification_loss_modes():
    logits = jnp.asarray([[2.0, -1.0], [0.0, 3.0]])
    labels = jnp.asarray([0, 1])
    ce = classification_loss(logits, labels, num_labels=2)
    assert float(ce) < 0.1
    reg = classification_loss(jnp.asarray([[1.5], [2.5]]), jnp.asarray([1.0, 3.0]), num_labels=1)
    assert float(reg) == pytest.approx(0.25)


@pytest.mark.slow
def test_finetune_learns_synthetic_task():
    """Token 1 at position 0 ⇒ label 1: a linearly separable 'task' the tiny
    model must crack in a few epochs; also exercises backbone grafting."""
    rs = np.random.RandomState(0)

    def make(n):
        ids = rs.randint(2, 64, size=(n, 12)).astype(np.int32)
        labels = rs.randint(0, 2, size=n)
        ids[:, 0] = np.where(labels == 1, 1, 2)
        return ids, labels

    train_ids, train_labels = make(256)
    eval_ids, eval_labels = make(64)
    bs = 32
    steps = len(train_ids) // bs

    def train_batches():
        order = rs.permutation(len(train_ids))
        for i in range(steps):
            sel = order[i * bs : (i + 1) * bs]
            yield train_ids[sel], train_labels[sel]

    def eval_batches():
        for i in range(0, len(eval_ids), bs):
            yield eval_ids[i : i + bs], eval_labels[i : i + bs]

    # a fake "pretrained" causal-LM tree to graft (random but well-formed)
    from relora_tpu.models.llama import LlamaForCausalLM

    lm = LlamaForCausalLM(TINY, dtype=jnp.float32)
    lm_params = init_params(lm, jax.random.PRNGKey(5), jnp.zeros((1, 8), jnp.int32))

    gcfg = GlueConfig(task="sst2", lr=5e-3, batch_size=bs, num_epochs=4, seed=0)
    metrics, _ = finetune(
        TINY,
        gcfg,
        train_batches,
        eval_batches,
        steps,
        pad_token_id=0,
        pretrained_backbone=lm_params,
    )
    assert metrics["accuracy"] > 0.9


@pytest.mark.slow
def test_finetune_with_lora():
    """GLUE fine-tuning with LoRA adapters on the classifier backbone."""
    rs = np.random.RandomState(1)

    def make(n):
        ids = rs.randint(2, 64, size=(n, 10)).astype(np.int32)
        labels = rs.randint(0, 2, size=n)
        ids[:, 0] = np.where(labels == 1, 1, 2)
        return ids, labels

    train_ids, train_labels = make(128)
    bs = 32
    steps = len(train_ids) // bs

    def batches():
        for i in range(steps):
            yield train_ids[i * bs:(i + 1) * bs], train_labels[i * bs:(i + 1) * bs]

    gcfg = GlueConfig(task="sst2", lr=8e-3, batch_size=bs, num_epochs=4,
                      use_lora=True, lora_r=4, seed=1)
    metrics, _ = finetune(TINY, gcfg, batches, batches, steps, pad_token_id=0)
    assert metrics["accuracy"] > 0.8


@pytest.mark.slow
def test_finetune_regression_stsb_path():
    """num_labels==1 regression: model learns a linear score of token id."""
    rs = np.random.RandomState(2)
    ids = rs.randint(2, 64, size=(192, 8)).astype(np.int32)
    # score determined by the last token (the pooled position)
    labels = (ids[:, -1] / 64.0) * 5.0
    bs = 32
    steps = len(ids) // bs

    def batches():
        for i in range(steps):
            yield ids[i * bs:(i + 1) * bs], labels[i * bs:(i + 1) * bs]

    gcfg = GlueConfig(task="stsb", lr=1e-2, batch_size=bs, num_epochs=8, seed=2)
    metrics, _ = finetune(TINY, gcfg, batches, batches, steps, pad_token_id=0)
    # the 2-layer toy model learns the signal only partially; the point is
    # exercising the MSE/regression path end-to-end
    assert metrics["pearson"] > 0.5 and metrics["spearmanr"] > 0.5
