"""Dryrun a production sharding at REAL tensor shapes on virtual CPU devices.

BASELINE configs 3-5 (1B r=128 FSDP on v4-32; 1B magnitude-pruning; 7B r=256
on v5p-64, frozen base sharded + LoRA replicated) can't run on this sandbox's
single chip — but their *shardings* can: XLA's CPU backend carves one host
into N virtual devices (``--xla_force_host_platform_device_count``), and the
GSPMD partitioner sees exactly the shapes it would see on the pod.

This tool jits the full sharded train step + the jitted merge at real
hidden/vocab dims (layer count reduced — depth repeats the same sharded
layer, so 2 scanned layers exercise every partition decision 32 would), then
measures what actually landed on device 0 — bytes of frozen base, trainable
params, and Adam moments, read from the live arrays' addressable shards —
and asserts each against tools/plan_memory.plan()'s analytic prediction.

    python tools/dryrun_at_shape.py --model llama_1b --rank 128 --mesh fsdp=16 \
        --layers 2 --seq 256 --chip v4
    python tools/dryrun_at_shape.py --model llama_7b --rank 256 \
        --mesh fsdp=8,tensor=4 --layers 2 --seq 256 --chip v5p

The core (``run_at_shape``) is importable and assumes jax is already up —
``__graft_entry__.dryrun_multichip`` runs it per round so the driver's
multichip artifact certifies the at-shape claim, not just a toy-shape smoke
(round-3 verdict).  ``main()`` adds the env setup needed for standalone use.

Reference configs: training_configs/1B_v1.0.yaml; BASELINE.json configs 3-5.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
from typing import Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

COLLECTIVE_FLAGS = (
    # real-dim shards on few host cores serialize device threads; the CPU
    # collective rendezvous hard-aborts at 40s by default — give the
    # virtual pod time to arrive
    " --xla_cpu_collective_call_warn_stuck_timeout_seconds=600"
    " --xla_cpu_collective_call_terminate_timeout_seconds=1200"
    " --xla_cpu_collective_timeout_seconds=1200"
)


def run_at_shape(
    model: str = "llama_1b",
    rank: int = 128,
    mesh_str: str = "fsdp=16",
    layers: int = 2,
    micro_batch: int = 0,
    seq: int = 256,
    chip: str = "v4",
    magnitude_reset: bool = False,
    attn: str = "auto",
    tolerance: float = 0.06,
    quantize: Optional[str] = None,
) -> dict:
    """Jit + run the full sharded train step at real dims and assert the
    measured per-device bytes against the analytic plan.  Requires jax to be
    initialized with enough devices for ``mesh_str``; returns the result
    dict (key ``ok``) with per-component measured/planned GB."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from relora_tpu.config.model import MODEL_ZOO
    from relora_tpu.core.optim import (
        build_optimizer,
        init_opt_state_sharded,
        reset_optimizer_state,
    )
    from relora_tpu.core.partition import partition
    from relora_tpu.core.relora import (
        LoraSpec,
        frozen_param_mask,
        merge_and_reinit,
        trainable_param_mask,
    )
    from relora_tpu.models.llama import LlamaForCausalLM
    from relora_tpu.models.params_util import init_params, logical_partition_specs
    from relora_tpu.parallel.mesh import (
        MeshSpec,
        batch_sharding,
        make_mesh,
        param_shardings,
        set_current_mesh,
        shard_params,
    )
    from relora_tpu.train.state import TrainState
    from relora_tpu.train.step import make_train_step
    from tools.plan_memory import parse_mesh, plan

    factors = parse_mesh(mesh_str)
    n_devices = math.prod(factors.values())
    devices = jax.devices()[:n_devices]
    assert len(devices) == n_devices, f"need {n_devices} devices, got {len(jax.devices())}"
    mesh = make_mesh(
        MeshSpec(
            data=factors.get("data", 1),
            fsdp=factors.get("fsdp", 1),
            tensor=factors.get("tensor", 1),
            sequence=factors.get("sequence", 1),
        ),
        devices=devices,
    )
    set_current_mesh(mesh)

    cfg = dataclasses.replace(MODEL_ZOO[model], num_hidden_layers=layers)
    spec = LoraSpec(r=rank, alpha=32, dropout=0.0, quantize=quantize)
    mdl = LlamaForCausalLM(
        cfg, lora=spec, dtype=jnp.bfloat16, scan_layers=True,
        attention_impl=attn,
    )

    batch_div = factors.get("data", 1) * factors.get("fsdp", 1)
    micro = micro_batch or batch_div
    sample = jnp.zeros((batch_div, 8 * factors.get("sequence", 1)), jnp.int32)
    params = init_params(mdl, jax.random.PRNGKey(0), sample)
    mask = trainable_param_mask(params)
    tx = build_optimizer(schedule=lambda s: 1e-3)

    shardings = param_shardings(mesh, logical_partition_specs(mdl, sample))
    params = shard_params(params, shardings)
    with mesh:
        opt_state = init_opt_state_sharded(tx, partition(params, mask)[0], mesh)
    state = TrainState.create(params, opt_state)

    dev0 = devices[0]

    def bytes_on_dev0(tree) -> int:
        total = 0
        for leaf in jax.tree_util.tree_leaves(tree):
            if not hasattr(leaf, "addressable_shards"):
                continue
            for shard in leaf.addressable_shards:
                if shard.device == dev0:
                    total += shard.data.size * shard.data.dtype.itemsize
        return total

    def measure(params, opt_state) -> dict:
        frozen = frozen_param_mask(params)
        frozen_tree = jax.tree_util.tree_map(
            lambda p, f: p if f else None, params, frozen
        )
        trainable_tree = jax.tree_util.tree_map(
            lambda p, f: None if f else p, params, frozen
        )
        return {
            "frozen_params": bytes_on_dev0(frozen_tree) / 1e9,
            "trainable_params": bytes_on_dev0(trainable_tree) / 1e9,
            "adam_moments": bytes_on_dev0(opt_state) / 1e9,
        }

    # measure against the ANNOTATED shardings, BEFORE the step donates the
    # buffers: the jitted step is free to propagate tighter output shardings
    # than the input annotations (observed: −16% trainable bytes at 7B
    # fsdp=8,tensor=4), which is a win to report, not an assertion target
    jax.block_until_ready(state.params)
    measured = measure(state.params, state.opt_state)

    step = jax.jit(make_train_step(mdl, tx, mask), donate_argnums=0)
    batch = jax.device_put(
        jax.random.randint(
            jax.random.PRNGKey(1), (1, micro, seq), 0, cfg.vocab_size
        ),
        batch_sharding(mesh, seq_sharded=factors.get("sequence", 1) > 1),
    )
    state, metrics = step(state, batch, jax.random.PRNGKey(2))
    loss = float(metrics["loss"])
    assert math.isfinite(loss), f"non-finite loss {loss}"

    # the defining ReLoRA ops, jitted over the same sharded tree at shape
    merged = jax.jit(lambda p, k: merge_and_reinit(p, k, spec))(
        state.params, jax.random.PRNGKey(3)
    )
    jax.block_until_ready(merged)
    if magnitude_reset:
        reset = jax.jit(
            lambda s: reset_optimizer_state(s, mode="magnitude", ratio=0.9)
        )(state.opt_state)
        jax.block_until_ready(reset)

    # post-step shardings (informational: whatever GSPMD propagated)
    after_step = measure(state.params, state.opt_state)

    predicted = {
        k: v / 1e9
        for k, v in plan(
            model,
            rank=rank,
            mesh=mesh_str,
            micro_batch=micro,
            seq=seq,
            chip=chip,
            layers=layers,
            quantize=quantize,
        )["per_device_bytes"].items()
    }

    failures = []
    for key, got in measured.items():
        want = predicted[key]
        rel = abs(got - want) / max(want, 1e-9)
        if rel > tolerance:
            failures.append(f"{key}: measured {got:.4f} GB vs planned {want:.4f} GB")
    return {
        "model": model,
        "mesh": mesh_str,
        "layers": layers,
        "seq": seq,
        "attn": attn,
        "quantize": quantize,
        "loss": round(loss, 4),
        "measured_dev0_gb": {k: round(v, 4) for k, v in measured.items()},
        "after_step_dev0_gb": {k: round(v, 4) for k, v in after_step.items()},
        "planned_dev0_gb": {k: predicted[k] for k in measured},
        "full_depth_plan_gb": plan(
            model, rank=rank, mesh=mesh_str, chip=chip, quantize=quantize
        )["per_device_gb"]["total"],
        "ok": not failures,
        "failures": failures,
    }


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="llama_1b")
    p.add_argument("--rank", type=int, default=128)
    p.add_argument("--mesh", default="fsdp=16")
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--micro-batch", type=int, default=0, help="0 = data*fsdp")
    p.add_argument("--seq", type=int, default=256)
    p.add_argument("--chip", default="v4")
    p.add_argument("--magnitude-reset", action="store_true")
    p.add_argument("--quantize", default=None, choices=["int8", "nf4"],
                   help="quantized frozen base: certifies the memory-win "
                        "claim at real dims (measured vs planned bytes)")
    p.add_argument(
        "--attn",
        default="auto",
        # ring_zigzag is deliberately absent: it needs the train step's
        # zigzag input permutation (train/step.py), which this tool
        # doesn't wire — accepting it would silently compute garbage
        choices=["auto", "xla", "pallas", "ring", "ulysses", "naive"],
        help="attention impl; 'ring' exercises the sequence-parallel "
        "shard_map path at shape (requires a sequence axis in --mesh)",
    )
    p.add_argument("--tolerance", type=float, default=0.06)
    args = p.parse_args()

    from tools.plan_memory import parse_mesh

    n_devices = math.prod(parse_mesh(args.mesh).values())

    # virtual devices must be configured before jax initializes
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        flags += f" --xla_force_host_platform_device_count={n_devices}"
    if "collective" not in flags:
        flags += COLLECTIVE_FLAGS
    os.environ["XLA_FLAGS"] = flags.strip()
    from relora_tpu.utils.logging import honor_platform_request

    honor_platform_request()

    out = run_at_shape(
        model=args.model,
        rank=args.rank,
        mesh_str=args.mesh,
        layers=args.layers,
        micro_batch=args.micro_batch,
        seq=args.seq,
        chip=args.chip,
        magnitude_reset=args.magnitude_reset,
        attn=args.attn,
        tolerance=args.tolerance,
        quantize=args.quantize,
    )
    print(json.dumps(out, indent=2))
    if out["failures"]:
        sys.exit(1)


if __name__ == "__main__":
    main()
