"""relora-tpu: a TPU-native (JAX/XLA/pjit/pallas) ReLoRA pretraining framework.

Re-designed from scratch with the capabilities of the reference implementation
(Guitaricet/relora, arXiv:2307.05695): parameter-efficient pretraining through
repeated low-rank updates that are periodically merged into frozen full-rank
weights, with synchronized optimizer-state resets and a cosine-with-restarts
learning-rate schedule.

Unlike the PyTorch reference (DDP/NCCL, in-place module surgery), everything
here is functional and compiler-first:

- models are Flax modules whose LoRA factors are ordinary pytree leaves
  (``relora_tpu.models``),
- merge-and-reinit is a pure jitted ``params -> params`` update
  (``relora_tpu.core.relora``),
- schedules and optimizer resets are pure optax-style transforms
  (``relora_tpu.core.schedules``, ``relora_tpu.core.optim``),
- parallelism is a ``jax.sharding.Mesh`` + NamedSharding over
  ``('data', 'fsdp', 'tensor', 'sequence')`` axes (``relora_tpu.parallel``),
- the data stack mirrors the reference's two pipelines: HF
  pretokenize-and-chunk and a Megatron-style mmap indexed dataset with a C++
  index builder (``relora_tpu.data``).
"""

__version__ = "0.1.0"

# Lazy top-level API: keeps `import relora_tpu` free of jax/flax import cost
# (and of XLA backend initialization — multi-host launchers must be able to
# import this package before jax.distributed.initialize()).
_API = {
    "TrainingConfig": "relora_tpu.config.training",
    "parse_train_args": "relora_tpu.config.training",
    "ModelConfig": "relora_tpu.config.model",
    "MODEL_ZOO": "relora_tpu.config.model",
    "load_model_config": "relora_tpu.config.model",
    "LoraSpec": "relora_tpu.core.relora",
    "merge_and_reinit": "relora_tpu.core.relora",
    "Trainer": "relora_tpu.train.trainer",
    "LlamaForCausalLM": "relora_tpu.models.llama",
    "GPTNeoXForCausalLM": "relora_tpu.models.pythia",
}


def __getattr__(name):
    if name in _API:
        import importlib

        return getattr(importlib.import_module(_API[name]), name)
    raise AttributeError(f"module 'relora_tpu' has no attribute {name!r}")


def __dir__():
    return sorted(list(globals()) + list(_API))
