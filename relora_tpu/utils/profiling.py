"""Profiling: XLA traces with the reference's schedule semantics.

The reference builds a torch profiler with schedule (wait=1, warmup=1,
active=3, repeat=2) writing TensorBoard traces per rank
(torchrun_main.py:322-335), stepped each update (:944).  Here the same
cadence drives ``jax.profiler`` trace windows: the trace captures XLA/TPU
timelines viewable in TensorBoard or Perfetto.
"""

from __future__ import annotations

import os
from typing import Optional

import jax

from relora_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class StepProfiler:
    """Step-driven trace windows: wait W steps, warm up, record A steps,
    repeat R times (parity: maybe_make_profiler, torchrun_main.py:322-335)."""

    def __init__(
        self,
        log_dir: str,
        *,
        wait: int = 1,
        warmup: int = 1,
        active: int = 3,
        repeat: int = 2,
    ):
        self.log_dir = os.path.abspath(log_dir)
        os.makedirs(self.log_dir, exist_ok=True)
        self.wait = wait
        self.warmup = warmup
        self.active = active
        self.repeat = max(1, repeat)
        self._step = 0
        self._cycles_done = 0
        self._tracing = False

    def step(self) -> None:
        if self._cycles_done >= self.repeat:
            return
        cycle_len = self.wait + self.warmup + self.active
        pos = self._step % cycle_len
        record_start = self.wait + self.warmup
        if pos == record_start and not self._tracing:
            jax.profiler.start_trace(self.log_dir)
            self._tracing = True
            logger.info(f"profiler: trace started -> {self.log_dir}")
        self._step += 1
        pos = self._step % cycle_len
        if self._tracing and pos == 0:
            jax.profiler.stop_trace()
            self._tracing = False
            self._cycles_done += 1
            logger.info(
                f"profiler: trace {self._cycles_done}/{self.repeat} written"
            )

    def stop(self) -> None:
        if self._tracing:
            jax.profiler.stop_trace()
            self._tracing = False

    # an exit mid-window (exception, preemption, budget hit) used to leak the
    # active jax.profiler trace — a global: the next start_trace anywhere in
    # the process would raise.  close() is the idempotent shutdown hook; the
    # trainer calls it from a finally, and `with StepProfiler(...)` works too.
    def close(self) -> None:
        self.stop()

    def __enter__(self) -> "StepProfiler":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def maybe_make_profiler(cfg, run_name: str = "run") -> Optional[StepProfiler]:
    """None unless --profile true (parity: torchrun_main.py:322-335)."""
    if not getattr(cfg, "profile", False):
        return None
    return StepProfiler(os.path.join("profiler_logs", run_name))
