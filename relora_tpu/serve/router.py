"""Health-aware HTTP router over N serving replicas.

The multi-replica front half of ROADMAP item 1: a stdlib-asyncio proxy that
sits in front of N ``serve.py`` processes (usually spawned by
serve/supervisor.py) and makes a replica crash or stall degrade to *slower*,
never *dropped*.  Deliberately jax-free — it imports in milliseconds and can
run on a box with no accelerator at all.

Three mechanisms, composed:

- **Health probing.**  Every ``probe_interval_s`` the router GETs each
  replica's ``/healthz``.  A 200 marks the replica routable and records its
  queue/slot gauges; any 503 (``draining`` / ``stuck`` / ``error``) or a
  connect failure ejects it from rotation.  Recovery is automatic: the next
  200 puts it back.
- **Least-loaded routing.**  Requests go to the routable replica with the
  smallest load score — the router's own in-flight count plus the replica's
  last-reported ``queue_depth + active_slots`` (ties rotate).  The score is
  at most one probe interval stale, which is exactly the staleness the
  in-flight count compensates for.  Multi-tenant requests (an ``"adapter"``
  body field) get **tenant affinity** first: the adapter name is
  rendezvous-hashed over the routable groups so each tenant keeps hitting
  one replica (its HBM adapter slot stays warm instead of loading on every
  replica); the least-loaded pick is the fallback whenever the home replica
  is unroutable, already tried, or its circuit breaker is open.
- **Per-replica circuit breaker.**  ``failure_threshold`` consecutive
  connect errors or 5xx responses open the circuit; after a cooldown
  (doubling per consecutive open, capped) one half-open trial — a health
  probe or a live request — closes it again.  The breaker is what stops a
  dead-but-listed replica from eating a connect timeout per request.

**The retry-idempotency boundary.**  A failed request is retried on another
replica (bounded backoff, each replica tried at most once) *iff zero SSE
body bytes have been forwarded to the client*.  Generation is not
idempotent from the middle: replaying a started request would re-stream
tokens the client already consumed, so a stream that dies after first byte
fails fast with a typed terminal event —

    data: {"error": {"type": "stream_interrupted", "replica": "r0",
           "detail": "...", "retryable": false}}

— and no ``data: [DONE]`` sentinel.  Clients treat a missing [DONE] plus an
``error`` event as "re-issue if you want; nothing was committed".  Unary
responses are buffered router-side and are therefore always
retry-or-deliver-whole.

**Sharded replicas.**  An endpoint value may name a *shard group* (see
``EndpointSource``): the group's replicas are one tp/fsdp-sharded model
instance, routable only while every shard answers ``/healthz`` (losing one
shard loses the instance), scored by the group's summed load, and proxied
to the group's primary (lowest rid — the shard serving HTTP).  Plain
endpoints are singleton groups, so unsharded fleets are unchanged.

Endpoints: ``POST /v1/generate`` (proxied; response carries
``X-Relora-Replica``), ``GET /healthz`` (200 iff >= 1 routable group, with
per-replica and per-group state), ``GET /metrics`` (Prometheus text,
namespace ``relora_router``: request/retry/failover counters labelled by
replica, per-replica and per-group health gauges).
"""

from __future__ import annotations

import asyncio
import dataclasses
import hashlib
import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence, Set, Tuple, Union

from relora_tpu.obs.metrics import MetricsRegistry
from relora_tpu.obs.tracer import Tracer, new_trace_id
from relora_tpu.serve import disagg
from relora_tpu.serve.wire import (
    MAX_BODY_BYTES,
    REASONS,
    head,
    read_http_request,
    respond,
    respond_json,
    sse,
)
from relora_tpu.utils.logging import get_logger

logger = get_logger(__name__)

#: upstream statuses worth trying another replica for (pre-stream only):
#: 5xx = replica broken, 429/503 = replica full/draining — a sibling may not be
RETRYABLE_STATUSES = (429, 500, 502, 503)

_REQUEST_TIMEOUT_S = 30.0

#: endpoints: static list/dict of (host, port), or a callable returning
#: {rid: (host, port-or-None)} — the supervisor's live view, re-read every
#: probe round so restarted replicas (new ephemeral ports) are picked up.
#: A value may carry a third element, the *shard group*: replicas sharing a
#: group are one tp/fsdp-sharded model instance (the group is routable only
#: when EVERY member answers healthz — losing one shard loses the whole
#: instance; requests go to the group's primary, the lowest rid, which is
#: the shard that serves HTTP).  A plain (host, port) value is its own
#: singleton group, so unsharded fleets behave exactly as before.
EndpointSource = Union[
    Sequence[Tuple],
    Mapping[str, Tuple],
    Callable[[], Mapping[str, Tuple]],
]


def rendezvous_home(adapter: str, groups: Sequence[str]) -> Optional[str]:
    """The shard group ``adapter``'s traffic homes to, by rendezvous
    (highest-random-weight) hashing over ``groups``.

    The property elastic scaling leans on: when a group joins or leaves,
    only the tenants whose maximal hash involved that group move — every
    other tenant keeps its home, so a fleet resize never thrashes the
    whole fleet's adapter slots, just the departed/added replica's share.
    """
    if not groups:
        return None
    return max(
        groups, key=lambda g: hashlib.sha1(f"{adapter}:{g}".encode()).digest()
    )


class _ClientGone(Exception):
    """The *downstream* client hung up mid-proxy — not the replica's fault,
    so it must not feed the replica's circuit breaker."""


async def _read_all(reader: asyncio.StreamReader, limit: int = MAX_BODY_BYTES) -> bytes:
    """Read a close-delimited body to EOF (``read(n)`` alone may return a
    partial chunk), bounded by ``limit``."""
    chunks: List[bytes] = []
    total = 0
    while total < limit:
        chunk = await reader.read(65536)
        if not chunk:
            break
        chunks.append(chunk)
        total += len(chunk)
    return b"".join(chunks)


class CircuitBreaker:
    """Consecutive-failure circuit breaker, single-threaded (event loop).

    closed --(failure_threshold consecutive failures)--> open
    open --(cooldown elapsed)--> half_open (exactly one trial allowed)
    half_open --success--> closed (cooldown resets)
    half_open --failure--> open (cooldown doubles, capped)
    """

    def __init__(
        self,
        *,
        failure_threshold: int = 3,
        cooldown_s: float = 1.0,
        cooldown_max_s: float = 30.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.failure_threshold = failure_threshold
        self.cooldown_s = cooldown_s
        self.cooldown_max_s = cooldown_max_s
        self._clock = clock
        self.state = "closed"  # "closed" | "open" | "half_open"
        self.failures = 0  # consecutive
        self.opens_total = 0
        self._opened_at = 0.0
        self._cooldown = cooldown_s
        self._trial_pending = False

    def allow(self) -> bool:
        """May a request be sent?  In half-open, only the single trial."""
        if self.state == "closed":
            return True
        if self.state == "open":
            if self._clock() - self._opened_at >= self._cooldown:
                self.state = "half_open"
                self._trial_pending = True
                return True
            return False
        # half_open: one trial in flight at a time
        if self._trial_pending:
            return False
        self._trial_pending = True
        return True

    def record_success(self) -> None:
        self.state = "closed"
        self.failures = 0
        self._trial_pending = False
        self._cooldown = self.cooldown_s

    def record_failure(self) -> None:
        self.failures += 1
        if self.state == "half_open":
            # failed trial: back to open, wait longer before the next one
            self._cooldown = min(self._cooldown * 2.0, self.cooldown_max_s)
            self._open()
        elif self.state == "closed" and self.failures >= self.failure_threshold:
            self._open()

    def _open(self) -> None:
        self.state = "open"
        self._opened_at = self._clock()
        self.opens_total += 1
        self._trial_pending = False


@dataclasses.dataclass
class ReplicaState:
    """The router's live view of one replica."""

    rid: str
    host: str
    port: Optional[int]  # None: no port file yet (down / restarting)
    breaker: CircuitBreaker
    group: str = ""  # shard group; "" = singleton group of just this replica
    healthy: bool = False
    status: str = "unknown"  # last healthz status string, or "unreachable"/"down"
    health: Dict[str, Any] = dataclasses.field(default_factory=dict)
    inflight: int = 0  # router-side, this instant
    probe_failures: int = 0  # consecutive

    def load(self) -> int:
        return (
            self.inflight
            + int(self.health.get("queue_depth", 0))
            + int(self.health.get("active_slots", 0))
        )


class Router:
    """Stdlib-asyncio reverse proxy with health-based failover.

    ``serve_forever()`` binds, starts the health prober, and runs until
    ``begin_shutdown()`` (thread-safe).  Mirrors GenerateServer's lifecycle
    surface (``started`` event, ``port`` rebound after bind) so the existing
    test/bench harnesses drive both the same way.
    """

    def __init__(
        self,
        endpoints: EndpointSource,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        probe_interval_s: float = 0.25,
        probe_timeout_s: float = 2.0,
        connect_timeout_s: float = 2.0,
        max_attempts: int = 3,
        retry_backoff_s: float = 0.05,
        retry_backoff_max_s: float = 0.5,
        failure_threshold: int = 3,
        cooldown_s: float = 1.0,
        cooldown_max_s: float = 30.0,
        tracer: Optional[Tracer] = None,
        extra_routes: Optional[Callable[[str], Optional[Tuple[int, str, bytes]]]] = None,
        classify_threshold: Optional[int] = None,
    ):
        self._endpoints = self._normalize_endpoints(endpoints)
        self.host = host
        self.port = port  # rebound after bind
        self.probe_interval_s = probe_interval_s
        self.probe_timeout_s = probe_timeout_s
        self.connect_timeout_s = connect_timeout_s
        self.max_attempts = max_attempts
        self.retry_backoff_s = retry_backoff_s
        self.retry_backoff_max_s = retry_backoff_max_s
        self._breaker_opts = dict(
            failure_threshold=failure_threshold,
            cooldown_s=cooldown_s,
            cooldown_max_s=cooldown_max_s,
        )
        self.stats = MetricsRegistry(namespace="relora_router")
        if tracer is None:
            # the proxy path spans join the replica's spans under the request
            # id; a JSONL sink (one file per process, like the replicas') lets
            # tools/trace_report.py merge router + replica streams offline
            trace_dir = os.environ.get("RELORA_TPU_TRACE_DIR")
            tracer = Tracer(
                service="router",
                jsonl_path=(
                    os.path.join(trace_dir, f"router_spans_{os.getpid()}.jsonl")
                    if trace_dir
                    else None
                ),
            )
        self.tracer = tracer
        # e.g. the supervisor's FleetCollector mounting /fleet/* on this
        # front-end: path -> (status, content_type, body) or None = 404
        self._extra_routes = extra_routes
        # disaggregated fleet: classify requests by prompt length into the
        # prefill vs decode replica pools (replica roles come from healthz);
        # None = role-blind routing, the pre-disagg behaviour
        self.classify_threshold = classify_threshold
        if classify_threshold is not None:
            self.stats.inc("routed_prefill_total", by=0)
            self.stats.inc("routed_decode_total", by=0)
            self.stats.inc("route_fallback_total", by=0)
        self.replicas: Dict[str, ReplicaState] = {}
        self.started = threading.Event()
        self._t_start = time.monotonic()
        self._rr = 0  # tie-break rotation among equally loaded replicas
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._shutdown: Optional[asyncio.Event] = None
        self._handler_tasks: Set[asyncio.Task] = set()

    @staticmethod
    def _normalize_endpoints(
        endpoints: EndpointSource,
    ) -> Callable[[], Mapping[str, Tuple[str, Optional[int]]]]:
        if callable(endpoints):
            return endpoints
        if isinstance(endpoints, Mapping):
            static_map = dict(endpoints)
        else:
            static_map = {f"r{i}": hp for i, hp in enumerate(endpoints)}
        return lambda: static_map

    # -- lifecycle -----------------------------------------------------------

    def begin_shutdown(self) -> None:
        loop, shutdown = self._loop, self._shutdown
        if loop is None or shutdown is None:
            return
        try:
            loop.call_soon_threadsafe(shutdown.set)
        except RuntimeError:
            pass  # loop already closed

    async def serve_forever(self) -> None:
        self._loop = asyncio.get_running_loop()
        self._shutdown = asyncio.Event()
        server = await asyncio.start_server(self._client_connected, self.host, self.port)
        self.port = server.sockets[0].getsockname()[1]
        prober = asyncio.ensure_future(self._prober())
        self.started.set()
        logger.info(f"router on http://{self.host}:{self.port}")
        async with server:
            await self._shutdown.wait()
            server.close()
            await server.wait_closed()
        prober.cancel()
        if self._handler_tasks:
            await asyncio.wait(set(self._handler_tasks), timeout=10.0)
        logger.info("router stopped")

    # -- health probing ------------------------------------------------------

    def _refresh_endpoints(self) -> None:
        eps = {}
        for rid, val in dict(self._endpoints()).items():
            # (host, port) = singleton group; (host, port, group) = shard
            h, p, g = val if len(val) == 3 else (val[0], val[1], rid)
            eps[rid] = (h, p, g)
        for rid, (h, p, g) in eps.items():
            st = self.replicas.get(rid)
            if st is None:
                self.replicas[rid] = ReplicaState(
                    rid=rid, host=h, port=p, group=g,
                    breaker=CircuitBreaker(**self._breaker_opts),
                )
            elif (st.host, st.port) != (h, p):
                # restarted under a new ephemeral port: fresh start — the old
                # failure streak belonged to the dead incarnation
                logger.info(f"replica {rid}: endpoint now {h}:{p}")
                st.host, st.port, st.group = h, p, g
                st.healthy, st.status, st.health = False, "restarted", {}
                st.breaker = CircuitBreaker(**self._breaker_opts)
            else:
                st.group = g
        for rid in list(self.replicas):
            if rid not in eps:
                del self.replicas[rid]

    def _groups(self) -> Dict[str, List[ReplicaState]]:
        """Replicas keyed by shard group (a plain replica is its own
        group).  One group = one servable model instance."""
        groups: Dict[str, List[ReplicaState]] = {}
        for st in self.replicas.values():
            groups.setdefault(st.group or st.rid, []).append(st)
        return groups

    async def _prober(self) -> None:
        # one span per probe *round* (not per replica probe: at 4 Hz x N
        # replicas that would drown the flight ring); per-replica health and
        # breaker *transitions* are instant events on the same trace
        prev_state: Dict[str, Tuple[bool, str]] = {}
        while True:
            try:
                self._refresh_endpoints()
                round_span = self.tracer.start_span("probe_round", trace_id="probes")
                await asyncio.gather(*(self._probe(st) for st in self.replicas.values()))
                for st in self.replicas.values():
                    prev = prev_state.get(st.rid)
                    cur = (st.healthy, st.breaker.state)
                    if prev is not None and prev != cur:
                        if prev[0] != st.healthy:
                            self.tracer.event(
                                "replica_health_flip", trace_id="probes",
                                replica=st.rid, healthy=st.healthy, status=st.status,
                            )
                        if prev[1] != st.breaker.state:
                            self.tracer.event(
                                "circuit_transition", trace_id="probes",
                                replica=st.rid, frm=prev[1], to=st.breaker.state,
                            )
                    prev_state[st.rid] = cur
                for rid in list(prev_state):
                    if rid not in self.replicas:
                        del prev_state[rid]
                healthy = sum(st.healthy for st in self.replicas.values())
                self.stats.set_gauge("healthy_replicas", healthy)
                self.stats.set_gauge("known_replicas", len(self.replicas))
                groups = self._groups()
                self.stats.set_gauge(
                    "healthy_groups",
                    sum(all(st.healthy for st in m) for m in groups.values()),
                )
                self.stats.set_gauge("known_groups", len(groups))
                for gid, members in groups.items():
                    self.stats.set_gauge(
                        f"group_{gid}_healthy",
                        int(all(st.healthy for st in members)),
                    )
                for st in self.replicas.values():
                    self.stats.set_gauge(f"replica_{st.rid}_healthy", int(st.healthy))
                    self.stats.set_gauge(
                        f"replica_{st.rid}_circuit_open",
                        int(st.breaker.state != "closed"),
                    )
                    self.stats.set_gauge(f"replica_{st.rid}_load", st.load())
                round_span.set(
                    healthy=healthy, known=len(self.replicas)
                ).end()
            except Exception as e:  # the prober must never die
                logger.warning(f"health probe round failed: {e!r}")
            await asyncio.sleep(self.probe_interval_s)

    async def _probe(self, st: ReplicaState) -> None:
        if st.port is None:
            st.healthy, st.status, st.health = False, "down", {}
            return
        writer = None
        try:
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(st.host, st.port), self.probe_timeout_s
            )
            writer.write(
                b"GET /healthz HTTP/1.1\r\nHost: router\r\nConnection: close\r\n\r\n"
            )
            await writer.drain()
            raw = await asyncio.wait_for(_read_all(reader), self.probe_timeout_s)
            code, _hdrs, body = _parse_response(raw)
            payload = json.loads(body.decode() or "{}")
            st.health = payload if isinstance(payload, dict) else {}
            st.status = str(st.health.get("status", code))
            st.healthy = code == 200
            st.probe_failures = 0
            if st.healthy and st.breaker.state != "closed":
                # the half-open probe that closes the circuit: the replica
                # answers healthz again, so let requests flow
                st.breaker.record_success()
        except (OSError, asyncio.TimeoutError, ValueError) as e:
            st.healthy, st.health = False, {}
            st.status = "unreachable"
            st.probe_failures += 1
            if st.probe_failures == 1:
                logger.warning(f"replica {st.rid} unreachable: {e!r}")
        finally:
            if writer is not None:
                writer.close()

    # -- selection -----------------------------------------------------------

    def _pick(
        self,
        exclude: Set[str],
        adapter: Optional[str] = None,
        role: Optional[str] = None,
    ) -> Optional[ReplicaState]:
        # a group is routable only when every shard is healthy; requests go
        # to its primary (lowest rid), scored by the whole group's load
        candidates: List[Tuple[ReplicaState, int]] = []
        routable_groups: List[str] = []
        for gid, members in self._groups().items():
            if not all(st.healthy and st.port is not None for st in members):
                continue
            routable_groups.append(gid)
            primary = min(members, key=lambda s: s.rid)
            if primary.rid in exclude:
                continue
            candidates.append((primary, sum(st.load() for st in members)))
        if adapter is not None and routable_groups:
            # tenant affinity: rendezvous-hash the adapter over the routable
            # groups so each tenant keeps hitting one replica (its slot pool
            # stays warm — no cross-fleet slot thrash) and keeps its home as
            # long as that group stays up.  Fall back to least-loaded when
            # the home is excluded (already tried) or its breaker won't
            # admit a request.
            home = rendezvous_home(adapter, routable_groups)
            for st, _load in candidates:
                if (st.group or st.rid) != home:
                    continue
                if st.breaker.state == "closed" or st.breaker.allow():
                    self.stats.inc("affinity_routed_total", ("replica", st.rid))
                    return st
                break
            self.stats.inc("affinity_fallback_total")
        if role is not None and candidates:
            # role routing: prefer the request's pool (replica roles come
            # from healthz), then mixed replicas, then — degraded fleet —
            # anyone routable; each widening is a counted fallback
            pool = [
                (st, load)
                for st, load in candidates
                if str(st.health.get("role", "mixed")) == role
            ]
            if not pool:
                pool = [
                    (st, load)
                    for st, load in candidates
                    if str(st.health.get("role", "mixed")) == "mixed"
                ]
                self.stats.inc("route_fallback_total")
            if pool:
                candidates = pool
        ready = [(st, load) for st, load in candidates if st.breaker.state == "closed"]
        if not ready:
            # no closed circuit: offer half-open trials (allow() mutates)
            ready = [(st, load) for st, load in candidates if st.breaker.allow()]
        if not ready:
            return None
        best = min(load for _, load in ready)
        pool = sorted((st for st, load in ready if load == best), key=lambda s: s.rid)
        self._rr += 1
        return pool[self._rr % len(pool)]

    # -- request handling ----------------------------------------------------

    async def _client_connected(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        task = asyncio.current_task()
        if task is not None:
            self._handler_tasks.add(task)
            task.add_done_callback(self._handler_tasks.discard)
        try:
            await self._handle(reader, writer)
        except (ConnectionError, asyncio.IncompleteReadError, TimeoutError):
            pass
        except Exception as e:
            logger.warning(f"router handler error: {e!r}")
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass

    async def _handle(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        try:
            parsed = await asyncio.wait_for(read_http_request(reader), _REQUEST_TIMEOUT_S)
        except ValueError as e:
            await respond_json(writer, 400, {"error": str(e)})
            return
        if parsed is None:
            return
        method, path, headers, body = parsed
        route = path.split("?", 1)[0]
        if route == "/healthz" and method == "GET":
            await self._handle_healthz(writer)
        elif route == "/metrics" and method == "GET":
            await respond(
                writer, 200, self.stats.render(), content_type="text/plain; version=0.0.4"
            )
        elif route == "/v1/generate":
            if method != "POST":
                await respond_json(writer, 405, {"error": "use POST"})
                return
            self.stats.inc("requests_total")
            await self._proxy_generate(writer, body, headers)
        elif (
            method == "GET"
            and self._extra_routes is not None
            and (mounted := self._extra_routes(path)) is not None
        ):
            status, ctype, payload = mounted
            await respond(writer, status, payload.decode(), content_type=ctype)
        else:
            await respond_json(writer, 404, {"error": f"no route {route}"})

    async def _handle_healthz(self, writer: asyncio.StreamWriter) -> None:
        replicas = {}
        queue_depth = active_slots = 0
        for st in self.replicas.values():
            replicas[st.rid] = {
                "host": st.host,
                "port": st.port,
                "group": st.group or st.rid,
                "healthy": st.healthy,
                "status": st.status,
                "circuit": st.breaker.state,
                "inflight": st.inflight,
                "load": st.load(),
            }
            if st.healthy:
                queue_depth += int(st.health.get("queue_depth", 0))
                active_slots += int(st.health.get("active_slots", 0))
        # one group = one servable (possibly tp/fsdp-sharded) model instance:
        # the router is "ok" iff at least one WHOLE group answers, a stricter
        # bar than any-replica-healthy when groups have > 1 shard
        groups = {}
        for gid, members in self._groups().items():
            groups[gid] = {
                "shards": len(members),
                "healthy": all(st.healthy for st in members),
                "members": sorted(st.rid for st in members),
                "load": sum(st.load() for st in members),
            }
        healthy = sum(st.healthy for st in self.replicas.values())
        healthy_groups = sum(g["healthy"] for g in groups.values())
        payload = {
            "status": "ok" if healthy_groups else "unavailable",
            "healthy_replicas": healthy,
            "known_replicas": len(self.replicas),
            "healthy_groups": healthy_groups,
            "known_groups": len(groups),
            "queue_depth": queue_depth,
            "active_slots": active_slots,
            "uptime_s": round(time.monotonic() - self._t_start, 3),
            "replicas": replicas,
            "groups": groups,
        }
        await respond_json(writer, 200 if healthy_groups else 503, payload)

    async def _proxy_generate(
        self,
        writer: asyncio.StreamWriter,
        body: bytes,
        headers: Dict[str, str],
    ) -> None:
        rid_hdr = (headers.get("x-request-id") or "").strip() or new_trace_id()
        # tenant affinity key + route class: a parse failure routes anywhere
        # and the replica's own body validation answers the 400
        adapter: Optional[str] = None
        role: Optional[str] = None
        try:
            payload = json.loads(body.decode("utf-8") or "{}")
            if isinstance(payload, dict):
                name = payload.get("adapter")
                if isinstance(name, str) and name.strip():
                    adapter = name.strip()
                if self.classify_threshold is not None:
                    prompt = payload.get("prompt")
                    role = disagg.classify_request(
                        len(prompt) if isinstance(prompt, list) else 0,
                        self.classify_threshold,
                    )
                    self.stats.inc(f"routed_{role}_total")
        except (UnicodeDecodeError, json.JSONDecodeError):
            pass
        # root span of this process's share of the request: trace_id is the
        # request id, the same id the replica uses for its own spans, so the
        # merged trace (tools/trace_report.py) shows router -> replica ->
        # model thread as one tree
        root = self.tracer.start_span(
            "route", trace_id=rid_hdr, adapter=adapter, route_class=role
        )
        try:
            outcome = await self._proxy_attempts(
                writer, body, rid_hdr, root, adapter, role
            )
        finally:
            root.set(outcome=outcome if isinstance(outcome, str) else "error").end()

    async def _proxy_attempts(
        self,
        writer: asyncio.StreamWriter,
        body: bytes,
        rid_hdr: str,
        root,
        adapter: Optional[str] = None,
        role: Optional[str] = None,
    ) -> str:
        # shared across attempts: once any SSE body byte reaches the client,
        # the request is no longer retryable (the idempotency boundary)
        sent = {"head": False, "bytes": 0}
        tried: List[str] = []
        backoff = self.retry_backoff_s
        passthrough: Optional[Tuple[int, Dict[str, str], bytes]] = None
        for attempt in range(self.max_attempts):
            st = self._pick(exclude=set(tried), adapter=adapter, role=role)
            if st is None:
                break
            tried.append(st.rid)
            if attempt > 0:
                self.stats.inc("retries_total")
            st.inflight += 1
            attempt_span = self.tracer.start_span(
                "proxy_attempt", trace_id=rid_hdr, parent=root,
                replica=st.rid, attempt=attempt,
            )
            outcome, info = "error", None
            try:
                outcome, info = await self._forward(st, writer, body, rid_hdr, sent)
            finally:
                st.inflight -= 1
                attempt_span.set(outcome=outcome).end()
            if outcome == "done":
                if attempt > 0:
                    self.stats.inc("failovers_total", ("replica", st.rid))
                    self.tracer.event(
                        "failover", trace_id=rid_hdr, replica=st.rid, attempt=attempt
                    )
                self.stats.inc("proxied_total", ("replica", st.rid))
                return "done"
            if outcome == "client_gone":
                self.stats.inc("client_disconnects_total")
                return "client_gone"
            if outcome == "midstream":
                # started stream died: typed terminal event, never a replay
                self.stats.inc("midstream_errors_total", ("replica", st.rid))
                self.tracer.event(
                    "midstream_error", trace_id=rid_hdr, replica=st.rid, detail=str(info)
                )
                logger.warning(f"stream via {st.rid} interrupted: {info}")
                event = {
                    "error": {
                        "type": "stream_interrupted",
                        "replica": st.rid,
                        "detail": str(info),
                        "retryable": False,
                    }
                }
                try:
                    writer.write(sse(event))
                    await writer.drain()
                except (ConnectionError, OSError):
                    pass
                return "midstream"
            # outcome == "retry": zero body bytes forwarded; try a sibling
            self.stats.inc("upstream_failures_total", ("replica", st.rid))
            if isinstance(info, tuple):
                passthrough = info  # a real upstream response (429/5xx body)
                logger.info(f"upstream {st.rid} answered {info[0]}; trying another replica")
            else:
                logger.warning(f"upstream {st.rid} failed pre-stream: {info}")
            await asyncio.sleep(backoff)
            backoff = min(backoff * 2.0, self.retry_backoff_max_s)

        # every replica tried (or none routable)
        self.stats.inc("exhausted_total")
        if sent["head"]:
            event = {
                "error": {
                    "type": "no_replica_available",
                    "detail": f"tried {tried or 'no replicas'}",
                    "retryable": True,
                }
            }
            try:
                writer.write(sse(event))
                await writer.drain()
            except (ConnectionError, OSError):
                pass
            return "exhausted"
        if passthrough is not None:
            # deliver the last real upstream answer (e.g. 429 + Retry-After)
            status, up_headers, up_body = passthrough
            extra = {"X-Request-Id": rid_hdr}
            if "retry-after" in up_headers:
                extra["Retry-After"] = up_headers["retry-after"]
            ct = up_headers.get("content-type", "application/json")
            writer.write(head(status, REASONS.get(status, "?"), ct, extra, len(up_body)))
            writer.write(up_body)
            await writer.drain()
            return "passthrough"
        await respond_json(
            writer,
            503,
            {"error": "no healthy replica available"},
            extra_headers={"Retry-After": "1", "X-Request-Id": rid_hdr},
        )
        return "no_replica"

    async def _forward(
        self,
        st: ReplicaState,
        client: asyncio.StreamWriter,
        body: bytes,
        rid: str,
        sent: Dict[str, int],
    ) -> Tuple[str, Any]:
        """One proxy attempt against one replica.  Returns (outcome, info):

        - ``("done", None)``      — response fully delivered to the client
        - ``("retry", reason)``   — failed with zero body bytes forwarded;
          ``reason`` is a string, or ``(status, headers, body)`` when the
          upstream produced a real retryable response worth passing through
        - ``("midstream", why)``  — stream died after >= 1 forwarded byte
        - ``("client_gone", why)``— the *client* hung up; stop, no retry
        """

        async def to_client(data: bytes) -> None:
            try:
                client.write(data)
                await client.drain()
            except (ConnectionError, OSError) as e:
                raise _ClientGone(repr(e)) from None

        upstream: Optional[asyncio.StreamWriter] = None
        try:
            try:
                reader, upstream = await asyncio.wait_for(
                    asyncio.open_connection(st.host, st.port), self.connect_timeout_s
                )
            except (OSError, asyncio.TimeoutError) as e:
                st.breaker.record_failure()
                return "retry", f"connect failed: {e!r}"
            req = (
                f"POST /v1/generate HTTP/1.1\r\n"
                f"Host: {st.host}:{st.port}\r\n"
                f"Content-Type: application/json\r\n"
                f"Content-Length: {len(body)}\r\n"
                f"X-Request-Id: {rid}\r\n"
                f"Connection: close\r\n\r\n"
            ).encode() + body
            upstream.write(req)
            await upstream.drain()
            status_line = await asyncio.wait_for(reader.readline(), _REQUEST_TIMEOUT_S)
            if not status_line.strip():
                # connection accepted then dropped without a byte
                # (serve_accept_drop drill, or a process dying on accept)
                st.breaker.record_failure()
                return "retry", "connection dropped before response"
            status = int(status_line.split()[1])
            up_headers: Dict[str, str] = {}
            while True:
                raw = await asyncio.wait_for(reader.readline(), _REQUEST_TIMEOUT_S)
                if raw in (b"\r\n", b"\n", b""):
                    break
                k, _, v = raw.decode("latin-1").partition(":")
                up_headers[k.strip().lower()] = v.strip()
            if status in RETRYABLE_STATUSES:
                up_body = await _read_all(reader)
                if status >= 500:
                    st.breaker.record_failure()
                else:
                    st.breaker.record_success()  # 429 = busy, not broken
                return "retry", (status, up_headers, up_body)
            st.breaker.record_success()
            ct = up_headers.get("content-type", "application/octet-stream")
            fwd_headers = {"X-Request-Id": rid, "X-Relora-Replica": st.rid}
            if "x-relora-weights" in up_headers:
                # surface which weights version served this response so a
                # rolling update is observable from outside the fleet
                fwd_headers["X-Relora-Weights"] = up_headers["x-relora-weights"]
            if "text/event-stream" in ct:
                # SSE: forward bytes as they arrive.  The head goes out once
                # (a retry after head-only keeps streaming into the same
                # response — no events were delivered, so nothing replays).
                if not sent["head"]:
                    await to_client(
                        head(200, "OK", ct, {"Cache-Control": "no-cache", **fwd_headers})
                    )
                    sent["head"] = True
                tail = b""
                while True:
                    chunk = await reader.read(65536)
                    if not chunk:
                        break
                    sent["bytes"] += len(chunk)
                    tail = (tail + chunk)[-24:]
                    await to_client(chunk)
                if b"[DONE]" in tail:
                    return "done", None
                # EOF without the sentinel: the replica died mid-stream
                st.breaker.record_failure()
                if sent["bytes"] == 0:
                    return "retry", "upstream closed before first event"
                return "midstream", "upstream closed before [DONE]"
            # unary (or error) response: buffer whole, then deliver whole —
            # a failure while reading stays retryable
            up_body = await _read_all(reader)
            await to_client(
                head(status, REASONS.get(status, "?"), ct, fwd_headers, len(up_body))
                + up_body
            )
            return "done", None
        except _ClientGone as e:
            return "client_gone", str(e)
        except (OSError, asyncio.TimeoutError, asyncio.IncompleteReadError, ValueError) as e:
            st.breaker.record_failure()
            if sent["bytes"] > 0:
                return "midstream", f"{e!r}"
            return "retry", f"{e!r}"
        finally:
            if upstream is not None:
                upstream.close()


def _parse_response(raw: bytes) -> Tuple[int, Dict[str, str], bytes]:
    """Split a full close-delimited HTTP response into (status, headers, body)."""
    head_part, _, body = raw.partition(b"\r\n\r\n")
    lines = head_part.decode("latin-1").split("\r\n")
    parts = lines[0].split()
    if len(parts) < 2 or not parts[1].isdigit():
        raise ValueError(f"malformed status line: {lines[0]!r}")
    headers: Dict[str, str] = {}
    for line in lines[1:]:
        k, _, v = line.partition(":")
        headers[k.strip().lower()] = v.strip()
    return int(parts[1]), headers, body
