"""Ring attention correctness on the 8-virtual-device CPU mesh: must equal
single-device full attention exactly (it is exact, not approximate)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from relora_tpu.ops.attention import dot_product_attention
from relora_tpu.parallel.mesh import MeshSpec, make_mesh
from relora_tpu.parallel.ring_attention import ring_attention


def make_qkv(B=2, S=32, N=4, H=16, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (B, S, N, H), jnp.float32) for k in ks)


@pytest.mark.parametrize("ring", [2, 4, 8])
@pytest.mark.parametrize("causal", [True, False])
def test_ring_matches_full_attention(ring, causal, devices):
    mesh = make_mesh(MeshSpec(data=1, sequence=ring))
    q, k, v = make_qkv(S=32)
    spec = NamedSharding(mesh, P(("data", "fsdp"), "sequence", None, None))
    qs, ks, vs = (jax.device_put(x, spec) for x in (q, k, v))

    out_ring = jax.jit(lambda a, b, c: ring_attention(a, b, c, mesh, causal=causal))(qs, ks, vs)
    out_ref = dot_product_attention(q, k, v, causal=causal, impl="naive")
    np.testing.assert_allclose(np.asarray(out_ring), np.asarray(out_ref), atol=2e-5)
    # the output really is sequence-sharded
    assert not out_ring.sharding.is_fully_replicated


def test_ring_with_data_parallel_axis(devices):
    """Batch sharded over data at the same time as sequence over the ring."""
    mesh = make_mesh(MeshSpec(data=2, sequence=4))
    q, k, v = make_qkv(B=4, S=16)
    spec = NamedSharding(mesh, P(("data", "fsdp"), "sequence", None, None))
    qs, ks, vs = (jax.device_put(x, spec) for x in (q, k, v))
    out = jax.jit(lambda a, b, c: ring_attention(a, b, c, mesh, causal=True))(qs, ks, vs)
    ref = dot_product_attention(q, k, v, causal=True, impl="naive")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_gradients_match(devices):
    """Backward through the ring (ppermute transpose) matches full attention."""
    mesh = make_mesh(MeshSpec(data=1, sequence=4))
    q, k, v = make_qkv(B=1, S=16, N=2, H=8)
    spec = NamedSharding(mesh, P(("data", "fsdp"), "sequence", None, None))

    def loss_ring(q, k, v):
        return jnp.sum(jnp.square(ring_attention(q, k, v, mesh, causal=True)))

    def loss_ref(q, k, v):
        return jnp.sum(jnp.square(dot_product_attention(q, k, v, causal=True, impl="naive")))

    args = tuple(jax.device_put(x, spec) for x in (q, k, v))
    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(*args)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4)


@pytest.mark.parametrize("sp", [2, 4])
@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_matches_full_attention(sp, causal, devices):
    from relora_tpu.parallel.ulysses import ulysses_attention

    mesh = make_mesh(MeshSpec(data=1, sequence=sp))
    q, k, v = make_qkv(S=32, N=4)
    spec = NamedSharding(mesh, P(("data", "fsdp"), "sequence", None, None))
    qs, ks, vs = (jax.device_put(x, spec) for x in (q, k, v))
    out = jax.jit(lambda a, b, c: ulysses_attention(a, b, c, mesh, causal=causal))(qs, ks, vs)
    ref = dot_product_attention(q, k, v, causal=causal, impl="naive")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
    assert not out.sharding.is_fully_replicated


def test_ulysses_gradients_match(devices):
    from relora_tpu.parallel.ulysses import ulysses_attention

    mesh = make_mesh(MeshSpec(data=1, sequence=4))
    q, k, v = make_qkv(B=1, S=16, N=4, H=8)
    spec = NamedSharding(mesh, P(("data", "fsdp"), "sequence", None, None))
    args = tuple(jax.device_put(x, spec) for x in (q, k, v))
    g_u = jax.jit(jax.grad(
        lambda a, b, c: jnp.sum(jnp.square(ulysses_attention(a, b, c, mesh, causal=True))),
        argnums=(0, 1, 2),
    ))(*args)
    g_ref = jax.grad(
        lambda a, b, c: jnp.sum(jnp.square(dot_product_attention(a, b, c, causal=True, impl="naive"))),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(g_u, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4)


def test_ulysses_head_divisibility(devices):
    from relora_tpu.parallel.ulysses import ulysses_attention

    mesh = make_mesh(MeshSpec(data=1, sequence=4))
    q, k, v = make_qkv(S=16, N=2)  # 2 heads, sp=4 -> error
    with pytest.raises(ValueError, match="num_heads"):
        ulysses_attention(q, k, v, mesh)


@pytest.mark.parametrize("ring", [2, 4])
def test_zigzag_ring_matches_full_attention(ring, devices):
    """Zigzag-balanced causal ring: exact vs dense attention, both through
    the permute-around wrapper and with pre-permuted inputs."""
    from relora_tpu.parallel.ring_attention import (
        ring_attention_zigzag,
        zigzag_inverse,
        zigzag_permutation,
    )

    mesh = make_mesh(MeshSpec(data=1, sequence=ring))
    q, k, v = make_qkv(S=32, N=4)
    spec = NamedSharding(mesh, P(("data", "fsdp"), "sequence", None, None))
    qs, ks, vs = (jax.device_put(x, spec) for x in (q, k, v))
    ref = dot_product_attention(q, k, v, causal=True, impl="naive")

    out = jax.jit(lambda a, b, c: ring_attention_zigzag(a, b, c, mesh))(qs, ks, vs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    # pre-permuted path: permute inputs, compute, unpermute the output
    perm = zigzag_permutation(32, ring)
    inv = zigzag_inverse(32, ring)
    qp, kp, vp = (jax.device_put(x[:, perm], spec) for x in (q, k, v))
    outp = jax.jit(
        lambda a, b, c: ring_attention_zigzag(a, b, c, mesh, inputs_permuted=True)
    )(qp, kp, vp)
    np.testing.assert_allclose(np.asarray(outp)[:, inv], np.asarray(ref), atol=2e-5)


def test_zigzag_permutation_properties():
    from relora_tpu.parallel.ring_attention import zigzag_inverse, zigzag_permutation

    perm = zigzag_permutation(16, 2)
    inv = zigzag_inverse(16, 2)
    assert sorted(perm) == list(range(16))
    np.testing.assert_array_equal(perm[inv], np.arange(16))
    # device 0 holds chunks 0 and 3; device 1 holds 1 and 2 (C = 4)
    np.testing.assert_array_equal(perm[:8], [0, 1, 2, 3, 12, 13, 14, 15])
    np.testing.assert_array_equal(perm[8:], [4, 5, 6, 7, 8, 9, 10, 11])
    with pytest.raises(ValueError, match="divide"):
        zigzag_permutation(10, 2)


def make_gqa_qkv(B=2, S=32, N=8, NKV=2, H=16, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (B, S, N, H), jnp.float32)
    k = jax.random.normal(ks[1], (B, S, NKV, H), jnp.float32)
    v = jax.random.normal(ks[2], (B, S, NKV, H), jnp.float32)
    return q, k, v


def gqa_oracle(q, k, v, causal=True):
    """Dense oracle via explicit K/V head repetition (the convention grouped
    impls must match: kv head j serves query heads j*G..(j+1)*G-1)."""
    G = q.shape[2] // k.shape[2]
    return dot_product_attention(
        q, jnp.repeat(k, G, axis=2), jnp.repeat(v, G, axis=2), causal=causal, impl="naive"
    )


def test_naive_and_xla_gqa_match_repeat_oracle():
    q, k, v = make_gqa_qkv()
    ref = gqa_oracle(q, k, v)
    got_naive = dot_product_attention(q, k, v, causal=True, impl="naive")
    got_xla = dot_product_attention(q, k, v, causal=True, impl="xla")
    np.testing.assert_allclose(np.asarray(got_naive), np.asarray(ref), atol=2e-5)
    np.testing.assert_allclose(np.asarray(got_xla), np.asarray(ref), atol=2e-3)


@pytest.mark.parametrize("ring", [2, 4])
def test_ring_gqa_matches_oracle(ring, devices):
    """Grouped K/V ride the ring un-repeated and still give exact attention."""
    mesh = make_mesh(MeshSpec(data=1, sequence=ring))
    q, k, v = make_gqa_qkv(S=32)
    spec = NamedSharding(mesh, P(("data", "fsdp"), "sequence", None, None))
    qs, ks, vs = (jax.device_put(x, spec) for x in (q, k, v))
    out = jax.jit(lambda a, b, c: ring_attention(a, b, c, mesh, causal=True))(qs, ks, vs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(gqa_oracle(q, k, v)), atol=2e-5)


@pytest.mark.parametrize("tile", [4, 8, 16])
def test_ring_tile_streaming_matches(tile, devices):
    """The flash key-tile streaming inside each block is tile-size invariant."""
    mesh = make_mesh(MeshSpec(data=1, sequence=2))
    q, k, v = make_qkv(S=32)
    spec = NamedSharding(mesh, P(("data", "fsdp"), "sequence", None, None))
    qs, ks, vs = (jax.device_put(x, spec) for x in (q, k, v))
    ref = dot_product_attention(q, k, v, causal=True, impl="naive")
    out = jax.jit(
        lambda a, b, c: ring_attention(a, b, c, mesh, causal=True, tile=tile)
    )(qs, ks, vs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("ring", [2, 4])
def test_zigzag_gqa_and_tiles_match(ring, devices):
    from relora_tpu.parallel.ring_attention import ring_attention_zigzag

    mesh = make_mesh(MeshSpec(data=1, sequence=ring))
    q, k, v = make_gqa_qkv(S=32)
    spec = NamedSharding(mesh, P(("data", "fsdp"), "sequence", None, None))
    qs, ks, vs = (jax.device_put(x, spec) for x in (q, k, v))
    ref = gqa_oracle(q, k, v)
    out = jax.jit(lambda a, b, c: ring_attention_zigzag(a, b, c, mesh, tile=4))(qs, ks, vs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ulysses_gqa_matches_oracle(devices):
    from relora_tpu.parallel.ulysses import ulysses_attention

    mesh = make_mesh(MeshSpec(data=1, sequence=2))
    q, k, v = make_gqa_qkv(S=16, N=8, NKV=2)  # n_kv=2 divides sp=2: stays grouped
    spec = NamedSharding(mesh, P(("data", "fsdp"), "sequence", None, None))
    qs, ks, vs = (jax.device_put(x, spec) for x in (q, k, v))
    out = jax.jit(lambda a, b, c: ulysses_attention(a, b, c, mesh, causal=True))(qs, ks, vs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(gqa_oracle(q, k, v)), atol=2e-3)

    # n_kv=2 does NOT divide sp=4: falls back to expanded K/V, still exact
    mesh4 = make_mesh(MeshSpec(data=1, sequence=4))
    q4, k4, v4 = make_gqa_qkv(S=16, N=8, NKV=2)
    spec4 = NamedSharding(mesh4, P(("data", "fsdp"), "sequence", None, None))
    args = tuple(jax.device_put(x, spec4) for x in (q4, k4, v4))
    out4 = jax.jit(lambda a, b, c: ulysses_attention(a, b, c, mesh4, causal=True))(*args)
    np.testing.assert_allclose(np.asarray(out4), np.asarray(gqa_oracle(q4, k4, v4)), atol=2e-3)


def test_zigzag_gradients_match(devices):
    from relora_tpu.parallel.ring_attention import ring_attention_zigzag

    mesh = make_mesh(MeshSpec(data=1, sequence=4))
    q, k, v = make_qkv(B=1, S=16, N=2, H=8)
    spec = NamedSharding(mesh, P(("data", "fsdp"), "sequence", None, None))
    args = tuple(jax.device_put(x, spec) for x in (q, k, v))
    g_z = jax.jit(jax.grad(
        lambda a, b, c: jnp.sum(jnp.square(ring_attention_zigzag(a, b, c, mesh))),
        argnums=(0, 1, 2),
    ))(*args)
    g_ref = jax.grad(
        lambda a, b, c: jnp.sum(jnp.square(dot_product_attention(a, b, c, causal=True, impl="naive"))),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(g_z, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4)
