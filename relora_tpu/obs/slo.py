"""Declarative SLOs with multi-window burn-rate alerts over a SeriesStore.

The fleet collector (:mod:`relora_tpu.obs.fleet`) retains the signals; this
module decides when they constitute an incident.  Two detectors, both cheap
enough to run every scrape round:

- **Burn-rate alerts** (the Google SRE workbook construction): an
  :class:`SLO` declares which samples of a series are *bad* and what good
  fraction the objective demands; the error budget is ``1 - objective``.  An
  alert fires when the budget is being consumed faster than a window pair
  allows — the *long* window proves the burn is sustained, the *short*
  window proves it is still happening (so a stale incident cannot re-page).
  The default pairs (1h/5m @ 14.4x, 6h/30m @ 6x, 3d/6h @ 1x) follow the SRE
  workbook; drills and tests compress them with ``window_scale``.  An alert
  clears when every pair's short window drops back under its burn factor.
- **Series anomaly detection**: the trainer's ``LossSpikeDetector``
  (median/MAD with patience, outliers excluded from the baseline) reused
  verbatim over arbitrary stored series — a TTFT regression or MFU collapse
  is "a loss spike" on a different curve, and parity with the trainer's
  detector is pinned by test.

Alert transitions are *events*: they land in the store (persisted, rendered
on the fleet_report timeline), the flight recorder (crash forensics), and the
supervisor's log.  ROADMAP item 4's canary/rollback consumes exactly this
seam — "roll back" is "an SLO burn alert fired during the roll".

Stdlib-only and jax-free (``train.resilience`` is host-side code).
"""

from __future__ import annotations

import dataclasses
import json
import time
from collections import deque
from typing import TYPE_CHECKING, Any, Deque, Dict, Iterable, List, Optional, Tuple

from relora_tpu.utils.logging import get_logger

if TYPE_CHECKING:  # pragma: no cover
    from relora_tpu.train.resilience import LossSpikeDetector, SpikeEvent

__all__ = [
    "SLO",
    "AnomalySpec",
    "Alert",
    "SLOEngine",
    "SeriesAnomalyDetector",
    "default_slos",
    "load_slo_config",
]

logger = get_logger("relora_tpu.slo")

#: (long_window_s, short_window_s, burn_factor) — SRE-workbook defaults:
#: 14.4x burn exhausts a 30-day budget in ~2 days (page now), 6x in ~5 days,
#: 1x is the slow-burn ticket tier.
DEFAULT_WINDOWS: Tuple[Tuple[float, float, float], ...] = (
    (3600.0, 300.0, 14.4),
    (21600.0, 1800.0, 6.0),
    (259200.0, 21600.0, 1.0),
)


@dataclasses.dataclass
class SLO:
    """One declarative objective over a stored series.

    A sample is *bad* when ``value <bad_when> threshold`` (``"lt"`` or
    ``"gt"``).  ``source=None`` evaluates the SLO independently against every
    source that carries the series (each replica gets its own budget);
    pinning ``source`` scopes it (e.g. the MFU floor to ``"train"``).
    """

    name: str
    series: str
    threshold: float
    bad_when: str = "gt"
    objective: float = 0.999
    source: Optional[str] = None
    windows: Tuple[Tuple[float, float, float], ...] = DEFAULT_WINDOWS
    description: str = ""

    def __post_init__(self) -> None:
        if self.bad_when not in ("lt", "gt"):
            raise ValueError(f"bad_when must be 'lt' or 'gt', got {self.bad_when!r}")
        if not 0.0 < self.objective < 1.0:
            raise ValueError(f"objective must be in (0, 1), got {self.objective}")

    @property
    def budget(self) -> float:
        return 1.0 - self.objective

    def is_bad(self, value: float) -> bool:
        return value < self.threshold if self.bad_when == "lt" else value > self.threshold


@dataclasses.dataclass
class AnomalySpec:
    """Median/MAD anomaly detection over a stored series, parameterized the
    same way as the trainer's loss-spike config."""

    series: str
    source: Optional[str] = None
    threshold: float = 4.0
    window: int = 64
    min_history: int = 16
    patience: int = 3
    min_deviation: float = 0.05
    direction: str = "high"  # "high": spikes up are anomalous; "low": drops

    def __post_init__(self) -> None:
        if self.direction not in ("high", "low"):
            raise ValueError(f"direction must be 'high' or 'low', got {self.direction!r}")


@dataclasses.dataclass
class Alert:
    """Lifecycle record of one (SLO, source) alert."""

    slo: str
    source: str
    state: str  # "firing" | "cleared"
    fired_at: float
    window_s: float = 0.0
    burn_long: float = 0.0
    burn_short: float = 0.0
    cleared_at: Optional[float] = None

    def key(self) -> str:
        return f"{self.slo}_{self.source}".replace("-", "_").replace(".", "_")


class SeriesAnomalyDetector:
    """Per-(source, series) :class:`LossSpikeDetector` bank.

    ``observe`` feeds one sample and returns the detector's ``SpikeEvent``
    when a sustained outlier run crosses patience — byte-for-byte the
    trainer's spike semantics, so a series that would have tripped the
    trainer's detector trips this one at the same sample (pinned by test).
    ``direction="low"`` negates samples so collapses (MFU falling off a
    cliff) register as spikes.
    """

    def __init__(self, specs: Iterable[AnomalySpec] = ()):
        self.specs = list(specs)
        self._detectors: Dict[Tuple[str, str], "LossSpikeDetector"] = {}
        self._steps: Dict[Tuple[str, str], int] = {}

    def _spec_for(self, source: str, series: str) -> Optional[AnomalySpec]:
        for spec in self.specs:
            if spec.series == series and spec.source in (None, source):
                return spec
        return None

    def observe(self, source: str, series: str, value: float) -> Optional["SpikeEvent"]:
        spec = self._spec_for(source, series)
        if spec is None:
            return None
        # Lazy: train.resilience itself is stdlib-only, but the train package
        # __init__ imports jax — keep that out of router/supervisor processes
        # until an anomaly spec is actually in use.
        from relora_tpu.train.resilience import LossSpikeDetector

        key = (source, series)
        det = self._detectors.get(key)
        if det is None:
            det = self._detectors[key] = LossSpikeDetector(
                threshold=spec.threshold,
                window=spec.window,
                min_history=spec.min_history,
                patience=spec.patience,
                min_deviation=spec.min_deviation,
            )
        step = self._steps.get(key, 0)
        self._steps[key] = step + 1
        return det.update(step, value if spec.direction == "high" else -value)


class SLOEngine:
    """Evaluates SLOs + anomaly specs against a SeriesStore and manages
    alert lifecycle.  ``evaluate`` is called by the collector once per scrape
    round; it is idempotent per timestamp and safe to call ad hoc (the
    fleet_report calls it once over a store rebuilt from disk)."""

    def __init__(
        self,
        slos: Iterable[SLO] = (),
        anomalies: Iterable[AnomalySpec] = (),
        history: int = 256,
    ):
        self.slos = list(slos)
        self.anomaly = SeriesAnomalyDetector(anomalies)
        self._alerts: Dict[Tuple[str, str], Alert] = {}
        self.history: Deque[Alert] = deque(maxlen=history)
        self._anomaly_seen: Dict[Tuple[str, str], float] = {}
        self._last_status: List[Dict[str, Any]] = []

    @classmethod
    def from_config(cls, path: Optional[str]) -> "SLOEngine":
        if path is None:
            return cls(default_slos())
        slos, anomalies = load_slo_config(path)
        return cls(slos, anomalies)

    # -- evaluation ----------------------------------------------------------

    def evaluate(self, store, now: Optional[float] = None) -> List[Dict[str, Any]]:
        """One evaluation pass.  Returns the list of transition events fired
        this pass (already recorded into store/flight/log)."""
        now = time.time() if now is None else now
        fired: List[Dict[str, Any]] = []
        status: List[Dict[str, Any]] = []
        for slo in self.slos:
            sources = [slo.source] if slo.source else [
                s for s in store.sources() if store.samples(s, slo.series)
            ]
            for source in sources:
                st = self._evaluate_one(store, slo, source, now)
                if st is None:
                    continue
                status.append(st)
                if st.pop("_transition", None) is not None:
                    fired.append(st)
        fired.extend(self._evaluate_anomalies(store, now))
        self._last_status = status
        return fired

    def _evaluate_one(
        self, store, slo: SLO, source: str, now: float
    ) -> Optional[Dict[str, Any]]:
        worst_burn = 0.0
        should_fire = False
        short_ok = True
        fire_window = 0.0
        burn_l = burn_s = 0.0
        evaluated = False
        for long_s, short_s, factor in slo.windows:
            long_vals = store.window_values(source, slo.series, long_s, now=now)
            short_vals = store.window_values(source, slo.series, short_s, now=now)
            if not long_vals:
                continue
            evaluated = True
            frac_long = sum(1 for v in long_vals if slo.is_bad(v)) / len(long_vals)
            frac_short = (
                sum(1 for v in short_vals if slo.is_bad(v)) / len(short_vals)
                if short_vals
                else 0.0
            )
            bl = frac_long / slo.budget
            bs = frac_short / slo.budget
            worst_burn = max(worst_burn, bl)
            if bs >= factor:
                short_ok = False
            if bl >= factor and bs >= factor:
                should_fire = True
                if bl > burn_l:
                    burn_l, burn_s, fire_window = bl, bs, long_s
        if not evaluated:
            return None
        key = (slo.name, source)
        active = self._alerts.get(key)
        transition = None
        if should_fire and (active is None or active.state != "firing"):
            active = Alert(
                slo=slo.name, source=source, state="firing", fired_at=now,
                window_s=fire_window, burn_long=burn_l, burn_short=burn_s,
            )
            self._alerts[key] = active
            self.history.append(active)
            transition = "fire"
            self._emit(store, "fire", slo, active, now)
        elif active is not None and active.state == "firing":
            active.burn_long, active.burn_short = max(burn_l, worst_burn), burn_s
            if short_ok and not should_fire:
                active.state = "cleared"
                active.cleared_at = now
                transition = "clear"
                self._emit(store, "clear", slo, active, now)
        return {
            "slo": slo.name,
            "source": source,
            "series": slo.series,
            "objective": slo.objective,
            "budget": slo.budget,
            "max_burn": round(worst_burn, 3),
            "state": "firing" if (active is not None and active.state == "firing") else "ok",
            "_transition": transition,
        }

    def _evaluate_anomalies(self, store, now: float) -> List[Dict[str, Any]]:
        fired: List[Dict[str, Any]] = []
        for spec in self.anomaly.specs:
            sources = [spec.source] if spec.source else store.sources()
            for source in sources:
                key = (source, spec.series)
                seen = self._anomaly_seen.get(key, 0.0)
                for t, v in store.samples(source, spec.series):
                    if t <= seen:
                        continue
                    self._anomaly_seen[key] = t
                    spike = self.anomaly.observe(source, spec.series, v)
                    if spike is not None:
                        detail = {
                            "series": spec.series,
                            "value": spike.loss,
                            "median": spike.median,
                            "mad": spike.mad,
                        }
                        store.add_event("series_anomaly", source, t=now, **detail)
                        self._flight_event("series_anomaly", source, detail)
                        logger.warning(f"series anomaly: {source}/{spec.series} {detail}")
                        fired.append({"anomaly": True, "source": source, **detail})
        return fired

    def _emit(self, store, kind: str, slo: SLO, alert: Alert, now: float) -> None:
        detail = {
            "slo": alert.slo,
            "series": slo.series,
            "state": kind,
            "window_s": alert.window_s,
            "burn_long": round(alert.burn_long, 3),
            "burn_short": round(alert.burn_short, 3),
            "objective": slo.objective,
        }
        store.add_event("slo_burn_alert", alert.source, t=now, **detail)
        self._flight_event("slo_burn_alert", alert.source, detail)
        log = logger.warning if kind == "fire" else logger.info
        log(
            f"SLO burn alert {kind}: {alert.slo} on {alert.source} "
            f"(burn {alert.burn_long:.1f}x long / {alert.burn_short:.1f}x short, "
            f"window {alert.window_s:g}s, objective {slo.objective})"
        )

    @staticmethod
    def _flight_event(name: str, source: str, detail: Dict[str, Any]) -> None:
        try:
            from relora_tpu.obs.flight import default_recorder

            default_recorder().add_event(
                {
                    "name": name,
                    "trace_id": "fleet",
                    "parent_id": None,
                    "t": time.monotonic(),
                    "thread": "fleet-collector",
                    "service": "fleet",
                    "attrs": {"source": source, **detail},
                }
            )
        except Exception:
            pass  # forensics must never break the control loop

    # -- queries -------------------------------------------------------------

    def active_alerts(self) -> List[Alert]:
        return [a for a in self._alerts.values() if a.state == "firing"]

    def status(self) -> Dict[str, Any]:
        """JSON-able SLO/error-budget status for ``/fleet/series`` and the
        fleet_report."""
        return {
            "objectives": [dict(s) for s in self._last_status],
            "active": [dataclasses.asdict(a) for a in self.active_alerts()],
            "history": [dataclasses.asdict(a) for a in self.history],
        }


def default_slos(window_scale: float = 1.0) -> List[SLO]:
    """The fleet's standing objectives.  ``window_scale`` compresses the
    burn windows (drills, tests); thresholds are deliberately loose — they
    are floors for "clearly broken", tuned per deployment via JSON config."""
    w = tuple(
        (long_s * window_scale, short_s * window_scale, factor)
        for long_s, short_s, factor in DEFAULT_WINDOWS
    )
    return [
        SLO(
            name="availability", series="up", threshold=1.0, bad_when="lt",
            objective=0.999, windows=w,
            description="replica answers /healthz 200",
        ),
        SLO(
            name="ttft_p95", series="relora_serve_ttft_seconds_p95", threshold=2.0,
            bad_when="gt", objective=0.95, windows=w,
            description="scraped p95 time-to-first-token under 2s",
        ),
        SLO(
            name="tpot_p95", series="relora_serve_tpot_seconds_p95", threshold=0.5,
            bad_when="gt", objective=0.95, windows=w,
            description="scraped p95 per-token latency under 500ms",
        ),
        SLO(
            name="error_rate", series="error_rate", threshold=0.05, bad_when="gt",
            objective=0.99, windows=w,
            description="under 5% of finished requests errored per scrape interval",
        ),
        SLO(
            name="mfu_floor", series="mfu", threshold=0.05, bad_when="lt",
            objective=0.90, source="train", windows=w,
            description="training MFU above 5% (collapse detector, not a target)",
        ),
    ]


def load_slo_config(path: str) -> Tuple[List[SLO], List[AnomalySpec]]:
    """Load a JSON SLO config::

        {
          "window_scale": 1.0,             # optional, scales default windows
          "slos": [ {"name": ..., "series": ..., "threshold": ...,
                     "bad_when": "gt", "objective": 0.99,
                     "source": null, "windows": [[60, 5, 10.0]] } ],
          "anomalies": [ {"series": "loss", "threshold": 4.0,
                          "patience": 3, "direction": "high"} ]
        }

    Omitting ``slos`` keeps the defaults (scaled); per-SLO ``windows``
    override the scaled defaults for that SLO.
    """
    with open(path) as fh:
        cfg = json.load(fh)
    scale = float(cfg.get("window_scale", 1.0))
    scaled = tuple(
        (long_s * scale, short_s * scale, factor)
        for long_s, short_s, factor in DEFAULT_WINDOWS
    )
    slos: List[SLO] = []
    if "slos" in cfg:
        for raw in cfg["slos"]:
            raw = dict(raw)
            windows = raw.pop("windows", None)
            slo = SLO(**raw)
            slo.windows = (
                tuple(tuple(wp) for wp in windows) if windows is not None else scaled
            )
            slos.append(slo)
    else:
        slos = default_slos(window_scale=scale)
    anomalies = [AnomalySpec(**raw) for raw in cfg.get("anomalies", [])]
    return slos, anomalies
