from relora_tpu.data.hf_pipeline import (
    tokenize_and_chunk,
    TokenBatchIterator,
    StreamingTokenIterator,
)
