"""Build GLUE-format classification tasks from local text (air-gapped hosts).

The reference evaluates ReLoRA-pretrained checkpoints on GLUE via
run_glue.py (reference run_glue.py:496-501); this sandbox has no hub
access, so these tasks stand in for GLUE in the pretrain -> fine-tune ->
metric pipeline: real discriminative tasks over the SAME local text the
pretraining corpus was built from (tools/build_text_corpus.py roots), in
run_glue.py's custom csv schema (``sentence[,sentence2],label``).

Three tasks, mirroring GLUE's task shapes:

- ``locdoc``   (SST-2 shape)  single segment, binary: code (.py) vs prose
  (.md/.rst/.txt).  Metric: accuracy.
- ``locpair``  (MRPC shape)   segment pair, binary: same document vs
  different documents.  Metrics: accuracy + F1.
- ``locorder`` (CoLA shape)   single segment, binary: natural word order
  vs seeded word-shuffle.  Metric: accuracy (+F1; CoLA's Matthews is
  keyed to the task name "cola" in eval/glue.py:task_metrics).
- ``locsim``   (STS-B shape)  segment pair, CONTINUOUS 0-5 label = 5x the
  exact character-overlap fraction between the two windows.  Metrics:
  pearson + spearman (run_glue.py infers regression from the float
  labels, the reference's dtype rule).
- ``locnsp``   (RTE shape)    short segment pair, binary: does sentence2
  directly continue sentence1?  Negatives are same-doc-far or cross-doc.
  Segments sized to survive seq-128 truncation.  Metric: accuracy + F1.

Usage::

    python tools/build_local_glue.py --out /tmp/local_glue \
        --roots /opt/venv/lib/python3.12/site-packages /usr/share/doc \
        --train 2000 --eval 400 --test 400
"""

from __future__ import annotations

import argparse
import csv
import json
import os
import random
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.build_text_corpus import harvest  # same harvest as the pretrain corpus

PROSE_EXT = (".md", ".rst", ".txt")
SEG_MIN, SEG_MAX = 200, 400  # chars per segment


def _segments(text: str, rng: random.Random, max_segments: int = 4):
    """Cut a file into a few clean, non-overlapping segments."""
    out = []
    n = len(text)
    if n < SEG_MIN:
        return out
    starts = rng.sample(range(0, max(n - SEG_MAX, 1)), k=min(max_segments, max(n // SEG_MAX, 1)))
    for s in sorted(starts):
        seg = " ".join(text[s : s + rng.randint(SEG_MIN, SEG_MAX)].split())
        if len(seg) >= SEG_MIN // 2:
            out.append(seg)
    return out


def build_pools(roots, max_mb: float, seed: int, need_per_class: int = 0):
    """Harvest files and bucket segments by document and by code/prose.

    Prose files (.md/.rst/.txt) are a small minority of the roots (mostly
    python trees), so a flat byte cap starves the code-vs-prose task; keep
    harvesting past the cap until BOTH classes can fill ``need_per_class``
    segments (or the roots are exhausted)."""
    rng = random.Random(seed)
    docs = []  # (is_code, [segments])
    rawdocs = []  # (is_code, full_text) — for continuity/overlap tasks
    n_code = n_prose = 0
    harvested = 0
    for path, text in harvest(roots, 1 << 40):
        harvested += len(text)
        segs = _segments(text, rng)
        if len(segs) >= 2:
            is_code = path.endswith(".py")
            docs.append((is_code, segs))
            rawdocs.append((is_code, text))
            if is_code:
                n_code += len(segs)
            else:
                n_prose += len(segs)
        if harvested >= max_mb * 1e6 and (
            not need_per_class or min(n_code, n_prose) >= need_per_class
        ):
            break
    rng.shuffle(docs)
    rng.shuffle(rawdocs)
    return docs, rawdocs, rng


def write_csv(path, rows, fields):
    with open(path, "w", newline="") as f:
        w = csv.DictWriter(f, fieldnames=fields)
        w.writeheader()
        w.writerows(rows)


def split_rows(rows, sizes):
    out, i = [], 0
    for n in sizes:
        out.append(rows[i : i + n])
        i += n
    return out


def task_locdoc(docs, rng, total):
    """code vs prose, single segment, balanced."""
    code = [s for is_code, segs in docs if is_code for s in segs]
    prose = [s for is_code, segs in docs if not is_code for s in segs]
    n = min(total // 2, len(code), len(prose))
    rows = [{"sentence": s, "label": 1} for s in rng.sample(code, n)] + [
        {"sentence": s, "label": 0} for s in rng.sample(prose, n)
    ]
    rng.shuffle(rows)
    return rows, ("sentence", "label")


def task_locpair(docs, rng, total):
    """same-doc vs cross-doc segment pairs, balanced."""
    if len(docs) < 2:
        raise ValueError("locpair needs at least 2 docs to draw cross-doc negatives")
    rows = []
    for i, (_, segs) in enumerate(docs):
        if len(rows) >= total:
            break
        a, b = rng.sample(segs, 2)
        rows.append({"sentence1": a, "sentence2": b, "label": 1})
        # re-draw until the 'other' doc differs: skipping the negative here
        # would drift the pair task off 50/50 balance
        other = docs[rng.randrange(len(docs))]
        while other[1] is segs:
            other = docs[rng.randrange(len(docs))]
        rows.append({"sentence1": rng.choice(segs), "sentence2": rng.choice(other[1]), "label": 0})
    rng.shuffle(rows)
    return rows[:total], ("sentence1", "sentence2", "label")


def task_locorder(docs, rng, total):
    """natural vs word-shuffled segments, balanced (CoLA-like acceptability)."""
    segs = [s for _, ss in docs for s in ss]
    rng.shuffle(segs)
    rows = []
    for i, s in enumerate(segs[:total]):
        if i % 2 == 0:
            rows.append({"sentence": s, "label": 1})
        else:
            words = s.split()
            rng.shuffle(words)
            rows.append({"sentence": " ".join(words), "label": 0})
    rng.shuffle(rows)
    return rows, ("sentence", "label")


SIM_LEN = 200  # chars per side: a pair fits seq 128 (~500 chars of tokens),
               # the truncation wall that made locpair chance-level at 128


def _clean(s: str) -> str:
    return " ".join(s.split())


def task_locsim(rawdocs, rng, total):
    """Graded-overlap similarity pairs, continuous 0-5 label (STS-B shape).

    sentence2 is a window shifted to share an exact fraction f of
    sentence1's characters; label = 5*f.  Half the f=0 pairs are cross-doc
    (no shared text at all).  Lexical overlap is a real, learnable,
    *continuous* signal, so pearson/spearman measure genuine regression
    ability — the reference's stsb path (run_glue.py:57-67, 496-501)."""
    texts = [t for _, t in rawdocs if len(t) >= 3 * SIM_LEN]
    if len(texts) < 2:
        raise ValueError("locsim needs at least 2 docs of >= 3*SIM_LEN chars")
    fractions = (0.0, 0.2, 0.4, 0.6, 0.8, 1.0)
    rows = []
    while len(rows) < total:
        t = texts[rng.randrange(len(texts))]
        s = rng.randrange(0, len(t) - 2 * SIM_LEN)
        a = _clean(t[s : s + SIM_LEN])
        f = fractions[len(rows) % len(fractions)]  # uniform label coverage
        if f == 0.0 and rng.random() < 0.5:
            o = texts[rng.randrange(len(texts))]
            while o is t:
                o = texts[rng.randrange(len(texts))]
            so = rng.randrange(0, len(o) - SIM_LEN)
            b = _clean(o[so : so + SIM_LEN])
        else:
            shift = int(SIM_LEN * (1.0 - f))
            b = _clean(t[s + shift : s + shift + SIM_LEN])
        if len(a) < SIM_LEN // 2 or len(b) < SIM_LEN // 2:
            continue
        rows.append({"sentence1": a, "sentence2": b, "label": round(5.0 * f, 1)})
    rng.shuffle(rows)
    return rows, ("sentence1", "sentence2", "label")


def task_locnsp(rawdocs, rng, total):
    """Next-segment prediction, binary (RTE shape, short segments).

    sentence2 either directly continues sentence1 (label 1) or is drawn
    far away in the same doc / from another doc (label 0, half each) —
    same-doc-far negatives force continuity understanding, not topic
    matching.  Segments are SIM_LEN chars so pairs survive seq-128
    truncation (locpair's 200-400-char segments did not)."""
    texts = [t for _, t in rawdocs if len(t) >= 6 * SIM_LEN]
    if len(texts) < 2:
        raise ValueError("locnsp needs at least 2 docs of >= 6*SIM_LEN chars")
    rows = []
    while len(rows) < total:
        t = texts[rng.randrange(len(texts))]
        s = rng.randrange(0, len(t) - 2 * SIM_LEN)
        a = _clean(t[s : s + SIM_LEN])
        b_pos = _clean(t[s + SIM_LEN : s + 2 * SIM_LEN])
        if len(a) < SIM_LEN // 2 or len(b_pos) < SIM_LEN // 2:
            continue
        rows.append({"sentence1": a, "sentence2": b_pos, "label": 1})
        if rng.random() < 0.5:
            far = rng.randrange(0, len(t) - SIM_LEN)
            while abs(far - (s + SIM_LEN)) < 2 * SIM_LEN:
                far = rng.randrange(0, len(t) - SIM_LEN)
            b_neg = _clean(t[far : far + SIM_LEN])
        else:
            o = texts[rng.randrange(len(texts))]
            while o is t:
                o = texts[rng.randrange(len(texts))]
            so = rng.randrange(0, len(o) - SIM_LEN)
            b_neg = _clean(o[so : so + SIM_LEN])
        rows.append({"sentence1": a, "sentence2": b_neg, "label": 0})
    rng.shuffle(rows)
    return rows[:total], ("sentence1", "sentence2", "label")


# segment-pool tasks consume (is_code, [segments]); raw-text tasks consume
# (is_code, full_text) — continuity and overlap need contiguous documents
TASKS = {"locdoc": task_locdoc, "locpair": task_locpair, "locorder": task_locorder}
RAW_TASKS = {"locsim": task_locsim, "locnsp": task_locnsp}


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--out", required=True)
    p.add_argument(
        "--roots",
        nargs="+",
        default=["/opt/venv/lib/python3.12/site-packages", "/usr/share/doc", "/usr/lib/python3.12"],
    )
    p.add_argument("--max-mb", type=float, default=60.0)
    p.add_argument("--train", type=int, default=2000)
    p.add_argument("--eval", type=int, default=400)
    p.add_argument("--test", type=int, default=400)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)

    total = args.train + args.eval + args.test
    docs, rawdocs, rng = build_pools(args.roots, args.max_mb, args.seed, need_per_class=total // 2)
    print(f"harvested {len(docs)} documents")
    meta = {"roots": args.roots, "seed": args.seed, "n_docs": len(docs), "tasks": {}}
    for name, fn in {**TASKS, **RAW_TASKS}.items():
        rows, fields = fn(rawdocs if name in RAW_TASKS else docs, rng, total)
        sizes = (args.train, args.eval, args.test)
        if len(rows) < total:
            # a class pool ran dry (prose is scarce in python trees): keep
            # the requested train:eval:test proportions over what exists
            sizes = tuple(int(len(rows) * s / total) for s in sizes)
        tr, ev, te = split_rows(rows, sizes)
        tdir = os.path.join(args.out, name)
        os.makedirs(tdir, exist_ok=True)
        write_csv(os.path.join(tdir, "train.csv"), tr, fields)
        write_csv(os.path.join(tdir, "validation.csv"), ev, fields)
        write_csv(os.path.join(tdir, "test.csv"), te, fields)
        kind = "continuous" if name == "locsim" else "binary"
        bal = sum(r["label"] for r in ev) / max(len(ev), 1)
        stat = "eval_label_mean" if kind == "continuous" else "eval_label_balance"
        meta["tasks"][name] = {"train": len(tr), "validation": len(ev), "test": len(te),
                               stat: round(bal, 3), "fields": list(fields),
                               "label_kind": kind}
        print(f"{name}: train={len(tr)} validation={len(ev)} test={len(te)} "
              + (f"eval_label_mean={bal:.3f}" if kind == "continuous" else f"eval_pos_rate={bal:.3f}"))
    with open(os.path.join(args.out, "meta.json"), "w") as f:
        json.dump(meta, f, indent=2)


if __name__ == "__main__":
    main()
