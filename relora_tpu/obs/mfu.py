"""Peak-FLOPs detection and live MFU from compiled-step cost analysis.

Two MFU paths share this module:

- ``bench.py`` / ``utils/benchlib.py``: offline throughput benches that
  previously hardcoded the v5e peak (``PEAK_FLOPS_V5E``).
- the trainer's **live MFU gauge**: per-update MFU computed from the actual
  FLOPs XLA reports for the compiled train step (``lower(...).cost_analysis()
  ['flops']``), falling back to the 6ND approximation when cost analysis is
  unavailable.  cost_analysis counts what the program *really* does —
  attention scores, remat recomputation, LoRA factor matmuls — where 6ND is
  a dense-transformer estimate, so the two can legitimately differ by tens
  of percent under remat.

Peak-FLOPs resolution order: ``RELORA_TPU_PEAK_FLOPS`` env override, then a
``device_kind`` substring match against :data:`PEAK_FLOPS_BY_KIND`, then the
v5e default (keeps historical bench numbers comparable when detection
fails, e.g. on the CPU backend).
"""

from __future__ import annotations

import os
from typing import Any, Optional

__all__ = [
    "PEAK_FLOPS_BY_KIND",
    "PEAK_FLOPS_DEFAULT",
    "peak_flops",
    "step_flops_from_cost_analysis",
]

#: bf16 peak FLOPs/s of one chip, keyed by a lowercase substring of
#: ``jax.devices()[0].device_kind``.  Order matters: first match wins, so
#: longer / more specific kinds come before their prefixes (v5e before v5,
#: v6e before v6).
PEAK_FLOPS_BY_KIND = (
    ("v6e", 918e12),        # Trillium
    ("v5p", 459e12),
    ("v5e", 197e12),        # aka v5 lite
    ("v5litepod", 197e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
    ("h100", 989e12),       # dense bf16, SXM
    ("a100", 312e12),
)

#: historical default (one TPU v5e chip) — used when the device kind is
#: unrecognized, e.g. the CPU backend in tests
PEAK_FLOPS_DEFAULT = 197e12


def peak_flops(device: Optional[Any] = None) -> float:
    """Peak bf16 FLOPs/s for ``device`` (default: ``jax.devices()[0]``).

    ``RELORA_TPU_PEAK_FLOPS`` overrides everything — the escape hatch for
    hardware this table has not met.
    """
    env = os.environ.get("RELORA_TPU_PEAK_FLOPS")
    if env:
        return float(env)
    if device is None:
        try:
            import jax

            device = jax.devices()[0]
        except Exception:
            return PEAK_FLOPS_DEFAULT
    kind = str(getattr(device, "device_kind", "")).lower()
    for needle, flops in PEAK_FLOPS_BY_KIND:
        if needle in kind:
            return flops
    return PEAK_FLOPS_DEFAULT


def step_flops_from_cost_analysis(cost: Any) -> Optional[float]:
    """Extract total FLOPs from a jax cost-analysis result.

    Handles both shapes jax returns across versions: ``lowered.cost_analysis()``
    gives a dict, ``compiled.cost_analysis()`` gives a list of per-computation
    dicts.  Returns None when no positive 'flops' entry exists (e.g. some
    backends report nothing), signalling the caller to fall back to 6ND.
    """
    if cost is None:
        return None
    if isinstance(cost, dict):
        cost = [cost]
    try:
        total = sum(float(c.get("flops", 0.0)) for c in cost if isinstance(c, dict))
    except (TypeError, ValueError):
        return None
    return total if total > 0 else None
