"""Tests for relora_tpu.analysis — the RTL footgun linter.

Per rule: a bad fixture that must fire and the corrected idiom that must
stay quiet.  Plus suppression (# noqa), baseline round-trip, and the repo
self-check (the tree lints clean against the checked-in baseline, with no
stale entries).

Pure stdlib — no jax import, no devices; these run anywhere, fast.
"""

import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

from relora_tpu.analysis import (
    RULE_CATALOG,
    BaselineEntry,
    Finding,
    format_baseline_entry,
    lint_paths,
    lint_text,
    load_baseline,
)

pytestmark = pytest.mark.lint

REPO_ROOT = Path(__file__).resolve().parents[1]


def codes(src: str, *, hot: bool = False) -> list:
    return [f.code for f in lint_text(textwrap.dedent(src), force_hot=hot)]


# ---------------------------------------------------------------------------
# RTL1xx retrace hazards


def test_rtl101_branch_on_tracer_fires():
    src = """
        import jax

        @jax.jit
        def f(x):
            if x > 0:
                return x
            return -x
    """
    assert "RTL101" in codes(src)


def test_rtl101_clean_where_idiom():
    src = """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            return jnp.where(x > 0, x, -x)
    """
    assert codes(src) == []


def test_rtl101_static_shape_checks_ok():
    # shape/ndim/isinstance/None-checks on traced args are host-static
    src = """
        import jax

        @jax.jit
        def f(x, mask=None):
            if x.ndim == 2:
                x = x[None]
            if mask is None:
                return x
            if isinstance(mask, tuple):
                mask = mask[0]
            return x * mask
    """
    assert codes(src) == []


def test_rtl102_unhashable_static_arg_fires():
    src = """
        import jax

        def f(x, sizes):
            return x

        g = jax.jit(f, static_argnums=(1,))

        def caller(x):
            return g(x, [1, 2, 3])
    """
    assert "RTL102" in codes(src)


def test_rtl102_tuple_static_arg_ok():
    src = """
        import jax

        def f(x, sizes):
            return x

        g = jax.jit(f, static_argnums=(1,))

        def caller(x):
            return g(x, (1, 2, 3))
    """
    assert codes(src) == []


def test_rtl103_jit_inside_loop_fires():
    src = """
        import jax

        def run(fns, x):
            for fn in fns:
                x = jax.jit(fn)(x)
            return x
    """
    assert "RTL103" in codes(src)


def test_rtl103_jit_hoisted_ok():
    src = """
        import jax

        def run(fn, xs):
            fast = jax.jit(fn)
            for x in xs:
                x = fast(x)
            return x
    """
    assert codes(src) == []


def test_rtl104_fstring_on_tracer_fires():
    src = """
        import jax

        @jax.jit
        def f(x):
            print(f"x is {x}")
            return x
    """
    assert "RTL104" in codes(src)


def test_rtl104_debug_print_ok():
    src = """
        import jax

        @jax.jit
        def f(x):
            jax.debug.print("x is {}", x)
            return x
    """
    assert codes(src) == []


# ---------------------------------------------------------------------------
# RTL2xx host syncs (hot regions; force_hot marks the fixture hot)


def test_rtl201_item_fires_hot_only():
    src = """
        def loop(xs):
            total = 0.0
            for x in xs:
                total += x.mean().item()
            return total
    """
    assert "RTL201" in codes(src, hot=True)
    assert codes(src, hot=False) == []  # same code cold: no finding


def test_rtl202_float_on_computed_fires():
    src = """
        def loop(metrics):
            return float(metrics["loss"])
    """
    assert "RTL202" in codes(src, hot=True)


def test_rtl202_static_scalars_ok():
    src = """
        import time

        def loop(batch, dt):
            n = int(batch.size)
            t = float(time.monotonic())
            return n, t, float(dt)
    """
    assert codes(src, hot=True) == []


def test_rtl203_block_until_ready_fires():
    src = """
        import jax

        def loop(state):
            jax.block_until_ready(state.params)
    """
    assert "RTL203" in codes(src, hot=True)


def test_rtl204_np_asarray_fires_jnp_ok():
    bad = """
        import numpy as np

        def loop(x):
            return np.asarray(x)
    """
    good = """
        import jax.numpy as jnp

        def loop(x):
            return jnp.asarray(x)  # host->device: fine
    """
    assert "RTL204" in codes(bad, hot=True)
    assert codes(good, hot=True) == []


def test_hot_marker_comment_activates_rules():
    src = """
        # relora-lint: hot-path

        def loop(x):
            return x.item()
    """
    assert "RTL201" in codes(src)


# ---------------------------------------------------------------------------
# RTL3xx donation


def test_rtl301_read_after_donation_fires():
    src = """
        import jax

        def make(step):
            step_fn = jax.jit(step, donate_argnums=(0,))

            def run(state, batch):
                new_state, metrics = step_fn(state, batch)
                return new_state, state.step  # donated buffer read
            return run
    """
    assert "RTL301" in codes(src)


def test_rtl301_rebind_ok():
    src = """
        import jax

        def make(step):
            step_fn = jax.jit(step, donate_argnums=(0,))

            def run(state, batch):
                state, metrics = step_fn(state, batch)
                return state, state.step
            return run
    """
    assert codes(src) == []


def test_rtl301_loop_reuse_fires():
    # donated on iteration 1, passed again on iteration 2
    src = """
        import jax

        def make(step):
            step_fn = jax.jit(step, donate_argnums=(0,))

            def run(state, batches):
                for b in batches:
                    new_state = step_fn(state, b)
                return new_state
            return run
    """
    assert "RTL301" in codes(src)


def test_rtl301_donation_is_function_scoped():
    # two sibling functions binding the same name: one donates, one doesn't.
    # the non-donating one must not inherit the other's donate_argnums.
    src = """
        import jax

        def donating(step, state, batch):
            step = jax.jit(step, donate_argnums=0)
            new_state, m = step(state, batch)
            return new_state

        def plain(step, state, batch):
            step = jax.jit(step)
            new_state, m = step(state, batch)
            return new_state, state.step  # fine: nothing was donated
    """
    assert codes(src) == []


def test_rtl302_missing_donation_fires():
    src = """
        import jax

        def step(state, batch):
            return state

        step_fn = jax.jit(step)
    """
    assert "RTL302" in codes(src)


def test_rtl302_decorated_def_fires():
    src = """
        import jax

        @jax.jit
        def train_step(params, opt_state, batch):
            return params, opt_state
    """
    assert "RTL302" in codes(src)


def test_rtl302_with_donation_ok():
    src = """
        import functools
        import jax

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def train_step(params, opt_state, batch):
            return params, opt_state

        def step(state, batch):
            return state

        step_fn = jax.jit(step, donate_argnums=(0,))
    """
    assert codes(src) == []


# ---------------------------------------------------------------------------
# RTL4xx RNG hygiene


def test_rtl401_key_reuse_fires():
    src = """
        import jax

        def init(key):
            a = jax.random.normal(key, (4,))
            b = jax.random.normal(key, (4,))
            return a, b
    """
    assert "RTL401" in codes(src)


def test_rtl401_split_ok():
    src = """
        import jax

        def init(key):
            key, sub = jax.random.split(key)
            a = jax.random.normal(sub, (4,))
            key, sub = jax.random.split(key)
            b = jax.random.normal(sub, (4,))
            return a, b
    """
    assert codes(src) == []


def test_rtl401_exclusive_branches_ok():
    # one consumption per runtime path is fine
    src = """
        import jax

        def draw(key, uniform):
            if uniform:
                return jax.random.uniform(key, (4,))
            else:
                return jax.random.normal(key, (4,))
    """
    assert codes(src) == []


def test_rtl402_time_seed_fires():
    src = """
        import time
        import jax

        def make_key():
            return jax.random.PRNGKey(int(time.time()))
    """
    assert "RTL402" in codes(src)


def test_rtl402_config_seed_ok():
    src = """
        import jax

        def make_key(cfg):
            return jax.random.PRNGKey(cfg.seed)
    """
    assert codes(src) == []


# ---------------------------------------------------------------------------
# RTL5xx pytree / sharding


def test_rtl501_inplace_params_mutation_fires():
    src = """
        def graft(params, new_head):
            params["lm_head"] = new_head
            return params
    """
    assert "RTL501" in codes(src)


def test_rtl501_dict_mutator_fires():
    src = """
        def prune(params, name):
            params.pop(name)
            return params
    """
    assert "RTL501" in codes(src)


def test_rtl501_rebuild_or_rebind_ok():
    src = """
        def graft(params, new_head):
            return {**params, "lm_head": new_head}

        def prune(params, name):
            params = dict(params)
            params.pop(name)
            return params
    """
    assert codes(src) == []


def test_rtl502_specless_shard_map_fires():
    src = """
        from jax.experimental.shard_map import shard_map

        def wrap(f, mesh):
            return shard_map(f, mesh)
    """
    assert "RTL502" in codes(src)


def test_rtl502_explicit_specs_ok():
    src = """
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        def wrap(f, mesh):
            return shard_map(f, mesh, in_specs=(P("dp"),), out_specs=P("dp"))
    """
    assert codes(src) == []


# ---------------------------------------------------------------------------
# engine: catalog, suppression, baseline, CLI, repo self-check


def test_catalog_covers_all_families():
    assert len(RULE_CATALOG) >= 10
    families = {code[:4] for code in RULE_CATALOG}
    assert families == {"RTL1", "RTL2", "RTL3", "RTL4", "RTL5"}


def test_noqa_suppresses_specific_and_bare():
    src = """
        def graft(params, new_head):
            params["lm_head"] = new_head  # noqa: RTL501
            return params

        def graft2(params, new_head):
            params["lm_head"] = new_head  # noqa
            return params

        def graft3(params, new_head):
            params["lm_head"] = new_head  # noqa: RTL101
            return params
    """
    found = lint_text(textwrap.dedent(src))
    # first two suppressed; the wrong-code noqa does not suppress
    assert [f.code for f in found] == ["RTL501"]


def test_baseline_roundtrip(tmp_path):
    f = Finding("pkg/mod.py", 3, "RTL501", "msg", 'params["x"] = y')
    line = format_baseline_entry(f, "intentional: grafting owns the dict")
    p = tmp_path / "baseline.txt"
    p.write_text("# comment\n\n" + line + "\n")
    entries = load_baseline(str(p))
    assert len(entries) == 1 and entries[0].matches(f)
    # different line text (the code changed) no longer matches
    assert not entries[0].matches(
        Finding("pkg/mod.py", 3, "RTL501", "msg", 'params["y"] = y')
    )


def test_baseline_requires_justification(tmp_path):
    p = tmp_path / "baseline.txt"
    p.write_text("a.py | RTL501 | x = 1 |\n")
    with pytest.raises(ValueError):
        load_baseline(str(p))


def test_lint_paths_baseline_and_stale(tmp_path):
    mod = tmp_path / "mod.py"
    mod.write_text("def f(params, v):\n    params['k'] = v\n    return params\n")
    baseline = [
        BaselineEntry("mod.py", "RTL501", "params['k'] = v", "ok", 1),
        BaselineEntry("mod.py", "RTL101", "gone", "stale entry", 2),
    ]
    report = lint_paths([str(mod)], root=str(tmp_path), baseline=baseline)
    assert report.new == []
    assert report.baselined == 1
    assert [e.lineno for e in report.stale_baseline] == [2]


def test_cli_exit_codes(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("def f(params, v):\n    params['k'] = v\n")
    clean = tmp_path / "clean.py"
    clean.write_text("def f(params, v):\n    return {**params, 'k': v}\n")
    env_root = str(REPO_ROOT)

    r = subprocess.run(
        [sys.executable, "-m", "relora_tpu.analysis", "--no-baseline", str(bad)],
        capture_output=True,
        text=True,
        cwd=env_root,
    )
    assert r.returncode == 1
    assert "RTL501" in r.stdout

    r = subprocess.run(
        [sys.executable, "-m", "relora_tpu.analysis", "--no-baseline", str(clean)],
        capture_output=True,
        text=True,
        cwd=env_root,
    )
    assert r.returncode == 0
    assert r.stdout == ""


def test_repo_lints_clean_against_baseline():
    """The tree itself must pass: no new findings, no stale baseline rows,
    no parse errors.  This is the tier-1 lint gate."""
    report = lint_paths(
        [str(REPO_ROOT / "relora_tpu")],
        root=str(REPO_ROOT),
        baseline=str(REPO_ROOT / "tools" / "lint_baseline.txt"),
    )
    assert report.parse_errors == []
    assert [f.render() for f in report.new] == []
    assert [e.path + "|" + e.code for e in report.stale_baseline] == []
    # the linter actually ran over the package, not an empty dir
    assert report.files_scanned > 40


def test_repo_baseline_entries_are_justified():
    entries = load_baseline(str(REPO_ROOT / "tools" / "lint_baseline.txt"))
    assert entries, "baseline exists and has entries"
    for e in entries:
        assert len(e.justification) > 10, f"thin justification: {e}"
