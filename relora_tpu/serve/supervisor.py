"""Process supervisor for the multi-replica serving tier.

Spawns N replica processes (normally ``serve.py --port 0 --port-file ...``),
restarts the ones that crash, and turns SIGTERM into a rolling drain.  Pure
stdlib (subprocess + threading) and jax-free: this process must stay
responsive while its children fight the accelerator.

Restart policy:

- **Crash-loop backoff.**  A replica that exits uncleanly is respawned
  after ``min(backoff_base_s * 2^(consecutive-1), backoff_cap_s)`` plus up
  to ``backoff_jitter`` relative jitter (so N replicas felled by one cause
  do not respawn in lockstep).  A clean exit during a drain is not a crash.
- **Quarantine.**  A replica that crashes ``quarantine_after`` times within
  ``crash_window_s`` is quarantined: no further restarts, a loud log line,
  and a ``quarantined`` flag in :meth:`status` — flapping is a bug to
  diagnose (docs/operations.md has the runbook), not a loop to hide.
- **Rolling drain.**  ``begin_rolling_drain()`` (wired to SIGTERM by the
  CLI) SIGTERMs replicas one at a time, waiting for each to finish its
  graceful drain (``serve_drain_complete``) before touching the next — the
  router keeps serving from the others throughout, so a fleet SIGTERM
  loses zero requests.
- **Elastic scaling.**  ``scale_up()``/``scale_down()`` add or drain one
  replica at a time (serve/autoscale.py decides when, ``--autoscale`` arms
  it).  Scale actions and the rolling drain serialize behind one scale
  lock; a drain cancels every scale action requested after it began, so a
  SIGTERM never races a concurrent autoscaler decision.

Port discovery is file-based and restart-safe: each replica gets
``--port 0 --port-file <workdir>/replica_<i>.port``; the supervisor deletes
the port file before every (re)spawn and :meth:`endpoints` reports ``None``
until the new incarnation has bound.  The router re-reads ``endpoints``
every probe round, so a restarted replica's new ephemeral port is picked up
automatically.  A ``replica_<i>.pid`` file is kept current for external
drills (``kill -9 $(cat replica_0.pid)`` in scripts/smoke_test.sh).

CLI — supervisor + router as one front-end process::

    python -m relora_tpu.serve.supervisor --replicas 2 \\
        --router-port 8000 --workdir /tmp/fleet -- \\
        python serve.py --checkpoint ckpts/relora/model_20000 \\
            --model_config llama_250m --max-batch 4
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import random
import signal
import subprocess
import sys
import threading
import time
from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Sequence, Tuple, Union

from relora_tpu.utils.logging import get_logger

logger = get_logger(__name__)

#: command: a base argv (the supervisor appends ``--port 0 --port-file ...``),
#: or a callable ``(replica_idx, port_file) -> argv`` for full control
ReplicaCommand = Union[Sequence[str], Callable[[int, str], Sequence[str]]]


def backoff_delay(
    consecutive: int,
    *,
    base_s: float = 0.5,
    cap_s: float = 30.0,
    jitter: float = 0.1,
    rand: Callable[[], float] = random.random,
) -> float:
    """Exponential crash-loop backoff: ``min(base * 2^(n-1), cap)`` plus up
    to ``jitter`` relative jitter.  ``consecutive`` is the crash streak
    (>= 1)."""
    delay = min(base_s * (2.0 ** max(consecutive - 1, 0)), cap_s)
    return delay * (1.0 + jitter * rand())


@dataclasses.dataclass
class _Replica:
    idx: int
    port_file: str
    pid_file: str
    log_path: str
    proc: Optional[subprocess.Popen] = None
    log_fh: Optional[object] = None
    restarts: int = 0
    consecutive_crashes: int = 0
    crash_times: Deque[float] = dataclasses.field(default_factory=deque)
    restart_at: Optional[float] = None  # backoff deadline; None = not pending
    quarantined: bool = False
    draining: bool = False  # SIGTERM sent by a rolling drain; exit expected
    last_exit_code: Optional[int] = None

    @property
    def rid(self) -> str:
        return f"r{self.idx}"


class ReplicaSupervisor:
    """Spawn, watch, restart, quarantine, and drain N replica processes."""

    def __init__(
        self,
        command: ReplicaCommand,
        n_replicas: int,
        workdir: str,
        *,
        backoff_base_s: float = 0.5,
        backoff_cap_s: float = 30.0,
        backoff_jitter: float = 0.1,
        quarantine_after: int = 5,
        crash_window_s: float = 120.0,
        drain_timeout_s: float = 60.0,
        poll_interval_s: float = 0.1,
        env_overrides: Optional[Dict[int, Dict[str, str]]] = None,
        env_overrides_respawn: bool = True,
        on_event: Optional[Callable[[str, int, Dict], None]] = None,
        roles: Optional[Dict[int, str]] = None,
        peer_file: Optional[str] = None,
    ):
        if n_replicas < 1:
            raise ValueError(f"n_replicas must be >= 1, got {n_replicas}")
        self.command = command
        self.workdir = workdir
        # disaggregated fleet: replica index -> role (absent = "mixed"); the
        # supervisor maintains peer_file (peers.json) so prefill replicas can
        # find decode peers without a discovery service
        self.roles = dict(roles or {})
        self.peer_file = peer_file
        self._last_peers: Optional[str] = None
        self.backoff_base_s = backoff_base_s
        self.backoff_cap_s = backoff_cap_s
        self.backoff_jitter = backoff_jitter
        self.quarantine_after = quarantine_after
        self.crash_window_s = crash_window_s
        self.drain_timeout_s = drain_timeout_s
        self.poll_interval_s = poll_interval_s
        # per-replica-index env on top of os.environ: how a drill arms a
        # fault on one replica.  env_overrides_respawn=False applies them to
        # the first incarnation only — crash once, come back clean, which is
        # the "kill one replica under load" drill shape.
        self.env_overrides = env_overrides or {}
        self.env_overrides_respawn = env_overrides_respawn
        self.on_event = on_event  # (event, replica_idx, detail) — tests hook this
        os.makedirs(workdir, exist_ok=True)
        self._replicas = [
            _Replica(
                idx=i,
                port_file=os.path.join(workdir, f"replica_{i}.port"),
                pid_file=os.path.join(workdir, f"replica_{i}.pid"),
                log_path=os.path.join(workdir, f"replica_{i}.log"),
            )
            for i in range(n_replicas)
        ]
        self._lock = threading.RLock()
        # serializes scale actions against each other AND against the rolling
        # drain: begin_rolling_drain holds it for its whole duration, so a
        # concurrent autoscaler decision either completes first or is
        # cancelled — never interleaves with the drain (the SIGTERM race)
        self._scale_lock = threading.RLock()
        self._next_idx = n_replicas  # monotonic: freed indices are never reused
        self._stop = threading.Event()
        self._draining = False
        self._monitor: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        for rep in self._replicas:
            self._spawn(rep, first=True)
        self._monitor = threading.Thread(
            target=self._monitor_loop, name="supervisor", daemon=True
        )
        self._monitor.start()

    def stop(self) -> None:
        """Immediate teardown (test/bench cleanup): SIGKILL everything."""
        self._stop.set()
        with self._lock:
            reps = list(self._replicas)
            for rep in reps:
                if rep.proc is not None and rep.proc.poll() is None:
                    rep.proc.kill()
        for rep in reps:
            if rep.proc is not None:
                try:
                    rep.proc.wait(timeout=10.0)
                except subprocess.TimeoutExpired:
                    pass
            if rep.log_fh is not None:
                rep.log_fh.close()
                rep.log_fh = None
        if self._monitor is not None:
            self._monitor.join(timeout=5.0)

    def begin_rolling_drain(self) -> None:
        """SIGTERM replicas one at a time, each graceful drain completing
        before the next starts — the rest of the fleet keeps serving.
        Blocks until every replica has exited (or drain_timeout_s forces a
        kill); idempotent-ish: a second call finds nothing left to drain.

        Holds the scale lock for its whole duration: an in-flight scale
        action finishes first, and every scale action requested after the
        drain began is cancelled (``_draining`` is set before the lock is
        released to a waiting ``scale_up``/``scale_down``)."""
        with self._lock:
            self._draining = True
        with self._scale_lock:
            logger.info("rolling drain: one replica at a time")
            with self._lock:
                reps = list(self._replicas)
            for rep in reps:
                with self._lock:
                    proc = rep.proc
                    if proc is None or proc.poll() is not None:
                        continue
                    rep.draining = True
                self._event("drain_begin", rep)
                proc.send_signal(signal.SIGTERM)
                try:
                    proc.wait(timeout=self.drain_timeout_s)
                except subprocess.TimeoutExpired:
                    logger.error(
                        f"replica {rep.rid}: drain exceeded {self.drain_timeout_s}s; killing"
                    )
                    proc.kill()
                    proc.wait(timeout=10.0)
                self._remove_stale(rep)
                self._event("drain_complete", rep, exit_code=proc.returncode)
                logger.info(f"replica {rep.rid} drained (exit {proc.returncode})")
            self._stop.set()

    # -- elastic scaling (the autoscaler's levers) ---------------------------

    def scale_up(self) -> Optional[str]:
        """Add one replica: spawn a new process with the next (never-reused)
        index and report it via ``endpoints`` — the router picks it up on
        its next probe round.  Returns the new rid, or ``None`` when the
        action was cancelled because the fleet is draining or stopping
        (a decision made *before* a SIGTERM landed must not spawn a process
        the drain will never visit)."""
        with self._scale_lock:
            with self._lock:
                if self._draining or self._stop.is_set():
                    self._event("autoscale_up_cancelled", None, reason="draining")
                    logger.info("autoscale: scale-up cancelled — fleet is draining")
                    return None
                idx = self._next_idx
                self._next_idx += 1
                rep = _Replica(
                    idx=idx,
                    port_file=os.path.join(self.workdir, f"replica_{idx}.port"),
                    pid_file=os.path.join(self.workdir, f"replica_{idx}.pid"),
                    log_path=os.path.join(self.workdir, f"replica_{idx}.log"),
                )
                self._replicas.append(rep)
                self._spawn(rep, first=True)
                pid = rep.proc.pid if rep.proc is not None else None
            self._event("autoscale_up", rep, pid=pid)
            logger.info(f"autoscale: added replica {rep.rid} (pid {pid})")
            return rep.rid

    def scale_down(self, idx: Optional[int] = None) -> Optional[str]:
        """Drain and remove one replica (default: the newest non-draining
        one).  Blocks through the graceful drain, then drops the replica
        from the fleet entirely — ``endpoints``/``status`` stop reporting
        it.  Refuses (returns ``None``) when the fleet is draining/stopping,
        when it would leave fewer than one live replica, or when ``idx``
        names a replica that is gone or already draining."""
        with self._scale_lock:
            with self._lock:
                if self._draining or self._stop.is_set():
                    return None
                candidates = [
                    r for r in self._replicas if not r.draining and not r.quarantined
                ]
                if len(candidates) <= 1:
                    return None
                if idx is None:
                    rep = candidates[-1]
                else:
                    matches = [r for r in candidates if r.idx == idx]
                    if not matches:
                        return None
                    rep = matches[0]
                rep.draining = True
                proc = rep.proc
            self._event("autoscale_down", rep)
            exit_code: Optional[int] = None
            if proc is not None and proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
                try:
                    proc.wait(timeout=self.drain_timeout_s)
                except subprocess.TimeoutExpired:
                    logger.error(
                        f"replica {rep.rid}: scale-down drain exceeded "
                        f"{self.drain_timeout_s}s; killing"
                    )
                    proc.kill()
                    proc.wait(timeout=10.0)
                exit_code = proc.returncode
            self._remove_stale(rep)
            with self._lock:
                self._replicas = [r for r in self._replicas if r is not rep]
            if rep.log_fh is not None:
                rep.log_fh.close()
                rep.log_fh = None
            self._event("autoscale_down_complete", rep, exit_code=exit_code)
            logger.info(f"autoscale: removed replica {rep.rid} (exit {exit_code})")
            return rep.rid

    def n_live(self) -> int:
        """Replicas that count toward capacity: not draining, not
        quarantined (a crash-looping replica in backoff still counts — it
        is coming back; the autoscaler must not double-provision it)."""
        with self._lock:
            return sum(
                1 for r in self._replicas if not r.draining and not r.quarantined
            )

    # -- the router's view ---------------------------------------------------

    def endpoints(self) -> Dict[str, Tuple[str, Optional[int]]]:
        """Live {rid: (host, port-or-None)} — port None while a replica is
        down, restarting, or quarantined.  The router polls this every probe
        round, so restarts (new ephemeral ports) propagate automatically."""
        out: Dict[str, Tuple[str, Optional[int]]] = {}
        with self._lock:
            reps = list(self._replicas)
        for rep in reps:
            port: Optional[int] = None
            if rep.proc is not None and rep.proc.poll() is None:
                try:
                    with open(rep.port_file) as f:
                        port = int(f.read().strip())
                except (OSError, ValueError):
                    port = None  # not bound yet
            out[rep.rid] = ("127.0.0.1", port)
        return out

    def status(self) -> Dict[str, Dict]:
        with self._lock:
            return {
                rep.rid: {
                    "pid": rep.proc.pid if rep.proc is not None else None,
                    "running": rep.proc is not None and rep.proc.poll() is None,
                    "restarts": rep.restarts,
                    "consecutive_crashes": rep.consecutive_crashes,
                    "quarantined": rep.quarantined,
                    "draining": rep.draining,
                    "last_exit_code": rep.last_exit_code,
                }
                for rep in self._replicas
            }

    def pid(self, idx: int) -> Optional[int]:
        rep = self._rep_by_idx(idx)
        return rep.proc.pid if rep is not None and rep.proc is not None else None

    def send_signal(self, idx: int, sig: int) -> None:
        """Deliver a signal to one replica (drills: SIGKILL under load)."""
        rep = self._rep_by_idx(idx)
        if rep is not None and rep.proc is not None and rep.proc.poll() is None:
            rep.proc.send_signal(sig)

    # -- internals -----------------------------------------------------------

    def _rep_by_idx(self, idx: int) -> Optional[_Replica]:
        # replica index != list position once the fleet has scaled
        with self._lock:
            for rep in self._replicas:
                if rep.idx == idx:
                    return rep
        return None

    def _event(self, event: str, rep: Optional[_Replica], **detail) -> None:
        if self.on_event is not None:
            try:
                self.on_event(event, rep.idx if rep is not None else None, detail)
            except Exception:
                pass

    def _argv(self, rep: _Replica) -> List[str]:
        if callable(self.command):
            return list(self.command(rep.idx, rep.port_file))
        return list(self.command) + ["--port", "0", "--port-file", rep.port_file]

    def _remove_stale(self, rep: _Replica) -> None:
        for path in (rep.port_file, rep.pid_file):
            try:
                os.remove(path)
            except OSError:
                pass

    def _spawn(self, rep: _Replica, *, first: bool) -> None:
        self._remove_stale(rep)  # never route to a dead incarnation's port
        env = dict(os.environ)
        # replica identity for the shared metrics schema: serve.py stamps
        # every metrics.jsonl record with _source=<rid>, so fleet tooling can
        # join a replica's log against the collector's store by source
        env["RELORA_TPU_REPLICA_ID"] = rep.rid
        if first or self.env_overrides_respawn:
            env.update(self.env_overrides.get(rep.idx, {}))
        if rep.log_fh is None:
            rep.log_fh = open(rep.log_path, "ab")
        argv = self._argv(rep)
        rep.proc = subprocess.Popen(
            argv,
            stdout=rep.log_fh,
            stderr=subprocess.STDOUT,
            env=env,
            start_new_session=True,  # a fleet SIGTERM is ours to orchestrate
        )
        with open(rep.pid_file, "w") as f:
            f.write(str(rep.proc.pid))
        rep.restart_at = None
        self._event("spawn" if first else "respawn", rep, pid=rep.proc.pid)
        logger.info(f"replica {rep.rid}: pid {rep.proc.pid} ({' '.join(argv[:3])} ...)")

    def _monitor_loop(self) -> None:
        while not self._stop.is_set():
            time.sleep(self.poll_interval_s)
            with self._lock:
                if self._draining:
                    continue  # begin_rolling_drain owns the processes now
                for rep in self._replicas:
                    self._check(rep)
            self._update_peers()

    def role_of(self, idx: int) -> str:
        return self.roles.get(idx, "mixed")

    def _update_peers(self) -> None:
        """Keep peers.json current with the bound fleet: {rid, host, port,
        role} per live replica.  Written atomically and only on change (the
        replicas mtime-cache it via disagg.load_peers)."""
        if self.peer_file is None:
            return
        import json as _json

        replicas = [
            {"rid": rid, "host": host, "port": port,
             "role": self.role_of(int(rid[1:]))}  # noqa: RTL202 - rid string parse
            for rid, (host, port) in sorted(self.endpoints().items())
            if port is not None
        ]
        doc = _json.dumps({"replicas": replicas}, sort_keys=True)
        if doc == self._last_peers:
            return
        tmp = self.peer_file + ".tmp"
        try:
            with open(tmp, "w") as f:
                f.write(doc)
            os.replace(tmp, self.peer_file)
            self._last_peers = doc
        except OSError as e:
            logger.warning(f"peers.json update failed: {e!r}")

    def _check(self, rep: _Replica) -> None:
        now = time.monotonic()
        if rep.quarantined or rep.draining:
            # a draining replica's exit is expected, not a crash; scale_down
            # owns it until it is removed from the fleet
            return
        if rep.restart_at is not None:
            if now >= rep.restart_at:
                rep.restarts += 1
                self._spawn(rep, first=False)
            return
        proc = rep.proc
        if proc is None or proc.poll() is None:
            return
        # the replica exited outside a drain: a crash
        code = proc.returncode
        rep.last_exit_code = code
        self._remove_stale(rep)
        rep.consecutive_crashes += 1
        rep.crash_times.append(now)
        while rep.crash_times and now - rep.crash_times[0] > self.crash_window_s:
            rep.crash_times.popleft()
        self._event("crash", rep, exit_code=code)
        if len(rep.crash_times) >= self.quarantine_after:
            rep.quarantined = True
            rep.proc = None
            self._event("quarantine", rep, crashes=len(rep.crash_times))
            logger.error(
                f"replica {rep.rid} QUARANTINED: {len(rep.crash_times)} crashes "
                f"within {self.crash_window_s:.0f}s (last exit {code}) — "
                "not restarting; see docs/operations.md (replica crash-looping)"
            )
            return
        delay = backoff_delay(
            rep.consecutive_crashes,
            base_s=self.backoff_base_s,
            cap_s=self.backoff_cap_s,
            jitter=self.backoff_jitter,
        )
        rep.restart_at = now + delay
        rep.proc = None
        logger.warning(
            f"replica {rep.rid} exited {code}; restart #{rep.restarts + 1} "
            f"in {delay:.2f}s (crash streak {rep.consecutive_crashes})"
        )

    def note_healthy(self, idx: int) -> None:
        """Optional: callers that know a replica is serving again (e.g. the
        CLI watching router health) can clear its crash streak so an
        occasional crash every few hours never accumulates to quarantine."""
        rep = self._rep_by_idx(idx)
        if rep is not None:
            with self._lock:
                rep.consecutive_crashes = 0


# -- CLI: supervisor + router in one front-end process -----------------------


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        description="Run N serve.py replicas behind the health-aware router.",
        epilog="Everything after '--' is the replica command; the supervisor "
        "appends --port 0 --port-file <workdir>/replica_<i>.port to it.",
    )
    p.add_argument("--replicas", type=int, default=2)
    p.add_argument(
        "--prefill-replicas",
        type=int,
        default=0,
        help="disaggregated fleet: the first N replicas run --role prefill "
        "(long prompts; finished page runs migrate to decode peers)",
    )
    p.add_argument(
        "--decode-replicas",
        type=int,
        default=0,
        help="disaggregated fleet: the next N replicas run --role decode "
        "(short prompts + migrated runs); the rest stay mixed/fallback",
    )
    p.add_argument(
        "--classify-threshold",
        type=int,
        default=None,
        help="prompt-length (tokens) routing threshold between the decode "
        "and prefill pools (default 128 when roles are in play)",
    )
    p.add_argument("--workdir", required=True, help="port/pid/log files live here")
    p.add_argument("--router-host", default="127.0.0.1")
    p.add_argument("--router-port", type=int, default=8000, help="0 = ephemeral")
    p.add_argument("--router-port-file", default=None)
    p.add_argument("--backoff-base-s", type=float, default=0.5)
    p.add_argument("--backoff-cap-s", type=float, default=30.0)
    p.add_argument("--quarantine-after", type=int, default=5)
    p.add_argument("--crash-window-s", type=float, default=120.0)
    p.add_argument("--drain-timeout-s", type=float, default=60.0)
    p.add_argument("--probe-interval-s", type=float, default=0.25)
    p.add_argument(
        "--replica-env",
        action="append",
        default=[],
        metavar="IDX:KEY=VALUE",
        help="env override for one replica's FIRST incarnation only (drills: "
        "arm a faults.py site on r0; the respawn comes back clean)",
    )
    p.add_argument(
        "--autoscale",
        action="store_true",
        help="SLO-driven elastic scaling: grow the fleet on sustained TTFT/"
        "queue/slot burn, drain it back on sustained idle (docs/operations.md "
        "has the runbook).  Requires the fleet collector (--fleet-cadence-s > 0); "
        "--replicas is the starting size.",
    )
    p.add_argument("--min-replicas", type=int, default=1)
    p.add_argument("--max-replicas", type=int, default=4)
    p.add_argument(
        "--ttft-p95-target-s", type=float, default=2.0,
        help="scale-up high-water mark for per-replica TTFT p95",
    )
    p.add_argument("--queue-depth-high", type=float, default=4.0)
    p.add_argument("--slot-util-high", type=float, default=0.9)
    p.add_argument(
        "--burn-window-s", type=float, default=5.0,
        help="pressure must be sustained this long on every replica to add one",
    )
    p.add_argument(
        "--idle-window-s", type=float, default=15.0,
        help="quiet must be sustained this long on every replica to drain one",
    )
    p.add_argument("--cooldown-s", type=float, default=10.0)
    p.add_argument("--autoscale-interval-s", type=float, default=1.0)
    p.add_argument(
        "--fleet-cadence-s",
        type=float,
        default=1.0,
        help="FleetCollector scrape cadence; <= 0 disables the collector",
    )
    p.add_argument(
        "--fleet-persist",
        default=None,
        help="fleet series JSONL path (default <workdir>/fleet_series.jsonl)",
    )
    p.add_argument("--slo-config", default=None, help="JSON SLO config (docs/observability.md)")
    p.add_argument(
        "--watch-checkpoints",
        default=None,
        metavar="DIR",
        help="continuous deployment: poll DIR/latest (published by the "
        "trainer at every manifest commit) and roll verified new checkpoints "
        "across the fleet one replica at a time — canary-gated, automatic "
        "fleet-wide rollback on any failure (docs/operations.md)",
    )
    p.add_argument(
        "--watch-interval-s", type=float, default=2.0, help="checkpoint watcher poll interval"
    )
    p.add_argument(
        "--canary-prompts",
        default=None,
        metavar="FILE",
        help="canary prompt-set for rolling updates: one token-id prompt per "
        "line (comma/space-separated ints); default: a built-in tiny set. "
        "Ignored when the checkpoint ships its own canary.json baseline.",
    )
    p.add_argument(
        "--canary-max-new-tokens",
        type=int,
        default=8,
        help="greedy tokens per canary prompt (token-identical gate)",
    )
    p.add_argument(
        "command", nargs=argparse.REMAINDER, help="replica command (after --)"
    )
    args = p.parse_args(argv)
    command = list(args.command)
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        raise SystemExit("pass the replica command after '--'")

    env_overrides: Dict[int, Dict[str, str]] = {}
    for spec in args.replica_env:
        idx_s, _, kv = spec.partition(":")
        key, _, value = kv.partition("=")
        env_overrides.setdefault(int(idx_s), {})[key] = value

    from relora_tpu.obs.fleet import FleetCollector  # jax-free, like this module
    from relora_tpu.obs.slo import SLOEngine
    from relora_tpu.serve import disagg as _disagg
    from relora_tpu.serve.router import Router

    # disaggregated fleet: the first --prefill-replicas indices are prefill,
    # the next --decode-replicas are decode, the rest mixed (the fallback
    # pool).  Each replica learns its role + the peer roster via flags the
    # supervisor appends to the base command; the fleet-url file lets them
    # reach the collector's prefix directory once the router has bound.
    if args.prefill_replicas + args.decode_replicas > args.replicas:
        raise SystemExit("--prefill-replicas + --decode-replicas exceeds --replicas")
    disagg_on = args.prefill_replicas + args.decode_replicas > 0
    roles: Dict[int, str] = {}
    for i in range(args.prefill_replicas):
        roles[i] = "prefill"
    for i in range(args.prefill_replicas, args.prefill_replicas + args.decode_replicas):
        roles[i] = "decode"
    peer_file = os.path.join(args.workdir, "peers.json") if disagg_on else None
    router_port_path = os.path.join(args.workdir, "router.port")
    replica_command: ReplicaCommand = command
    if disagg_on:

        def replica_command(idx: int, port_file: str) -> List[str]:
            return list(command) + [
                "--port", "0",
                "--port-file", port_file,
                "--role", roles.get(idx, "mixed"),
                "--peer-file", peer_file,
                "--fleet-url", router_port_path,
            ]

    sup = ReplicaSupervisor(
        replica_command,
        args.replicas,
        args.workdir,
        backoff_base_s=args.backoff_base_s,
        backoff_cap_s=args.backoff_cap_s,
        quarantine_after=args.quarantine_after,
        crash_window_s=args.crash_window_s,
        drain_timeout_s=args.drain_timeout_s,
        env_overrides=env_overrides,
        env_overrides_respawn=False,
        roles=roles,
        peer_file=peer_file,
    )

    # fleet observability plane: the collector scrapes every replica plus the
    # router itself into a SeriesStore, runs the SLO engine each round, and
    # mounts /fleet/metrics + /fleet/series on the router front-end
    collector: Optional[FleetCollector] = None
    router_holder: Dict[str, Router] = {}

    def fleet_endpoints() -> Dict[str, Tuple[str, Optional[int]]]:
        eps: Dict[str, Tuple[str, Optional[int]]] = dict(sup.endpoints())
        r = router_holder.get("router")
        if r is not None and r.started.is_set():
            eps["router"] = (args.router_host, r.port)
        return eps

    if args.fleet_cadence_s > 0:
        collector = FleetCollector(
            fleet_endpoints,
            slo_engine=SLOEngine.from_config(args.slo_config),
            cadence_s=args.fleet_cadence_s,
            persist_path=args.fleet_persist
            or os.path.join(args.workdir, "fleet_series.jsonl"),
        )
        sup.on_event = lambda event, idx, detail: collector.record_supervisor_event(
            event, idx, str(detail)
        )

    router = Router(
        sup.endpoints,
        host=args.router_host,
        port=args.router_port,
        probe_interval_s=args.probe_interval_s,
        extra_routes=collector.handle_fleet_route if collector is not None else None,
        classify_threshold=(
            (
                args.classify_threshold
                if args.classify_threshold is not None
                else _disagg.DEFAULT_CLASSIFY_THRESHOLD
            )
            if disagg_on
            else args.classify_threshold
        ),
    )
    router_holder["router"] = router
    sup.start()
    if collector is not None:
        collector.start()

    # elastic scaling: the collector's store drives replica count through
    # the supervisor's scale levers (decisions land as autoscale_* events)
    autoscaler = None
    if args.autoscale:
        if collector is None:
            raise SystemExit("--autoscale requires the collector (--fleet-cadence-s > 0)")
        from relora_tpu.serve.autoscale import Autoscaler, AutoscalerPolicy

        policy = AutoscalerPolicy(
            min_replicas=args.min_replicas,
            max_replicas=args.max_replicas,
            ttft_p95_target_s=args.ttft_p95_target_s,
            queue_depth_high=args.queue_depth_high,
            slot_util_high=args.slot_util_high,
            burn_window_s=args.burn_window_s,
            idle_window_s=args.idle_window_s,
            cooldown_s=args.cooldown_s,
        )
        autoscaler = Autoscaler(
            policy, sup, collector.store, interval_s=args.autoscale_interval_s
        ).start()
        logger.info(
            f"autoscaler armed: {args.min_replicas}..{args.max_replicas} replicas, "
            f"burn window {args.burn_window_s:g}s / idle window {args.idle_window_s:g}s"
        )

    # continuous deployment: watcher verifies each published checkpoint, the
    # rolling updater hot-swaps it across the fleet behind the canary gate
    watcher = None
    if args.watch_checkpoints:
        from relora_tpu.serve.deploy import CheckpointWatcher, RollingUpdater

        canary_prompts = None
        if args.canary_prompts:
            with open(args.canary_prompts) as f:
                canary_prompts = [
                    [int(t) for t in line.replace(",", " ").split()]
                    for line in f
                    if line.strip()
                ]

        def deploy_emit(event: str, idx, detail: Dict) -> None:
            if collector is not None:
                collector.record_supervisor_event(event, idx, str(detail))

        updater = RollingUpdater(
            sup.endpoints,
            canary_prompts=canary_prompts,
            canary_max_new_tokens=args.canary_max_new_tokens,
            expect_replicas=args.replicas,
            emit=deploy_emit,
        )
        watcher = CheckpointWatcher(
            args.watch_checkpoints,
            updater.run,
            interval_s=args.watch_interval_s,
            on_reject=lambda path, reason: deploy_emit(
                "deploy_reject", None, {"checkpoint": path, "reason": reason}
            ),
        ).start()
        logger.info(
            f"continuous deployment armed: watching {args.watch_checkpoints}/latest "
            f"every {args.watch_interval_s:g}s, canary gate "
            f"{args.canary_max_new_tokens} greedy tokens"
        )

    def on_sigterm(signum, frame):
        logger.info("SIGTERM: rolling drain, then router shutdown")

        def _drain():
            sup.begin_rolling_drain()
            router.begin_shutdown()

        threading.Thread(target=_drain, name="rolling-drain", daemon=True).start()

    signal.signal(signal.SIGTERM, on_sigterm)
    signal.signal(signal.SIGINT, on_sigterm)

    import asyncio

    async def _main() -> None:
        serve = asyncio.ensure_future(router.serve_forever())
        while not router.started.is_set():
            await asyncio.sleep(0.01)
            if serve.done():
                break
        if not serve.done():
            # workdir copy feeds the replicas' --fleet-url (the collector's
            # /fleet/prefix directory mounts on the router front-end)
            with open(router_port_path, "w") as f:
                f.write(str(router.port))
            if args.router_port_file:
                with open(args.router_port_file, "w") as f:
                    f.write(str(router.port))
        await serve

    try:
        asyncio.run(_main())
    finally:
        if autoscaler is not None:
            autoscaler.stop()
        if watcher is not None:
            watcher.stop()
        if collector is not None:
            collector.stop()
        sup.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
