// relora-tpu native dataset index builders.
//
// C++ equivalents of the reference's runtime-compiled pybind11 helpers
// (peft_pretraining/megatron_dataset/helpers.cpp): the O(total_tokens) /
// O(total_samples) index-construction loops that are too slow in Python for
// billion-token corpora.  Re-implemented as a flat extern-C API loaded via
// ctypes (pybind11 is not part of this toolchain); NumPy-owned buffers are
// passed as raw pointers, so no copies are made in either direction.
//
// Differential-tested against the pure-NumPy implementations in
// relora_tpu/data/sample_index.py and blendable.py (the same oracle strategy
// the reference uses: dataset.py:275-320 is its Python fallback).
//
// Build: see native/build.py (g++ -O3 -shared -fPIC, no dependencies).

#include <cstdint>
#include <cstring>
#include <algorithm>
#include <random>
#include <vector>

// ---------------------------------------------------------------------------
// Sample-index packing (parity: helpers.cpp:91-259)
//
// Walk the (epoch-repeated, shuffled) document list, packing windows of
// seq_length + 1 tokens; record the (position-in-doc_idx, offset-in-doc)
// pair at each sample boundary.  The +1/-1 bookkeeping exists because
// consecutive samples share one boundary token (input/target shift).
//
// sample_idx must hold 2 * (num_samples + 1) entries.  Returns 0 on success,
// -1 if the documents ran out before num_samples were packed (corrupt input).
// ---------------------------------------------------------------------------

template <typename IndexT>
static int pack_sample_index(const int32_t* sizes,
                             const IndexT* doc_idx,
                             int64_t doc_idx_len,
                             int32_t seq_length,
                             int64_t num_samples,
                             IndexT* sample_idx) {
  int64_t out = 0;
  int64_t doc_pos = 0;     // index into doc_idx
  int64_t doc_offset = 0;  // token offset within the current document

  sample_idx[2 * out] = static_cast<IndexT>(doc_pos);
  sample_idx[2 * out + 1] = static_cast<IndexT>(doc_offset);
  ++out;

  while (out <= num_samples) {
    int64_t remaining = static_cast<int64_t>(seq_length) + 1;
    while (remaining > 0) {
      if (doc_pos >= doc_idx_len) return -1;
      const int64_t doc_len = static_cast<int64_t>(sizes[doc_idx[doc_pos]]) - doc_offset;
      if (doc_len >= remaining) {
        // window ends inside this document; next sample re-reads the
        // boundary token (hence the -1)
        doc_offset += remaining - 1;
        remaining = 0;
      } else {
        remaining -= doc_len;
        ++doc_pos;
        doc_offset = 0;
      }
    }
    sample_idx[2 * out] = static_cast<IndexT>(doc_pos);
    sample_idx[2 * out + 1] = static_cast<IndexT>(doc_offset);
    ++out;
  }
  return 0;
}

static void fisher_yates_i64(int64_t* data, int64_t n, std::mt19937_64& rng) {
  for (int64_t i = n - 1; i > 0; --i) {
    std::uniform_int_distribution<int64_t> dist(0, i);
    std::swap(data[i], data[dist(rng)]);
  }
}

extern "C" {

int relora_build_sample_idx_i32(const int32_t* sizes,
                                const int32_t* doc_idx,
                                int64_t doc_idx_len,
                                int32_t seq_length,
                                int64_t num_samples,
                                int32_t* sample_idx) {
  return pack_sample_index<int32_t>(sizes, doc_idx, doc_idx_len, seq_length,
                                    num_samples, sample_idx);
}

int relora_build_sample_idx_i64(const int32_t* sizes,
                                const int64_t* doc_idx,
                                int64_t doc_idx_len,
                                int32_t seq_length,
                                int64_t num_samples,
                                int64_t* sample_idx) {
  return pack_sample_index<int64_t>(sizes, doc_idx, doc_idx_len, seq_length,
                                    num_samples, sample_idx);
}

// ---------------------------------------------------------------------------
// Weighted-blend index construction (parity: helpers.cpp:34-89)
//
// Greedy max-error interleave: at each global sample, emit the dataset whose
// achieved count lags its target fraction the most.  dataset_index gets the
// chosen dataset id; dataset_sample_index the running per-dataset counter.
// ---------------------------------------------------------------------------

void relora_build_blending_indices(uint8_t* dataset_index,
                                   int64_t* dataset_sample_index,
                                   const double* weights,
                                   int32_t num_datasets,
                                   int64_t size) {
  std::vector<int64_t> taken(num_datasets, 0);
  for (int64_t i = 0; i < size; ++i) {
    const double position = std::max(static_cast<double>(i), 1.0);
    int32_t best = 0;
    double best_error = weights[0] * position - static_cast<double>(taken[0]);
    for (int32_t d = 1; d < num_datasets; ++d) {
      const double err = weights[d] * position - static_cast<double>(taken[d]);
      if (err > best_error) {
        best_error = err;
        best = d;
      }
    }
    dataset_index[i] = static_cast<uint8_t>(best);
    dataset_sample_index[i] = taken[best];
    ++taken[best];
  }
}

// ---------------------------------------------------------------------------
// In-place Fisher-Yates shuffle (mirrors the shuffle the reference embeds in
// its BERT mapping builders)
// ---------------------------------------------------------------------------

void relora_shuffle_i64(int64_t* data, int64_t n, uint64_t seed) {
  std::mt19937_64 rng(seed);
  fisher_yates_i64(data, n, rng);
}

}  // extern "C"

// ---------------------------------------------------------------------------
// BERT-style sentence-span mappings (parity: helpers.cpp:261-747).
//
// Documents are ranges of sentences: docs[d]..docs[d+1] index into `sizes`
// (tokens per sentence).  Samples greedily pack consecutive sentences up to
// a target length (occasionally shortened with prob `short_seq_prob`, the
// reference's short_seq_ratio trick), skipping empty/one-sentence documents
// and documents containing a sentence longer than `long_sentence_len`.
//
// Two-pass contract for a flat C API: `count` returns the number of samples
// for a given epoch budget; `fill` re-runs the identical seeded walk to
// populate the caller-allocated buffer, then Fisher-Yates shuffles rows.
//
// relora_*_bert_mapping rows: (first_sentence, end_sentence, target_len)
// relora_*_block_mapping rows: (first_sentence, end_sentence, doc, target_len)
// ---------------------------------------------------------------------------

namespace {

constexpr int32_t kLongSentenceLen = 512;

inline int32_t target_sample_len(int32_t short_seq_ratio, int32_t max_length,
                                 std::mt19937& rng) {
  const uint32_t r = rng();
  if (short_seq_ratio > 0 && (r % short_seq_ratio) == 0) {
    return 2 + static_cast<int32_t>(r % (max_length - 1));
  }
  return max_length;
}

// One deterministic walk over epochs*documents; invokes emit(start, end, doc,
// target_len) for every packed span.  Returns the number of spans visited
// (bounded by max_num_samples).
template <typename Emit>
int64_t walk_spans(const int64_t* docs, int64_t n_docs, const int32_t* sizes,
                   int32_t num_epochs, int64_t max_num_samples,
                   int32_t max_seq_length, double short_seq_prob, uint32_t seed,
                   Emit emit) {
  const int32_t short_ratio =
      short_seq_prob > 0 ? static_cast<int32_t>(std::lround(1.0 / short_seq_prob)) : 0;
  std::mt19937 rng(seed);
  int64_t emitted = 0;
  for (int32_t epoch = 0; epoch < num_epochs; ++epoch) {
    if (emitted >= max_num_samples) break;
    for (int64_t doc = 0; doc < n_docs; ++doc) {
      const int64_t first = docs[doc];
      const int64_t last = docs[doc + 1];
      int64_t remaining = last - first;
      if (remaining < 2) continue;  // empty/one-sentence docs are skipped
      bool has_long = false;
      for (int64_t s = first; s < last; ++s) {
        if (sizes[s] > kLongSentenceLen) { has_long = true; break; }
      }
      if (has_long) continue;

      int64_t span_start = first;
      int32_t seq_len = 0;
      int32_t num_sent = 0;
      int32_t target = target_sample_len(short_ratio, max_seq_length, rng);
      for (int64_t s = first; s < last; ++s) {
        seq_len += sizes[s];
        ++num_sent;
        --remaining;
        const bool full = seq_len >= target && remaining > 1 && num_sent > 1;
        if (full || remaining == 0) {
          emit(emitted, span_start, s + 1, doc, target);
          ++emitted;
          span_start = s + 1;
          target = target_sample_len(short_ratio, max_seq_length, rng);
          seq_len = 0;
          num_sent = 0;
        }
      }
    }
  }
  return emitted;
}

template <int kCols>
void shuffle_rows(int64_t* maps, int64_t n, uint32_t seed) {
  std::mt19937_64 rng(seed + 1);
  for (int64_t i = n - 1; i > 0; --i) {
    const int64_t j = static_cast<int64_t>(rng() % static_cast<uint64_t>(i + 1));
    for (int c = 0; c < kCols; ++c) std::swap(maps[kCols * i + c], maps[kCols * j + c]);
  }
}

}  // namespace

extern "C" {

int64_t relora_count_bert_mapping(const int64_t* docs, int64_t n_docs,
                                  const int32_t* sizes, int32_t num_epochs,
                                  int64_t max_num_samples, int32_t max_seq_length,
                                  double short_seq_prob, uint32_t seed) {
  return walk_spans(docs, n_docs, sizes, num_epochs, max_num_samples,
                    max_seq_length, short_seq_prob, seed,
                    [](int64_t, int64_t, int64_t, int64_t, int32_t) {});
}

void relora_fill_bert_mapping(const int64_t* docs, int64_t n_docs,
                              const int32_t* sizes, int32_t num_epochs,
                              int64_t max_num_samples, int32_t max_seq_length,
                              double short_seq_prob, uint32_t seed,
                              int64_t* maps) {
  const int64_t n = walk_spans(
      docs, n_docs, sizes, num_epochs, max_num_samples, max_seq_length,
      short_seq_prob, seed,
      [maps](int64_t i, int64_t start, int64_t end, int64_t, int32_t target) {
        maps[3 * i] = start;
        maps[3 * i + 1] = end;
        maps[3 * i + 2] = target;
      });
  shuffle_rows<3>(maps, n, seed);
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Block-span mapping, bit-parity with the reference's build_blocks_mapping
// (helpers.cpp:513-747).  Differences from the BERT walk above that matter
// for exactness:
//
//   - per-document target length: max_seq_length - titles_sizes[doc]
//     (each block leaves room for its document's title); NO short-seq
//     randomness — the walk is fully deterministic
//   - rows are (span_start, span_end, doc, block_id), where block_id is a
//     per-epoch running counter over emitted blocks (used downstream to
//     build block indexes), not the target length
//   - min_num_sent is 2, or 1 under use_one_sent_blocks, and gates both the
//     doc-skip and the "enough sentences left" emission condition
//   - the max_num_samples budget is only checked at epoch boundaries: a
//     started epoch always completes
//
// The final Fisher-Yates shuffle matches the reference exactly:
// mt19937_64(seed + 1) with j = rng() % (i + 1)  (shuffle_rows above).
// ---------------------------------------------------------------------------

namespace {

template <typename Emit>
int64_t walk_blocks(const int64_t* docs, int64_t n_docs, const int32_t* sizes,
                    const int32_t* titles_sizes, int32_t num_epochs,
                    int64_t max_num_samples, int32_t max_seq_length,
                    int32_t min_num_sent, Emit emit) {
  int64_t emitted = 0;
  for (int32_t epoch = 0; epoch < num_epochs; ++epoch) {
    if (emitted >= max_num_samples) break;
    int64_t block_id = 0;
    for (int64_t doc = 0; doc < n_docs; ++doc) {
      const int64_t first = docs[doc];
      const int64_t last = docs[doc + 1];
      const int32_t target = max_seq_length - titles_sizes[doc];
      int64_t remaining = last - first;
      if (remaining < min_num_sent) continue;
      bool has_long = false;
      for (int64_t s = first; s < last; ++s) {
        if (sizes[s] > kLongSentenceLen) { has_long = true; break; }
      }
      if (has_long) continue;

      int64_t span_start = first;
      int32_t seq_len = 0;
      int32_t num_sent = 0;
      for (int64_t s = first; s < last; ++s) {
        seq_len += sizes[s];
        ++num_sent;
        --remaining;
        const bool full =
            seq_len >= target && remaining >= min_num_sent && num_sent >= min_num_sent;
        if (full || remaining == 0) {
          emit(emitted, span_start, s + 1, doc, block_id);
          ++emitted;
          ++block_id;
          span_start = s + 1;
          seq_len = 0;
          num_sent = 0;
        }
      }
    }
  }
  return emitted;
}

}  // namespace

extern "C" {

int64_t relora_count_blocks_mapping(const int64_t* docs, int64_t n_docs,
                                    const int32_t* sizes,
                                    const int32_t* titles_sizes,
                                    int32_t num_epochs, int64_t max_num_samples,
                                    int32_t max_seq_length,
                                    int32_t use_one_sent_blocks) {
  const int32_t min_sent = use_one_sent_blocks ? 1 : 2;
  return walk_blocks(docs, n_docs, sizes, titles_sizes, num_epochs,
                     max_num_samples, max_seq_length, min_sent,
                     [](int64_t, int64_t, int64_t, int64_t, int64_t) {});
}

void relora_fill_blocks_mapping(const int64_t* docs, int64_t n_docs,
                                const int32_t* sizes,
                                const int32_t* titles_sizes, int32_t num_epochs,
                                int64_t max_num_samples, int32_t max_seq_length,
                                int32_t use_one_sent_blocks, uint32_t seed,
                                int64_t* maps) {
  const int32_t min_sent = use_one_sent_blocks ? 1 : 2;
  const int64_t n = walk_blocks(
      docs, n_docs, sizes, titles_sizes, num_epochs, max_num_samples,
      max_seq_length, min_sent,
      [maps](int64_t i, int64_t start, int64_t end, int64_t doc, int64_t block_id) {
        maps[4 * i] = start;
        maps[4 * i + 1] = end;
        maps[4 * i + 2] = doc;
        maps[4 * i + 3] = block_id;
      });
  shuffle_rows<4>(maps, n, seed);
}

}  // extern "C"
