"""Disaggregated prefill/decode tests: the cross-replica handoff oracle.

A two-pool drain — long prompts prefilled on a ``role="prefill"`` scheduler,
their finished page runs shipped through the real wire framing
(``encode_page_run``/``decode_page_run``) into a ``role="decode"`` peer,
short prompts decoded on the peer directly — must be **token-identical** to
one mixed replica draining the same request stream, because the migrated
run carries the exact pool bytes (int8 codes + per-page k/v scales), the
exact positions, and sampling keys stay ``(uid, token_index)``.  On top of
parity: every failure path (sink rejection, malformed frame, pool/slot
exhaustion) fails *open* to local decode with the same tokens, donor-side
prefix exports pin their pages against eviction for the transfer's
lifetime, and a warmed receiver adopts migrated runs with zero
steady-state retraces.
"""

import numpy as np

import jax
import pytest

from relora_tpu.config.model import ModelConfig
from relora_tpu.models.params_util import init_params
from relora_tpu.serve import disagg, wire
from relora_tpu.serve.engine import InferenceEngine, build_decode_model
from relora_tpu.serve.paging import PageAllocator, PrefixCache
from relora_tpu.serve.scheduler import PagedContinuousBatchingScheduler, Request

pytestmark = [pytest.mark.serve, pytest.mark.disagg]

TINY_LLAMA = ModelConfig(
    family="llama",
    vocab_size=256,
    hidden_size=64,
    intermediate_size=160,
    num_hidden_layers=2,
    num_attention_heads=4,
    max_sequence_length=64,
)
TINY_NEOX = ModelConfig(
    family="neox",
    vocab_size=256,
    hidden_size=64,
    intermediate_size=160,
    num_hidden_layers=2,
    num_attention_heads=4,
    max_sequence_length=64,
    rotary_pct=0.25,
)

MAX_BATCH = 2
CHUNK = 8
PAGE = 8
CACHE = 32
THRESHOLD = 12  # prompt tokens at/above this go to the prefill pool

_ENGINES: dict = {}


def make_engine(cfg, *, fresh=False):
    """One int8-pool paged engine per config (the wire's 4x-under-bf16 claim
    rides the int8 codes + per-page scales, so the tests exercise exactly
    that layout).  Cached so parity drains share jit caches and weights."""
    key = cfg.family
    if not fresh and key in _ENGINES:
        return _ENGINES[key]
    model = build_decode_model(cfg, cache_size=CACHE)
    base = type(model)(cfg, dtype=jax.numpy.float32, scan_layers=True)
    params = init_params(base, jax.random.PRNGKey(0), jax.numpy.zeros((1, 8), jax.numpy.int32))
    engine = InferenceEngine(
        cfg,
        params,
        cache_size=CACHE,
        page_size=PAGE,
        num_pages=3 * (CACHE // PAGE) + 1,
        chunk_size=CHUNK,
        kv_dtype="int8",
    )
    if not fresh:
        _ENGINES[key] = engine
    return engine


def make_sched(engine, role="mixed", **kw):
    return PagedContinuousBatchingScheduler(
        engine,
        max_batch=MAX_BATCH,
        eos_id=9,
        key=jax.random.PRNGKey(42),
        role=role,
        **kw,
    )


def mixed_requests(vocab=256):
    """Long (prefill-pool) and short (decode-pool) prompts interleaved,
    greedy AND sampled — the sampled rows prove the keys travel."""
    rng = np.random.default_rng(7)
    mk = lambda uid, L, new, **kw: Request(
        uid=uid, prompt=rng.integers(1, vocab, L).tolist(), max_new_tokens=new, **kw
    )
    return [
        mk(1, 13, 6),
        mk(2, 5, 8, temperature=0.8, top_p=0.9),
        mk(3, 21, 5, temperature=1.1),
        mk(4, 3, 6),
    ]


def drain_disagg_pair(engine, reqs, *, wire_hook=None, sink_override=None):
    """Drive a prefill-role donor and a decode-role receiver to completion,
    relaying every handoff through the real wire framing.  Returns
    ``(completions, donor, recv)``; a handoff that cannot land immediately
    (receiver slots full) waits, like the in-flight async transfer it
    models, and any insert error fails open to donor-local decode."""
    donor = make_sched(engine, role="prefill")
    recv = make_sched(engine, role="decode")
    completions = {}

    def finish(c):
        assert c.tokens is not None
        assert completions.setdefault(c.uid, c) is c, f"uid {c.uid} finished twice"

    handoffs = []
    if sink_override is not None:
        donor.migration_sink = sink_override
    else:
        def sink(record, entries):
            blob = wire.encode_page_run(record, entries)
            if wire_hook is not None:
                blob = wire_hook(blob)
            handoffs.append((int(record["uid"]), blob))
            return True

        donor.migration_sink = sink

    for req in reqs:
        pool = donor if len(req.prompt) >= THRESHOLD else recv
        assert disagg.classify_request(len(req.prompt), THRESHOLD) == (
            "prefill" if pool is donor else "decode"
        )
        pool.submit(req, on_finish=finish)

    for _ in range(400):
        if not (donor.has_work() or recv.has_work() or handoffs):
            break
        if donor.has_work():
            donor.step()
        still_waiting = []
        for uid, blob in handoffs:
            try:
                record, arrays = wire.decode_page_run(blob)
                recv.submit_migrated(record, arrays, on_finish=finish)
                donor.migration_commit(uid, len(blob))
            except RuntimeError:
                still_waiting.append((uid, blob))  # no free slot: transfer waits
            except Exception as e:
                donor.migration_failed(uid, str(e))
        handoffs[:] = still_waiting
        if recv.has_work():
            recv.step()
    else:
        raise AssertionError("disagg drain did not converge")
    return completions, donor, recv


# -- wire framing -------------------------------------------------------------


def test_wire_round_trip_bitwise():
    rng = np.random.default_rng(3)
    arrays = []
    for i, (dtype, shape) in enumerate(
        [("int8", (2, 3, 8, 4, 16)), ("float32", (2, 3, 8)), ("bfloat16", (1, 4))]
    ):
        if dtype == "bfloat16":
            raw = rng.integers(0, 256, int(np.prod(shape)) * 2, dtype=np.uint8).tobytes()
        else:
            raw = np.ascontiguousarray(
                rng.integers(-100, 100, shape).astype(dtype)
            ).tobytes()
        arrays.append((f"leaf{i}", dtype, shape, raw))
    meta = {"uid": 7, "prompt": [1, 2, 3], "position": 3, "n_pages": 1}
    blob = wire.encode_page_run(meta, arrays)
    meta2, arrays2 = wire.decode_page_run(blob)
    assert meta2 == meta
    assert len(arrays2) == len(arrays)
    for (n, d, s, raw), (n2, d2, s2, raw2) in zip(arrays, arrays2):
        assert (n2, d2, tuple(s2)) == (n, d, tuple(s))
        assert raw2 == raw  # bitwise: the pool bytes survive the frame intact
    # a second encode of the same inputs is byte-identical (stable framing)
    assert wire.encode_page_run(meta, arrays) == blob


def test_wire_rejects_torn_and_corrupt_frames():
    blob = wire.encode_page_run(
        {"uid": 1}, [("k", "int8", (2, 2), bytes(range(4)))]
    )
    for bad in (
        b"",  # empty
        blob[:7],  # shorter than any valid frame
        blob[:-3],  # truncated mid-crc
        blob[: len(blob) // 2],  # torn payload
        b"XXXX" + blob[4:],  # bad magic
        blob[:-4] + b"\x00\x00\x00\x00",  # crc mismatch
        blob + b"trailing",  # crc covers length: garbage tail rejected
        blob[:10] + bytes([blob[10] ^ 0xFF]) + blob[11:],  # flipped byte
    ):
        with pytest.raises(ValueError):
            wire.decode_page_run(bad)


# -- roles, classification, peers ---------------------------------------------


def test_classify_and_pick_peers():
    assert disagg.classify_request(128, 128) == "prefill"
    assert disagg.classify_request(127, 128) == "decode"
    peers = [
        {"rid": "r0", "host": "h", "port": 1, "role": "prefill"},
        {"rid": "r1", "host": "h", "port": 2, "role": "decode"},
        {"rid": "r2", "host": "h", "port": 3, "role": "mixed"},
        {"rid": "r3", "host": "h", "port": 4, "role": "decode"},
    ]
    picks = disagg.pick_peers(peers, role="decode", exclude_rid="r1")
    assert [p["rid"] for p in picks] == ["r3", "r2"]  # role first, mixed fallback
    picks = disagg.pick_peers(
        [p for p in peers if p["role"] != "decode"], role="decode", exclude_rid="r0"
    )
    assert [p["rid"] for p in picks] == ["r2"]  # degraded fleet: mixed only


def test_prefix_directory_update_lookup_drop():
    d = disagg.PrefixPageDirectory(max_entries=4)
    d.update("r0", "h0", 1, ["aa", "bb"])
    d.update("r1", "h1", 2, ["bb", "cc"])
    # caller order (longest prefix first) wins; r1 re-advertised "bb" last
    assert d.lookup(["zz", "bb"]) == ("bb", "r1", "h1", 2)
    # exclude keeps a replica from fetching from itself
    assert d.lookup(["cc"], exclude_rid="r1") is None
    d.update("r0", "h0", 1, ["aa"])  # "bb" no longer advertised by r0 either
    d.drop_replica("r1")
    assert d.lookup(["bb", "cc", "aa"]) == ("aa", "r0", "h0", 1)
    # LRU bound: flooding evicts the oldest entries without breaking rid sets
    d.update("r2", "h2", 3, [f"d{i}" for i in range(6)])
    assert len(d) <= 4
    d.drop_replica("r2")
    assert d.lookup([f"d{i}" for i in range(6)]) is None


# -- donor-side export pinning ------------------------------------------------


def test_prefix_cache_acquire_pins_against_eviction():
    """Property (seeded sweep): pages pinned by ``acquire`` for an in-flight
    export NEVER return to the free list — not under LRU eviction, not under
    ``clear``, not under allocation pressure — until the matching decref."""
    rng = np.random.default_rng(13)
    for trial in range(25):
        alloc = PageAllocator(num_pages=17, page_size=4)
        cache = PrefixCache(alloc, max_entries=int(rng.integers(1, 5)))
        live = []  # (digest_hex, pinned_pages)
        registered = []
        for op in range(40):
            roll = rng.random()
            if roll < 0.45:
                n_pages = int(rng.integers(1, 4))
                pages = alloc.alloc(n_pages)
                if pages is None:
                    cache.evict(n_pages)
                    pages = alloc.alloc(n_pages)
                if pages is None:
                    continue
                prompt = rng.integers(1, 99, n_pages * 4).tolist()
                cache.register(prompt, pages)
                registered.append(prompt)
                alloc.decref(pages)  # cache refs keep the run alive
            elif roll < 0.7 and cache.digests():
                digest = str(rng.choice(cache.digests()))
                got = cache.acquire(digest)
                if got is not None:
                    live.append((digest, got[0]))
            elif roll < 0.85:
                cache.evict(int(rng.integers(1, 17)))
            elif live:
                digest, pages = live.pop(int(rng.integers(len(live))))
                alloc.decref(pages)
            # invariant: every pinned page is still referenced, and a fresh
            # all-or-nothing alloc can never be handed a pinned page
            pinned = {p for _, pages in live for p in pages}
            for p in pinned:
                assert alloc.refcount(p) >= 1, f"trial {trial}: pinned page {p} freed"
            grab = alloc.alloc(alloc.free_pages)
            if grab is not None:
                assert not (set(grab) & pinned)
                alloc.decref(grab)
        cache.clear()
        for digest, pages in live:
            pinned = set(pages)
            assert all(alloc.refcount(p) >= 1 for p in pinned)
            alloc.decref(pages)
        assert alloc.used_pages == 0  # every pin released -> pool fully free
        assert cache.acquire("zz") is None  # non-hex digest: miss, not a raise


# -- migration parity ---------------------------------------------------------


def test_migrated_insert_zero_steady_state_retraces():
    """warmup(migrate=True) compiles the page-run gather/scatter buckets;
    afterwards a full disagg drain — exports, wire, adopts, decodes to
    finish — never retraces on either side.  Runs first in this section so
    the warmed engine it builds is the one every later llama test reuses:
    the module pays one compile budget, not two."""
    engine = make_engine(TINY_LLAMA, fresh=True)
    report = engine.warmup(MAX_BATCH, migrate=True)
    assert report["shapes"]["page_run"] == list(engine.page_run_buckets())
    completions, donor, recv = drain_disagg_pair(engine, mixed_requests())
    assert len(completions) == 4
    assert recv._migrated_inserts == 2
    assert engine.compile_watcher.steady_state_retraces == 0
    _ENGINES[TINY_LLAMA.family] = engine


def mixed_baseline(engine):
    """One mixed-replica drain per engine, memoized: three parity tests
    compare against the identical request stream, so run it once."""
    key = id(engine)
    if key not in _BASELINES:
        _BASELINES[key] = make_sched(engine).run(mixed_requests())
    return _BASELINES[key]


_BASELINES: dict = {}


@pytest.mark.parametrize(
    "cfg",
    [
        TINY_LLAMA,
        # neox rides the slow battery: same gather/scatter and key path, but
        # its compile set doesn't fit the tier-1 wall-clock budget
        pytest.param(TINY_NEOX, marks=pytest.mark.slow),
    ],
    ids=lambda c: c.family,
)
def test_disagg_drain_token_identical(cfg):
    """The tentpole oracle: prefill-pool + decode-pool greedy/sampled drain
    == one mixed replica, token for token, reason for reason — and the
    handoff really happened (pages migrated over the wire, not failed open).
    """
    engine = make_engine(cfg)
    baseline = mixed_baseline(engine)
    completions, donor, recv = drain_disagg_pair(engine, mixed_requests())
    assert set(completions) == set(baseline)
    for uid, base in baseline.items():
        got = completions[uid]
        assert got.tokens == base.tokens, f"uid {uid} diverged"
        assert got.finish_reason == base.finish_reason
    assert recv._migrated_inserts == 2  # both long prompts adopted remotely
    assert donor._pages_migrated > 0
    assert donor._migration_bytes > 0
    assert donor._migration_failures == 0
    # all donor pages freed after commit; receiver retired its slots clean
    if donor.prefix_cache is not None:
        donor.prefix_cache.clear()
        recv.prefix_cache.clear()
    assert donor.allocator.used_pages == 0
    assert recv.allocator.used_pages == 0


def test_disagg_sink_rejection_fails_open_token_identical():
    """A handoff the sink refuses (no peers, closed loop, cancelled ticket)
    must leave the donor decoding locally with the SAME tokens — the client
    stream never notices, the failure is a counter."""
    engine = make_engine(TINY_LLAMA)
    baseline = mixed_baseline(engine)
    completions, donor, recv = drain_disagg_pair(
        engine, mixed_requests(), sink_override=lambda record, entries: False
    )
    assert {u: c.tokens for u, c in completions.items()} == {
        u: c.tokens for u, c in baseline.items()
    }
    assert donor._migration_failures == 2
    assert recv._migrated_inserts == 0


def test_disagg_corrupt_frame_fails_open_token_identical():
    """A frame torn in flight decodes to ValueError on the receiver; the
    donor fails open and the drain stays token-identical, zero drops."""
    engine = make_engine(TINY_LLAMA)
    baseline = mixed_baseline(engine)
    completions, donor, recv = drain_disagg_pair(
        engine, mixed_requests(), wire_hook=lambda blob: blob[:-9]
    )
    assert {u: c.tokens for u, c in completions.items()} == {
        u: c.tokens for u, c in baseline.items()
    }
    assert recv._migrated_inserts == 0
    assert donor._migration_failures == 2  # typed fail-open, never a drop
    assert len(completions) == 4


def test_submit_migrated_rejects_inconsistent_runs():
    engine = make_engine(TINY_LLAMA)
    donor = make_sched(engine, role="prefill")
    recv = make_sched(engine, role="decode")
    grabbed = {}
    donor.migration_sink = lambda record, entries: grabbed.update(
        record=dict(record), entries=entries
    ) or True
    req = mixed_requests()[0]
    donor.submit(req)
    for _ in range(20):
        if grabbed:
            break
        donor.step()
    assert grabbed, "donor never exported the run"
    record, entries = grabbed["record"], grabbed["entries"]

    bad = dict(record, position=record["position"] + 1)
    with pytest.raises(ValueError, match="inconsistent"):
        recv.submit_migrated(bad, entries)
    bad = dict(record, n_pages=record["n_pages"] + 1)
    with pytest.raises(ValueError, match="inconsistent"):
        recv.submit_migrated(bad, entries)
    # malformed entries (wrong leaf set) must reject before touching the pool
    with pytest.raises(ValueError):
        recv.submit_migrated(record, entries[:1])
    assert recv.allocator.used_pages == 0  # every rejection rolled back

    recv.submit_migrated(record, entries)
    with pytest.raises(ValueError, match="already in flight"):
        recv.submit_migrated(record, entries)  # dup uid
    donor.migration_commit(record["uid"], 0)
    recv.cancel(record["uid"])
    recv.run([])

