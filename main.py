"""relora-tpu training CLI — the torchrun_main.py equivalent.

Single entry point for pretraining (full-rank or ReLoRA) on TPU.  Unlike the
reference there is no process launcher: on a TPU pod slice, run this same
script on every host (`jax.distributed.initialize` discovers the slice); on
one host it just runs.

Examples (reference README parity)::

    # full-rank warmup
    python main.py --model_config llama_35m --dataset_path data/c4_tok \
        --batch_size 24 --total_batch_size 1152 --lr 5e-4 \
        --num_training_steps 10000 --save_dir ckpts/warmup

    # ReLoRA from the warmup
    python main.py --model_config llama_250m --dataset_path data/c4_tok \
        --batch_size 24 --total_batch_size 1152 --lr 1e-3 --use_peft true \
        --relora 5000 --cycle_length 5000 --restart_warmup_steps 100 \
        --scheduler cosine_restarts --warmed_up_model ckpts/warmup/model_10000 \
        --num_training_steps 20000 --save_dir ckpts/relora

    # or everything from a YAML recipe (reference format)
    python main.py --training_config training_configs/1B_v1.0.yaml
"""

from __future__ import annotations

import json
import os


def main(argv=None) -> dict:
    from relora_tpu.utils.logging import honor_platform_request

    honor_platform_request()
    from relora_tpu.utils.logging import enable_compile_cache, enable_xla_overlap_flags

    # before the first jax import below: XLA reads XLA_FLAGS exactly once at
    # backend init, and the tp/fsdp step wants its collectives overlapped
    enable_xla_overlap_flags()
    enable_compile_cache()
    from relora_tpu.config.training import parse_train_args
    from relora_tpu.utils.logging import get_logger

    logger = get_logger("relora_tpu.main")
    cfg = parse_train_args(argv)

    import jax

    if cfg.prng_impl:
        # e.g. 'rbg': hardware random bits instead of threefry — dropout
        # bits per LoRA-wrapped linear are a measurable TPU cost (the
        # bench_sweep --prng lever, promoted to a recipe knob)
        jax.config.update("jax_default_prng_impl", cfg.prng_impl)

    if int(os.environ.get("RELORA_TPU_DISTRIBUTED", "0")):
        # multi-host pod: coordinator discovery via TPU metadata
        jax.distributed.initialize()

    from relora_tpu.train.trainer import Trainer

    trainer = Trainer(cfg)

    if cfg.dataset_path is not None:
        train_factory, eval_factory = _hf_data(cfg, trainer)
    else:
        train_factory, eval_factory = _megatron_data(cfg, trainer)

    result = trainer.fit(
        train_factory(), eval_factory, train_iter_factory=train_factory
    )
    logger.info(f"Result: {result}")
    return result


def _hf_data(cfg, trainer):
    """Pretokenized HF dataset path (parity: torchrun_main.py:431-462 incl.
    provenance/size checks)."""
    import datasets

    from relora_tpu.data.hf_pipeline import TokenBatchIterator
    from relora_tpu.utils.logging import get_logger

    logger = get_logger("relora_tpu.main")
    ds = datasets.load_from_disk(cfg.dataset_path)
    if isinstance(ds, datasets.DatasetDict):
        train_ds = ds["train"]
        eval_ds = ds.get("validation") or ds.get("test")
    else:
        split = ds.train_test_split(test_size=min(2000, max(2, len(ds) // 100)), seed=cfg.seed)
        train_ds, eval_ds = split["train"], split["test"]

    # provenance check (parity: torchrun_main.py:452-455)
    prov = os.path.join(cfg.dataset_path, "args.json")
    if os.path.exists(prov):
        with open(prov) as f:
            args = json.load(f)
        if args.get("sequence_length") not in (None, cfg.max_length):
            raise ValueError(
                f"Dataset was pretokenized with sequence_length="
                f"{args.get('sequence_length')}, but max_length={cfg.max_length}"
            )

    # dataset big enough for the planned run (parity: torchrun_main.py:446-450)
    planned_tokens = cfg.num_training_steps * cfg.total_batch_size * cfg.max_length
    available = len(train_ds) * cfg.max_length
    if available < planned_tokens:
        logger.warning(
            f"Dataset has ~{available:,} tokens but the run plans "
            f"{planned_tokens:,}; training will stop early"
        )

    import jax

    def train_factory():
        return iter(
            TokenBatchIterator(
                train_ds,
                microbatch=cfg.batch_size * trainer.n_batch_shards // jax.process_count(),
                grad_accum=trainer.grad_accum,
                skip_updates=trainer.update_step,
                process_index=jax.process_index(),
                process_count=jax.process_count(),
            )
        )

    def eval_factory():
        return iter(
            TokenBatchIterator(
                eval_ds,
                microbatch=cfg.batch_size * trainer.n_batch_shards // jax.process_count(),
                grad_accum=None,
                process_index=jax.process_index(),
                process_count=jax.process_count(),
            )
        )

    return train_factory, eval_factory


def _megatron_data(cfg, trainer):
    """Megatron mmap dataset path (parity: load_megatron_dataset,
    torchrun_main.py:276-319)."""
    from relora_tpu.data.megatron import build_train_valid_test_iterators

    return build_train_valid_test_iterators(cfg, trainer)


if __name__ == "__main__":
    main()
