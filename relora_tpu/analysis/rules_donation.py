"""RTL3xx — buffer donation and aliasing.

``donate_argnums`` lets XLA reuse an input buffer for the output — the only
way the big train-state/KV-cache updates run without doubling their memory
footprint.  Two ways to get it wrong:

- RTL301: **use after donation** — reading a donated argument after the
  jitted call returns.  The buffer now holds the *output* (or garbage);
  JAX raises on CPU but on TPU a deleted-buffer read can surface as a
  cryptic error far from the cause.  Rebind the result over the donated
  name in the same statement (``state, m = step(state, ...)``).
- RTL302: **missing donation** — a same-module jitted function whose
  parameters include large mutable state (named ``state`` / ``opt_state``
  / ``cache`` / ``dcache``) with no ``donate_argnums``/``donate_argnames``:
  every call allocates a second copy of that state.  Parameter trees that
  are *reused* across calls (e.g. eval ``params``) must NOT be donated —
  hence the rule keys on the state-like names only.

Scope: donation tracking is per-module and per-class (``self._step =
jax.jit(..., donate_argnums=...)`` assignments are visible to every method
of the class).  Cross-object aliasing (another object's donated buffers)
is out of reach for an AST pass.
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, Iterator, List, Optional, Set, Tuple

from relora_tpu.analysis.core import (
    FileContext,
    Finding,
    catalog,
    checker,
    const_int_set,
    dotted_name,
    get_kwarg,
    is_jit_call,
    target_path,
    unwrap_partial,
)

catalog(
    RTL301="donated argument read after the jitted call (buffer reused by the output)",
    RTL302="jitted function with large-state params lacks donate_argnums (doubles state memory per call)",
)

DONATABLE = frozenset({"state", "opt_state", "cache", "dcache"})


def _donated_nums(call: ast.Call) -> Optional[FrozenSet[int]]:
    """Donated positions of a jit call; None when not a donating jit."""
    val = get_kwarg(call, "donate_argnums")
    if val is None:
        return None
    return const_int_set(val) or frozenset()


def _collect_defs(tree: ast.Module) -> Dict[str, ast.FunctionDef]:
    defs: Dict[str, ast.FunctionDef] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            defs.setdefault(node.name, node)
    return defs


def _wrapped_params(call: ast.Call, defs) -> Optional[List[str]]:
    """Positional parameter names of the function a jit call wraps, when
    resolvable (local def, lambda, or partial of a local def)."""
    if not call.args:
        return None
    target = call.args[0]
    if isinstance(target, ast.Lambda):
        return [a.arg for a in target.args.posonlyargs + target.args.args]
    if isinstance(target, ast.Call) and dotted_name(target.func) in (
        "partial",
        "functools.partial",
    ):
        if target.args and isinstance(target.args[0], ast.Name):
            target = target.args[0]
        else:
            return None
    if isinstance(target, ast.Name):
        fn = defs.get(target.id)
        if fn is not None:
            return [a.arg for a in fn.args.posonlyargs + fn.args.args]
    return None


# ---------------------------------------------------------------------------
# RTL301: in-order use-after-donation simulation


class _Events:
    """In-order load/store/consume event stream for one function body,
    honoring Python evaluation order (values before targets; call
    arguments before the donation takes effect).  Loop bodies replay
    twice so a consume at the bottom meets the loads at the top."""

    def __init__(self, donating: Dict[str, FrozenSet[int]]):
        self.donating = donating
        self.stream: List[Tuple[str, str, ast.AST]] = []

    def expr(self, node: ast.AST) -> None:
        if node is None:
            return
        path = target_path(node)
        if path:
            self.stream.append(("load", path, node))
            return
        if isinstance(node, ast.Call):
            self.expr(node.func)
            for arg in node.args:
                self.expr(arg)
            for kw in node.keywords:
                self.expr(kw.value)
            callee = target_path(node.func)
            donated = self.donating.get(callee)
            if donated:
                for i in donated:
                    if i < len(node.args):
                        arg_path = target_path(node.args[i])
                        if arg_path:
                            self.stream.append(("consume", arg_path, node.args[i]))
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self.expr(child)

    def store(self, node: ast.AST) -> None:
        if isinstance(node, (ast.Tuple, ast.List)):
            for elt in node.elts:
                self.store(elt)
            return
        if isinstance(node, ast.Starred):
            self.store(node.value)
            return
        path = target_path(node)
        if path:
            self.stream.append(("store", path, node))
        elif isinstance(node, ast.Subscript):
            # writing into a slot of a donated buffer is also a use
            self.expr(node.value)

    def stmts(self, body) -> None:
        for stmt in body:
            if isinstance(stmt, ast.Assign):
                self.expr(stmt.value)
                for tgt in stmt.targets:
                    self.store(tgt)
            elif isinstance(stmt, ast.AnnAssign):
                self.expr(stmt.value)
                self.store(stmt.target)
            elif isinstance(stmt, ast.AugAssign):
                self.expr(stmt.value)
                self.expr(stmt.target)
                self.store(stmt.target)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                self.expr(stmt.iter)
                for _ in range(2):  # two passes: catch cross-iteration reads
                    self.store(stmt.target)
                    self.stmts(stmt.body)
                self.stmts(stmt.orelse)
            elif isinstance(stmt, ast.While):
                for _ in range(2):
                    self.expr(stmt.test)
                    self.stmts(stmt.body)
                self.stmts(stmt.orelse)
            elif isinstance(stmt, ast.If):
                self.expr(stmt.test)
                self.stmts(stmt.body)
                self.stmts(stmt.orelse)
            elif isinstance(stmt, ast.With):
                for item in stmt.items:
                    self.expr(item.context_expr)
                    if item.optional_vars is not None:
                        self.store(item.optional_vars)
                self.stmts(stmt.body)
            elif isinstance(stmt, ast.Try):
                self.stmts(stmt.body)
                for handler in stmt.handlers:
                    self.stmts(handler.body)
                self.stmts(stmt.orelse)
                self.stmts(stmt.finalbody)
            elif isinstance(stmt, (ast.Expr, ast.Return, ast.Assert, ast.Raise)):
                for child in ast.iter_child_nodes(stmt):
                    if isinstance(child, ast.expr):
                        self.expr(child)
            elif isinstance(stmt, ast.FunctionDef):
                self.stmts(stmt.body)  # closure over the same locals


def _related(a: str, b: str) -> bool:
    return a == b or a.startswith(b + ".") or b.startswith(a + ".")


def _simulate(
    ctx: FileContext, fn: ast.FunctionDef, donating: Dict[str, FrozenSet[int]]
) -> Iterator[Finding]:
    ev = _Events(donating)
    ev.stmts(fn.body)
    consumed: Dict[str, int] = {}  # path -> line of the donating call
    reported: Set[Tuple[str, int]] = set()
    for kind, path, node in ev.stream:
        if kind == "store":
            for c in [c for c in consumed if _related(c, path)]:
                del consumed[c]
        elif kind == "consume":
            consumed[path] = getattr(node, "lineno", 0)
        elif kind == "load":
            for c, at_line in consumed.items():
                if path == c or path.startswith(c + "."):
                    key = (path, getattr(node, "lineno", 0))
                    if key not in reported:
                        reported.add(key)
                        yield ctx.finding(
                            node,
                            "RTL301",
                            f"`{path}` was donated to the jitted call at line "
                            f"{at_line} and read afterwards — the buffer now "
                            "holds the output; rebind the result over the "
                            "donated name",
                        )
                    break


def _scope_locals(body, out: Dict[str, FrozenSet[int]]) -> Dict[str, FrozenSet[int]]:
    """Donating jit assignments to bare names within one scope's statements
    (nested function/class bodies excluded — they are their own scopes).
    A non-donating jit rebind records an empty set, shadowing any inherited
    donating binding of the same name."""
    for stmt in body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            continue
        if isinstance(stmt, ast.Assign) and is_jit_call(stmt.value):
            donated = _donated_nums(stmt.value) or frozenset()
            for tgt in stmt.targets:
                if isinstance(tgt, ast.Name):
                    out[tgt.id] = donated
        for field in ("body", "orelse", "finalbody"):
            sub = getattr(stmt, field, None)
            if sub:
                _scope_locals(sub, out)
        for handler in getattr(stmt, "handlers", []):
            _scope_locals(handler.body, out)
    return out


def _scoped_registries(
    tree: ast.Module, shared: Dict[str, FrozenSet[int]]
) -> Dict[int, Dict[str, FrozenSet[int]]]:
    """Per-FunctionDef donation registry: `shared` (attribute paths like
    ``self._step``, donating decorated defs) + module-level names + the
    locals of every enclosing function.  Bare-name jit bindings are
    function-scoped on purpose — two test functions both naming their
    callable ``step`` must not see each other's donate_argnums."""
    per_fn: Dict[int, Dict[str, FrozenSet[int]]] = {}

    def recurse(body, inherited: Dict[str, FrozenSet[int]]) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                own = dict(inherited)
                _scope_locals(stmt.body, own)
                per_fn[id(stmt)] = {**shared, **own}
                recurse(stmt.body, own)
            elif isinstance(stmt, ast.ClassDef):
                recurse(stmt.body, inherited)
            else:
                for field in ("body", "orelse", "finalbody"):
                    sub = getattr(stmt, field, None)
                    if sub:
                        recurse(sub, inherited)
                for handler in getattr(stmt, "handlers", []):
                    recurse(handler.body, inherited)

    recurse(tree.body, _scope_locals(tree.body, {}))
    return per_fn


@checker
def check_donation(ctx: FileContext) -> List[Finding]:
    findings: List[Finding] = []
    defs = _collect_defs(ctx.tree)

    # -- collect donating callables reachable from any scope ----------------
    # dotted attribute paths (`self._step = jax.jit(..., donate_argnums=..)`)
    # are visible class/module-wide; bare names are scoped per function below
    donating: Dict[str, FrozenSet[int]] = {}
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Assign) and is_jit_call(node.value):
            donated = _donated_nums(node.value)
            if donated:
                for tgt in node.targets:
                    path = target_path(tgt)
                    if path and "." in path:
                        donating[path] = donated

    for node in ast.walk(ctx.tree):
        if not is_jit_call(node):
            continue
        if (
            _donated_nums(node) is not None
            or get_kwarg(node, "donate_argnames") is not None
        ):
            continue
        params = _wrapped_params(node, defs)
        if not params:
            continue
        stateful = [p for p in params if p in DONATABLE]
        if stateful:
            findings.append(
                ctx.finding(
                    node,
                    "RTL302",
                    f"jitted function takes large state ({', '.join(stateful)}) "
                    "but has no donate_argnums — every call allocates a second "
                    "copy of that state",
                )
            )

    # decorated defs: bare `@jax.jit` (or a jit/partial call without donate
    # kwargs) on a def with state-like params is the same missing-donation
    # bug; with donate_argnums it registers the def as a donating callable.
    for fn in defs.values():
        for dec in fn.decorator_list:
            call = dec if is_jit_call(dec) else unwrap_partial(dec)
            is_bare_jit = dotted_name(dec) in ("jit", "jax.jit")
            if call is None and not is_bare_jit:
                continue
            donated = _donated_nums(call) if call is not None else None
            names_kw = (
                get_kwarg(call, "donate_argnames") if call is not None else None
            )
            if donated:
                donating.setdefault(fn.name, donated)
            elif donated is None and names_kw is None:
                params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
                stateful = [p for p in params if p in DONATABLE]
                if stateful:
                    findings.append(
                        ctx.finding(
                            fn,
                            "RTL302",
                            f"jitted function takes large state "
                            f"({', '.join(stateful)}) but has no "
                            "donate_argnums — every call allocates a second "
                            "copy of that state",
                        )
                    )

    # -- RTL301: simulate each function against its scoped registry ---------
    registries = _scoped_registries(ctx.tree, donating)
    for fn in defs.values():
        findings.extend(_simulate(ctx, fn, registries.get(id(fn), donating)))
    return findings
