#!/bin/bash
# TPU queue v3 — round-5, post bf16-base OOM analysis.
#
# Window-2 findings this supersedes v2 with: the bf16-base lever works (no
# convert temps in the OOM dump) but dots-policy residuals are dominated by
# FOUR intermediate-width (5461) tensors per layer (~4 GB at mb4) plus ~3 GB
# of XLA layout copies of the MLP kernels the planner cannot see.  The new
# 'dots_narrow' remat policy (params_util.remat_policy) recomputes the
# gate/up projections (2 of ~12 projection-matmul units) and drops the
# intermediate-width residual term entirely: planner says bf16-base fits
# through mb12 (8.45 GB at mb4); with the ~3-4 GB layout-copy blind spot,
# mb8 is the realistic top try.  OOM failures are cheap (~90 s to the
# compile error) so the ladder tries mb8 -> mb6 -> mb4 and stops at the
# first success (same FLOPs/token; larger mb is strictly >= on MXU
# utilization).
#
# Usage: nohup bash scripts/tpu_queue_v3.sh > /tmp/tpu_queue_v3.log 2>&1 &
set -u
cd "$(dirname "$0")/.."
RES=bench_results
mkdir -p "$RES"

commit() { # commit <message> -- <paths...>
  local msg="$1"; shift; shift
  git add "$@" 2>/dev/null
  git diff --cached --quiet || git commit -q -m "$msg

No-Verification-Needed: bench/measurement artifacts only" -- "$@"
}

probe() {
  timeout -k 10 180 python -c \
    "import jax,jax.numpy as jnp;print(float(jax.jit(lambda a:(a@a).sum())(jnp.ones((128,128)))))" \
    >/dev/null 2>&1
}

sweep() { # sweep <args...> ; returns 0 iff a measurement landed
  BENCH_WATCHDOG_SECS=1500 timeout 1800 python scripts/bench_sweep.py \
      --out "$RES/r5_sweep.jsonl" "$@"
  local rc=$?
  if [ $rc -ne 0 ]; then
    echo "{\"error\": \"failed: $*\"}" >> "$RES/r5_sweep.jsonl"
  fi
  commit "On-chip sweep: $*" -- "$RES/r5_sweep.jsonl"
  return $rc
}

replay_winner() {
  local BEST
  BEST=$(python - <<'EOF'
import json, re
best_mfu, best = 0.0, ""
try:
    lines = list(open("bench_results/r5_sweep.jsonl"))
except OSError:
    lines = []
for line in lines:
    try:
        r = json.loads(line)
    except ValueError:
        continue
    label = r.get("label", "")
    mfu = r.get("mfu") or 0.0
    if label and mfu > best_mfu:
        m = re.search(r"mb(\d+)", label)
        ga = re.search(r"ga(\d+)", label)
        best_mfu = mfu
        # ORDER MATTERS: dots_narrow/dots_all both contain 'dots'
        if "dots_narrow" in label:
            policy = "dots_narrow"
        elif "dots_all" in label:
            policy = "dots_all"
        elif "dots" in label:
            policy = "dots"
        else:
            policy = "full"
        best = ":".join((
            ga.group(1) if ga else "1",
            policy,
            m.group(1) if m else "8",
            "chunked" if "chunked" in label else "dense",
            "0" if "dropout0" in label else "0.1",
            "int8" if "int8" in label else ("nf4" if "nf4" in label else ""),
            "bf16" if "bf16 base" in label else "",
            "1" if "pallas-dequant" in label else "0",
        ))
# Missing or malformed headline file means there is no committed headline
# to beat — replay at mfu=0 rather than silently skipping the refresh.
try:
    head_mfu = json.load(open("bench_results/BENCH_r5_local.json"))["detail"]["mfu"]
except (OSError, ValueError, KeyError, TypeError):
    head_mfu = 0.0
print(best if best_mfu > head_mfu else "")
EOF
)
  [ -z "$BEST" ] && return 0
  local BEST_GA BEST_POLICY BEST_MB BEST_LOSS BEST_DROPOUT BEST_QUANT BEST_BASE BEST_PALLAS
  IFS=: read -r BEST_GA BEST_POLICY BEST_MB BEST_LOSS BEST_DROPOUT BEST_QUANT BEST_BASE BEST_PALLAS <<< "$BEST"
  RELORA_TPU_PALLAS_QUANT="${BEST_PALLAS:-0}" \
    BENCH_REMAT_POLICY="$BEST_POLICY" BENCH_MICRO_BATCH="$BEST_MB" \
    BENCH_GRAD_ACCUM="$BEST_GA" \
    BENCH_LOSS_IMPL="$BEST_LOSS" BENCH_DROPOUT="$BEST_DROPOUT" \
    BENCH_QUANTIZE="$BEST_QUANT" BENCH_BASE_DTYPE="$BEST_BASE" \
    BENCH_WATCHDOG_SECS=1500 timeout 1800 python bench.py \
    > "$RES/BENCH_r5_local_${BEST_POLICY}.json" 2>/dev/null \
    && commit "On-chip headline bench with $BEST_POLICY remat (mb $BEST_MB, $BEST_LOSS loss, base ${BEST_BASE:-${BEST_QUANT:-f32}})" -- "$RES/BENCH_r5_local_${BEST_POLICY}.json" "$RES/last_onchip.json"
}

echo "queue v3 start $(date -u +%FT%TZ)"
while ! probe; do
  echo "tunnel down $(date -u +%FT%TZ)"
  sleep 240
done
echo "tunnel UP $(date -u +%FT%TZ)"

# 1. dots_narrow ladder, largest mb first; stop at first success (same
# FLOPs/token; larger mb is >= on MXU utilization).  OOM failures cost
# ~90 s; successful compiles are the slow part.
if sweep --base-dtype bf16 --remat --remat-policy dots_narrow --loss-impl chunked --micro-batch 12 --label "bf16 base dots_narrow chunked mb12"; then
  :
elif sweep --base-dtype bf16 --remat --remat-policy dots_narrow --loss-impl chunked --micro-batch 8 --label "bf16 base dots_narrow chunked mb8"; then
  :
elif sweep --base-dtype bf16 --remat --remat-policy dots_narrow --loss-impl chunked --micro-batch 6 --label "bf16 base dots_narrow chunked mb6"; then
  :
else
  sweep --base-dtype bf16 --remat --remat-policy dots_narrow --loss-impl chunked --micro-batch 4 --label "bf16 base dots_narrow chunked mb4"
fi

# 1b. the dots-policy family predicts 36.4% (r5_lever_rank) but measured
# 29.1% at mb2 (small-batch MXU penalty) and OOMed by 854 MB at bf16 mb4
# — mb3 is the untried point between
sweep --base-dtype bf16 --remat --remat-policy dots --loss-impl chunked --micro-batch 3 --label "bf16 base dots chunked mb3"

# 2. headline refresh if anything beat the committed headline
replay_winner

# 3. loss parity (verdict must: <=1% at 35m / 1000-step cycles / 4000 steps).
# Corpus is usually prebuilt by this point; WAIT_CORPUS_SECS opts into
# waiting for a still-running fresh-sandbox rebuild (loss_parity.sh
# defaults to fail-fast).
CORPUS=/tmp/corpus/local400 WORK=/tmp/loss_parity WAIT_CORPUS_SECS=5400 \
  STEPS_WARMUP=500 STEPS_TOTAL=4000 timeout 10800 bash scripts/loss_parity.sh \
  > /tmp/loss_parity.log 2>&1
echo "loss_parity exit=$? $(date -u +%FT%TZ)"
if [ -f /tmp/loss_parity/compare_llama_35m.json ]; then
  cp /tmp/loss_parity/compare_llama_35m.json "$RES/r5_loss_parity_chip.json"
  commit "On-chip loss-parity result (llama_35m, 1000-step cycles, 4000 steps)" -- "$RES/r5_loss_parity_chip.json"
fi
CORPUS=/tmp/corpus/local400 WORK=/tmp/loss_parity OPT_PRUNE=0.9 WAIT_CORPUS_SECS=5400 \
  STEPS_WARMUP=500 STEPS_TOTAL=4000 timeout 10800 bash scripts/loss_parity.sh \
  > /tmp/loss_parity_mag.log 2>&1
echo "loss_parity magnitude exit=$? $(date -u +%FT%TZ)"
if [ -f /tmp/loss_parity/compare_llama_35m_mag0.9.json ]; then
  cp /tmp/loss_parity/compare_llama_35m_mag0.9.json "$RES/r5_loss_parity_chip_mag.json"
  commit "On-chip loss-parity: magnitude-pruning reset at 1000-step cycles" -- "$RES/r5_loss_parity_chip_mag.json"
fi

# 4. attention op-level A/B — MHA then GQA (16q/4kv, the un-expanded path)
timeout 2400 python scripts/bench_attention.py --seqs 1024 4096 16384 --impls xla pallas \
  > "$RES/r5_attn.jsonl" 2>/tmp/attn_r5.err \
  && commit "Attention op-level A/B (xla vs pallas, 1k/4k/16k)" -- "$RES/r5_attn.jsonl"
timeout 2400 python scripts/bench_attention.py --seqs 4096 16384 --impls xla pallas \
  --kv-heads 4 >> "$RES/r5_attn.jsonl" 2>>/tmp/attn_r5.err \
  && commit "Attention op-level A/B: GQA 16q/4kv" -- "$RES/r5_attn.jsonl"

# 5. remaining utilization/base-storage levers, by expected value
sweep --base-dtype bf16 --remat --loss-impl chunked --micro-batch 24 --label "bf16 base full chunked mb24"
sweep --remat --loss-impl chunked --micro-batch 16 --label "remat full chunked mb16"
sweep --base-dtype bf16 --remat --remat-policy dots_all --loss-impl chunked --micro-batch 2 --label "bf16 base dots_all chunked mb2"
sweep --remat --quantize int8 --label "remat int8-base"
sweep --remat --quantize nf4 --label "remat nf4-base"
RELORA_TPU_PALLAS_QUANT=1 sweep --remat --quantize int8 --label "remat int8-base pallas-dequant"
sweep --remat --dropout 0 --label "remat full dropout0"
replay_winner

# 6. extra bench configs
BENCH_CONFIG=llama_250m BENCH_WATCHDOG_SECS=1500 timeout 1800 python bench.py > "$RES/BENCH_r5_250m.json" 2>/dev/null \
  && commit "On-chip bench: llama_250m config" -- "$RES/BENCH_r5_250m.json"
BENCH_CONFIG=llama_1b_magnitude BENCH_WATCHDOG_SECS=1500 timeout 1800 python bench.py > "$RES/BENCH_r5_magnitude.json" 2>/dev/null \
  && commit "On-chip bench: magnitude-reset config" -- "$RES/BENCH_r5_magnitude.json"

# 7. long-context throughput: one JSON line per seq, append-mode
for S in 4096 16384 32768; do
  grep -q "\"seq\": $S" "$RES/r5_longcontext.jsonl" 2>/dev/null && continue
  timeout 1800 python tools/bench_longcontext.py --mode throughput --seq "$S" \
    >> "$RES/r5_longcontext.jsonl" 2>/tmp/longctx_r5.err \
    || echo "{\"error\": \"failed: seq $S\"}" >> "$RES/r5_longcontext.jsonl"
done
grep -q tokens_per_sec "$RES/r5_longcontext.jsonl" 2>/dev/null \
  && commit "Long-context throughput bench (4k/16k/32k)" -- "$RES/r5_longcontext.jsonl"

# 8. slow compiles / lower-value retries, one attempt each.  The f32
# dots_narrow point isolates the bf16-base contribution from the policy's.
sweep --remat --remat-policy dots_narrow --loss-impl chunked --micro-batch 6 --label "remat dots_narrow chunked mb6"
sweep --quantize int8 --remat --remat-policy dots --loss-impl chunked --micro-batch 4 --label "int8 base dots chunked mb4 retry"
replay_winner
echo "queue v3 done $(date -u +%FT%TZ)"
