"""Ulysses-style sequence parallelism: all-to-all head/sequence exchange.

The second context-parallel mode (the task's "ring attention or all-to-all
sequence parallelism"): activations arrive sequence-sharded ``(B, S/sp, N,
H)``; an all-to-all over the ``sequence`` axis re-partitions them to
head-sharded ``(B, S, N/sp, H)``, each device runs ordinary full attention
over its head subset with the complete sequence, and a reverse all-to-all
restores sequence sharding.

Trade-off vs ring attention (parallel/ring_attention.py): Ulysses moves
2×(B·S·N·H) elements per call through two all-to-alls but then attends with
one dense kernel (better MXU utilization, no block-level load imbalance);
the ring streams K/V with sp ppermutes and never materializes the full
sequence on any device (lower peak memory, better for extreme S).  Requires
``num_heads % sp == 0``.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
from jax.sharding import Mesh, PartitionSpec as P

from relora_tpu.parallel._compat import shard_map

from relora_tpu.ops.attention import dot_product_attention
from relora_tpu.parallel.mesh import DATA_AXIS, FSDP_AXIS, SEQUENCE_AXIS


def _ulysses_local(q, k, v, *, axis_name: str, causal: bool, scale: float, inner_impl: str):
    # (B, S/sp, N, H) -> (B, S, N/sp, H): concat seq shards, split heads
    def to_heads(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    def to_seq(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
    out = dot_product_attention(qh, kh, vh, causal=causal, impl=inner_impl, scale=scale)
    return to_seq(out)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    seq_axis: str = SEQUENCE_AXIS,
    inner_impl: str = "xla",
) -> jax.Array:
    """Causal attention over (B, S, N, H) with S sharded on ``seq_axis``.
    ``num_heads`` must divide by the axis size.  Grouped K/V stay grouped
    when ``n_kv`` also divides by the axis size (the all-to-all then moves
    ``n_kv/N`` of the K/V bytes); otherwise they are expanded first.
    """
    sp = mesh.shape[seq_axis]
    if q.shape[2] % sp != 0:
        raise ValueError(f"num_heads={q.shape[2]} must divide by sequence axis size {sp}")
    if k.shape[2] != q.shape[2] and k.shape[2] % sp != 0:
        from relora_tpu.ops.attention import _expand_grouped_kv

        k, v = _expand_grouped_kv(q, k, v)
    if scale is None:
        scale = q.shape[-1] ** -0.5
    spec = P((DATA_AXIS, FSDP_AXIS), seq_axis, None, None)
    fn = shard_map(
        functools.partial(
            _ulysses_local,
            axis_name=seq_axis,
            causal=causal,
            scale=scale,
            inner_impl=inner_impl,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
