"""HTTP front-end tests: the asyncio server over the incremental scheduler.

Covers the serving acceptance criteria on CPU with a tiny model:

- streamed SSE output is token-identical to ``scheduler.run()`` for the same
  (uid, key) — HTTP adds transport, not nondeterminism;
- a full admission queue answers 429 + Retry-After while in-flight streams
  keep going (bounded memory under overload);
- ``deadline_s`` expiry mid-decode returns the partial output with
  ``finish_reason: "timeout"``;
- a client disconnect frees the decode slot for the next request;
- drain (the SIGTERM handler's body; the real signal is exercised by
  scripts/smoke_test.sh) finishes in-flight work, 503s new work, and stops
  the server.

The server runs in a background thread (signal handlers off — they need the
main thread's loop); clients are raw close-delimited HTTP/1.1 sockets, so
these tests pin the exact wire format the stdlib front-end speaks.
"""

import asyncio
import json
import socket
import threading
import time

import jax
import jax.numpy as jnp
import pytest

from relora_tpu.config.model import ModelConfig
from relora_tpu.models.params_util import init_params
from relora_tpu.serve.engine import InferenceEngine, build_decode_model
from relora_tpu.serve.scheduler import ContinuousBatchingScheduler, Request
from relora_tpu.serve.server import BadRequest, GenerateServer, parse_generate_body

pytestmark = pytest.mark.serve

TINY = ModelConfig(
    family="llama",
    vocab_size=256,
    hidden_size=64,
    intermediate_size=160,
    num_hidden_layers=2,
    num_attention_heads=4,
    max_sequence_length=512,
)
CACHE = 512


@pytest.fixture(scope="module")
def engine():
    model = build_decode_model(TINY, cache_size=CACHE)
    base = type(model)(TINY, lora=None, dtype=jnp.float32, scan_layers=True)
    params = init_params(base, jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    return InferenceEngine(TINY, params, cache_size=CACHE)


class _Server:
    """Run a GenerateServer in a background thread for the duration of a test.

    Exit drains (idempotent if the test already drained) and asserts the
    model thread did not die — a worker exception fails the test instead of
    hanging it."""

    def __init__(self, engine, *, max_batch=1, max_queue=4, key_seed=0, **kwargs):
        self.scheduler = ContinuousBatchingScheduler(
            engine, max_batch=max_batch, key=jax.random.PRNGKey(key_seed)
        )
        self.server = GenerateServer(
            self.scheduler, port=0, max_queue=max_queue, **kwargs
        )
        self.thread = threading.Thread(
            target=lambda: asyncio.run(
                self.server.serve_forever(install_signal_handlers=False)
            ),
            daemon=True,
        )

    def __enter__(self) -> GenerateServer:
        self.thread.start()
        assert self.server.started.wait(60), "server failed to start"
        return self.server

    def __exit__(self, *exc):
        self.server.begin_drain()
        self.thread.join(60)
        assert not self.thread.is_alive(), "server did not drain within 60s"
        assert self.server._worker_error is None, repr(self.server._worker_error)


# -- raw HTTP/1.1 clients (close-delimited, like the server speaks) -----------


def _request_bytes(method: str, path: str, body: bytes) -> bytes:
    head = (
        f"{method} {path} HTTP/1.1\r\nHost: test\r\n"
        f"Content-Length: {len(body)}\r\n\r\n"
    )
    return head.encode() + body


def _parse_response(data: bytes):
    head, _, rest = data.partition(b"\r\n\r\n")
    lines = head.split(b"\r\n")
    status = int(lines[0].split(b" ", 2)[1])
    headers = {}
    for line in lines[1:]:
        key, _, value = line.decode("latin-1").partition(":")
        headers[key.strip().lower()] = value.strip()
    return status, headers, rest


def _http(port: int, method: str, path: str, body=None, timeout=60.0):
    """One request, read to EOF (the server closes every connection)."""
    payload = b"" if body is None else (
        body if isinstance(body, bytes) else json.dumps(body).encode()
    )
    with socket.create_connection(("127.0.0.1", port), timeout=timeout) as sock:
        sock.sendall(_request_bytes(method, path, payload))
        data = b""
        while True:
            chunk = sock.recv(65536)
            if not chunk:
                break
            data += chunk
    return _parse_response(data)


def _sse_events(body: bytes):
    events = []
    for block in body.decode().split("\n\n"):
        block = block.strip()
        if not block.startswith("data: "):
            continue
        payload = block[len("data: "):]
        events.append("[DONE]" if payload == "[DONE]" else json.loads(payload))
    return events


def _generate(port: int, payload: dict):
    """POST /v1/generate and split the SSE stream into (tokens, final record)."""
    status, headers, body = _http(port, "POST", "/v1/generate", payload)
    assert status == 200, body
    events = _sse_events(body)
    assert events[-1] == "[DONE]"
    final = events[-2]
    token_events = events[:-2]
    assert [e["index"] for e in token_events] == list(range(len(token_events)))
    return [e["token"] for e in token_events], final


class _Stream:
    """An open streaming request: read SSE events one at a time, or hang up
    mid-stream (the disconnect / overload tests)."""

    def __init__(self, port: int, payload: dict, timeout=60.0):
        self.sock = socket.create_connection(("127.0.0.1", port), timeout=timeout)
        self.sock.sendall(
            _request_bytes("POST", "/v1/generate", json.dumps(payload).encode())
        )
        self.buf = b""
        head = self._read_until(b"\r\n\r\n")
        assert head is not None, "no response head"
        self.status = int(head.split(b" ", 2)[1])

    def _read_until(self, marker: bytes):
        while marker not in self.buf:
            chunk = self.sock.recv(4096)
            if not chunk:
                return None
            self.buf += chunk
        idx = self.buf.index(marker) + len(marker)
        out, self.buf = self.buf[:idx], self.buf[idx:]
        return out

    def next_event(self):
        block = self._read_until(b"\n\n")
        if block is None:
            return None
        text = block.decode().strip()
        assert text.startswith("data: "), text
        payload = text[len("data: "):]
        return "[DONE]" if payload == "[DONE]" else json.loads(payload)

    def read_to_done(self):
        events = []
        while True:
            event = self.next_event()
            assert event is not None, "stream ended before [DONE]"
            if event == "[DONE]":
                return events
            events.append(event)

    def close(self):
        self.sock.close()


def _solo_tokens(engine, uid: int, payload: dict, key_seed: int):
    """Reference: the same request alone through scheduler.run()."""
    sched = ContinuousBatchingScheduler(
        engine, max_batch=1, key=jax.random.PRNGKey(key_seed)
    )
    req = Request(
        uid=uid,
        prompt=payload["prompt"],
        max_new_tokens=payload["max_new_tokens"],
        temperature=payload.get("temperature", 0.0),
        top_p=payload.get("top_p", 1.0),
    )
    return sched.run([req])[uid].tokens


# -- request validation (no engine) -------------------------------------------


def test_parse_generate_body_validation():
    fields = parse_generate_body(
        json.dumps({"prompt": [1, 2, 3]}).encode(),
        default_max_new_tokens=8,
        default_temperature=0.5,
        default_top_p=0.9,
    )
    assert fields["prompt"] == [1, 2, 3]
    assert fields["max_new_tokens"] == 8
    assert fields["temperature"] == 0.5
    assert fields["top_p"] == 0.9
    assert fields["stream"] is True
    assert fields["deadline_s"] is None
    assert fields["spec"] is True  # per-request opt-out defaults to on

    opted_out = parse_generate_body(
        json.dumps({"prompt": [1], "spec": False}).encode(),
        default_max_new_tokens=8,
        default_temperature=0.0,
        default_top_p=1.0,
    )
    assert opted_out["spec"] is False

    bad = [
        b"not json",
        b"[1, 2]",
        json.dumps({}).encode(),
        json.dumps({"prompt": "text"}).encode(),
        json.dumps({"prompt": [1, True]}).encode(),
        json.dumps({"prompt": [1], "max_new_tokens": 0}).encode(),
        json.dumps({"prompt": [1], "temperature": -0.1}).encode(),
        json.dumps({"prompt": [1], "top_p": 0.0}).encode(),
        json.dumps({"prompt": [1], "top_p": 1.5}).encode(),
        json.dumps({"prompt": [1], "stream": "yes"}).encode(),
        json.dumps({"prompt": [1], "deadline_s": -1}).encode(),
        json.dumps({"prompt": [1], "spec": "on"}).encode(),
    ]
    for body in bad:
        with pytest.raises(BadRequest):
            parse_generate_body(
                body, default_max_new_tokens=8, default_temperature=0.0, default_top_p=1.0
            )


# -- determinism over HTTP ----------------------------------------------------


def test_streamed_tokens_match_scheduler_run(engine):
    """Acceptance: concurrent sampled HTTP streams produce exactly the tokens
    ``scheduler.run()`` produces for the same (uid, key) — batch composition
    and transport change nothing."""
    key_seed = 7
    payloads = [
        {"prompt": [1 + i, 2, 3], "max_new_tokens": 6, "temperature": 0.9}
        for i in range(3)
    ]
    results = {}

    def post(port, payload):
        tokens, final = _generate(port, payload)
        results[final["uid"]] = (payload, tokens, final)

    with _Server(engine, max_batch=2, max_queue=4, key_seed=key_seed) as server:
        threads = [
            threading.Thread(target=post, args=(server.port, p)) for p in payloads
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
    assert sorted(results) == [0, 1, 2]
    for uid, (payload, tokens, final) in results.items():
        assert final["finish_reason"] == "length"
        assert final["tokens"] == tokens, "stream diverged from the finish record"
        assert tokens == _solo_tokens(engine, uid, payload, key_seed)


def test_unary_response_matches_scheduler_run(engine):
    payload = {"prompt": [9, 8, 7], "max_new_tokens": 5, "stream": False}
    with _Server(engine, max_batch=1, key_seed=3) as server:
        status, _, body = _http(server.port, "POST", "/v1/generate", payload)
    assert status == 200
    record = json.loads(body)
    assert record["finish_reason"] == "length"
    assert record["tokens"] == _solo_tokens(engine, record["uid"], payload, 3)


# -- error paths and introspection endpoints ----------------------------------


def test_http_error_paths_and_endpoints(engine):
    with _Server(engine, max_batch=1) as server:
        port = server.port
        status, _, body = _http(port, "POST", "/v1/generate", b"not json")
        assert status == 400 and b"JSON" in body
        status, _, body = _http(port, "POST", "/v1/generate", {"prompt": []})
        assert status == 400 and b"prompt" in body
        # capacity violations surface as 400 before admission, not as a
        # decode-loop crash later
        status, _, body = _http(
            port, "POST", "/v1/generate",
            {"prompt": [1] * 16, "max_new_tokens": CACHE},
        )
        assert status == 400 and b"cache entries" in body
        status, _, _ = _http(port, "GET", "/v1/generate")
        assert status == 405
        status, _, _ = _http(port, "GET", "/no/such/route")
        assert status == 404
        # malformed request line -> 400, not a hung connection
        with socket.create_connection(("127.0.0.1", port), timeout=10) as sock:
            sock.sendall(b"garbage\r\n\r\n")
            data = b""
            while True:
                chunk = sock.recv(4096)
                if not chunk:
                    break
                data += chunk
        assert b"400" in data.split(b"\r\n", 1)[0]

        status, _, body = _http(port, "GET", "/healthz")
        assert status == 200
        health = json.loads(body)
        assert health["status"] == "ok"
        assert health["max_batch"] == 1 and health["max_queue"] == 4
        status, _, body = _http(port, "GET", "/metrics")
        assert status == 200
        text = body.decode()
        assert 'relora_serve_http_requests_total{route="healthz"} 1' in text
        assert 'relora_serve_rejected_total{reason="bad_request"}' in text


# -- flow control -------------------------------------------------------------


def test_overload_sheds_load_with_429(engine):
    """max_batch=1 + max_queue=1: one request decoding, one waiting; the
    third is rejected with 429 + Retry-After while the first keeps
    streaming — in-system work stays bounded under overload."""
    with _Server(engine, max_batch=1, max_queue=1, retry_after_s=2.0) as server:
        port = server.port
        a = _Stream(port, {"prompt": [1, 2], "max_new_tokens": 300})
        assert a.status == 200
        first = a.next_event()
        assert first["index"] == 0  # A holds the decode slot
        b = _Stream(port, {"prompt": [3, 4], "max_new_tokens": 50})
        assert b.status == 200  # B accepted: it fills the admission queue

        status, headers, body = _http(
            port, "POST", "/v1/generate", {"prompt": [5, 6], "max_new_tokens": 4}
        )
        assert status == 429, body
        assert headers.get("retry-after") == "2"
        assert b"admission queue full" in body

        # the reject did not disturb the in-flight stream
        assert a.next_event()["token"] is not None

        status, _, body = _http(port, "GET", "/metrics")
        assert 'relora_serve_rejected_total{reason="queue_full"} 1' in body.decode()
        a.close()
        b.close()


def test_deadline_expiry_returns_partial_output(engine):
    """A request that cannot finish inside deadline_s stops at a step
    boundary with its partial tokens and finish_reason "timeout"."""
    with _Server(engine, max_batch=1) as server:
        tokens, final = _generate(
            server.port,
            {"prompt": [1, 2, 3], "max_new_tokens": 480, "deadline_s": 0.25},
        )
    assert final["finish_reason"] == "timeout"
    assert 0 < len(tokens) < 480
    assert final["tokens"] == tokens


def test_client_disconnect_frees_slot(engine):
    """Hanging up mid-stream cancels the request at the next step boundary:
    the slot frees, metrics record the disconnect, and the next request gets
    the slot."""
    with _Server(engine, max_batch=1) as server:
        port = server.port
        a = _Stream(port, {"prompt": [1, 2], "max_new_tokens": 400})
        assert a.next_event()["index"] == 0
        a.close()

        deadline = time.monotonic() + 30.0
        freed = False
        while time.monotonic() < deadline:
            _, _, body = _http(port, "GET", "/metrics")
            text = body.decode()
            if (
                'relora_serve_requests_finished_total{reason="cancelled"} 1' in text
                and "relora_serve_active_slots 0" in text
            ):
                freed = True
                break
            time.sleep(0.05)
        assert freed, "slot was not freed after client disconnect"
        assert "relora_serve_disconnects_total 1" in text

        tokens, final = _generate(port, {"prompt": [7, 8], "max_new_tokens": 4})
        assert final["finish_reason"] == "length" and len(tokens) == 4


def test_drain_finishes_in_flight_and_rejects_new(engine):
    """begin_drain (the SIGTERM handler's body): in-flight streams run to
    completion, new requests get 503 + Retry-After, /healthz flips to
    draining, and serve_forever returns."""
    holder = _Server(engine, max_batch=1)
    with holder as server:
        port = server.port
        a = _Stream(port, {"prompt": [1, 2], "max_new_tokens": 60})
        assert a.next_event()["index"] == 0

        server.begin_drain()
        status, _, body = _http(port, "GET", "/healthz")
        assert status == 503 and json.loads(body)["status"] == "draining"
        status, headers, _ = _http(
            port, "POST", "/v1/generate", {"prompt": [9], "max_new_tokens": 2}
        )
        assert status == 503 and "retry-after" in headers

        events = a.read_to_done()
        final = events[-1]
        assert final["finish_reason"] == "length"
        assert len(final["tokens"]) == 60
        a.close()
        assert server.drained.wait(60), "model thread did not exit after drain"
        holder.thread.join(60)
        assert not holder.thread.is_alive(), "serve_forever did not return"


def test_request_id_header_and_span_propagation(engine):
    """One request id threads the whole stack: the client's X-Request-Id
    becomes the span trace_id on every phase (request, queue_wait, prefill,
    insert, decode, sse_flush) and is echoed on the response; without the
    header the server mints one."""
    from relora_tpu.obs.flight import FlightRecorder
    from relora_tpu.obs.tracer import Tracer

    recorder = FlightRecorder()
    tracer = Tracer(service="serve", recorder=recorder)
    with _Server(engine, tracer=tracer) as server:
        port = server.port
        rid = "feedfacecafebeef"
        head = (
            "POST /v1/generate HTTP/1.1\r\nHost: test\r\n"
            f"X-Request-Id: {rid}\r\n"
        )
        payload = json.dumps({"prompt": [1, 2, 3], "max_new_tokens": 4}).encode()
        with socket.create_connection(("127.0.0.1", port), timeout=60) as sock:
            sock.sendall(
                head.encode() + f"Content-Length: {len(payload)}\r\n\r\n".encode()
                + payload
            )
            data = b""
            while True:
                chunk = sock.recv(65536)
                if not chunk:
                    break
                data += chunk
        status, headers, body = _parse_response(data)
        assert status == 200
        assert headers["x-request-id"] == rid
        events = _sse_events(body)
        assert events[-1] == "[DONE]" and events[-2]["finish_reason"] == "length"

        # the root "request" span ends in the finish callback on the event
        # loop — give it a moment to land in the recorder
        deadline = time.monotonic() + 10.0
        spans = {}
        while time.monotonic() < deadline:
            spans = {
                s["name"]: s for s in recorder.spans() if s["trace_id"] == rid
            }
            if "request" in spans:
                break
            time.sleep(0.02)
        assert {
            "request", "queue_wait", "prefill", "insert", "decode", "sse_flush"
        } <= set(spans)
        root = spans["request"]
        assert root["parent_id"] is None
        assert root["attrs"]["finish_reason"] == "length"
        # cross-thread spans carry an explicit parent link to the root
        assert spans["queue_wait"]["parent_id"] == root["span_id"]
        assert spans["sse_flush"]["parent_id"] == root["span_id"]
        # model-thread phases ran off the HTTP thread but share the trace
        assert spans["prefill"]["thread"] != root["thread"]

        # no header -> the server mints a fresh 16-hex id and echoes it
        status2, headers2, _ = _http(
            port, "POST", "/v1/generate", {"prompt": [5], "max_new_tokens": 2}
        )
        assert status2 == 200
        rid2 = headers2["x-request-id"]
        assert rid2 != rid and len(rid2) == 16
        int(rid2, 16)  # hex


# -- dynamic Retry-After ------------------------------------------------------


def test_dynamic_retry_after_tracks_queue_and_tpot():
    """The Retry-After hint is the time for the current queue to clear at
    the observed decode rate (depth x rolling TPOT), clamped to
    [max(1, floor), 30]; a cold server falls back to the configured floor."""
    from relora_tpu.serve.admission import AdmissionController, Ticket

    def _ticket(uid):
        return Ticket(
            uid=uid,
            request=Request(uid=uid, prompt=[1], max_new_tokens=1),
            deadline=None,
            on_token=lambda *_: None,
            on_finish=lambda *_: None,
        )

    adm = AdmissionController(8, retry_after_s=2.0)
    assert adm.retry_after_s == 2.0  # cold: the old fixed behaviour
    adm.note_tpot(0.5)
    assert adm.retry_after_s == 2.0  # empty queue: floor still rules
    for uid in range(6):
        adm.try_admit(_ticket(uid))
    assert adm.retry_after_s == pytest.approx(6 * 0.5)  # depth x TPOT
    adm.note_tpot(10.0)  # EWMA folds 0.8/0.2 -> 2.4 s/token
    assert adm.retry_after_s == pytest.approx(6 * 2.4)
    adm.note_tpot(100.0)  # estimate explodes past the cap
    assert adm.retry_after_s == AdmissionController.RETRY_AFTER_CAP_S
    adm.note_tpot(-1.0)  # nonsense observations are ignored
    assert adm.retry_after_s == AdmissionController.RETRY_AFTER_CAP_S

    # sub-second floors round up to 1s: "Retry-After: 0" helps nobody
    assert AdmissionController(8, retry_after_s=0.2).retry_after_s == 1.0


# -- self-diagnosis drills (fault-injected) -----------------------------------

from relora_tpu.utils import faults  # noqa: E402


@pytest.fixture
def disarm_faults():
    faults.reset()
    yield
    faults.reset()


@pytest.mark.faults
def test_model_thread_death_fails_all_requests(engine, disarm_faults):
    """An exception on the model thread (injected ``serve_decode``) must
    terminally complete every in-flight and queued request with
    ``finish_reason="error"`` — not strand their streams — and flip
    /healthz to 503 "error" while the listener lingers."""
    faults.configure("serve_decode", exc=RuntimeError, at_token=4)
    scheduler = ContinuousBatchingScheduler(
        engine, max_batch=2, key=jax.random.PRNGKey(11)
    )
    server = GenerateServer(scheduler, port=0, max_queue=4, error_linger_s=8.0)

    def run():
        try:
            asyncio.run(server.serve_forever(install_signal_handlers=False))
        except RuntimeError:
            pass  # serve_forever re-raises the worker death; expected here

    thread = threading.Thread(target=run, daemon=True)
    thread.start()
    assert server.started.wait(60), "server failed to start"
    port = server.port
    a = _Stream(port, {"prompt": [1, 2], "max_new_tokens": 50})
    b = _Stream(port, {"prompt": [3, 4], "max_new_tokens": 50})
    assert a.status == 200 and b.status == 200
    for stream in (a, b):
        events = stream.read_to_done()  # [DONE] still arrives: typed failure
        final = events[-1]
        assert final["finish_reason"] == "error"
        assert "model thread died" in final["error"]
        assert "injected fault at 'serve_decode'" in final["error"]
    a.close()
    b.close()

    # the listener lingers so probes see *why* it is about to exit
    status, _, body = _http(port, "GET", "/healthz")
    health = json.loads(body)
    assert status == 503 and health["status"] == "error"
    assert "injected fault" in health["detail"]
    # new work fails fast instead of queueing behind a dead worker
    status, _, body = _http(
        port, "POST", "/v1/generate", {"prompt": [5], "max_new_tokens": 2}
    )
    assert status == 500 and b"model thread died" in body
    status, _, body = _http(port, "GET", "/metrics")
    text = body.decode()
    assert "relora_serve_model_dead 1" in text
    assert 'relora_serve_requests_finished_total{reason="error"} 2' in text

    thread.join(60)
    assert not thread.is_alive(), "server did not shut down after worker death"
    assert isinstance(server._worker_error, RuntimeError)


@pytest.mark.faults
def test_stall_watchdog_flips_healthz_and_recovers(
    engine, disarm_faults, tmp_path, monkeypatch
):
    """No decode progress for stall_timeout_s (injected ``serve_stall``)
    flips /healthz to 503 "stuck" and dumps the flight recorder; when the
    decode loop resumes, the replica un-sticks by itself."""
    monkeypatch.setenv("RELORA_TPU_FLIGHT_DIR", str(tmp_path))
    faults.configure("serve_stall", sleep_s=1.5, at_token=2)
    with _Server(engine, max_batch=1, stall_timeout_s=0.3) as server:
        port = server.port
        a = _Stream(port, {"prompt": [1, 2], "max_new_tokens": 30})
        assert a.status == 200

        saw = None
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline:
            status, _, body = _http(port, "GET", "/healthz")
            saw = (status, json.loads(body))
            if status == 503 and saw[1]["status"] == "stuck":
                break
            time.sleep(0.03)
        assert saw is not None and saw[1]["status"] == "stuck", saw
        assert "no decode step" in saw[1]["detail"]

        # the stall ends; the stream still finishes in full
        events = a.read_to_done()
        assert events[-1]["finish_reason"] == "length"
        assert len(events[-1]["tokens"]) == 30
        a.close()

        recovered = False
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline:
            status, _, body = _http(port, "GET", "/healthz")
            if status == 200 and json.loads(body)["status"] == "ok":
                recovered = True
                break
            time.sleep(0.03)
        assert recovered, "healthz never recovered after the stall"
        _, _, body = _http(port, "GET", "/metrics")
        assert "relora_serve_stuck 0" in body.decode()

    dumps = list(tmp_path.glob("flight_serve_stall_*.json"))
    assert dumps, "watchdog did not dump the flight recorder"
    dump = json.loads(dumps[0].read_text())
    assert dump["reason"] == "serve_stall"


@pytest.mark.faults
def test_accept_drop_closes_connection_then_recovers(engine, disarm_faults):
    """``serve_accept_drop``: the first accepted connection dies with zero
    response bytes (what a router's pre-stream retry must absorb); the next
    one is served normally."""
    faults.configure("serve_accept_drop", times=1)
    with _Server(engine, max_batch=1) as server:
        port = server.port
        with socket.create_connection(("127.0.0.1", port), timeout=10) as sock:
            sock.sendall(_request_bytes("GET", "/healthz", b""))
            # closed with zero response bytes: clean EOF or RST (the server
            # hung up with our request unread), never a served response
            try:
                assert sock.recv(4096) == b"", "dropped connection sent data"
            except ConnectionResetError:
                pass
        status, _, _ = _http(port, "GET", "/healthz")
        assert status == 200
        status, _, body = _http(port, "GET", "/metrics")
        assert "relora_serve_accept_drops_total 1" in body.decode()
