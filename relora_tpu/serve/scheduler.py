"""Slot-based continuous batching over a preallocated decode cache.

The decode step is compiled once for a fixed ``(max_batch, cache_size)`` cache
and keeps running as requests come and go — no retracing on admission or
eviction, which is the property that makes continuous batching cheap on
XLA-compiled accelerators:

- **Admit**: a new request prefills alone (bucketed lengths, so a handful of
  prefill compilations total), then ``engine.insert`` copies its single-row
  cache into a free slot of the persistent batch cache; its first sampled
  token and position join the step's token/pos arrays.
- **Step**: one jitted decode for all ``max_batch`` slots, occupied or not —
  a free slot decodes garbage at position 0, which is invisible (the
  ``j <= position`` mask) and overwritten by the next admission's insert.
- **Evict**: a row that hits EOS or its token budget is simply marked free;
  the arrays keep their shape, so nothing recompiles.

Sampling stays deterministic per request regardless of batch composition:
each row draws from a key folded from ``(request id, token index)``, never
from the slot index or the global step — the batched greedy drain is
token-identical to unbatched decode, and sampled requests reproduce across
different interleavings.

Per-request latency and throughput go to the existing metrics.jsonl sink
(utils/logging.MetricsLogger): ``serve_request`` records with time-to-first-
token, total latency, and decode tokens/sec.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, Iterable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from relora_tpu.serve.engine import InferenceEngine, bucket_length
from relora_tpu.serve.sampling import SamplingParams
from relora_tpu.utils.logging import MetricsLogger, get_logger

logger = get_logger(__name__)


@dataclasses.dataclass(frozen=True)
class Request:
    """One generation request: token-id prompt plus per-request sampling.
    ``top_k`` is batch-global (static shape) and lives on the scheduler."""

    uid: int
    prompt: Sequence[int]
    max_new_tokens: int
    temperature: float = 0.0
    top_p: float = 1.0


@dataclasses.dataclass
class Completion:
    uid: int
    tokens: List[int]
    finish_reason: str  # "eos" | "length"
    prompt_tokens: int
    ttft_s: float
    latency_s: float


@dataclasses.dataclass
class _Slot:
    request: Request
    pos: int  # absolute position of the next cache write
    tokens: List[int]
    t_admit: float
    t_first: float


class ContinuousBatchingScheduler:
    """Drains a stream of requests through ``max_batch`` decode slots."""

    def __init__(
        self,
        engine: InferenceEngine,
        *,
        max_batch: int,
        eos_id: Optional[int] = None,
        top_k: int = 0,
        metrics: Optional[MetricsLogger] = None,
        key: Optional[jax.Array] = None,
    ):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self.engine = engine
        self.max_batch = max_batch
        self.eos_id = eos_id
        self.top_k = top_k
        self.metrics = metrics
        self.key = key if key is not None else jax.random.PRNGKey(0)
        self._step_count = 0

    def _request_key(self, req: Request, token_index: int) -> jax.Array:
        # keyed by (uid, token index): a request's sample stream does not
        # depend on which slot it landed in or what shares its batch
        return jax.random.fold_in(jax.random.fold_in(self.key, req.uid), token_index)

    def run(self, requests: Iterable[Request]) -> Dict[int, Completion]:
        """Admit-and-decode until every request completes.  Returns
        completions keyed by ``Request.uid``."""
        pending: List[Request] = list(requests)
        for req in pending:
            need = len(req.prompt) + req.max_new_tokens
            if len(req.prompt) < 1:
                raise ValueError(f"request {req.uid}: empty prompt")
            if need > self.engine.cache_size:
                raise ValueError(
                    f"request {req.uid} needs {need} cache entries, "
                    f"capacity is {self.engine.cache_size}"
                )
        slots: List[Optional[_Slot]] = [None] * self.max_batch
        completions: Dict[int, Completion] = {}
        cache = self.engine.init_cache(self.max_batch)
        tokens = np.zeros(self.max_batch, np.int32)
        positions = np.zeros(self.max_batch, np.int32)
        t_start = time.monotonic()

        while pending or any(s is not None for s in slots):
            # -- admit into free slots ---------------------------------------
            for slot_idx in range(self.max_batch):
                if slots[slot_idx] is not None or not pending:
                    continue
                req = pending.pop(0)
                t_admit = time.monotonic()
                cache, first = self._admit(req, slot_idx, cache)
                slots[slot_idx] = _Slot(
                    request=req,
                    pos=len(req.prompt),
                    tokens=[first],
                    t_admit=t_admit,
                    t_first=time.monotonic(),
                )
                tokens[slot_idx] = first
                positions[slot_idx] = len(req.prompt)
                self._finish_if_done(slots, slot_idx, completions)

            if not any(s is not None for s in slots):
                continue  # everything admitted this round finished at once

            # -- one decode step over all slots ------------------------------
            logits, cache = self.engine.decode(
                cache, jnp.asarray(tokens)[:, None], jnp.asarray(positions)[:, None]
            )
            self._step_count += 1
            # one bulk pull for the whole batch, then plain Python ints —
            # per-slot int(next_tokens[i]) would be a device sync per row
            next_tokens = self._sample_rows(logits, slots).tolist()
            for slot_idx, slot in enumerate(slots):
                if slot is None:
                    continue
                tok = next_tokens[slot_idx]
                slot.tokens.append(tok)
                slot.pos += 1
                tokens[slot_idx] = tok
                positions[slot_idx] = slot.pos
                self._finish_if_done(slots, slot_idx, completions)

        logger.info(
            f"drained {len(completions)} requests in {time.monotonic() - t_start:.2f}s "
            f"({self._step_count} decode steps)"
        )
        return completions

    # -- internals -----------------------------------------------------------

    def _admit(self, req: Request, slot_idx: int, cache):
        """Prefill one request (batch of 1, bucketed length) and copy its
        cache row into ``slot_idx``.  Returns (cache, first sampled token)."""
        L = len(req.prompt)
        T = min(bucket_length(L), self.engine.cache_size)
        ids = np.zeros((1, T), np.int32)
        ids[0, :L] = np.asarray(req.prompt, np.int32)
        logits, pcache = self.engine.prefill(jnp.asarray(ids))
        cache = self.engine.insert(cache, pcache, slot_idx)
        first = self.engine._sample(
            logits[:, L - 1, :],
            self._request_key(req, 0),
            temperature=req.temperature,
            top_k=self.top_k,
            top_p=req.top_p,
        )
        return cache, int(np.asarray(first)[0])

    def _sample_rows(self, logits, slots) -> np.ndarray:
        temps = np.zeros(self.max_batch, np.float32)
        top_ps = np.ones(self.max_batch, np.float32)
        keys = []
        for slot_idx, slot in enumerate(slots):
            if slot is None:
                keys.append(self.key)  # unused row; any key works
                continue
            temps[slot_idx] = slot.request.temperature
            top_ps[slot_idx] = slot.request.top_p
            keys.append(self._request_key(slot.request, len(slot.tokens)))
        drawn = self.engine._sample(
            logits,
            jnp.stack(keys),
            temperature=jnp.asarray(temps),
            top_k=self.top_k,
            top_p=jnp.asarray(top_ps),
        )
        return np.asarray(drawn)

    def _finish_if_done(self, slots, slot_idx: int, completions) -> None:
        slot = slots[slot_idx]
        req = slot.request
        last = slot.tokens[-1]
        reason = None
        if self.eos_id is not None and last == self.eos_id:
            reason = "eos"
        elif len(slot.tokens) >= req.max_new_tokens:
            reason = "length"
        if reason is None:
            return
        now = time.monotonic()
        completion = Completion(
            uid=req.uid,
            tokens=list(slot.tokens),
            finish_reason=reason,
            prompt_tokens=len(req.prompt),
            ttft_s=slot.t_first - slot.t_admit,
            latency_s=now - slot.t_admit,
        )
        completions[req.uid] = completion
        slots[slot_idx] = None  # evict: slot is free, nothing recompiles
        if self.metrics is not None:
            decode_s = max(now - slot.t_first, 1e-9)
            self.metrics.log(
                {
                    "serve_request": req.uid,
                    "serve/prompt_tokens": completion.prompt_tokens,
                    "serve/output_tokens": len(completion.tokens),
                    "serve/finish_reason": reason,
                    "serve/ttft_s": completion.ttft_s,
                    "serve/latency_s": completion.latency_s,
                    "serve/decode_tokens_per_s": (len(completion.tokens) - 1) / decode_s
                    if len(completion.tokens) > 1
                    else 0.0,
                }
            )
