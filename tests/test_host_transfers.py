"""Host-transfer discipline of the train loop.

The RTL2xx linter rules keep syncs out of the hot loop *statically*; these
tests pin the dynamic behavior: all device metrics are materialized through
``_pull_metric_records`` in bulk at the ``log_every`` cadence, and the step
loop itself performs no per-step device->host pulls.
"""

import jax
import jax.numpy as jnp
import pytest

from relora_tpu.train import trainer as trainer_mod

from tests.test_end_to_end import TINY, FakeTokens, make_cfg, make_iterators


def test_pull_metric_records_single_bulk_transfer(monkeypatch):
    """N pending metric dicts -> exactly ONE jax.device_get, plain-Python out."""
    calls = []
    orig = jax.device_get

    def counting_get(x):
        calls.append(x)
        return orig(x)

    monkeypatch.setattr(trainer_mod.jax, "device_get", counting_get)
    dicts = [
        {
            "loss": jnp.asarray(1.5 + i),
            "grad_norm": jnp.asarray(0.5),
            "skipped": jnp.asarray(0.0),
            "n_skipped": jnp.asarray(float(i)),
        }
        for i in range(5)
    ]
    records = trainer_mod._pull_metric_records(dicts)

    assert len(calls) == 1  # one bulk pull for all five steps
    assert len(records) == 5
    for i, rec in enumerate(records):
        assert rec["loss"] == pytest.approx(1.5 + i)
        assert isinstance(rec["loss"], float)
        # count-like metrics come back as ints (log/event payloads)
        assert rec["n_skipped"] == i and isinstance(rec["n_skipped"], int)
    assert records[0]["skipped"] == 0


def test_pull_metric_records_empty():
    assert trainer_mod._pull_metric_records([]) == []


@pytest.mark.slow
def test_step_loop_pulls_only_at_log_cadence(tmp_path, monkeypatch):
    """8 updates with log_every=4 -> exactly 2 bulk pulls (one mid-run, one
    at the final flush) and no other device_get anywhere in the loop."""
    cfg = make_cfg(
        tmp_path,
        num_training_steps=8,
        log_every=4,
        save_dir=None,  # no checkpoint traffic in this run
        eval_every=100,
    )
    data = FakeTokens(n=256)
    trainer = trainer_mod.Trainer(cfg, model_cfg=TINY)
    train_factory, _ = make_iterators(cfg, trainer, data)

    pulls = []
    orig_pull = trainer_mod._pull_metric_records
    monkeypatch.setattr(
        trainer_mod,
        "_pull_metric_records",
        lambda ds: (pulls.append(len(ds)), orig_pull(ds))[1],
    )
    gets = []
    orig_get = jax.device_get
    monkeypatch.setattr(
        trainer_mod.jax, "device_get", lambda x: (gets.append(1), orig_get(x))[1]
    )

    result = trainer.fit(train_factory(), None)

    assert result["update_step"] == 8
    # steps 1-4 batch up, flushed before step 5's record; 5-8 drain at the end
    assert pulls == [4, 4]
    # and those two bulk pulls are the ONLY host transfers the loop made
    assert len(gets) == 2


@pytest.mark.slow
def test_log_every_preserves_metrics(tmp_path):
    """Batched materialization must not drop or reorder records: every
    update step appears exactly once in metrics.jsonl regardless of cadence."""
    import json
    import os

    cfg = make_cfg(tmp_path, num_training_steps=8, log_every=3, eval_every=100)
    data = FakeTokens(n=256)
    trainer = trainer_mod.Trainer(cfg, model_cfg=TINY)
    train_factory, _ = make_iterators(cfg, trainer, data)
    trainer.fit(train_factory(), None)

    steps = []
    with open(os.path.join(cfg.save_dir, "metrics.jsonl")) as fh:
        for line in fh:
            rec = json.loads(line)
            if "loss" in rec and "update_step" in rec:
                steps.append(rec["update_step"])
    assert steps == list(range(1, 9))
