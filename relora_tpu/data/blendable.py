"""Weighted mixture over datasets (parity: megatron_dataset/blendable_dataset.py).

The blend index (which dataset serves global sample i, and which of its local
samples) is built by the greedy max-error interleave in C++
(native/helpers.cpp), with a NumPy oracle for differential testing.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import numpy as np


def build_blending_indices_py(weights: np.ndarray, size: int) -> Tuple[np.ndarray, np.ndarray]:
    """NumPy oracle of the greedy interleave (helpers.cpp parity)."""
    weights = np.asarray(weights, dtype=np.float64)
    taken = np.zeros(len(weights), dtype=np.int64)
    dataset_index = np.zeros(size, dtype=np.uint8)
    dataset_sample_index = np.zeros(size, dtype=np.int64)
    for i in range(size):
        position = max(float(i), 1.0)
        errors = weights * position - taken
        best = int(np.argmax(errors))
        dataset_index[i] = best
        dataset_sample_index[i] = taken[best]
        taken[best] += 1
    return dataset_index, dataset_sample_index


class BlendableDataset:
    """Mixture dataset honoring per-corpus weights (normalized)."""

    def __init__(self, datasets: Sequence, weights: Sequence[float]):
        if len(datasets) != len(weights):
            raise ValueError("datasets and weights must align")
        self.datasets = list(datasets)
        w = np.asarray(weights, dtype=np.float64)
        if (w <= 0).any():
            raise ValueError("weights must be positive")
        self.weights = w / w.sum()
        self.size = int(sum(len(d) for d in datasets))

        from relora_tpu.data.native import build_blending_indices_native

        built = build_blending_indices_native(self.weights, self.size)
        if built is None:
            built = build_blending_indices_py(self.weights, self.size)
        self.dataset_index, self.dataset_sample_index = built

    def __len__(self) -> int:
        return self.size

    def __getitem__(self, idx: int):
        d = int(self.dataset_index[idx])
        s = int(self.dataset_sample_index[idx])
        ds = self.datasets[d]
        return ds[s % len(ds)]
