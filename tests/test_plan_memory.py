"""tools/plan_memory.py — abstract per-device HBM accounting."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_plan(*args):
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "plan_memory.py"), *args],
        capture_output=True,
        text=True,
        timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert r.returncode == 0, r.stderr[-2000:]
    return json.loads(r.stdout)


def test_headline_config_fits_v5e():
    out = run_plan("--model", "llama_1b", "--rank", "128", "--micro-batch", "8", "--seq", "1024")
    assert out["fits"] is True
    # measured reality check: this config runs on the chip with ~7GB headroom
    assert 5 < out["per_device_gb"]["total"] < 14


def test_no_remat_matches_measured_oom():
    """Without remat the dense S^2 f32 attention residuals dominate — the
    on-chip compile fails allocating 51.5GB (BASELINE.md round-2 finding 2);
    the estimate must land in the same does-not-fit regime."""
    out = run_plan(
        "--model", "llama_1b", "--rank", "128", "--micro-batch", "8",
        "--seq", "1024", "--remat", "none",
    )
    assert out["fits"] is False
    assert out["per_device_gb"]["activations"] > 16


def test_quantized_base_shrinks_frozen_params():
    full = run_plan("--model", "llama_250m", "--rank", "128")
    nf4 = run_plan("--model", "llama_250m", "--rank", "128", "--quantize", "nf4")
    int8 = run_plan("--model", "llama_250m", "--rank", "128", "--quantize", "int8")
    f, i, n = (
        x["per_device_gb"]["frozen_params"] for x in (full, int8, nf4)
    )
    assert n < i < f
    # nf4 ≈ 1/8 of f32, int8 ≈ 1/4
    assert n < f / 6 and i < f / 3


def test_sharding_divides_params():
    one = run_plan("--model", "llama_1b", "--rank", "0")
    fsdp = run_plan("--model", "llama_1b", "--rank", "0", "--mesh", "fsdp=8")
    # fsdp shards the embed dim of every kernel: frozen+trainable+adam all shrink
    assert (
        fsdp["per_device_gb"]["adam_moments"]
        < one["per_device_gb"]["adam_moments"] / 4
    )
    assert fsdp["devices"] == 8


def test_chunked_loss_removes_logits():
    dense = run_plan("--model", "llama_1b")
    chunked = run_plan("--model", "llama_1b", "--loss", "chunked")
    assert dense["per_device_gb"]["logits"] > 0.5
    assert chunked["per_device_gb"]["logits"] == 0
