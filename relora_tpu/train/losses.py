"""Loss functions.

Parity: the reference computes a shifted cross-entropy over all positions
with labels = input_ids (torchrun_main.py:786, modeling_llama.py:694-708);
pretokenized data is chunked with no padding, so no masking is needed, but we
accept an optional mask for datasets that have one.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def causal_lm_loss(
    logits: jax.Array,
    input_ids: jax.Array,
    mask: Optional[jax.Array] = None,
    labels: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Shifted next-token CE in f32.

    Returns ``(mean_loss, n_tokens)`` where n_tokens is the count the mean ran
    over (needed by distributed eval aggregation, torchrun_main.py:159-183).

    With explicit ``labels`` (same shape as input_ids; -100 = ignore, the
    reference CE's ignore_index), no shift is applied — the caller aligned
    targets itself (used by the zigzag sequence layout, where position i's
    successor is not i+1).
    """
    if labels is not None:
        lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        safe = jnp.maximum(labels, 0)
        token_ll = jnp.take_along_axis(lp, safe[..., None], axis=-1)[..., 0]
        valid = (labels >= 0).astype(jnp.float32)
        if mask is not None:
            valid = valid * mask.astype(jnp.float32)
        n = jnp.maximum(valid.sum(), 1.0)
        return -(token_ll * valid).sum() / n, n
    # upcast per-position inside log_softmax; accepts bf16 logits (the
    # bf16_logits option) without a separate f32 materialization
    shift_logits = logits[:, :-1, :].astype(jnp.float32)
    shift_labels = input_ids[:, 1:]
    logp = jax.nn.log_softmax(shift_logits, axis=-1)
    token_ll = jnp.take_along_axis(logp, shift_labels[..., None], axis=-1)[..., 0]
    if mask is not None:
        shift_mask = mask[:, 1:].astype(jnp.float32)
        n = jnp.maximum(shift_mask.sum(), 1.0)
        return -(token_ll * shift_mask).sum() / n, n
    n = jnp.asarray(token_ll.size, jnp.float32)
    return -token_ll.mean(), n
