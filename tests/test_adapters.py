"""Multi-tenant LoRA serving tests: grouped kernel, registry, token parity.

The acceptance invariant, pinned at every layer: a batch row decoding
through adapter slot ``j`` produces exactly the tokens a single-adapter
``--no-merge`` engine holding that adapter's factors produces for the same
prompt (greedy), for both model families — multi-tenancy changes batch
composition, never numerics.  Plus:

- grouped-kernel differential: all-rows-one-adapter equals the fused
  single-adapter kernel bitwise in f32; a mixed-idx batch equals a per-row
  fused loop;
- AdapterRegistry refcounted-LRU properties (jax-free);
- zero steady-state retraces while adapters load/evict/swap mid-traffic
  (CompileWatcher asserts);
- the HTTP front-end: ``"adapter"`` body field end to end, per-adapter
  metrics materialized at zero, /healthz slot stats;
- serve.py flag validation (--adapter-dir/--adapters/--adapter-slots).
"""

import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from relora_tpu.config.model import ModelConfig
from relora_tpu.core.relora import LoraSpec
from relora_tpu.models.params_util import init_params
from relora_tpu.ops.lora_dispatch import (
    ARMS,
    GROUPED_ARMS,
    choose_grouped_arm,
    estimate_grouped_arm_times,
    lora_matmul_grouped,
)
from relora_tpu.ops.pallas_lora_matmul import (
    fused_lora_matmul,
    grouped_lora_matmul,
    grouped_lora_reference,
)
from relora_tpu.ops.quant import quantize_int8
from relora_tpu.serve.adapters import (
    BASE_ADAPTER,
    RELORA_CONFIG_FILE,
    AdapterRegistry,
    extract_lora_factors,
)
from relora_tpu.serve.engine import InferenceEngine, build_decode_model
from relora_tpu.serve.scheduler import (
    ContinuousBatchingScheduler,
    PagedContinuousBatchingScheduler,
    Request,
)

# compile-heavy integration tests (engine/scheduler/HTTP parity, churn
# retrace guard) carry @pytest.mark.slow and run from smoke stage 9e, like
# the parallel-composition suite; the kernel/registry/router/collector
# logic tests stay in tier-1
pytestmark = pytest.mark.adapters

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TINY_LLAMA = ModelConfig(
    family="llama",
    vocab_size=256,
    hidden_size=64,
    intermediate_size=160,
    num_hidden_layers=2,
    num_attention_heads=4,
    max_sequence_length=64,
)
TINY_NEOX = ModelConfig(
    family="neox",
    vocab_size=256,
    hidden_size=64,
    intermediate_size=160,
    num_hidden_layers=2,
    num_attention_heads=4,
    max_sequence_length=64,
    rotary_pct=0.25,
)

FAMILIES = [
    pytest.param(TINY_LLAMA, id="llama"),
    pytest.param(TINY_NEOX, id="pythia"),
]

SPEC = LoraSpec(r=4, alpha=8)


# -- grouped-kernel differential ----------------------------------------------


def _grouped_operands(seed=0, M=6, K=32, N=128, r=4, S=3):
    ks = jax.random.split(jax.random.PRNGKey(seed), 5)
    x = jax.random.normal(ks[0], (M, K), jnp.float32)
    w = jax.random.normal(ks[1], (K, N), jnp.float32) * 0.1
    a = jax.random.normal(ks[2], (S, K, r), jnp.float32) * 0.1
    b = jax.random.normal(ks[3], (S, r, N), jnp.float32) * 0.1
    s = jnp.asarray([0.0, 2.0, 0.5], jnp.float32)
    idx = jnp.asarray([0, 1, 2, 1, 0, 2], jnp.int32)
    return x, w, a, b, s, idx


def test_grouped_all_rows_one_adapter_matches_fused_bitwise():
    """Every row on the same slot: the grouped kernel must reproduce the
    single-adapter fused kernel *bitwise* in f32 — same contraction shapes,
    same accumulation order, just a prefetch-steered factor fetch."""
    x, w, a, b, s, _ = _grouped_operands()
    for j in range(a.shape[0]):
        idx = jnp.full((x.shape[0],), j, jnp.int32)
        got = grouped_lora_matmul(x, w, a, b, s, idx, interpret=True)
        want = fused_lora_matmul(
            x, w, a[j], b[j], float(s[j]), block_m=1, block_n=128, interpret=True
        )
        assert np.array_equal(np.asarray(got), np.asarray(want)), f"slot {j}"


def test_grouped_mixed_idx_matches_per_row_fused_loop():
    """A mixed-tenant batch equals running each row alone through the fused
    kernel with its own adapter — the per-row slot routing is exact."""
    x, w, a, b, s, idx = _grouped_operands()
    got = np.asarray(grouped_lora_matmul(x, w, a, b, s, idx, interpret=True))
    for m in range(x.shape[0]):
        j = int(idx[m])
        row = fused_lora_matmul(
            x[m : m + 1], w, a[j], b[j], float(s[j]),
            block_m=1, block_n=128, interpret=True,
        )
        assert np.array_equal(got[m : m + 1], np.asarray(row)), f"row {m}"


def test_grouped_reference_matches_kernel():
    x, w, a, b, s, idx = _grouped_operands()
    got = grouped_lora_matmul(x, w, a, b, s, idx, interpret=True)
    want = grouped_lora_reference(x, w, a, b, s, idx)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_grouped_slot_zero_is_identity():
    """Rows on slot 0 (zero factors) decode the pure base matmul."""
    x, w, a, b, s, _ = _grouped_operands()
    a = a.at[0].set(0.0)
    b = b.at[0].set(0.0)
    idx = jnp.zeros((x.shape[0],), jnp.int32)
    got = grouped_lora_matmul(x, w, a, b, s, idx, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(x @ w), atol=1e-5)


def test_grouped_validation_errors():
    x, w, a, b, s, idx = _grouped_operands()
    with pytest.raises(ValueError, match="contraction mismatch"):
        grouped_lora_matmul(x[:, :16], w, a, b, s, idx, interpret=True)
    with pytest.raises(ValueError, match="B stack"):
        grouped_lora_matmul(x, w, a, b[:, :, :64], s, idx, interpret=True)
    with pytest.raises(ValueError, match="adapter_idx"):
        grouped_lora_matmul(x, w, a, b, s, idx[:3], interpret=True)
    with pytest.raises(ValueError, match="unknown grouped arm"):
        lora_matmul_grouped(x, w, a, b, s, idx, arm="fused")


def test_grouped_arm_vocabulary_disjoint_from_single_adapter_arms():
    """The grouped dispatcher has its own arm vocabulary; the single-adapter
    ``ARMS`` tuple (pinned by test_lora_kernels) is untouched."""
    assert set(GROUPED_ARMS) == {"grouped", "gathered", "looped"}
    assert not set(GROUPED_ARMS) & set(ARMS)
    times = estimate_grouped_arm_times(256, 64, 128, 4, num_adapters=2)
    assert set(times) == set(GROUPED_ARMS)
    assert all(t > 0 for t in times.values())


def test_grouped_cost_model_scales_with_distinct_adapters():
    """The grouped arm's modeled bytes scale with the *distinct* adapters a
    batch touches (G), not the batch size — the property the kernel exists
    for — so its estimate grows with G and beats the M-scaling gather for
    large batches over few tenants."""
    M, K, N, r = 4096, 1024, 1024, 16
    few = estimate_grouped_arm_times(M, K, N, r, num_adapters=2)
    many = estimate_grouped_arm_times(M, K, N, r, num_adapters=64)
    assert few["grouped"] <= many["grouped"]
    assert few["grouped"] < few["gathered"]
    # the G-launch loop loses once it re-reads W per adapter
    assert many["looped"] > many["grouped"]
    # off-TPU / int8 / untileable N: both kernel arms struck
    assert choose_grouped_arm(M, K, N, r, 2, grouped_available=False) == "gathered"
    assert choose_grouped_arm(M, K, 130, r, 2) == "gathered"


@pytest.mark.parametrize("arm", ["gathered", "grouped", "looped"])
def test_lora_matmul_grouped_numerics_arm_independent(arm):
    x, w, a, b, s, idx = _grouped_operands()
    want = grouped_lora_reference(x, w, a, b, s, idx)
    got = lora_matmul_grouped(x, w, a, b, s, idx, arm=arm)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_lora_matmul_grouped_int8_base_takes_reference():
    x, w, a, b, s, idx = _grouped_operands()
    q, qscale = quantize_int8(w)
    got = lora_matmul_grouped(x, (q, qscale), a, b, s, idx, arm="auto")
    want = grouped_lora_reference(x, w, a, b, s, idx)
    # int8 dequant noise dominates; the shape/path must still be right
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=0.15)


# -- AdapterRegistry: refcounted LRU properties (jax-free) --------------------


def _fake_adapter_dir(tmp_path, names):
    root = tmp_path / "adapters"
    for name in names:
        d = root / name
        d.mkdir(parents=True)
        (d / RELORA_CONFIG_FILE).write_text(json.dumps({"r": 4, "alpha": 8}))
    return str(root)


def _registry(tmp_path, names=("tA", "tB", "tC"), num_slots=3, writer=None):
    writes = []

    def record(slot, factors, scale):
        writes.append((slot, factors, scale))
        if writer is not None:
            writer(slot, factors, scale)

    reg = AdapterRegistry(
        _fake_adapter_dir(tmp_path, names),
        num_slots,
        writer=record,
        loader=lambda path, r: ({"dense": {"lora_a": os.path.basename(path)}}, 2.0),
    )
    return reg, writes


def test_registry_identity_slot_and_validation(tmp_path):
    reg, writes = _registry(tmp_path)
    assert reg.acquire(None) == 0
    assert reg.acquire(BASE_ADAPTER) == 0
    reg.release(None)  # no-op, never raises
    reg.release(BASE_ADAPTER)
    assert not writes  # slot 0 is never written
    assert reg.known(BASE_ADAPTER) and reg.known("tA") and not reg.known("nope")
    assert reg.list_adapters() == ["tA", "tB", "tC"]
    with pytest.raises(ValueError, match="num_slots must be >= 2"):
        AdapterRegistry(None, 1)
    with pytest.raises(ValueError, match="reserved"):
        reg.preload(BASE_ADAPTER, {}, 1.0)


def test_registry_load_hit_refcount_and_release(tmp_path):
    reg, writes = _registry(tmp_path)
    s1 = reg.acquire("tA")
    assert s1 == 1 and reg.misses_total == 1 and reg.loads_total == 1
    assert writes[-1][0] == 1 and writes[-1][2] == 2.0
    assert reg.acquire("tA") == s1  # hit: same slot, no new load
    assert reg.hits_total == 1 and reg.loads_total == 1
    assert reg.stats()["resident"]["tA"]["refs"] == 2
    reg.release("tA")
    reg.release("tA")
    assert reg.stats()["resident"]["tA"]["refs"] == 0
    with pytest.raises(ValueError, match="no active requests"):
        reg.release("tA")
    assert reg.slot_of("tA") == s1  # stays warm after release


def test_registry_lru_eviction_skips_pinned(tmp_path):
    reg, _ = _registry(tmp_path, num_slots=3)  # 2 loadable slots
    reg.acquire("tA")
    reg.acquire("tB")
    # both pinned: a third tenant cannot be admitted -> stay queued
    assert reg.acquire("tC") is None and reg.evictions_total == 0
    reg.release("tA")  # tA unpinned AND least-recently-used -> the victim
    assert reg.acquire("tC") == 1 and reg.evictions_total == 1
    assert reg.slot_of("tA") is None and reg.slot_of("tB") == 2
    # a hit refreshes recency: tB becomes MRU, tC is now the LRU victim
    reg.release("tB")
    reg.release("tC")
    reg.acquire("tB")
    reg.release("tB")
    assert reg.acquire("tA") == 1  # tC's old slot: tC was the LRU victim
    assert reg.evictions_total == 2
    assert reg.slot_of("tC") is None and reg.slot_of("tB") == 2


def test_registry_failed_load_keeps_slot_clean(tmp_path):
    calls = {"n": 0}

    def flaky(path, r):
        calls["n"] += 1
        if calls["n"] == 1:
            raise ValueError("corrupt checkpoint")
        return {"dense": {"lora_a": "ok"}}, 1.0

    reg = AdapterRegistry(
        _fake_adapter_dir(tmp_path, ["tA"]), 2, loader=flaky
    )
    with pytest.raises(ValueError, match="corrupt"):
        reg.acquire("tA")
    assert reg.slot_of("tA") is None and reg.stats()["slots_free"] == 1
    assert reg.acquire("tA") == 1  # the slot was returned to the free list
    with pytest.raises(ValueError, match="unknown adapter"):
        reg.acquire("missing")


def test_registry_preload_and_stats(tmp_path):
    reg, writes = _registry(tmp_path, num_slots=4)
    assert reg.preload("warm", {"dense": {"lora_a": 1}}, 0.5) == 1
    assert reg.preload("warm", {}, 0.5) == 1  # idempotent
    assert writes[-1][0] == 1 and writes[-1][2] == 0.5
    assert reg.known("warm")  # resident without a checkpoint dir
    stats = reg.stats()
    assert stats["num_slots"] == 4 and stats["slots_used"] == 2
    assert stats["resident"]["warm"] == {"slot": 1, "refs": 0}
    reg.acquire("tA")
    reg.acquire("tA")
    reg.release("tA")
    assert reg.stats()["hit_rate"] == 0.5


# -- engine: slot writes, zero retraces, per-family token parity --------------


def _perturbed(params, leaf, seed):
    return jax.tree_util.tree_map_with_path(
        lambda path, t: (
            jax.random.normal(
                jax.random.fold_in(
                    jax.random.PRNGKey(seed),
                    abs(hash(jax.tree_util.keystr(path))) % (2**31),
                ),
                t.shape,
                t.dtype,
            )
            * 0.1
            if any(getattr(k, "key", None) in leaf for k in path)
            else t
        ),
        params,
    )


def _lora_raw(cfg, seed=0):
    model = build_decode_model(cfg, cache_size=32, lora=SPEC)
    return init_params(model, jax.random.PRNGKey(seed), jnp.zeros((1, 8), jnp.int32))


@pytest.mark.slow
@pytest.mark.parametrize("cfg", FAMILIES)
def test_multi_tenant_rows_match_single_adapter_engines(cfg):
    """THE acceptance invariant (greedy, both families): each row of a
    mixed-tenant batch reproduces a single-adapter --no-merge engine holding
    that row's factors; slot-0 rows reproduce the base model."""
    raw = _lora_raw(cfg)
    raw_a = _perturbed(raw, ("lora_a", "lora_b"), seed=11)
    raw_b = _perturbed(raw, ("lora_a", "lora_b"), seed=22)
    multi = InferenceEngine(cfg, raw, cache_size=32, lora=SPEC, adapter_slots=3)
    multi.write_adapter_slot(1, extract_lora_factors(raw_a), SPEC.scale)
    multi.write_adapter_slot(2, extract_lora_factors(raw_b), SPEC.scale)

    prompts = [[1, 2, 3], [1, 2, 3], [1, 2, 3], [9, 8]]
    tokens = multi.generate(prompts, max_new_tokens=5, adapter_idx=[0, 1, 2, 1])

    solo_base = InferenceEngine(cfg, raw, cache_size=32, lora=SPEC)
    solo_a = InferenceEngine(cfg, raw_a, cache_size=32, lora=SPEC)
    solo_b = InferenceEngine(cfg, raw_b, cache_size=32, lora=SPEC)
    assert tokens[0] == solo_base.generate([prompts[0]], max_new_tokens=5)[0]
    assert tokens[1] == solo_a.generate([prompts[1]], max_new_tokens=5)[0]
    assert tokens[2] == solo_b.generate([prompts[2]], max_new_tokens=5)[0]
    assert tokens[3] == solo_a.generate([prompts[3]], max_new_tokens=5)[0]
    # the adapters actually steer: tenant rows diverge from base
    assert tokens[1] != tokens[0]


@pytest.mark.slow
def test_adapter_churn_causes_zero_steady_state_retraces():
    """Load/evict/swap mid-traffic is pure data movement: after warmup, any
    number of slot writes and mixed-idx steps adds zero compiles."""
    raw = _lora_raw(TINY_LLAMA)
    engine = InferenceEngine(
        TINY_LLAMA, raw, cache_size=32, lora=SPEC, adapter_slots=3
    )
    report = engine.warmup(2)
    assert "adapter_write" in {c["fn"] for c in report["compiles"]}
    prompts = [[1, 2, 3], [4, 5]]
    engine.generate(prompts, max_new_tokens=4, adapter_idx=[0, 1])
    cw = engine.compile_watcher
    baseline = cw.steady_state_retraces
    # churn: load two tenants, swap one slot's contents twice, decode mixed
    for seed in (1, 2, 3, 4):
        factors = extract_lora_factors(_perturbed(raw, ("lora_a", "lora_b"), seed))
        engine.write_adapter_slot(1 + seed % 2, factors, SPEC.scale)
        engine.generate(prompts, max_new_tokens=4, adapter_idx=[seed % 3, 1])
    assert cw.steady_state_retraces == baseline, [
        (e.fn, e.reason) for e in cw.compile_events() if not e.expected
    ]


@pytest.mark.slow
def test_engine_adapter_validation():
    raw = _lora_raw(TINY_LLAMA)
    with pytest.raises(ValueError, match="adapter_slots"):
        InferenceEngine(TINY_LLAMA, raw, cache_size=32, adapter_slots=3)
    with pytest.raises(ValueError, match="adapter_slots"):
        InferenceEngine(TINY_LLAMA, raw, cache_size=32, lora=SPEC, adapter_slots=1)
    engine = InferenceEngine(
        TINY_LLAMA, raw, cache_size=32, lora=SPEC, adapter_slots=2
    )
    factors = extract_lora_factors(_perturbed(raw, ("lora_a", "lora_b"), 1))
    with pytest.raises(ValueError, match="slot"):
        engine.write_adapter_slot(0, factors, 1.0)  # identity slot is immutable
    with pytest.raises(ValueError, match="slot"):
        engine.write_adapter_slot(2, factors, 1.0)  # out of range
    bad = jax.tree_util.tree_map(lambda t: t[..., :2], factors)
    with pytest.raises(ValueError, match="shape"):
        engine.write_adapter_slot(1, bad, 1.0)


# -- scheduler: multi-tenant drain parity, admission, eviction ----------------


def _tenant_registry(engine, raw, names=("tA", "tB"), num_slots=3):
    reg = AdapterRegistry(None, num_slots, writer=engine.adapter_writer())
    for i, name in enumerate(names):
        factors = extract_lora_factors(
            _perturbed(raw, ("lora_a", "lora_b"), seed=11 * (i + 1))
        )
        reg.preload(name, factors, SPEC.scale)
    return reg


def _drain(scheduler, adapters, prompt=(5, 9, 3), n=5):
    reqs = [
        Request(uid=i, prompt=list(prompt), max_new_tokens=n, adapter=a)
        for i, a in enumerate(adapters)
    ]
    done = scheduler.run(reqs)
    return {uid: c.tokens for uid, c in done.items()}


@pytest.mark.slow
def test_scheduler_multi_tenant_parity_and_validation():
    raw = _lora_raw(TINY_LLAMA)
    engine = InferenceEngine(
        TINY_LLAMA, raw, cache_size=32, lora=SPEC, adapter_slots=3
    )
    reg = _tenant_registry(engine, raw)
    sched = ContinuousBatchingScheduler(
        engine, max_batch=3, adapter_registry=reg
    )
    mixed = _drain(sched, [None, "tA", "tB"])
    # each tenant alone reproduces its tokens from the mixed batch
    for uid, name in ((0, None), (1, "tA"), (2, "tB")):
        solo = ContinuousBatchingScheduler(
            engine, max_batch=1, adapter_registry=reg
        )
        assert _drain(solo, [name])[0] == mixed[uid], (uid, name)
    assert mixed[1] != mixed[0] and mixed[2] != mixed[1]
    # refcounts drained back to zero; adapters stay warm
    stats = sched.adapter_stats()
    assert all(v["refs"] == 0 for v in stats["resident"].values())
    with pytest.raises(ValueError, match="unknown adapter"):
        sched.validate_request(
            Request(uid=9, prompt=[1], max_new_tokens=1, adapter="nope")
        )
    bare = ContinuousBatchingScheduler(engine, max_batch=1)
    with pytest.raises(ValueError, match="adapter"):
        bare.validate_request(
            Request(uid=9, prompt=[1], max_new_tokens=1, adapter="tA")
        )
    with pytest.raises(ValueError, match="engine built with adapter_slots"):
        ContinuousBatchingScheduler(
            InferenceEngine(TINY_LLAMA, raw, cache_size=32, lora=SPEC),
            max_batch=1,
            adapter_registry=reg,
        )


@pytest.mark.slow
def test_paged_scheduler_matches_contiguous_multi_tenant():
    raw = _lora_raw(TINY_LLAMA)
    contiguous = InferenceEngine(
        TINY_LLAMA, raw, cache_size=32, lora=SPEC, adapter_slots=3
    )
    reg_c = _tenant_registry(contiguous, raw)
    got_c = _drain(
        ContinuousBatchingScheduler(contiguous, max_batch=3, adapter_registry=reg_c),
        [None, "tA", "tB"],
    )
    paged = InferenceEngine(
        TINY_LLAMA, raw, cache_size=32, lora=SPEC, adapter_slots=3,
        page_size=8, num_pages=17, chunk_size=8,
    )
    reg_p = _tenant_registry(paged, raw)
    got_p = _drain(
        PagedContinuousBatchingScheduler(paged, max_batch=3, adapter_registry=reg_p),
        [None, "tA", "tB"],
    )
    assert got_p == got_c


@pytest.mark.slow
def test_scheduler_slot_contention_evicts_then_retries():
    """num_slots=2 (one loadable slot), two tenants: the second queues until
    the first's pin drops, then evicts and completes — exactly one eviction,
    zero failures."""
    raw = _lora_raw(TINY_LLAMA)
    engine = InferenceEngine(
        TINY_LLAMA, raw, cache_size=32, lora=SPEC, adapter_slots=2
    )
    reg = AdapterRegistry(
        None, 2, writer=engine.adapter_writer(),
    )
    factors = {
        name: extract_lora_factors(_perturbed(raw, ("lora_a", "lora_b"), seed))
        for name, seed in (("tA", 11), ("tB", 22))
    }
    # loader-backed residency without disk: known() needs residency or a dir,
    # so preload tA and let tB load through a stub loader on admission
    reg.preload("tA", factors["tA"], SPEC.scale)
    reg._loader = lambda path, r: (factors["tB"], SPEC.scale)
    reg.adapter_path = lambda name: name if name in factors else None
    sched = ContinuousBatchingScheduler(engine, max_batch=2, adapter_registry=reg)
    done = _drain(sched, ["tA", "tB"])
    assert sorted(done) == [0, 1]
    assert len(done[0]) == 5 and len(done[1]) == 5
    assert reg.evictions_total == 1  # tA evicted once its request retired
    assert reg.slot_of("tB") == 1 and reg.slot_of("tA") is None
    # parity survives the eviction dance
    solo = ContinuousBatchingScheduler(engine, max_batch=1, adapter_registry=reg)
    assert _drain(solo, ["tB"])[0] == done[1]


# -- server: the "adapter" body field end to end ------------------------------


def test_parse_generate_body_adapter_field():
    from relora_tpu.serve.server import BadRequest, parse_generate_body

    kw = dict(default_max_new_tokens=4, default_temperature=0.0, default_top_p=1.0)
    assert parse_generate_body(json.dumps({"prompt": [1]}).encode(), **kw)[
        "adapter"
    ] is None
    assert (
        parse_generate_body(
            json.dumps({"prompt": [1], "adapter": " tA "}).encode(), **kw
        )["adapter"]
        == "tA"
    )
    for bad in ("", "   ", 5, False, ["tA"]):
        with pytest.raises(BadRequest, match="adapter"):
            parse_generate_body(
                json.dumps({"prompt": [1], "adapter": bad}).encode(), **kw
            )


@pytest.mark.slow
def test_http_two_adapter_server_matches_single_adapter_runs(tmp_path):
    """End to end over HTTP: a 2-adapter server returns, per tenant, exactly
    the tokens of a single-adapter --no-merge run; /metrics materializes the
    per-adapter series at zero and /healthz carries slot stats."""
    import socket
    import threading

    from relora_tpu.serve.server import GenerateServer

    raw = _lora_raw(TINY_LLAMA)
    engine = InferenceEngine(
        TINY_LLAMA, raw, cache_size=32, lora=SPEC, adapter_slots=3
    )
    raws = {
        "tA": _perturbed(raw, ("lora_a", "lora_b"), 11),
        "tB": _perturbed(raw, ("lora_a", "lora_b"), 22),
    }
    reg = AdapterRegistry(
        _fake_adapter_dir(tmp_path, list(raws)), 3, writer=engine.adapter_writer()
    )
    for name, tree in raws.items():
        reg.preload(name, extract_lora_factors(tree), SPEC.scale)
    scheduler = ContinuousBatchingScheduler(
        engine, max_batch=2, adapter_registry=reg
    )
    server = GenerateServer(scheduler, port=0, max_queue=4)

    import asyncio

    thread = threading.Thread(
        target=lambda: asyncio.run(
            server.serve_forever(install_signal_handlers=False)
        ),
        daemon=True,
    )
    thread.start()
    assert server.started.wait(60)

    def post(path, payload):
        body = json.dumps(payload).encode()
        req = (
            f"POST {path} HTTP/1.1\r\nHost: t\r\nContent-Length: {len(body)}\r\n\r\n"
        ).encode() + body
        with socket.create_connection(("127.0.0.1", server.port), timeout=60) as s:
            s.sendall(req)
            data = b""
            while chunk := s.recv(65536):
                data += chunk
        head, _, rest = data.partition(b"\r\n\r\n")
        return int(head.split(b" ", 2)[1]), rest

    def get(path):
        req = f"GET {path} HTTP/1.1\r\nHost: t\r\nContent-Length: 0\r\n\r\n".encode()
        with socket.create_connection(("127.0.0.1", server.port), timeout=60) as s:
            s.sendall(req)
            data = b""
            while chunk := s.recv(65536):
                data += chunk
        head, _, rest = data.partition(b"\r\n\r\n")
        return int(head.split(b" ", 2)[1]), rest

    try:
        # materialized-at-zero before any traffic
        status, metrics = get("/metrics")
        assert status == 200
        text = metrics.decode()
        assert 'relora_serve_adapter_requests_total{adapter="base"} 0' in text
        assert 'relora_serve_adapter_requests_total{adapter="tA"} 0' in text
        assert 'relora_serve_adapter_requests_total{adapter="tB"} 0' in text
        assert "relora_serve_adapter_evictions_total 0" in text
        assert "relora_serve_adapter_load_seconds_count 0" in text

        http_tokens = {}
        for name in (None, "tA", "tB"):
            payload = {"prompt": [5, 9, 3], "max_new_tokens": 5, "stream": False}
            if name:
                payload["adapter"] = name
            status, body = post("/v1/generate", payload)
            assert status == 200, body
            http_tokens[name] = json.loads(body)["tokens"]

        status, body = post(
            "/v1/generate",
            {"prompt": [1], "max_new_tokens": 1, "adapter": "nope", "stream": False},
        )
        assert status == 400 and b"unknown adapter" in body

        status, body = get("/healthz")
        health = json.loads(body)
        assert health["adapters"]["num_slots"] == 3
        assert set(health["adapters"]["resident"]) == {"tA", "tB"}

        status, metrics = get("/metrics")
        text = metrics.decode()
        assert 'relora_serve_adapter_requests_total{adapter="base"} 1' in text
        assert 'relora_serve_adapter_requests_total{adapter="tA"} 1' in text
        assert "relora_serve_adapter_slots_used 3" in text
    finally:
        server.begin_drain()
        thread.join(60)
    assert not thread.is_alive() and server._worker_error is None

    # the parity half: single-adapter --no-merge engines, same greedy prompt
    for name, tree in (("tA", raws["tA"]), ("tB", raws["tB"]), (None, raw)):
        solo = InferenceEngine(TINY_LLAMA, tree, cache_size=32, lora=SPEC)
        assert http_tokens[name] == solo.generate([[5, 9, 3]], max_new_tokens=5)[0]
    assert http_tokens["tA"] != http_tokens[None]


# -- router tenant affinity ---------------------------------------------------


def test_router_affinity_is_sticky_and_falls_back():
    from relora_tpu.serve.router import Router

    router = Router([("h", 1), ("h", 2), ("h", 3)])
    router._refresh_endpoints()
    for st in router.replicas.values():
        st.healthy = True

    picks = {router._pick(set(), adapter="tenant-7").rid for _ in range(8)}
    assert len(picks) == 1  # sticky: same replica every time
    home = picks.pop()
    tenants = [f"tenant-{i}" for i in range(12)]
    homes = {t: router._pick(set(), adapter=t).rid for t in tenants}
    assert len(set(homes.values())) > 1  # tenants spread over the fleet

    # losing one replica re-homes only its own tenants (the rendezvous
    # property; a mod-hash would reshuffle everyone)
    router.replicas[home].healthy = False
    for t in tenants:
        if homes[t] != home:
            assert router._pick(set(), adapter=t).rid == homes[t]
    router.replicas[home].healthy = True

    # home already tried (excluded) -> least-loaded fallback, not a dead end
    other = router._pick({home}, adapter="tenant-7")
    assert other is not None and other.rid != home
    # breaker open on the home -> fallback too
    router.replicas[home].breaker._open()
    st = router._pick(set(), adapter="tenant-7")
    assert st is not None and st.rid != home
    # no adapter: plain least-loaded routing is unchanged
    assert router._pick(set()) is not None


# -- serve.py flag validation -------------------------------------------------


def test_cli_adapter_flag_validation(tmp_path):
    sys.path.insert(0, ROOT)
    import serve

    common = [
        "--model_config", "llama_9m",
        "--checkpoint", "nowhere",
        "--prompt", "1 2 3",
    ]
    with pytest.raises(SystemExit, match="requires --no-merge"):
        serve.main(common + ["--adapter-dir", str(tmp_path)])
    with pytest.raises(SystemExit, match="requires --adapter-dir"):
        serve.main(common + ["--no-merge", "--adapters", "tA"])
    with pytest.raises(SystemExit, match="requires --adapter-dir"):
        serve.main(common + ["--no-merge", "--adapter-slots", "4"])
    with pytest.raises(SystemExit, match="must be >= 2"):
        serve.main(
            common
            + ["--no-merge", "--adapter-dir", str(tmp_path), "--adapter-slots", "1"]
        )
    with pytest.raises(SystemExit, match="not a directory"):
        serve.main(
            common + ["--no-merge", "--adapter-dir", str(tmp_path / "missing")]
        )


# -- fleet observability ------------------------------------------------------


def test_fleet_collector_derives_adapter_churn():
    from relora_tpu.obs.fleet import FleetCollector

    coll = FleetCollector(lambda: {})
    text = (
        "relora_serve_adapter_evictions_total 4\n"
        "relora_serve_adapter_slots_used 2\n"
    )
    first = {}
    coll._ingest_metrics("r0", text, first, now=100.0)
    # first scrape: the lifetime total is not churn (a report rebuilt from
    # disk must not see the whole run's evictions as one round)
    assert first["adapter_churn"] == 0.0
    assert not coll.store.events(kinds=("adapter_thrash",))

    second = {}
    coll._ingest_metrics(
        "r0",
        "relora_serve_adapter_evictions_total 7\n"
        "relora_serve_adapter_slots_used 2\n",
        second,
        now=101.0,
    )
    assert second["adapter_churn"] == 3.0  # delta, not total
    events = coll.store.events(kinds=("adapter_thrash",))
    assert len(events) == 1  # 3 evictions >= the 2-slot pool: one turnover
    assert events[0]["evictions"] == 3.0 and events[0]["slots_used"] == 2.0

    third = {}
    coll._ingest_metrics(
        "r0",
        "relora_serve_adapter_evictions_total 8\n"
        "relora_serve_adapter_slots_used 2\n",
        third,
        now=102.0,
    )
    assert third["adapter_churn"] == 1.0
    assert len(coll.store.events(kinds=("adapter_thrash",))) == 1  # no new event
