"""Serve-path tests: cached-decode parity, engine steps, continuous batching.

The acceptance oracle for the inference subsystem: prefill + decode-with-cache
must reproduce the teacher-forced full forward *exactly* (f32, atol 1e-5) at
every position for both model families, and the continuous-batching scheduler
must drain a mixed-length, staggered, early-EOS batch to the same tokens as
unbatched greedy decode.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from relora_tpu.config.model import ModelConfig
from relora_tpu.models.params_util import init_params
from relora_tpu.serve.engine import InferenceEngine, bucket_length, build_decode_model
from relora_tpu.serve.sampling import SamplingParams
from relora_tpu.serve.scheduler import ContinuousBatchingScheduler, Request

pytestmark = pytest.mark.serve

TINY_LLAMA = ModelConfig(
    family="llama",
    vocab_size=256,
    hidden_size=64,
    intermediate_size=160,
    num_hidden_layers=2,
    num_attention_heads=4,
    max_sequence_length=64,
)
TINY_NEOX = ModelConfig(
    family="neox",
    vocab_size=256,
    hidden_size=64,
    intermediate_size=160,
    num_hidden_layers=2,
    num_attention_heads=4,
    max_sequence_length=64,
    rotary_pct=0.25,
)
TINY_GQA = ModelConfig(
    family="llama",
    vocab_size=256,
    hidden_size=64,
    intermediate_size=160,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
    max_sequence_length=64,
)

FAMILIES = [
    pytest.param(TINY_LLAMA, id="llama"),
    pytest.param(TINY_NEOX, id="neox"),
    pytest.param(TINY_GQA, id="llama-gqa"),
]


def make_engine(cfg, *, cache_size=32, scan_layers=True, seed=0):
    model = build_decode_model(cfg, cache_size=cache_size, scan_layers=scan_layers)
    base = type(model)(cfg, lora=None, dtype=jnp.float32, scan_layers=scan_layers)
    ids = jnp.zeros((1, 8), jnp.int32)
    params = init_params(base, jax.random.PRNGKey(seed), ids)
    engine = InferenceEngine(
        cfg, params, cache_size=cache_size, scan_layers=scan_layers
    )
    return engine, base, params


@pytest.mark.parametrize("cfg", FAMILIES)
@pytest.mark.parametrize("scan_layers", [True, False], ids=["scan", "unroll"])
def test_prefill_decode_matches_full_forward(cfg, scan_layers):
    """Acceptance parity: prefill(0..p) then one-token decode for each later
    position reproduces the teacher-forced logits at EVERY position."""
    engine, base, params = make_engine(cfg, scan_layers=scan_layers)
    S, prefill_len = 12, 5
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, S), 0, cfg.vocab_size)
    full = base.apply({"params": params}, ids)

    logits, cache = engine.prefill(ids[:, :prefill_len])
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(full[:, :prefill_len]), atol=1e-5
    )
    pos = np.full((2, 1), prefill_len, np.int32)
    for t in range(prefill_len, S):
        step, cache = engine.decode(cache, ids[:, t : t + 1], jnp.asarray(pos))
        np.testing.assert_allclose(np.asarray(step), np.asarray(full[:, t]), atol=1e-5)
        pos += 1


@pytest.mark.parametrize("cfg", [FAMILIES[0], FAMILIES[1]])
def test_right_padded_prefill_parity(cfg):
    """Rows shorter than the prefill bucket must produce the same logits (at
    their real positions) and the same decode continuation as unpadded rows —
    pad garbage beyond a row's length is overwritten before it is visible."""
    engine, base, params = make_engine(cfg)
    L = 6
    ids = jax.random.randint(jax.random.PRNGKey(2), (1, L), 0, cfg.vocab_size)
    full = base.apply({"params": params}, ids)

    padded = np.zeros((1, 16), np.int32)
    padded[0, :L] = np.asarray(ids[0])
    logits, cache = engine.prefill(jnp.asarray(padded))
    np.testing.assert_allclose(np.asarray(logits[:, :L]), np.asarray(full), atol=1e-5)

    # greedy continuation from the padded cache == teacher-forced next logits
    nxt = jnp.argmax(logits[:, L - 1], axis=-1)
    step, _ = engine.decode(cache, nxt[:, None], jnp.full((1, 1), L, jnp.int32))
    ref_ids = jnp.concatenate([ids, nxt[:, None]], axis=1)
    ref = base.apply({"params": params}, ref_ids)
    np.testing.assert_allclose(np.asarray(step[0]), np.asarray(ref[0, L]), atol=1e-5)


def unbatched_greedy(engine, prompt, max_new_tokens, eos_id=None):
    """Reference decode: one request alone through the engine."""
    [tokens] = engine.generate(
        [list(prompt)], max_new_tokens=max_new_tokens, eos_id=eos_id
    )
    return tokens


@pytest.mark.parametrize("cfg", [FAMILIES[0], FAMILIES[1]])
def test_scheduler_matches_unbatched_greedy(cfg):
    """Acceptance: staggered admissions + mixed lengths + early EOS drain to
    exactly the unbatched greedy tokens."""
    engine, _, _ = make_engine(cfg, cache_size=48)
    rng = np.random.RandomState(0)
    prompts = [list(rng.randint(0, cfg.vocab_size, size=n)) for n in (3, 7, 5, 11, 2)]
    max_new = 8

    # pick an EOS that actually fires early for at least one request: a token
    # some unbatched greedy stream emits mid-generation
    refs_no_eos = [unbatched_greedy(engine, p, max_new) for p in prompts]
    eos_id = refs_no_eos[1][2]
    refs = [unbatched_greedy(engine, p, max_new, eos_id=eos_id) for p in prompts]
    assert any(len(r) < max_new for r in refs), "EOS must fire early for the test to bite"
    assert len({len(r) for r in refs}) > 1, "mixed completion lengths expected"

    # max_batch=2 over 5 requests forces staggered admissions and slot reuse
    sched = ContinuousBatchingScheduler(engine, max_batch=2, eos_id=eos_id)
    completions = sched.run(
        [Request(uid=i, prompt=p, max_new_tokens=max_new) for i, p in enumerate(prompts)]
    )
    assert sorted(completions) == list(range(len(prompts)))
    for i, ref in enumerate(refs):
        assert completions[i].tokens == ref, f"request {i} diverged from unbatched greedy"
        expected = "eos" if ref[-1] == eos_id else "length"
        assert completions[i].finish_reason == expected


def test_scheduler_sampled_stream_independent_of_batching():
    """A sampled request's tokens depend on (key, uid, step) only — not on
    which other requests shared its decode batches."""
    engine, _, _ = make_engine(TINY_LLAMA, cache_size=48)
    key = jax.random.PRNGKey(7)
    reqs = [
        Request(uid=i, prompt=[1 + i, 2, 3], max_new_tokens=6, temperature=0.9)
        for i in range(3)
    ]
    solo = {}
    for r in reqs:
        sched = ContinuousBatchingScheduler(engine, max_batch=1, key=key)
        solo[r.uid] = sched.run([r])[r.uid].tokens
    batched = ContinuousBatchingScheduler(engine, max_batch=3, key=key).run(reqs)
    for r in reqs:
        assert batched[r.uid].tokens == solo[r.uid]


def test_scheduler_incremental_api_matches_run():
    """submit + step-until-idle produces exactly what run() produces, and the
    token callbacks replay each request's stream in order — the contract the
    HTTP front-end is built on."""
    engine, _, _ = make_engine(TINY_LLAMA, cache_size=48)
    key = jax.random.PRNGKey(3)
    reqs = [
        Request(uid=i, prompt=[1 + i, 2, 3], max_new_tokens=5, temperature=0.5)
        for i in range(3)
    ]
    ref = ContinuousBatchingScheduler(engine, max_batch=2, key=key).run(reqs)

    sched = ContinuousBatchingScheduler(engine, max_batch=2, key=key)
    streamed = {}
    completions = {}
    for r in reqs:
        sched.submit(
            r,
            on_token=lambda uid, tok, idx: streamed.setdefault(uid, []).append((idx, tok)),
            on_finish=lambda c: completions.__setitem__(c.uid, c),
        )
    while sched.has_work():
        sched.step()
    assert sorted(completions) == sorted(ref)
    for uid in ref:
        assert completions[uid].tokens == ref[uid].tokens
        assert [i for i, _ in streamed[uid]] == list(range(len(ref[uid].tokens)))
        assert [t for _, t in streamed[uid]] == ref[uid].tokens


def test_scheduler_validate_request_and_duplicate_uid():
    engine, _, _ = make_engine(TINY_LLAMA, cache_size=16)
    sched = ContinuousBatchingScheduler(engine, max_batch=1)
    with pytest.raises(ValueError, match="empty prompt"):
        sched.validate_request(Request(uid=0, prompt=[], max_new_tokens=4))
    with pytest.raises(ValueError, match="cache entries"):
        sched.validate_request(Request(uid=0, prompt=[1] * 10, max_new_tokens=10))
    with pytest.raises(ValueError, match="max_new_tokens"):
        sched.validate_request(Request(uid=0, prompt=[1], max_new_tokens=0))
    sched.submit(Request(uid=5, prompt=[1, 2], max_new_tokens=4))
    with pytest.raises(ValueError, match="already in flight"):
        sched.submit(Request(uid=5, prompt=[3, 4], max_new_tokens=4))


def test_scheduler_cancel():
    """cancel() mid-decode reports the partial output and frees the slot;
    cancelling a queued request reports empty output; unknown uids (already
    finished — cancellation raced completion) return None."""
    engine, _, _ = make_engine(TINY_LLAMA, cache_size=48)
    sched = ContinuousBatchingScheduler(engine, max_batch=1)
    finishes = []
    sched.submit(
        Request(uid=0, prompt=[1, 2], max_new_tokens=8), on_finish=finishes.append
    )
    sched.submit(
        Request(uid=1, prompt=[3, 4], max_new_tokens=2), on_finish=finishes.append
    )
    sched.step()  # admits uid 0 (token 0) and decodes one round (token 1)
    assert sched.active_slots == 1 and sched.queue_depth == 1

    queued = sched.cancel(1)
    assert queued.finish_reason == "cancelled" and queued.tokens == []
    active = sched.cancel(0)
    assert active.finish_reason == "cancelled" and len(active.tokens) == 2
    assert sched.cancel(0) is None
    assert sched.active_slots == 0 and not sched.has_work()
    assert [c.uid for c in finishes] == [1, 0]


def test_scheduler_deadline_timeout():
    """Deadlines expire at step boundaries: a decoding request keeps its
    partial output with reason "timeout"; a request whose deadline passed
    while queued is never admitted (no prefill spent on it)."""
    engine, _, _ = make_engine(TINY_LLAMA, cache_size=48)
    sched = ContinuousBatchingScheduler(engine, max_batch=1)
    finishes = []
    sched.submit(
        Request(uid=0, prompt=[1, 2], max_new_tokens=40),
        on_finish=finishes.append,
        deadline=time.monotonic() + 60.0,
    )
    sched.submit(
        Request(uid=1, prompt=[3, 4], max_new_tokens=4),
        on_finish=finishes.append,
        deadline=time.monotonic() - 1.0,  # already expired when admission runs
    )
    for _ in range(3):
        sched.step()
    # force uid 0 past its deadline instead of sleeping: the expiry check
    # runs at the next step boundary either way
    sched._slots[0].deadline = time.monotonic() - 1.0
    done = {c.uid: c for c in sched.step()}
    assert done[0].finish_reason == "timeout"
    assert 0 < len(done[0].tokens) < 40
    assert done[1].finish_reason == "timeout" and done[1].tokens == []
    assert not sched.has_work()
    assert sorted(c.uid for c in finishes) == [0, 1]


def test_scheduler_step_gauge_records(tmp_path):
    """Every decode step logs queue-depth / active-slot gauges so load
    tooling has a per-step signal."""
    import json

    from relora_tpu.utils.logging import MetricsLogger

    engine, _, _ = make_engine(TINY_LLAMA, cache_size=48)
    metrics = MetricsLogger(run_dir=str(tmp_path))
    sched = ContinuousBatchingScheduler(engine, max_batch=2, metrics=metrics)
    sched.run([Request(uid=i, prompt=[1, 2, 3], max_new_tokens=4) for i in range(3)])
    metrics.finish()
    records = [
        json.loads(line) for line in (tmp_path / "metrics.jsonl").read_text().splitlines()
    ]
    gauges = [r for r in records if "serve/decode_step" in r]
    assert gauges
    assert [g["serve/decode_step"] for g in gauges] == list(range(1, len(gauges) + 1))
    assert all("serve/queue_depth" in g and "serve/active_slots" in g for g in gauges)
    assert max(g["serve/active_slots"] for g in gauges) == 2
    assert max(g["serve/queue_depth"] for g in gauges) >= 1


def test_scheduler_metrics_records(tmp_path):
    import json

    from relora_tpu.utils.logging import MetricsLogger

    engine, _, _ = make_engine(TINY_LLAMA, cache_size=48)
    metrics = MetricsLogger(run_dir=str(tmp_path))
    sched = ContinuousBatchingScheduler(engine, max_batch=2, metrics=metrics)
    sched.run([Request(uid=i, prompt=[1, 2, 3], max_new_tokens=4) for i in range(3)])
    metrics.finish()
    records = [
        json.loads(line) for line in (tmp_path / "metrics.jsonl").read_text().splitlines()
    ]
    served = [r for r in records if "serve_request" in r]
    assert len(served) == 3
    for r in served:
        assert r["serve/output_tokens"] == 4
        assert r["serve/finish_reason"] == "length"
        assert r["serve/latency_s"] >= r["serve/ttft_s"] >= 0.0
        assert r["serve/decode_tokens_per_s"] > 0.0


def test_generate_respects_eos_and_budget():
    engine, _, _ = make_engine(TINY_LLAMA, cache_size=48)
    outs = engine.generate([[5, 6], [7, 8, 9]], max_new_tokens=5)
    assert all(len(t) == 5 for t in outs)
    eos = outs[0][1]
    outs_eos = engine.generate([[5, 6], [7, 8, 9]], max_new_tokens=5, eos_id=eos)
    assert outs_eos[0] == outs[0][:2]  # truncated at its own EOS


def test_cache_capacity_guard():
    engine, _, _ = make_engine(TINY_LLAMA, cache_size=16)
    with pytest.raises(ValueError, match="exceeds cache capacity"):
        engine.generate([[1] * 10], max_new_tokens=10)
    sched = ContinuousBatchingScheduler(engine, max_batch=1)
    with pytest.raises(ValueError, match="cache entries"):
        sched.run([Request(uid=0, prompt=[1] * 10, max_new_tokens=10)])


def test_bucket_length():
    assert bucket_length(1) == 16
    assert bucket_length(16) == 16
    assert bucket_length(17) == 32
    assert bucket_length(100) == 128
    with pytest.raises(ValueError):
        bucket_length(0)


def test_engine_unmerged_lora_matches_merged():
    """serve.py --no-merge path: an engine holding raw LoRA factors (decode
    forward routed through the shape-aware dispatcher, weights_static) must
    generate exactly the same tokens as the default merge-at-load engine."""
    from relora_tpu.core.relora import LoraSpec, merged_params

    spec = LoraSpec(r=4, alpha=8)
    lora_model = build_decode_model(TINY_LLAMA, cache_size=32, lora=spec)
    ids = jnp.zeros((1, 8), jnp.int32)
    raw = init_params(lora_model, jax.random.PRNGKey(0), ids)
    # lora_b is zeros at init; perturb every lora_b so the branch contributes
    raw = jax.tree_util.tree_map_with_path(
        lambda path, t: (
            jax.random.normal(
                jax.random.PRNGKey(abs(hash(jax.tree_util.keystr(path))) % (2**31)),
                t.shape,
                t.dtype,
            )
            * 0.1
            if any(getattr(k, "key", None) == "lora_b" for k in path)
            else t
        ),
        raw,
    )
    unmerged = InferenceEngine(TINY_LLAMA, raw, cache_size=32, lora=spec)
    merged = InferenceEngine(
        TINY_LLAMA, merged_params(raw, spec), cache_size=32
    )
    prompts = [[1, 2, 3], [4, 5, 6, 7]]
    out_unmerged = unmerged.generate(prompts, max_new_tokens=6)
    out_merged = merged.generate(prompts, max_new_tokens=6)
    assert out_unmerged == out_merged


def test_engine_on_mesh():
    """Same engine code under an explicit device mesh: params shard per the
    logical rules, the cache batch axis shards over data, results match the
    meshless engine."""
    from relora_tpu.parallel.mesh import MeshSpec, make_mesh

    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices")
    mesh = make_mesh(MeshSpec(data=2, fsdp=1, tensor=1, sequence=1), jax.devices()[:2])
    engine, base, params = make_engine(TINY_LLAMA, cache_size=32)
    sharded = InferenceEngine(TINY_LLAMA, params, cache_size=32, mesh=mesh)
    out_ref = engine.generate([[1, 2, 3], [4, 5, 6]], max_new_tokens=4)
    out_mesh = sharded.generate([[1, 2, 3], [4, 5, 6]], max_new_tokens=4)
    assert out_ref == out_mesh
