"""Golden-value tests for LR schedules against an independent math oracle.

The reference schedules are pure lambdas (training_utils.py:173-236); the
oracles below re-derive them in plain Python/math so the jnp implementations
are differentially tested step by step.
"""

import math

import jax.numpy as jnp
import numpy as np
import pytest

from relora_tpu.core.schedules import (
    cosine_with_restarts,
    cyclical_cosine_with_min_lr,
    linear_with_warmup,
    make_schedule,
)


def oracle_cyclical_cosine(step, *, warmup, cycle_length, min_lr_ratio):
    cycle_step = step % cycle_length
    if cycle_step < warmup:
        if step != cycle_step and cycle_step < 2:
            return 1e-7
        return cycle_step / max(1, warmup)
    progress = (cycle_step - warmup) / max(1, cycle_length - warmup)
    return min_lr_ratio + (1 - min_lr_ratio) * 0.5 * (1 + math.cos(math.pi * progress))


def oracle_cosine_restarts(
    step, *, total, first_warmup, restart_warmup, restart_every, min_lr_ratio, adjust_step=0
):
    if step < first_warmup:
        return step / max(1, first_warmup)
    s = step + adjust_step
    restart_step = s % restart_every
    restart_number = s // restart_every
    if restart_step < restart_warmup and step >= restart_every:
        end_progress = (restart_number * restart_every + restart_warmup - first_warmup) / max(
            1, total - first_warmup
        )
        decay = 0.5 * (1 + math.cos(math.pi * end_progress))
        target = min_lr_ratio + (1 - min_lr_ratio) * decay
        return restart_step / max(1, restart_warmup) * target
    progress = (s - first_warmup) / max(1, total - first_warmup)
    decay = 0.5 * (1 + math.cos(math.pi * progress))
    return min_lr_ratio + (1 - min_lr_ratio) * decay


def test_linear_schedule():
    sched = linear_with_warmup(1e-3, warmup_steps=100, num_training_steps=1000)
    assert float(sched(0)) == 0.0
    assert float(sched(50)) == pytest.approx(0.5e-3)
    assert float(sched(100)) == pytest.approx(1e-3)
    assert float(sched(550)) == pytest.approx(0.5e-3)
    assert float(sched(1000)) == pytest.approx(0.0)


def test_cyclical_cosine_matches_oracle():
    kw = dict(warmup=50, cycle_length=500, min_lr_ratio=0.1)
    sched = cyclical_cosine_with_min_lr(
        peak_lr=1.0, warmup_steps=50, num_training_steps=2000, cycle_length=500, min_lr_ratio=0.1
    )
    steps = list(range(0, 2000, 7)) + [0, 1, 499, 500, 501, 502, 999, 1000, 1001]
    got = np.asarray(sched(jnp.asarray(steps)))  # schedules are elementwise
    want = np.array([oracle_cyclical_cosine(s, **kw) for s in steps])
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-7)


def test_cyclical_cosine_later_cycle_quirk():
    """First two steps of cycles after the first return 1e-7 (ref :179-183)."""
    sched = cyclical_cosine_with_min_lr(1.0, 50, 2000, 500, 0.1)
    assert float(sched(500)) == pytest.approx(1e-7)
    assert float(sched(501)) == pytest.approx(1e-7)
    assert float(sched(502)) == pytest.approx(2 / 50)
    # First cycle unaffected
    assert float(sched(0)) == 0.0
    assert float(sched(1)) == pytest.approx(1 / 50)


@pytest.mark.parametrize("adjust_step", [0, 150])
def test_cosine_restarts_matches_oracle(adjust_step):
    kw = dict(
        total=10_000,
        first_warmup=200,
        restart_warmup=50,
        restart_every=1000,
        min_lr_ratio=0.1,
        adjust_step=adjust_step,
    )
    sched = cosine_with_restarts(
        peak_lr=1.0,
        first_warmup_steps=200,
        restart_warmup_steps=50,
        restart_every=1000,
        num_training_steps=10_000,
        min_lr_ratio=0.1,
        adjust_step=adjust_step,
    )
    steps = sorted(
        set(
            list(range(0, 10_000, 13))
            + [0, 1, 199, 200, 999, 1000, 1001, 1049, 1050, 1051, 4999, 5000, 5049, 9999]
        )
    )
    got = np.asarray(sched(jnp.asarray(steps)))
    want = np.array([oracle_cosine_restarts(s, **kw) for s in steps])
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-7)


def test_cosine_restarts_rewarmup_shape():
    """After each restart, LR ramps linearly to the decayed envelope."""
    sched = cosine_with_restarts(1.0, 100, 50, 1000, 10_000, 0.1)
    # step 1000: restart boundary, LR drops to 0
    assert float(sched(1000)) == pytest.approx(0.0)
    # mid-rewarmup: half the envelope
    env = float(sched(1050))
    assert float(sched(1025)) == pytest.approx(env / 2 * (25 / 25) / 1, rel=0.3)
    # monotone increase during rewarmup
    vals = np.asarray(sched(jnp.arange(1000, 1051)))
    assert (np.diff(vals) >= 0).all()
    # after rewarmup, rejoins global cosine (decreasing)
    vals = np.asarray(sched(jnp.arange(1050, 1200, 10)))
    assert (np.diff(vals) <= 0).all()


def test_cosine_restarts_validation():
    with pytest.raises(ValueError, match="divisible"):
        cosine_with_restarts(1.0, 100, 50, 999, 10_000, 0.1)
    with pytest.raises(ValueError, match="before the first warmup"):
        cosine_with_restarts(1.0, 900, 50, 800, 8000, 0.1)
    with pytest.raises(ValueError):
        make_schedule("cosine", lr=1.0, num_training_steps=1000, warmup_steps=10,
                      cycle_length=300)  # not divisible
    with pytest.raises(ValueError, match="adjust_step"):
        make_schedule("linear", lr=1.0, num_training_steps=1000, warmup_steps=10,
                      adjust_step=5)


def test_make_schedule_dispatch():
    s = make_schedule(
        "cosine_restarts",
        lr=4e-4,
        num_training_steps=130_000,
        warmup_steps=500,
        min_lr_ratio=0.1,
        cycle_length=1000,
        restart_warmup_steps=100,
    )
    # the 1B production recipe's schedule (training_configs/1B_v1.0.yaml)
    assert float(s(0)) == 0.0
    assert float(s(500)) == pytest.approx(4e-4, rel=1e-5)
    assert float(s(130_000 - 1)) == pytest.approx(4e-5, rel=0.01)  # min_lr_ratio floor


def test_schedule_is_jittable():
    import jax

    sched = cosine_with_restarts(1.0, 100, 50, 1000, 10_000, 0.1)
    jitted = jax.jit(sched)
    assert float(jitted(jnp.asarray(1025))) == pytest.approx(float(sched(1025)))
