"""Loss functions.

Parity: the reference computes a shifted cross-entropy over all positions
with labels = input_ids (torchrun_main.py:786, modeling_llama.py:694-708);
pretokenized data is chunked with no padding, so no masking is needed, but we
accept an optional mask for datasets that have one.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def causal_lm_loss(
    logits: jax.Array,
    input_ids: jax.Array,
    mask: Optional[jax.Array] = None,
    labels: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Shifted next-token CE in f32.

    Returns ``(mean_loss, n_tokens)`` where n_tokens is the count the mean ran
    over (needed by distributed eval aggregation, torchrun_main.py:159-183).

    With explicit ``labels`` (same shape as input_ids; -100 = ignore, the
    reference CE's ignore_index), no shift is applied — the caller aligned
    targets itself (used by the zigzag sequence layout, where position i's
    successor is not i+1).
    """
    if labels is not None:
        lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        safe = jnp.maximum(labels, 0)
        token_ll = jnp.take_along_axis(lp, safe[..., None], axis=-1)[..., 0]
        valid = (labels >= 0).astype(jnp.float32)
        if mask is not None:
            valid = valid * mask.astype(jnp.float32)
        n = jnp.maximum(valid.sum(), 1.0)
        return -(token_ll * valid).sum() / n, n
    # upcast per-position inside log_softmax; accepts bf16 logits (the
    # bf16_logits option) without a separate f32 materialization
    shift_logits = logits[:, :-1, :].astype(jnp.float32)
    shift_labels = input_ids[:, 1:]
    logp = jax.nn.log_softmax(shift_logits, axis=-1)
    token_ll = jnp.take_along_axis(logp, shift_labels[..., None], axis=-1)[..., 0]
    if mask is not None:
        shift_mask = mask[:, 1:].astype(jnp.float32)
        n = jnp.maximum(shift_mask.sum(), 1.0)
        return -(token_ll * shift_mask).sum() / n, n
    n = jnp.asarray(token_ll.size, jnp.float32)
    return -token_ll.mean(), n


def chunked_softmax_ce(
    hidden: jax.Array,
    head_kernel: jax.Array,
    targets: jax.Array,
    chunk_size: int = 8192,
) -> Tuple[jax.Array, jax.Array]:
    """CE computed from hidden states without materializing (B, S, V) logits.

    Streams over vocab chunks: each scan step projects one logits chunk
    (bf16 matmul, f32 accumulation), folds it into a running
    max/log-sum-exp, and gathers the target logit when the target falls in
    the chunk.  Peak activation memory is O(B·S·chunk) instead of O(B·S·V)
    — the lever for large-vocab models where f32 logits dominate the loss's
    HBM traffic.  ``jax.checkpoint`` on the body keeps backward at the same
    bound (chunk logits recomputed).

    ``targets``: (B, S) with -100 = ignore.  Returns (mean_loss, n_tokens).
    """
    B, S, E = hidden.shape
    V = head_kernel.shape[-1]
    n_chunks = -(-V // chunk_size)
    pad_v = n_chunks * chunk_size - V
    kernel = head_kernel
    if pad_v:
        kernel = jnp.pad(head_kernel, ((0, 0), (0, pad_v)))
    kernel_chunks = kernel.reshape(E, n_chunks, chunk_size).transpose(1, 0, 2)

    h = hidden.reshape(B * S, E)
    tgt = jnp.maximum(targets.reshape(B * S), 0)
    valid = (targets.reshape(B * S) >= 0).astype(jnp.float32)

    @jax.checkpoint
    def fold(carry, inp):
        m, lse_acc, t_logit = carry
        idx, kchunk = inp
        logits = jnp.matmul(h, kchunk.astype(h.dtype)).astype(jnp.float32)
        if pad_v:
            # padded lanes of the last chunk must not enter the softmax
            lane = idx * chunk_size + jnp.arange(chunk_size)
            logits = jnp.where(lane[None, :] < V, logits, -jnp.inf)
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
        lse_acc = lse_acc * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits - m_new[:, None]), axis=-1
        )
        local = tgt - idx * chunk_size
        in_chunk = (local >= 0) & (local < chunk_size)
        gathered = jnp.take_along_axis(
            logits, jnp.clip(local, 0, chunk_size - 1)[:, None], axis=-1
        )[:, 0]
        t_logit = jnp.where(in_chunk, gathered, t_logit)
        return (m_new, lse_acc, t_logit), None

    init = (
        jnp.full((B * S,), -jnp.inf, jnp.float32),
        jnp.zeros((B * S,), jnp.float32),
        jnp.zeros((B * S,), jnp.float32),
    )
    (m, lse_acc, t_logit), _ = jax.lax.scan(
        fold, init, (jnp.arange(n_chunks), kernel_chunks)
    )
    nll = jnp.log(lse_acc) + m - t_logit
    n = jnp.maximum(valid.sum(), 1.0)
    return (nll * valid).sum() / n, n
