"""TPU-native GPT-NeoX / Pythia decoder (Flax) with first-class LoRA leaves.

Capability parity with the reference's modified HF GPT-NeoX
(peft_pretraining/modeling_pythia.py): fused QKV ``query_key_value`` linear
(:108), partial rotary embeddings (``rotary_pct``, :97, :184-197), parallel
residual blocks (:443-456), LayerNorm with biases, GELU MLP, causal SDPA
(:245-295), and a causal-LM head (:701-857).

Used by the production 1B recipe (training_configs/1B_v1.0.yaml:
EleutherAI/pythia-1b warm start).  Weight layout matches HF exactly — the
fused QKV out-dim is interleaved per head as (heads, 3, head_dim) — so
hf_compat transfers Pythia checkpoints without reshuffling.

Same TPU-first choices as models/llama.py: scan-over-layers, optional remat,
bf16 matmuls with f32 norms/rotary/softmax.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from relora_tpu.config.model import ModelConfig
from relora_tpu.core.relora import LoraSpec
from relora_tpu.models.llama import (
    apply_rotary,
    attend_with_cache,
    attend_with_paged_cache,
    rotary_tables,
)
from relora_tpu.models.lora import LoRALinear
from relora_tpu.ops.attention import dot_product_attention


class LayerNorm(nn.Module):
    """f32 LayerNorm with bias (NeoX style)."""

    eps: float = 1e-5
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        scale = self.param(
            "scale",
            nn.with_logical_partitioning(nn.initializers.ones_init(), ("embed",)),
            (x.shape[-1],),
            jnp.float32,
        )
        bias = self.param(
            "bias",
            nn.with_logical_partitioning(nn.initializers.zeros_init(), ("embed",)),
            (x.shape[-1],),
            jnp.float32,
        )
        x32 = x.astype(jnp.float32)
        mean = jnp.mean(x32, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(x32 - mean), axis=-1, keepdims=True)
        y = (x32 - mean) * jax.lax.rsqrt(var + self.eps)
        return (y * scale + bias).astype(self.dtype)


class NeoXAttention(nn.Module):
    config: ModelConfig
    lora: Optional[LoraSpec] = None
    dtype: jnp.dtype = jnp.bfloat16
    attention_impl: str = "auto"
    decode: bool = False
    cache_size: int = 0
    # page_size > 0 switches the decode cache to the shared paged pool
    # (see models/llama.attend_with_paged_cache)
    page_size: int = 0
    num_pages: int = 0
    kv_dtype: str = "bf16"

    @nn.compact
    def __call__(self, x, cos, sin, positions=None, deterministic: bool = True, block_tables=None, adapter_idx=None, row_map=None):
        cfg = self.config
        h, n, hd = cfg.hidden_size, cfg.num_attention_heads, cfg.head_dim
        rot = cfg.rotary_dim

        qkv = LoRALinear(
            3 * h,
            use_bias=True,
            lora=self.lora,
            dtype=self.dtype,
            kernel_axes=("embed", "qkv"),
            name="query_key_value",
        )(x, deterministic, adapter_idx)
        B, S = x.shape[:2]
        # HF NeoX fused layout: out dim is (heads, 3 * head_dim) interleaved
        qkv = qkv.reshape(B, S, n, 3 * hd)
        q, k, v = qkv[..., :hd], qkv[..., hd : 2 * hd], qkv[..., 2 * hd :]

        # partial rotary: rotate the first rotary_dim dims, pass the rest
        # (modeling_pythia.py:184-197)
        q = jnp.concatenate([apply_rotary(q[..., :rot], cos, sin), q[..., rot:]], axis=-1)
        k = jnp.concatenate([apply_rotary(k[..., :rot], cos, sin), k[..., rot:]], axis=-1)

        if self.decode and self.page_size > 0:
            out = attend_with_paged_cache(self, q, k, v, positions, block_tables, row_map)
        elif self.decode:
            out = attend_with_cache(self, q, k, v, positions)
        else:
            out = dot_product_attention(q, k, v, causal=True, impl=self.attention_impl)
        out = out.reshape(B, S, h)
        return LoRALinear(
            h,
            use_bias=True,
            lora=self.lora,
            dtype=self.dtype,
            kernel_axes=("qkv", "embed"),
            name="dense",
        )(out, deterministic, adapter_idx)


class NeoXMLP(nn.Module):
    config: ModelConfig
    lora: Optional[LoraSpec] = None
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, deterministic: bool = True, adapter_idx=None):
        cfg = self.config
        dense = functools.partial(
            LoRALinear, use_bias=True, lora=self.lora, dtype=self.dtype
        )
        y = dense(cfg.intermediate_size, kernel_axes=("embed", "mlp"), name="dense_h_to_4h")(
            x, deterministic, adapter_idx
        )
        y = nn.gelu(y, approximate=False)
        return dense(cfg.hidden_size, kernel_axes=("mlp", "embed"), name="dense_4h_to_h")(
            y, deterministic, adapter_idx
        )


class NeoXLayer(nn.Module):
    """Scan-compatible block; parallel residual by default
    (modeling_pythia.py:443-456)."""

    config: ModelConfig
    lora: Optional[LoraSpec] = None
    dtype: jnp.dtype = jnp.bfloat16
    attention_impl: str = "auto"
    decode: bool = False
    cache_size: int = 0
    page_size: int = 0
    num_pages: int = 0
    kv_dtype: str = "bf16"

    @nn.compact
    def __call__(self, x, cos, sin, positions=None, deterministic: bool = True, block_tables=None, adapter_idx=None, row_map=None):
        cfg = self.config
        attn_in = LayerNorm(eps=cfg.layer_norm_eps, dtype=self.dtype, name="input_layernorm")(x)
        attn_out = NeoXAttention(
            cfg, self.lora, self.dtype, self.attention_impl,
            self.decode, self.cache_size, self.page_size, self.num_pages,
            self.kv_dtype,
            name="attention"
        )(attn_in, cos, sin, positions, deterministic, block_tables, adapter_idx, row_map)
        mlp_in = LayerNorm(
            eps=cfg.layer_norm_eps, dtype=self.dtype, name="post_attention_layernorm"
        )(x if cfg.use_parallel_residual else x + attn_out)
        mlp_out = NeoXMLP(cfg, self.lora, self.dtype, name="mlp")(mlp_in, deterministic, adapter_idx)
        if cfg.use_parallel_residual:
            # x + attn(ln1(x)) + mlp(ln2(x))
            return x + attn_out + mlp_out, None
        return x + attn_out + mlp_out, None  # sequential: mlp_in already includes attn


class GPTNeoXForCausalLM(nn.Module):
    """Causal LM with f32 logits (parity: modeling_pythia.py:701-857)."""

    config: ModelConfig
    lora: Optional[LoraSpec] = None
    dtype: jnp.dtype = jnp.bfloat16
    scan_layers: bool = True
    remat: bool = False
    remat_policy: str = "full"  # 'full' | 'dots' (see params_util.remat_policy)
    attention_impl: str = "auto"
    logits_dtype: jnp.dtype = jnp.float32
    # inference: decode=True turns on the per-layer KV caches ("cache"
    # variable collection) of capacity cache_size (see serve/engine.py);
    # page_size > 0 additionally switches them to the shared paged pool,
    # reached through the ``block_tables`` call argument; kv_dtype="int8"
    # stores the pool quantized (see models/llama.attend_with_paged_cache)
    decode: bool = False
    cache_size: int = 0
    page_size: int = 0
    num_pages: int = 0
    kv_dtype: str = "bf16"

    @nn.compact
    def __call__(
        self,
        input_ids: jax.Array,
        positions: Optional[jax.Array] = None,
        deterministic: bool = True,
        return_hidden: bool = False,
        block_tables: Optional[jax.Array] = None,
        adapter_idx: Optional[jax.Array] = None,
        row_map: Optional[jax.Array] = None,
    ) -> jax.Array:
        cfg = self.config
        x = nn.Embed(
            cfg.vocab_size,
            cfg.hidden_size,
            embedding_init=nn.with_logical_partitioning(
                nn.initializers.normal(stddev=cfg.initializer_range), ("vocab", "embed")
            ),
            param_dtype=jnp.float32,
            dtype=self.dtype,
            name="embed_in",
        )(input_ids)

        if positions is None:
            positions = jnp.arange(input_ids.shape[1])[None, :]
        cos, sin = rotary_tables(
            positions,
            cfg.rotary_dim,
            cfg.rotary_emb_base,
            scaling_type=cfg.rope_scaling_type,
            scaling_factor=cfg.rope_scaling_factor,
            max_position=cfg.max_sequence_length,
            current_length=input_ids.shape[1],
        )

        block = NeoXLayer
        if self.remat:
            from relora_tpu.models.params_util import remat_policy

            block = nn.remat(
                block,
                prevent_cse=not self.scan_layers,
                static_argnums=(5,),
                policy=remat_policy(
                    self.remat_policy, max_save_width=self.config.hidden_size
                ),
            )
        layer_kwargs = dict(
            config=cfg, lora=self.lora, dtype=self.dtype,
            attention_impl=self.attention_impl, decode=self.decode,
            cache_size=self.cache_size, page_size=self.page_size,
            num_pages=self.num_pages, kv_dtype=self.kv_dtype,
        )
        if self.scan_layers:
            variable_axes = {"params": 0}
            if self.decode:
                # per-layer KV cache stacks on the same leading "layers" axis
                variable_axes["cache"] = 0
            scanned = nn.scan(
                block,
                variable_axes=variable_axes,
                split_rngs={"params": True, "dropout": True},
                in_axes=(nn.broadcast,) * 7,
                length=cfg.num_hidden_layers,
                metadata_params={nn.PARTITION_NAME: "layers"},
            )
            x, _ = scanned(**layer_kwargs, name="layers")(
                x, cos, sin, positions, deterministic, block_tables, adapter_idx, row_map
            )
        else:
            for i in range(cfg.num_hidden_layers):
                x, _ = block(**layer_kwargs, name=f"layers_{i}")(
                    x, cos, sin, positions, deterministic, block_tables, adapter_idx, row_map
                )

        x = LayerNorm(eps=cfg.layer_norm_eps, dtype=self.dtype, name="final_layer_norm")(x)
        if return_hidden:
            return x
        logits = LoRALinear(
            cfg.vocab_size,
            lora=None,
            dtype=self.dtype,
            kernel_axes=("embed", "vocab"),
            name="embed_out",
        )(x)
        return logits.astype(self.logits_dtype)
