#!/bin/bash
# TPU-tunnel recovery watcher — round-5 priorities, v2 (post first window).
#
# The 2026-07-31 03:44-04:26Z tunnel window landed the first driver-grade
# on-chip headline in four rounds (6,920.7 tok/s, 26.85% MFU) plus one sweep
# point (dots/chunked mb2: 7,498.7 tok/s, 29.1% MFU) and three *informative*
# OOM failures: XLA hoists the all-layers f32->bf16 kernel converts out of
# the scan loop, costing ~5 GB the planner never saw (dots/chunked mb4:
# planned 14.08 GB, used 19.04 GB).  That finding produced the
# LoraSpec.base_dtype='bf16' lever (no f32 master for the frozen base: no
# convert temps, half the base bytes) — this queue leads with it, and loss
# parity moved up (it is the longest stage and a verdict must-have; the
# first window died before reaching it at queue position 6).
#
# Usage: nohup bash scripts/tpu_recovery_watch.sh > /tmp/tpu_watch_r5.log 2>&1 &
set -u
cd "$(dirname "$0")/.."
RES=bench_results
mkdir -p "$RES"

commit() { # commit <message> -- <paths...>
  local msg="$1"; shift; shift
  git add "$@" 2>/dev/null
  git diff --cached --quiet || git commit -q -m "$msg

No-Verification-Needed: bench/measurement artifacts only" -- "$@"
}

probe() {
  timeout -k 10 180 python -c \
    "import jax,jax.numpy as jnp;print(float(jax.jit(lambda a:(a@a).sum())(jnp.ones((128,128)))))" \
    >/dev/null 2>&1
}

sweep() { # sweep <args...>
  # each config is a FRESH program on-chip; remote compiles ran 5-25 min in
  # past windows, so give the compile room — the watchdog only bounds a
  # wedged tunnel, not a slow compile
  BENCH_WATCHDOG_SECS=1500 timeout 1800 python scripts/bench_sweep.py \
      --out "$RES/r5_sweep.jsonl" "$@" \
    || echo "{\"error\": \"failed: $*\"}" >> "$RES/r5_sweep.jsonl"
  commit "On-chip sweep: $*" -- "$RES/r5_sweep.jsonl"
}

echo "watcher start $(date -u +%FT%TZ)"
while ! probe; do
  echo "tunnel down $(date -u +%FT%TZ)"
  sleep 240
done
echo "tunnel UP $(date -u +%FT%TZ)"

# 1. the bf16-base lever, best-first (quant_replan: dots/chunked mb4 plans
# 11.67 GB with no convert temps — the f32 version of this config used
# 19.04 GB and OOMed; mb2 is the safe A/B against f32's measured 29.1%)
sweep --base-dtype bf16 --remat --remat-policy dots --loss-impl chunked --micro-batch 4 --label "bf16 base dots chunked mb4"
# mb2 won the first window at 29.1% but updates the optimizer every 2048
# tokens; ga4 keeps the mb2 memory footprint (grad accum adds only the
# trainable-grad buffer the scan already carries) while amortizing the
# update + host sync over 8192 tokens like the mb8 baseline
sweep --remat --remat-policy dots --loss-impl chunked --micro-batch 2 --grad-accum 4 --label "dots chunked mb2 ga4"
sweep --base-dtype bf16 --remat --remat-policy dots --loss-impl chunked --micro-batch 2 --label "bf16 base dots chunked mb2"

# 2. winner replay through bench.py: refreshes last_onchip.json +
# BENCH_r5_local so the driver's end-of-round run reflects the best
# measured config even through an outage.  A function — called again after
# the stage-5/8 sweeps so a late winner (e.g. bf16-base full mb24) can
# still take the headline; any sweep row beats the headline on mfu,
# full-remat labels included.
replay_winner() {
  local BEST
  BEST=$(python - <<'EOF'
import json, re
best_mfu, best = 0.0, ""
try:
    for line in open("bench_results/r5_sweep.jsonl"):
        r = json.loads(line)
        label = r.get("label", "")
        mfu = r.get("mfu") or 0.0
        if label and mfu > best_mfu:
            m = re.search(r"mb(\d+)", label)
            ga = re.search(r"ga(\d+)", label)
            best_mfu = mfu
            best = ":".join((
                ga.group(1) if ga else "1",
                "dots_all" if "dots_all" in label
                else ("dots" if "dots" in label else "full"),
                m.group(1) if m else "8",
                "chunked" if "chunked" in label else "dense",
                "0" if "dropout0" in label else "0.1",
                # quantized/bf16-base winners must be replayed with the SAME
                # base storage: bench.py honors BENCH_QUANTIZE and
                # BENCH_BASE_DTYPE, and an f32 replay of a bf16-base winner
                # is the 19-GB plan the compile already rejected
                "int8" if "int8" in label else ("nf4" if "nf4" in label else ""),
                "bf16" if "bf16 base" in label else "",
            ))
    head = json.load(open("bench_results/BENCH_r5_local.json"))
    print(best if best_mfu > head["detail"]["mfu"] else "")
except Exception:
    print("")
EOF
)
  [ -z "$BEST" ] && return 0
  local BEST_GA BEST_POLICY BEST_MB BEST_LOSS BEST_DROPOUT BEST_QUANT BEST_BASE
  IFS=: read -r BEST_GA BEST_POLICY BEST_MB BEST_LOSS BEST_DROPOUT BEST_QUANT BEST_BASE <<< "$BEST"
  BENCH_REMAT_POLICY="$BEST_POLICY" BENCH_MICRO_BATCH="$BEST_MB" \
    BENCH_GRAD_ACCUM="$BEST_GA" \
    BENCH_LOSS_IMPL="$BEST_LOSS" BENCH_DROPOUT="$BEST_DROPOUT" \
    BENCH_QUANTIZE="$BEST_QUANT" BENCH_BASE_DTYPE="$BEST_BASE" \
    BENCH_WATCHDOG_SECS=1500 timeout 1800 python bench.py \
    > "$RES/BENCH_r5_local_${BEST_POLICY}.json" 2>/dev/null \
    && commit "On-chip headline bench with $BEST_POLICY remat (mb $BEST_MB, $BEST_LOSS loss, base ${BEST_BASE:-${BEST_QUANT:-f32}})" -- "$RES/BENCH_r5_local_${BEST_POLICY}.json" "$RES/last_onchip.json"
}
replay_winner

# 3. loss parity (the longest stage, and a verdict must: gap <=1% at 35m
# with 1000-step cycles).  4000 steps; the magnitude variant reuses the
# shared warmup + full-rank branches, so only its ReLoRA branch runs.
# timeout: a wedged tunnel mid-branch must not starve stages 4-8 (the
# documented failure mode black-holes device calls); 3h bounds the two
# fresh branches + compiles, and autoresume means a retry loses nothing
CORPUS=/tmp/corpus/local400 WORK=/tmp/loss_parity \
  STEPS_WARMUP=500 STEPS_TOTAL=4000 timeout 10800 bash scripts/loss_parity.sh \
  > /tmp/loss_parity.log 2>&1
echo "loss_parity exit=$? $(date -u +%FT%TZ)"
if [ -f /tmp/loss_parity/compare_llama_35m.json ]; then
  cp /tmp/loss_parity/compare_llama_35m.json "$RES/r5_loss_parity_chip.json"
  commit "On-chip loss-parity result (llama_35m, 1000-step cycles, 4000 steps)" -- "$RES/r5_loss_parity_chip.json"
fi
CORPUS=/tmp/corpus/local400 WORK=/tmp/loss_parity OPT_PRUNE=0.9 \
  STEPS_WARMUP=500 STEPS_TOTAL=4000 timeout 10800 bash scripts/loss_parity.sh \
  > /tmp/loss_parity_mag.log 2>&1
echo "loss_parity magnitude exit=$? $(date -u +%FT%TZ)"
if [ -f /tmp/loss_parity/compare_llama_35m_mag0.9.json ]; then
  cp /tmp/loss_parity/compare_llama_35m_mag0.9.json "$RES/r5_loss_parity_chip_mag.json"
  commit "On-chip loss-parity: magnitude-pruning reset at 1000-step cycles" -- "$RES/r5_loss_parity_chip_mag.json"
fi

# 4. attention op-level A/B — MHA then GQA (16q/4kv, the un-expanded path)
timeout 2400 python scripts/bench_attention.py --seqs 1024 4096 16384 --impls xla pallas \
  > "$RES/r5_attn.jsonl" 2>/tmp/attn_r5.err \
  && commit "Attention op-level A/B (xla vs pallas, 1k/4k/16k)" -- "$RES/r5_attn.jsonl"
timeout 2400 python scripts/bench_attention.py --seqs 4096 16384 --impls xla pallas \
  --kv-heads 4 >> "$RES/r5_attn.jsonl" 2>>/tmp/attn_r5.err \
  && commit "Attention op-level A/B: GQA 16q/4kv" -- "$RES/r5_attn.jsonl"

# 5. remaining utilization/base-storage levers, by expected value.  The
# first window's OOMs: f32 full/chunked OOMed at mb32 (20.37 GB), so mb16
# is the biggest safe f32 step; bf16-base full/chunked saves ~4.8 GB so
# mb24 should fit where f32 mb32 did not.  int8 at mb8 compiles like the
# baseline (no dots interplay); the int8+dots combination compiled >25 min
# and is deprioritized to last.
sweep --remat --loss-impl chunked --micro-batch 16 --label "remat full chunked mb16"
sweep --base-dtype bf16 --remat --loss-impl chunked --micro-batch 24 --label "bf16 base full chunked mb24"
sweep --base-dtype bf16 --remat --remat-policy dots_all --loss-impl chunked --micro-batch 2 --label "bf16 base dots_all chunked mb2"
sweep --remat --quantize int8 --label "remat int8-base"
sweep --remat --quantize nf4 --label "remat nf4-base"
RELORA_TPU_PALLAS_QUANT=1 sweep --remat --quantize int8 --label "remat int8-base pallas-dequant"
sweep --remat --dropout 0 --label "remat full dropout0"

# a stage-5 sweep (e.g. bf16-base full mb24) may have beaten the earlier
# headline — give it the replay before spending chip time on extras
replay_winner

# 6. extra bench configs
BENCH_CONFIG=llama_250m BENCH_WATCHDOG_SECS=1500 timeout 1800 python bench.py > "$RES/BENCH_r5_250m.json" 2>/dev/null \
  && commit "On-chip bench: llama_250m config" -- "$RES/BENCH_r5_250m.json"
BENCH_CONFIG=llama_1b_magnitude BENCH_WATCHDOG_SECS=1500 timeout 1800 python bench.py > "$RES/BENCH_r5_magnitude.json" 2>/dev/null \
  && commit "On-chip bench: magnitude-reset config" -- "$RES/BENCH_r5_magnitude.json"

# 7. long-context throughput (verdict weak #4): flash ring fold body at
# long context, one JSON line per seq.  Append-mode survives an outage;
# already-measured seqs are skipped on a watcher restart (no dupes), and
# the commit only lands if at least one real measurement exists.
for S in 4096 16384 32768; do
  grep -q "\"seq\": $S" "$RES/r5_longcontext.jsonl" 2>/dev/null && continue
  timeout 1800 python tools/bench_longcontext.py --mode throughput --seq "$S" \
    >> "$RES/r5_longcontext.jsonl" 2>/tmp/longctx_r5.err \
    || echo "{\"error\": \"failed: seq $S\"}" >> "$RES/r5_longcontext.jsonl"
done
grep -q tokens_per_sec "$RES/r5_longcontext.jsonl" 2>/dev/null \
  && commit "Long-context throughput bench (4k/16k/32k)" -- "$RES/r5_longcontext.jsonl"

# 8. slow compiles, one attempt each
sweep --quantize int8 --remat --remat-policy dots --loss-impl chunked --micro-batch 4 --label "int8 base dots chunked mb4 retry"
replay_winner
echo "watcher done $(date -u +%FT%TZ)"
