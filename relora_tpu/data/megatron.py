"""Megatron-style data path: config parsing, dataset building, iterators.

Capability parity with megatron_dataset/data_utils.py +
the NeoXArgs data surface the training script actually uses
(torchrun_main.py:276-319): mmap ``.bin``/``.idx`` corpora, weighted
multi-corpus blending, train/valid/test from either explicit path lists or a
single ``data_path`` with a ``split`` string, deterministic resume rewind,
and per-host batch sharding.

The 2,800-LoC NeoXArgs dataclass aggregation collapses to the one small
typed config below: everything the reference's loader reads from it
(data paths/weights, split, seq_length, data_impl, seed) — the rest of the
reference YAML (model settings consumed by NeoX proper) is accepted and
ignored, so existing config files (configs/pile_megatron_dataset.yaml) load
unchanged.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Iterator, List, Optional, Sequence

import numpy as np
import yaml

from relora_tpu.data.blendable import BlendableDataset
from relora_tpu.data.memmap import open_token_dataset
from relora_tpu.data.sample_index import PackedCausalDataset
from relora_tpu.utils.logging import get_logger

logger = get_logger(__name__)


@dataclasses.dataclass
class MegatronDataConfig:
    train_data_paths: Optional[List[str]] = None
    valid_data_paths: Optional[List[str]] = None
    test_data_paths: Optional[List[str]] = None
    label_data_paths: Optional[List[str]] = None  # aligned with train_data_paths
    train_data_weights: Optional[List[float]] = None
    valid_data_weights: Optional[List[float]] = None
    test_data_weights: Optional[List[float]] = None
    data_path: Optional[str] = None
    split: str = "969,30,1"
    seq_length: int = 2048
    seed: int = 1234
    data_impl: str = "mmap"
    # NeoX batch-arithmetic keys found in the YAML (not consumed for
    # training — the training config owns batch arithmetic — but kept so
    # the loader can solve/cross-check them once the mesh size is known)
    neox_batch_keys: dict = dataclasses.field(default_factory=dict)

    @classmethod
    def from_yaml(cls, path: str) -> "MegatronDataConfig":
        with open(path) as f:
            raw = yaml.safe_load(f)
        known = {f.name for f in dataclasses.fields(cls) if f.name != "neox_batch_keys"}
        kwargs = {k: v for k, v in raw.items() if k in known and v not in ("", None)}
        cfg = cls(**kwargs)
        # retained for the dp-aware cross-check once the mesh is known
        # (cross_check_neox_batch in build_train_valid_test_iterators)
        cfg.neox_batch_keys = _check_neox_batch_keys(raw, path)
        if cfg.data_impl not in ("mmap", "lazy", "cached", "infer"):
            raise NotImplementedError(
                f"data_impl={cfg.data_impl!r}: supported are mmap/lazy/cached/infer"
            )
        if cfg.data_path is None and not cfg.train_data_paths:
            raise ValueError("config needs train_data_paths or data_path")
        return cfg


def solve_batch_parameters(
    dp_world_size: int,
    train_batch: Optional[int] = None,
    micro_batch: Optional[int] = None,
    grad_acc: Optional[int] = None,
) -> tuple:
    """Solve the NeoX batch triple for whatever values are missing.

    Reference-equivalent case analysis (NeoXArgs.calculate_batch_parameters,
    megatron_dataset/arguments.py:753-791), floor-division quirks included:
    given any sufficient subset of {train_batch_size,
    micro_batch_per_rank, grad_accum_steps} and the data-parallel world
    size, returns the completed ``(train_batch, micro_batch, grad_acc)``.
    Raises ValueError when neither train_batch nor micro_batch is given
    (the reference asserts there).
    """
    if train_batch is not None and micro_batch is not None and grad_acc is not None:
        pass  # fully specified
    elif train_batch is not None and micro_batch is not None:
        grad_acc = (train_batch // micro_batch) // dp_world_size
    elif train_batch is not None and grad_acc is not None:
        micro_batch = (train_batch // dp_world_size) // grad_acc
    elif micro_batch is not None and grad_acc is not None:
        train_batch = micro_batch * grad_acc * dp_world_size
    elif train_batch is not None:
        grad_acc = 1
        micro_batch = train_batch // dp_world_size
    elif micro_batch is not None:
        train_batch = micro_batch * dp_world_size
        grad_acc = 1
    else:
        raise ValueError(
            "batch arithmetic needs train_batch_size or "
            "train_micro_batch_size_per_gpu (arguments.py:788-791)"
        )
    return int(train_batch), int(micro_batch), int(grad_acc)


def check_batch_parameters(
    dp_world_size: int, train_batch: int, micro_batch: int, grad_acc: int
) -> None:
    """Validate a completed batch triple (reference:
    NeoXArgs.check_batch_parameters, arguments.py:793-812): all three
    positive and train_batch == micro_batch * grad_acc * dp_world_size.
    Raises ValueError on violation."""
    for name, v in (
        ("train_batch_size", train_batch),
        ("micro_batch_per_rank", micro_batch),
        ("gradient_accumulation_steps", grad_acc),
    ):
        if v <= 0:
            raise ValueError(f"{name}={v} must be > 0")
    if train_batch != micro_batch * grad_acc * dp_world_size:
        raise ValueError(
            f"inconsistent batch arithmetic: train_batch_size={train_batch} != "
            f"micro={micro_batch} * grad_accum={grad_acc} * dp={dp_world_size}"
        )


def cross_check_neox_batch(
    mcfg: "MegatronDataConfig",
    path: str,
    dp_world_size: int,
    micro_batch: int,
    grad_accum: int,
    total_batch_size: int,
) -> None:
    """Solve the YAML's NeoX batch keys against the ACTUAL mesh size and
    warn when the result disagrees with the training config.

    The training config owns batch arithmetic in this framework (the
    reference instead derives it from the NeoX YAML + world size,
    arguments.py:753-812); a NeoX YAML carrying batch keys that solve to a
    different recipe than the one actually running deserves a loud warning,
    not silence — but not a hard failure, since reference data YAMLs must
    keep loading unchanged.
    """
    keys = mcfg.neox_batch_keys or {}
    if not keys:
        return
    try:
        solved = solve_batch_parameters(
            dp_world_size,
            train_batch=keys.get("train_batch_size"),
            micro_batch=keys.get("train_micro_batch_size_per_gpu"),
            grad_acc=keys.get("gradient_accumulation_steps"),
        )
        check_batch_parameters(dp_world_size, *solved)
    # ZeroDivisionError: a zero-valued divisor key (e.g. micro_batch: 0 with
    # no grad_acc) reaches the solver's floor divisions — warn, don't crash
    except (ValueError, TypeError, ZeroDivisionError) as e:
        logger.warning("%s: NeoX batch keys do not solve at dp=%s: %s", path, dp_world_size, e)
        return
    actual = (total_batch_size, micro_batch, grad_accum)
    if solved != actual:
        logger.warning(
            "%s: NeoX batch keys solve to (train=%s, micro=%s, grad_acc=%s) at "
            "dp=%s, but the training config runs (total=%s, micro=%s, "
            "grad_acc=%s) — the training config wins",
            path, *solved, dp_world_size, *actual,
        )
    else:
        logger.info(
            "%s: NeoX batch keys consistent with the training config at dp=%s",
            path, dp_world_size,
        )


def _check_neox_batch_keys(raw: dict, path: str) -> dict:
    """Collect the NeoX batch-arithmetic keys we deliberately don't consume
    for training, warning that they are data-YAML passengers here.

    The reference solves/validates train_batch_size = micro_batch_per_gpu *
    gradient_accumulation_steps * world_size when loading a NeoX YAML
    (megatron_dataset/arguments.py:753-812).  The full solve/validate runs
    later, against the real mesh size (cross_check_neox_batch); this
    load-time pass only flags triples that are impossible at ANY world size.
    Returns the present keys (ints where parseable).
    """
    present = {}
    for k in (
        "train_batch_size",
        "train_micro_batch_size_per_gpu",
        "gradient_accumulation_steps",
    ):
        v = raw.get(k)
        if v is None:
            continue
        try:
            present[k] = int(v)
        except (TypeError, ValueError):
            present[k] = v
    if present:
        logger.warning(
            "%s: NeoX batch keys %s are not consumed by relora_tpu "
            "(batch arithmetic is set by the training config, not the data "
            "YAML); they will be cross-checked against the mesh at startup",
            path,
            sorted(present),
        )
    tbs = present.get("train_batch_size")
    micro = present.get("train_micro_batch_size_per_gpu")
    ga = present.get("gradient_accumulation_steps")
    if isinstance(tbs, int) and isinstance(micro, int) and isinstance(ga, int):
        # world_size isn't knowable yet; consistency at ANY size requires
        # train_batch_size to be a positive multiple of micro * grad_accum
        per_rank = micro * ga
        if per_rank <= 0 or tbs <= 0 or tbs % per_rank != 0:
            logger.warning(
                "%s: inconsistent NeoX batch arithmetic: train_batch_size=%s "
                "is not a positive multiple of train_micro_batch_size_per_gpu=%s "
                "* gradient_accumulation_steps=%s (reference validates this in "
                "arguments.py:753-812)",
                path,
                tbs,
                micro,
                ga,
            )
    return present


def parse_split_string(split: str, n: int) -> List[range]:
    """'969,30,1' (or '969/30/1') -> three contiguous document ranges
    covering [0, n) (bit-parity: data_utils.get_train_valid_test_split_
    :163-187).

    The rounding correction matters: the reference subtracts the cumulative
    rounding excess from *every* bound, not just the last — clamping only
    the tail can produce a zero-width middle split at small n (e.g.
    '1,1,1' over 10 docs is [0,4,7,10] here, not [0,3,6,10]).
    """
    s = str(split)
    sep = "," if "," in s else ("/" if "/" in s else None)
    parts = [float(x) for x in s.split(sep)] if sep else [float(s)]
    while len(parts) < 3:
        parts.append(0.0)
    parts = parts[:3]
    total = sum(parts)
    if total == 0:
        raise ValueError("split must have a nonzero component")
    fracs = [p / total for p in parts]
    bounds = [0]
    for f in fracs:
        bounds.append(bounds[-1] + int(round(f * float(n))))
    diff = bounds[-1] - n
    bounds = [bounds[0]] + [b - diff for b in bounds[1:]]
    if any(b < 0 for b in bounds) or any(
        bounds[i] > bounds[i + 1] for i in range(3)
    ):
        # degenerate splits (e.g. '0,1,1' over 3 docs) make the uniform
        # correction go negative; the reference silently emits the same
        # bounds and then wraps to wrong documents — fail loudly instead
        raise ValueError(
            f"split {split!r} over {n} documents produces invalid bounds {bounds}"
        )
    return [range(bounds[i], bounds[i + 1]) for i in range(3)]


def _build_packed(
    prefix: str,
    documents: np.ndarray,
    num_samples: int,
    seq_length: int,
    seed: int,
    name: str,
    is_coordinator: bool,
    barrier,
    data_impl: str = "infer",
):
    data = open_token_dataset(prefix, data_impl)
    return PackedCausalDataset(
        name=name,
        data=data,
        documents=documents,
        num_samples=num_samples,
        seq_length=seq_length,
        seed=seed,
        is_coordinator=is_coordinator,
        barrier=barrier,
    )


def build_split_datasets(
    mcfg: MegatronDataConfig,
    num_samples: Sequence[int],
    is_coordinator: bool = True,
    barrier=None,
):
    """(train, valid, test) datasets — weighted blends of explicit path lists,
    or a split of a single corpus (parity: data_utils.py:325-441)."""
    names = ("train", "valid", "test")
    out = []
    if mcfg.train_data_paths:
        path_lists = (mcfg.train_data_paths, mcfg.valid_data_paths, mcfg.test_data_paths)
        weight_lists = (mcfg.train_data_weights, mcfg.valid_data_weights, mcfg.test_data_weights)
        for name, paths, weights, n in zip(names, path_lists, weight_lists, num_samples):
            if not paths:
                out.append(None)
                continue
            weights = weights or [1.0] * len(paths)
            w = np.asarray(weights, dtype=np.float64)
            w = w / w.sum()
            label_paths = mcfg.label_data_paths if name == "train" else None
            parts = []
            for i, p in enumerate(paths):
                data = open_token_dataset(p, mcfg.data_impl)
                docs = np.arange(len(data), dtype=np.int32)
                # each corpus supplies its weighted share of samples (+5%
                # headroom, as the blend is not exactly proportional)
                share = int(np.ceil(n * w[i] * 1.05)) + 1
                parts.append(
                    PackedCausalDataset(
                        name=f"{name}_{i}",
                        data=data,
                        documents=docs,
                        num_samples=share,
                        seq_length=mcfg.seq_length,
                        seed=mcfg.seed,
                        is_coordinator=is_coordinator,
                        barrier=barrier,
                        label_data=(
                            open_token_dataset(label_paths[i], mcfg.data_impl) if label_paths else None
                        ),
                    )
                )
            out.append(parts[0] if len(parts) == 1 else BlendableDataset(parts, w))
    else:
        data = open_token_dataset(mcfg.data_path, mcfg.data_impl)
        ranges = parse_split_string(mcfg.split, len(data))
        for name, rng_, n in zip(names, ranges, num_samples):
            if len(rng_) == 0 or n == 0:
                out.append(None)
                continue
            docs = np.arange(rng_.start, rng_.stop, dtype=np.int32)
            out.append(
                _build_packed(
                    mcfg.data_path, docs, n, mcfg.seq_length, mcfg.seed,
                    name, is_coordinator, barrier, data_impl=mcfg.data_impl,
                )
            )
    return tuple(out)


class PackedBatchIterator:
    """Batches a random-access packed dataset into device-ready arrays with
    deterministic per-host slicing and update-step rewind (parity:
    DistributedBatchSampler + start_iter, samplers.py:88-165,
    data_utils.py:443-466).

    ``interleaved=False`` gives each host a contiguous run of the global
    batch; ``True`` stripes hosts across it (the reference supports both
    slicings, samplers.py:159-165).
    """

    def __init__(
        self,
        dataset,
        *,
        microbatch: int,
        grad_accum: Optional[int] = None,
        skip_updates: int = 0,
        process_index: int = 0,
        process_count: int = 1,
        interleaved: bool = False,
    ):
        self.dataset = dataset
        self.microbatch = microbatch
        self.grad_accum = grad_accum
        self.process_index = process_index
        self.process_count = process_count
        self.interleaved = interleaved
        self._per_update = microbatch * (grad_accum or 1) * process_count
        self._start = skip_updates * self._per_update
        self._n_updates = len(dataset) // self._per_update

    def __len__(self) -> int:
        return max(0, self._n_updates - self._start // self._per_update)

    def _host_rows(self, start: int, per_host: int) -> list:
        if self.interleaved:
            idxs = range(start + self.process_index, start + self._per_update, self.process_count)
        else:
            lo = start + self.process_index * per_host
            idxs = range(lo, lo + per_host)
        return [self.dataset[i]["input_ids"] for i in idxs]

    def __iter__(self) -> Iterator[np.ndarray]:
        per_host = self.microbatch * (self.grad_accum or 1)
        for start in range(self._start, self._n_updates * self._per_update, self._per_update):
            arr = np.asarray(self._host_rows(start, per_host), dtype=np.int32)
            if self.grad_accum is None:
                yield arr
            else:
                yield arr.reshape(self.grad_accum, self.microbatch, -1)


def build_train_valid_test_iterators(cfg, trainer):
    """Wire the megatron path into the Trainer (parity:
    build_train_valid_test_dataloaders, data_utils.py:308-467)."""
    import jax

    mcfg = MegatronDataConfig.from_yaml(cfg.megatron_dataset_config)
    if mcfg.seq_length + 1 < cfg.max_length:
        logger.warning(
            f"megatron seq_length={mcfg.seq_length} < max_length={cfg.max_length}"
        )
    # the mesh is known here: solve the YAML's NeoX batch keys at the real
    # data-parallel size and compare with what's actually running
    cross_check_neox_batch(
        mcfg,
        cfg.megatron_dataset_config,
        dp_world_size=trainer.n_batch_shards,
        micro_batch=cfg.batch_size,
        grad_accum=trainer.grad_accum,
        total_batch_size=cfg.total_batch_size,
    )

    n_train = cfg.num_training_steps * cfg.total_batch_size
    # eval sees each token at most once (one pass of the split), capped at
    # what the 100M-token final eval needs (torchrun_main.py:984-987)
    n_eval = (120_000_000 // mcfg.seq_length) + 1
    barrier = None
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        barrier = lambda: multihost_utils.sync_global_devices("megatron_index_build")

    # cap each eval split at one pass of its own tokens: the packed dataset
    # otherwise up-samples across epochs to satisfy any requested count, and
    # a 100M-token final eval would loop a small split thousands of times
    def one_pass_cap(split_tokens: int) -> int:
        return max(1, min(n_eval, split_tokens // (mcfg.seq_length + 1)))

    if mcfg.train_data_paths:
        def paths_tokens(paths):
            return sum(open_token_dataset(p, mcfg.data_impl).n_tokens for p in paths) if paths else 0

        valid_tokens = paths_tokens(mcfg.valid_data_paths)
        test_tokens = paths_tokens(mcfg.test_data_paths)
    else:
        data = open_token_dataset(mcfg.data_path, mcfg.data_impl)
        sizes = np.asarray(data.sizes)
        ranges = parse_split_string(mcfg.split, len(data))
        valid_tokens = int(sizes[list(ranges[1])].sum()) if len(ranges[1]) else 0
        test_tokens = int(sizes[list(ranges[2])].sum()) if len(ranges[2]) else 0

    train_ds, valid_ds, test_ds = build_split_datasets(
        mcfg,
        (n_train, one_pass_cap(valid_tokens), one_pass_cap(test_tokens)),
        is_coordinator=jax.process_index() == 0,
        barrier=barrier,
    )

    micro = cfg.batch_size * trainer.n_batch_shards // jax.process_count()

    def train_factory():
        return iter(
            PackedBatchIterator(
                train_ds,
                microbatch=micro,
                grad_accum=trainer.grad_accum,
                skip_updates=trainer.update_step,
                process_index=jax.process_index(),
                process_count=jax.process_count(),
            )
        )

    def eval_factory():
        source = valid_ds if valid_ds is not None else test_ds
        return iter(
            PackedBatchIterator(
                source,
                microbatch=micro,
                grad_accum=None,
                process_index=jax.process_index(),
                process_count=jax.process_count(),
            )
        )

    return train_factory, (eval_factory if (valid_ds or test_ds) else None)
