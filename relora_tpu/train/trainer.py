"""Training orchestration: the TPU-native torchrun_main.main().

Owns what the reference's 700-line main() owns (torchrun_main.py:338-1018):
mesh/process setup, model+optimizer construction, warm-start / resume /
autoresume, the update loop with its two reset triggers, NaN accounting,
evaluation, checkpointing, and metrics — but with all device work factored
into the pure jitted functions of relora_tpu.train.step /
core.relora / core.optim, so the loop itself is trivial host logic.

Trigger semantics preserved exactly (SURVEY.md §3.1): resets fire at
``(update_step - scheduler_start_step) % cycle == 1`` — the step *after* the
scheduler boundary — and are gated by ``can_reset_*`` so a warm-started model
completes its first partial cycle (torchrun_main.py:874-912); ``relora``
(merge cadence) and ``cycle_length`` (optimizer/LR cadence) stay independent
knobs.
"""

from __future__ import annotations

import functools
import os
import time
from typing import Any, Iterator, Optional

import jax
import jax.numpy as jnp
import numpy as np

from relora_tpu.config.model import ModelConfig, load_model_config
from relora_tpu.config.training import TrainingConfig
from relora_tpu.core.optim import (
    build_optimizer,
    init_opt_state_sharded,
    reset_optimizer_state,
    zeroed_fraction,
)
from relora_tpu.core.partition import partition
from relora_tpu.core.relora import (
    LoraSpec,
    merge_and_reinit,
    split_param_counts,
    trainable_param_mask,
)
from relora_tpu.core.schedules import make_schedule
from relora_tpu.models.llama import LlamaForCausalLM
from relora_tpu.models.params_util import init_params, logical_partition_specs
from relora_tpu.obs import flight
from relora_tpu.obs import memory as obs_memory
from relora_tpu.obs.compile import CompileWatcher
from relora_tpu.obs.metrics import MetricsRegistry
from relora_tpu.obs.mfu import peak_flops, step_flops_from_cost_analysis
from relora_tpu.obs.tracer import Tracer
from relora_tpu.parallel.mesh import (
    MeshSpec,
    batch_sharding,
    eval_batch_sharding,
    make_mesh,
    mesh_metadata,
    param_shardings,
)
from relora_tpu.train import checkpoint as ckpt
from relora_tpu.train.resilience import LossSpikeDetector, PreemptionGuard, SpikeEvent
from relora_tpu.train.state import TrainState
from relora_tpu.train.step import make_eval_step, make_train_step, make_watch_histograms
from relora_tpu.utils import faults
from relora_tpu.utils.logging import MetricsLogger, get_logger, set_process_index

logger = get_logger(__name__)

PyTree = Any

_DTYPES = {
    "bfloat16": jnp.bfloat16,
    "bf16": jnp.bfloat16,
    "float32": jnp.float32,
    "fp32": jnp.float32,
}


#: metric keys materialized as ints (counts), everything else as floats
_INT_METRICS = frozenset({"skipped", "n_skipped"})

#: per-direction ICI bandwidth per chip (v4/v5e-class link budget), used to
#: cost the modeled collectives behind the mfu_gap "comms" share.  Like the
#: roofline constants in ops/attention_dispatch, absolute accuracy matters
#: less than the ratio against measured device time — the modeled seconds
#: are clamped to the compute fence actually observed at the flush.
ICI_BW_BYTES = float(os.environ.get("RELORA_TPU_ICI_BW", 9.0e10))


def _pull_metric_records(metric_dicts):
    """Materialize a batch of per-step device metric dicts in ONE bulk
    device->host transfer and return plain-Python records.

    This is the sanctioned landing spot for train-loop host syncs (see
    docs/static-analysis.md, RTL2xx): the fit loop accumulates device-side
    metric dicts for ``log_every`` updates and pays a single blocking round
    trip here, instead of one ``float()`` per metric per step inside the
    hot loop.  Values come back as Python floats (counts as ints) so the
    logging code downstream never touches a device array.
    """
    host = jax.device_get(list(metric_dicts))
    return [
        {k: (int(v) if k in _INT_METRICS else float(v)) for k, v in d.items()}
        for d in host
    ]


def _fence_metrics(metric_dicts) -> float:
    """Wait for the newest pending metric dict to finish computing and return
    the wait in seconds — the "compute" share of the mfu_gap waterfall.

    Lives outside the hot functions (RTL203) for the same reason as
    ``_pull_metric_records``: it runs once per ``log_every`` flush, right
    before the bulk pull, so it splits the sync the flush already pays into
    a device-wait part and a transfer part without adding a new sync point.
    The newest dict depends on every preceding step's params, so this one
    fence covers the whole window.
    """
    t0 = time.perf_counter()
    jax.block_until_ready(metric_dicts[-1])
    return time.perf_counter() - t0


def build_model(model_cfg: ModelConfig, lora: Optional[LoraSpec], cfg: TrainingConfig):
    compute_dtype = _DTYPES[cfg.dtype]
    if cfg.sp_size > 1:
        # context parallelism: sequence sharded; ring streams K/V blocks
        # (ring_zigzag additionally load-balances the causal mask),
        # ulysses all-to-alls to head sharding
        if cfg.sp_impl not in ("ring", "ring_zigzag", "ulysses"):
            raise ValueError(
                f"sp_impl must be 'ring', 'ring_zigzag' or 'ulysses', got {cfg.sp_impl!r}"
            )
        attention_impl = cfg.sp_impl
    elif cfg.flash_attention and _on_tpu():
        # explicit forcing knob: bypass the dispatcher, always the pallas arm
        attention_impl = "pallas"
    else:
        # per-shape roofline dispatch (ops/attention_dispatch.choose_training_arm):
        # flash vs xla vs naive chosen from (B, S, heads, head_dim) with
        # backward cost modeled, flash struck off-TPU automatically
        attention_impl = "auto"
    kwargs = dict(
        config=model_cfg,
        lora=lora,
        dtype=compute_dtype,
        scan_layers=True,
        remat=cfg.remat,
        remat_policy=cfg.remat_policy,
        attention_impl=attention_impl,
        logits_dtype=jnp.bfloat16 if cfg.bf16_logits else jnp.float32,
    )
    if model_cfg.family == "llama":
        return LlamaForCausalLM(**kwargs)
    from relora_tpu.models.pythia import GPTNeoXForCausalLM

    return GPTNeoXForCausalLM(**kwargs)


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform in ("tpu", "axon")
    except Exception:
        return False


class Trainer:
    """End-to-end training driver.  Typical use::

        trainer = Trainer(cfg)
        trainer.fit(train_iter_factory, eval_iter_factory)
    """

    def __init__(
        self,
        cfg: TrainingConfig,
        model_cfg: Optional[ModelConfig] = None,
        mesh=None,
    ):
        cfg.finalize()
        self.cfg = cfg
        set_process_index(jax.process_index())

        # ---- mesh / batch arithmetic -------------------------------------
        self.mesh = mesh if mesh is not None else make_mesh(
            MeshSpec(
                data=cfg.dp_size if cfg.dp_size else -1,
                fsdp=cfg.fsdp_size,
                tensor=cfg.tp_size,
                sequence=cfg.sp_size,
            )
        )
        from relora_tpu.parallel.mesh import set_current_mesh

        set_current_mesh(self.mesh)
        mesh_shape = dict(zip(self.mesh.axis_names, self.mesh.devices.shape))
        self.n_batch_shards = mesh_shape["data"] * mesh_shape["fsdp"]
        self.grad_accum = cfg.grad_accum_for(self.n_batch_shards)
        logger.info(
            f"mesh={mesh_shape} grad_accum={self.grad_accum} "
            f"global_microbatch={cfg.batch_size * self.n_batch_shards} "
            f"total_batch={cfg.total_batch_size}"
        )

        # ---- model -------------------------------------------------------
        if model_cfg is None:
            model_cfg = load_model_config(cfg.model_config or cfg.model_name_or_path)
        self.model_cfg = model_cfg
        # base kernels are only materialized when something needs them
        # (parity: need_linear_weight, torchrun_main.py:531-553)
        need_linear_weight = (
            cfg.relora is not None
            or cfg.force_keep_original
            or cfg.warmed_up_model is not None
        )
        self.lora_spec = (
            LoraSpec(
                r=cfg.lora_r,
                alpha=cfg.lora_alpha,
                dropout=cfg.lora_dropout,
                trainable_scaling=cfg.train_scaling,
                quantize=cfg.quantize,
                use_double_quant=cfg.use_double_quant,
                base_dtype=cfg.base_dtype,
                lora_only=not need_linear_weight,
                fused="auto" if cfg.lora_fused == "auto" else cfg.lora_fused == "true",
            )
            if cfg.use_peft
            else None
        )
        self.model = build_model(model_cfg, self.lora_spec, cfg)

        sample = jnp.zeros((1, cfg.max_length), jnp.int32)
        self.param_specs = logical_partition_specs(self.model, sample)
        self.shardings = param_shardings(self.mesh, self.param_specs)
        self.batch_shard = batch_sharding(self.mesh, seq_sharded=cfg.sp_size > 1)
        self.eval_batch_shard = eval_batch_sharding(self.mesh, seq_sharded=cfg.sp_size > 1)

        # ---- counters (may be overwritten by resume) ---------------------
        self.update_step = 0
        self.global_step = 0
        self.tokens_seen = 0
        self.tokens_seen_before = 0
        self.n_lora_restarts = 0
        self.n_optimizer_resets = 0
        self.n_spike_rollbacks = 0
        self._local_updates = 0
        self._resumed = False
        self._wandb_id: Optional[str] = None

        # ---- resolve resume target (parity: torchrun_main.py:374-399) ----
        self.resume_dir: Optional[str] = None
        if cfg.autoresume and cfg.save_dir and os.path.isdir(cfg.save_dir):
            training_state, self.resume_dir = ckpt.get_last_checkpoint(cfg.save_dir)
            if self.resume_dir:
                self._guard_batch_size_unchanged()
        elif cfg.resume_from:
            self.resume_dir = cfg.resume_from
            self._guard_batch_size_unchanged()

        # ---- params ------------------------------------------------------
        init_rng = jax.random.PRNGKey(cfg.seed)
        with self.mesh:
            params = jax.jit(
                lambda r: init_params(self.model, r, sample),
                out_shardings=self.shardings,
            )(init_rng)
        counts = split_param_counts(params)
        logger.info(
            f"params: total={counts['total_params']/1e6:.2f}M "
            f"trainable={counts['trainable_params']/1e6:.2f}M "
            f"lora={counts['lora_params']/1e6:.2f}M "
            f"equivalent={counts['equivalent_params']/1e6:.2f}M"
        )
        self.param_counts = counts
        self._comms_per_update_s = self._modeled_comms_per_update_s()
        if self._comms_per_update_s:
            logger.info(
                f"modeled comms: {self._comms_per_update_s * 1e3:.2f} ms/update "
                f"over ICI (mfu_gap/comms share)"
            )

        if cfg.warmed_up_model and not self.resume_dir:
            params = self._load_warm_start(params, cfg.warmed_up_model)

        # ---- optimizer + schedule ----------------------------------------
        self.trainable_mask = trainable_param_mask(params)
        if self.resume_dir:
            ts = ckpt.load_training_state(self.resume_dir)
            self.update_step = ts["update_step"]
            self.global_step = ts["global_step"]
            self.tokens_seen = ts["tokens_seen"]
            self.tokens_seen_before = ts.get("tokens_seen_before", 0)
            self.n_lora_restarts = ts.get("n_lora_restarts", 0)
            self.n_optimizer_resets = ts.get("n_optimizer_resets", 0)
            self.n_spike_rollbacks = ts.get("n_spike_rollbacks", 0)
            # a previous run's automatic spike rollback may have extended the
            # blacklist; without merging it a restart would replay the
            # poisoned window
            cfg.skip_batches |= set(ts.get("skip_batches") or ())
            self._wandb_id = ts.get("wandb_id")
            self._resumed = True
            # Keep the schedule identical across restarts: restore the
            # schedule origin instead of re-deriving it from the resume point
            # (the reference re-derives, subtly reshaping the schedule on
            # every autoresume — we persist it for bit-exact resume, the
            # reference's own oracle (f) in SURVEY.md §4).
            self.scheduler_start_step = ts.get("scheduler_start_step", self.update_step)
        else:
            if cfg.warmed_up_model:
                ws = self._warm_start_counters(cfg.warmed_up_model)
                if ws:
                    self.update_step = ws.get("update_step", 0)
                    self.global_step = ws.get("global_step", 0)
                    self.tokens_seen = ws.get("tokens_seen", 0)
            # scheduler runs over the remaining steps with a fresh first
            # warmup (parity: torchrun_main.py:679-691)
            self.scheduler_start_step = self.update_step

        self.schedule = make_schedule(
            cfg.scheduler,
            lr=cfg.lr,
            num_training_steps=cfg.num_training_steps - self.scheduler_start_step,
            warmup_steps=cfg.warmup_steps,
            min_lr_ratio=cfg.min_lr_ratio,
            cycle_length=cfg.cycle_length or cfg.relora,
            restart_warmup_steps=cfg.restart_warmup_steps,
            adjust_step=cfg.adjust_step,
        )
        self.tx = build_optimizer(
            schedule=self.schedule,
            beta1=cfg.adam_beta1,
            beta2=cfg.adam_beta2,
            eps=cfg.adam_eps,
            weight_decay=cfg.weight_decay,
        )

        with self.mesh:
            trainable, _ = partition(params, self.trainable_mask)
            opt_state = init_opt_state_sharded(
                self.tx,
                trainable,
                self.mesh,
                shardings=partition(self.shardings, self.trainable_mask)[0],
            )
        self.state = TrainState.create(params, opt_state)
        self.state = self.state.replace(step=jnp.asarray(self.update_step, jnp.int32))
        self.state = self._normalize_placement(self.state)

        if self.resume_dir and cfg.load_optimizer_state_on_resume:
            self.state = self._normalize_placement(self._restore_state(self.resume_dir))
            logger.info(f"Restored full train state from {self.resume_dir}")
        elif self.resume_dir:
            from relora_tpu.core.optim import set_schedule_count

            restored = self._restore_state(self.resume_dir)
            self.state = self.state.replace(
                params=restored.params,
                # fresh optimizer, but the LR schedule continues from the
                # checkpoint position (parity: scheduler replay,
                # torchrun_main.py:693-699)
                opt_state=set_schedule_count(
                    self.state.opt_state, self.update_step - self.scheduler_start_step
                ),
            )
            logger.info(f"Restored params (fresh optimizer) from {self.resume_dir}")

        # ---- compiled programs -------------------------------------------
        # metric LR is reported relative to the schedule origin, matching the
        # optax-internal count (both freeze on NaN-skipped updates)
        start = self.scheduler_start_step
        zigzag_ring = cfg.sp_size if (cfg.sp_size > 1 and cfg.sp_impl == "ring_zigzag") else None
        self._train_step = jax.jit(
            make_train_step(
                self.model,
                self.tx,
                self.trainable_mask,
                clip_grad_norm=cfg.clip_grad_norm,
                schedule=lambda s: self.schedule(s - start),
                grad_breakdown=cfg.wandb_watch,
                zigzag_ring=zigzag_ring,
                loss_impl=cfg.loss_impl,
                vocab_chunk=cfg.vocab_chunk,
                log_per_layer_scaling=cfg.train_scaling,
                nan_grad_steps=faults.nan_grad_steps(),
            ),
            donate_argnums=0,
        )
        self._eval_step = jax.jit(
            make_eval_step(
                self.model,
                zigzag_ring=zigzag_ring,
                loss_impl=cfg.loss_impl,
                vocab_chunk=cfg.vocab_chunk,
            )
        )
        # wandb.watch parity (torchrun_main.py:624-627): histograms run as
        # their own compiled program at eval cadence, never in the hot step
        self._watch_step = (
            jax.jit(
                make_watch_histograms(
                    self.model,
                    self.trainable_mask,
                    loss_impl=cfg.loss_impl,
                    vocab_chunk=cfg.vocab_chunk,
                    zigzag_ring=zigzag_ring,
                )
            )
            if cfg.wandb_watch
            else None
        )
        if self.lora_spec is not None:
            # prune-retrain state (relora_tpu/compress): the keep-mask is
            # computed once at the first merge past prune_start_step, then
            # baked into the merge program so every later cycle re-zeroes the
            # pruned positions before requant.  Resume restores the sidecar
            # so the holes survive a process restart.
            self._prune_mask = None
            self._prune_meta: Optional[dict] = None
            if self.resume_dir and cfg.prune_enabled:
                from relora_tpu.compress import prune as compress_prune

                mask, meta = compress_prune.load_mask(self.resume_dir)
                if mask is not None:
                    self._prune_mask = mask
                    self._prune_meta = meta
                    logger.info(
                        f"Restored prune mask from {self.resume_dir} "
                        f"(sparsity {meta.get('sparsity', 0) if meta else 0:.3f})"
                    )
            self._build_merge_fn()
        self._reset_fn = jax.jit(
            functools.partial(
                reset_optimizer_state,
                mode=cfg.optimizer_reset_mode or "zero",
                ratio=cfg.optimizer_reset_ratio,
            ),
            donate_argnums=0,
        )

        # ---- observability ----------------------------------------------
        run_config = dict(cfg.to_dict())
        run_config.update(
            {
                "model": model_cfg.to_dict(),
                "mesh": mesh_shape,
                "grad_accum": self.grad_accum,
                **{k: v / 1e6 for k, v in counts.items()},
            }
        )
        self.metrics = MetricsLogger(
            run_dir=cfg.save_dir,
            run_name=None,
            config=run_config,
            use_wandb=cfg.wandb,
            resume_id=self._wandb_id,
            source="train",  # fleet series schema: obs/fleet.py joins this file
        )
        self._wandb_id = self.metrics.run_id
        # span tracer for the update loop (data_fetch / dispatch / metric_pull
        # / checkpoint / merge / reset); finished spans land in the flight
        # recorder ring buffer for crash dumps, and optionally in a JSONL
        # stream when RELORA_TPU_TRACE_DIR is set
        trace_dir = os.environ.get("RELORA_TPU_TRACE_DIR")
        self.tracer = Tracer(
            service="train",
            jsonl_path=os.path.join(trace_dir, "train_spans.jsonl") if trace_dir else None,
        )
        self.obs = MetricsRegistry(namespace="relora_train")
        # compile telemetry: the wrapped step tracks its abstract call
        # signatures — a recompile after the first one is a steady-state
        # retrace (compile_steady_state_retraces counter, `compile` events
        # in metrics.jsonl; see docs/observability.md)
        self.compile_watcher = CompileWatcher(
            service="train", tracer=self.tracer, registry=self.obs, metrics=self.metrics
        )
        self._train_step = self.compile_watcher.wrap("train_step", self._train_step)
        # HBM accounting: live gauges polled at the metric-flush cadence, and
        # the per-pytree plan (what the resident state occupies) emitted once
        self._mem_poller = obs_memory.MemoryPoller(registry=self.obs)
        self._memory_plan = obs_memory.pytree_breakdown(
            {"params": self.state.params, "opt_state": self.state.opt_state}
        )
        self.metrics.event(
            "memory_plan",
            step=self.update_step,
            source="pytree",
            **self._memory_plan,
            **{f"live_{k}": v for k, v in self._mem_poller.poll().items()},
        )
        if cfg.save_dir:
            flight.configure(dump_dir=cfg.save_dir)
        # live MFU: measured step FLOPs (XLA cost_analysis, filled in lazily
        # on the first batch) over the device's peak; 6ND analytic fallback
        self._peak_flops = peak_flops()
        self._n_params_6nd = (
            model_cfg.num_params(include_embeddings=False)
            + model_cfg.vocab_size * model_cfg.hidden_size
        )
        self._step_flops: Optional[float] = None
        self._mfu_measured = False
        if cfg.save_dir and jax.process_index() == 0:
            os.makedirs(cfg.save_dir, exist_ok=True)
            cfg.save(os.path.join(cfg.save_dir, "training_config.yaml"))

    # ------------------------------------------------------------------
    def _build_merge_fn(self) -> None:
        """(Re)compile the merge-and-reinit program with the current prune
        mask and reset-init dial baked in.

        Rebuilt at most twice per run (construction + the first prune event)
        — merge cadence, never the hot step.  out_shardings pins the merged
        tree to the same placement as the donated input: without it a
        tp/fsdp-sharded param tree could come back replicated after a
        merge-and-reinit, silently turning every later train step into a
        resharding collective."""
        from relora_tpu.compress.resets import make_reinit_fn

        self._merge_fn = jax.jit(
            functools.partial(
                merge_and_reinit,
                spec=self.lora_spec,
                a_init=make_reinit_fn(self.cfg.reset_init),
                mask=self._prune_mask,
            ),
            donate_argnums=0,
            out_shardings=self.shardings,
        )

    def _maybe_compute_prune_mask(self) -> None:
        """First prune event: derive the fixed keep-mask from the just-merged
        base, zero the pruned positions in place, and rebake the merge
        program so every later cycle re-applies the mask before requant."""
        cfg = self.cfg
        if (
            self._prune_mask is not None
            or not cfg.prune_enabled
            or self.update_step < cfg.prune_start_step
        ):
            return
        from relora_tpu.compress import prune as compress_prune

        t0 = time.time()
        self._prune_mask = magnitude = compress_prune.magnitude_mask(
            self.state.params,
            cfg.prune_sparsity,
            scope=cfg.prune_scope,
            nm=cfg.prune_nm,
        )
        stats = compress_prune.sparsity_stats(magnitude)
        self._prune_meta = {
            "target_sparsity": cfg.prune_sparsity,
            "scope": cfg.prune_scope,
            "nm": cfg.prune_nm,
            "computed_at_step": self.update_step,
        }
        with self.mesh:
            masked = jax.jit(
                functools.partial(compress_prune.apply_mask, mask=self._prune_mask),
                donate_argnums=0,
                out_shardings=self.shardings,
            )(self.state.params)
        self.state = self.state.replace(params=masked)
        jax.block_until_ready(self.state.params)
        self._build_merge_fn()
        self.metrics.event(
            "prune_mask_computed",
            step=self.update_step,
            sparsity=stats["sparsity"],
            mask_crc32=compress_prune.mask_checksum(magnitude),
        )
        logger.info(
            f"Prune mask computed at update {self.update_step}: "
            f"{stats['sparsity']*100:.2f}% of base weights zeroed "
            f"({time.time() - t0:.2f}s)"
        )

    # ------------------------------------------------------------------
    def _restore_state(self, path: str) -> PyTree:
        """Restore a full TrainState from ``path`` onto this mesh.

        Same-topology checkpoints take Orbax's fast path (shards restored
        straight onto the recorded layout).  A checkpoint whose manifest
        records a *different* mesh shape or chip count — a preempted-and-
        resized run — goes through the elastic reshard: host-side restore,
        then re-placement under this mesh's partition rules, optimizer
        state included (train/elastic.py)."""
        from relora_tpu.train import elastic

        meta = ckpt.load_manifest_metadata(path)
        if elastic.needs_reshard(meta, self.mesh):
            ok, reason = elastic.validate_reshard(meta, self.mesh)
            if not ok:
                raise RuntimeError(f"cannot elastically resume from {path}: {reason}")
            logger.info(
                f"Elastic resume: checkpoint saved on {meta.get('chip_count')} "
                f"chip(s) {meta.get('mesh_shape')}, resharding onto "
                f"{dict(zip(self.mesh.axis_names, self.mesh.devices.shape))}"
            )
            return elastic.restore_resharded(path, self.state)
        return ckpt.restore_checkpoint(path, self.state)

    def _normalize_placement(self, tree: PyTree) -> PyTree:
        """Ensure every leaf lives on this mesh's device set: leaves already
        sharded over the full mesh are kept; stragglers (jit-placed or
        checkpoint-restored scalars committed to one device) are replicated.
        jit requires all arguments to share one device set."""
        from jax.sharding import NamedSharding, PartitionSpec

        mesh_devices = set(self.mesh.devices.flat)
        rep = NamedSharding(self.mesh, PartitionSpec())

        def fix(leaf):
            if not hasattr(leaf, "sharding"):
                return leaf
            try:
                if set(leaf.sharding.device_set) == mesh_devices:
                    return leaf
            except Exception:
                pass
            return jax.device_put(leaf, rep)

        return jax.tree_util.tree_map(fix, tree)

    def _guard_batch_size_unchanged(self) -> None:
        """Resume with a different batch size breaks the data rewind
        (parity: torchrun_main.py:710-716)."""
        import yaml

        p = os.path.join(os.path.dirname(self.resume_dir), "training_config.yaml")
        if not os.path.exists(p) and self.cfg.save_dir:
            p = os.path.join(self.cfg.save_dir, "training_config.yaml")
        if os.path.exists(p):
            with open(p) as f:
                old = yaml.safe_load(f)
            if old.get("batch_size") != self.cfg.batch_size:
                raise RuntimeError(
                    "Cannot resume from a checkpoint with a different batch size"
                )

    def _load_warm_start(self, params: PyTree, path: str) -> PyTree:
        """Full-rank weights into a (possibly LoRA) tree — the
        full-rank→ReLoRA transition (torchrun_main.py:505-553)."""
        from relora_tpu.models.hf_compat import graft_base_weights, hf_to_params

        state_dir = os.path.join(path, ckpt.STATE_SUBDIR)
        if os.path.isdir(state_dir):
            # a previous run of ours (any shape — full-rank or LoRA):
            # template-free host restore, then graft by name
            base = ckpt.restore_params_host(path)
        else:
            bin_path = os.path.join(path, "pytorch_model.bin")
            if not os.path.exists(bin_path):
                raise ValueError(f"warmed_up_model {path!r} has neither state/ nor pytorch_model.bin")
            import torch

            sd = torch.load(bin_path, map_location="cpu", weights_only=True)
            base = hf_to_params(sd, self.model_cfg, scan_layers=True)
        grafted = graft_base_weights(params, base)
        logger.info(f"Warm-started base weights from {path}")
        return grafted

    def _warm_start_counters(self, path: str) -> Optional[dict]:
        p = os.path.join(path, ckpt.TRAINING_STATE_FILE)
        if os.path.exists(p):
            import json

            with open(p) as f:
                return json.load(f)
        logger.warning(f"No training state found in {path}; counters start from zero")
        return None

    # ------------------------------------------------------------------
    def device_batch(self, local_batch: np.ndarray) -> jax.Array:
        """Host numpy -> global sharded device array.  3-D arrays are train
        updates (ga, local_micro, seq); 2-D are eval batches (micro, seq)."""
        shard = self.batch_shard if local_batch.ndim == 3 else self.eval_batch_shard
        if jax.process_count() == 1:
            return jax.device_put(local_batch, shard)
        return jax.make_array_from_process_local_data(shard, local_batch)

    def _prefetched(self, train_iter, depth: int = 2):
        """Keep ``depth`` batches already transferred to the device, so host
        reads and H2D copies overlap the running step (device_put is async;
        starting the next transfer before the current step is consumed keeps
        it off the critical path)."""
        import collections

        queue = collections.deque()
        it = iter(train_iter)
        try:
            while len(queue) < depth:
                queue.append(self.device_batch(next(it)))
        except StopIteration:
            pass
        while queue:
            out = queue.popleft()
            try:
                queue.append(self.device_batch(next(it)))
            except StopIteration:
                pass
            yield out

    # ------------------------------------------------------------------
    def _measure_step_flops(self, batch, rng) -> Optional[float]:
        """Total FLOPs of one compiled train step, from XLA's cost model.

        Runs once, lazily, on the first real batch (abstract lowering only —
        no compile, no device work).  Returns None when the backend offers no
        cost model or ``RELORA_TPU_LIVE_MFU=0``; the MFU gauge then falls
        back to the 6ND analytic estimate (docs/observability.md).

        Side effect: reuses the lowering for the train step's static HBM plan
        (``compiled.memory_analysis()`` -> a ``memory_plan`` event).  That
        path DOES compile, and an AOT compile does not warm the traced-call
        cache — ``RELORA_TPU_MEM_PLAN=0`` skips it where a duplicate compile
        of a big model is too expensive."""
        if os.environ.get("RELORA_TPU_LIVE_MFU", "1") == "0":
            return None
        try:
            def abs_of(x):
                return jax.ShapeDtypeStruct(np.shape(x), x.dtype)

            abs_args = jax.tree_util.tree_map(abs_of, (self.state, batch, rng))
            with self.mesh:
                lowered = self._train_step.lower(*abs_args)
            flops = step_flops_from_cost_analysis(lowered.cost_analysis())
        except Exception as e:  # backend-specific; never fail the run over MFU
            logger.info(f"live MFU: cost_analysis unavailable ({e}); using 6ND estimate")
            return None
        if os.environ.get("RELORA_TPU_MEM_PLAN", "1") != "0":
            try:
                with self.mesh, self.compile_watcher.expected_compiles("memory_plan"):
                    plan = obs_memory.xla_memory_plan(lowered.compile())
                if plan:
                    recon = obs_memory.reconcile(plan.get("plan_total_bytes"))
                    recon.pop("plan_total_bytes", None)  # already in the plan
                    self.metrics.event(
                        "memory_plan",
                        step=self.update_step,
                        source="xla_train_step",
                        **plan,
                        **recon,
                    )
            except Exception as e:  # a plan must never fail the run
                logger.info(f"HBM plan: memory_analysis unavailable ({e})")
        if flops:
            logger.info(f"live MFU: measured step cost {flops:.3e} FLOPs (cost_analysis)")
        return flops

    # ------------------------------------------------------------------
    def _modeled_comms_per_update_s(self) -> float:
        """Analytic per-update collective seconds for the current mesh:
        grad all-reduce over data×fsdp, fsdp param all-gather (fwd + bwd
        re-gather), and tp activation all-reduces (2 fwd + 2 bwd per layer
        per microbatch), each costed as a ring over ICI
        (``2(n-1)/n × bytes / BW``).  Zero on a single-chip mesh.  This is
        the model behind the ``mfu_gap/comms`` share: it decomposes the
        measured compute fence, it does not add to it."""
        shape = dict(zip(self.mesh.axis_names, self.mesh.devices.shape))
        n_batch = shape["data"] * shape["fsdp"]
        n_f, n_t = shape["fsdp"], shape["tensor"]
        act_bytes = jnp.dtype(_DTYPES[self.cfg.dtype]).itemsize
        ring = lambda n, nbytes: 2.0 * (n - 1) / n * nbytes
        total = 0.0
        if n_batch > 1:
            # grads sync once per update in f32, trainable params only
            total += ring(n_batch, self.param_counts["trainable_params"] * 4)
        if n_f > 1:
            # params all-gather for fwd and again for the remat'd bwd
            total += 2.0 * ring(n_f, self.param_counts["total_params"] * act_bytes)
        if n_t > 1:
            mc = self.model_cfg
            act = self.cfg.batch_size * self.cfg.max_length * mc.hidden_size * act_bytes
            total += 4.0 * mc.num_hidden_layers * self.grad_accum * ring(n_t, act)
        return total / ICI_BW_BYTES

    # ------------------------------------------------------------------
    def fit(
        self,
        train_iter: Iterator[np.ndarray],
        eval_iter_factory=None,
        train_iter_factory=None,
    ) -> dict:
        """The update loop (parity: torchrun_main.py:768-947).

        ``train_iter_factory`` (optional) rebuilds the training iterator from
        the trainer's *current* counters — required for automatic loss-spike
        rollback, which rewinds ``update_step`` and needs the data stream
        re-aligned to it.  Without it, spikes are detected and logged but not
        rolled back.  SIGTERM/SIGINT during the loop triggers a graceful
        emergency checkpoint at the next update boundary
        (``cfg.handle_preemption``); the result dict reports ``preempted``.
        """
        cfg = self.cfg
        exhausted = True  # for-else: did the data run out before the step budget?
        update_start = time.time()
        rng = jax.random.PRNGKey(cfg.seed + 1)
        saved_at = -1
        aborted = False
        preempted = False
        detector = (
            LossSpikeDetector(
                cfg.spike_threshold,
                window=cfg.spike_window,
                min_history=cfg.spike_min_history,
                patience=cfg.spike_patience,
            )
            if cfg.spike_threshold > 0
            else None
        )
        spike: Optional[SpikeEvent] = None

        from relora_tpu.utils.profiling import maybe_make_profiler

        prof = maybe_make_profiler(cfg, run_name=os.path.basename(cfg.save_dir or "run"))

        logger.info(
            f"Starting training at update step {self.update_step} "
            f"({cfg.num_training_steps - self.update_step} to go)"
        )
        # Metrics are materialized with a one-step lag: float()-ing the
        # current step's device metrics would block the host on the step's
        # completion every iteration (costly through a TPU tunnel); by
        # logging the previous step's metrics while the current one computes,
        # data loading and logging overlap device work.  With
        # cfg.log_every > 1 the lag grows to at most log_every updates and
        # all lagged records are pulled in ONE bulk transfer
        # (_pull_metric_records).  The NaN-abort check runs on materialized
        # values, so it lags by the same bound — a few extra steps before an
        # abort is harmless.
        pending: list = []  # (metrics, update_step, global_step, tokens, dt, counters, span_s)
        window_t0 = time.perf_counter()  # mfu_gap waterfall window start

        def flush_pending() -> bool:
            """Log all lagged metric records; returns False if training must
            abort.  One bulk device pull for the whole batch — keep
            float()/int() on device values out of here (RTL202).

            Also emits the mfu_gap waterfall for the flushed window: the
            flush's single sync is split into a device-wait fence (the
            "compute" share) and the transfer, and the window's wall time is
            partitioned into data_fetch / dispatch / compute / comms / host
            shares that sum to ~100% by construction (comms is the modeled
            collective time carved out of the fence; host is the residual:
            transfer, logging, python, and any eval/checkpoint cadence work
            that landed in the window)."""
            nonlocal spike, window_t0
            if not pending:
                return True
            with self.tracer.span("metric_pull", n_records=len(pending)):
                devs = [p[0] for p in pending]
                compute_s = _fence_metrics(devs)
                records = _pull_metric_records(devs)
            batch = [(m, *rest) for m, (_, *rest) in zip(records, pending)]
            pending.clear()
            now = time.perf_counter()
            wall = now - window_t0
            window_t0 = now
            data_s = sum(b[-1][0] for b in batch)
            disp_s = sum(b[-1][1] for b in batch)
            if wall > 0:
                host_s = max(0.0, wall - data_s - disp_s - compute_s)
                # comms-vs-compute split of the device fence: the modeled
                # collective seconds (clamped so a wrong model can never
                # claim more than the device time actually measured) come
                # out of the compute share, so the five shares still sum to
                # ~100% and a growing comms share reads as "the step is
                # waiting on ICI, not on the MXU"
                comms_s = min(compute_s, self._comms_per_update_s * len(batch))
                gap = {
                    "mfu_gap/window_steps": len(batch),
                    "mfu_gap/wall_s": round(wall, 4),
                    "mfu_gap/data_fetch": round(min(1.0, data_s / wall), 4),
                    "mfu_gap/dispatch": round(min(1.0, disp_s / wall), 4),
                    "mfu_gap/compute": round(min(1.0, (compute_s - comms_s) / wall), 4),
                    "mfu_gap/comms": round(min(1.0, comms_s / wall), 4),
                    "mfu_gap/host": round(min(1.0, host_s / wall), 4),
                    "compile/steady_state_retraces": self.compile_watcher.steady_state_retraces,
                }
                for key in ("data_fetch", "dispatch", "compute", "comms", "host"):
                    self.obs.set_gauge(f"mfu_gap_{key}", gap[f"mfu_gap/{key}"])
                # live HBM gauges at the same cadence (no-op on CPU; the
                # poller must never run inside the per-step loop)
                mem = self._mem_poller.poll()
                if mem["available"]:
                    gap["hbm/bytes_in_use"] = mem["bytes_in_use"]
                    gap["hbm/peak_bytes_in_use"] = mem["peak_bytes_in_use"]
                self.metrics.log(gap, step=batch[-1][2])
            for metrics, at_step, at_global, tokens_in_update, dt, counters, _span_s in batch:
                if metrics["skipped"]:
                    logger.error(
                        f"NaN update skipped at step {at_step} "
                        f"({metrics['n_skipped']} total)"
                    )
                    self.metrics.event(
                        "nan_skip", step=at_step, n_skipped=metrics["n_skipped"]
                    )
                    if metrics["n_skipped"] > cfg.nan_abort_fraction * cfg.num_training_steps:
                        logger.error("More than 5% of updates NaN-skipped; aborting")
                        return False
                loss_val = faults.perturb("loss", metrics["loss"], step=at_step)
                if detector is not None and spike is None:
                    spike = detector.update(at_step, loss_val)
                tokens_per_sec = tokens_in_update / dt
                # live MFU: measured step FLOPs when the backend's cost model
                # provided them, 6ND otherwise (same formula as bench MFU)
                if self._step_flops:
                    mfu = self._step_flops / dt / self._peak_flops
                else:
                    mfu = tokens_per_sec * 6 * self._n_params_6nd / self._peak_flops
                self.obs.set_gauge("mfu", mfu)
                self.obs.set_gauge("throughput_tokens_per_s", tokens_per_sec)
                record = {
                    "loss": loss_val,
                    "lr": metrics.get("lr", 0.0),
                    "update_step": at_step,
                    "grad_norm": metrics["grad_norm"],
                    "mfu": mfu,
                    "throughput_tokens": tokens_per_sec,
                    "throughput_examples": cfg.total_batch_size / dt,
                    "throughput_batches": self.grad_accum * self.n_batch_shards / dt,
                    # snapshotted when the record was created, so counts
                    # attribute to the update they happened at despite the lag
                    **counters,
                }
                # extra metrics (grad_norm/* breakdown, lora_scaling, ...)
                for k, v in metrics.items():
                    if k not in record and k not in ("skipped", "n_skipped"):
                        record[k] = v
                self.metrics.log(record, step=at_global)
            return True

        if self.update_step >= cfg.num_training_steps:
            # already-finished run (e.g. autoresume past the budget): don't
            # pull/transfer any data
            train_iter = iter(())
        try:
            with PreemptionGuard(enabled=cfg.handle_preemption) as guard:
              # the while wrapper exists solely for spike rollback: a rollback
              # rewinds counters and restarts the for loop on a rebuilt iterator
              while True:
                restart = False
                exhausted = True
                batches = self._prefetched(train_iter)
                while True:
                  # one "update_step" span per iteration; the explicit next() puts
                  # the data wait inside it as a "data_fetch" child (a for-loop
                  # fetches in the header, outside any span).  Two-space nesting
                  # keeps the loop body's indentation unchanged.
                  with self.tracer.span("update_step", step=self.update_step):
                    with self.tracer.span("data_fetch") as sp_fetch:
                        batch = next(batches, None)
                    if batch is None:
                        break  # data ran out; exhausted stays True (for-else parity)
                    if self.update_step >= cfg.num_training_steps:
                        exhausted = False
                        break
                    if self.update_step in cfg.skip_batches:
                        # loss-spike blacklist, manual (torchrun_main.py:772-775)
                        # or auto-extended by rollback: the batch is consumed
                        # (data stream stays aligned) but its transfer is wasted
                        # — acceptable for a rare blacklist
                        self.metrics.event("batch_skipped", step=self.update_step)
                        self.update_step += 1
                        self.global_step += self.grad_accum
                        continue

                    self.tokens_seen += int(batch.size)

                    if not self._mfu_measured:
                        # first real batch: ask XLA's cost model what one step
                        # costs, so the MFU gauge uses measured FLOPs not 6ND
                        self._mfu_measured = True
                        self._step_flops = self._measure_step_flops(
                            batch, jax.random.fold_in(rng, self.update_step)
                        )
                    with self.tracer.span("dispatch", step=self.update_step) as sp_dispatch:
                        # async dispatch: this span is enqueue cost, not device
                        # step time — the blocking pull happens in metric_pull
                        self.state, metrics = self._train_step(
                            self.state, batch, jax.random.fold_in(rng, self.update_step)
                        )
                    self.update_step += 1
                    self._local_updates += 1
                    self.global_step += self.grad_accum

                    # ---- graceful preemption --------------------------------
                    faults.tick("preempt", self.update_step)
                    if guard.requested:
                        self.metrics.event(
                            "preemption", step=self.update_step, signum=guard.signum
                        )
                        flush_pending()
                        if cfg.save_dir:
                            path = self.save(time.time() - update_start)
                            if path:
                                saved_at = self.update_step
                                self.metrics.event(
                                    "emergency_checkpoint",
                                    step=self.update_step,
                                    path=path,
                                )
                        preempted = True
                        exhausted = False
                        break

                    # ---- save -----------------------------------------------
                    if (
                        cfg.save_dir
                        and cfg.save_every > 0
                        and self._local_updates > 1
                        and self.update_step % cfg.save_every == 0
                    ):
                        if self.save(time.time() - update_start):
                            saved_at = self.update_step

                    # ---- eval -----------------------------------------------
                    if (
                        eval_iter_factory is not None
                        and cfg.eval_every > 0
                        and self.update_step % cfg.eval_every == 0
                    ):
                        with self.tracer.span("eval", step=self.update_step):
                            eval_loss, eval_tokens = self.evaluate(
                                eval_iter_factory(), cfg.eval_tokens_during_training
                            )
                        self.metrics.log(
                            {"final_eval_loss": eval_loss, "final_eval_tokens": eval_tokens},
                            step=self.global_step,
                        )
                        logger.info(f"Eval loss at step {self.update_step}: {eval_loss:.4f}")

                    # ---- wandb.watch histograms (torchrun_main.py:624-627) --
                    if (
                        self._watch_step is not None
                        and cfg.eval_every > 0
                        and self.update_step % cfg.eval_every == 0
                    ):
                        with self.tracer.span("watch_histograms", step=self.update_step):
                            hists = self._watch_step(
                                self.state.params,
                                batch[0],
                                jax.random.fold_in(rng, 2**30 + self.update_step),
                            )
                            # one bulk transfer: per-element int()/float() on device
                            # arrays would sync once per bin through the TPU tunnel
                            self.metrics.log_histograms(
                                jax.device_get(hists), step=self.global_step
                            )

                    # ---- ReLoRA merge (torchrun_main.py:874-893) ------------
                    relora_every = cfg.relora  # 0 normalized to None in finalize
                    can_merge = relora_every is not None and (
                        self._resumed or self._local_updates >= relora_every
                    )
                    if can_merge and (self.update_step - self.scheduler_start_step) % relora_every == 1:
                        t0 = time.time()
                        self.n_lora_restarts += 1
                        with self.tracer.span(
                            "relora_merge", step=self.update_step, n=self.n_lora_restarts
                        ):
                            self.state = self.state.replace(
                                params=self._merge_fn(
                                    self.state.params,
                                    jax.random.fold_in(jax.random.PRNGKey(cfg.seed + 2), self.update_step),
                                )
                            )
                            jax.block_until_ready(self.state.params)
                            # PERP prune-retrain: first eligible merge fixes
                            # the mask (later merges re-apply it inside
                            # _merge_fn before requant)
                            self._maybe_compute_prune_mask()
                        logger.info(
                            f"LoRA merge #{self.n_lora_restarts} at update {self.update_step} "
                            f"took {time.time() - t0:.2f}s"
                        )

                    # ---- optimizer reset (torchrun_main.py:895-912) ---------
                    cycle = cfg.cycle_length or cfg.relora
                    can_reset = cfg.relora is not None and cycle is not None and (
                        self._resumed or self._local_updates >= cycle
                    )
                    if can_reset and (self.update_step - self.scheduler_start_step) % cycle == 1:
                        self.n_optimizer_resets += 1
                        reset_rng = jax.random.fold_in(jax.random.PRNGKey(cfg.seed + 3), self.update_step)
                        with self.tracer.span(
                            "optimizer_reset", step=self.update_step, n=self.n_optimizer_resets
                        ):
                            self.state = self.state.replace(
                                opt_state=self._reset_fn(self.state.opt_state, rng=reset_rng)
                            )
                            z = float(zeroed_fraction(self.state.opt_state))
                        logger.info(
                            f"Optimizer reset #{self.n_optimizer_resets} "
                            f"({cfg.optimizer_reset_mode}) at update {self.update_step}: "
                            f"{z*100:.2f}% of moments zero"
                        )
                        # post-reset LR sanity (training_utils.py:391-404)
                        lr_now = float(self.schedule(jnp.asarray(self.update_step - self.scheduler_start_step)))
                        if lr_now > self.cfg.lr:
                            self.metrics.alert(
                                "Learning rate issue",
                                f"LR after reset is {lr_now} > max {self.cfg.lr}",
                            )

                    # ---- metrics (torchrun_main.py:918-943), lagged ---------
                    # flush BEFORE appending: with log_every=1 this is exactly
                    # the historical one-step lag; larger values batch up to
                    # log_every records into one device pull
                    if len(pending) >= cfg.log_every and not flush_pending():
                        exhausted = False
                        aborted = True
                        break
                    update_time = time.time() - update_start
                    update_start = time.time()
                    tokens_in_update = self.tokens_seen - self.tokens_seen_before
                    self.tokens_seen_before = self.tokens_seen
                    pending.append(
                        (
                            metrics,
                            self.update_step,
                            self.global_step,
                            tokens_in_update,
                            update_time,
                            {
                                "tokens_seen": self.tokens_seen,
                                "n_lora_restarts": self.n_lora_restarts,
                                "n_optimizer_resets": self.n_optimizer_resets,
                            },
                            # per-step host-side time for the mfu_gap
                            # waterfall (spans are closed by here)
                            (sp_fetch.duration_s or 0.0, sp_dispatch.duration_s or 0.0),
                        )
                    )
                    if prof is not None:
                        # per update step, regardless of the flush cadence
                        prof.step()

                    # ---- loss-spike rollback --------------------------------
                    if spike is not None:
                        ev, spike = spike, None
                        rolled_back = self._handle_spike(
                            ev, can_realign=train_iter_factory is not None
                        )
                        detector.reset_streak()
                        if rolled_back:
                            # drop the lagged metric records — the steps they
                            # describe were just undone
                            pending.clear()
                            restart = True
                            exhausted = False
                            break
                if restart:
                    train_iter = train_iter_factory()
                    update_start = time.time()
                    continue
                break
        except BaseException:
            # any crash inside the update loop leaves a flight dump
            # behind: the last ~2k spans/events, rendered by
            # tools/trace_report.py (docs/observability.md)
            flight.dump_on_fault("crash")
            raise
        finally:
            if prof is not None:
                # close(), not stop(): a mid-window exit must not leak
                # the process-global jax.profiler trace
                prof.close()
        if not flush_pending():
            aborted = True
        if exhausted and self.update_step < cfg.num_training_steps:
            # for-else equivalent (torchrun_main.py:945-947)
            logger.warning("Reached the end of the dataset before num_training_steps")

        # final save + eval (torchrun_main.py:956-1012)
        if cfg.save_dir and self.update_step != saved_at:
            self.save(time.time() - update_start)
        result = {
            "update_step": self.update_step,
            "tokens_seen": self.tokens_seen,
            "aborted": aborted,
            "preempted": preempted,
            "n_rollbacks": self.n_spike_rollbacks,
            "n_skipped": int(self.state.n_skipped),  # noqa: RTL202 - once, after the loop
        }
        if eval_iter_factory is not None and not preempted:
            final_loss, final_tokens = self.evaluate(
                eval_iter_factory(), target_tokens=cfg.final_eval_tokens
            )
            self.metrics.log(
                {"final_eval_loss": final_loss, "final_eval_tokens": final_tokens},
                step=self.global_step,
            )
            result["final_eval_loss"] = final_loss
        self.metrics.finish()
        self.tracer.close()  # flush + release the JSONL sink, if configured
        # fence pending async checkpoint writes before declaring the run done
        # (process exit must not truncate an in-flight save)
        ckpt.wait_for_save()
        logger.info("Training finished")
        return result

    # ------------------------------------------------------------------
    def evaluate(
        self,
        eval_iter: Iterator[np.ndarray],
        target_tokens: int = -1,
        sync_every: int = 8,
    ):
        """Token-weighted mean eval loss (parity: evaluate_model,
        torchrun_main.py:143-189; target 10M during training, 100M final,
        -1 = full set).

        Loss/token sums accumulate on-device and are pulled to the host only
        every ``sync_every`` batches (and once at the end) — the reference's
        per-batch ``.item()`` round trip is the kind of host sync the train
        loop carefully lags, and through the sandbox's device tunnel it
        dominates eval wall time.  The token target is tracked host-side from
        batch shapes (free — no device sync), so the loop drains early when
        the target is near and overshoots by at most one batch (same bound as
        the reference), not ``sync_every - 1``.
        """
        pending: list = []  # device-side partial sums, drained in one pull
        loss_sum = 0.0
        n_tokens = 0.0
        expected_tokens = 0  # host-side upper bound on device n_tokens

        def drain():
            nonlocal loss_sum, n_tokens
            if not pending:
                return
            # one stacked pull = one blocking device round trip per drain
            sums = np.asarray(
                jnp.stack(
                    [
                        jnp.sum(jnp.stack([p[k] for p in pending]))
                        for k in ("loss_sum", "n_tokens")
                    ]
                )
            )
            s_loss, s_tok = sums.tolist()  # host array -> plain floats
            loss_sum += s_loss
            n_tokens += s_tok
            pending.clear()
            if np.isnan(loss_sum):
                raise RuntimeError("NaN in evaluation loss")

        for arr in eval_iter:
            pending.append(self._eval_step(self.state.params, self.device_batch(arr)))
            # shifted-label estimate: the loss sees at most seq-1 targets per
            # row (fewer with padding), so batch*(seq-1) upper-bounds the
            # loss-token count far tighter than raw batch size.  The device
            # n_tokens is a global sum over hosts, each feeding an
            # equally-shaped local slice, so scale by process_count to keep
            # the host-side estimate an upper bound on the global count.
            shape = np.shape(arr)  # host-side metadata, no device transfer
            expected_tokens += (
                shape[0] * max(shape[-1] - 1, 1) * jax.process_count()
            )
            if len(pending) >= max(sync_every, 1) or (
                target_tokens > 0 and expected_tokens >= target_tokens
            ):
                drain()
                if target_tokens > 0:
                    if n_tokens >= target_tokens:
                        break
                    # re-arm the early-drain trigger from the true count:
                    # with padded data the host estimate overshoots, and
                    # without this reset every subsequent batch would drain
                    # (one device round trip each) until the real count
                    # caught up — exactly the per-batch sync sync_every
                    # exists to avoid
                    expected_tokens = int(n_tokens)
        drain()
        return loss_sum / max(n_tokens, 1.0), n_tokens

    # ------------------------------------------------------------------
    def _handle_spike(self, spike: SpikeEvent, can_realign: bool) -> bool:
        """Roll back to the last committed checkpoint preceding the spike and
        blacklist the poisoned update window.  Returns True when a rollback
        happened (the caller must rebuild the data iterator); on False the
        spike is logged and training continues forward."""
        cfg = self.cfg
        self.metrics.event(
            "loss_spike",
            step=spike.last_step,
            first_step=spike.first_step,
            last_step=spike.last_step,
            loss=spike.loss,
            median=spike.median,
            mad=spike.mad,
        )
        logger.error(
            f"Sustained loss spike over updates {spike.first_step}..{spike.last_step} "
            f"(loss={spike.loss:.4f}, baseline median={spike.median:.4f}, "
            f"mad={spike.mad:.4f})"
        )
        # forensics before any rollback mutates state: what was the loop
        # doing in the steps leading up to the spike?
        flight.dump_on_fault("loss_spike")
        reason = None
        if self.n_spike_rollbacks >= cfg.max_spike_rollbacks:
            reason = f"rollback budget exhausted ({cfg.max_spike_rollbacks})"
        elif not can_realign:
            reason = "no train_iter_factory to realign the data stream"
        elif not cfg.save_dir:
            reason = "no save_dir to roll back to"
        if reason is None:
            # the spike's own steps may have just been checkpointed; only a
            # checkpoint strictly before the spike is a valid target
            ckpt.wait_for_save()
            ts, target = ckpt.get_last_checkpoint(
                cfg.save_dir, before_step=spike.first_step
            )
            if target is None:
                reason = "no committed checkpoint precedes the spike"
        if reason is not None:
            logger.error(f"Loss spike NOT rolled back: {reason}")
            self.metrics.event("rollback_skipped", step=spike.last_step, reason=reason)
            return False
        # skip indices are matched against the pre-increment counter, so
        # skipping index k suppresses logged update k+1: the spiked logged
        # window [first, last] maps to indices [first-1, last-1], and the
        # margin extends the blacklist past the last observed outlier
        new_skips = set(
            range(spike.first_step - 1, spike.last_step + cfg.spike_rollback_margin)
        )
        cfg.skip_batches |= new_skips
        self.state = self._normalize_placement(self._restore_state(target))
        if self.lora_spec is not None and cfg.prune_enabled:
            # the rollback target may predate the prune event: resync the
            # mask (or its absence) from the target's sidecar so the merge
            # program matches the restored weights
            from relora_tpu.compress import prune as compress_prune

            self._prune_mask, self._prune_meta = compress_prune.load_mask(target)
            self._build_merge_fn()
        self.update_step = ts["update_step"]
        self.global_step = ts["global_step"]
        self.tokens_seen = ts["tokens_seen"]
        self.tokens_seen_before = ts.get("tokens_seen_before", self.tokens_seen)
        self.n_lora_restarts = ts.get("n_lora_restarts", self.n_lora_restarts)
        self.n_optimizer_resets = ts.get("n_optimizer_resets", self.n_optimizer_resets)
        # same trigger gating as a process-restart resume: the first partial
        # cycle after the rollback point completes before new merges/resets
        self._local_updates = 0
        self._resumed = True
        self.n_spike_rollbacks += 1
        self.metrics.event(
            "rollback",
            step=self.update_step,
            target=target,
            skip_batches=sorted(new_skips),
            n_spike_rollbacks=self.n_spike_rollbacks,
        )
        logger.warning(
            f"Rolled back to {target} (update {self.update_step}); "
            f"blacklisted batch indices {sorted(new_skips)} "
            f"(rollback {self.n_spike_rollbacks}/{cfg.max_spike_rollbacks})"
        )
        return True

    # ------------------------------------------------------------------
    def save(self, update_time: float = 0.0) -> str:
        training_state = {
            "global_step": self.global_step,
            "update_step": self.update_step,
            "tokens_seen": self.tokens_seen,
            "tokens_seen_before": self.tokens_seen_before,
            "n_lora_restarts": self.n_lora_restarts,
            "n_optimizer_resets": self.n_optimizer_resets,
            "update_time": update_time,
            "wandb_id": self._wandb_id,
            # extensions over the reference schema: the schedule origin lets
            # resume rebuild the exact same LR schedule (see __init__), and
            # the blacklist/rollback counters make automatic spike recovery
            # survive a process restart
            "scheduler_start_step": self.scheduler_start_step,
            "skip_batches": sorted(self.cfg.skip_batches),
            "n_spike_rollbacks": self.n_spike_rollbacks,
        }
        try:
            with self.tracer.span("checkpoint", step=self.update_step):
                path = ckpt.save_checkpoint(
                    self.cfg.save_dir,
                    self.update_step,
                    self.state,
                    training_state,
                    self.lora_spec,
                    retries=self.cfg.save_retries,
                    retry_backoff=self.cfg.save_retry_backoff,
                    manifest_metadata=mesh_metadata(self.mesh),
                )
        except (OSError, ValueError) as e:
            # a lost periodic checkpoint must not kill a long run: the
            # previous committed checkpoint stays the resume target and the
            # next save cadence tries again
            logger.error(f"Checkpoint save at step {self.update_step} abandoned: {e}")
            self.metrics.event("save_failed", step=self.update_step, error=str(e))
            return ""
        if getattr(self, "_prune_mask", None) is not None and jax.process_index() == 0:
            # mask sidecar rides in the checkpoint dir (and its manifest's
            # file walk): resume and the serving/export paths read it back
            from relora_tpu.compress import prune as compress_prune

            compress_prune.save_mask(path, self._prune_mask, self._prune_meta)
        ckpt.delete_old_checkpoints(self.cfg.save_dir, self.cfg.keep_checkpoints)
        return path
