"""Jittable token sampling for the decode loop.

One function, ``sample``, covers the standard policies — greedy, temperature,
top-k, top-p (nucleus) — composed in the usual order: top-k filter, then
nucleus filter, then temperature-scaled categorical.  Everything traces under
``jax.jit``:

- ``temperature`` and ``top_p`` may be traced scalars or per-row ``(B,)``
  arrays (the continuous-batching scheduler mixes requests with different
  sampling settings in one decode step).  ``temperature <= 0`` selects greedy
  for that row — computed as a ``where`` over both branches, so the compiled
  step never retraces when a greedy request shares a batch with sampled ones.
- ``top_k`` is a static int (it changes the ``lax.top_k`` shape); 0 disables.
- ``key`` is either one PRNG key shared across the batch, or a stacked
  ``(B, key_size)`` batch of per-row keys.  Per-row keys make a request's
  sample stream independent of which other requests happen to share its
  batch — fold in the request id, not the slot index.

``spec_verify_draws`` is the speculative-decoding verify sampler: one jitted
pass over the verify window's ``(B, S, V)`` logits that produces everything
the scheduler's host-side accept/rollback walk needs — greedy accept bits,
rejection-sampling accept bits (uniform vs the *filtered* target probability
of each drafted token), and per-row alternative tokens (residual sample on
rejection, plain sample for the bonus position).  All PRNG keys derive from
the same ``(uid, token_index)`` scheme the plain decode path uses, folded
with small constants per draw kind, so a request's committed stream stays
independent of batch composition and of how many drafts rode along.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

_NEG_INF = jnp.finfo(jnp.float32).min


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Per-request sampling policy.  ``temperature=0`` is greedy."""

    temperature: float = 0.0
    top_k: int = 0  # 0 disables; static (changes compiled shapes)
    top_p: float = 1.0

    def __post_init__(self):
        if self.top_k < 0:
            raise ValueError(f"top_k must be >= 0, got {self.top_k}")
        if not 0.0 < self.top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {self.top_p}")


def top_k_mask(logits: jax.Array, k: int) -> jax.Array:
    """Keep the k largest logits per row, -inf the rest.  ``k`` static."""
    if k <= 0 or k >= logits.shape[-1]:
        return logits
    kth = jax.lax.top_k(logits, k)[0][..., -1:]
    return jnp.where(logits < kth, _NEG_INF, logits)


def top_p_mask(logits: jax.Array, top_p: jax.Array) -> jax.Array:
    """Nucleus filter: keep the smallest set of tokens whose probability mass
    reaches ``top_p``, -inf the rest.  A token stays iff the mass *strictly
    before* it (descending order) is < top_p — so the argmax always survives
    and the kept set's mass is the smallest one >= top_p."""
    order = jnp.argsort(logits, axis=-1)[..., ::-1]  # descending
    sorted_logits = jnp.take_along_axis(logits, order, axis=-1)
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    mass_before = jnp.cumsum(probs, axis=-1) - probs
    keep_sorted = mass_before < jnp.asarray(top_p, jnp.float32)[..., None]
    inverse = jnp.argsort(order, axis=-1)
    keep = jnp.take_along_axis(keep_sorted, inverse, axis=-1)
    return jnp.where(keep, logits, _NEG_INF)


def sample(
    logits: jax.Array,
    key: jax.Array,
    *,
    temperature=0.0,
    top_k: int = 0,
    top_p=1.0,
) -> jax.Array:
    """Sample next-token ids ``(B,)`` from logits ``(B, V)``.

    ``temperature``/``top_p`` broadcast per-row; rows with ``temperature <= 0``
    take the argmax.  ``key`` is one key or a ``(B, ...)`` stack of keys.
    """
    logits = logits.astype(jnp.float32)
    B = logits.shape[0]
    greedy = jnp.argmax(logits, axis=-1)

    filtered = top_k_mask(logits, top_k)
    filtered = top_p_mask(filtered, jnp.broadcast_to(jnp.asarray(top_p, jnp.float32), (B,)))
    temp = jnp.broadcast_to(jnp.asarray(temperature, jnp.float32), (B,))
    scaled = filtered / jnp.maximum(temp, 1e-6)[:, None]
    if key.ndim > 1:  # per-row keys
        drawn = jax.vmap(jax.random.categorical)(key, scaled)
    else:
        drawn = jax.random.categorical(key, scaled)
    return jnp.where(temp <= 0.0, greedy, drawn)


#: fold_in constants separating the verify round's PRNG draws per
#: (uid, token_index): 1 = acceptance uniform, 2 = residual/bonus sample.
#: Each (uid, token_index, kind) is consumed at most once over a request's
#: lifetime — a rejected round never commits the indices past the rejection,
#: and the round that commits an index is the only round whose walk uses its
#: draws — so reuse across rounds never correlates committed samples.
_SPEC_ACCEPT = 1
_SPEC_ALT = 2


def spec_verify_draws(
    logits: jax.Array,
    draft: jax.Array,
    base_key: jax.Array,
    uids: jax.Array,
    start_index: jax.Array,
    k_eff: jax.Array,
    *,
    temperature,
    top_k: int = 0,
    top_p=1.0,
):
    """Everything the speculative accept/rollback walk needs, in one jit.

    Inputs: ``logits`` ``(B, S, V)`` from the verify forward (row ``i``
    predicts generated-token index ``start_index + i``), ``draft`` ``(B,
    S-1)`` the drafted candidates (``draft[:, i]`` judged by logits row
    ``i``), ``uids``/``start_index``/``k_eff`` ``(B,)`` int32 — request id,
    index of the first token this window can commit, and how many leading
    draft entries are real (the rest is padding).  ``temperature``/``top_p``
    broadcast per-row like :func:`sample`; ``top_k`` is static.

    Returns ``(accept, alt)``:

    - ``accept`` ``(B, S-1)`` bool — greedy rows accept iff the draft equals
      the row argmax; sampled rows accept with probability ``p(draft)``
      under the *same* filtered target distribution :func:`sample` draws
      from (top-k → top-p → temperature), the textbook deterministic-
      proposal rejection rule, so the committed marginal is exactly the
      target distribution.
    - ``alt`` ``(B, S)`` int32 — the token to commit when the walk stops at
      row ``i``: for ``i < k_eff`` the residual sample (target with the
      rejected draft token removed, renormalized); for ``i == k_eff`` a
      plain target sample (the bonus after full acceptance).  Greedy rows
      get the row argmax everywhere.

    Host walk per row: ``a`` = leading accepts among the first ``k_eff``
    entries; commit ``draft[:a]`` then ``alt[a]``.
    """
    logits = logits.astype(jnp.float32)
    B, S, V = logits.shape
    greedy = jnp.argmax(logits, axis=-1)  # (B, S)

    temp = jnp.broadcast_to(jnp.asarray(temperature, jnp.float32), (B,))
    top_p_b = jnp.broadcast_to(jnp.asarray(top_p, jnp.float32), (B,))
    flat = logits.reshape(B * S, V)
    filtered = top_k_mask(flat, top_k)
    filtered = top_p_mask(
        filtered, jnp.repeat(top_p_b, S)
    ).reshape(B, S, V)
    scaled = filtered / jnp.maximum(temp, 1e-6)[:, None, None]
    probs = jax.nn.softmax(scaled, axis=-1)  # (B, S, V) the target p

    # per-(row, window-slot) keys: the SAME (uid, token_index) stream the
    # plain decode path folds, built in-device to avoid B*S host fold_ins
    def row_keys(uid, start):
        def one(i):
            return jax.random.fold_in(jax.random.fold_in(base_key, uid), start + i)

        return jax.vmap(one)(jnp.arange(S, dtype=jnp.int32))

    keys = jax.vmap(row_keys)(uids.astype(jnp.int32), start_index.astype(jnp.int32))

    accept_keys = jax.vmap(jax.vmap(lambda k: jax.random.fold_in(k, _SPEC_ACCEPT)))(
        keys
    )
    alt_keys = jax.vmap(jax.vmap(lambda k: jax.random.fold_in(k, _SPEC_ALT)))(keys)
    # a row that drafted nothing commits exactly one token — the bonus draw
    # at window slot 0 — and consumes no acceptance uniform, so it uses the
    # PLAIN (uid, token_index) key there: its committed stream is bit-equal
    # to the non-window sample() path no matter which rounds carried drafts
    # for other rows (the packed scheduler relies on this invariance)
    no_draft = (k_eff.astype(jnp.int32) == 0)[:, None]  # (B, 1)
    slot0 = jnp.arange(S, dtype=jnp.int32)[None, :] == 0
    alt_keys = jnp.where((no_draft & slot0)[..., None], keys, alt_keys)

    # acceptance: rows 0..S-2 judge draft[:, 0..S-2]
    p_draft = jnp.take_along_axis(probs[:, :-1, :], draft[..., None], axis=-1)[..., 0]
    u = jax.vmap(jax.vmap(jax.random.uniform))(accept_keys[:, :-1])
    accept_sampled = u < p_draft
    accept_greedy = greedy[:, :-1] == draft
    accept = jnp.where((temp <= 0.0)[:, None], accept_greedy, accept_sampled)

    # alternative tokens: residual (draft slot zeroed, renormalized) where a
    # real draft exists, plain target at the bonus slot; categorical over
    # log-probs is invariant to the normalizer, so masking the scaled logits
    # IS the renormalized residual draw
    slot = jnp.arange(S, dtype=jnp.int32)[None, :]  # (1, S)
    has_draft = slot < k_eff.astype(jnp.int32)[:, None]  # (B, S)
    draft_full = jnp.concatenate(
        [draft, jnp.zeros((B, 1), draft.dtype)], axis=1
    )  # (B, S); last col unused (has_draft is False there)
    onehot = jax.nn.one_hot(draft_full, V, dtype=bool)
    residual_logits = jnp.where(
        has_draft[..., None] & onehot, _NEG_INF, scaled
    )
    alt_sampled = jax.vmap(jax.vmap(jax.random.categorical))(
        alt_keys, residual_logits
    )
    alt = jnp.where((temp <= 0.0)[:, None], greedy, alt_sampled)
    return accept, alt
