"""Trainable/frozen param-tree partitioning.

The train step differentiates only the trainable subtree (LoRA factors +
embeddings/norms/lm_head); the frozen base kernels are closed over — so no
gradient or optimizer state is ever materialized for them.  This is the
reference's ``requires_grad`` split (torchrun_main.py:631-633) expressed as
tree surgery, and it is what makes ReLoRA's HBM savings real on TPU.
"""

from __future__ import annotations

from typing import Any, Tuple

import jax

PyTree = Any


def _is_none(x) -> bool:
    return x is None


def partition(params: PyTree, mask: PyTree) -> Tuple[PyTree, PyTree]:
    """Split into (selected, rest); non-selected positions become None leaves
    (None is a valid empty-subtree marker for jax transformations)."""
    selected = jax.tree_util.tree_map(lambda p, m: p if m else None, params, mask)
    rest = jax.tree_util.tree_map(lambda p, m: None if m else p, params, mask)
    return selected, rest


def combine(a: PyTree, b: PyTree) -> PyTree:
    """Inverse of partition: positions that are None in ``a`` come from ``b``."""
    return jax.tree_util.tree_map(
        lambda x, y: y if x is None else x, a, b, is_leaf=_is_none
    )
