"""Headline benchmark: ReLoRA training throughput on one TPU chip.

Default config mirrors BASELINE.md benchmark 3 scaled to a single chip:
llama_1b, LoRA r=128 (the production 1B recipe's rank), seq 1024, bf16
compute, remat-over-scanned-layers, scan grad-accum train step.  Prints ONE
JSON line::

    {"metric": "...", "value": N, "unit": "tokens/sec/chip", "vs_baseline": N}

``vs_baseline`` is measured MFU / 0.5 — the reference repo publishes no
throughput numbers (BASELINE.md), so the committed target is the north-star
"≥50% MFU" from BASELINE.json; 1.0 means that target is met on this chip.
(Note: the sandbox's remote-compile tunnel rejects programs above a size
threshold, which caps microbatch at 8 here; MFU counts only the 6N model
FLOPs, so remat recompute deflates it.)

Outage behavior: a fast pre-probe initializes the device in a subprocess;
if it times out (tunnel down) or reports a cpu-only backend, the script
emits the last committed on-chip measurement from
``bench_results/last_onchip.json`` with ``detail.stale: true`` and the
reason — old-but-real signal instead of a zero.  ``BENCH_FORCE=1`` skips
the probe.

Other BASELINE.md benchmark configs are selectable by env var, e.g.
``BENCH_CONFIG=llama_250m python bench.py``.  The measurement loop itself
lives in relora_tpu.utils.benchlib (shared with scripts/bench_sweep.py).

``--mode decode`` benchmarks the inference engine instead (relora_tpu/serve):
prefill tokens/sec, steady-state decode tokens/sec, and p50/p95 per-token
latency, written to ``BENCH_serve.json`` and printed as one JSON line.
Configured by env: BENCH_SERVE_MODEL (default llama_250m), BENCH_SERVE_BATCH,
BENCH_SERVE_PROMPT_LEN, BENCH_SERVE_NEW_TOKENS.  Runs on whatever backend is
up — CPU included — so it carries no probe/stale-fallback machinery; the
device lands in the artifact for the reader to judge.

``--mode serve_load`` load-tests the online HTTP front-end (relora_tpu/serve/
server.py) end to end: boots an in-process server over a randomly initialized
model, sweeps offered QPS open-loop (uniform arrivals), then saturates it
closed-loop, and writes throughput, p50/p95 TTFT and TPOT, and rejection rate
per level to ``BENCH_http.json``.  Every paged level also records its
dispatch economics (dispatches per round, tokens per dispatch, packed token
utilization, prefill stall share) under ``detail.levels[].dispatch``, and a
``detail.packed_run`` phase re-drives the load through the single-dispatch
packed scheduler (``BENCH_HTTP_PACKED_STEP=0`` skips it).  Env:
BENCH_HTTP_MODEL (default llama_9m), BENCH_HTTP_MAX_BATCH, BENCH_HTTP_QUEUE,
BENCH_HTTP_QPS ("4,16,64"), BENCH_HTTP_DURATION, BENCH_HTTP_PROMPT_LEN,
BENCH_HTTP_NEW_TOKENS.  Runs on
any backend, CPU included — the device lands in the artifact.  With
``--router`` it additionally boots a 2-replica subprocess fleet
(``serve.py --random-init`` under ReplicaSupervisor) behind the
health-aware Router and drives the same open-loop load twice — once clean,
once SIGKILLing replica 0 mid-run — recording failover/retry counts, typed
mid-stream errors, hung requests (must be 0), and p95 TTFT for both runs
under ``detail.router``.

``--mode autoscale`` drives a low→high→low QPS ramp against an elastically
scaled subprocess fleet: one ``serve.py --random-init`` replica under
ReplicaSupervisor, the FleetCollector feeding an Autoscaler (min 1, max 2),
and the health-aware Router in front.  The burst must scale the fleet to 2,
the quiet tail back to 1, and no accepted request may be dropped across
either transition.  Records replicas-over-time, per-phase p95 TTFT, and the
dropped-request count into ``BENCH_http.json`` under
``detail.autoscale_run`` (merged — an existing serve_load artifact keeps its
other sections).  Env: BENCH_HTTP_MODEL (default llama_9m),
BENCH_AS_MAX_BATCH, BENCH_AS_LOW_QPS, BENCH_AS_HIGH_QPS, BENCH_AS_PHASE_S,
BENCH_AS_NEW_TOKENS.  Runs on any backend, CPU included — the gate's
zero-drop rule is structural (it counts requests, not time).

``--mode obs_overhead`` measures what the span tracer (relora_tpu/obs) costs
on the training hot path: the same tiny jitted train step is driven twice,
once under a real ``Tracer`` emitting the trainer's per-update spans and once
under ``NoopTracer``, best-of-N loops each.  Writes overhead percentage and
per-span cost to ``BENCH_obs.json``; the committed budget is <1% of step
time.  Env: BENCH_OBS_MODEL (default llama_9m), BENCH_OBS_STEPS,
BENCH_OBS_REPEATS, BENCH_OBS_SEQ.  Runs on any backend, CPU included.

``--mode lora_kernel`` times the three execution arms of the LoRA composite
``x@W + ((x@A)@B)*s`` (fused pallas / ordered-unfused / merged — see
relora_tpu/ops/lora_dispatch) per shape bucket, written to
``BENCH_lora.json``.  Env: BENCH_LORA_SHAPES ("M:K:N,..."), BENCH_LORA_RANKS,
BENCH_LORA_ITERS, BENCH_LORA_DTYPE (f32|bf16).  Off-TPU the fused arm runs
the pallas *interpreter* — orders of magnitude slower than XLA, reported for
parity-debugging only; arm-vs-arm conclusions need the TPU run.

``--mode compress`` runs the prune-retrain quality ladder
(relora_tpu/compress, docs/compression.md): per sparsity level it reports
the post-prune eval-loss delta, the LoRA-only retrain recovery, a
synthetic-GLUE score of the pruned backbone, and the greedy accept rate +
token parity of a pruned draft model speculating against its own dense base
(``--spec model``).  Writes ``BENCH_compress.json`` and mirrors the
model-draft entries into ``BENCH_http.json``'s ``detail.spec_runs``.  The
gated numbers are structural, so the mode runs on any backend, CPU
included.  Env: BENCH_COMPRESS_MODEL (default llama_9m),
BENCH_COMPRESS_SPARSITIES, BENCH_COMPRESS_PRETRAIN_STEPS,
BENCH_COMPRESS_RETRAIN_STEPS, BENCH_COMPRESS_GLUE_EPOCHS,
BENCH_COMPRESS_SPEC_K.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading

from relora_tpu.utils.logging import enable_xla_overlap_flags

# before any jax import: the measured step should run with the same
# async-collective/collective-matmul overlap the training entry point gets
# (no-op off-TPU or under JAX_PLATFORMS=cpu)
enable_xla_overlap_flags()

# Watchdog: if the TPU tunnel wedges (observed in this sandbox), emit the
# last committed on-chip measurement (marked stale) instead of hanging
# forever.  A daemon thread (not SIGALRM): the hang sits inside native
# device-init code where signal handlers never get a chance to run, but
# GIL-releasing native waits let threads proceed.
WATCHDOG_SECS = int(os.environ.get("BENCH_WATCHDOG_SECS", "900"))
# Fast pre-probe: a subprocess that just initializes jax.devices().  The
# observed tunnel failure mode black-holes device init, so a healthy chip
# answers in seconds while a wedged tunnel times out — fail in ~1 min, not
# after the full watchdog window.
PROBE_SECS = int(os.environ.get("BENCH_PROBE_SECS", "75"))
LAST_ONCHIP = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "bench_results", "last_onchip.json")


def _emit_stale(reason: str) -> None:
    """Emit the last committed on-chip result, marked stale, as the one
    JSON line — an outage should degrade the artifact to 'old but real',
    never to zero signal (rounds 1-4 shipped four empty artifacts).

    Always exits 2: a stale line is informative to the driver artifact
    (which records stdout regardless of exit code) but must read as a
    failure to exit-code consumers — scripts/tpu_recovery_watch.sh gates
    its 'on-chip headline' commit on rc==0, and yesterday's number must
    never be committed as a fresh measurement."""
    try:
        with open(LAST_ONCHIP) as f:
            last = json.load(f)
        last.setdefault("detail", {})
        last["detail"]["stale"] = True
        last["detail"]["stale_reason"] = reason
        last["detail"]["measured_at"] = last.pop("measured_at", "unknown")
        last["detail"]["provenance"] = last.pop("provenance", "")
        # a stale replay is not a measurement: it must never claim progress
        # against the 50%-MFU target, so the snapshot's vs_baseline is
        # dropped (tools/bench_gate.py skips stale rounds entirely)
        last.pop("vs_baseline", None)
        print(json.dumps(last))
    except Exception as e:  # no fallback snapshot — zero line, still rc=2
        print(
            json.dumps(
                {
                    "metric": "bench watchdog",
                    "value": 0,
                    "unit": "tokens/sec/chip",
                    "vs_baseline": 0,
                    "detail": {"error": reason, "fallback_error": repr(e)},
                }
            )
        )
    sys.stdout.flush()
    os._exit(2)


def _probe_device() -> tuple:
    """Initialize jax.devices() in a throwaway subprocess; return
    (platform, error) — platform '' means init failed, with error saying
    whether it timed out (tunnel down) or crashed (env/config bug, which
    waiting out an outage will not fix).  Runs with the parent's env so it
    exercises the same PJRT plugin path the real run will."""
    try:
        out = subprocess.run(
            [sys.executable, "-c",
             "import jax; print('PLATFORM=' + jax.devices()[0].platform)"],
            capture_output=True, text=True, timeout=PROBE_SECS,
        )
        for line in out.stdout.splitlines():
            if line.startswith("PLATFORM="):
                return line.split("=", 1)[1], ""
        tail = (out.stderr or "").strip().splitlines()[-3:]
        return "", (f"device-init probe exited rc={out.returncode} "
                    f"without a device: {' | '.join(tail)}")
    except subprocess.TimeoutExpired:
        return "", (f"device init did not answer within {PROBE_SECS}s "
                    "pre-probe (TPU tunnel down)")
    except OSError as e:
        return "", f"device-init probe failed to launch: {e!r}"


def _watchdog():
    _emit_stale(f"no result within {WATCHDOG_SECS}s (TPU tunnel stalled mid-run)")


# Named benchmark configs (BASELINE.md's benchmark list).  "magnitude"
# proves the pruning-reset path on-chip (run once between warmup and the
# timed window) and reports the post-reset steady-state throughput; the 1B
# recipe amortizes the reset over 1000 steps, so it is deliberately
# excluded from the per-step figure.
BENCH_CONFIGS = {
    # llama_1b defaults track the best on-chip combo.  2026-07-31 window
    # measured dots-remat + chunked CE at mb2 = 7,498.7 tok/s / 29.1% MFU
    # vs full-remat mb8's 6,920.7 / 26.85%.  dots_narrow + fused LoRA is
    # the tuned candidate for the next window: narrow-dot saves drop the
    # wide-matmul recompute that the dots policy still pays, and the fused
    # pallas LoRA arm keeps the adapter matmuls on-MXU, so the compiled
    # step's mfu_gap compute share should rise.  Env overrides
    # (BENCH_REMAT_POLICY/BENCH_MICRO_BATCH/BENCH_LOSS_IMPL/
    # BENCH_LORA_FUSED/...) still win, so the winner-replay can pin the
    # measured-best combo if the candidate regresses.
    "llama_1b": dict(
        model_name="llama_1b", micro_batch=2, grad_accum=1, seq=1024,
        remat_policy="dots_narrow", loss_impl="chunked",
    ),
    "llama_250m": dict(model_name="llama_250m", micro_batch=24, grad_accum=1, seq=512),
    "llama_1b_magnitude": dict(
        model_name="llama_1b", micro_batch=8, grad_accum=1, seq=1024, magnitude_reset=True
    ),
}
_CFG_NAME = os.environ.get("BENCH_CONFIG", "llama_1b")
if _CFG_NAME not in BENCH_CONFIGS:
    sys.exit(f"Unknown BENCH_CONFIG={_CFG_NAME!r}; choose from {sorted(BENCH_CONFIGS)}")
_CFG = BENCH_CONFIGS[_CFG_NAME]


def main() -> None:
    from relora_tpu.utils.benchlib import run_throughput_bench

    # Lever precedence: named-config defaults (the measured-best combo for
    # each config) < env overrides (BENCH_REMAT_POLICY/BENCH_MICRO_BATCH/
    # BENCH_LOSS_IMPL/BENCH_DROPOUT/BENCH_QUANTIZE/BENCH_BASE_DTYPE), so
    # the winner-replay in scripts/tpu_recovery_watch.sh can pin any combo.
    cfg = dict(_CFG)
    policy = os.environ.get("BENCH_REMAT_POLICY") or cfg.get("remat_policy", "full")
    loss_impl = os.environ.get("BENCH_LOSS_IMPL") or cfg.get("loss_impl", "dense")
    cfg.pop("remat_policy", None)
    cfg.pop("loss_impl", None)
    mb_override = os.environ.get("BENCH_MICRO_BATCH")
    if mb_override:
        cfg["micro_batch"] = int(mb_override)
    ga_override = os.environ.get("BENCH_GRAD_ACCUM")
    if ga_override:
        cfg["grad_accum"] = int(ga_override)
    dropout = float(os.environ.get("BENCH_DROPOUT", "0.1"))
    quantize = os.environ.get("BENCH_QUANTIZE") or None  # int8 | nf4 frozen base
    base_dtype = os.environ.get("BENCH_BASE_DTYPE") or None  # bf16 frozen base
    # fused-LoRA lever: "auto" (dispatch decides per shape), "1" (force the
    # pallas fused arm), "0" (force ordered-unfused)
    lora_fused_env = os.environ.get("BENCH_LORA_FUSED", "auto")
    lora_fused = {"1": True, "0": False}.get(lora_fused_env, "auto")
    res = run_throughput_bench(
        remat=True, remat_policy=policy, rank=128, loss_impl=loss_impl,
        dropout=dropout, quantize=quantize, base_dtype=base_dtype,
        lora_fused=lora_fused, **cfg
    )
    line = {
        "metric": f"{_CFG_NAME} ReLoRA r=128 seq{_CFG['seq']} bf16 "
        "training throughput",
        "value": res["tokens_per_sec"],
        "unit": "tokens/sec/chip",
        "vs_baseline": round(res["mfu"] / 0.5, 4),
        "detail": {
            "mfu": res["mfu"],
            "step_time_s": res["step_time_s"],
            "tokens_per_update": res["tokens_per_update"],
            "loss": res["loss"],
            "device": res["device"],
            "config": _CFG_NAME,
            "remat_policy": policy,
            "loss_impl": loss_impl,
            "micro_batch": cfg["micro_batch"],
            "quantize": quantize,
            "base_dtype": base_dtype,
            "lora_fused": lora_fused_env,
        },
    }
    print(json.dumps(line))
    # Refresh the stale-fallback snapshot so the next outage serves the
    # freshest real measurement (committed alongside the round's results).
    # Headline config only: a llama_250m or magnitude run must not become
    # the number _emit_stale later serves as "the" headline.
    if _CFG_NAME == "llama_1b" and "cpu" not in str(res["device"]).lower():
        try:
            import datetime

            snap = dict(line)
            snap["measured_at"] = datetime.date.today().isoformat()
            snap["provenance"] = "bench.py on-chip run"
            with open(LAST_ONCHIP, "w") as f:
                json.dump(snap, f, indent=2)
        except OSError:
            pass


def lint_main() -> None:
    """--mode lint: run the RTL static-analysis pass over the package and
    emit the finding counts to BENCH_lint.json.  Tracks footgun debt over
    time: ``findings`` should only move by deliberate baseline edits, and
    ``baseline_size`` should trend down as grandfathered violations get
    fixed.  No devices touched (stdlib AST only)."""
    import time

    from relora_tpu.analysis import RULE_CATALOG, lint_paths

    repo = os.path.dirname(os.path.abspath(__file__))
    baseline_path = os.path.join(repo, "tools", "lint_baseline.txt")
    t0 = time.monotonic()
    report = lint_paths(
        [os.path.join(repo, "relora_tpu")],
        root=repo,
        baseline=baseline_path if os.path.isfile(baseline_path) else None,
    )
    elapsed = time.monotonic() - t0
    # per-family rollup (RTL1..RTL7) so bench_gate/fleet_report can watch the
    # finding trajectory of the concurrency/fleet families independently of
    # the older JAX-footgun families
    families = {}
    for code in RULE_CATALOG:
        fam = code[:4]
        families.setdefault(
            fam, {"rules": 0, "findings": 0, "new": 0}
        )["rules"] += 1
    for f in report.findings:
        families[f.code[:4]]["findings"] += 1
    for f in report.new:
        families[f.code[:4]]["new"] += 1
    result = {
        "bench": "lint",
        "metric": "relora-lint findings over relora_tpu/",
        "value": len(report.findings),
        "unit": "findings",
        "detail": {
            "rules_run": len(RULE_CATALOG),
            "files_scanned": report.files_scanned,
            "findings": len(report.findings),
            "new": len(report.new),
            "baselined": report.baselined,
            "noqa_suppressed": report.noqa_suppressed,
            "baseline_size": report.baselined + len(report.stale_baseline),
            "stale_baseline": len(report.stale_baseline),
            "by_rule": report.rule_counts,
            "by_family": {fam: families[fam] for fam in sorted(families)},
            "elapsed_sec": round(elapsed, 3),
        },
    }
    out_path = os.path.join(repo, "BENCH_lint.json")
    with open(out_path, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps(result))


def decode_main() -> None:
    """--mode decode: benchmark the serve engine's prefill and decode steps."""
    import time

    model_name = os.environ.get("BENCH_SERVE_MODEL", "llama_250m")
    batch = int(os.environ.get("BENCH_SERVE_BATCH", "8"))
    prompt_len = int(os.environ.get("BENCH_SERVE_PROMPT_LEN", "128"))
    new_tokens = int(os.environ.get("BENCH_SERVE_NEW_TOKENS", "64"))

    import jax
    import jax.numpy as jnp
    import numpy as np

    from relora_tpu.config.model import load_model_config
    from relora_tpu.models.params_util import init_params
    from relora_tpu.serve.engine import InferenceEngine, build_decode_model

    cfg = load_model_config(model_name)
    cache_size = prompt_len + new_tokens + 8
    on_tpu = jax.default_backend() == "tpu"
    dtype = jnp.bfloat16 if on_tpu else jnp.float32
    model = build_decode_model(cfg, cache_size=cache_size, dtype=dtype)
    params = init_params(model, jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    engine = InferenceEngine(cfg, params, cache_size=cache_size, dtype=dtype)

    prompt = jax.random.randint(
        jax.random.PRNGKey(1), (batch, prompt_len), 0, cfg.vocab_size
    )
    # warm the prefill compile, then time one prefill
    logits, _ = engine.prefill(prompt)
    jax.block_until_ready(logits)
    t0 = time.perf_counter()
    logits, cache = engine.prefill(prompt)
    jax.block_until_ready(logits)
    prefill_s = time.perf_counter() - t0

    token = jnp.argmax(logits[:, -1, :], axis=-1)[:, None]
    pos = jnp.full((batch, 1), prompt_len, jnp.int32)
    # warm the decode compile (first step, excluded from the timings)
    step_logits, cache = engine.decode(cache, token, pos)
    jax.block_until_ready(step_logits)
    token = jnp.argmax(step_logits, axis=-1)[:, None]
    pos = pos + 1
    latencies = []
    for _ in range(new_tokens):
        t0 = time.perf_counter()
        step_logits, cache = engine.decode(cache, token, pos)
        jax.block_until_ready(step_logits)
        latencies.append(time.perf_counter() - t0)
        token = jnp.argmax(step_logits, axis=-1)[:, None]
        pos = pos + 1

    lat = np.asarray(latencies)
    result = {
        "metric": f"{model_name} serve decode throughput",
        "value": round(batch * len(lat) / float(lat.sum()), 2),
        "unit": "tokens/sec",
        "detail": {
            "model": model_name,
            "device": str(jax.devices()[0]),
            "dtype": "bf16" if on_tpu else "f32",
            "batch": batch,
            "prompt_len": prompt_len,
            "new_tokens": new_tokens,
            "prefill_tokens_per_sec": round(batch * prompt_len / prefill_s, 2),
            "decode_tokens_per_sec": round(batch * len(lat) / float(lat.sum()), 2),
            "per_token_latency_p50_ms": round(float(np.percentile(lat, 50)) * 1e3, 3),
            "per_token_latency_p95_ms": round(float(np.percentile(lat, 95)) * 1e3, 3),
        },
    }
    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "BENCH_serve.json")
    with open(out_path, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps(result))


def serve_load_main(router: bool = False) -> None:
    """--mode serve_load: closed+open-loop load generator against the HTTP
    serving front-end, in one process over loopback.  ``router=True`` adds
    the multi-replica failover phase (subprocess fleet + Router)."""
    import asyncio
    import time

    import numpy as np

    model_name = os.environ.get("BENCH_HTTP_MODEL", "llama_9m")
    max_batch = int(os.environ.get("BENCH_HTTP_MAX_BATCH", "4"))
    max_queue = int(os.environ.get("BENCH_HTTP_QUEUE", "8"))
    qps_levels = [float(v) for v in os.environ.get("BENCH_HTTP_QPS", "4,16,64").split(",")]
    duration = float(os.environ.get("BENCH_HTTP_DURATION", "2.0"))
    prompt_len = int(os.environ.get("BENCH_HTTP_PROMPT_LEN", "8"))
    new_tokens = int(os.environ.get("BENCH_HTTP_NEW_TOKENS", "16"))
    # paged serving (default): page-pool KV cache with chunked prefill and
    # prefix caching; BENCH_HTTP_PAGED=0 measures the contiguous baseline
    paged = os.environ.get("BENCH_HTTP_PAGED", "1") != "0"
    page_size = int(os.environ.get("BENCH_HTTP_PAGE_SIZE", "16"))
    num_pages_env = int(os.environ.get("BENCH_HTTP_NUM_PAGES", "0"))
    chunk_size = int(os.environ.get("BENCH_HTTP_CHUNK", "64"))
    # long+short mix: every Nth request carries a long prompt that opens
    # with a shared system prefix, so the paged run exercises chunked
    # prefill AND prefix-cache reuse under load
    long_prompt_len = int(os.environ.get("BENCH_HTTP_LONG_PROMPT_LEN", str(4 * prompt_len)))
    long_share = float(os.environ.get("BENCH_HTTP_LONG_SHARE", "0.25"))
    # multi-tenant sweep: tok/s + tail latency vs how many distinct adapters
    # the same offered load touches (0 = lora-enabled engine, all-base
    # requests, isolating the grouped-path overhead). "" disables the sweep.
    adapter_counts = [
        int(v)
        for v in os.environ.get("BENCH_HTTP_ADAPTER_COUNTS", "0,2,4").split(",")
        if v.strip()
    ]

    import jax
    import jax.numpy as jnp

    from relora_tpu.config.model import load_model_config
    from relora_tpu.models.params_util import init_params
    from relora_tpu.serve.engine import InferenceEngine, build_decode_model
    from relora_tpu.serve.scheduler import (
        ContinuousBatchingScheduler,
        PagedContinuousBatchingScheduler,
    )
    from relora_tpu.serve.server import GenerateServer

    cfg = load_model_config(model_name)
    max_prompt = max(prompt_len, long_prompt_len if long_share > 0 else 0)
    cache_size = 1 << (max_prompt + new_tokens + 8 - 1).bit_length()
    model = build_decode_model(cfg, cache_size=cache_size)
    params = init_params(model, jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    # paged runs sweep the kv_dtype dial so the artifact shows the int8
    # slot-count / TTFT / TPOT effect next to bf16 (first dtype is the
    # headline run the gate reads)
    kv_dtypes = (
        [d.strip() for d in os.environ.get("BENCH_HTTP_KV_DTYPES", "bf16,int8").split(",") if d.strip()]
        if paged
        else ["bf16"]
    )

    def build_stack(kv_dtype: str, spec: str = "off", spec_k: int = 0, packed: bool = False):
        if paged:
            num_pages = num_pages_env or (max_batch * (cache_size // page_size) + 1)
            # packed mode: budget = every decode window + one chunk of prefill
            window = (spec_k + 1) if spec != "off" else 1
            budget = max_batch * window + chunk_size if packed else None
            eng = InferenceEngine(
                cfg, params, cache_size=cache_size,
                page_size=page_size, num_pages=num_pages, chunk_size=chunk_size,
                kv_dtype=kv_dtype, spec_k=spec_k, token_budget=budget,
            )
            eng.warmup(max_batch, packed=packed)
            sched = PagedContinuousBatchingScheduler(
                eng, max_batch=max_batch, spec=spec, packed=packed
            )
        else:
            eng = InferenceEngine(cfg, params, cache_size=cache_size)
            buckets = sorted({prompt_len} | ({long_prompt_len} if long_share > 0 else set()))
            eng.warmup(max_batch, prompt_buckets=tuple(buckets))
            sched = ContinuousBatchingScheduler(eng, max_batch=max_batch)
        return eng, sched, GenerateServer(sched, port=0, max_queue=max_queue)

    engine, scheduler, server = build_stack(kv_dtypes[0])

    rng = np.random.RandomState(0)
    prompts = [
        [int(t) for t in rng.randint(0, cfg.vocab_size, size=prompt_len)]
        for _ in range(64)
    ]
    # long prompts: identical system prefix (half the length) + random tail
    system_prefix = [int(t) for t in rng.randint(0, cfg.vocab_size, size=long_prompt_len // 2)]
    long_prompts = [
        system_prefix
        + [int(t) for t in rng.randint(0, cfg.vocab_size, size=long_prompt_len - len(system_prefix))]
        for _ in range(16)
    ]
    long_every = int(round(1.0 / long_share)) if long_share > 0 else 0

    def pick_prompt(i: int) -> list:
        if long_every and i % long_every == 0:
            return long_prompts[(i // long_every) % len(long_prompts)]
        return prompts[i % len(prompts)]

    # the adapter sweep swaps this per run; None = no "adapter" body field
    adapter_for = {"fn": None}

    async def one_request(i: int, port: int = 0) -> dict:
        payload = {
            "prompt": pick_prompt(i),
            "max_new_tokens": new_tokens,
            "stream": True,
        }
        if adapter_for["fn"] is not None:
            name = adapter_for["fn"](i)
            if name is not None:
                payload["adapter"] = name
        body = json.dumps(payload).encode()
        t_send = time.perf_counter()
        reader, writer = await asyncio.open_connection("127.0.0.1", port or server.port)
        writer.write(
            (
                "POST /v1/generate HTTP/1.1\r\nHost: bench\r\n"
                "Content-Type: application/json\r\n"
                f"Content-Length: {len(body)}\r\nConnection: close\r\n\r\n"
            ).encode()
            + body
        )
        await writer.drain()
        status = int((await reader.readline()).split()[1])
        while (await reader.readline()).strip():
            pass  # headers
        token_times, finish, error_event = [], None, None
        if status == 200:
            buf = b""
            while True:
                chunk = await reader.read(4096)
                if not chunk:
                    break
                buf += chunk
                while b"\n\n" in buf:
                    raw, buf = buf.split(b"\n\n", 1)
                    if not raw.startswith(b"data: ") or raw == b"data: [DONE]":
                        continue
                    event = json.loads(raw[6:])
                    if "token" in event:
                        token_times.append(time.perf_counter())
                    elif "finish_reason" in event:
                        finish = event
                    elif "error" in event:
                        error_event = event["error"]
        writer.close()
        try:
            await writer.wait_closed()
        except (ConnectionError, OSError):
            pass
        return {
            "status": status,
            "t_send": t_send,
            "token_times": token_times,
            "tokens": len(finish["tokens"]) if finish else 0,
            "error_event": error_event,
        }

    def summarize(level, results, wall: float) -> dict:
        done = [r for r in results if r["status"] == 200 and r["tokens"]]
        rejected = [r for r in results if r["status"] == 429]
        ttfts = [r["token_times"][0] - r["t_send"] for r in done if r["token_times"]]
        tpots = [
            b - a
            for r in done
            for a, b in zip(r["token_times"], r["token_times"][1:])
        ]
        pct = lambda xs, q: round(float(np.percentile(xs, q)) * 1e3, 3) if xs else None
        return {
            "offered": level,
            "sent": len(results),
            "completed": len(done),
            "rejected_429": len(rejected),
            "reject_rate": round(len(rejected) / max(len(results), 1), 4),
            "achieved_qps": round(len(done) / wall, 2),
            "throughput_tokens_per_s": round(sum(r["tokens"] for r in done) / wall, 2),
            "ttft_p50_ms": pct(ttfts, 50),
            "ttft_p95_ms": pct(ttfts, 95),
            "tpot_p50_ms": pct(tpots, 50),
            "tpot_p95_ms": pct(tpots, 95),
        }

    async def open_loop(qps: float) -> dict:
        interval, n = 1.0 / qps, max(1, int(duration * qps))
        tasks = []
        t0 = time.perf_counter()
        for i in range(n):
            delay = i * interval - (time.perf_counter() - t0)
            if delay > 0:
                await asyncio.sleep(delay)
            tasks.append(asyncio.ensure_future(one_request(i)))
        results = list(await asyncio.gather(*tasks))
        return summarize(f"{qps:g} qps", results, time.perf_counter() - t0)

    async def closed_loop(workers: int) -> dict:
        results = []
        t0 = time.perf_counter()
        stop = t0 + duration

        async def worker(w: int) -> None:
            i = w
            while time.perf_counter() < stop:
                r = await one_request(i)
                results.append(r)
                i += workers
                if r["status"] == 429:
                    await asyncio.sleep(0.05)

        await asyncio.gather(*(worker(w) for w in range(workers)))
        return summarize(f"closed:{workers}", results, time.perf_counter() - t0)

    def level_paging_stats(before: dict) -> dict:
        """Per-level pool pressure: peak utilization since the level started
        plus the level's own prefix-cache hit rate (counter deltas)."""
        alloc = scheduler.allocator
        stats = {
            "kv_pages_peak": alloc.peak_used,
            "kv_pages_total": alloc.num_pages - 1,  # null page is not usable
            "cache_utilization_peak": round(alloc.peak_used / (alloc.num_pages - 1), 4),
        }
        pc = scheduler.prefix_cache
        if pc is not None:
            lookups = pc.lookups - before["lookups"]
            hits = pc.hits - before["hits"]
            stats["prefix_lookups"] = lookups
            stats["prefix_hits"] = hits
            stats["prefix_hit_rate"] = round(hits / max(lookups, 1), 4)
        return stats

    def level_dispatch_stats(before: dict) -> dict:
        """Per-level dispatch economics from counter deltas: how many model
        dispatches a scheduler round cost, how full each dispatch was, and
        the share of wall time the level spent stalled on prefill."""
        after = scheduler.dispatch_stats()
        rounds = after["rounds"] - before["rounds"]
        disp = after["model_dispatches"] - before["model_dispatches"]
        tok = after["tokens_total"] - before["tokens_total"]
        real = after["tokens_real"] - before["tokens_real"]
        admit = after["admit_time_s"] - before["admit_time_s"]
        decode = after["decode_time_s"] - before["decode_time_s"]
        return {
            "mode": after["mode"],
            "rounds": rounds,
            "model_dispatches": disp,
            "dispatches_per_round": round(disp / max(rounds, 1), 4),
            "tokens_per_dispatch": round(tok / max(disp, 1), 4),
            "packed_token_utilization": round(real / max(tok, 1), 4),
            "prefill_stall_share": round(admit / max(admit + decode, 1e-9), 4),
        }

    async def run_level(coro) -> dict:
        if not paged:
            return await coro
        pc = scheduler.prefix_cache
        before = {
            "lookups": pc.lookups if pc is not None else 0,
            "hits": pc.hits if pc is not None else 0,
        }
        before_disp = scheduler.dispatch_stats()
        scheduler.allocator.peak_used = scheduler.allocator.used_pages
        row = await coro
        row["paging"] = level_paging_stats(before)
        row["dispatch"] = level_dispatch_stats(before_disp)
        return row

    async def bench() -> list:
        serve_task = asyncio.ensure_future(
            server.serve_forever(install_signal_handlers=False)
        )
        while not server.started.is_set():
            await asyncio.sleep(0.01)
            if serve_task.done():
                serve_task.result()  # surface startup errors
        rows = []
        for qps in qps_levels:
            rows.append(await run_level(open_loop(qps)))
        rows.append(await run_level(closed_loop(max_batch + max_queue)))
        server.begin_drain()
        await serve_task
        return rows

    # -- multi-replica failover phase (--router) ------------------------------

    async def guarded_request(i: int, port: int, results: list) -> None:
        """one_request that can never hang the bench: a request still open
        after 90s is recorded as hung — the exact failure the router layer
        exists to prevent."""
        try:
            r = await asyncio.wait_for(one_request(i, port=port), timeout=90.0)
        except asyncio.TimeoutError:
            r = {
                "status": -1, "t_send": 0.0, "token_times": [],
                "tokens": 0, "error_event": None, "hung": True,
            }
        except (ConnectionError, OSError) as e:
            r = {
                "status": -2, "t_send": 0.0, "token_times": [],
                "tokens": 0, "error_event": repr(e),
            }
        results.append(r)

    def router_phase() -> dict:
        """2 serve.py --random-init replicas under ReplicaSupervisor behind
        the Router; the same open-loop load twice — clean, then with replica
        0 SIGKILLed mid-run."""
        import signal as _signal
        import tempfile
        import threading as _threading

        from relora_tpu.serve.router import Router
        from relora_tpu.serve.supervisor import ReplicaSupervisor

        here = os.path.dirname(os.path.abspath(__file__))
        workdir = tempfile.mkdtemp(prefix="bench_router_")
        sup = ReplicaSupervisor(
            [
                sys.executable, os.path.join(here, "serve.py"),
                "--model_config", model_name, "--random-init",
                "--max-batch", str(max_batch), "--max-queue", str(max_queue),
                "--no-warmup",
            ],
            2,
            workdir,
            backoff_base_s=0.1,
            backoff_cap_s=1.0,
            backoff_jitter=0.0,
            poll_interval_s=0.05,
        )
        rtr = Router(
            sup.endpoints, port=0, probe_interval_s=0.1,
            retry_backoff_s=0.02, failure_threshold=2, cooldown_s=0.2,
        )
        rtr_thread = _threading.Thread(
            target=lambda: asyncio.run(rtr.serve_forever()), daemon=True
        )
        qps = qps_levels[0] if qps_levels else 4.0
        r_duration = max(duration, 4.0)

        async def drive(level: str, kill_at) -> dict:
            interval, n = 1.0 / qps, max(1, int(r_duration * qps))
            results, tasks = [], []
            killed = False
            t0 = time.perf_counter()
            for i in range(n):
                delay = i * interval - (time.perf_counter() - t0)
                if delay > 0:
                    await asyncio.sleep(delay)
                if kill_at is not None and not killed and time.perf_counter() - t0 >= kill_at:
                    sup.send_signal(0, _signal.SIGKILL)
                    killed = True
                tasks.append(asyncio.ensure_future(guarded_request(i, rtr.port, results)))
            await asyncio.gather(*tasks)
            row = summarize(level, results, time.perf_counter() - t0)
            row["typed_errors"] = sum(1 for r in results if r.get("error_event"))
            row["hung_requests"] = sum(1 for r in results if r.get("hung"))
            return row

        async def warm() -> None:
            # no --no-warmup-free lunch: pay each replica's prefill-bucket
            # compiles (long prompt = i 0, short = i 1) outside the timed runs
            for _rid, (_h, p) in sorted(sup.endpoints().items()):
                if p:
                    await one_request(0, port=p)
                    await one_request(1, port=p)

        restarted = False
        try:
            sup.start()
            rtr_thread.start()
            if not rtr.started.wait(30):
                raise RuntimeError("router failed to start")
            deadline = time.monotonic() + 180.0
            while time.monotonic() < deadline:
                if sum(st.healthy for st in rtr.replicas.values()) >= 2:
                    break
                time.sleep(0.1)
            else:
                raise RuntimeError(f"fleet never became healthy: {sup.status()}")
            asyncio.run(warm())
            clean = asyncio.run(drive("router:clean", None))
            kill = asyncio.run(drive("router:kill", r_duration * 0.3))
            # the killed replica must come back and be routable again
            deadline = time.monotonic() + 120.0
            while time.monotonic() < deadline:
                if (
                    sup.status()["r0"]["restarts"] >= 1
                    and sum(st.healthy for st in rtr.replicas.values()) >= 2
                ):
                    restarted = True
                    break
                time.sleep(0.2)
            snap = rtr.stats.snapshot()
        finally:
            rtr.begin_shutdown()
            rtr_thread.join(30)
            sup.stop()

        failovers = int(sum(v for k, v in snap.items() if k.startswith("failovers_total")))
        retries = int(snap.get("retries_total", 0))
        sent = clean["sent"] + kill["sent"]
        return {
            "replicas": 2,
            "offered_qps": qps,
            "duration_s_per_level": r_duration,
            "clean": clean,
            "kill": kill,
            "failover_count": failovers,
            "retries_total": retries,
            "retry_rate": round(retries / max(sent, 1), 4),
            "midstream_errors": int(
                sum(v for k, v in snap.items() if k.startswith("midstream_errors_total"))
            ),
            "hung_requests": clean["hung_requests"] + kill["hung_requests"],
            "replica0_restarted": restarted,
        }

    rows = asyncio.run(bench())
    dtype_runs = {}
    if paged:
        def dtype_entry(eng, run_rows) -> dict:
            pk = max(run_rows, key=lambda r: r["throughput_tokens_per_s"])
            return {
                "kv_cache_bytes": eng.pool_bytes(),
                "kv_bytes_per_token": round(eng.kv_bytes_per_token(), 4),
                "page_bytes": eng.pool_bytes() // eng.num_pages,
                # the slot-count effect: pages one GiB of pool HBM would hold
                "pages_per_gib": int((1 << 30) // max(eng.pool_bytes() // eng.num_pages, 1)),
                "peak_throughput_tokens_per_s": pk["throughput_tokens_per_s"],
                "ttft_p50_ms_at_peak": pk["ttft_p50_ms"],
                "tpot_p50_ms_at_peak": pk["tpot_p50_ms"],
                "levels": run_rows,
            }

        dtype_runs[kv_dtypes[0]] = dtype_entry(engine, rows)
        for kv_dtype in kv_dtypes[1:]:
            engine, scheduler, server = build_stack(kv_dtype)
            dtype_runs[kv_dtype] = dtype_entry(engine, asyncio.run(bench()))
    # speculative-decoding sweep (paged only): each level rebuilds the stack
    # on the headline kv_dtype with the given draft mode/K and reruns the
    # load levels — "off" reuses the headline run (same configuration)
    spec_runs = {}
    if paged:
        spec_levels = [
            s.strip()
            for s in os.environ.get(
                "BENCH_HTTP_SPEC_LEVELS", "off,ngram:2,ngram:4,ngram:8"
            ).split(",")
            if s.strip()
        ]

        def spec_entry(run_rows, stats) -> dict:
            pk = max(run_rows, key=lambda r: r["throughput_tokens_per_s"])
            return {
                "mode": stats["mode"],
                "k": stats["k"],
                "drafted": stats["drafted"],
                "accepted": stats["accepted"],
                "accept_rate": stats["accept_rate"],
                "effective_tokens_per_s": pk["throughput_tokens_per_s"],
                "ttft_p50_ms_at_peak": pk["ttft_p50_ms"],
                "tpot_p50_ms_at_peak": pk["tpot_p50_ms"],
                "levels": run_rows,
            }

        for level in spec_levels:
            if level == "off":
                spec_runs["off"] = spec_entry(
                    rows,
                    {"mode": "off", "k": 0, "drafted": 0, "accepted": 0, "accept_rate": 0.0},
                )
                continue
            mode, _, kstr = level.partition(":")
            engine, scheduler, server = build_stack(
                kv_dtypes[0], spec=mode, spec_k=int(kstr or "4")
            )
            spec_runs[level] = spec_entry(asyncio.run(bench()), scheduler.spec_stats())
    # packed single-dispatch run (paged only): same headline kv_dtype and
    # load levels with the token-budget packed scheduler — the artifact the
    # gate compares against the sequential headline (TTFT must not regress)
    packed_run = None
    if paged and os.environ.get("BENCH_HTTP_PACKED_STEP", "1") != "0":
        engine, scheduler, server = build_stack(kv_dtypes[0], packed=True)
        p_rows = asyncio.run(bench())
        pk = max(p_rows, key=lambda r: r["throughput_tokens_per_s"])
        packed_run = {
            "token_budget": engine.token_budget,
            "buckets": list(engine.packed_buckets()),
            "peak_throughput_tokens_per_s": pk["throughput_tokens_per_s"],
            "ttft_p50_ms_at_peak": pk["ttft_p50_ms"],
            "ttft_p95_ms_at_peak": pk["ttft_p95_ms"],
            "tpot_p50_ms_at_peak": pk["tpot_p50_ms"],
            "dispatch": scheduler.dispatch_stats(),
            "levels": p_rows,
        }
    # disaggregated handoff run (paged only): an in-process prefill-role ->
    # decode-role scheduler pair drains the long+short mix through the real
    # wire framing and compares against one mixed scheduler.  The numbers
    # the gate reads are structural (token parity, drops, int8-vs-bf16
    # migrated-bytes ratio — counts, not wall time), so the rule holds
    # off-TPU too.
    disagg_run = None
    if paged and os.environ.get("BENCH_HTTP_DISAGG", "1") != "0":
        from relora_tpu.serve import wire as _wire
        from relora_tpu.serve.scheduler import Request as _Request

        n_disagg = int(os.environ.get("BENCH_HTTP_DISAGG_REQUESTS", "24"))
        disagg_threshold = (
            (prompt_len + long_prompt_len) // 2 if long_share > 0 else prompt_len + 1
        )
        disagg_reqs = [
            _Request(uid=i, prompt=pick_prompt(i), max_new_tokens=new_tokens)
            for i in range(n_disagg)
        ]

        def disagg_drain(kv_dtype: str) -> dict:
            num_pages = num_pages_env or (max_batch * (cache_size // page_size) + 1)
            eng = InferenceEngine(
                cfg, params, cache_size=cache_size,
                page_size=page_size, num_pages=num_pages, chunk_size=chunk_size,
                kv_dtype=kv_dtype,
            )
            eng.warmup(max_batch, migrate=True)
            mk = lambda role: PagedContinuousBatchingScheduler(
                eng, max_batch=max_batch, role=role, key=jax.random.PRNGKey(1)
            )
            t0 = time.perf_counter()
            baseline = mk("mixed").run(disagg_reqs)
            mixed_s = time.perf_counter() - t0
            donor, recv = mk("prefill"), mk("decode")
            completions, handoffs = {}, []
            donor.migration_sink = lambda record, entries: handoffs.append(
                (int(record["uid"]), _wire.encode_page_run(record, entries))
            ) or True
            finish = lambda c: completions.__setitem__(c.uid, c)
            for req in disagg_reqs:
                pool_sched = donor if len(req.prompt) >= disagg_threshold else recv
                pool_sched.submit(req, on_finish=finish)
            t0 = time.perf_counter()
            # bounded: a wedged drain surfaces as dropped_requests, not a hang
            for _ in range(64 * (n_disagg + 1) * (new_tokens + 1)):
                if not (donor.has_work() or recv.has_work() or handoffs):
                    break
                if donor.has_work():
                    donor.step()
                waiting = []
                for uid, blob in handoffs:
                    try:
                        record, arrays = _wire.decode_page_run(blob)
                        recv.submit_migrated(record, arrays, on_finish=finish)
                        donor.migration_commit(uid, len(blob))
                    except RuntimeError:
                        waiting.append((uid, blob))  # receiver full: wait
                    except Exception as e:
                        donor.migration_failed(uid, str(e))
                handoffs[:] = waiting
                if recv.has_work():
                    recv.step()
            disagg_s = time.perf_counter() - t0
            parity = len(completions) == len(baseline) and all(
                uid in completions and completions[uid].tokens == c.tokens
                for uid, c in baseline.items()
            )
            return {
                "kv_dtype": kv_dtype,
                "requests": len(disagg_reqs),
                "token_parity": parity,
                "dropped_requests": len(baseline) - len(completions),
                "migrated_inserts": recv._migrated_inserts,
                "pages_migrated": donor._pages_migrated,
                "migration_bytes": donor._migration_bytes,
                "migration_failures": donor._migration_failures,
                "mixed_drain_s": round(mixed_s, 3),
                "disagg_drain_s": round(disagg_s, 3),
            }

        d_runs = {d: disagg_drain(d) for d in ("int8", "bf16")}
        bf16_bytes = d_runs["bf16"]["migration_bytes"]
        disagg_run = {
            "classify_threshold": disagg_threshold,
            "runs": d_runs,
            "migrated_bytes_ratio_int8_vs_bf16": (
                round(d_runs["int8"]["migration_bytes"] / bf16_bytes, 4)
                if bf16_bytes
                else None
            ),
        }
    # -- multi-tenant adapter sweep -------------------------------------------
    # Each count rebuilds the stack with a lora-enabled engine, an
    # AdapterRegistry preloaded with `count` tenants (distinct factor
    # scalings of the same shapes — perf, not quality), and re-drives the
    # load levels with requests round-robining over the tenants.

    def build_adapter_stack(num_adapters: int):
        from relora_tpu.core.relora import LoraSpec
        from relora_tpu.serve.adapters import AdapterRegistry, extract_lora_factors

        lspec = LoraSpec(r=int(os.environ.get("BENCH_HTTP_ADAPTER_RANK", "8")), alpha=16)
        slots = max(2, num_adapters + 1)
        lmodel = build_decode_model(cfg, cache_size=cache_size, lora=lspec)
        lparams = init_params(lmodel, jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
        if paged:
            num_pages = num_pages_env or (max_batch * (cache_size // page_size) + 1)
            eng = InferenceEngine(
                cfg, lparams, cache_size=cache_size,
                page_size=page_size, num_pages=num_pages, chunk_size=chunk_size,
                lora=lspec, adapter_slots=slots,
            )
            eng.warmup(max_batch)
        else:
            eng = InferenceEngine(
                cfg, lparams, cache_size=cache_size, lora=lspec, adapter_slots=slots
            )
            buckets = sorted({prompt_len} | ({long_prompt_len} if long_share > 0 else set()))
            eng.warmup(max_batch, prompt_buckets=tuple(buckets))
        # preload after warmup: warmup's compile-priming zero-write targets
        # the last slot and would clobber a tenant loaded first
        reg = AdapterRegistry(None, slots, expected_r=lspec.r, writer=eng.adapter_writer())
        base_factors = extract_lora_factors(lparams)
        for g in range(num_adapters):
            factors = jax.tree_util.tree_map(
                lambda t, _g=g: t * (0.5 + 0.25 * _g), base_factors
            )
            reg.preload(f"t{g}", factors, lspec.scale)
        sched_cls = PagedContinuousBatchingScheduler if paged else ContinuousBatchingScheduler
        sched = sched_cls(eng, max_batch=max_batch, adapter_registry=reg)
        return eng, sched, GenerateServer(sched, port=0, max_queue=max_queue), reg

    adapter_runs = {}
    for count in adapter_counts:
        engine, scheduler, server, adapter_registry = build_adapter_stack(count)
        adapter_for["fn"] = (
            (lambda i, _c=count: f"t{i % _c}") if count else (lambda i: None)
        )
        run_rows = asyncio.run(bench())
        adapter_for["fn"] = None
        pk = max(run_rows, key=lambda r: r["throughput_tokens_per_s"])
        reg_stats = adapter_registry.stats()
        adapter_runs[str(count)] = {
            "adapters": count,
            "adapter_slots": adapter_registry.num_slots,
            "peak_throughput_tokens_per_s": pk["throughput_tokens_per_s"],
            "ttft_p95_ms_at_peak": pk["ttft_p95_ms"],
            "tpot_p95_ms_at_peak": pk["tpot_p95_ms"],
            "slot_hit_rate": reg_stats["hit_rate"],
            "evictions_total": reg_stats["evictions_total"],
            "levels": run_rows,
        }

    router_detail = router_phase() if router else None
    peak = max(rows, key=lambda r: r["throughput_tokens_per_s"])
    saturated = max(rows, key=lambda r: r["reject_rate"])
    result = {
        "bench": "serve_load",
        "metric": f"{model_name} HTTP serving peak throughput "
        f"({'paged' if paged else 'contiguous'} KV, "
        f"max_batch={max_batch}, max_queue={max_queue})",
        "value": peak["throughput_tokens_per_s"],
        "unit": "tokens/sec",
        "detail": {
            "model": model_name,
            "device": str(jax.devices()[0]),
            "max_batch": max_batch,
            "max_queue": max_queue,
            "prompt_len": prompt_len,
            "long_prompt_len": long_prompt_len if long_share > 0 else 0,
            "long_share": long_share,
            "new_tokens": new_tokens,
            "duration_s_per_level": duration,
            "paged": paged,
            **(
                {
                    "page_size": page_size,
                    "num_pages": engine.num_pages,
                    "chunk_size": engine.chunk_size,
                    "kv_dtype": kv_dtypes[0],
                    "kv_dtype_runs": dtype_runs,
                    "spec_runs": spec_runs,
                    **({"packed_run": packed_run} if packed_run is not None else {}),
                    **({"disagg_run": disagg_run} if disagg_run is not None else {}),
                }
                if paged
                else {}
            ),
            "reject_rate_at_saturation": saturated["reject_rate"],
            "adapter_runs": adapter_runs,
            "levels": rows,
            **({"router": router_detail} if router_detail is not None else {}),
        },
    }
    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "BENCH_http.json")
    with open(out_path, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps(result))


def autoscale_main() -> None:
    """--mode autoscale: QPS ramp against an elastically scaled fleet.

    One serve.py replica under ReplicaSupervisor, the FleetCollector feeding
    an Autoscaler (min 1, max 2), the Router in front.  Three open-loop
    phases — low, burst, low — then a settle wait; the artifact records the
    replica timeline, per-phase p95 TTFT, and how many requests were dropped
    (no terminal response).  tools/bench_gate.py holds dropped at zero and
    requires both scale transitions to have happened."""
    import asyncio
    import tempfile
    import threading
    import time

    from relora_tpu.obs.fleet import FleetCollector, SeriesStore
    from relora_tpu.serve.autoscale import Autoscaler, AutoscalerPolicy
    from relora_tpu.serve.router import Router
    from relora_tpu.serve.supervisor import ReplicaSupervisor

    model_name = os.environ.get("BENCH_HTTP_MODEL", "llama_9m")
    max_batch = int(os.environ.get("BENCH_AS_MAX_BATCH", "2"))
    max_queue = int(os.environ.get("BENCH_AS_QUEUE", "16"))
    prompt_len = int(os.environ.get("BENCH_HTTP_PROMPT_LEN", "8"))
    new_tokens = int(os.environ.get("BENCH_AS_NEW_TOKENS", "8"))
    low_qps = float(os.environ.get("BENCH_AS_LOW_QPS", "1"))
    high_qps = float(os.environ.get("BENCH_AS_HIGH_QPS", "12"))
    phase_s = float(os.environ.get("BENCH_AS_PHASE_S", "8"))
    settle_s = float(os.environ.get("BENCH_AS_SETTLE_S", "45"))

    here = os.path.dirname(os.path.abspath(__file__))
    workdir = tempfile.mkdtemp(prefix="bench_autoscale_")
    sup = ReplicaSupervisor(
        [
            sys.executable, os.path.join(here, "serve.py"),
            "--model_config", model_name, "--random-init",
            "--max-batch", str(max_batch), "--max-queue", str(max_queue),
            "--no-warmup",
        ],
        1,
        workdir,
        backoff_base_s=0.1,
        backoff_cap_s=1.0,
        backoff_jitter=0.0,
        poll_interval_s=0.05,
        drain_timeout_s=30.0,
    )
    store = SeriesStore()
    collector = FleetCollector(sup.endpoints, store=store, cadence_s=0.25)
    sup.on_event = lambda event, idx, detail: collector.record_supervisor_event(
        event, idx, str(detail)
    )
    policy = AutoscalerPolicy(
        min_replicas=1,
        max_replicas=2,
        # TTFT on the CPU bench is dominated by on-demand compiles, not
        # capacity — park the target high so queue depth drives the ramp
        ttft_p95_target_s=float(os.environ.get("BENCH_AS_TTFT_TARGET_S", "30")),
        queue_depth_high=2.0,
        slot_util_high=0.95,
        burn_window_s=1.5,
        idle_window_s=5.0,
        cooldown_s=3.0,
    )
    autoscaler = Autoscaler(policy, sup, store, interval_s=0.25)
    rtr = Router(
        sup.endpoints, port=0, probe_interval_s=0.1,
        retry_backoff_s=0.02, failure_threshold=2, cooldown_s=0.2,
    )
    rtr_thread = threading.Thread(
        target=lambda: asyncio.run(rtr.serve_forever()), daemon=True
    )

    # replica-count timeline: change points only, seconds since ramp start
    timeline: list = []
    t0 = time.monotonic()
    sampler_stop = threading.Event()

    def sample_replicas() -> None:
        while not sampler_stop.is_set():
            n = sup.n_live()
            if not timeline or timeline[-1][1] != n:
                timeline.append((round(time.monotonic() - t0, 2), n))
            sampler_stop.wait(0.1)

    async def one_request(i: int) -> dict:
        """POST one streamed generate through the router; classify the
        outcome: ok (finish + [DONE]), rejected (HTTP 429/503 — typed
        backpressure, not data loss), or dropped (no terminal response)."""
        body = json.dumps(
            {
                "prompt": [(i * 7) % 50 + 2] * prompt_len,
                "max_new_tokens": new_tokens,
                "stream": True,
            }
        ).encode()
        t_send = time.perf_counter()
        try:
            reader, writer = await asyncio.open_connection("127.0.0.1", rtr.port)
            writer.write(
                (
                    "POST /v1/generate HTTP/1.1\r\nHost: bench\r\n"
                    "Content-Type: application/json\r\n"
                    f"Content-Length: {len(body)}\r\nConnection: close\r\n\r\n"
                ).encode()
                + body
            )
            await writer.drain()
            status = int((await reader.readline()).split()[1])
            while (await reader.readline()).strip():
                pass  # headers
            ttft, done = None, False
            if status == 200:
                buf = b""
                while True:
                    chunk = await reader.read(4096)
                    if not chunk:
                        break
                    buf += chunk
                    while b"\n\n" in buf:
                        raw, buf = buf.split(b"\n\n", 1)
                        if not raw.startswith(b"data: "):
                            continue
                        if raw == b"data: [DONE]":
                            done = True
                        elif ttft is None and b'"token"' in raw:
                            ttft = time.perf_counter() - t_send
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass
        except (ConnectionError, OSError, IndexError, ValueError):
            return {"outcome": "dropped", "ttft": None}
        except asyncio.TimeoutError:
            return {"outcome": "dropped", "ttft": None}
        if status == 200 and done:
            return {"outcome": "ok", "ttft": ttft}
        if status in (429, 503):
            return {"outcome": "rejected", "ttft": None}
        return {"outcome": "dropped", "ttft": None}

    async def drive_phase(name: str, qps: float) -> dict:
        interval, n = 1.0 / qps, max(1, int(phase_s * qps))
        tasks = []
        t_start = time.perf_counter()
        for i in range(n):
            delay = i * interval - (time.perf_counter() - t_start)
            if delay > 0:
                await asyncio.sleep(delay)
            tasks.append(
                asyncio.ensure_future(asyncio.wait_for(one_request(i), 60.0))
            )
        results = []
        for t in tasks:
            try:
                results.append(await t)
            except asyncio.TimeoutError:
                results.append({"outcome": "dropped", "ttft": None})
        ttfts = sorted(r["ttft"] for r in results if r["ttft"] is not None)
        p95 = ttfts[min(len(ttfts) - 1, int(0.95 * len(ttfts)))] if ttfts else None
        return {
            "phase": name,
            "offered_qps": qps,
            "sent": len(results),
            "ok": sum(r["outcome"] == "ok" for r in results),
            "rejected": sum(r["outcome"] == "rejected" for r in results),
            "dropped": sum(r["outcome"] == "dropped" for r in results),
            "ttft_p95_ms": round(p95 * 1e3, 1) if p95 is not None else None,
            "replicas_at_end": sup.n_live(),
        }

    phases = []
    try:
        sup.start()
        collector.start()
        rtr_thread.start()
        if not rtr.started.wait(30):
            raise RuntimeError("router failed to start")
        deadline = time.monotonic() + 180.0
        while time.monotonic() < deadline:
            if sum(st.healthy for st in rtr.replicas.values()) >= 1:
                break
            time.sleep(0.1)
        else:
            raise RuntimeError(f"fleet never became healthy: {sup.status()}")
        # pay the single replica's compile buckets outside the timed phases
        asyncio.run(one_request(0))
        autoscaler.start()
        # rebase the clock before the sampler thread starts, so every
        # change-point is in seconds since ramp start
        t0 = time.monotonic()
        timeline.append((0.0, sup.n_live()))
        threading.Thread(target=sample_replicas, daemon=True).start()
        phases.append(asyncio.run(drive_phase("low", low_qps)))
        phases.append(asyncio.run(drive_phase("burst", high_qps)))
        phases.append(asyncio.run(drive_phase("low_tail", low_qps)))
        # idle settle: the quiet tail plus cooldown must bring the fleet
        # back to the floor before the run is scored
        deadline = time.monotonic() + settle_s
        while time.monotonic() < deadline and sup.n_live() > 1:
            time.sleep(0.25)
    finally:
        sampler_stop.set()
        # the settle loop exits the instant n_live drops — record the final
        # count ourselves, the sampler may have been stopped before its next poll
        n_final = sup.n_live()
        if not timeline or timeline[-1][1] != n_final:
            timeline.append((round(time.monotonic() - t0, 2), n_final))
        autoscaler.stop()
        rtr.begin_shutdown()
        rtr_thread.join(30)
        collector.stop()
        sup.stop()

    events = [
        {
            "t": round(e.get("_time", 0.0), 2),
            "event": e.get("_event"),
            "action": e.get("action"),
            "reason": e.get("reason"),
        }
        for e in store.events()
        if str(e.get("_event", "")).startswith("autoscale_")
    ]
    max_seen = max(n for _, n in timeline)
    run = {
        "model": model_name,
        "max_batch": max_batch,
        "low_qps": low_qps,
        "high_qps": high_qps,
        "phase_s": phase_s,
        "phases": phases,
        "replica_timeline": [list(p) for p in timeline],
        "max_replicas_seen": max_seen,
        "final_replicas": timeline[-1][1],
        "scaled_up": max_seen >= 2,
        "scaled_down": timeline[-1][1] == 1,
        "dropped_requests": sum(p["dropped"] for p in phases),
        "autoscale_events": events[-60:],
    }

    # merge into BENCH_http.json: a prior serve_load artifact keeps its
    # levels/spec/packed sections, only autoscale_run is replaced
    out_path = os.path.join(here, "BENCH_http.json")
    doc = None
    try:
        with open(out_path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError):
        doc = None
    if not isinstance(doc, dict):
        doc = {
            "bench": "serve_autoscale",
            "metric": f"{model_name} elastic fleet 1->2->1 resize under QPS ramp",
            "value": run["phases"][1]["ok"] if len(run["phases"]) > 1 else 0,
            "unit": "requests",
            "detail": {},
        }
    doc.setdefault("detail", {})["autoscale_run"] = run
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=2)
    print(json.dumps({"autoscale_run": run}))


def lora_kernel_main() -> None:
    """--mode lora_kernel: per-shape step time of the three LoRA composite
    arms (fused pallas / ordered-unfused / merged), plus what the dispatch
    cost model would pick.  Like --mode decode, runs on whatever backend is
    up; off-TPU the fused arm is the interpreter (reported, but not a
    performance claim — the artifact records the device)."""
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from relora_tpu.ops.lora_dispatch import (
        choose_arm,
        choose_grouped_arm,
        lora_matmul,
        lora_matmul_grouped,
        plan_blocks,
    )

    on_tpu = jax.default_backend() == "tpu"
    # CPU-interpret fused arms are slow: default to small buckets off-TPU.
    default_shapes = "8:2048:2048,512:2048:2048,4096:2048:2048" if on_tpu else (
        "8:512:512,128:512:512,512:512:512"
    )
    shapes = [
        tuple(int(v) for v in bucket.split(":"))
        for bucket in os.environ.get("BENCH_LORA_SHAPES", default_shapes).split(",")
    ]
    ranks = [int(v) for v in os.environ.get("BENCH_LORA_RANKS", "8,128").split(",")]
    iters = int(os.environ.get("BENCH_LORA_ITERS", "20" if on_tpu else "5"))
    dtype_name = os.environ.get("BENCH_LORA_DTYPE", "bf16" if on_tpu else "f32")
    dtype = jnp.bfloat16 if dtype_name == "bf16" else jnp.float32

    def time_arm(fn, *operands) -> float:
        jax.block_until_ready(fn(*operands))  # compile outside the window
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*operands)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / iters

    key = jax.random.PRNGKey(0)
    buckets = []
    for M, K, N in shapes:
        for r in ranks:
            ks = jax.random.split(jax.random.fold_in(key, M * 131 + r), 4)
            x = jax.random.normal(ks[0], (M, K), dtype)
            w = jax.random.normal(ks[1], (K, N), dtype)
            a = jax.random.normal(ks[2], (K, r), dtype) * 0.01
            b = jax.random.normal(ks[3], (r, N), dtype) * 0.01
            scale = 0.25
            row = {"M": M, "K": K, "N": N, "r": r,
                   "planned_blocks": plan_blocks(M, N)}
            for arm in ("fused", "ordered", "merged"):
                fn = jax.jit(
                    lambda x, w, a, b, _arm=arm: lora_matmul(
                        x, w, a, b, scale, arm=_arm, dtype=dtype
                    )
                )
                row[f"{arm}_ms"] = round(time_arm(fn, x, w, a, b) * 1e3, 4)
            nbytes = jnp.dtype(dtype).itemsize
            row["model_choice"] = choose_arm(
                M, K, N, r, nbytes, nbytes, fused_available=on_tpu
            )
            row["measured_best"] = min(
                ("fused", "ordered", "merged"), key=lambda arm: row[f"{arm}_ms"]
            )
            buckets.append(row)

    # multi-tenant grouped buckets: the three grouped arms per
    # (B, K, N, r, distinct-adapters).  B rows round-robin over G adapter
    # slots; off-TPU the scalar-prefetch kernel is the interpreter so the
    # default shapes stay small (the dispatch model routes to "gathered"
    # there anyway — model_choice records it).
    group_counts = [
        int(v) for v in os.environ.get("BENCH_LORA_GROUPS", "1,4").split(",") if v.strip()
    ]
    grouped_default = "8:2048:2048,256:2048:2048" if on_tpu else "8:512:512,32:512:512"
    grouped_shapes = [
        tuple(int(v) for v in bucket.split(":"))
        for bucket in os.environ.get("BENCH_LORA_GROUP_SHAPES", grouped_default).split(",")
    ]
    nbytes = jnp.dtype(dtype).itemsize
    grouped_buckets = []
    for B, K, N in grouped_shapes:
        for r in ranks:
            for G in group_counts:
                S = max(G, 1)
                ks = jax.random.split(jax.random.fold_in(key, B * 977 + r * 31 + G), 4)
                x = jax.random.normal(ks[0], (B, K), dtype)
                w = jax.random.normal(ks[1], (K, N), dtype)
                a_stack = jax.random.normal(ks[2], (S, K, r), dtype) * 0.01
                b_stack = jax.random.normal(ks[3], (S, r, N), dtype) * 0.01
                scale_stack = jnp.full((S,), 0.25, dtype)
                idx = jnp.arange(B, dtype=jnp.int32) % S
                row = {"B": B, "K": K, "N": N, "r": r, "distinct_adapters": G}
                for arm in ("grouped", "gathered", "looped"):
                    fn = jax.jit(
                        lambda x, w, a, b, s, i, _arm=arm: lora_matmul_grouped(
                            x, w, a, b, s, i, arm=_arm
                        )
                    )
                    row[f"{arm}_ms"] = round(
                        time_arm(fn, x, w, a_stack, b_stack, scale_stack, idx) * 1e3, 4
                    )
                row["model_choice"] = choose_grouped_arm(
                    B, K, N, r, G, nbytes, nbytes, grouped_available=on_tpu
                )
                row["measured_best"] = min(
                    ("grouped", "gathered", "looped"), key=lambda arm: row[f"{arm}_ms"]
                )
                grouped_buckets.append(row)

    top = buckets[-1]
    result = {
        "metric": f"fused LoRA kernel speedup vs unfused "
        f"(M={top['M']} K={top['K']} N={top['N']} r={top['r']}, {dtype_name})",
        "value": round(top["ordered_ms"] / top["fused_ms"], 4),
        "unit": "x",
        "detail": {
            "device": str(jax.devices()[0]),
            "backend": jax.default_backend(),
            "fused_is_interpret": not on_tpu,
            "dtype": dtype_name,
            "iters": iters,
            "buckets": buckets,
            "grouped_buckets": grouped_buckets,
        },
    }
    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "BENCH_lora.json")
    with open(out_path, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps(result))


def attention_main() -> None:
    """--mode attention: per-shape step time of the serving attention arms
    against a real page pool — naive (gather + masked einsum) vs the fused
    paged-decode kernel, each over bf16-stored and int8-quantized pools —
    plus causal prefill arms (naive / xla / pallas flash) and what the
    ops/attention_dispatch cost model would pick.  Mirrors BENCH_lora.json:
    off-TPU the pallas arms run the interpreter (``is_interpret`` flagged in
    the artifact — a correctness record, not a performance claim)."""
    import time

    import jax
    import jax.numpy as jnp

    from relora_tpu.ops.attention import (
        dot_product_attention,
        flash_block_size,
        paged_cached_attention,
        paged_decode_attention,
    )
    from relora_tpu.ops.attention_dispatch import choose_arm, choose_training_arm
    from relora_tpu.ops.quant import quantize_kv_page

    on_tpu = jax.default_backend() == "tpu"
    # decode shapes are (B, S_kv); CPU-interpret fused arms are slow, so
    # default small off-TPU
    decode_default = "4:1024,8:2048" if on_tpu else "2:128,4:256"
    prefill_default = "1:1024,1:2048" if on_tpu else "1:128,1:256"
    decode_shapes = [
        tuple(int(v) for v in s.split(":"))
        for s in os.environ.get("BENCH_ATTN_DECODE_SHAPES", decode_default).split(",")
    ]
    prefill_shapes = [
        tuple(int(v) for v in s.split(":"))
        for s in os.environ.get("BENCH_ATTN_PREFILL_SHAPES", prefill_default).split(",")
    ]
    heads = int(os.environ.get("BENCH_ATTN_HEADS", "8"))
    kv_heads = int(os.environ.get("BENCH_ATTN_KV_HEADS", "4"))
    head_dim = int(os.environ.get("BENCH_ATTN_HEAD_DIM", "64"))
    page_size = int(os.environ.get("BENCH_ATTN_PAGE_SIZE", "16"))
    iters = int(os.environ.get("BENCH_ATTN_ITERS", "20" if on_tpu else "3"))
    dtype_name = os.environ.get("BENCH_ATTN_DTYPE", "bf16" if on_tpu else "f32")
    dtype = jnp.bfloat16 if dtype_name == "bf16" else jnp.float32

    def time_arm(fn, *operands) -> float:
        jax.block_until_ready(fn(*operands))  # compile outside the window
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*operands)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / iters

    key = jax.random.PRNGKey(0)
    buckets = []
    for B, S_kv in decode_shapes:
        if S_kv % page_size:
            continue
        W = S_kv // page_size
        num_pages = B * W + 1
        ks = jax.random.split(jax.random.fold_in(key, B * 131 + S_kv), 3)
        q = jax.random.normal(ks[0], (B, 1, heads, head_dim), dtype)
        pool_k = jax.random.normal(ks[1], (num_pages, page_size, kv_heads, head_dim), dtype)
        pool_v = jax.random.normal(ks[2], (num_pages, page_size, kv_heads, head_dim), dtype)
        # each row owns its own W pages (1-based: page 0 is the null page)
        bt = 1 + jnp.arange(B * W, dtype=jnp.int32).reshape(B, W)
        pos = jnp.full((B, 1), S_kv - 1, jnp.int32)
        qk, k_scale = quantize_kv_page(pool_k)
        qv, v_scale = quantize_kv_page(pool_v)

        row = {
            "kind": "decode", "B": B, "S_kv": S_kv, "heads": heads,
            "kv_heads": kv_heads, "head_dim": head_dim, "page_size": page_size,
        }
        naive16 = jax.jit(lambda q, k, v, bt, pos: paged_cached_attention(q, k, v, bt, pos))
        row["naive_bf16_ms"] = round(time_arm(naive16, q, pool_k, pool_v, bt, pos) * 1e3, 4)
        fused16 = jax.jit(
            lambda q, k, v, bt, pos: paged_decode_attention(
                q, k, v, bt, pos, interpret=not on_tpu
            )
        )
        row["paged_decode_bf16_ms"] = round(time_arm(fused16, q, pool_k, pool_v, bt, pos) * 1e3, 4)
        naive8 = jax.jit(
            lambda q, k, v, bt, pos, ks, vs: paged_cached_attention(
                q, k, v, bt, pos, k_scale=ks, v_scale=vs
            )
        )
        row["naive_int8_ms"] = round(
            time_arm(naive8, q, qk, qv, bt, pos, k_scale, v_scale) * 1e3, 4
        )
        fused8 = jax.jit(
            lambda q, k, v, bt, pos, ks, vs: paged_decode_attention(
                q, k, v, bt, pos, k_scale=ks, v_scale=vs, interpret=not on_tpu
            )
        )
        row["paged_decode_int8_ms"] = round(
            time_arm(fused8, q, qk, qv, bt, pos, k_scale, v_scale) * 1e3, 4
        )
        for kv_bytes, tag in ((jnp.dtype(dtype).itemsize, "bf16"), (1, "int8")):
            row[f"model_choice_{tag}"] = choose_arm(
                B, 1, S_kv, heads, kv_heads, head_dim, page_size, kv_bytes,
                fused_available=on_tpu, allow=("naive", "paged_decode"),
            )
        row["measured_best"] = min(
            ("naive_bf16", "paged_decode_bf16", "naive_int8", "paged_decode_int8"),
            key=lambda a: row[f"{a}_ms"],
        )
        buckets.append(row)

    for B, S in prefill_shapes:
        ks = jax.random.split(jax.random.fold_in(key, B * 977 + S), 3)
        q = jax.random.normal(ks[0], (B, S, heads, head_dim), dtype)
        k = jax.random.normal(ks[1], (B, S, kv_heads, head_dim), dtype)
        v = jax.random.normal(ks[2], (B, S, kv_heads, head_dim), dtype)
        row = {
            "kind": "prefill", "B": B, "S": S, "heads": heads,
            "kv_heads": kv_heads, "head_dim": head_dim,
            "flash_block": flash_block_size(S, S),
        }
        for impl in ("naive", "xla") + (("pallas",) if on_tpu else ()):
            fn = jax.jit(
                lambda q, k, v, _impl=impl: dot_product_attention(
                    q, k, v, causal=True, impl=_impl
                )
            )
            row[f"{impl}_ms"] = round(time_arm(fn, q, k, v) * 1e3, 4)
        row["model_choice"] = choose_arm(
            B, S, S, heads, kv_heads, head_dim, page_size,
            jnp.dtype(dtype).itemsize, fused_available=on_tpu,
        )
        # what the training path (impl="auto" fwd+bwd) would run at this shape
        row["training_choice"] = choose_training_arm(
            B, S, heads, kv_heads, head_dim,
            act_bytes=jnp.dtype(dtype).itemsize, fused_available=on_tpu,
        )
        buckets.append(row)

    decode_rows = [r for r in buckets if r["kind"] == "decode"]
    top = decode_rows[-1] if decode_rows else None
    result = {
        "bench": "attention",
        "metric": (
            f"paged-decode fused kernel speedup vs naive gather "
            f"(int8 pool, B={top['B']} S_kv={top['S_kv']}, {dtype_name})"
            if top
            else "paged-decode attention (no decode buckets)"
        ),
        "value": (
            round(top["naive_int8_ms"] / top["paged_decode_int8_ms"], 4) if top else 0.0
        ),
        "unit": "x",
        "detail": {
            "device": str(jax.devices()[0]),
            "backend": jax.default_backend(),
            "is_interpret": not on_tpu,
            "dtype": dtype_name,
            "iters": iters,
            "page_size": page_size,
            "buckets": buckets,
        },
    }
    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "BENCH_attn.json")
    with open(out_path, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps(result))


def _collector_overhead_ab() -> dict:
    """Fleet-collector scrape cost on a live replica: closed-loop tok/s A/B.

    Boots one in-process GenerateServer over a tiny random-init model, then
    drives closed-loop generation with the FleetCollector alternately off
    and on (scraping ``/metrics`` + ``/healthz`` at a sub-second cadence —
    far hotter than the supervisor's 1s default, so the measurement bounds
    production).  Arms are interleaved and best-of so both see the same
    thermal/scheduler conditions; overhead is the on-arm throughput loss,
    clipped at zero (scrapes ride the idle event loop, so small negative
    deltas are pure noise)."""
    import asyncio
    import time

    import jax
    import jax.numpy as jnp

    from relora_tpu.config.model import load_model_config
    from relora_tpu.models.params_util import init_params
    from relora_tpu.obs.fleet import FleetCollector
    from relora_tpu.serve.engine import InferenceEngine, build_decode_model
    from relora_tpu.serve.scheduler import ContinuousBatchingScheduler
    from relora_tpu.serve.server import GenerateServer

    model_name = os.environ.get("BENCH_OBS_SERVE_MODEL", "llama_9m")
    duration = float(os.environ.get("BENCH_OBS_SERVE_DURATION", "2.0"))
    cadence = float(os.environ.get("BENCH_OBS_CADENCE_S", "0.25"))
    ab_repeats = int(os.environ.get("BENCH_OBS_AB_REPEATS", "3"))
    prompt_len, new_tokens, workers = 8, 16, 4

    cfg = load_model_config(model_name)
    cache_size = 1 << (prompt_len + new_tokens + 8 - 1).bit_length()
    model = build_decode_model(cfg, cache_size=cache_size)
    params = init_params(model, jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    engine = InferenceEngine(cfg, params, cache_size=cache_size)
    engine.warmup(workers, prompt_buckets=(prompt_len,))
    scheduler = ContinuousBatchingScheduler(engine, max_batch=workers)
    server = GenerateServer(scheduler, port=0, max_queue=2 * workers)

    async def one_request(i: int) -> int:
        body = json.dumps(
            {"prompt": [(i * 7 + j) % cfg.vocab_size for j in range(prompt_len)],
             "max_new_tokens": new_tokens, "stream": False}
        ).encode()
        reader, writer = await asyncio.open_connection("127.0.0.1", server.port)
        writer.write(
            (
                "POST /v1/generate HTTP/1.1\r\nHost: bench\r\n"
                "Content-Type: application/json\r\n"
                f"Content-Length: {len(body)}\r\nConnection: close\r\n\r\n"
            ).encode()
            + body
        )
        await writer.drain()
        status = int((await reader.readline()).split()[1])
        while (await reader.readline()).strip():
            pass
        payload = await reader.read()
        writer.close()
        try:
            await writer.wait_closed()
        except (ConnectionError, OSError):
            pass
        if status != 200:
            return 0
        return len(json.loads(payload).get("tokens", []))

    async def closed_loop_tok_s() -> float:
        tokens = 0
        t0 = time.perf_counter()
        stop = t0 + duration

        async def worker(w: int) -> None:
            nonlocal tokens
            i = w
            while time.perf_counter() < stop:
                tokens += await one_request(i)
                i += workers

        await asyncio.gather(*(worker(w) for w in range(workers)))
        return tokens / (time.perf_counter() - t0)

    async def bench() -> dict:
        serve_task = asyncio.ensure_future(
            server.serve_forever(install_signal_handlers=False)
        )
        while not server.started.is_set():
            await asyncio.sleep(0.01)
            if serve_task.done():
                serve_task.result()
        await closed_loop_tok_s()  # warm both arms' code paths
        off_runs, on_runs, scrapes = [], [], 0
        for _ in range(ab_repeats):
            off_runs.append(await closed_loop_tok_s())
            coll = FleetCollector(
                lambda: {"r0": ("127.0.0.1", server.port)},
                cadence_s=cadence, timeout_s=0.5,
            )
            coll.start()
            try:
                on_runs.append(await closed_loop_tok_s())
            finally:
                coll.stop()
            scrapes += len(coll.store.samples("r0", "up"))
        server.begin_drain()
        await serve_task
        off_tok_s, on_tok_s = max(off_runs), max(on_runs)
        overhead_pct = max(0.0, 100.0 * (off_tok_s - on_tok_s) / off_tok_s)
        return {
            "off_tok_s": round(off_tok_s, 2),
            "on_tok_s": round(on_tok_s, 2),
            "overhead_pct": round(overhead_pct, 3),
            "cadence_s": cadence,
            "scrapes": scrapes,
            "duration_s": duration,
            "repeats": ab_repeats,
            "budget_pct": 1.0,
            "within_budget": overhead_pct < 1.0,
        }

    return asyncio.run(bench())


def obs_overhead_main() -> None:
    """--mode obs_overhead: tracer cost on the train hot path.

    Drives one jitted train step of a tiny model in a loop, once wrapped in
    the trainer's per-update span structure (update_step > data_fetch +
    dispatch, real ``Tracer`` feeding a flight ring buffer) and once under
    ``NoopTracer`` (the disabled state).  Best-of-R loop times per arm keep
    scheduler noise out of the comparison; the artifact records both arms,
    the relative overhead, and the standalone per-span cost."""
    import time

    import jax
    import jax.numpy as jnp

    from relora_tpu.config.model import MODEL_ZOO
    from relora_tpu.core.optim import build_optimizer
    from relora_tpu.core.partition import partition
    from relora_tpu.core.relora import LoraSpec, trainable_param_mask
    from relora_tpu.models.llama import LlamaForCausalLM
    from relora_tpu.models.params_util import init_params
    from relora_tpu.obs.flight import FlightRecorder
    from relora_tpu.obs.tracer import NoopTracer, Tracer
    from relora_tpu.train.state import TrainState
    from relora_tpu.train.step import make_train_step

    model_name = os.environ.get("BENCH_OBS_MODEL", "llama_9m")
    seq = int(os.environ.get("BENCH_OBS_SEQ", "128"))
    steps = int(os.environ.get("BENCH_OBS_STEPS", "50"))
    repeats = int(os.environ.get("BENCH_OBS_REPEATS", "3"))

    cfg = MODEL_ZOO[model_name]
    model = LlamaForCausalLM(
        cfg, lora=LoraSpec(r=8, alpha=32, dropout=0.0), dtype=jnp.float32, scan_layers=True
    )
    params = init_params(model, jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    mask = trainable_param_mask(params)
    tx = build_optimizer(schedule=lambda s: 1e-3)
    opt_state = jax.jit(tx.init)(partition(params, mask)[0])
    state = TrainState.create(params, opt_state)
    step = jax.jit(make_train_step(model, tx, mask), donate_argnums=0)
    batch = jax.random.randint(jax.random.PRNGKey(1), (1, 2, seq), 0, cfg.vocab_size)
    rng = jax.random.PRNGKey(2)

    def run_loop(tracer) -> float:
        nonlocal state
        state, metrics = step(state, batch, jax.random.fold_in(rng, 0))  # warm
        float(metrics["loss"])
        t0 = time.perf_counter()
        for i in range(steps):
            # the trainer's per-update span structure (trainer.fit)
            with tracer.span("update_step", step=i):
                with tracer.span("data_fetch"):
                    b = batch
                with tracer.span("dispatch", step=i):
                    state, metrics = step(state, b, jax.random.fold_in(rng, i))
        float(metrics["loss"])  # one sync for the whole chain
        return (time.perf_counter() - t0) / steps

    # interleave arms and keep the best loop per arm: both see the same
    # thermal/scheduler conditions, min() discards interference
    traced_tracer = Tracer(service="bench", recorder=FlightRecorder())
    noop_s = min(run_loop(NoopTracer()) for _ in range(repeats))
    traced_s = min(run_loop(traced_tracer) for _ in range(repeats))
    overhead_pct = 100.0 * (traced_s - noop_s) / noop_s

    # standalone per-span cost (enter+exit+record), away from step noise
    probe = Tracer(service="bench", recorder=FlightRecorder())
    n_probe = 20000
    t0 = time.perf_counter()
    for i in range(n_probe):
        with probe.span("probe"):
            pass
    span_us = (time.perf_counter() - t0) / n_probe * 1e6

    collector = _collector_overhead_ab()

    result = {
        "metric": f"span tracer overhead on {model_name} train step "
        f"(3 spans/step, best of {repeats}x{steps})",
        "value": round(overhead_pct, 3),
        "unit": "% of step time",
        "detail": {
            "device": str(jax.devices()[0]),
            "backend": jax.default_backend(),
            "noop_step_ms": round(noop_s * 1e3, 4),
            "traced_step_ms": round(traced_s * 1e3, 4),
            "span_cost_us": round(span_us, 3),
            "spans_per_step": 3,
            # attributable overhead from the measured per-span cost; the
            # loop delta above can go negative in scheduler noise
            "analytic_overhead_pct": round(100.0 * 3 * span_us / (noop_s * 1e6), 4),
            "budget_pct": 1.0,
            "within_budget": overhead_pct < 1.0,
            "collector": collector,
        },
    }
    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "BENCH_obs.json")
    with open(out_path, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps(result))


def compress_main() -> None:
    """--mode compress: the prune-retrain quality ladder (relora_tpu/compress)
    over sparsity levels — post-prune eval-loss delta, LoRA-only retrain
    recovery (PERP), a synthetic-GLUE probe of the pruned backbone, and the
    model-draft accept rate of each pruned draft speculating against its own
    dense base.  The numbers the gate reads are structural (loss deltas,
    accept rates, token parity — not wall time), so the artifact is
    meaningful off-TPU.  The model-draft entries are also merged into
    BENCH_http.json's ``detail.spec_runs`` (keys ``model:<sparsity>``) so
    the spec-decoding gate rule sees them next to the ngram sweep.

    Env: BENCH_COMPRESS_MODEL (default llama_9m), BENCH_COMPRESS_SPARSITIES
    ("0.0,0.25,0.5,0.75"), BENCH_COMPRESS_PRETRAIN_STEPS,
    BENCH_COMPRESS_RETRAIN_STEPS, BENCH_COMPRESS_GLUE_EPOCHS,
    BENCH_COMPRESS_SPEC_K, BENCH_COMPRESS_BATCH, BENCH_COMPRESS_SEQ."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    model_name = os.environ.get("BENCH_COMPRESS_MODEL", "llama_9m")
    sparsities = [
        float(s)
        for s in os.environ.get(
            "BENCH_COMPRESS_SPARSITIES", "0.0,0.25,0.5,0.75"
        ).split(",")
        if s.strip()
    ]
    pretrain_steps = int(os.environ.get("BENCH_COMPRESS_PRETRAIN_STEPS", "30"))
    retrain_steps = int(os.environ.get("BENCH_COMPRESS_RETRAIN_STEPS", "20"))
    glue_epochs = int(os.environ.get("BENCH_COMPRESS_GLUE_EPOCHS", "2"))
    spec_k = int(os.environ.get("BENCH_COMPRESS_SPEC_K", "4"))
    batch = int(os.environ.get("BENCH_COMPRESS_BATCH", "4"))
    seq = int(os.environ.get("BENCH_COMPRESS_SEQ", "32"))
    rank = int(os.environ.get("BENCH_COMPRESS_RANK", "8"))

    from relora_tpu.compress.prune import apply_mask, magnitude_mask, sparsity_stats
    from relora_tpu.config.model import load_model_config
    from relora_tpu.core.relora import LoraSpec, merged_params, trainable_param_mask
    from relora_tpu.eval.glue import GlueConfig, finetune
    from relora_tpu.models.params_util import init_params
    from relora_tpu.serve.engine import InferenceEngine, build_decode_model
    from relora_tpu.serve.scheduler import PagedContinuousBatchingScheduler, Request
    from relora_tpu.train.losses import causal_lm_loss

    cfg = load_model_config(model_name)
    lspec = LoraSpec(r=rank, alpha=2 * rank)
    family_cls = type(build_decode_model(cfg, cache_size=8))
    model = family_cls(cfg, lora=lspec, dtype=jnp.float32, scan_layers=True)
    params = init_params(model, jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))

    # successor-token data: next = (cur + 1) % vocab — a pattern the tiny
    # model learns in a few dozen steps, so pruning has real loss to damage
    # and LoRA retraining has real signal to recover it with
    rs = np.random.RandomState(0)

    def make_ids(n: int) -> np.ndarray:
        start = rs.randint(1, cfg.vocab_size - 1, size=(n, 1))
        return ((start + np.arange(seq)[None, :]) % cfg.vocab_size).astype(np.int32)

    eval_ids = jnp.asarray(make_ids(16))

    @jax.jit
    def eval_loss(p) -> jax.Array:
        logits = model.apply({"params": p}, eval_ids, deterministic=True)
        loss, _ = causal_lm_loss(logits, eval_ids)
        return loss

    def make_step(tx):
        @jax.jit
        def step(p, opt_state, ids):
            def lf(q):
                logits = model.apply({"params": q}, ids, deterministic=True)
                loss, _ = causal_lm_loss(logits, ids)
                return loss

            loss, grads = jax.value_and_grad(lf)(p)
            updates, opt_state = tx.update(grads, opt_state, p)
            return optax.apply_updates(p, updates), opt_state, loss

        return step

    # brief full-parameter "pretrain" so base magnitudes carry signal
    pre_tx = optax.adam(1e-2)
    pre_step = make_step(pre_tx)
    opt_state = pre_tx.init(params)
    for i in range(pretrain_steps):
        params, opt_state, _ = pre_step(params, opt_state, jnp.asarray(make_ids(batch)))
    dense_loss = float(eval_loss(params))

    # PERP retrain: only the LoRA factors move, so base zeros stay zero
    lora_mask = trainable_param_mask(params, lora_only=True)
    ft_tx = optax.masked(optax.adam(1e-2), lora_mask)
    ft_step = make_step(ft_tx)

    # synthetic GLUE (the test_glue task: token at position 0 decides the
    # label) — same data for every level, score differences are the prune
    glue_rs = np.random.RandomState(1)

    def glue_make(n):
        ids = glue_rs.randint(3, 64, size=(n, 12)).astype(np.int32)
        labels = glue_rs.randint(0, 2, size=n)
        ids[:, 0] = np.where(labels == 1, 1, 2)
        return ids, labels

    g_train = glue_make(128)
    g_eval = glue_make(64)
    g_bs = 32
    g_steps = len(g_train[0]) // g_bs

    def glue_score(backbone) -> float:
        def train_batches():
            for i in range(g_steps):
                yield g_train[0][i * g_bs:(i + 1) * g_bs], g_train[1][i * g_bs:(i + 1) * g_bs]

        def eval_batches():
            for i in range(0, len(g_eval[0]), g_bs):
                yield g_eval[0][i:i + g_bs], g_eval[1][i:i + g_bs]

        gcfg = GlueConfig(task="sst2", lr=5e-3, batch_size=g_bs, num_epochs=glue_epochs, seed=0)
        metrics, _ = finetune(
            cfg, gcfg, train_batches, eval_batches, g_steps,
            pad_token_id=0, pretrained_backbone=backbone,
        )
        return metrics["accuracy"]

    # draft accept-rate probe: a paged base engine speculating with the
    # pruned draft, drained against a plain engine for greedy token parity
    cache_size, page_size, chunk_size, probe_batch = 64, 8, 16, 2
    probe_pages = 2 * probe_batch * (cache_size // page_size) + 1
    probe_reqs = [
        Request(uid=i, prompt=[(7 * i + j) % 97 + 1 for j in range(10)], max_new_tokens=8)
        for i in range(4)
    ]

    def spec_probe(base_tree, draft_tree) -> dict:
        kw = dict(
            cache_size=cache_size, page_size=page_size,
            num_pages=probe_pages, chunk_size=chunk_size,
        )
        plain_eng = InferenceEngine(cfg, base_tree, **kw)
        plain = PagedContinuousBatchingScheduler(
            plain_eng, max_batch=probe_batch, eos_id=-1, key=jax.random.PRNGKey(42)
        ).run(list(probe_reqs))
        spec_eng = InferenceEngine(cfg, base_tree, spec_k=spec_k, **kw)
        spec_eng.load_draft_params(draft_tree)
        sched = PagedContinuousBatchingScheduler(
            spec_eng, max_batch=probe_batch, eos_id=-1,
            key=jax.random.PRNGKey(42), spec="model",
        )
        drained = sched.run(list(probe_reqs))
        stats = sched.spec_stats()
        parity = len(drained) == len(plain) and all(
            uid in drained and drained[uid].tokens == c.tokens
            for uid, c in plain.items()
        )
        stats["token_parity"] = parity
        return stats

    levels = []
    for level in sparsities:
        mask = magnitude_mask(params, level)
        stats = sparsity_stats(mask)
        pruned = apply_mask(params, mask)
        loss_pruned = float(eval_loss(pruned))
        p, opt_state = pruned, ft_tx.init(pruned)
        for i in range(retrain_steps):
            p, opt_state, _ = ft_step(p, opt_state, jnp.asarray(make_ids(batch)))
        loss_retrained = float(eval_loss(p))
        # the base is the retrained model's own dense merge — deployment
        # serves the trained checkpoint and exports the draft from that same
        # checkpoint, so at sparsity 0.0 draft == base and accept is 1.0 by
        # construction.  draft = merge, then re-apply the mask (merging folds
        # BA back into pruned positions; the exported draft must be sparse)
        base_tree = jax.tree_util.tree_map(np.asarray, merged_params(p, lspec))
        draft_tree = jax.tree_util.tree_map(np.asarray, apply_mask(base_tree, mask))
        spec_stats = spec_probe(base_tree, draft_tree)
        levels.append({
            "sparsity": level,
            "actual_sparsity": round(stats["sparsity"], 4),
            "loss_dense": round(dense_loss, 4),
            "loss_pruned": round(loss_pruned, 4),
            "loss_delta": round(loss_pruned - dense_loss, 4),
            "loss_retrained": round(loss_retrained, 4),
            "loss_recovered_delta": round(loss_retrained - dense_loss, 4),
            "glue_score": round(glue_score(draft_tree), 4),
            "spec": spec_stats,
        })
        print(json.dumps({"level": levels[-1]}))

    result = {
        "bench": "compress",
        "metric": f"{model_name} prune-retrain ladder ({len(levels)} sparsity levels)",
        "value": levels[-1]["spec"]["accept_rate"],
        "unit": "accept_rate_at_max_sparsity",
        "detail": {
            "model": model_name,
            "device": str(jax.devices()[0]),
            "spec_k": spec_k,
            "lora_rank": rank,
            "pretrain_steps": pretrain_steps,
            "retrain_steps": retrain_steps,
            "baseline_eval_loss": round(dense_loss, 4),
            "levels": levels,
        },
    }
    repo = os.path.dirname(os.path.abspath(__file__))
    with open(os.path.join(repo, "BENCH_compress.json"), "w") as f:
        json.dump(result, f, indent=2)
    # mirror the model-draft runs into the HTTP artifact's spec_runs block,
    # keyed "model:<sparsity>", so check_spec sees model drafting next to
    # the ngram sweep without rerunning the load bench
    http_path = os.path.join(repo, "BENCH_http.json")
    if os.path.exists(http_path):
        try:
            with open(http_path) as f:
                http = json.load(f)
            spec_runs = http.setdefault("detail", {}).setdefault("spec_runs", {})
            for lv in levels:
                spec_runs[f"model:{lv['sparsity']}"] = {
                    **lv["spec"],
                    "sparsity": lv["sparsity"],
                }
            with open(http_path, "w") as f:
                json.dump(http, f, indent=2)
        except (json.JSONDecodeError, OSError) as e:
            print(f"skipping BENCH_http.json spec_runs merge: {e}")
    print(json.dumps(result))


if __name__ == "__main__":
    import argparse

    _ap = argparse.ArgumentParser()
    _ap.add_argument(
        "--mode",
        choices=["train", "decode", "lint", "lora_kernel", "attention", "serve_load", "autoscale", "obs_overhead", "compress"],
        default="train",
    )
    _ap.add_argument(
        "--router",
        action="store_true",
        help="serve_load: add the 2-replica failover phase (subprocess fleet "
        "behind the health-aware router, with a mid-run SIGKILL)",
    )
    _cli = _ap.parse_args()
    if _cli.mode == "lint":
        lint_main()
        sys.exit(0)
    if _cli.mode == "obs_overhead":
        obs_overhead_main()
        sys.exit(0)
    if _cli.mode == "decode":
        decode_main()
        sys.exit(0)
    if _cli.mode == "serve_load":
        serve_load_main(router=_cli.router)
        sys.exit(0)
    if _cli.mode == "autoscale":
        autoscale_main()
        sys.exit(0)
    if _cli.mode == "lora_kernel":
        lora_kernel_main()
        sys.exit(0)
    if _cli.mode == "attention":
        attention_main()
        sys.exit(0)
    if _cli.mode == "compress":
        compress_main()
        sys.exit(0)
    if os.environ.get("BENCH_FORCE") != "1":
        platform, err = _probe_device()
        if not platform:
            _emit_stale(err)
        if platform == "cpu":
            _emit_stale("no accelerator (cpu-only jax backend)")
    timer = threading.Timer(WATCHDOG_SECS, _watchdog)
    timer.daemon = True
    timer.start()
    main()
    timer.cancel()
