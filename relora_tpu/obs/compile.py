"""Compilation telemetry: when jitted entry points compile, and why.

The RTL1xx lint rules keep retrace *causes* out of the code statically; this
module is their runtime counterpart.  A :class:`CompileWatcher` wraps jitted
callables (the trainer's ``_train_step``, the engine's prefill / insert /
decode) and tracks each call's **abstract signature** — the (treedef, per-leaf
shape/dtype) fingerprint jit keys its cache on.  A call with a new signature
is about to trace (and almost always compile); the watcher times it, emits a
``compile`` span + metrics event, and classifies it:

- **expected** — the first signature a wrapped function ever sees, or any
  compile inside an :meth:`CompileWatcher.expected_compiles` block
  (``engine.warmup`` wraps its pre-traffic compiles in one);
- **steady-state retrace** — everything else: a shape-unstable input slipped
  into the hot loop after warmup.  The ``compile_steady_state_retraces``
  counter should stay at 0 for the whole run; docs/operations.md has the
  triage recipe when it does not.

Per-call overhead on the warm path is one ``tree_flatten`` of the argument
*metadata* plus a set lookup — microseconds, no device work, no sync (the
module is registered hot in analysis/hotpaths.py).  jax is imported lazily so
``relora_tpu.obs`` stays import-light.
"""

from __future__ import annotations

import contextlib
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = [
    "CompileEvent",
    "CompileWatcher",
    "abstract_signature",
    "signature_diff",
]


def abstract_signature(args: tuple, kwargs: dict) -> Tuple[Any, Tuple[str, ...]]:
    """The (treedef, per-leaf "dtype(shape)") fingerprint of a call.

    Matches what jit's dispatch cache keys on for our entry points: pytree
    structure plus each array leaf's shape and dtype; non-array leaves
    (static ints, floats, None) contribute their ``repr``.  The treedef is
    returned as-is — PyTreeDef is hashable and cheap to compare, where
    ``str(treedef)`` on a large param tree is not.
    """
    import jax

    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    sig = []
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None and dtype is None:
            sig.append(repr(leaf))
        else:
            sig.append(f"{dtype}{tuple(shape) if shape is not None else ''}")
    return treedef, tuple(sig)


def signature_diff(
    prev: Optional[Tuple[str, ...]], new: Tuple[str, ...], limit: int = 8
) -> List[str]:
    """Human-readable leaf-level diff between two abstract signatures — the
    first thing to read when a steady-state retrace fires (which argument
    changed shape?)."""
    if prev is None:
        return []
    out: List[str] = []
    for i in range(max(len(prev), len(new))):
        a = prev[i] if i < len(prev) else "<absent>"
        b = new[i] if i < len(new) else "<absent>"
        if a != b:
            out.append(f"leaf[{i}]: {a} -> {b}")
            if len(out) >= limit:
                out.append("...")
                break
    if not out:
        out.append("<structure changed, leaf shapes identical>")
    return out


@dataclass
class CompileEvent:
    """One observed compile (first call with a new abstract signature)."""

    fn: str
    expected: bool
    reason: str  # "first_call" | an expected_compiles reason | "steady_state"
    duration_s: float
    n_leaves: int
    signature: Tuple[str, ...] = field(repr=False)
    changed: List[str] = field(default_factory=list)


class CompileWatcher:
    """Shared compile ledger for a set of wrapped jitted callables.

    Sinks are all optional and may be attached after construction (the
    trainer builds its MetricsLogger later than its jitted step):

    - ``tracer`` — each compile becomes a ``compile`` span covering the
      compiling call;
    - ``registry`` — ``compile_total`` / ``compile_steady_state_retraces``
      counters, labelled by function;
    - ``metrics`` — a ``compile`` event per observation into metrics.jsonl,
      which is what ``tools/perf_report.py`` reads.
    """

    def __init__(
        self,
        service: str = "app",
        *,
        tracer: Any = None,
        registry: Any = None,
        metrics: Any = None,
    ):
        self.service = service
        self.tracer = tracer
        self.registry = registry
        self.metrics = metrics
        self._lock = threading.Lock()
        self._events: List[CompileEvent] = []
        self._last_sig: Dict[str, Tuple[str, ...]] = {}
        self._first_seen: set = set()
        self._expected_depth = 0
        self._expected_reason = "expected"
        self._retraces = 0
        self._calls: Dict[str, int] = {}

    # -- classification -------------------------------------------------------

    @contextlib.contextmanager
    def expected_compiles(self, reason: str = "warmup"):
        """Compiles inside this block are expected (warmup, memory plans)."""
        with self._lock:
            self._expected_depth += 1
            prev = self._expected_reason
            self._expected_reason = reason
        try:
            yield
        finally:
            with self._lock:
                self._expected_depth -= 1
                self._expected_reason = prev

    @property
    def steady_state_retraces(self) -> int:
        """Compiles observed after a function's first signature, outside any
        ``expected_compiles`` block.  Healthy runs hold this at 0."""
        return self._retraces

    def compile_events(self) -> List[CompileEvent]:
        return list(self._events)

    def call_counts(self) -> Dict[str, int]:
        """Total calls per wrapped function (compiling or warm) — how tests
        count model dispatches: a scheduler round's dispatch count is the
        sum of the per-entry deltas across the round."""
        with self._lock:
            return dict(self._calls)

    def summary(self) -> Dict[str, Any]:
        by_fn: Dict[str, int] = {}
        for ev in self._events:
            by_fn[ev.fn] = by_fn.get(ev.fn, 0) + 1
        return {
            "compiles": len(self._events),
            "steady_state_retraces": self._retraces,
            "by_fn": by_fn,
        }

    # -- wrapping -------------------------------------------------------------

    def wrap(self, name: str, fn: Callable) -> "_WatchedFunction":
        """Wrap a jitted callable; attribute access (``.lower``, ...) passes
        through to the wrapped function."""
        return _WatchedFunction(self, name, fn)

    def _record(
        self, name: str, sig: Tuple[str, ...], duration_s: float
    ) -> CompileEvent:
        with self._lock:
            first = name not in self._first_seen
            self._first_seen.add(name)
            if first:
                expected, reason = True, "first_call"
            elif self._expected_depth > 0:
                expected, reason = True, self._expected_reason
            else:
                expected, reason = False, "steady_state"
                self._retraces += 1
            changed = [] if first else signature_diff(self._last_sig.get(name), sig)
            self._last_sig[name] = sig
            event = CompileEvent(
                fn=name,
                expected=expected,
                reason=reason,
                duration_s=duration_s,
                n_leaves=len(sig),
                signature=sig,
                changed=changed,
            )
            self._events.append(event)
        if self.registry is not None:
            self.registry.inc("compile_total", label=("fn", name))
            if not expected:
                self.registry.inc("compile_steady_state_retraces", label=("fn", name))
        if self.metrics is not None:
            self.metrics.event(
                "compile",
                fn=name,
                service=self.service,
                expected=expected,
                reason=reason,
                duration_s=round(duration_s, 4),
                changed=changed,
            )
        return event


class _WatchedFunction:
    """Signature-tracking pass-through around one jitted callable."""

    def __init__(self, watcher: CompileWatcher, name: str, fn: Callable):
        self._watcher = watcher
        self._name = name
        self.__wrapped__ = fn
        self._known: set = set()

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        watcher = self._watcher
        with watcher._lock:
            watcher._calls[self._name] = watcher._calls.get(self._name, 0) + 1
        treedef, sig = abstract_signature(args, kwargs)
        key = (treedef, sig)
        if key in self._known:
            return self.__wrapped__(*args, **kwargs)
        # new abstract signature: this call traces (and compiles, unless an
        # identical program is already in-process).  The timed duration is
        # trace + compile; execution is async-dispatched and not included.
        watcher = self._watcher
        t0 = time.monotonic()
        if watcher.tracer is not None:
            with watcher.tracer.span("compile", fn=self._name) as sp:
                out = self.__wrapped__(*args, **kwargs)
                self._known.add(key)
                event = watcher._record(self._name, sig, time.monotonic() - t0)
                sp.set(expected=event.expected, reason=event.reason)
        else:
            out = self.__wrapped__(*args, **kwargs)
            self._known.add(key)
            watcher._record(self._name, sig, time.monotonic() - t0)
        return out

    def __getattr__(self, item: str) -> Any:
        return getattr(self.__wrapped__, item)
