"""Magnitude-informed A/B re-initialization at ReLoRA resets.

"The Primacy of Magnitude in Low-Rank Adaptation" (arXiv:2507.06558) argues
the blind kaiming re-draw at every ReLoRA reset wastes the information the
merged base already carries: input rows with large weight magnitude are the
rows whose updates matter, so the fresh A should put its variance there.

The dial is ``reset_init``:

- ``"random"`` — today's behavior, byte-for-byte: plain
  :func:`relora_tpu.core.relora.kaiming_uniform` (the default ``a_init=None``
  path of ``merge_and_reinit`` draws from the identical key sequence).
- ``"magnitude"`` — the kaiming draw re-scaled per input row by the merged
  kernel's row-magnitude profile, RMS-normalized so the overall init
  variance matches the random draw in expectation.  B stays zero either
  way, so the delta starts at 0 and the loss curve is continuous across
  the reset regardless of the dial.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from relora_tpu.core.relora import kaiming_uniform

#: signature of a pluggable A-init: (key, a_shape, merged_base_f32) -> array
AInitFn = Callable[[jax.Array, Tuple[int, ...], Optional[jax.Array]], jax.Array]

_EPS = 1e-8


def magnitude_a_init(
    key: jax.Array, shape: Tuple[int, ...], merged: Optional[jax.Array]
) -> jax.Array:
    """Weight-magnitude-aligned A init.

    ``shape`` is the lora_a shape ``(..., in, r)``; ``merged`` is the f32
    merged (and, under pruning, masked) base kernel ``(..., in, out)``.
    Each input row of the kaiming draw is scaled by that row's RMS weight
    magnitude, normalized so the mean squared scale is 1 — the init keeps
    kaiming's overall energy but concentrates it on high-magnitude rows
    (pruned-away rows get exactly zero signal).
    """
    base = kaiming_uniform(key, shape)
    if merged is None:
        return base
    row = jnp.sqrt(jnp.mean(jnp.square(merged), axis=-1, keepdims=True))  # (..., in, 1)
    rms = jnp.sqrt(jnp.mean(jnp.square(row), axis=-2, keepdims=True))
    return base * (row / jnp.maximum(rms, _EPS))


def make_reinit_fn(reset_init: str) -> Optional[AInitFn]:
    """``reset_init`` dial -> the ``a_init`` argument of ``merge_and_reinit``.

    ``"random"`` maps to None (the built-in kaiming path — byte-for-byte
    today's behavior), ``"magnitude"`` to :func:`magnitude_a_init`.
    """
    if reset_init == "random":
        return None
    if reset_init == "magnitude":
        return magnitude_a_init
    raise ValueError(f"reset_init must be 'random' or 'magnitude', got {reset_init!r}")
