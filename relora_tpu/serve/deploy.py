"""Continuous deployment: checkpoint watcher, fleet hot-swap, canary gate.

The train->merge->serve pipeline's last mile.  Training continuously emits
servable full-rank checkpoints (every ReLoRA merge boundary); this module
moves them into a running fleet with zero downtime and a way back:

- ``publish_latest`` / ``read_latest`` — an atomically-replaced ``latest``
  pointer file next to the checkpoints.  The trainer publishes it from the
  manifest-finalizing fence (train/checkpoint.py), so the pointer only ever
  names manifest-committed dirs; a torn write leaves the old pointer intact.
- ``CheckpointWatcher`` — polls the pointer and hands *verified* checkpoint
  dirs to a callback.  The size+crc32 manifest check
  (utils/integrity.verify_checkpoint_files) runs before the callback ever
  sees a path: the watcher never acts on an unverified or torn dir.
- ``RollingUpdater`` — one-replica-at-a-time fleet hot-swap over the
  server's ``POST /admin/reload`` seam: reload, health-probe until the new
  ``weights_version`` reports healthy, replay a canary prompt-set requiring
  token-identical greedy output, then the next replica.  Any failure rolls
  the *whole fleet* back to the previous version (the LossSpikeDetector
  shape, with the fleet as the trainer and the last healthy version as the
  rollback checkpoint), and every transition lands as a ``deploy_*`` event
  in the fleet SeriesStore timeline.

Everything here is stdlib-only and jax-free: the watcher and updater run in
supervisor/front-end processes that must never pay a device runtime import.

Drill sites (utils/faults.py): ``deploy_corrupt_manifest`` (publish flips a
byte in the checkpoint's manifest), ``deploy_reload`` (the server's apply
boundary raises), ``deploy_crash_mid_update`` (the updater dies between
replicas).  ``tests/test_deploy.py`` and smoke stage 14 drive all three to
a healthy fleet on one consistent version.
"""

from __future__ import annotations

import http.client
import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from relora_tpu.utils import faults
from relora_tpu.utils.integrity import MANIFEST_FILE, verify_checkpoint_files
from relora_tpu.utils.logging import get_logger

logger = get_logger(__name__)

LATEST_FILE = "latest"

# default canary prompt-set: tiny token-id prompts every model config can
# decode; real deployments pass their own (tokenized) prompts
DEFAULT_CANARY_PROMPTS: Tuple[Tuple[int, ...], ...] = ((1, 2, 3), (4, 5, 6, 7), (2,))
CANARY_FILE = "canary.json"


def checkpoint_step(path: str) -> Optional[int]:
    """The step encoded in a ``model_{step}`` dir name, or None.  Doubles as
    the monotonic ``weights_version`` for that checkpoint fleet-wide."""
    base = os.path.basename(os.path.normpath(path))
    prefix, _, step = base.rpartition("_")
    if prefix.startswith("model") and step.isdigit():
        return int(step)
    return None


def publish_latest(save_dir: str, path: str) -> str:
    """Atomically point ``save_dir/latest`` at checkpoint ``path``.

    tmp + ``os.replace`` — a reader sees the old pointer or the new one,
    never a torn file.  Call only for manifest-committed dirs (the trainer
    publishes from the manifest-finalizing fence; the CLI verifies first).
    Returns the pointer path."""
    pointer = os.path.join(save_dir, LATEST_FILE)
    record = {
        "path": os.path.basename(os.path.normpath(path)),
        "step": checkpoint_step(path),
        "published_unix": time.time(),
    }
    tmp = pointer + ".tmp"
    with open(tmp, "w") as f:
        json.dump(record, f)
    os.replace(tmp, pointer)
    logger.info(f"published latest -> {record['path']}")
    if faults.should("deploy_corrupt_manifest"):
        # drill: the published checkpoint's manifest gets a flipped byte —
        # watchers must reject the dir and the fleet must hold its version
        manifest = os.path.join(path, MANIFEST_FILE)
        try:
            with open(manifest, "r+b") as f:
                byte = f.read(1)
                f.seek(0)
                f.write(bytes([byte[0] ^ 0xFF]) if byte else b"X")
            logger.warning(f"fault deploy_corrupt_manifest: corrupted {manifest}")
        except OSError as e:
            logger.warning(f"fault deploy_corrupt_manifest could not corrupt: {e}")
    return pointer


def read_latest(save_dir: str) -> Optional[str]:
    """The checkpoint dir the ``latest`` pointer names (absolute), or None
    when there is no pointer / it is unreadable (a torn pointer is treated
    as absent, never as an error — the previous poll's answer stands)."""
    pointer = os.path.join(save_dir, LATEST_FILE)
    try:
        with open(pointer) as f:
            record = json.load(f)
    except (OSError, json.JSONDecodeError, ValueError):
        return None
    name = record.get("path")
    if not isinstance(name, str) or not name or os.sep in name:
        return None
    return os.path.abspath(os.path.join(save_dir, name))


class CheckpointWatcher:
    """Polls ``save_dir/latest`` and hands each *new, verified* checkpoint
    dir to ``on_new(path)``.

    The verification gate is absolute: ``on_new`` never sees a dir that
    failed the manifest check.  A rejected dir is remembered by its manifest
    signature (mtime+size) so the poll loop does not re-crc an unchanged bad
    dir every interval, but a re-publish (or a repaired manifest) is
    re-verified from scratch.  ``on_reject(path, reason)`` is optional
    telemetry for the reject path.
    """

    def __init__(
        self,
        save_dir: str,
        on_new: Callable[[str], None],
        *,
        interval_s: float = 2.0,
        verify: Callable[[str], Tuple[bool, str]] = verify_checkpoint_files,
        on_reject: Optional[Callable[[str, str], None]] = None,
        current: Optional[str] = None,
    ):
        self.save_dir = save_dir
        self.on_new = on_new
        self.on_reject = on_reject
        self.interval_s = interval_s
        self.verify = verify
        # the dir currently serving (startup checkpoint): the watcher only
        # fires for pointers that differ from it
        self._current = os.path.abspath(current) if current else None
        self._rejected: Optional[Tuple[str, Any]] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _signature(self, path: str) -> Tuple[str, Any]:
        manifest = os.path.join(path, MANIFEST_FILE)
        try:
            st = os.stat(manifest)
            return path, (st.st_mtime_ns, st.st_size)
        except OSError:
            return path, None

    def poll_once(self) -> Optional[str]:
        """One poll: returns the newly accepted checkpoint path, or None."""
        target = read_latest(self.save_dir)
        if target is None or target == self._current:
            return None
        sig = self._signature(target)
        if sig == self._rejected:
            return None  # same bad dir, unchanged since the last reject
        ok, reason = self.verify(target)
        if not ok:
            self._rejected = sig
            logger.warning(f"checkpoint watcher: rejecting {target}: {reason}")
            if self.on_reject is not None:
                self.on_reject(target, reason)
            return None
        self._rejected = None
        logger.info(f"checkpoint watcher: verified new checkpoint {target}")
        if self.on_new(target) is False:
            # the rollout reported failure (no live replicas yet, reload
            # refused, canary rollback): leave ``_current`` unlatched so the
            # next poll retries — a transient failure heals on its own, a
            # persistent one surfaces as repeated deploy_* events
            return None
        self._current = target
        return target

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.poll_once()
            except Exception as e:
                # the watch loop must survive a callback blowing up — the
                # next publish still deserves a chance
                logger.error(f"checkpoint watcher poll failed: {e!r}")

    def start(self) -> "CheckpointWatcher":
        self._thread = threading.Thread(
            target=self._run, name="ckpt-watcher", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)


# ---------------------------------------------------------------------------
# rolling fleet update


def _http_json(
    host: str,
    port: int,
    method: str,
    path: str,
    body: Optional[dict] = None,
    timeout: float = 120.0,
) -> Tuple[int, dict]:
    """One request against a replica; returns (status, parsed body).  The
    server speaks close-delimited HTTP/1.1, so a fresh connection per call."""
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        payload = json.dumps(body).encode() if body is not None else None
        conn.request(
            method, path, body=payload,
            headers={"Content-Type": "application/json"} if payload else {},
        )
        resp = conn.getresponse()
        raw = resp.read()
        try:
            return resp.status, json.loads(raw.decode() or "{}")
        except (json.JSONDecodeError, UnicodeDecodeError):
            return resp.status, {}
    finally:
        conn.close()


class CanaryMismatch(Exception):
    """A replica's greedy canary output diverged from the recorded baseline."""


class _ReplicaUpdateFailed(Exception):
    """A replica's reload or post-reload health probe failed mid-rollout."""


class RollingUpdater:
    """Drain-free rolling weight update with a canary gate and automatic
    fleet-wide rollback.

    ``endpoints`` is a zero-arg callable returning ``{idx: (host, port)}``
    (``ReplicaSupervisor.endpoints``); ``emit(event, idx, detail)`` forwards
    ``deploy_*`` lifecycle events (wired to the fleet SeriesStore by the
    supervisor CLI).  One replica at a time: reload via ``/admin/reload``
    (the server itself fences the swap between decode rounds, so in-flight
    requests are never dropped), health-probe until the replica reports the
    new ``weights_version`` with status ok, then replay the canary prompts
    requiring token-identical greedy output against the baseline *for the
    new version* — loaded from ``<ckpt>/canary.json`` when the trainer
    recorded one, else recorded from the first updated replica (which makes
    that replica the canary and pins the rest of the fleet to bit-identical
    behavior).  Any reload/probe/canary failure rolls every replica back to
    the previous version and reports False.
    """

    def __init__(
        self,
        endpoints: Callable[[], Dict[int, Tuple[str, Optional[int]]]],
        *,
        canary_prompts: Optional[List[List[int]]] = None,
        canary_max_new_tokens: int = 8,
        expect_replicas: Optional[int] = None,
        emit: Optional[Callable[[str, Optional[int], dict], None]] = None,
        probe_timeout_s: float = 120.0,
        probe_interval_s: float = 0.2,
        request_timeout_s: float = 120.0,
        verify: Callable[[str], Tuple[bool, str]] = verify_checkpoint_files,
    ):
        self.endpoints = endpoints
        self.canary_prompts = [
            list(p) for p in (canary_prompts or DEFAULT_CANARY_PROMPTS)
        ]
        self.canary_max_new_tokens = canary_max_new_tokens
        self.expect_replicas = expect_replicas
        self._emit_cb = emit
        self.probe_timeout_s = probe_timeout_s
        self.probe_interval_s = probe_interval_s
        self.request_timeout_s = request_timeout_s
        self.verify = verify

    # -- plumbing ------------------------------------------------------------

    def _emit(self, event: str, idx: Optional[int], **detail: Any) -> None:
        logger.info(f"{event} replica={idx} {detail}")
        if self._emit_cb is not None:
            try:
                self._emit_cb(event, idx, detail)
            except Exception as e:
                logger.warning(f"deploy event sink failed: {e!r}")

    def _live_endpoints(self) -> Dict[int, Tuple[str, int]]:
        return {
            idx: (host, port)
            for idx, (host, port) in sorted(self.endpoints().items())
            if port is not None
        }

    def _healthz(self, host: str, port: int) -> dict:
        try:
            _, body = _http_json(host, port, "GET", "/healthz", timeout=10.0)
            return body
        except OSError:
            return {}

    def _probe_until(self, idx: int, host: str, port: int, version: int) -> bool:
        """Wait for the replica to report status ok on the given version."""
        deadline = time.monotonic() + self.probe_timeout_s
        while time.monotonic() < deadline:
            h = self._healthz(host, port)
            if h.get("status") == "ok" and h.get("weights_version") == version:
                return True
            time.sleep(self.probe_interval_s)
        return False

    def _generate(self, host: str, port: int, prompt: List[int]) -> List[int]:
        status, body = _http_json(
            host, port, "POST", "/v1/generate",
            {
                "prompt": prompt,
                "max_new_tokens": self.canary_max_new_tokens,
                "temperature": 0.0,  # greedy: token-identical is meaningful
                "stream": False,
            },
            timeout=self.request_timeout_s,
        )
        if status != 200 or body.get("finish_reason") not in ("eos", "length"):
            raise CanaryMismatch(
                f"canary request failed on replica port {port}: "
                f"HTTP {status} {body.get('finish_reason') or body.get('error')}"
            )
        return list(body.get("tokens") or [])

    def _reload(self, host: str, port: int, path: str) -> Tuple[bool, dict]:
        try:
            status, body = _http_json(
                host, port, "POST", "/admin/reload", {"checkpoint": path},
                timeout=self.request_timeout_s,
            )
        except OSError as e:
            return False, {"error": f"{e!r}"}
        return status == 200 and bool(body.get("ok")), body

    def _load_baseline(self, path: str) -> Optional[List[List[int]]]:
        """Trainer-recorded canary baseline for this checkpoint, if any."""
        canary = os.path.join(path, CANARY_FILE)
        try:
            with open(canary) as f:
                record = json.load(f)
        except (OSError, json.JSONDecodeError):
            return None
        prompts = record.get("prompts")
        tokens = record.get("tokens")
        if not isinstance(prompts, list) or not isinstance(tokens, list):
            return None
        self.canary_prompts = [list(p) for p in prompts]
        if isinstance(record.get("max_new_tokens"), int):
            self.canary_max_new_tokens = record["max_new_tokens"]
        return [list(t) for t in tokens]

    def _run_canary(
        self, idx: int, host: str, port: int, baseline: Optional[List[List[int]]]
    ) -> List[List[int]]:
        """Replay the canary prompts; raises CanaryMismatch on divergence.
        Returns the outputs (the recorded baseline for the first replica)."""
        outs = [self._generate(host, port, p) for p in self.canary_prompts]
        if baseline is not None:
            for i, (got, want) in enumerate(zip(outs, baseline)):
                if got != want:
                    raise CanaryMismatch(
                        f"replica {idx} canary prompt {i} diverged: "
                        f"got {got}, baseline {want}"
                    )
        return outs

    # -- the rolling update --------------------------------------------------

    def run(self, new_path: str) -> bool:
        """Roll the fleet onto ``new_path``.  True on full success; False
        after an automatic rollback (or when there is nothing to do)."""
        new_path = os.path.abspath(new_path)
        ok, reason = self.verify(new_path)
        if not ok:
            # belt and braces: the watcher already verifies, but run() is
            # also a public entry point (CLI, supervisor signal)
            self._emit("deploy_reject", None, checkpoint=new_path, reason=reason)
            return False
        version = checkpoint_step(new_path)
        eps = self._live_endpoints()
        if not eps or (self.expect_replicas and len(eps) < self.expect_replicas):
            # a partially-booted fleet must not be walked: updating only the
            # visible replicas would latch a mixed-version fleet.  Reporting
            # failure leaves the watcher unlatched, so the rollout retries
            # once the whole fleet is up.
            self._emit(
                "deploy_reject", None, checkpoint=new_path,
                reason=f"{len(eps)}/{self.expect_replicas or '?'} replicas live",
            )
            return False
        # what is the fleet serving right now?  A crashed previous update
        # leaves mixed versions, so look at every replica: replicas already
        # on the target still get re-walked (reload is idempotent), and the
        # rollback target must come from a replica NOT yet on the target —
        # reading it off an updated one would make rollback a no-op
        states = {idx: self._healthz(host, port) for idx, (host, port) in eps.items()}
        on_target = [
            idx
            for idx, h in states.items()
            if h.get("weights_checkpoint")
            and os.path.abspath(h["weights_checkpoint"]) == new_path
        ]
        if len(on_target) == len(eps):
            return True  # whole fleet already on this checkpoint
        prev_version, prev_path = None, None
        for idx, h in states.items():
            ck = h.get("weights_checkpoint")
            if ck and os.path.abspath(ck) != new_path:
                prev_version, prev_path = h.get("weights_version"), ck
                break
        if version is None:
            version = (prev_version or 0) + 1
        self._emit(
            "deploy_begin", None,
            checkpoint=new_path, version=version,
            prev_version=prev_version, replicas=len(eps),
        )
        baseline = self._load_baseline(new_path)
        recorded = baseline is not None
        updated: List[int] = []
        try:
            for idx, (host, port) in eps.items():
                ok, body = self._reload(host, port, new_path)
                if not ok:
                    self._emit(
                        "deploy_reload_failed", idx,
                        checkpoint=new_path, error=body.get("error", f"{body}"),
                    )
                    raise _ReplicaUpdateFailed("reload failed")
                if not self._probe_until(idx, host, port, version):
                    self._emit(
                        "deploy_probe_failed", idx,
                        checkpoint=new_path, version=version,
                    )
                    raise _ReplicaUpdateFailed("health probe failed")
                outs = self._run_canary(idx, host, port, baseline)
                if baseline is None:
                    baseline = outs
                    self._emit(
                        "deploy_canary_recorded", idx,
                        version=version, prompts=len(outs),
                    )
                updated.append(idx)
                self._emit("deploy_replica_updated", idx, version=version)
                # drill: die between replicas, leaving a mixed-version fleet
                # for the recovery path to converge
                faults.crash_point("deploy_crash_mid_update")
        except CanaryMismatch as e:
            self._emit("deploy_canary_fail", None, error=f"{e}", updated=len(updated))
            self._rollback(eps, prev_version, prev_path, from_version=version)
            return False
        except _ReplicaUpdateFailed as e:
            self._emit("deploy_fail", None, error=f"{e}", updated=len(updated))
            self._rollback(eps, prev_version, prev_path, from_version=version)
            return False
        self._emit(
            "deploy_complete", None,
            version=version, checkpoint=new_path,
            canary_recorded=not recorded, replicas=len(updated),
        )
        return True

    def _rollback(
        self,
        eps: Dict[int, Tuple[str, int]],
        prev_version: Optional[int],
        prev_path: Optional[str],
        from_version: Optional[int] = None,
    ) -> None:
        """Fleet-wide rollback to the previous version — every replica, not
        just the updated ones, so the fleet always converges to ONE version
        (a replica that half-applied anything gets re-asserted too)."""
        if not prev_path:
            self._emit("deploy_rollback_impossible", None, reason="no previous checkpoint known")
            return
        self._emit(
            "deploy_rollback", None,
            to_version=prev_version, to_checkpoint=prev_path, from_version=from_version,
        )
        for idx, (host, port) in eps.items():
            h = self._healthz(host, port)
            if h.get("status") == "ok" and h.get("weights_version") == prev_version:
                continue  # never updated (or already back): nothing to undo
            ok, body = self._reload(host, port, prev_path)
            if not ok:
                self._emit(
                    "deploy_rollback_replica_failed", idx,
                    error=body.get("error", f"{body}"),
                )
                continue
            if self._probe_until(idx, host, port, prev_version):
                self._emit("deploy_replica_rolled_back", idx, version=prev_version)
            else:
                self._emit("deploy_rollback_replica_failed", idx, error="probe timeout")


def main(argv: Optional[List[str]] = None) -> int:
    """CLI: ``python -m relora_tpu.serve.deploy publish <ckpt_dir>`` verifies
    a checkpoint dir and atomically publishes its save-dir's ``latest``
    pointer at it (the by-hand twin of the trainer's automatic publish)."""
    import argparse

    ap = argparse.ArgumentParser(description=main.__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)
    pub = sub.add_parser("publish", help="verify + publish latest -> DIR")
    pub.add_argument("checkpoint", help="model_{step} checkpoint dir")
    pub.add_argument(
        "--force", action="store_true",
        help="publish even if verification fails (corruption drills only)",
    )
    args = ap.parse_args(argv)

    path = os.path.abspath(args.checkpoint)
    ok, reason = verify_checkpoint_files(path)
    if not ok and not args.force:
        print(f"refusing to publish {path}: {reason}")
        return 1
    pointer = publish_latest(os.path.dirname(path), path)
    print(f"published {pointer} -> {os.path.basename(path)} ({reason})")
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
