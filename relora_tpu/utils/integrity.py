"""File-level checkpoint integrity checks, importable without jax.

The checkpoint layer (``train/checkpoint.py``) records a ``manifest.json``
per committed ``model_{step}`` dir: per-array shapes/dtypes plus per-file
size+crc32.  Verifying the *file* half of that contract needs nothing from
jax/orbax — just a directory walk and a crc pass — so it lives here, where
the deployment plane (``serve/deploy.py``) and the supervisor can use it
without dragging an accelerator runtime into a watcher process.

``verify_checkpoint_files`` is the single torn/corrupt-dir gate: the serve
startup path, every in-place reload, and the checkpoint watcher all route
through it before any device write happens.
"""

from __future__ import annotations

import json
import os
import zlib
from typing import Tuple

STATE_SUBDIR = "state"
MANIFEST_FILE = "manifest.json"


def file_crc32(path: str) -> int:
    crc = 0
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            crc = zlib.crc32(chunk, crc)
    return crc


def verify_checkpoint_files(path: str) -> Tuple[bool, str]:
    """Integrity-check a checkpoint dir against its size+crc32 manifest.

    Returns ``(ok, reason)``; on failure ``reason`` names the failing file.
    A dir without a manifest is accepted as a legacy checkpoint (pre-manifest
    saves, or a run killed before the finalizing fence) — commit-detection
    via ``state/`` still applies, so a torn async write is always rejected.
    """
    state_path = os.path.join(path, STATE_SUBDIR)
    if not os.path.isdir(state_path):
        return False, "uncommitted: no state/ subdir"
    manifest_path = os.path.join(path, MANIFEST_FILE)
    if not os.path.exists(manifest_path):
        return True, "legacy checkpoint without manifest"
    try:
        with open(manifest_path) as f:
            manifest = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        return False, f"unreadable manifest: {e}"
    for rel, rec in manifest.get("files", {}).items():
        full = os.path.join(path, rel)
        if not os.path.exists(full):
            return False, f"missing file {rel}"
        size = os.path.getsize(full)
        if size != rec["size"]:
            return False, f"size mismatch for {rel}: {size} != {rec['size']}"
        if file_crc32(full) != rec["crc32"]:
            return False, f"checksum mismatch for {rel}"
    return True, "ok"
