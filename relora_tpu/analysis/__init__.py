"""relora_tpu.analysis — AST-based JAX/TPU footgun linter (stdlib-only).

Rule families (full catalog in ``docs/static-analysis.md``):

- RTL1xx retrace hazards (Python control flow on tracers, unhashable
  static args, jit-inside-loop, str()/f-string on tracers)
- RTL2xx host syncs in hot paths (.item(), float(), np.asarray,
  block_until_ready inside the train/decode loops)
- RTL3xx donation/aliasing (use-after-donation, missing donate_argnums)
- RTL4xx RNG hygiene (key reuse, entropy-seeded keys)
- RTL5xx pytree/sharding (in-place params mutation, spec-less shard_map)
- RTL6xx concurrency (cross-thread writes without a common lock, blocking
  calls in async bodies, asyncio mutation off the loop, lock-order cycles)
- RTL7xx fleet consistency (consumed-but-never-produced series/event names,
  counters missing zero materialization, unknown fault sites) — a
  project-wide pass over the whole-repo symbol table/call graph

Usage::

    python -m relora_tpu.analysis [paths] [--baseline FILE]

This package deliberately imports neither jax nor numpy so it runs in a
bare interpreter (CI lint stage) in milliseconds.
"""

from relora_tpu.analysis.core import (  # noqa: F401  (re-exports)
    CHECKERS,
    PROJECT_CHECKERS,
    RULE_CATALOG,
    BaselineEntry,
    FileContext,
    Finding,
    ModuleIndex,
    ProjectIndex,
    Report,
    build_project_index,
    format_baseline_entry,
    get_module_index,
    lint_paths,
    lint_text,
    load_baseline,
)

# importing the rule modules registers their checkers/catalog entries
from relora_tpu.analysis import (  # noqa: F401
    rules_concurrency,
    rules_donation,
    rules_fleet,
    rules_hostsync,
    rules_pytree,
    rules_retrace,
    rules_rng,
)

__all__ = [
    "CHECKERS",
    "PROJECT_CHECKERS",
    "RULE_CATALOG",
    "BaselineEntry",
    "FileContext",
    "Finding",
    "ModuleIndex",
    "ProjectIndex",
    "Report",
    "build_project_index",
    "format_baseline_entry",
    "get_module_index",
    "lint_paths",
    "lint_text",
    "load_baseline",
]
