"""Fleet observability plane: scrape, retain, and serve cross-replica series.

Everything the serving tier exposes today is per-process and per-instant: a
replica's ``/metrics`` is Prometheus text that evaporates unless something
polls it, ``/healthz`` is a point-in-time verdict, and the trainer's
metrics.jsonl lives in a run directory nobody joins against serving latency.
This module adds the retention layer those signals need before fleet-level
automation (ROADMAP item 4's canary/rollback) can exist:

- :class:`SeriesStore` — an in-memory ring-buffer time-series store keyed
  ``(source, series)`` with bounded JSONL persistence.  The on-disk schema is
  *exactly* the trainer's metrics.jsonl schema (flat numeric records with
  ``_time``, ``_event`` for structured events) plus a ``_source`` tag, so one
  loader reads both and training MFU/loss sit next to serving TTFT/TPOT.
- :class:`FleetCollector` — scrapes every replica's and the router's
  ``/metrics`` + ``/healthz`` on a cadence into the store, derives quantile
  and rate series from histogram buckets and counter deltas, tails optional
  metrics.jsonl files (the trainer's) into the same store, records health
  transitions as structured events, and drives the SLO engine
  (:mod:`relora_tpu.obs.slo`) once per round.
- ``/fleet/metrics`` and ``/fleet/series`` payload rendering shared by the
  supervisor-hosted deployment (routes served by the router front-end) and
  the standalone CLI (``python -m relora_tpu.obs.fleet``).

Stdlib-only and jax-free, like the rest of ``obs/``: the collector runs in a
daemon thread inside the supervisor process and must never import the model
stack.
"""

from __future__ import annotations

import argparse
import collections
import http.client
import json
import os
import threading
import time
from typing import Any, Callable, Deque, Dict, Iterable, List, Mapping, Optional, Tuple

from relora_tpu.obs.metrics import MetricsRegistry
from relora_tpu.serve.disagg import PrefixPageDirectory
from relora_tpu.utils.logging import get_logger

__all__ = [
    "FleetCollector",
    "SeriesStore",
    "histogram_quantile",
    "load_series_jsonl",
    "parse_prometheus",
]

logger = get_logger("relora_tpu.fleet")


# -- Prometheus text parsing --------------------------------------------------


def parse_prometheus(text: str) -> Tuple[Dict[str, float], Dict[str, Dict[str, Any]]]:
    """Parse Prometheus 0.0.4 text exposition into flat samples + histograms.

    Returns ``(flat, hists)``.  ``flat`` maps metric name -> value, with the
    one-level labels this codebase uses joined as ``name.labelvalue`` (the
    same convention as ``MetricsRegistry.snapshot``).  ``hists`` maps
    histogram name -> ``{"buckets": [(le, cumcount), ...], "sum": float,
    "count": int}`` with ``le`` as float (``inf`` for +Inf).  Unparseable
    lines are skipped — a scrape must survive a foreign exporter.
    """
    flat: Dict[str, float] = {}
    hists: Dict[str, Dict[str, Any]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            name_part, value_s = line.rsplit(None, 1)
            value = float(value_s)
        except ValueError:
            continue
        label_val = None
        if "{" in name_part:
            name, _, label_s = name_part.partition("{")
            label_s = label_s.rstrip("}")
            try:
                _, _, label_val = label_s.partition("=")
                label_val = label_val.strip('"')
            except ValueError:
                continue
        else:
            name = name_part
        if name.endswith("_bucket") and label_val is not None:
            base = name[: -len("_bucket")]
            h = hists.setdefault(base, {"buckets": [], "sum": 0.0, "count": 0})
            le = float("inf") if label_val == "+Inf" else float(label_val)
            h["buckets"].append((le, value))
        elif name.endswith("_sum") and name[: -len("_sum")] in hists:
            hists[name[: -len("_sum")]]["sum"] = value
        elif name.endswith("_count") and name[: -len("_count")] in hists:
            hists[name[: -len("_count")]]["count"] = int(value)
        elif label_val is not None:
            flat[f"{name}.{label_val}"] = value
        else:
            flat[name] = value
    return flat, hists


def histogram_quantile(buckets: Iterable[Tuple[float, float]], q: float) -> float:
    """Quantile from cumulative ``(le, count)`` buckets — the same
    first-bound-reaching-q·count rule as ``Histogram.quantile`` so a scraped
    p95 matches what the replica would report about itself."""
    buckets = sorted(buckets)
    if not buckets:
        return 0.0
    total = buckets[-1][1]
    if total <= 0:
        return 0.0
    target = q * total
    for bound, cum in buckets:
        if cum >= target:
            return bound
    return buckets[-1][0]


# -- time-series store --------------------------------------------------------


class SeriesStore:
    """Ring-buffer time series keyed ``(source, series)`` + an event log.

    ``source`` is a replica id ("r0"), "router", "train", ...; ``series`` is
    a metric name.  Samples are ``(wall_time, float)``.  Persistence writes
    one flat JSONL record per ``add_samples`` call and one per event, in the
    metrics.jsonl schema (``_time``/``_event``/``_source`` plus plain numeric
    keys), rotating ``path`` -> ``path.1`` when the file exceeds
    ``persist_max_bytes`` so disk use stays bounded at ~2x that.
    """

    def __init__(
        self,
        max_points: int = 1024,
        max_events: int = 1024,
        persist_path: Optional[str] = None,
        persist_max_bytes: int = 8 * 1024 * 1024,
    ):
        self.max_points = max_points
        self._series: Dict[Tuple[str, str], Deque[Tuple[float, float]]] = {}
        self._events: Deque[Dict[str, Any]] = collections.deque(maxlen=max_events)
        self._lock = threading.Lock()
        self.persist_path = persist_path
        self.persist_max_bytes = persist_max_bytes
        self._fh = None
        if persist_path:
            d = os.path.dirname(os.path.abspath(persist_path))
            os.makedirs(d, exist_ok=True)
            self._fh = open(persist_path, "a")

    # -- ingestion ----------------------------------------------------------

    def add_sample(self, source: str, series: str, value: float, t: Optional[float] = None) -> None:
        self.add_samples(source, {series: value}, t=t)

    def add_samples(
        self, source: str, values: Mapping[str, float], t: Optional[float] = None, persist: bool = True
    ) -> None:
        t = time.time() if t is None else t
        with self._lock:
            for name, value in values.items():
                try:
                    v = float(value)
                except (TypeError, ValueError):
                    continue
                key = (source, name)
                dq = self._series.get(key)
                if dq is None:
                    dq = self._series[key] = collections.deque(maxlen=self.max_points)
                dq.append((t, v))
        if persist and values:
            self._persist({**{k: v for k, v in values.items()}, "_source": source, "_time": t})

    def add_event(
        self, kind: str, source: str, t: Optional[float] = None, persist: bool = True, **fields: Any
    ) -> Dict[str, Any]:
        t = time.time() if t is None else t
        record = {"_event": kind, "_source": source, "_time": t, **fields}
        with self._lock:
            self._events.append(record)
        if persist:
            self._persist(record)
        return record

    def ingest_record(self, record: Mapping[str, Any], source: Optional[str] = None) -> None:
        """Ingest one metrics.jsonl-schema record (the shared schema): an
        ``_event`` record lands in the event log, anything else contributes
        its numeric non-underscore keys as samples at ``_time``."""
        src = record.get("_source") or source or "unknown"
        t = record.get("_time")
        t = time.time() if not isinstance(t, (int, float)) else float(t)
        if "_event" in record:
            fields = {k: v for k, v in record.items() if k not in ("_event", "_source", "_time")}
            self.add_event(str(record["_event"]), src, t=t, persist=False, **fields)
            return
        values = {
            k: v
            for k, v in record.items()
            if not k.startswith("_") and isinstance(v, (int, float)) and not isinstance(v, bool)
        }
        if values:
            self.add_samples(src, values, t=t, persist=False)

    # -- queries ------------------------------------------------------------

    def sources(self) -> List[str]:
        with self._lock:
            return sorted({src for (src, _) in self._series})

    def series_names(self, source: str) -> List[str]:
        with self._lock:
            return sorted(name for (src, name) in self._series if src == source)

    def samples(
        self, source: str, series: str, since: Optional[float] = None
    ) -> List[Tuple[float, float]]:
        with self._lock:
            dq = self._series.get((source, series))
            if dq is None:
                return []
            out = list(dq)
        if since is not None:
            out = [(t, v) for (t, v) in out if t >= since]
        return out

    def latest(self, source: str, series: str) -> Optional[Tuple[float, float]]:
        with self._lock:
            dq = self._series.get((source, series))
            return dq[-1] if dq else None

    def window_values(
        self, source: str, series: str, window_s: float, now: Optional[float] = None
    ) -> List[float]:
        now = time.time() if now is None else now
        return [v for (_, v) in self.samples(source, series, since=now - window_s)]

    def events(
        self, kinds: Optional[Iterable[str]] = None, since: Optional[float] = None
    ) -> List[Dict[str, Any]]:
        with self._lock:
            out = list(self._events)
        if kinds is not None:
            kinds = set(kinds)
            out = [e for e in out if e.get("_event") in kinds]
        if since is not None:
            out = [e for e in out if e.get("_time", 0.0) >= since]
        return out

    # -- persistence --------------------------------------------------------

    def _persist(self, record: Mapping[str, Any]) -> None:
        fh = self._fh
        if fh is None:
            return
        with self._lock:
            try:
                fh.write(json.dumps(record) + "\n")
                fh.flush()
                if fh.tell() > self.persist_max_bytes:
                    fh.close()
                    os.replace(self.persist_path, self.persist_path + ".1")
                    self._fh = open(self.persist_path, "a")
            except (OSError, ValueError) as e:
                logger.warning(f"fleet store persistence failed: {e}")
                self._fh = None

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None


def load_series_jsonl(
    store: SeriesStore, path: str, source: Optional[str] = None, include_rotated: bool = True
) -> int:
    """Replay a persisted JSONL file (store persistence or a trainer
    metrics.jsonl) into ``store``.  Torn-tail tolerant: a half-written final
    line (crash mid-flush) or any corrupt line is skipped, everything parseable
    is kept.  Reads ``path.1`` first when present so rotation keeps order.
    Returns the number of records ingested."""
    n = 0
    paths = ([path + ".1"] if include_rotated and os.path.exists(path + ".1") else []) + [path]
    for p in paths:
        if not os.path.exists(p):
            continue
        with open(p, "r", errors="replace") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    record = json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn tail / corruption: skip, keep the rest
                if isinstance(record, dict):
                    store.ingest_record(record, source=source)
                    n += 1
    return n


# -- HTTP scraping ------------------------------------------------------------


def _http_get(host: str, port: int, path: str, timeout_s: float) -> Tuple[int, bytes]:
    """Minimal GET via http.client (deliberately not urllib: no proxy-env
    surprises inside test sandboxes).  Raises OSError-family on connect
    failure; returns (status, body) otherwise — 503 healthz bodies are data,
    not errors."""
    conn = http.client.HTTPConnection(host, port, timeout=timeout_s)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


class FleetCollector:
    """Scrapes a fleet of HTTP endpoints into a :class:`SeriesStore`.

    ``endpoints`` is a zero-arg callable returning ``{source: (host, port)}``
    — the supervisor's ``endpoints()`` shape; ``port=None`` means the replica
    has not published its port yet and scores as down.  A "router" entry is
    just another source.  Each round:

    1. GET ``/healthz``: ``up`` (1.0 iff HTTP 200), numeric payload fields
       (queue_depth, active_slots, ...), status-flip events.
    2. GET ``/metrics``: gauges/counters via :func:`parse_prometheus`;
       histograms become ``<name>_p50``/``<name>_p95`` series from
       per-round bucket deltas (quiet rounds emit no sample); counters
       become ``<name>_per_s`` rate series from deltas; serve-style
       ``requests_finished_total`` reasons collapse into an ``error_rate``
       series.  Router group-health gauges flip into events.
    3. Tail configured metrics.jsonl files (the trainer's) into the store.
    4. Run the SLO engine, if attached.

    One flat record per (source, round) is persisted, so a fleet_report can
    rebuild the store from disk after the supervisor dies.
    """

    def __init__(
        self,
        endpoints: Callable[[], Mapping[str, Tuple[str, Optional[int]]]],
        *,
        store: Optional[SeriesStore] = None,
        slo_engine=None,
        cadence_s: float = 1.0,
        timeout_s: float = 0.5,
        persist_path: Optional[str] = None,
        jsonl_sources: Optional[Mapping[str, str]] = None,
    ):
        self.endpoints = endpoints
        self.store = store or SeriesStore(persist_path=persist_path)
        self.slo = slo_engine
        self.cadence_s = cadence_s
        self.timeout_s = timeout_s
        self.jsonl_sources = dict(jsonl_sources or {})
        self.metrics = MetricsRegistry(namespace="relora_fleet")
        # fleet-wide prefix-page directory (serve/disagg): fed from the
        # prefix_digests list each replica advertises on /healthz, served to
        # replicas via /fleet/prefix so a local PrefixCache miss becomes a
        # peer fetch instead of a recompute
        self.directory = PrefixPageDirectory()
        self._jsonl_offsets: Dict[str, int] = {}
        self._prev_counters: Dict[Tuple[str, str], Tuple[float, float]] = {}
        self._prev_hist_buckets: Dict[Tuple[str, str], Dict[float, float]] = {}
        self._last_status: Dict[str, str] = {}
        self._last_gauges: Dict[Tuple[str, str], float] = {}
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- one scrape round ---------------------------------------------------

    def scrape_once(self, now: Optional[float] = None) -> Dict[str, float]:
        """One scrape round over all endpoints.  Returns {source: up}."""
        now = time.time() if now is None else now
        t0 = time.monotonic()
        ups: Dict[str, float] = {}
        for source, (host, port) in sorted(self.endpoints().items()):
            ups[source] = self._scrape_target(source, host, port, now)
        for source, path in self.jsonl_sources.items():
            self._tail_jsonl(source, path)
        self.metrics.inc("scrape_rounds_total")
        self.metrics.set_gauge("sources_known", len(ups))
        self.metrics.set_gauge("sources_up", sum(1 for u in ups.values() if u >= 1.0))
        self.metrics.set_gauge("last_scrape_duration_s", time.monotonic() - t0)
        if self.slo is not None:
            self.slo.evaluate(self.store, now=now)
            self.metrics.set_gauge("alerts_firing", len(self.slo.active_alerts()))
        return ups

    def _scrape_target(self, source: str, host: str, port: Optional[int], now: float) -> float:
        values: Dict[str, float] = {}
        status_str = "down"
        up = 0.0
        if port is not None:
            try:
                code, body = _http_get(host, port, "/healthz", self.timeout_s)
                up = 1.0 if code == 200 else 0.0
                try:
                    payload = json.loads(body)
                    status_str = str(payload.get("status", code))
                    for k, v in payload.items():
                        if isinstance(v, (int, float)) and not isinstance(v, bool):
                            values[f"healthz_{k}"] = float(v)
                    digests = payload.get("prefix_digests")
                    if code == 200 and isinstance(digests, list):
                        self.directory.update(
                            source, host, int(port), [str(d) for d in digests]
                        )
                except (json.JSONDecodeError, AttributeError):
                    status_str = str(code)
            except OSError:
                self.metrics.inc("scrape_errors_total", ("source", source))
            try:
                code, body = _http_get(host, port, "/metrics", self.timeout_s)
                if code == 200:
                    self._ingest_metrics(source, body.decode(errors="replace"), values, now)
            except OSError:
                self.metrics.inc("scrape_errors_total", ("source", source))
        values["up"] = up
        if up < 1.0:
            # a down replica's pages are unreachable; stale directory entries
            # would send fetchers into connect timeouts until the next scrape
            self.directory.drop_replica(source)
        prev_status = self._last_status.get(source)
        if prev_status is not None and prev_status != status_str:
            self.store.add_event(
                "health_flip", source, t=now, frm=prev_status, to=status_str
            )
            logger.info(f"fleet: {source} health {prev_status} -> {status_str}")
        self._last_status[source] = status_str
        self.metrics.inc("scrapes_total", ("source", source))
        self.store.add_samples(source, values, t=now)
        return up

    def _ingest_metrics(self, source: str, text: str, values: Dict[str, float], now: float) -> None:
        flat, hists = parse_prometheus(text)
        finished_total = 0.0
        finished_bad = 0.0
        spec_drafted = None
        spec_accepted = None
        evict_delta = None
        mig_fail_delta = None
        disp_delta = None
        round_delta = None
        disp_tokens = None
        disp_real = None
        for name, value in flat.items():
            values[name] = value
            # drafting-mode gauge (1 = model draft, 0 = ngram), aliased to
            # its bare name so the replica comparison's spec_acc mode suffix
            # reads one series whatever the registry namespace
            if name.endswith("spec_mode_model"):
                values["spec_mode_model"] = value
            if name.endswith("_total") or "_total." in name:
                prev = self._prev_counters.get((source, name))
                self._prev_counters[(source, name)] = (now, value)
                if prev is not None and now > prev[0]:
                    rate = max(0.0, value - prev[1]) / (now - prev[0])
                    values[f"{name}_per_s"] = rate
                if "requests_finished_total." in name:
                    delta = max(0.0, value - prev[1]) if prev is not None else value
                    finished_total += delta
                    if name.endswith(".error"):
                        finished_bad += delta
                # per-replica speculative accept rate from counter deltas
                # (falls back to lifetime totals on the first scrape)
                if name.endswith("spec_drafted_total"):
                    spec_drafted = max(0.0, value - prev[1]) if prev is not None else value
                elif name.endswith("spec_accepted_total"):
                    spec_accepted = max(0.0, value - prev[1]) if prev is not None else value
                # adapter churn is a delta, not a lifetime total: the first
                # scrape contributes 0 so a report rebuilt from disk does not
                # see the whole run's evictions as one giant round
                elif name.endswith("adapter_evictions_total"):
                    evict_delta = max(0.0, value - prev[1]) if prev is not None else 0.0
                # KV-migration fail-open falls are a delta for the same
                # reason: a rebuilt report must not replay lifetime failures
                # as one round's incident
                elif name.endswith("migration_failures_total"):
                    mig_fail_delta = max(0.0, value - prev[1]) if prev is not None else 0.0
                # packed-dispatch economics from counter deltas: how many
                # model dispatches a scheduler round costs, and how much of
                # each packed dispatch was real work vs bucket padding
                elif name.endswith("model_dispatches_total"):
                    disp_delta = max(0.0, value - prev[1]) if prev is not None else value
                elif name.endswith("sched_rounds_total"):
                    round_delta = max(0.0, value - prev[1]) if prev is not None else value
                elif name.endswith("dispatch_tokens_real_total"):
                    disp_real = max(0.0, value - prev[1]) if prev is not None else value
                elif name.endswith("dispatch_tokens_total"):
                    disp_tokens = max(0.0, value - prev[1]) if prev is not None else value
            if "group_" in name and name.endswith("_healthy"):
                prev_g = self._last_gauges.get((source, name))
                if prev_g is not None and prev_g != value:
                    self.store.add_event(
                        "group_health_flip", source, t=now, gauge=name, frm=prev_g, to=value
                    )
                self._last_gauges[(source, name)] = value
        if finished_total > 0:
            values["error_rate"] = finished_bad / finished_total
        elif any("requests_finished_total" in k for k in flat):
            values["error_rate"] = 0.0
        if spec_drafted is not None:
            values["spec_accept_rate"] = (
                (spec_accepted or 0.0) / spec_drafted if spec_drafted > 0 else 0.0
            )
        if disp_delta is not None and round_delta is not None and round_delta > 0:
            values["dispatches_per_round"] = disp_delta / round_delta
        if disp_tokens is not None and disp_delta is not None and disp_delta > 0:
            values["tokens_per_dispatch"] = disp_tokens / disp_delta
        if disp_real is not None and disp_tokens is not None and disp_tokens > 0:
            values["packed_token_utilization"] = disp_real / disp_tokens
        if evict_delta is not None:
            # per-replica adapter churn: evictions this round.  A round that
            # turns over the whole slot pool means tenants are thrashing each
            # other's slots — the operations.md triage is "raise
            # --adapter-slots", so surface it on the fleet timeline.
            values["adapter_churn"] = evict_delta
            slots_used = next(
                (v for k, v in flat.items() if k.endswith("adapter_slots_used")), None
            )
            if evict_delta >= max(2.0, slots_used or 0.0):
                self.store.add_event(
                    "adapter_thrash", source, t=now,
                    evictions=evict_delta, slots_used=slots_used,
                )
        if mig_fail_delta:
            # every fall back to local decode is a typed event on the fleet
            # timeline (docs/operations.md "migration_failed" runbook) — the
            # request was served, but the disagg tier is leaking work
            self.store.add_event(
                "migration_failed", source, t=now, failures=mig_fail_delta
            )
        for name, h in hists.items():
            # Quantiles of the *recent* distribution, from bucket deltas
            # between scrape rounds.  The exposition is cumulative over the
            # replica's lifetime; a lifetime p95 never recovers from one
            # compile storm, which would latch the autoscaler's burn signal
            # above target long after traffic has drained.  A round with no
            # new observations emits no sample at all (the series goes
            # quiet rather than repeating a stale value), so windowed
            # readers like AutoscalerPolicy see only live traffic.
            prev_b = self._prev_hist_buckets.get((source, name))
            self._prev_hist_buckets[(source, name)] = {
                le: c for le, c in h["buckets"]
            }
            if prev_b is None:
                delta = h["buckets"]  # first scrape: lifetime is the window
            else:
                delta = [
                    (le, max(0.0, c - prev_b.get(le, 0.0)))
                    for le, c in h["buckets"]
                ]
            if delta and max(c for _, c in delta) > 0:
                values[f"{name}_p50"] = histogram_quantile(delta, 0.50)
                values[f"{name}_p95"] = histogram_quantile(delta, 0.95)

    def _tail_jsonl(self, source: str, path: str) -> None:
        """Incrementally ingest new complete lines of a metrics.jsonl file.
        A torn tail (no trailing newline yet) is left for the next round; a
        truncated/rotated file resets the offset."""
        try:
            size = os.path.getsize(path)
        except OSError:
            return
        offset = self._jsonl_offsets.get(path, 0)
        if size < offset:
            offset = 0  # rotated or truncated underneath us
        if size == offset:
            return
        try:
            with open(path, "r", errors="replace") as fh:
                fh.seek(offset)
                chunk = fh.read(size - offset)
        except OSError:
            return
        complete, _, tail = chunk.rpartition("\n")
        self._jsonl_offsets[path] = size - len(tail.encode())
        for line in complete.splitlines():
            try:
                record = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(record, dict):
                self.store.ingest_record(record, source=source)

    # -- supervisor integration ---------------------------------------------

    def record_supervisor_event(self, event: str, replica_idx, detail: str) -> None:
        """`ReplicaSupervisor.on_event` adapter: restarts, quarantines,
        rolling-drain steps, and deployment transitions become store events
        on the fleet timeline.  ``deploy_*`` events (the rolling updater's
        lifecycle) and ``autoscale_*`` events (elastic scaling decisions)
        keep their own namespaces; everything else gets the ``supervisor_``
        prefix.  ``replica_idx`` may be an int index or an rid string
        ("r0"); None means the fleet as a whole."""
        if replica_idx is None:
            source = "supervisor"
        elif isinstance(replica_idx, int):
            source = f"r{replica_idx}"
        else:
            source = str(replica_idx)
        kind = (
            event
            if event.startswith(("deploy_", "autoscale_"))
            else f"supervisor_{event}"
        )
        self.store.add_event(kind, source, detail=detail)

    # -- background loop ----------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, name="fleet-collector", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=max(2.0, 2 * self.cadence_s))
        self.store.close()

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.scrape_once()
            except Exception as e:  # never kill the supervisor over a scrape
                self.metrics.inc("scrape_round_failures_total")
                logger.warning(f"fleet scrape round failed: {e}")
            self._stop.wait(self.cadence_s)

    # -- exposure ------------------------------------------------------------

    def render_metrics(self) -> str:
        """``/fleet/metrics`` body: the collector's own registry plus a
        per-source ``up`` gauge and firing-alert gauges."""
        for source in self.store.sources():
            latest = self.store.latest(source, "up")
            if latest is not None:
                self.metrics.set_gauge(f"source_{source}_up", latest[1])
        if self.slo is not None:
            for alert in self.slo.active_alerts():
                self.metrics.set_gauge(f"alert_{alert.key()}_firing", 1)
        return self.metrics.render()

    def series_payload(
        self,
        source: Optional[str] = None,
        series: Optional[str] = None,
        since: Optional[float] = None,
        last: int = 256,
    ) -> Dict[str, Any]:
        """``/fleet/series`` body: JSON time series + events (+ SLO status)."""
        out: Dict[str, Any] = {"sources": {}, "events": self.store.events(since=since)}
        for src in self.store.sources():
            if source is not None and src != source:
                continue
            names = self.store.series_names(src)
            if series is not None:
                names = [n for n in names if n == series]
            out["sources"][src] = {
                n: [[round(t, 3), v] for (t, v) in self.store.samples(src, n, since=since)][-last:]
                for n in names
            }
        if self.slo is not None:
            out["slo"] = self.slo.status()
        return out

    def handle_fleet_route(self, path: str) -> Optional[Tuple[int, str, bytes]]:
        """Shared HTTP routing for ``/fleet/*``: returns (status,
        content_type, body) or None when the path is not a fleet route.
        Query strings: ``/fleet/series?source=r0&series=up&last=64``."""
        from urllib.parse import parse_qs, urlsplit

        parts = urlsplit(path)
        if parts.path == "/fleet/metrics":
            return 200, "text/plain; version=0.0.4", self.render_metrics().encode()
        if parts.path == "/fleet/series":
            q = parse_qs(parts.query)

            def one(key: str) -> Optional[str]:
                vals = q.get(key)
                return vals[0] if vals else None

            last_s = one("last")
            payload = self.series_payload(
                source=one("source"),
                series=one("series"),
                last=int(last_s) if last_s and last_s.isdigit() else 256,
            )
            return 200, "application/json", json.dumps(payload).encode()
        if parts.path == "/fleet/prefix":
            q = parse_qs(parts.query)
            raw = (q.get("d") or [""])[0]
            digests = [d for d in raw.split(",") if d]
            exclude = (q.get("exclude") or [None])[0]
            hit = self.directory.lookup(digests, exclude_rid=exclude) if digests else None
            if hit is None:
                body = json.dumps({"error": "no holder known"}).encode()
                return 404, "application/json", body
            digest, rid, host, port = hit
            body = json.dumps(
                {"digest": digest, "replica": rid, "host": host, "port": port}
            ).encode()
            return 200, "application/json", body
        return None


# -- standalone CLI -----------------------------------------------------------


def _parse_target(spec: str) -> Tuple[str, Tuple[str, int]]:
    """``name=host:port`` -> (name, (host, port))."""
    name, _, addr = spec.partition("=")
    host, _, port = addr.rpartition(":")
    return name, (host or "127.0.0.1", int(port))


def main(argv: Optional[List[str]] = None) -> int:
    """Standalone collector: scrape targets on a cadence and serve
    ``/fleet/metrics`` + ``/fleet/series`` over its own tiny HTTP server —
    the same plane the supervisor hosts, runnable against any fleet."""
    ap = argparse.ArgumentParser(description="standalone fleet metrics collector")
    ap.add_argument("--target", action="append", default=[], metavar="NAME=HOST:PORT",
                    help="scrape target (repeatable), e.g. r0=127.0.0.1:8101")
    ap.add_argument("--train-jsonl", action="append", default=[], metavar="NAME=PATH",
                    help="metrics.jsonl file to tail into the store (repeatable)")
    ap.add_argument("--cadence-s", type=float, default=1.0)
    ap.add_argument("--timeout-s", type=float, default=0.5)
    ap.add_argument("--persist", default=None, help="JSONL persistence path")
    ap.add_argument("--slo-config", default=None, help="JSON SLO config (see docs)")
    ap.add_argument("--port", type=int, default=0, help="HTTP port for /fleet/* (0 = ephemeral)")
    ap.add_argument("--port-file", default=None, help="write the bound port here")
    ap.add_argument("--rounds", type=int, default=0, help="scrape N rounds then exit (0 = forever)")
    args = ap.parse_args(argv)

    from relora_tpu.obs.slo import SLOEngine

    targets = dict(_parse_target(s) for s in args.target)
    jsonl_sources = dict(s.partition("=")[::2] for s in args.train_jsonl)
    engine = SLOEngine.from_config(args.slo_config)
    collector = FleetCollector(
        lambda: targets,
        slo_engine=engine,
        cadence_s=args.cadence_s,
        timeout_s=args.timeout_s,
        persist_path=args.persist,
        jsonl_sources=jsonl_sources,
    )

    if args.rounds > 0:
        for _ in range(args.rounds):
            collector.scrape_once()
            time.sleep(args.cadence_s)
        print(json.dumps(collector.series_payload(), indent=2))
        collector.store.close()
        return 0

    import http.server

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self) -> None:  # noqa: N802 (stdlib API)
            routed = collector.handle_fleet_route(self.path)
            if routed is None:
                self.send_error(404)
                return
            status, ctype, body = routed
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, fmt: str, *fa: Any) -> None:
            pass  # quiet: the collector logs transitions itself

    httpd = http.server.ThreadingHTTPServer(("127.0.0.1", args.port), Handler)
    if args.port_file:
        with open(args.port_file, "w") as fh:
            fh.write(str(httpd.server_address[1]))
    collector.start()
    logger.info(f"fleet collector on 127.0.0.1:{httpd.server_address[1]} "
                f"scraping {sorted(targets)} every {args.cadence_s}s")
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        collector.stop()
        httpd.server_close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
