from relora_tpu.train.losses import causal_lm_loss
