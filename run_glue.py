"""GLUE / text-classification fine-tuning CLI — the reference run_glue.py
equivalent (reference run_glue.py:209-623).

Fine-tunes a (ReLoRA-)pretrained checkpoint on a GLUE task — or any custom
csv/json classification dataset — and reports the task metrics.  Knob parity
with the reference's HfArgumentParser surface: task or custom files, sample
caps for train/eval/predict, padding strategy, do_train/do_eval/do_predict,
label remapping inferred from the training split, regression (stsb), and an
output dir holding ``all_results.json`` + ``predict_results_{task}.txt``.
(The reference forces ``save_strategy="no"`` — GLUE runs don't checkpoint —
so there is deliberately no resume path here either.)

Examples::

    # a GLUE task from the hub (network required)
    python run_glue.py --task_name sst2 --model_config llama_250m \
        --checkpoint ckpts/relora/model_20000 --tokenizer t5-base \
        --batch_size 32 --num_epochs 3 --max_seq_length 128

    # a custom csv (columns: sentence[,sentence2],label) with a local
    # tokenizer.json (air-gapped hosts)
    python run_glue.py --task_name myset --train_file train.csv \
        --validation_file dev.csv --test_file test.csv --do_predict true \
        --model_config llama_35m --checkpoint ckpts/relora/model_8000 \
        --tokenizer /data/corpus.tokenizer.json --output_dir glue_out
"""

from __future__ import annotations

import argparse
import csv
import json
import os


def _flag(x) -> bool:
    return str(x).lower() == "true"


def parse_args(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--task_name", required=True,
                   help="GLUE task, or a name for a custom-file dataset")
    p.add_argument("--train_file", default=None, help="custom csv/json train split")
    p.add_argument("--validation_file", default=None, help="custom csv/json validation split")
    p.add_argument("--test_file", default=None, help="custom csv/json test split (do_predict)")
    p.add_argument("--model_config", required=True)
    p.add_argument("--checkpoint", default=None, help="relora-tpu checkpoint dir (model_N)")
    p.add_argument("--tokenizer", required=True,
                   help="HF tokenizer name/dir, or a local tokenizers-json file")
    # reference HF-Trainer flag names accepted as aliases (run_glue.py parity)
    p.add_argument("--lr", "--learning_rate", type=float, default=2e-5)
    p.add_argument(
        "--batch_size", "--per_device_train_batch_size", type=int, default=32
    )
    p.add_argument("--num_epochs", "--num_train_epochs", type=int, default=3)
    p.add_argument("--max_seq_length", "--max_length", dest="max_seq_length",
                   type=int, default=128)
    p.add_argument("--pad_to_max_length", type=_flag, default=True,
                   help="false = dynamic padding to the batch max (rounded up "
                        "to 32 to bound recompiles)")
    p.add_argument("--weight_decay", type=float, default=0.01)
    p.add_argument("--use_lora", type=_flag, default=False)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--max_train_samples", type=int, default=None)
    p.add_argument("--max_eval_samples", type=int, default=None)
    p.add_argument("--max_predict_samples", type=int, default=None)
    p.add_argument("--do_train", type=_flag, default=True)
    p.add_argument("--do_eval", type=_flag, default=True)
    p.add_argument("--do_predict", type=_flag, default=False)
    p.add_argument("--output_dir", default=None)
    p.add_argument("--overwrite_output_dir", type=_flag, default=False)
    return p.parse_args(argv)


def load_tokenizer(name_or_path: str):
    """HF tokenizer by name/dir, or a raw ``tokenizers`` JSON file (the
    air-gapped path — e.g. tools/build_text_corpus.py output)."""
    from transformers import AutoTokenizer, PreTrainedTokenizerFast

    if name_or_path.endswith(".json") and os.path.exists(name_or_path):
        tok = PreTrainedTokenizerFast(tokenizer_file=name_or_path)
        if tok.pad_token_id is None:
            tok.add_special_tokens({"pad_token": "<pad>"})
        return tok
    tok = AutoTokenizer.from_pretrained(name_or_path)
    if tok.pad_token_id is None:
        tok.pad_token = tok.eos_token
    return tok


def read_split(path: str):
    """csv or json-lines split -> list of dicts (parity: data_files loading,
    run_glue.py:342-367)."""
    rows = []
    if path.endswith(".csv"):
        with open(path, newline="") as f:
            rows = list(csv.DictReader(f))
    else:
        with open(path) as f:
            rows = [json.loads(line) for line in f if line.strip()]
    if not rows:
        raise ValueError(f"{path} is empty")
    return rows


def main(argv=None):
    args = parse_args(argv)

    from relora_tpu.utils.logging import honor_platform_request

    honor_platform_request()

    import numpy as np

    from relora_tpu.config.model import load_model_config
    from relora_tpu.eval.glue import GlueConfig, TASK_NUM_LABELS, TASK_TO_KEYS, finetune

    model_cfg = load_model_config(args.model_config)
    tokenizer = load_tokenizer(args.tokenizer)
    is_custom = any(
        f is not None for f in (args.train_file, args.validation_file, args.test_file)
    )
    is_regression = args.task_name == "stsb"

    # ---- load splits ------------------------------------------------------
    if is_custom:
        needed = []
        if args.do_train and not args.train_file:
            needed.append("--train_file (do_train)")
        if args.do_eval and not args.validation_file:
            needed.append("--validation_file (do_eval)")
        if args.do_predict and not args.test_file:
            needed.append("--test_file (do_predict)")
        if needed:
            raise ValueError(
                "custom-file mode is missing required splits: " + ", ".join(needed)
                + " — pass the file or disable the stage"
            )
        raw = {}
        if args.train_file:
            raw["train"] = read_split(args.train_file)
        if args.validation_file:
            raw["validation"] = read_split(args.validation_file)
        if args.test_file:
            raw["test"] = read_split(args.test_file)
        cols = [c for c in raw[next(iter(raw))][0] if c != "label"]
        key1, key2 = cols[0], (cols[1] if len(cols) > 1 else None)
        # infer regression from float-typed labels, the reference's
        # behavior for user datasets (run_glue.py:392-398 checks the label
        # feature dtype).  CSV labels are strings, so "float-typed" means
        # every label parses as a float and at least one is not an integer
        # literal — {"0","1"} stays classification, {"0.0","3.3"} is
        # regression.
        if not is_regression:
            # empty label cells (an unlabeled CSV test split reads as "")
            # are skipped per-row, not allowed to void the inference
            seen = [
                s
                for split in raw.values()
                for r in split
                if (s := str(r.get("label", "")).strip())
            ]

            def _as_float(s: str):
                try:
                    return float(s)
                except ValueError:
                    return None

            vals = [_as_float(s) for s in seen]
            # decimal-literal check (not int(v) comparison: "inf"/"nan"
            # would overflow or false-positive) — {"0","1"} stays
            # classification, {"0.0","3.3","1e-1"} is regression
            is_regression = bool(seen) and all(v is not None for v in vals) and any(
                "." in s or "e" in s.lower() for s in seen
            )
    else:
        import datasets

        hub = datasets.load_dataset("glue", args.task_name)
        eval_split = "validation_matched" if args.task_name == "mnli" else "validation"
        raw = {"train": hub["train"], "validation": hub[eval_split]}
        if args.do_predict:
            raw["test"] = hub["test_matched" if args.task_name == "mnli" else "test"]
        key1, key2 = TASK_TO_KEYS[args.task_name]

    # ---- label remapping (parity: run_glue.py:392-411, 466-470) -----------
    if is_regression:
        num_labels, label2id, id2label = 1, None, None
    elif is_custom:
        # infer the label set from a split that actually carries labels
        # (predict-only runs may load just an unlabeled test file)
        labeled = next(
            (
                raw[name]
                for name in ("train", "validation", "test")
                if raw.get(name) and "label" in raw[name][0]
            ),
            None,
        )
        if labeled is None:
            raise SystemExit(
                "custom task needs at least one split with a 'label' column "
                "to infer the label set (got only unlabeled files)"
            )
        label_list = sorted({str(r["label"]) for r in labeled})
        label2id = {l: i for i, l in enumerate(label_list)}
        id2label = {i: l for l, i in label2id.items()}
        num_labels = len(label_list)
    else:
        num_labels, label2id = TASK_NUM_LABELS[args.task_name], None
        # hub tasks: predictions are written as label NAMES (parity:
        # label_list[item], run_glue.py:601-614)
        feat = raw["train"].features["label"]
        names = getattr(feat, "names", None)
        id2label = dict(enumerate(names)) if names else None

    # ---- tokenize ---------------------------------------------------------
    def encode(split, limit=None, with_labels=True):
        rows = raw[split]
        if limit is not None:
            rows = rows[: min(limit, len(rows))] if is_custom else rows.select(
                range(min(limit, len(rows)))
            )
        texts1 = [r[key1] for r in rows] if is_custom else rows[key1]
        pair = ([r[key2] for r in rows] if is_custom else rows[key2]) if key2 else None
        enc = tokenizer(
            texts1, pair,
            truncation=True,
            max_length=args.max_seq_length,
            padding="max_length" if args.pad_to_max_length else "longest",
        )
        ids = np.asarray(enc["input_ids"], dtype=np.int32)
        if not with_labels:
            return ids, None
        rl = [r["label"] for r in rows] if is_custom else rows["label"]
        if is_regression:
            labels = np.asarray(rl, dtype=np.float32)
        elif label2id is not None:
            labels = np.asarray([label2id[str(l)] for l in rl])
        else:
            labels = np.asarray(rl)
        return ids, labels

    bs = args.batch_size

    def pad_bucket(batch_ids):
        """Dynamic padding: trim to the longest row, rounded up to 32 so the
        jitted step sees a handful of shapes, not one per batch."""
        if args.pad_to_max_length:
            return batch_ids
        pad_id = tokenizer.pad_token_id or 0
        lengths = (batch_ids != pad_id).sum(axis=1)
        width = min(args.max_seq_length, max(32, int(-(-lengths.max() // 32) * 32)))
        return batch_ids[:, :width]

    train_ids, train_labels = (None, None)
    steps_per_epoch = 1
    if args.do_train:
        train_ids, train_labels = encode("train", args.max_train_samples)
        steps_per_epoch = max(1, len(train_ids) // bs)

    eval_ids, eval_labels = (None, None)
    if args.do_eval:
        eval_ids, eval_labels = encode("validation", args.max_eval_samples)

    epoch_counter = iter(range(10**9))

    def train_batches():
        # fresh shuffle each epoch (finetune() calls this once per epoch;
        # HF-Trainer parity — a fixed seed would replay epoch 1's order)
        rs = np.random.RandomState(args.seed + next(epoch_counter))
        order = rs.permutation(len(train_ids))
        for i in range(steps_per_epoch):
            sel = order[i * bs : (i + 1) * bs]
            yield pad_bucket(train_ids[sel]), train_labels[sel]

    def eval_batches():
        for i in range(0, len(eval_ids), bs):
            sel = slice(i, min(i + bs, len(eval_ids)))
            yield pad_bucket(eval_ids[sel]), eval_labels[sel]

    predict_batches = None
    if args.do_predict:
        test_ids, _ = encode("test", args.max_predict_samples, with_labels=False)

        def predict_batches():
            for i in range(0, len(test_ids), bs):
                yield pad_bucket(test_ids[i : i + bs])

    # fail on a dirty output dir BEFORE the (possibly hours-long) finetune
    # (parity: HF TrainingArguments errors at startup)
    if args.output_dir and os.path.isdir(args.output_dir) and os.listdir(args.output_dir):
        if not args.overwrite_output_dir:
            raise ValueError(
                f"output_dir {args.output_dir} exists and is not empty "
                "(use --overwrite_output_dir true)"
            )

    # ---- checkpoint backbone (merge LoRA first if present) ----------------
    pretrained = None
    if args.checkpoint:
        from relora_tpu.core.relora import merged_params
        from relora_tpu.train.checkpoint import load_lora_spec, restore_params_host

        pretrained = restore_params_host(args.checkpoint)
        spec = load_lora_spec(args.checkpoint)
        if spec is not None:
            # an unmerged ReLoRA checkpoint: fold A@B*scale into the base so
            # the classifier starts from the equivalent full-rank model
            pretrained = merged_params(pretrained, spec)

    gcfg = GlueConfig(
        task=args.task_name,
        lr=args.lr,
        batch_size=bs,
        num_epochs=args.num_epochs,
        max_length=args.max_seq_length,
        weight_decay=args.weight_decay,
        use_lora=args.use_lora,
        seed=args.seed,
        num_labels=num_labels,
    )
    metrics, predictions = finetune(
        model_cfg,
        gcfg,
        train_batches,
        eval_batches,
        steps_per_epoch,
        pad_token_id=tokenizer.pad_token_id or 0,
        pretrained_backbone=pretrained,
        predict_batches=predict_batches,
        do_train=args.do_train,
        do_eval=args.do_eval,
    )

    # parity: HF Trainer prefixes evaluation metrics with eval_ in
    # all_results.json (trainer.evaluate -> eval_accuracy etc.)
    result = {"task": args.task_name}
    for k, v in metrics.items():
        result[k if k.startswith(("eval_", "train_")) else f"eval_{k}"] = v
    print(json.dumps(result))
    if args.output_dir:
        os.makedirs(args.output_dir, exist_ok=True)
        with open(os.path.join(args.output_dir, "all_results.json"), "w") as f:
            json.dump(result, f, indent=2)
        if predictions is not None:
            # parity: predict_results_{task}.txt, run_glue.py:601-614
            out = os.path.join(args.output_dir, f"predict_results_{args.task_name}.txt")
            with open(out, "w") as f:
                f.write("index\tprediction\n")
                for i, pred in enumerate(predictions):
                    if is_regression:
                        f.write(f"{i}\t{float(pred):.3f}\n")
                    else:
                        label = id2label[int(pred)] if id2label else int(pred)
                        f.write(f"{i}\t{label}\n")
    return result


if __name__ == "__main__":
    main()
