"""Ring attention: exact causal attention over a sequence-sharded mesh axis.

A capability the reference does not have (SURVEY.md §5.7 — max trained
context 2048, plain SDPA): long sequences are sharded over the ``sequence``
mesh axis; each device keeps its resident query block and streams K/V blocks
around the ring with ``ppermute`` over ICI, folding each block into a
streaming-softmax (flash-style m/l/o) accumulator.  Communication overlaps
compute block-by-block, memory per device is O(S/ring · S/ring) for scores
and O(S/ring) for activations, and the result is numerically exact (not an
approximation) — verified against single-device attention in tests.

Causality is handled at block granularity: a K/V block strictly in the
future of the resident query block contributes nothing (skipped via masking
to -inf), the diagonal block applies the intra-block causal mask, and past
blocks attend densely.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

from relora_tpu.parallel.mesh import DATA_AXIS, FSDP_AXIS, SEQUENCE_AXIS

_NEG_INF = -1e30  # finite sentinel: keeps exp()/where math NaN-free


def _ring_attention_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str,
    causal: bool,
    scale: float,
) -> jax.Array:
    """Per-device body (runs under shard_map).  Shapes (B, S_local, N, H)."""
    ring = jax.lax.axis_size(axis_name)
    me = jax.lax.axis_index(axis_name)
    B, S, N, H = q.shape

    qf = q.astype(jnp.float32)
    q_pos = me * S + jnp.arange(S)

    o0 = jnp.zeros((B, N, S, H), jnp.float32)
    l0 = jnp.zeros((B, N, S), jnp.float32)
    m0 = jnp.full((B, N, S), _NEG_INF, jnp.float32)

    def fold(i, carry):
        o, l, m, k_blk, v_blk = carry
        # which global block is resident after i rotations (blocks travel
        # to the next-higher index each step, so we see me, me-1, ...)
        src = (me - i) % ring
        scores = jnp.einsum("bqnh,bknh->bnqk", qf, k_blk.astype(jnp.float32)) * scale
        if causal:
            k_pos = src * S + jnp.arange(S)
            visible = k_pos[None, :] <= q_pos[:, None]
            scores = jnp.where(visible[None, None], scores, _NEG_INF)

        blk_max = jnp.max(scores, axis=-1)
        m_new = jnp.maximum(m, blk_max)
        p = jnp.exp(scores - m_new[..., None])
        # rows with no visible keys yet: m_new stays at the sentinel and the
        # exp() above evaluated exp(0)=1 on masked lanes — zero them out
        p = jnp.where(scores <= _NEG_INF / 2, 0.0, p)
        correction = jnp.exp(m - m_new)
        l = l * correction + jnp.sum(p, axis=-1)
        o = o * correction[..., None] + jnp.einsum(
            "bnqk,bknh->bnqh", p, v_blk.astype(jnp.float32)
        )

        k_blk, v_blk = jax.lax.ppermute(
            (k_blk, v_blk),
            axis_name,
            perm=[(j, (j + 1) % ring) for j in range(ring)],
        )
        return o, l, m_new, k_blk, v_blk

    o, l, m, _, _ = jax.lax.fori_loop(0, ring, fold, (o0, l0, m0, k, v))
    out = o / jnp.maximum(l[..., None], 1e-30)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    seq_axis: str = SEQUENCE_AXIS,
) -> jax.Array:
    """Causal attention over (B, S, N, H) arrays whose S dim is sharded on
    ``seq_axis``.  Composable with jit: shard_map slots into the surrounding
    GSPMD program."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    spec = P((DATA_AXIS, FSDP_AXIS), seq_axis, None, None)
    fn = shard_map(
        functools.partial(
            _ring_attention_local, axis_name=seq_axis, causal=causal, scale=scale
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        # the streaming accumulators start replicated-typed and become
        # device-varying after the first fold; skip the static vma check
        check_vma=False,
    )
    return fn(q, k, v)


# ---------------------------------------------------------------------------
# Zigzag layout: causal load balancing
#
# With contiguous sequence shards, causal ring attention wastes half its
# FLOPs: device 0's queries can only ever see block 0, yet every device
# computes (and masks away) every rotation.  The zigzag layout splits the
# sequence into 2·ring chunks and gives device d chunks (d, 2·ring-1-d) —
# one early + one late — so each device's *useful* work is the same, and
# per-(query-chunk, key-chunk) `lax.cond`s skip the provably-invisible
# pairs.  Total computed chunk pairs drop from 4·ring² to ~2·ring² + ring.
#
# The kernel expects inputs already permuted by `zigzag_permutation` along S
# (persist the permuted layout across the model for free gains — RoPE uses
# true positions, so only the loss's token adjacency needs care — or use the
# convenience wrapper below, which permutes/unpermutes around the call).
# ---------------------------------------------------------------------------


def zigzag_permutation(seq_len: int, ring: int):
    """perm[i] = original index of permuted position i (gather indices)."""
    import numpy as np

    if seq_len % (2 * ring):
        raise ValueError(f"seq_len={seq_len} must divide by 2*ring={2*ring}")
    C = seq_len // (2 * ring)
    order = []
    for d in range(ring):
        order.extend(range(d * C, (d + 1) * C))
        order.extend(range((2 * ring - 1 - d) * C, (2 * ring - d) * C))
    return np.asarray(order)


def zigzag_inverse(seq_len: int, ring: int):
    import numpy as np

    perm = zigzag_permutation(seq_len, ring)
    inv = np.empty_like(perm)
    inv[perm] = np.arange(seq_len)
    return inv


def _zz_positions(block: jax.Array, ring: int, C: int):
    """(early_pos, late_pos) for the device holding zigzag block ``block``."""
    early = block * C + jnp.arange(C)
    late = (2 * ring - 1 - block) * C + jnp.arange(C)
    return early, late


def _zz_fold_pair(carry, q, q_pos, k, v, k_pos, scale):
    """Fold one (query-chunk, key-chunk) pair into (o, l, m) accumulators."""
    o, l, m = carry
    scores = jnp.einsum("bqnh,bknh->bnqk", q, k.astype(jnp.float32)) * scale
    visible = k_pos[None, :] <= q_pos[:, None]
    scores = jnp.where(visible[None, None], scores, _NEG_INF)
    blk_max = jnp.max(scores, axis=-1)
    m_new = jnp.maximum(m, blk_max)
    p = jnp.exp(scores - m_new[..., None])
    p = jnp.where(scores <= _NEG_INF / 2, 0.0, p)
    corr = jnp.exp(m - m_new)
    l = l * corr + jnp.sum(p, axis=-1)
    o = o * corr[..., None] + jnp.einsum("bnqk,bknh->bnqh", p, v.astype(jnp.float32))
    return o, l, m_new


def _ring_attention_zigzag_local(q, k, v, *, axis_name: str, scale: float):
    """Per-device body for zigzag layout.  Shapes (B, 2C, N, H) local."""
    ring = jax.lax.axis_size(axis_name)
    me = jax.lax.axis_index(axis_name)
    B, S2, N, H = q.shape
    C = S2 // 2

    qE = q[:, :C].astype(jnp.float32)
    qL = q[:, C:].astype(jnp.float32)
    myE_pos, myL_pos = _zz_positions(me, ring, C)

    def acc0():
        return (
            jnp.zeros((B, N, C, H), jnp.float32),
            jnp.zeros((B, N, C), jnp.float32),
            jnp.full((B, N, C), _NEG_INF, jnp.float32),
        )

    def fold(i, carry):
        accE, accL, k_blk, v_blk = carry
        src = (me - i) % ring
        srcE_pos, srcL_pos = _zz_positions(src, ring, C)
        kE, vE = k_blk[:, :C], v_blk[:, :C]
        kL, vL = k_blk[:, C:], v_blk[:, C:]

        # chunk-level visibility: chunk a sees chunk b iff b's start <= a's
        # end; chunk index order IS position order, so compare block ids.
        # qE chunk id = me, qL id = 2*ring-1-me; kE id = src, kL id = 2*ring-1-src.
        qE_id, qL_id = me, 2 * ring - 1 - me
        kE_id, kL_id = src, 2 * ring - 1 - src

        def maybe(acc, pred, qc, q_pos, kc, vc, k_pos):
            return jax.lax.cond(
                pred,
                lambda c: _zz_fold_pair(c, qc, q_pos, kc, vc, k_pos, scale),
                lambda c: c,
                acc,
            )

        accE = maybe(accE, kE_id <= qE_id, qE, myE_pos, kE, vE, srcE_pos)
        accE = maybe(accE, kL_id <= qE_id, qE, myE_pos, kL, vL, srcL_pos)
        accL = maybe(accL, kE_id <= qL_id, qL, myL_pos, kE, vE, srcE_pos)
        accL = maybe(accL, kL_id <= qL_id, qL, myL_pos, kL, vL, srcL_pos)

        k_blk, v_blk = jax.lax.ppermute(
            (k_blk, v_blk), axis_name, perm=[(j, (j + 1) % ring) for j in range(ring)]
        )
        return accE, accL, k_blk, v_blk

    accE, accL, _, _ = jax.lax.fori_loop(0, ring, fold, (acc0(), acc0(), k, v))

    def finish(acc):
        o, l, m = acc
        return (o / jnp.maximum(l[..., None], 1e-30)).transpose(0, 2, 1, 3)

    return jnp.concatenate([finish(accE), finish(accL)], axis=1).astype(q.dtype)


def ring_attention_zigzag(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    scale: Optional[float] = None,
    seq_axis: str = SEQUENCE_AXIS,
    inputs_permuted: bool = False,
) -> jax.Array:
    """Causal ring attention with zigzag load balancing.

    With ``inputs_permuted=False`` the wrapper gathers into the zigzag layout
    and scatters back around the kernel (convenient, but pays two reshards);
    persist the permuted layout end-to-end and pass ``inputs_permuted=True``
    for the full benefit.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    ring = mesh.shape[seq_axis]
    S = q.shape[1]
    spec = P((DATA_AXIS, FSDP_AXIS), seq_axis, None, None)

    if not inputs_permuted:
        perm = jnp.asarray(zigzag_permutation(S, ring))
        inv = jnp.asarray(zigzag_inverse(S, ring))
        q, k, v = (x[:, perm] for x in (q, k, v))

    fn = shard_map(
        functools.partial(_ring_attention_zigzag_local, axis_name=seq_axis, scale=scale),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    out = fn(q, k, v)
    if not inputs_permuted:
        out = out[:, inv]
    return out
