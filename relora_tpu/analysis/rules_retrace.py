"""RTL1xx — retrace hazards.

jit compiles one program per (shape, dtype, static-value) signature.  Code
that branches Python-side on *traced* values either crashes at trace time
(ConcretizationTypeError) or, worse, silently retraces every call — the
failure mode that killed throughput in the serve scheduler's early drafts
(the whole slot design exists so decode never retraces).

- RTL101: Python ``if``/``while`` on a value derived from a traced
  argument inside a jitted function.  Use ``jnp.where`` / ``lax.cond`` /
  ``lax.while_loop``.  (``x is None`` / ``isinstance`` tests and
  ``.shape``/``.ndim``/``.dtype``-derived conditions are static — fine.)
- RTL102: unhashable or array-valued argument in a static position of a
  jitted call — every call with a fresh list/dict/array retraces (or
  throws).  Pass tuples / hashable scalars.
- RTL103: ``jax.jit(...)`` constructed inside a loop — a fresh jit wrapper
  per iteration defeats the compile cache at best.  Build the jitted
  callable once, outside.
- RTL104: f-string / ``str()`` / ``print()`` on a traced value inside a
  jitted function — formats the tracer object (never the runtime value)
  and bakes the formatted garbage into the compiled program.  Use
  ``jax.debug.print``.
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from relora_tpu.analysis.core import (
    FileContext,
    Finding,
    catalog,
    checker,
    const_int_set,
    const_str_set,
    dotted_name,
    get_kwarg,
    is_jit_call,
    unwrap_partial,
)

catalog(
    RTL101="Python if/while on a traced value inside a jitted function (use jnp.where/lax.cond/lax.while_loop)",
    RTL102="unhashable/array-valued argument in a static position of a jitted call (retraces every call)",
    RTL103="jax.jit constructed inside a loop (build the jitted callable once, outside)",
    RTL104="f-string/str()/print() on a traced value inside a jitted function (formats the tracer; use jax.debug.print)",
)

# attribute reads that yield static (trace-time) values, not tracers
STATIC_ATTRS = frozenset({"shape", "ndim", "dtype", "size"})
# calls whose result is static regardless of argument taint
STATIC_CALLS = frozenset({"len", "isinstance", "type", "hasattr", "getattr"})
ARRAYISH_CALLS = frozenset(
    {"np.array", "np.asarray", "numpy.array", "numpy.asarray", "jnp.array", "jnp.asarray"}
)
STR_CALLS = frozenset({"str", "repr", "format", "print"})


def _jit_statics(call: ast.Call) -> Tuple[FrozenSet[int], FrozenSet[str]]:
    """(static positions, static names) from a jit(-like) call's kwargs."""
    nums = get_kwarg(call, "static_argnums")
    names = get_kwarg(call, "static_argnames")
    return (
        const_int_set(nums) or frozenset() if nums is not None else frozenset(),
        const_str_set(names) or frozenset() if names is not None else frozenset(),
    )


def _collect_defs(tree: ast.Module) -> Dict[str, ast.FunctionDef]:
    defs: Dict[str, ast.FunctionDef] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            defs.setdefault(node.name, node)
    return defs


def _jitted_functions(
    tree: ast.Module, defs: Dict[str, ast.FunctionDef]
) -> Dict[int, Tuple[ast.FunctionDef, FrozenSet[int], FrozenSet[str]]]:
    """Functions traced by jit: decorated (@jax.jit, @partial(jax.jit, ...))
    or referenced by name in a same-module ``jax.jit(fn, ...)`` call.
    Keyed by id(funcdef) to dedupe."""
    jitted: Dict[int, Tuple[ast.FunctionDef, FrozenSet[int], FrozenSet[str]]] = {}

    def mark(fn: ast.FunctionDef, call: Optional[ast.Call]) -> None:
        nums, names = _jit_statics(call) if call is not None else (frozenset(), frozenset())
        jitted.setdefault(id(fn), (fn, nums, names))

    for fn in defs.values():
        for dec in fn.decorator_list:
            if dotted_name(dec) in ("jit", "jax.jit", "pjit"):
                mark(fn, None)
            elif is_jit_call(dec):  # @jax.jit(static_argnums=...)
                mark(fn, dec)
            elif unwrap_partial(dec) is not None:  # @partial(jax.jit, ...)
                mark(fn, unwrap_partial(dec))
    for node in ast.walk(tree):
        if is_jit_call(node) and node.args and isinstance(node.args[0], ast.Name):
            target = defs.get(node.args[0].id)
            if target is not None:
                mark(target, node)
    return jitted


class _Taint:
    """Statement-ordered taint propagation through one jitted function."""

    def __init__(self, ctx: FileContext, fn: ast.FunctionDef, tainted: Set[str]):
        self.ctx = ctx
        self.tainted = tainted
        self.findings: List[Finding] = []
        self._seen_lines: Set[Tuple[int, str]] = set()

    def _emit(self, node: ast.AST, code: str, message: str) -> None:
        key = (getattr(node, "lineno", 0), code)
        if key not in self._seen_lines:
            self._seen_lines.add(key)
            self.findings.append(self.ctx.finding(node, code, message))

    # -- expression taint --------------------------------------------------

    def expr_tainted(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Attribute):
            if node.attr in STATIC_ATTRS:
                return False
            return self.expr_tainted(node.value)
        if isinstance(node, ast.Call):
            name = dotted_name(node.func)
            if name in STATIC_CALLS:
                return False
            parts = [node.func] + list(node.args) + [kw.value for kw in node.keywords]
            return any(self.expr_tainted(p) for p in parts)
        if isinstance(node, ast.Compare):
            # `x is None` / `x is not None`: static trace-time dispatch
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops) and all(
                isinstance(c, ast.Constant) and c.value is None
                for c in node.comparators
            ):
                return False
            return any(
                self.expr_tainted(c) for c in [node.left] + list(node.comparators)
            )
        if isinstance(node, (ast.expr,)):
            return any(
                self.expr_tainted(child)
                for child in ast.iter_child_nodes(node)
                if isinstance(child, ast.expr)
            )
        return False

    # -- RTL104 scan over one statement's expressions ----------------------

    def scan_strings(self, node: ast.AST) -> None:
        for sub in ast.walk(node):
            if isinstance(sub, ast.JoinedStr):
                for value in sub.values:
                    if isinstance(value, ast.FormattedValue) and self.expr_tainted(
                        value.value
                    ):
                        self._emit(
                            sub,
                            "RTL104",
                            "f-string interpolates a traced value inside a jitted "
                            "function (formats the tracer; use jax.debug.print)",
                        )
                        break
            elif isinstance(sub, ast.Call) and dotted_name(sub.func) in STR_CALLS:
                if any(self.expr_tainted(a) for a in sub.args):
                    self._emit(
                        sub,
                        "RTL104",
                        f"{dotted_name(sub.func)}() on a traced value inside a "
                        "jitted function (formats the tracer; use jax.debug.print)",
                    )

    # -- statement walk ----------------------------------------------------

    def _assign_targets(self, targets, value_tainted: bool) -> None:
        for tgt in targets:
            if isinstance(tgt, ast.Name):
                if value_tainted:
                    self.tainted.add(tgt.id)
                else:
                    self.tainted.discard(tgt.id)
            elif isinstance(tgt, (ast.Tuple, ast.List)):
                self._assign_targets(tgt.elts, value_tainted)
            elif isinstance(tgt, ast.Starred):
                self._assign_targets([tgt.value], value_tainted)

    def run(self, body) -> None:
        for stmt in body:
            if isinstance(stmt, ast.Assign):
                self.scan_strings(stmt.value)
                self._assign_targets(stmt.targets, self.expr_tainted(stmt.value))
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                self.scan_strings(stmt.value)
                self._assign_targets([stmt.target], self.expr_tainted(stmt.value))
            elif isinstance(stmt, ast.AugAssign):
                self.scan_strings(stmt.value)
                if self.expr_tainted(stmt.value):
                    self._assign_targets([stmt.target], True)
            elif isinstance(stmt, ast.If):
                self.scan_strings(stmt.test)
                if self.expr_tainted(stmt.test):
                    self._emit(
                        stmt,
                        "RTL101",
                        "`if` on a traced value inside a jitted function "
                        "(ConcretizationTypeError or silent retrace; use "
                        "jnp.where/lax.cond)",
                    )
                self.run(stmt.body)
                self.run(stmt.orelse)
            elif isinstance(stmt, ast.While):
                self.scan_strings(stmt.test)
                if self.expr_tainted(stmt.test):
                    self._emit(
                        stmt,
                        "RTL101",
                        "`while` on a traced value inside a jitted function "
                        "(use lax.while_loop/lax.fori_loop)",
                    )
                self.run(stmt.body)
                self.run(stmt.orelse)
            elif isinstance(stmt, ast.For):
                self.scan_strings(stmt.iter)
                self.run(stmt.body)
                self.run(stmt.orelse)
            elif isinstance(stmt, (ast.With,)):
                for item in stmt.items:
                    self.scan_strings(item.context_expr)
                self.run(stmt.body)
            elif isinstance(stmt, ast.Try):
                self.run(stmt.body)
                for handler in stmt.handlers:
                    self.run(handler.body)
                self.run(stmt.orelse)
                self.run(stmt.finalbody)
            elif isinstance(stmt, ast.FunctionDef):
                # nested def: traced as a closure when called from the
                # jitted body — propagate the current taint through it
                self.run(stmt.body)
            elif isinstance(stmt, (ast.Expr, ast.Return, ast.Assert, ast.Raise)):
                self.scan_strings(stmt)


def _tainted_params(
    fn: ast.FunctionDef, static_nums: FrozenSet[int], static_names: FrozenSet[str]
) -> Set[str]:
    names: Set[str] = set()
    params = fn.args.posonlyargs + fn.args.args
    for i, arg in enumerate(params):
        if arg.arg in ("self", "cls"):
            continue
        if i in static_nums or arg.arg in static_names:
            continue
        names.add(arg.arg)
    for arg in fn.args.kwonlyargs:
        if arg.arg not in static_names:
            names.add(arg.arg)
    return names


@checker
def check_retrace(ctx: FileContext) -> List[Finding]:
    findings: List[Finding] = []
    defs = _collect_defs(ctx.tree)
    jitted = _jitted_functions(ctx.tree, defs)

    # RTL101 + RTL104: taint pass over each jitted function
    for fn, nums, names in jitted.values():
        taint = _Taint(ctx, fn, _tainted_params(fn, nums, names))
        taint.run(fn.body)
        findings.extend(taint.findings)

    # RTL102: unhashable literals at static call positions.
    # Map names bound to `jax.jit(f, static_argnums=...)` results, then
    # check their call sites.
    static_by_name: Dict[str, FrozenSet[int]] = {}
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Assign) and is_jit_call(node.value):
            nums, _ = _jit_statics(node.value)
            if nums:
                for tgt in node.targets:
                    path = dotted_name(tgt)
                    if path:
                        static_by_name[path] = nums
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        callee = dotted_name(node.func)
        static_positions: Optional[FrozenSet[int]] = static_by_name.get(callee)
        if static_positions is None and is_jit_call(node.func):
            # direct `jax.jit(f, static_argnums=...)(args)` call
            static_positions, _ = _jit_statics(node.func)
        if not static_positions:
            continue
        for i in static_positions:
            if i < len(node.args):
                arg = node.args[i]
                if isinstance(arg, (ast.List, ast.Dict, ast.Set)) or (
                    isinstance(arg, ast.Call)
                    and dotted_name(arg.func) in ARRAYISH_CALLS
                ):
                    findings.append(
                        ctx.finding(
                            arg,
                            "RTL102",
                            f"unhashable/array-valued argument at static position "
                            f"{i} of jitted call {callee or 'jax.jit(...)'} "
                            f"(retraces or throws every call; pass a tuple/scalar)",
                        )
                    )

    # RTL103: jit construction inside a loop
    loop_stack = 0

    def walk_loops(node: ast.AST) -> None:
        nonlocal loop_stack
        is_loop = isinstance(node, (ast.For, ast.While, ast.AsyncFor))
        if is_loop:
            loop_stack += 1
        for child in ast.iter_child_nodes(node):
            if (
                loop_stack > 0
                and (is_jit_call(child) or unwrap_partial(child) is not None)
            ):
                findings.append(
                    ctx.finding(
                        child,
                        "RTL103",
                        "jax.jit constructed inside a loop — build the jitted "
                        "callable once outside (a fresh wrapper per iteration "
                        "defeats the compile cache)",
                    )
                )
            walk_loops(child)
        if is_loop:
            loop_stack -= 1

    walk_loops(ctx.tree)
    return findings
