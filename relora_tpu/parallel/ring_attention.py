"""Ring attention: exact causal attention over a sequence-sharded mesh axis.

A capability the reference does not have (SURVEY.md §5.7 — max trained
context 2048, plain SDPA): long sequences are sharded over the ``sequence``
mesh axis; each device keeps its resident query block and streams K/V blocks
around the ring with ``ppermute`` over ICI, folding each block into a
streaming-softmax (flash-style m/l/o) accumulator.  Communication overlaps
compute block-by-block, and the result is numerically exact (not an
approximation) — verified against single-device attention in tests.

The fold is flash-tiled *within* each resident block too: scores for at most
``tile`` keys exist at a time, so per-device score memory is
O(S_loc · tile), not O(S_loc²) — at the long contexts ring attention exists
for, the dense per-block buffer would dominate HBM.

Grouped-query attention is native: K/V may carry ``n_kv < n`` heads (any
divisor).  The grouped heads ride the ring un-repeated — ICI traffic and K/V
block memory shrink by ``n/n_kv`` — and the score einsum contracts against
the shared head directly instead of a materialized repeat.

Causality is handled at block granularity: a K/V block strictly in the
future of the resident query block contributes nothing, the diagonal block
applies the intra-block causal mask, and past blocks attend densely.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from relora_tpu.parallel._compat import axis_size, shard_map

from relora_tpu.parallel.mesh import DATA_AXIS, FSDP_AXIS, SEQUENCE_AXIS

_NEG_INF = -1e30  # finite sentinel: keeps exp()/where math NaN-free

# per-block key-tile width; scores live as (B, n_kv, G, Q, TILE) f32
DEFAULT_TILE = 512


def _pick_tile(S: int, tile: int) -> int:
    """Largest divisor of S that is <= tile (S and tile are trace-time ints)."""
    t = min(tile, S)
    while S % t:
        t -= 1
    return t


def _group_q(q: jax.Array, n_kv: int) -> jax.Array:
    """(B, Q, N, H) -> (B, Q, n_kv, G, H) f32, query head n = kv·G + g."""
    B, Q, N, H = q.shape
    if N % n_kv:
        raise ValueError(f"num_heads={N} must divide by kv heads={n_kv}")
    return q.astype(jnp.float32).reshape(B, Q, n_kv, N // n_kv, H)


def _flash_fold_block(carry, qg, q_pos, k_blk, v_blk, k_pos, *, scale, tile):
    """Fold one K/V block into flash (o, l, m) accumulators, streaming over
    key tiles so only (…, Q, tile) scores are live.

    qg: (B, Q, n_kv, G, H) f32 grouped queries; k_blk/v_blk: (B, S, n_kv, H);
    k_pos: (S,) global key positions, or None for non-causal.
    carry: o (B, n_kv, G, Q, H), l/m (B, n_kv, G, Q) — all f32.
    """
    S = k_blk.shape[1]
    T = _pick_tile(S, tile)

    def tfold(t, carry):
        o, l, m = carry
        kt = jax.lax.dynamic_slice_in_dim(k_blk, t * T, T, axis=1).astype(jnp.float32)
        vt = jax.lax.dynamic_slice_in_dim(v_blk, t * T, T, axis=1).astype(jnp.float32)
        scores = jnp.einsum("bqkgh,bskh->bkgqs", qg, kt) * scale
        if k_pos is not None:
            kp = jax.lax.dynamic_slice_in_dim(k_pos, t * T, T, axis=0)
            visible = kp[None, :] <= q_pos[:, None]
            scores = jnp.where(visible[None, None, None], scores, _NEG_INF)
        blk_max = jnp.max(scores, axis=-1)
        m_new = jnp.maximum(m, blk_max)
        p = jnp.exp(scores - m_new[..., None])
        # rows with no visible keys yet: m_new stays at the sentinel and the
        # exp() above evaluated exp(0)=1 on masked lanes — zero them out
        p = jnp.where(scores <= _NEG_INF / 2, 0.0, p)
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1)
        o = o * corr[..., None] + jnp.einsum("bkgqs,bskh->bkgqh", p, vt)
        return o, l, m_new

    return jax.lax.fori_loop(0, S // T, tfold, carry)


def _flash_finish(o, l, q_dtype):
    """(B, n_kv, G, Q, H) accumulators -> (B, Q, N, H) output."""
    out = o / jnp.maximum(l[..., None], 1e-30)
    B, K, G, Q, H = out.shape
    return out.transpose(0, 3, 1, 2, 4).reshape(B, Q, K * G, H).astype(q_dtype)


def _ring_attention_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str,
    causal: bool,
    scale: float,
    tile: int,
) -> jax.Array:
    """Per-device body (runs under shard_map).  q: (B, S_local, N, H);
    k/v: (B, S_local, n_kv, H) with n_kv | N."""
    ring = axis_size(axis_name)
    me = jax.lax.axis_index(axis_name)
    B, S, N, H = q.shape
    n_kv = k.shape[2]
    G = N // n_kv

    qg = _group_q(q, n_kv)
    q_pos = me * S + jnp.arange(S)

    acc0 = (
        jnp.zeros((B, n_kv, G, S, H), jnp.float32),
        jnp.zeros((B, n_kv, G, S), jnp.float32),
        jnp.full((B, n_kv, G, S), _NEG_INF, jnp.float32),
    )

    def fold(i, carry):
        o, l, m, k_blk, v_blk = carry
        # which global block is resident after i rotations (blocks travel
        # to the next-higher index each step, so we see me, me-1, ...)
        src = (me - i) % ring
        k_pos = src * S + jnp.arange(S) if causal else None
        o, l, m = _flash_fold_block(
            (o, l, m), qg, q_pos, k_blk, v_blk, k_pos, scale=scale, tile=tile
        )
        k_blk, v_blk = jax.lax.ppermute(
            (k_blk, v_blk),
            axis_name,
            perm=[(j, (j + 1) % ring) for j in range(ring)],
        )
        return o, l, m, k_blk, v_blk

    o, l, m, _, _ = jax.lax.fori_loop(0, ring, fold, (*acc0, k, v))
    return _flash_finish(o, l, q.dtype)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    seq_axis: str = SEQUENCE_AXIS,
    tile: int = DEFAULT_TILE,
) -> jax.Array:
    """Causal attention over (B, S, N, H) arrays whose S dim is sharded on
    ``seq_axis``; K/V may carry fewer (grouped) heads.  Composable with jit:
    shard_map slots into the surrounding GSPMD program."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    spec = P((DATA_AXIS, FSDP_AXIS), seq_axis, None, None)
    fn = shard_map(
        functools.partial(
            _ring_attention_local,
            axis_name=seq_axis,
            causal=causal,
            scale=scale,
            tile=tile,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        # the streaming accumulators start replicated-typed and become
        # device-varying after the first fold; skip the static vma check
        check_vma=False,
    )
    return fn(q, k, v)


# ---------------------------------------------------------------------------
# Zigzag layout: causal load balancing
#
# With contiguous sequence shards, causal ring attention wastes half its
# FLOPs: device 0's queries can only ever see block 0, yet every device
# computes (and masks away) every rotation.  The zigzag layout splits the
# sequence into 2·ring chunks and gives device d chunks (d, 2·ring-1-d) —
# one early + one late — so each device's *useful* work is the same, and
# per-(query-chunk, key-chunk) `lax.cond`s skip the provably-invisible
# pairs.  Total computed chunk pairs drop from 4·ring² to ~2·ring² + ring.
#
# The kernel expects inputs already permuted by `zigzag_permutation` along S
# (persist the permuted layout across the model for free gains — RoPE uses
# true positions, so only the loss's token adjacency needs care — or use the
# convenience wrapper below, which permutes/unpermutes around the call).
# ---------------------------------------------------------------------------


def zigzag_permutation(seq_len: int, ring: int):
    """perm[i] = original index of permuted position i (gather indices)."""
    import numpy as np

    if seq_len % (2 * ring):
        raise ValueError(f"seq_len={seq_len} must divide by 2*ring={2*ring}")
    C = seq_len // (2 * ring)
    order = []
    for d in range(ring):
        order.extend(range(d * C, (d + 1) * C))
        order.extend(range((2 * ring - 1 - d) * C, (2 * ring - d) * C))
    return np.asarray(order)


def zigzag_inverse(seq_len: int, ring: int):
    import numpy as np

    perm = zigzag_permutation(seq_len, ring)
    inv = np.empty_like(perm)
    inv[perm] = np.arange(seq_len)
    return inv


def _zz_positions(block: jax.Array, ring: int, C: int):
    """(early_pos, late_pos) for the device holding zigzag block ``block``."""
    early = block * C + jnp.arange(C)
    late = (2 * ring - 1 - block) * C + jnp.arange(C)
    return early, late


def _ring_attention_zigzag_local(q, k, v, *, axis_name: str, scale: float, tile: int):
    """Per-device body for zigzag layout.  q: (B, 2C, N, H) local;
    k/v: (B, 2C, n_kv, H) grouped."""
    ring = axis_size(axis_name)
    me = jax.lax.axis_index(axis_name)
    B, S2, N, H = q.shape
    C = S2 // 2
    n_kv = k.shape[2]
    G = N // n_kv

    qE = _group_q(q[:, :C], n_kv)
    qL = _group_q(q[:, C:], n_kv)
    myE_pos, myL_pos = _zz_positions(me, ring, C)

    def acc0():
        return (
            jnp.zeros((B, n_kv, G, C, H), jnp.float32),
            jnp.zeros((B, n_kv, G, C), jnp.float32),
            jnp.full((B, n_kv, G, C), _NEG_INF, jnp.float32),
        )

    def fold(i, carry):
        accE, accL, k_blk, v_blk = carry
        src = (me - i) % ring
        srcE_pos, srcL_pos = _zz_positions(src, ring, C)
        kE, vE = k_blk[:, :C], v_blk[:, :C]
        kL, vL = k_blk[:, C:], v_blk[:, C:]

        # chunk-level visibility: chunk a sees chunk b iff b's start <= a's
        # end; chunk index order IS position order, so compare block ids.
        # qE chunk id = me, qL id = 2*ring-1-me; kE id = src, kL id = 2*ring-1-src.
        qE_id, qL_id = me, 2 * ring - 1 - me
        kE_id, kL_id = src, 2 * ring - 1 - src

        def maybe(acc, pred, qc, q_pos, kc, vc, k_pos):
            return jax.lax.cond(
                pred,
                lambda c: _flash_fold_block(
                    c, qc, q_pos, kc, vc, k_pos, scale=scale, tile=tile
                ),
                lambda c: c,
                acc,
            )

        accE = maybe(accE, kE_id <= qE_id, qE, myE_pos, kE, vE, srcE_pos)
        accE = maybe(accE, kL_id <= qE_id, qE, myE_pos, kL, vL, srcL_pos)
        accL = maybe(accL, kE_id <= qL_id, qL, myL_pos, kE, vE, srcE_pos)
        accL = maybe(accL, kL_id <= qL_id, qL, myL_pos, kL, vL, srcL_pos)

        k_blk, v_blk = jax.lax.ppermute(
            (k_blk, v_blk), axis_name, perm=[(j, (j + 1) % ring) for j in range(ring)]
        )
        return accE, accL, k_blk, v_blk

    accE, accL, _, _ = jax.lax.fori_loop(0, ring, fold, (acc0(), acc0(), k, v))
    outE = _flash_finish(*accE[:2], q.dtype)
    outL = _flash_finish(*accL[:2], q.dtype)
    return jnp.concatenate([outE, outL], axis=1)


def ring_attention_zigzag(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    scale: Optional[float] = None,
    seq_axis: str = SEQUENCE_AXIS,
    inputs_permuted: bool = False,
    tile: int = DEFAULT_TILE,
) -> jax.Array:
    """Causal ring attention with zigzag load balancing (K/V may be grouped).

    With ``inputs_permuted=False`` the wrapper gathers into the zigzag layout
    and scatters back around the kernel (convenient, but pays two reshards);
    persist the permuted layout end-to-end and pass ``inputs_permuted=True``
    for the full benefit.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    ring = mesh.shape[seq_axis]
    S = q.shape[1]
    spec = P((DATA_AXIS, FSDP_AXIS), seq_axis, None, None)

    if not inputs_permuted:
        perm = jnp.asarray(zigzag_permutation(S, ring))
        inv = jnp.asarray(zigzag_inverse(S, ring))
        q, k, v = (x[:, perm] for x in (q, k, v))

    fn = shard_map(
        functools.partial(
            _ring_attention_zigzag_local, axis_name=seq_axis, scale=scale, tile=tile
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    out = fn(q, k, v)
    if not inputs_permuted:
        out = out[:, inv]
    return out
