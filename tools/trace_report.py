#!/usr/bin/env python
"""Render span traces: tree view, per-phase percentages, p50/p95 tables.

Reads either a flight-recorder dump (``flight_<reason>_<pid>.json``, written
by ``relora_tpu.obs.flight.dump_on_fault``) or a JSONL span stream (one span
dict per line — the trainer's ``RELORA_TPU_TRACE_DIR`` sink).  Prints:

1. a span tree per trace (``--trace`` selects one; default: the few most
   recent), children indented under parents, with duration and the share of
   the root span's wall time;
2. a phase summary across ALL loaded spans: count, total seconds, p50/p95,
   and percentage of the total traced time per span name.

``--chrome OUT.json`` additionally exports everything as Chrome trace-event
JSON — open in chrome://tracing or https://ui.perfetto.dev, where it overlays
with the XLA timelines StepProfiler writes.

    python tools/trace_report.py ckpts/flight_sigterm_1234.json
    python tools/trace_report.py traces/train_spans.jsonl --trace a1b2c3
    python tools/trace_report.py dump.json --chrome /tmp/trace.json
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

# runnable from any cwd without an installed package
sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from relora_tpu.obs.tracer import chrome_trace_events  # noqa: E402


def load(path: str) -> Tuple[List[Dict[str, Any]], List[Dict[str, Any]], Dict[str, Any]]:
    """Return (spans, events, header) from a flight dump or a JSONL stream."""
    if path.endswith(".jsonl"):
        spans: List[Dict[str, Any]] = []
        events: List[Dict[str, Any]] = []
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    record = json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn tail line from a killed writer
                # instant events share the stream, flagged with _event
                if record.pop("_event", None):
                    events.append(record)
                else:
                    spans.append(record)
        return spans, events, {"source": "jsonl"}
    with open(path) as fh:
        payload = json.load(fh)
    header = {k: v for k, v in payload.items() if k not in ("spans", "events")}
    return payload.get("spans", []), payload.get("events", []), header


def merge_streams(
    streams: List[Tuple[str, List[Dict[str, Any]], List[Dict[str, Any]]]],
) -> Tuple[List[Dict[str, Any]], List[Dict[str, Any]]]:
    """Join span streams from different processes into one timeline.

    Each process numbers its spans independently ("s000001" collides across
    files), so span/parent ids get a per-stream prefix — parent links stay
    intra-process, while the shared ``trace_id`` (the router's X-Request-Id)
    joins the trees.  Timestamps are per-process *monotonic* clocks with
    unrelated origins; spans recorded since ``t_wall`` exists are shifted
    onto the wall clock so router and replica phases interleave correctly.
    Every span/event is tagged with ``_pid`` (stream index + 1) and
    ``_stream`` (stream name) for the Chrome export's per-process grouping.
    """
    merged_spans: List[Dict[str, Any]] = []
    merged_events: List[Dict[str, Any]] = []
    for i, (name, spans, events) in enumerate(streams):
        prefix = f"p{i}:"
        for s in spans:
            s = dict(s)
            if s.get("span_id"):
                s["span_id"] = prefix + str(s["span_id"])
            if s.get("parent_id"):
                s["parent_id"] = prefix + str(s["parent_id"])
            t_wall = s.get("t_wall")
            if isinstance(t_wall, (int, float)) and s.get("t_start") is not None:
                shift = t_wall - s["t_start"]
                s["t_start"] = t_wall
                if s.get("t_end") is not None:
                    s["t_end"] = s["t_end"] + shift
            s["_pid"], s["_stream"] = i + 1, name
            merged_spans.append(s)
        for e in events:
            e = dict(e)
            if e.get("parent_id"):
                e["parent_id"] = prefix + str(e["parent_id"])
            if isinstance(e.get("t_wall"), (int, float)):
                e["t"] = e["t_wall"]
            e["_pid"], e["_stream"] = i + 1, name
            merged_events.append(e)
    # re-zero at the earliest stamp: wall-epoch microseconds confuse trace
    # viewers and make the tree's ms column unreadable
    t0 = min(
        [s["t_start"] for s in merged_spans if s.get("t_start") is not None]
        + [e["t"] for e in merged_events if e.get("t") is not None]
        or [0.0]
    )
    for s in merged_spans:
        if s.get("t_start") is not None:
            s["t_start"] -= t0
        if s.get("t_end") is not None:
            s["t_end"] -= t0
    for e in merged_events:
        if e.get("t") is not None:
            e["t"] -= t0
    merged_spans.sort(key=lambda s: s.get("t_start") or 0.0)
    merged_events.sort(key=lambda e: e.get("t") or 0.0)
    return merged_spans, merged_events


def percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile over raw durations (exact, not bucketed)."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def _fmt_attrs(attrs: Dict[str, Any], limit: int = 4) -> str:
    if not attrs:
        return ""
    items = list(attrs.items())[:limit]
    body = " ".join(f"{k}={v}" for k, v in items)
    more = "" if len(attrs) <= limit else " …"
    return f"  [{body}{more}]"


def print_tree(spans: List[Dict[str, Any]], trace_id: str, out=sys.stdout) -> None:
    trace = [s for s in spans if s.get("trace_id") == trace_id]
    by_id = {s["span_id"]: s for s in trace}
    children: Dict[Optional[str], List[Dict[str, Any]]] = {}
    for s in trace:
        parent = s.get("parent_id")
        # a parent evicted from the ring buffer orphans its children: show
        # them at the root rather than dropping them
        if parent not in by_id:
            parent = None
        children.setdefault(parent, []).append(s)
    for group in children.values():
        group.sort(key=lambda s: s.get("t_start") or 0.0)
    roots = children.get(None, [])
    total = sum(s.get("dur_s") or 0.0 for s in roots) or None
    # a cross-process trace (router + replica joined on one request id)
    # qualifies span names with their service so the tree reads as a hop
    # sequence; single-service traces render exactly as before
    services = {s.get("service") for s in trace if s.get("service")}
    qualify = len(services) > 1
    out.write(f"trace {trace_id}  ({len(trace)} spans)\n")

    def walk(span: Dict[str, Any], depth: int) -> None:
        dur = span.get("dur_s")
        dur_txt = "open" if dur is None else f"{dur * 1e3:.2f} ms"
        pct = ""
        if total and dur is not None:
            pct = f"  {100.0 * dur / total:5.1f}%"
        name = span.get("name", "?")
        if qualify:
            name = f"{span.get('service', '?')}/{name}"
        out.write(
            f"  {'  ' * depth}{name}  {dur_txt}{pct}"
            f"{_fmt_attrs(span.get('attrs') or {})}\n"
        )
        for child in children.get(span["span_id"], []):
            walk(child, depth + 1)

    for root in roots:
        walk(root, 0)


def phase_summary(spans: List[Dict[str, Any]], out=sys.stdout) -> None:
    by_name: Dict[str, List[float]] = {}
    for s in spans:
        dur = s.get("dur_s")
        if dur is not None:
            by_name.setdefault(s.get("name", "?"), []).append(dur)
    if not by_name:
        out.write("no finished spans\n")
        return
    # % is of the summed time across all phases — sibling phases of one step
    # roughly partition it, so the column reads as "where did the time go"
    grand_total = sum(sum(v) for v in by_name.values())
    out.write(
        f"\n{'phase':<20} {'count':>6} {'total_s':>9} {'p50_ms':>9} "
        f"{'p95_ms':>9} {'share':>7}\n"
    )
    for name, vals in sorted(by_name.items(), key=lambda kv: -sum(kv[1])):
        vals.sort()
        total = sum(vals)
        out.write(
            f"{name:<20} {len(vals):>6} {total:>9.3f} "
            f"{percentile(vals, 0.50) * 1e3:>9.2f} "
            f"{percentile(vals, 0.95) * 1e3:>9.2f} "
            f"{100.0 * total / grand_total:>6.1f}%\n"
        )


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "paths", nargs="+", metavar="path",
        help="flight_*.json dumps and/or *.jsonl span streams; several paths "
        "are merged into one timeline joined on shared trace ids "
        "(e.g. router_spans_*.jsonl + serve_spans_*.jsonl)",
    )
    ap.add_argument("--trace", help="render only this trace id")
    ap.add_argument(
        "--max-traces", type=int, default=3,
        help="without --trace: how many of the most recent traces to render",
    )
    ap.add_argument("--chrome", help="also export Chrome trace-event JSON here")
    args = ap.parse_args(argv)

    if len(args.paths) == 1:
        spans, events, header = load(args.paths[0])
        if header.get("reason"):
            sys.stdout.write(
                f"flight dump: reason={header['reason']} pid={header.get('pid')} "
                f"dropped_spans={header.get('dropped_spans', 0)}\n\n"
            )
    else:
        streams = []
        for path in args.paths:
            s, e, _ = load(path)
            streams.append((Path(path).name, s, e))
        spans, events = merge_streams(streams)
        sys.stdout.write(
            f"merged {len(args.paths)} streams: "
            + " ".join(name for name, _, _ in streams) + "\n\n"
        )
    if not spans and not events:
        print("empty trace")
        return 1

    if args.trace:
        trace_ids = [args.trace]
    else:
        seen: List[str] = []  # insertion order == recording order
        for s in spans:
            tid = s.get("trace_id")
            if tid and tid not in seen:
                seen.append(tid)
        trace_ids = seen[-args.max_traces:]
    for tid in trace_ids:
        print_tree(spans, tid)
    phase_summary(spans)

    if args.chrome:
        if len(args.paths) == 1:
            trace_events = chrome_trace_events(spans, events)
        else:
            # one Chrome process per source stream, labelled with the file
            # it came from, so Perfetto shows router and replicas as
            # separate swim lanes on the shared wall-clock axis
            trace_events = []
            for i, (name, _, _) in enumerate(streams):
                pid = i + 1
                trace_events.extend(
                    chrome_trace_events(
                        [s for s in spans if s.get("_pid") == pid],
                        [e for e in events if e.get("_pid") == pid],
                        pid=pid,
                    )
                )
                trace_events.append(
                    {"name": "process_name", "ph": "M", "pid": pid,
                     "args": {"name": name}}
                )
        with open(args.chrome, "w") as fh:
            json.dump({"traceEvents": trace_events}, fh)
        print(f"\nchrome trace written to {args.chrome}")
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:
        # downstream closed early (e.g. `| head`, `| grep -q`): not an error.
        # Point stdout at devnull so the interpreter's exit-time flush of the
        # dead pipe can't raise a second time.
        import os

        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        sys.exit(0)
