"""Build a csv text-classification dataset from labeled local directory
roots — GLUE-style custom-file tasks for air-gapped environments.

Each ``--root LABEL=PATH[,PATH...][@EXT,EXT...]`` contributes snippets
labeled LABEL; snippets are fixed-length character windows sampled from
matching files under the roots (default extensions: py,md,rst,txt).
Output: ``<out>/train.csv``, ``dev.csv``, ``test.csv`` with columns
(sentence, label) — consumable by run_glue.py --train_file.

Usage::

    python tools/build_cls_dataset.py --out /tmp/glue_pysrc \
        --root "code=/opt/venv/lib/python3.12/site-packages/numpy@py" \
        --root "prose=/opt/venv/lib/python3.12/site-packages@md,rst,txt" \
        --per-label 600
"""

from __future__ import annotations

import argparse
import csv
import os
import random


def snippets_from(paths, n, rng, width=400, exts=(".py", ".md", ".rst", ".txt")):
    files = []
    for root in paths:
        for dirpath, dirnames, names in os.walk(root):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            files.extend(
                os.path.join(dirpath, f) for f in names if f.endswith(tuple(exts))
            )
    rng.shuffle(files)
    out = []
    for path in files:
        if len(out) >= n:
            break
        try:
            with open(path, encoding="utf-8", errors="strict") as fh:
                text = fh.read()
        except (OSError, UnicodeDecodeError):
            continue
        if len(text) < width:
            continue
        for _ in range(min(3, 1 + len(text) // (4 * width))):
            if len(out) >= n:
                break
            start = rng.randrange(0, len(text) - width + 1)
            snippet = " ".join(text[start : start + width].split())
            if snippet:
                out.append(snippet)
    if len(out) < n:
        raise SystemExit(f"only {len(out)} snippets found (wanted {n})")
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", required=True)
    ap.add_argument("--root", action="append", required=True,
                    help="LABEL=PATH[,PATH...] (repeatable)")
    ap.add_argument("--per-label", type=int, default=600)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    rng = random.Random(args.seed)
    rows = []
    for spec in args.root:
        label, rest = spec.split("=", 1)
        paths, _, extspec = rest.partition("@")
        exts = tuple(
            e if e.startswith(".") else f".{e}" for e in extspec.split(",")
        ) if extspec else (".py", ".md", ".rst", ".txt")
        for s in snippets_from(paths.split(","), args.per_label, rng, exts=exts):
            rows.append({"sentence": s, "label": label})
    rng.shuffle(rows)

    os.makedirs(args.out, exist_ok=True)
    n = len(rows)
    splits = {
        "train.csv": rows[: int(n * 0.8)],
        "dev.csv": rows[int(n * 0.8) : int(n * 0.9)],
        "test.csv": rows[int(n * 0.9) :],
    }
    for name, split in splits.items():
        with open(os.path.join(args.out, name), "w", newline="") as f:
            w = csv.DictWriter(f, fieldnames=["sentence", "label"])
            w.writeheader()
            w.writerows(split)
        print(f"{name}: {len(split)} rows")


if __name__ == "__main__":
    main()
