"""relora_tpu.obs — unified observability: span tracing, shared metrics
registry, flight recorder, and MFU helpers.

Stdlib-only (``mfu`` imports jax lazily and only for device detection);
safe to import from the serving front-end, the trainer, and signal
handlers.  See docs/observability.md.
"""

from relora_tpu.obs.flight import FlightRecorder, configure, default_recorder, dump_on_fault
from relora_tpu.obs.metrics import LATENCY_BUCKETS, Histogram, MetricsRegistry
from relora_tpu.obs.mfu import peak_flops, step_flops_from_cost_analysis
from relora_tpu.obs.tracer import (
    NoopTracer,
    Span,
    Tracer,
    chrome_trace_events,
    default_tracer,
    new_trace_id,
    set_default_tracer,
)

__all__ = [
    "FlightRecorder",
    "configure",
    "default_recorder",
    "dump_on_fault",
    "LATENCY_BUCKETS",
    "Histogram",
    "MetricsRegistry",
    "peak_flops",
    "step_flops_from_cost_analysis",
    "NoopTracer",
    "Span",
    "Tracer",
    "chrome_trace_events",
    "default_tracer",
    "new_trace_id",
    "set_default_tracer",
]
