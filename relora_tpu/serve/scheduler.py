"""Slot-based continuous batching over a preallocated decode cache.

The decode step is compiled once for a fixed ``(max_batch, cache_size)`` cache
and keeps running as requests come and go — no retracing on admission or
eviction, which is the property that makes continuous batching cheap on
XLA-compiled accelerators:

- **Admit**: a new request prefills alone (bucketed lengths, so a handful of
  prefill compilations total), then ``engine.insert`` copies its single-row
  cache into a free slot of the persistent batch cache; its first sampled
  token and position join the step's token/pos arrays.
- **Step**: one jitted decode for all ``max_batch`` slots, occupied or not —
  a free slot decodes garbage at position 0, which is invisible (the
  ``j <= position`` mask) and overwritten by the next admission's insert.
- **Evict**: a row that hits EOS or its token budget is simply marked free;
  the arrays keep their shape, so nothing recompiles.

Sampling stays deterministic per request regardless of batch composition:
each row draws from a key folded from ``(request id, token index)``, never
from the slot index or the global step — the batched greedy drain is
token-identical to unbatched decode, and sampled requests reproduce across
different interleavings.

The scheduler is an *incremental* core so an online front-end
(serve/server.py) can drive it one round at a time:

- ``submit(req)`` queues a validated request (optionally with per-token /
  completion callbacks and an absolute deadline);
- ``step()`` performs one admit-plus-decode round and returns the requests
  that finished during it;
- ``cancel(uid)`` frees a request's slot mid-decode (client disconnects),
  returning a partial completion;
- ``run(requests)`` is a thin drain wrapper — submit everything, step until
  idle — preserving the original batch CLI behavior exactly.

The scheduler is single-threaded by design: all of ``submit``/``step``/
``cancel`` must be called from one thread (the server's model thread);
cross-thread admission is the AdmissionController's job (serve/admission.py).

Per-request latency and throughput go to the existing metrics.jsonl sink
(utils/logging.MetricsLogger): ``serve_request`` records with time-to-first-
token, total latency, and decode tokens/sec, plus one ``serve/queue_depth``
/ ``serve/active_slots`` gauge record per decode step so load tooling and
the ``/metrics`` endpoint have a per-step signal.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Any, Callable, Deque, Dict, Iterable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from relora_tpu.obs.tracer import NoopTracer
from relora_tpu.serve import wire
from relora_tpu.serve.engine import InferenceEngine, bucket_length
from relora_tpu.serve.paging import PageAllocator, PrefixCache, pages_needed
from relora_tpu.serve.sampling import SamplingParams, spec_verify_draws
from relora_tpu.utils import faults
from relora_tpu.utils.logging import MetricsLogger, get_logger

logger = get_logger(__name__)

#: uid, token id, token index within the generation (0 = first sampled token)
TokenCallback = Callable[[int, int, int], None]
#: called exactly once per request with its Completion
FinishCallback = Callable[["Completion"], None]


@dataclasses.dataclass(frozen=True)
class Request:
    """One generation request: token-id prompt plus per-request sampling.
    ``top_k`` is batch-global (static shape) and lives on the scheduler.
    ``spec`` opts this request out of speculative drafting (``False``) when
    the scheduler runs with it on — output distribution is identical either
    way; turning it off just skips the draft/verify work for this row.
    ``adapter`` names a tenant LoRA adapter (serve/adapters.py registry);
    ``None`` decodes the base model (slot 0, the identity adapter)."""

    uid: int
    prompt: Sequence[int]
    max_new_tokens: int
    temperature: float = 0.0
    top_p: float = 1.0
    spec: bool = True
    adapter: Optional[str] = None


@dataclasses.dataclass
class Completion:
    uid: int
    tokens: List[int]
    finish_reason: str  # "eos" | "length" | "timeout" | "cancelled" | "error"
    prompt_tokens: int
    ttft_s: float
    latency_s: float
    error: Optional[str] = None  # reader-facing detail when finish_reason="error"


@dataclasses.dataclass
class _Slot:
    request: Request
    pos: int  # absolute position of the next cache write
    tokens: List[int]
    t_admit: float
    t_first: float
    deadline: Optional[float] = None  # absolute time.monotonic(), None = no limit
    span: Optional[Any] = None  # per-request "decode" span; ended at retire
    adapter_slot: int = 0  # HBM slot this request's adapter is pinned to


class ContinuousBatchingScheduler:
    """Drains a stream of requests through ``max_batch`` decode slots."""

    def __init__(
        self,
        engine: InferenceEngine,
        *,
        max_batch: int,
        eos_id: Optional[int] = None,
        top_k: int = 0,
        metrics: Optional[MetricsLogger] = None,
        key: Optional[jax.Array] = None,
        tracer: Optional[Any] = None,
        obs_registry: Optional[Any] = None,
        adapter_registry: Optional[Any] = None,
    ):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if adapter_registry is not None and not getattr(engine, "adapter_slots", 0):
            raise ValueError(
                "adapter_registry needs an engine built with adapter_slots "
                "(the stacked multi-tenant LoRA layout)"
            )
        self.engine = engine
        self.max_batch = max_batch
        self.eos_id = eos_id
        self.top_k = top_k
        self.metrics = metrics
        self.adapter_registry = adapter_registry
        # tracing defaults to no-op so the batch CLI pays nothing; the HTTP
        # server injects its Tracer + ServeMetrics (per-phase histograms)
        self.tracer = tracer if tracer is not None else NoopTracer()
        self.obs_registry = obs_registry
        self.key = key if key is not None else jax.random.PRNGKey(0)
        self._step_count = 0
        self._pending: Deque[Request] = deque()
        self._slots: List[Optional[_Slot]] = [None] * max_batch
        self._cache = None  # allocated on first admission, then persistent
        self._tokens = np.zeros(max_batch, np.int32)
        self._positions = np.zeros(max_batch, np.int32)
        # per-row adapter slot indices for the grouped LoRA kernel; free rows
        # point at slot 0 (the identity adapter) so their garbage decode is
        # pure base-model work
        self._adapter_row = np.zeros(max_batch, np.int32)
        self._deadlines: Dict[int, float] = {}
        self._on_token: Dict[int, TokenCallback] = {}
        self._on_finish: Dict[int, FinishCallback] = {}
        self._trace_ids: Dict[int, str] = {}  # uid -> request trace id

    def _request_key(self, req: Request, token_index: int) -> jax.Array:
        # keyed by (uid, token index): a request's sample stream does not
        # depend on which slot it landed in or what shares its batch
        return jax.random.fold_in(jax.random.fold_in(self.key, req.uid), token_index)

    # -- incremental API ------------------------------------------------------

    def validate_request(self, req: Request) -> None:
        """Reject requests the decode loop could not serve: empty prompts and
        prompts whose generation cannot fit the cache.  The server maps this
        ``ValueError`` to HTTP 400; ``run()`` raises it from its preamble."""
        need = len(req.prompt) + req.max_new_tokens
        if len(req.prompt) < 1:
            raise ValueError(f"request {req.uid}: empty prompt")
        if need > self.engine.cache_size:
            raise ValueError(
                f"request {req.uid} needs {need} cache entries, "
                f"capacity is {self.engine.cache_size}"
            )
        if req.max_new_tokens < 1:
            raise ValueError(
                f"request {req.uid}: max_new_tokens must be >= 1, got {req.max_new_tokens}"
            )
        if req.adapter is not None:
            if self.adapter_registry is None:
                raise ValueError(
                    f"request {req.uid}: server is not running with an adapter "
                    "registry (--adapter-dir); 'adapter' is not accepted"
                )
            if not self.adapter_registry.known(req.adapter):
                raise ValueError(
                    f"request {req.uid}: unknown adapter {req.adapter!r}"
                )

    def submit(
        self,
        req: Request,
        *,
        on_token: Optional[TokenCallback] = None,
        on_finish: Optional[FinishCallback] = None,
        deadline: Optional[float] = None,
        trace_id: Optional[str] = None,
    ) -> None:
        """Queue a request for admission at the next ``step()``.

        ``on_token(uid, token, index)`` fires for every token as it is
        sampled (index 0 is the prefill's first token); ``on_finish`` fires
        exactly once with the Completion.  ``deadline`` is an absolute
        ``time.monotonic()`` bound — a request still decoding past it
        finishes with its partial output and reason ``"timeout"``.
        ``trace_id`` threads the caller's request id onto every phase span
        this request produces (prefill/insert/decode)."""
        self.validate_request(req)
        if req.uid in self._deadlines or req.uid in self._on_finish or any(
            r.uid == req.uid for r in self._pending
        ) or any(s is not None and s.request.uid == req.uid for s in self._slots):
            raise ValueError(f"request {req.uid}: uid already in flight")
        if deadline is not None:
            self._deadlines[req.uid] = deadline
        if on_token is not None:
            self._on_token[req.uid] = on_token
        if on_finish is not None:
            self._on_finish[req.uid] = on_finish
        if trace_id is not None:
            self._trace_ids[req.uid] = trace_id
        self._pending.append(req)

    def _observe(self, name: str, value: float) -> None:
        if self.obs_registry is not None:
            self.obs_registry.observe(name, value)

    def cancel(
        self, uid: int, reason: str = "cancelled", detail: Optional[str] = None
    ) -> Optional[Completion]:
        """Free a request's slot (or drop it from the pending queue) and
        report its partial output.  Returns the Completion, or None when the
        uid is unknown (already finished — cancellation raced completion)."""
        for req in list(self._pending):
            if req.uid == uid:
                self._pending.remove(req)
                return self._finalize_unadmitted(req, reason, detail)
        for slot_idx, slot in enumerate(self._slots):
            if slot is not None and slot.request.uid == uid:
                return self._retire(slot_idx, reason, detail)
        return None

    def fail_all(
        self, reason: str = "error", detail: Optional[str] = None
    ) -> List[Completion]:
        """Terminally complete every queued and active request — the
        model-thread-death path.  Each request gets whatever tokens it
        already produced plus ``finish_reason=reason`` (callbacks fire as
        usual), so no stream is ever left hanging on a dead worker.  Pure
        host-side bookkeeping: never touches the device, so it is safe to
        call after the jitted step itself blew up."""
        completions: List[Completion] = []
        for req in list(self._pending):
            self._pending.remove(req)
            completions.append(self._finalize_unadmitted(req, reason, detail))
        for slot_idx, slot in enumerate(self._slots):
            if slot is not None:
                completions.append(self._retire(slot_idx, reason, detail))
        return completions

    def has_work(self) -> bool:
        return bool(self._pending) or any(s is not None for s in self._slots)

    @property
    def queue_depth(self) -> int:
        return len(self._pending)

    @property
    def active_slots(self) -> int:
        return sum(s is not None for s in self._slots)

    def adapter_stats(self) -> Optional[Dict[str, Any]]:
        """Registry occupancy/churn counters for /healthz, or None when the
        server runs without multi-tenant adapters."""
        if self.adapter_registry is None:
            return None
        return self.adapter_registry.stats()

    def step(self) -> List[Completion]:
        """One admit-plus-decode round: expire deadlines, fill free slots
        from the pending queue, then run one jitted decode over all slots.
        Returns the requests that finished during the round (possibly at
        admission, when the first token already satisfies the request)."""
        finished: List[Completion] = []
        # admission (prefill + insert) runs on the decode loop's critical
        # path: its share of the step is the "prefill stall" every in-flight
        # stream pays, reported per step next to the batch-fill ratio
        t_step = time.monotonic()
        self._expire_deadlines(finished)
        while True:
            self._admit_pass(finished)
            if any(s is not None for s in self._slots) or not self._pending:
                break
            # everything admitted this round finished at once; keep admitting
            # (mirrors the original drain loop's `continue` back to admission)
        admit_s = time.monotonic() - t_step
        if not any(s is not None for s in self._slots):
            return finished

        # -- one decode step over all slots ----------------------------------
        # batch-level span (several requests share it): dispatch + the bulk
        # token pull, which is the step's device sync point
        t_decode = time.monotonic()
        n_active = self.active_slots  # the batch this decode step runs over
        with self.tracer.span(
            "decode_step", step=self._step_count, active_slots=n_active
        ):
            logits, self._cache = self.engine.decode(
                self._cache,
                jnp.asarray(self._tokens)[:, None],
                jnp.asarray(self._positions)[:, None],
                adapter_idx=self._adapter_row,
            )
            self._step_count += 1
            # one bulk pull for the whole batch, then plain Python ints —
            # per-slot int(next_tokens[i]) would be a device sync per row
            next_tokens = self._sample_rows(logits, self._slots).tolist()
        decode_s = time.monotonic() - t_decode
        self._observe("decode_step_seconds", decode_s)
        # utilization attribution: how full the decode batch actually was,
        # and what share of the step admissions stole from decoding
        batch_fill = n_active / self.max_batch
        stall_share = admit_s / max(admit_s + decode_s, 1e-9)
        if self.obs_registry is not None:
            self.obs_registry.set_gauge("batch_fill", batch_fill)
            self.obs_registry.set_gauge("prefill_stall_share", stall_share)
        for slot_idx, slot in enumerate(self._slots):
            if slot is None:
                continue
            tok = next_tokens[slot_idx]
            slot.tokens.append(tok)
            slot.pos += 1
            self._tokens[slot_idx] = tok
            self._positions[slot_idx] = slot.pos
            self._emit_token(slot.request.uid, tok, len(slot.tokens) - 1)
            self._finish_if_done(slot_idx, finished)
        record = None
        if self.metrics is not None:
            watcher = getattr(self.engine, "compile_watcher", None)
            record = {
                "serve/decode_step": self._step_count,
                "serve/queue_depth": len(self._pending),
                "serve/active_slots": self.active_slots,
                "serve/batch_fill": round(batch_fill, 4),
                "serve/prefill_stall_s": round(admit_s, 6),
                "serve/prefill_stall_share": round(stall_share, 4),
                # a nonzero here after warmup means a shape escaped the
                # warmed buckets — see docs/operations.md troubleshooting
                "compile/steady_state_retraces": (
                    watcher.steady_state_retraces if watcher is not None else 0
                ),
            }
        self._adapter_gauges(record)
        if record is not None:
            self.metrics.log(record)
        return finished

    def run(self, requests: Iterable[Request]) -> Dict[int, Completion]:
        """Admit-and-decode until every request completes.  Returns
        completions keyed by ``Request.uid``."""
        incoming = list(requests)
        for req in incoming:
            # validate everything before admitting anything, so a bad request
            # raises without leaving earlier ones queued on the scheduler
            self.validate_request(req)
        for req in incoming:
            self.submit(req)
        completions: Dict[int, Completion] = {}
        t_start = time.monotonic()
        while self.has_work():
            for completion in self.step():
                completions[completion.uid] = completion
        logger.info(
            f"drained {len(completions)} requests in {time.monotonic() - t_start:.2f}s "
            f"({self._step_count} decode steps)"
        )
        return completions

    # -- internals -----------------------------------------------------------

    def _expire_deadlines(self, finished: List[Completion]) -> None:
        if not self._deadlines:
            return
        now = time.monotonic()
        for slot_idx, slot in enumerate(self._slots):
            if slot is not None and slot.deadline is not None and now >= slot.deadline:
                finished.append(self._retire(slot_idx, "timeout"))

    def _admit_pass(self, finished: List[Completion]) -> None:
        for slot_idx in range(self.max_batch):
            if self._slots[slot_idx] is not None or not self._pending:
                continue
            req = self._pending.popleft()
            deadline = self._deadlines.get(req.uid)
            if deadline is not None and time.monotonic() >= deadline:
                # expired while queued: report the timeout without spending a
                # prefill on it; the slot stays free for the next admission
                finished.append(self._finalize_unadmitted(req, "timeout"))
                continue
            try:
                adapter_slot = self._acquire_adapter(req)
            except Exception as e:
                logger.warning(f"request {req.uid}: adapter load failed: {e!r}")
                finished.append(
                    self._finalize_unadmitted(req, "error", f"adapter load failed: {e}")
                )
                continue
            if adapter_slot is None:
                # every adapter slot pinned by live traffic: stay queued
                # (FIFO — later requests do not jump the head) and retry
                # after a retirement drops a pin
                self._pending.appendleft(req)
                return
            t_admit = time.monotonic()
            self._cache, first = self._admit(
                req, slot_idx, self._ensure_cache(), adapter_slot
            )
            self._slots[slot_idx] = _Slot(
                request=req,
                pos=len(req.prompt),
                tokens=[first],
                t_admit=t_admit,
                t_first=time.monotonic(),
                deadline=deadline,
                # the request's decode phase: open until EOS/budget/cancel
                span=self.tracer.start_span(
                    "decode", trace_id=self._trace_ids.get(req.uid), uid=req.uid
                ),
                adapter_slot=adapter_slot,
            )
            self._tokens[slot_idx] = first
            self._positions[slot_idx] = len(req.prompt)
            self._adapter_row[slot_idx] = adapter_slot
            self._emit_token(req.uid, first, 0)
            self._finish_if_done(slot_idx, finished)

    def _ensure_cache(self):
        if self._cache is None:
            self._cache = self.engine.init_cache(self.max_batch)
        return self._cache

    def _acquire_adapter(self, req: Request) -> Optional[int]:
        """Pin the request's adapter for admission.  Returns its HBM slot
        index, or ``None`` when every slot is pinned by live traffic — the
        caller keeps the request queued and retries next round (the prefix
        cache's evict-then-retry contract).  Raises when the adapter fails
        to load (bad checkpoint dir)."""
        if self.adapter_registry is None:
            return 0
        return self.adapter_registry.acquire(req.adapter)

    def _release_adapter(self, req: Request) -> None:
        if self.adapter_registry is not None and req.adapter is not None:
            self.adapter_registry.release(req.adapter)

    def _count_adapter_request(self, req: Request) -> None:
        if self.adapter_registry is not None and self.obs_registry is not None:
            self.obs_registry.inc(
                "adapter_requests_total", label=("adapter", req.adapter or "base")
            )

    def _adapter_gauges(self, record: Optional[Dict[str, Any]] = None) -> None:
        """Publish registry occupancy next to the step's other gauges (and
        into the step's metrics.jsonl record when one is being built)."""
        if self.adapter_registry is None:
            return
        stats = self.adapter_registry.stats()
        if self.obs_registry is not None:
            self.obs_registry.set_gauge("adapter_slots_used", stats["slots_used"])
            self.obs_registry.set_gauge("adapter_hit_rate", stats["hit_rate"])
        if record is not None:
            record["serve/adapter_slots_used"] = stats["slots_used"]
            record["serve/adapter_evictions_total"] = stats["evictions_total"]
            record["serve/adapter_hit_rate"] = stats["hit_rate"]

    def _admit(self, req: Request, slot_idx: int, cache, adapter_slot: int = 0):
        """Prefill one request (batch of 1, bucketed length) and copy its
        cache row into ``slot_idx``.  Returns (cache, first sampled token)."""
        L = len(req.prompt)
        T = min(bucket_length(L), self.engine.cache_size)
        ids = np.zeros((1, T), np.int32)
        ids[0, :L] = np.asarray(req.prompt, np.int32)
        tid = self._trace_ids.get(req.uid)
        # the prefill span includes the first-token sample pull: that host
        # pull is the sync point, so the span covers real compute, not just
        # async dispatch
        t0 = time.monotonic()
        with self.tracer.span(
            "prefill", trace_id=tid, uid=req.uid, prompt_tokens=L, bucket=T
        ):
            logits, pcache = self.engine.prefill(
                jnp.asarray(ids),
                adapter_idx=np.array([adapter_slot], np.int32),
            )
            first = self.engine._sample(
                logits[:, L - 1, :],
                self._request_key(req, 0),
                temperature=req.temperature,
                top_k=self.top_k,
                top_p=req.top_p,
            )
            first_id = int(np.asarray(first)[0])
        t1 = time.monotonic()
        self._observe("prefill_seconds", t1 - t0)
        with self.tracer.span("insert", trace_id=tid, uid=req.uid, slot=slot_idx):
            cache = self.engine.insert(cache, pcache, slot_idx)
        self._observe("insert_seconds", time.monotonic() - t1)
        return cache, first_id

    def _sample_rows(self, logits, slots) -> np.ndarray:
        temps = np.zeros(self.max_batch, np.float32)
        top_ps = np.ones(self.max_batch, np.float32)
        keys = []
        for slot_idx, slot in enumerate(slots):
            if slot is None:
                keys.append(self.key)  # unused row; any key works
                continue
            temps[slot_idx] = slot.request.temperature
            top_ps[slot_idx] = slot.request.top_p
            keys.append(self._request_key(slot.request, len(slot.tokens)))
        drawn = self.engine._sample(
            logits,
            jnp.stack(keys),
            temperature=jnp.asarray(temps),
            top_k=self.top_k,
            top_p=jnp.asarray(top_ps),
        )
        return np.asarray(drawn)

    def _emit_token(self, uid: int, token: int, index: int) -> None:
        callback = self._on_token.get(uid)
        if callback is None:
            return
        try:
            callback(uid, token, index)
        except Exception as e:  # a dead stream must not kill the decode loop
            logger.warning(f"request {uid}: token callback failed: {e!r}")
            self._on_token.pop(uid, None)

    def _finish_if_done(self, slot_idx: int, finished: List[Completion]) -> None:
        slot = self._slots[slot_idx]
        req = slot.request
        last = slot.tokens[-1]
        reason = None
        if self.eos_id is not None and last == self.eos_id:
            reason = "eos"
        elif len(slot.tokens) >= req.max_new_tokens:
            reason = "length"
        if reason is None:
            return
        finished.append(self._retire(slot_idx, reason))

    def _retire(
        self, slot_idx: int, reason: str, detail: Optional[str] = None
    ) -> Completion:
        """Evict a slot (EOS / budget / timeout / cancel / error): build the
        Completion, free the row — nothing recompiles — and notify."""
        slot = self._slots[slot_idx]
        req = slot.request
        now = time.monotonic()
        completion = Completion(
            uid=req.uid,
            tokens=list(slot.tokens),
            finish_reason=reason,
            prompt_tokens=len(req.prompt),
            ttft_s=slot.t_first - slot.t_admit,
            latency_s=now - slot.t_admit,
            error=detail,
        )
        self._slots[slot_idx] = None  # evict: slot is free, nothing recompiles
        self._adapter_row[slot_idx] = 0  # free rows decode the identity adapter
        self._release_adapter(req)
        self._count_adapter_request(req)
        if slot.span is not None:
            slot.span.set(
                finish_reason=reason, output_tokens=len(completion.tokens)
            ).end()
            self._observe("decode_seconds", now - slot.t_first)
        if self.metrics is not None:
            decode_s = max(now - slot.t_first, 1e-9)
            self.metrics.log(
                {
                    "serve_request": req.uid,
                    "serve/prompt_tokens": completion.prompt_tokens,
                    "serve/output_tokens": len(completion.tokens),
                    "serve/finish_reason": reason,
                    "serve/ttft_s": completion.ttft_s,
                    "serve/latency_s": completion.latency_s,
                    "serve/decode_tokens_per_s": (len(completion.tokens) - 1) / decode_s
                    if len(completion.tokens) > 1
                    else 0.0,
                }
            )
        self._finalize(completion)
        return completion

    def _finalize_unadmitted(
        self, req: Request, reason: str, detail: Optional[str] = None
    ) -> Completion:
        """A request that never reached a slot (cancelled or expired while
        queued): empty output, zero latency fields."""
        self._count_adapter_request(req)
        completion = Completion(
            uid=req.uid,
            tokens=[],
            finish_reason=reason,
            prompt_tokens=len(req.prompt),
            ttft_s=0.0,
            latency_s=0.0,
            error=detail,
        )
        if self.metrics is not None:
            self.metrics.log(
                {
                    "serve_request": req.uid,
                    "serve/prompt_tokens": completion.prompt_tokens,
                    "serve/output_tokens": 0,
                    "serve/finish_reason": reason,
                    "serve/ttft_s": 0.0,
                    "serve/latency_s": 0.0,
                    "serve/decode_tokens_per_s": 0.0,
                }
            )
        self._finalize(completion)
        return completion

    def _finalize(self, completion: Completion) -> None:
        self._deadlines.pop(completion.uid, None)
        self._on_token.pop(completion.uid, None)
        self._trace_ids.pop(completion.uid, None)
        callback = self._on_finish.pop(completion.uid, None)
        if callback is None:
            return
        try:
            callback(completion)
        except Exception as e:
            logger.warning(
                f"request {completion.uid}: finish callback failed: {e!r}"
            )


@dataclasses.dataclass
class _PagedSlot(_Slot):
    pages: List[int] = dataclasses.field(default_factory=list)  # logical order
    shared_pages: int = 0  # leading pages borrowed from the prefix cache
    prefill_progress: int = 0  # prompt tokens already written to the pool
    decoding: bool = False  # first token sampled; joins the decode batch
    seq: int = 0  # admission order; chunk scheduling is oldest-first
    migrating: bool = False  # handoff to a decode-pool peer is in flight
    # spec="model": the draft model's own page run (same worst-case size as
    # the base's), allocated at admission from the one shared pool
    draft_pages: List[int] = dataclasses.field(default_factory=list)


class PagedContinuousBatchingScheduler(ContinuousBatchingScheduler):
    """Continuous batching over the paged engine: budgeted rounds instead of
    prefill-on-admission.

    Each ``step()`` spends its budget as: expire deadlines, admit pending
    requests (page allocation + prefix-cache lookup only — cheap host work),
    run **at most one prefill chunk** for the oldest still-prefilling slot,
    then one paged decode over every decoding slot.  A long prompt therefore
    never stalls in-flight streams for more than one ``chunk_size`` forward —
    the contiguous scheduler's ``serve/prefill_stall_share`` is exactly the
    cost this removes.

    Admission is all-or-nothing on pages (worst case
    ``ceil((prompt + max_new_tokens) / page_size)``): when the pool is
    exhausted the queue head *stays queued* (FIFO — later requests do not
    jump it) and is retried next round after retired requests or evicted
    prefix entries free pages.  Contrast with the HTTP front-end's 429 path,
    which only bounds the *queue*; allocator pressure never rejects.

    Sampling keys stay ``(uid, token_index)`` — the same stream as the
    contiguous scheduler — and the paged attention math is bitwise-identical
    to the contiguous path (ops/attention.paged_cached_attention), so a
    drain through this scheduler is token-identical to the contiguous one
    for the same request stream (pinned by tests/test_paging.py).

    ``spec="ngram"`` (engine built with ``spec_k >= 1``) turns each decode
    round into a draft→verify→accept round: a prompt-lookup drafter proposes
    up to ``spec_k`` continuation tokens per row from the row's own
    prompt+generated context, one ``(batch, spec_k+1)`` verify forward
    scores the whole window, and a host-side walk commits the longest
    accepted prefix plus one corrective token — so an accepting row emits up
    to ``spec_k+1`` tokens for one forward's worth of HBM traffic (decode is
    memory-bound; the window reuses the same weight/KV stream).  Greedy rows
    accept by argmax match, so their output is token-identical to the
    non-speculative path (pinned by tests/test_spec.py); sampled rows use
    rejection sampling against the same filtered target distribution
    ``sample()`` draws from, keyed by the same ``(uid, token_index)``
    scheme, so their outputs stay exactly target-distributed.  Rejected
    drafts need no pool rollback: every window write lands inside the
    request's worst-case admission allocation (or the null page, via the
    verify table's trailing null column) and is overwritten before any
    later query can attend it — page accounting is untouched, which
    tests/test_paging.py pins under cancel/expiry mid-stream.  A round
    where no row drafted falls back to the plain ``decode_paged`` shape, so
    both steady-state shapes are warmed and nothing retraces.

    ``packed=True`` (engine built with ``token_budget``) replaces the whole
    round with ONE ``step_paged`` dispatch: every decoding row's window plus
    oldest-first prefill tokens from *multiple* slots, token-budget
    (Sarathi) style, padded to the smallest warmed bucket.  Prefill no
    longer serializes one chunk per round, decode rides a compute-dense
    forward instead of a memory-bound ``(B, 1)`` step, and per-round
    dispatch overhead halves — while sampling reuses the sequential calls
    and keys verbatim, so the drain stays token-identical (pinned by
    tests/test_packed.py).
    """

    #: longest context suffix the prompt-lookup drafter tries to match
    _NGRAM_MAX = 3

    def __init__(
        self,
        engine: InferenceEngine,
        *,
        prefix_cache: bool = True,
        prefix_cache_entries: int = 256,
        spec: str = "off",
        packed: bool = False,
        role: str = "mixed",
        **kwargs,
    ):
        super().__init__(engine, **kwargs)
        if role not in ("prefill", "decode", "mixed"):
            raise ValueError(
                f"role must be 'prefill', 'decode', or 'mixed', got {role!r}"
            )
        if spec not in ("off", "ngram", "model"):
            raise ValueError(
                f"spec must be 'off', 'ngram', or 'model', got {spec!r}"
            )
        if spec != "off" and getattr(engine, "spec_k", 0) < 1:
            raise ValueError(
                f"spec={spec!r} needs an engine built with spec_k >= 1 "
                "(the verify window compiles at (batch, spec_k+1))"
            )
        if spec == "model":
            if getattr(engine, "draft_params", None) is None:
                raise ValueError(
                    "spec='model' needs a draft model: call "
                    "engine.load_draft_params(...) before building the scheduler"
                )
            if packed:
                raise ValueError(
                    "spec='model' is incompatible with packed=True (the draft "
                    "proposal loop runs on the per-row decode path)"
                )
            if role != "mixed":
                raise ValueError(
                    "spec='model' needs role='mixed': draft KV pages cannot "
                    "migrate between disaggregated peers"
                )
            # base and draft prefill must stay in lockstep, so prefix-cache
            # page sharing (which skips base prefill work the draft still
            # needs) is disabled in model-drafted mode
            prefix_cache = False
        self._spec = spec
        self._spec_drafted = 0  # cumulative drafted tokens (counter)
        self._spec_accepted = 0  # cumulative accepted drafted tokens (counter)
        self._spec_sample = jax.jit(spec_verify_draws, static_argnames=("top_k",))
        if not getattr(engine, "paged", False):
            raise ValueError(
                "PagedContinuousBatchingScheduler needs an engine built with "
                "page_size/num_pages (got a contiguous InferenceEngine)"
            )
        self._packed = packed
        if packed:
            if not getattr(engine, "token_budget", 0):
                raise ValueError(
                    "packed=True needs an engine built with token_budget "
                    "(the packed step compiles at the budget's buckets)"
                )
            # every decoding row must fit its whole round window in one
            # dispatch — the budget only throttles prefill, never decode
            floor = self.max_batch * (
                engine.spec_k + 1 if spec == "ngram" else 1
            )
            if engine.token_budget < floor:
                raise ValueError(
                    f"token_budget ({engine.token_budget}) cannot hold every "
                    f"decode row's window: need >= {floor} "
                    f"(max_batch x window size)"
                )
        self.allocator = PageAllocator(
            engine.num_pages,
            engine.page_size,
            page_bytes=engine.pool_bytes() // engine.num_pages,
        )
        self.prefix_cache = (
            PrefixCache(self.allocator, max_entries=prefix_cache_entries)
            if prefix_cache
            else None
        )
        self._pool = None  # allocated on first admission, then persistent
        # per-row decode block tables: NULL rows for free / still-prefilling
        # slots, so their garbage decode write lands in the null page
        self._tables = np.zeros((self.max_batch, engine.block_table_width), np.int32)
        # spec="model": per-row draft-model block tables, same null-row
        # convention as ``_tables`` (free rows stay all-null so the draft
        # loop's garbage writes land in the null page)
        self._draft_tables = np.zeros(
            (self.max_batch, engine.block_table_width), np.int32
        )
        # the packed step's table matrix: every slot's table (W plus the
        # trailing null column) and a final all-null pad row that padding
        # tokens' row_map points at — maintained from admission so packed
        # rounds never rebuild tables on the hot path
        self._ptables = np.zeros(
            (self.max_batch + 1, engine.block_table_width + 1), np.int32
        )
        self._admit_seq = 0  # admission order, drives chunk scheduling (FIFO)
        self._pad_tokens = 0  # chunk padding written, cumulative
        self._prefill_tokens = 0  # real prompt tokens written, cumulative
        # dispatch economics (cumulative): rounds, model dispatches, and
        # packed-window tokens (total vs real) — the gauges the packed step
        # exists to move (serve/dispatches_per_round, tokens_per_dispatch,
        # packed_token_utilization)
        self._round_total = 0
        self._dispatch_total = 0
        self._dispatch_tokens = 0
        self._dispatch_tokens_real = 0
        self._admit_time_s = 0.0  # cumulative prefill/admission wall time
        self._decode_time_s = 0.0  # cumulative decode/packed-step wall time
        # static for the engine's lifetime (pool shapes never change): the
        # serve/kv_cache_bytes and serve/kv_bytes_per_token gauges
        self._kv_cache_bytes = engine.pool_bytes()
        self._kv_bytes_per_token = engine.kv_bytes_per_token()
        # disaggregated serving (docs/serving.md): a prefill-role scheduler
        # hands each finished prompt's page run to ``migration_sink`` (set by
        # the server; runs on the model thread, must not block) and parks the
        # slot as ``migrating`` until the peer commits or the handoff fails
        # open back to local decode.  ``prefix_fetch`` pulls prefix pages
        # from a peer on a local cache miss (the fleet prefix directory).
        self.role = role
        self.migration_sink: Optional[Callable[[Dict[str, Any], list], bool]] = None
        self.prefix_fetch: Optional[Callable[[List[str]], Any]] = None
        self._prefix_fetch_tried: set = set()
        self._pages_migrated = 0
        self._migration_bytes = 0
        self._migration_failures = 0
        self._migrated_inserts = 0
        self._prefix_fetches = 0
        self._prefix_fetch_failures = 0

    # -- admission ------------------------------------------------------------

    def _ensure_pool(self):
        if self._pool is None:
            self._pool = self.engine.init_pool()
        return self._pool

    def _admit_pass(self, finished: List[Completion]) -> None:
        """Fill free slots from the queue head: prefix lookup + page
        allocation only (no device work — the prefill happens one chunk per
        step).  Allocation failure leaves the head queued and stops."""
        while self._pending:
            slot_idx = next(
                (i for i in range(self.max_batch) if self._slots[i] is None), None
            )
            if slot_idx is None:
                return
            req = self._pending[0]
            deadline = self._deadlines.get(req.uid)
            if deadline is not None and time.monotonic() >= deadline:
                self._pending.popleft()
                finished.append(self._finalize_unadmitted(req, "timeout"))
                continue
            try:
                adapter_slot = self._acquire_adapter(req)
            except Exception as e:
                logger.warning(f"request {req.uid}: adapter load failed: {e!r}")
                self._pending.popleft()
                finished.append(
                    self._finalize_unadmitted(req, "error", f"adapter load failed: {e}")
                )
                continue
            if adapter_slot is None:
                # every adapter slot pinned by live traffic: the head stays
                # queued (FIFO) and retries after a retirement drops a pin —
                # the same contract as allocator exhaustion below
                return
            need = pages_needed(
                len(req.prompt) + req.max_new_tokens, self.engine.page_size
            )
            shared_pages: List[int] = []
            shared_tokens = 0
            if self.prefix_cache is not None:
                shared_pages, shared_tokens = self.prefix_cache.lookup(req.prompt)
                if (
                    not shared_pages
                    and self.prefix_fetch is not None
                    and req.uid not in self._prefix_fetch_tried
                ):
                    # one fetch attempt per uid: a miss (or a failed peer)
                    # falls open to local prefill, never a retry loop
                    if len(self._prefix_fetch_tried) > 8192:
                        self._prefix_fetch_tried.clear()
                    self._prefix_fetch_tried.add(req.uid)
                    shared_pages, shared_tokens = self._fetch_prefix(req)
            # spec="model": the draft model keeps its own KV pages in the one
            # shared pool — admission allocates both runs or neither
            draft_need = need if self._spec == "model" else 0
            fresh = self.allocator.alloc(need - len(shared_pages) + draft_need)
            if fresh is None and self.prefix_cache is not None:
                # under pressure: drop idle prefix entries (LRU) and retry —
                # entries shared with live requests survive via refcounts
                self.prefix_cache.evict(need - len(shared_pages) + draft_need)
                fresh = self.allocator.alloc(need - len(shared_pages) + draft_need)
            if fresh is None:
                # allocator exhausted: stay queued rather than reject; pages
                # free as decoding requests retire (docs/operations.md)
                if shared_pages:
                    self.allocator.decref(shared_pages)
                self._release_adapter(req)  # drop the pin while we wait
                return
            self._pending.popleft()
            t_admit = time.monotonic()
            base_fresh = fresh[: need - len(shared_pages)]
            draft_pages = fresh[need - len(shared_pages):]
            self._slots[slot_idx] = _PagedSlot(
                request=req,
                pos=0,
                tokens=[],
                t_admit=t_admit,
                t_first=t_admit,  # overwritten when the first token lands
                deadline=deadline,
                span=None,  # decode span opens at first token
                pages=shared_pages + base_fresh,
                shared_pages=len(shared_pages),
                prefill_progress=shared_tokens,
                seq=self._admit_seq,
                adapter_slot=adapter_slot,
                draft_pages=draft_pages,
            )
            self._admit_seq += 1
            # decode row stays NULL until this slot starts decoding
            self._tokens[slot_idx] = 0
            self._positions[slot_idx] = 0
            self._tables[slot_idx, :] = 0
            self._draft_tables[slot_idx, :] = 0
            # the packed table row is live from admission: prefill tokens
            # route through it the same round they are admitted
            self._ptables[slot_idx, :] = 0
            pages = shared_pages + base_fresh
            self._ptables[slot_idx, : len(pages)] = pages
            self._adapter_row[slot_idx] = adapter_slot

    # -- prefill (one chunk per round) ----------------------------------------

    def _prefill_pass(self, finished: List[Completion]) -> None:
        """Run one prefill chunk for the oldest still-prefilling slot; when
        it completes the prompt, sample the first token (key (uid, 0) — the
        same stream as the contiguous path) and arm the slot for decode."""
        prefilling = [
            (s.seq, i)
            for i, s in enumerate(self._slots)
            if s is not None and not s.decoding and not s.migrating
        ]
        if not prefilling:
            return
        slot_idx = min(prefilling)[1]  # oldest admission first (FIFO)
        slot = self._slots[slot_idx]
        req = slot.request
        L = len(req.prompt)
        chunk = self.engine.chunk_size
        start = slot.prefill_progress
        n_real = min(chunk, L - start)
        ids = np.zeros((1, chunk), np.int32)
        ids[0, :n_real] = list(req.prompt[start : start + n_real])
        table = np.zeros((1, self.engine.block_table_width), np.int32)
        table[0, : len(slot.pages)] = slot.pages
        self._pad_tokens += chunk - n_real
        self._prefill_tokens += n_real
        tid = self._trace_ids.get(req.uid)
        first_id = None
        t0 = time.monotonic()
        with self.tracer.span(
            "prefill_chunk", trace_id=tid, uid=req.uid, start=start, chunk=chunk
        ):
            logits, self._pool = self.engine.prefill_chunk(
                jnp.asarray(ids), start, self._ensure_pool(), table,
                adapter_idx=[slot.adapter_slot],
            )
            self._count_dispatch(chunk, n_real)
            if self._spec == "model":
                # the draft model prefills the same chunk into its own page
                # run, so base and draft KV stay in lockstep position-wise
                draft_table = np.zeros((1, self.engine.block_table_width), np.int32)
                draft_table[0, : len(slot.draft_pages)] = slot.draft_pages
                _, self._pool = self.engine.draft_prefill_chunk(
                    ids, start, self._pool, draft_table
                )
                self._count_dispatch(chunk, n_real)
            slot.prefill_progress = start + n_real
            if slot.prefill_progress >= L:
                first = self.engine._sample(
                    logits[:, L - 1 - start, :],
                    self._request_key(req, 0),
                    temperature=req.temperature,
                    top_k=self.top_k,
                    top_p=req.top_p,
                )
                first_id = int(np.asarray(first)[0])
        self._observe("prefill_seconds", time.monotonic() - t0)
        if first_id is None:
            return  # more chunks to go; decode proceeds this round regardless
        if self.prefix_cache is not None:
            # only pages fully covered by prompt tokens register — the
            # donor's decode writes (positions >= L) never touch them
            self.prefix_cache.register(list(req.prompt), slot.pages)
        slot.decoding = True
        slot.tokens = [first_id]
        slot.pos = L
        slot.t_first = time.monotonic()
        slot.span = self.tracer.start_span("decode", trace_id=tid, uid=req.uid)
        self._tokens[slot_idx] = first_id
        self._positions[slot_idx] = L
        self._tables[slot_idx, : len(slot.pages)] = slot.pages
        if slot.draft_pages:
            self._draft_tables[slot_idx, : len(slot.draft_pages)] = slot.draft_pages
        self._emit_token(req.uid, first_id, 0)
        self._finish_if_done(slot_idx, finished)
        self._maybe_migrate(slot_idx)

    # -- disaggregated handoff (prefill role -> decode peer) --------------------

    def _find_slot(self, uid: int) -> Optional[int]:
        for slot_idx, slot in enumerate(self._slots):
            if slot is not None and slot.request.uid == uid:
                return slot_idx
        return None

    def _maybe_migrate(self, slot_idx: int) -> None:
        """Donor side: a prefill-role scheduler that just completed a prompt
        exports its filled page run and hands ``(record, entries)`` to the
        server's migration sink.  The slot parks as ``migrating`` — out of
        both the prefill and decode sets — until ``migration_commit`` /
        ``migration_abort`` / ``migration_failed`` resolves it.  Any export
        or sink error fails open: the slot resumes decoding locally."""
        if self.role != "prefill" or self.migration_sink is None:
            return
        slot = self._slots[slot_idx]
        if slot is None or slot.migrating or not slot.decoding:
            return  # finished at prefill (eos / max_new_tokens == 1)
        req = slot.request
        n_pages = pages_needed(len(req.prompt), self.engine.page_size)
        try:
            faults.maybe_fail("serve_migrate")
            entries = self.engine.export_page_run(
                self._ensure_pool(), slot.pages[:n_pages]
            )
        except Exception as e:
            logger.warning(f"request {req.uid}: page-run export failed: {e!r}")
            self._count_migration_failure(req.uid, f"export failed: {e}")
            return  # slot keeps decoding locally, untouched
        record = wire.build_migration_record(
            uid=req.uid,
            prompt=req.prompt,
            max_new_tokens=req.max_new_tokens,
            temperature=req.temperature,
            top_p=req.top_p,
            spec=req.spec,
            adapter=req.adapter,
            first_token=slot.tokens[0],
            position=slot.pos,
            token_index=len(slot.tokens),
            n_pages=n_pages,
        )
        # park: the decode row goes back to the null table so this round's
        # (and every later round's) garbage write lands in the null page
        slot.migrating = True
        slot.decoding = False
        self._tokens[slot_idx] = 0
        self._positions[slot_idx] = 0
        self._tables[slot_idx, :] = 0
        ok = False
        try:
            ok = bool(self.migration_sink(record, entries))
        except Exception as e:
            logger.warning(f"request {req.uid}: migration sink failed: {e!r}")
        if not ok:
            self.migration_failed(req.uid, "sink rejected handoff")

    def migration_failed(self, uid: int, detail: Optional[str] = None) -> None:
        """Fail open: the handoff died before the peer relayed any token —
        resume decoding locally from exactly where prefill left off.  The
        client stream never notices (same sampling keys, same token
        indices); the failure is a typed counter + event, not an error."""
        slot_idx = self._find_slot(uid)
        if slot_idx is None:
            return  # cancelled/expired while the transfer was in flight
        slot = self._slots[slot_idx]
        if not slot.migrating:
            return
        slot.migrating = False
        slot.decoding = True
        self._tokens[slot_idx] = slot.tokens[-1]
        self._positions[slot_idx] = slot.pos
        self._tables[slot_idx, : len(slot.pages)] = slot.pages
        self._count_migration_failure(uid, detail)

    def _count_migration_failure(self, uid: int, detail: Optional[str]) -> None:
        self._migration_failures += 1
        logger.warning(
            f"request {uid}: migration failed open to local decode"
            + (f" ({detail})" if detail else "")
        )
        if self.obs_registry is not None:
            self.obs_registry.inc("migration_failures_total")

    def migration_commit(self, uid: int, bytes_sent: int = 0) -> Optional[Completion]:
        """The decode peer accepted the run and the relay delivered the
        peer's finish: retire the donor slot WITHOUT firing the client
        callbacks (the relay already owns that stream) and free its pages."""
        slot_idx = self._find_slot(uid)
        if slot_idx is None:
            return None
        slot = self._slots[slot_idx]
        if not slot.migrating:
            return None
        self._on_token.pop(uid, None)
        self._on_finish.pop(uid, None)
        n_pages = pages_needed(len(slot.request.prompt), self.engine.page_size)
        self._pages_migrated += n_pages
        self._migration_bytes += bytes_sent
        if self.obs_registry is not None:
            self.obs_registry.inc("pages_migrated_total", by=n_pages)
            self.obs_registry.inc("migration_bytes_total", by=bytes_sent)
        return self._retire(slot_idx, "migrated")

    def migration_abort(self, uid: int, detail: Optional[str] = None) -> Optional[Completion]:
        """The peer died AFTER relaying at least one token: the request
        cannot be silently replayed (PR 9 idempotency boundary), so the
        server sends the client a typed error finish and this retires the
        donor slot without firing the (already-detached) callbacks."""
        slot_idx = self._find_slot(uid)
        if slot_idx is None:
            return None
        slot = self._slots[slot_idx]
        if not slot.migrating:
            return None
        self._on_token.pop(uid, None)
        self._on_finish.pop(uid, None)
        self._count_migration_failure(uid, detail or "peer died mid-relay")
        return self._retire(slot_idx, "error", detail or "migration_failed")

    def submit_migrated(
        self,
        record: Dict[str, Any],
        entries: Sequence,
        *,
        on_token: Optional[TokenCallback] = None,
        on_finish: Optional[FinishCallback] = None,
        deadline: Optional[float] = None,
        trace_id: Optional[str] = None,
    ) -> None:
        """Receiver side: install a migrated request straight into a decode
        slot — scatter its page run into freshly allocated pages, arm the
        decode row at the donor's position, and continue sampling with keys
        ``(uid, token_index)`` unchanged, so the drain is token-identical to
        a mixed replica.  Raises on ANY precondition miss (dup uid, no free
        slot, no adapter capacity, pool exhausted, malformed run) — the
        donor maps a raise to fail-open local decode, so rejecting here is
        always safe.  Runs on the model thread, like every mutator."""
        fields = wire.parse_migration_record(record)
        req = Request(
            uid=fields["uid"],
            prompt=fields["prompt"],
            max_new_tokens=fields["max_new_tokens"],
            temperature=fields["temperature"],
            top_p=fields["top_p"],
            spec=fields["spec"],
            adapter=fields["adapter"],
        )
        self.validate_request(req)
        if req.uid in self._deadlines or req.uid in self._on_finish or any(
            r.uid == req.uid for r in self._pending
        ) or any(s is not None and s.request.uid == req.uid for s in self._slots):
            raise ValueError(f"migrated request {req.uid}: uid already in flight")
        L = len(req.prompt)
        n_pages = fields["n_pages"]
        if fields["position"] != L or n_pages != pages_needed(
            L, self.engine.page_size
        ):
            raise ValueError(
                f"migrated request {req.uid}: inconsistent run "
                f"(position {record['position']}, n_pages {n_pages}, prompt {L})"
            )
        slot_idx = next(
            (i for i in range(self.max_batch) if self._slots[i] is None), None
        )
        if slot_idx is None:
            raise RuntimeError(f"migrated request {req.uid}: no free slot")
        adapter_slot = self._acquire_adapter(req)
        if adapter_slot is None:
            raise RuntimeError(f"migrated request {req.uid}: no adapter capacity")
        try:
            need = pages_needed(L + req.max_new_tokens, self.engine.page_size)
            pages = self.allocator.alloc(need)
            if pages is None and self.prefix_cache is not None:
                self.prefix_cache.evict(need)
                pages = self.allocator.alloc(need)
            if pages is None:
                raise RuntimeError(f"migrated request {req.uid}: pool exhausted")
            try:
                self._pool = self.engine.import_page_run(
                    self._ensure_pool(), pages[:n_pages], entries
                )
            except Exception:
                self.allocator.decref(pages)
                raise
        except Exception:
            self._release_adapter(req)
            raise
        first = fields["first_token"]
        now = time.monotonic()
        self._slots[slot_idx] = _PagedSlot(
            request=req,
            pos=L,
            tokens=[first],
            t_admit=now,
            t_first=now,
            deadline=deadline,
            span=self.tracer.start_span("decode", trace_id=trace_id, uid=req.uid),
            pages=pages,
            shared_pages=0,
            prefill_progress=L,
            decoding=True,
            seq=self._admit_seq,
            adapter_slot=adapter_slot,
        )
        self._admit_seq += 1
        if deadline is not None:
            self._deadlines[req.uid] = deadline
        if on_token is not None:
            self._on_token[req.uid] = on_token
        if on_finish is not None:
            self._on_finish[req.uid] = on_finish
        if trace_id is not None:
            self._trace_ids[req.uid] = trace_id
        self._tokens[slot_idx] = first
        self._positions[slot_idx] = L
        self._tables[slot_idx, :] = 0
        self._tables[slot_idx, : len(pages)] = pages
        self._ptables[slot_idx, :] = 0
        self._ptables[slot_idx, : len(pages)] = pages
        self._adapter_row[slot_idx] = adapter_slot
        if self.prefix_cache is not None:
            # the migrated prompt's pages are as shareable as a locally
            # prefilled one's — register them for later local hits
            self.prefix_cache.register(list(req.prompt), pages)
        self._migrated_inserts += 1
        if self.obs_registry is not None:
            self.obs_registry.inc("migrated_inserts_total")

    def _fetch_prefix(self, req: Request) -> tuple:
        """Fleet prefix-page directory client path: on a local miss, ask the
        directory for the longest cached page-aligned prefix of ``req``'s
        prompt held by a peer, import its pages, register them locally, and
        re-run the local lookup.  Every failure path returns ``([], 0)`` —
        fail open to local prefill."""
        ps = self.engine.page_size
        k_max = (len(req.prompt) - 1) // ps
        if k_max < 1 or self.prefix_cache is None:
            return [], 0
        digests = [
            PrefixCache._digest(req.prompt[: k * ps]).hex()
            for k in range(k_max, 0, -1)
        ]
        try:
            faults.maybe_fail("serve_prefix_fetch")
            hit = self.prefix_fetch(digests)
            if hit is None:
                return [], 0
            n_tokens, entries, nbytes = hit
            n_tokens = int(n_tokens)
            if n_tokens < ps or n_tokens % ps or n_tokens > k_max * ps:
                raise ValueError(f"peer returned unusable prefix ({n_tokens} tokens)")
            n_pages = n_tokens // ps
            pages = self.allocator.alloc(n_pages)
            if pages is None:
                self.prefix_cache.evict(n_pages)
                pages = self.allocator.alloc(n_pages)
            if pages is None:
                return [], 0  # pool pressure: not a failure, just skip
            try:
                self._pool = self.engine.import_page_run(
                    self._ensure_pool(), pages, entries
                )
            except Exception:
                self.allocator.decref(pages)
                raise
            self.prefix_cache.register(list(req.prompt[:n_tokens]), pages)
            # the cache's own refs keep the run alive; drop the alloc ref and
            # let the re-lookup incref for this request like any local hit
            self.allocator.decref(pages)
            self._prefix_fetches += 1
            self._migration_bytes += int(nbytes)
            if self.obs_registry is not None:
                self.obs_registry.inc("prefix_fetch_total")
                self.obs_registry.inc("migration_bytes_total", by=int(nbytes))
            return self.prefix_cache.lookup(req.prompt)
        except Exception as e:
            logger.warning(f"request {req.uid}: prefix fetch failed: {e!r}")
            self._prefix_fetch_failures += 1
            if self.obs_registry is not None:
                self.obs_registry.inc("prefix_fetch_failures_total")
            return [], 0

    # -- speculative draft / verify --------------------------------------------

    def _ngram_draft(self, ctx: List[int], k: int) -> List[int]:
        """Prompt-lookup drafting: match the longest context suffix
        (n-gram, ``n <= _NGRAM_MAX``) against an earlier occurrence in the
        row's own prompt+generated tokens and propose the tokens that
        followed it (most recent occurrence wins).  Free — no second model,
        no device work — and effective exactly when generation repeats its
        context, the regime where speculation pays."""
        if k <= 0 or len(ctx) < 2:
            return []
        for n in range(min(self._NGRAM_MAX, len(ctx) - 1), 0, -1):
            pattern = ctx[-n:]
            for i in range(len(ctx) - n - 1, -1, -1):
                if ctx[i : i + n] == pattern:
                    return ctx[i + n : i + n + k]
        return []

    def _draft_pass(self) -> Dict[int, List[int]]:
        """Draft up to ``spec_k`` tokens per decoding row.  A row only
        drafts within its remaining budget minus one (the round always
        commits at least one token), so every window write — accepted or
        rejected — stays inside the worst-case admission allocation and
        rollback never touches the allocator."""
        drafts: Dict[int, List[int]] = {}
        spec_k = self.engine.spec_k
        for slot_idx, slot in enumerate(self._slots):
            if slot is None or not slot.decoding or not slot.request.spec:
                continue
            k = min(spec_k, slot.request.max_new_tokens - len(slot.tokens) - 1)
            if k <= 0:
                continue
            d = self._ngram_draft(list(slot.request.prompt) + slot.tokens, k)
            if d:
                drafts[slot_idx] = d
        return drafts

    def _model_draft_pass(self) -> Dict[int, List[int]]:
        """spec="model": the draft model proposes up to ``spec_k`` tokens per
        decoding row by running k batched ``(batch, 1)`` autoregressive decode
        steps over its own page run, chaining greedy (argmax) proposals on
        device and pulling the whole proposal matrix to the host once at the
        end.  Rows past their own draft budget go null mid-loop (all-null
        table, pos 0) so their garbage writes land in the null page.  The
        same budget rule as the ngram drafter applies (remaining minus one),
        so the verify window never writes past the admission allocation."""
        spec_k = self.engine.spec_k
        B = self.max_batch
        ks = np.zeros(B, np.int32)
        eligible: List[int] = []
        for slot_idx, slot in enumerate(self._slots):
            if slot is None or not slot.decoding or not slot.request.spec:
                continue
            k = min(spec_k, slot.request.max_new_tokens - len(slot.tokens) - 1)
            if k <= 0:
                continue
            eligible.append(slot_idx)
            ks[slot_idx] = k
        if not eligible:
            return {}
        k_max = int(ks.max())
        cur = jnp.asarray(self._tokens)[:, None]
        proposals = []
        for step in range(k_max):
            live = ks > step
            positions = np.where(live, self._positions + step, 0).astype(np.int32)
            tables = np.where(live[:, None], self._draft_tables, 0).astype(np.int32)
            logits, self._pool = self.engine.draft_decode_paged(
                self._ensure_pool(), cur, positions[:, None], tables
            )
            self._count_dispatch(B, int(live.sum()))
            cur = jnp.argmax(logits, axis=-1).astype(jnp.int32).reshape(-1, 1)
            proposals.append(cur)
        stacked = np.asarray(jnp.concatenate(proposals, axis=1))  # one host pull
        return {
            i: [int(t) for t in stacked[i, : int(ks[i])]] for i in eligible
        }

    def _verify_round(self, drafts: Dict[int, List[int]], finished: List[Completion]) -> None:
        """One ``(batch, spec_k+1)`` verify forward over every decoding row,
        then the host-side accept walk.  Window row 0 carries the pending
        token; rows ``1..k`` carry the drafts at consecutive positions.
        Padding rows (free / prefilling / short drafts) write through the
        trailing null column of the ``W+1``-wide tables at ``pos >=
        cache_size``, so no live page is ever touched.  The walk commits the
        longest accepted draft prefix plus one corrective token — greedy
        rows by argmax match, sampled rows by rejection sampling — through
        the same emit/finish flow as the plain path, stopping at EOS."""
        spec_k = self.engine.spec_k
        S = spec_k + 1
        B = self.max_batch
        W = self.engine.block_table_width
        null_pos = self.engine.cache_size  # clips into the null column
        tokens = np.zeros((B, S), np.int32)
        positions = np.full((B, S), null_pos, np.int32)
        tables = np.zeros((B, W + 1), np.int32)
        draft_mat = np.zeros((B, spec_k), np.int32)
        k_eff = np.zeros(B, np.int32)
        uids = np.zeros(B, np.int32)
        starts = np.zeros(B, np.int32)
        temps = np.zeros(B, np.float32)
        top_ps = np.ones(B, np.float32)
        offsets = np.arange(S, dtype=np.int32)
        for slot_idx, slot in enumerate(self._slots):
            if slot is None or not slot.decoding:
                continue
            d = drafts.get(slot_idx, [])
            tokens[slot_idx, 0] = self._tokens[slot_idx]
            tokens[slot_idx, 1 : 1 + len(d)] = d
            positions[slot_idx] = self._positions[slot_idx] + offsets
            tables[slot_idx, :W] = self._tables[slot_idx]
            draft_mat[slot_idx, : len(d)] = d
            k_eff[slot_idx] = len(d)
            uids[slot_idx] = slot.request.uid
            starts[slot_idx] = len(slot.tokens)
            temps[slot_idx] = slot.request.temperature
            top_ps[slot_idx] = slot.request.top_p
        n_dec = sum(1 for s in self._slots if s is not None and s.decoding)
        logits, self._pool = self.engine.verify_paged(
            self._ensure_pool(), tokens, positions, tables,
            adapter_idx=self._adapter_row,
        )
        self._count_dispatch(B * S, n_dec + int(k_eff.sum()))
        accept, alt = self._spec_sample(
            logits,
            jnp.asarray(draft_mat),
            self.key,
            jnp.asarray(uids),
            jnp.asarray(starts),
            jnp.asarray(k_eff),
            temperature=jnp.asarray(temps),
            top_k=self.top_k,
            top_p=jnp.asarray(top_ps),
        )
        self._commit_spec_walk(
            np.asarray(accept), np.asarray(alt), draft_mat, k_eff,
            set(i for i, s in enumerate(self._slots) if s is not None and s.decoding),
            finished,
        )

    def _commit_spec_walk(
        self,
        accept: np.ndarray,
        alt: np.ndarray,
        draft_mat: np.ndarray,
        k_eff: np.ndarray,
        eligible: set,
        finished: List[Completion],
    ) -> None:
        """The host-side accept walk shared by the sequential verify round
        and the packed step: for each eligible row commit the longest
        accepted draft prefix plus one corrective token through the normal
        emit/finish flow, stopping at EOS.  ``eligible`` is the set of slot
        indices that actually rode the verify window (the packed step must
        exclude slots it armed for decode *after* the dispatch)."""
        drafted = accepted = 0
        for slot_idx in sorted(eligible):
            slot = self._slots[slot_idx]
            if slot is None or not slot.decoding:
                continue
            k = int(k_eff[slot_idx])
            a = 0
            while a < k and accept[slot_idx, a]:
                a += 1
            drafted += k
            accepted += a
            commits = [int(t) for t in draft_mat[slot_idx, :a]]
            commits.append(int(alt[slot_idx, a]))
            req = slot.request
            for tok in commits:
                slot.tokens.append(tok)
                slot.pos += 1
                self._tokens[slot_idx] = tok
                self._positions[slot_idx] = slot.pos
                self._emit_token(req.uid, tok, len(slot.tokens) - 1)
                self._finish_if_done(slot_idx, finished)
                if self._slots[slot_idx] is None:
                    break  # EOS / budget inside the window: drop the rest
        self._spec_drafted += drafted
        self._spec_accepted += accepted
        if self.obs_registry is not None and drafted:
            self.obs_registry.inc("spec_drafted_total", by=drafted)
            self.obs_registry.inc("spec_accepted_total", by=accepted)

    # -- the budgeted round ----------------------------------------------------

    def step(self) -> List[Completion]:
        """One budgeted round: expire deadlines, admit (page accounting
        only), at most one prefill chunk, then one paged decode over every
        decoding slot.  Returns the requests that finished during it.
        ``packed=True`` replaces the whole round body with the single-
        dispatch packed step (``_step_packed``)."""
        if self._packed:
            return self._step_packed()
        finished: List[Completion] = []
        t_step = time.monotonic()
        d0 = self._dispatch_total
        self._expire_deadlines(finished)
        self._admit_pass(finished)
        self._prefill_pass(finished)
        admit_s = time.monotonic() - t_step
        decoding = [
            s is not None and s.decoding for s in self._slots
        ]
        n_decoding = sum(decoding)
        if n_decoding == 0:
            if self._dispatch_total > d0:
                self._count_round()  # pure-prefill round still dispatched
                self._admit_time_s += admit_s  # a 100%-stall round
            elif any(s is not None and s.migrating for s in self._slots):
                time.sleep(0.001)  # only parked handoffs: don't hot-spin
            return finished  # pure-prefill round (or idle)

        t_decode = time.monotonic()
        if self._spec == "ngram":
            drafts = self._draft_pass()
        elif self._spec == "model":
            drafts = self._model_draft_pass()
        else:
            drafts = {}
        n_drafted = sum(len(d) for d in drafts.values())
        with self.tracer.span(
            "decode_step",
            step=self._step_count,
            active_slots=n_decoding,
            spec_drafted=n_drafted,
        ):
            if drafts:
                # draft→verify→accept: the walk commits straight into the
                # slots, so there is no next_tokens loop for this branch
                self._verify_round(drafts, finished)
                self._step_count += 1
                next_tokens = None
            else:
                # no row drafted (spec off, or nothing to look up): the
                # plain warmed (batch, 1) decode shape
                logits, self._pool = self.engine.decode_paged(
                    self._ensure_pool(),
                    jnp.asarray(self._tokens)[:, None],
                    jnp.asarray(self._positions)[:, None],
                    self._tables,
                    adapter_idx=self._adapter_row,
                )
                self._count_dispatch(self.max_batch, n_decoding)
                self._step_count += 1
                masked = [
                    s if (s is not None and s.decoding) else None for s in self._slots
                ]
                next_tokens = self._sample_rows(logits, masked).tolist()
        decode_s = time.monotonic() - t_decode
        self._observe("decode_step_seconds", decode_s)
        self._count_round()
        if next_tokens is not None:
            for slot_idx, slot in enumerate(self._slots):
                if slot is None or not slot.decoding:
                    continue
                tok = next_tokens[slot_idx]
                slot.tokens.append(tok)
                slot.pos += 1
                self._tokens[slot_idx] = tok
                self._positions[slot_idx] = slot.pos
                self._emit_token(slot.request.uid, tok, len(slot.tokens) - 1)
                self._finish_if_done(slot_idx, finished)
        self._round_metrics(admit_s, decode_s, n_decoding)
        return finished

    # -- dispatch accounting ----------------------------------------------------

    def _count_dispatch(self, tokens: int, real: int) -> None:
        """One model dispatch of ``tokens`` window positions, ``real`` of
        which carried live work (the rest is shape padding)."""
        self._dispatch_total += 1
        self._dispatch_tokens += tokens
        self._dispatch_tokens_real += real
        if self.obs_registry is not None:
            self.obs_registry.inc("model_dispatches_total")
            self.obs_registry.inc("dispatch_tokens_total", by=tokens)
            self.obs_registry.inc("dispatch_tokens_real_total", by=real)

    def _count_round(self) -> None:
        self._round_total += 1
        if self.obs_registry is not None:
            self.obs_registry.inc("sched_rounds_total")

    def _round_metrics(self, admit_s: float, decode_s: float, n_decoding: int) -> None:
        """Publish the round's gauges and metrics.jsonl record — shared by
        the sequential and packed step bodies so both expose an identical
        telemetry surface."""
        batch_fill = n_decoding / self.max_batch
        stall_share = admit_s / max(admit_s + decode_s, 1e-9)
        self._admit_time_s += admit_s
        self._decode_time_s += decode_s
        pad_share = self._pad_tokens / max(self._pad_tokens + self._prefill_tokens, 1)
        hit_rate = self.prefix_cache.hit_rate if self.prefix_cache is not None else 0.0
        dispatches_per_round = self._dispatch_total / max(self._round_total, 1)
        tokens_per_dispatch = self._dispatch_tokens / max(self._dispatch_total, 1)
        token_utilization = self._dispatch_tokens_real / max(self._dispatch_tokens, 1)
        if self.obs_registry is not None:
            self.obs_registry.set_gauge("batch_fill", batch_fill)
            self.obs_registry.set_gauge("prefill_stall_share", stall_share)
            self.obs_registry.set_gauge("kv_pages_used", self.allocator.used_pages)
            self.obs_registry.set_gauge("kv_pages_free", self.allocator.free_pages)
            self.obs_registry.set_gauge("prefix_cache_hit_rate", hit_rate)
            self.obs_registry.set_gauge("prefill_pad_share", pad_share)
            self.obs_registry.set_gauge("kv_cache_bytes", self._kv_cache_bytes)
            self.obs_registry.set_gauge("kv_bytes_per_token", self._kv_bytes_per_token)
            self.obs_registry.set_gauge("dispatches_per_round", dispatches_per_round)
            self.obs_registry.set_gauge("tokens_per_dispatch", tokens_per_dispatch)
            self.obs_registry.set_gauge("packed_token_utilization", token_utilization)
            # by=0 materializes the counters at 0 so /metrics always exposes
            # them (and scrapers' delta logic sees the series from the start)
            self.obs_registry.inc("model_dispatches_total", by=0)
            self.obs_registry.inc("sched_rounds_total", by=0)
            self.obs_registry.inc("dispatch_tokens_total", by=0)
            self.obs_registry.inc("dispatch_tokens_real_total", by=0)
            self.obs_registry.inc("pages_migrated_total", by=0)
            self.obs_registry.inc("migration_bytes_total", by=0)
            self.obs_registry.inc("migration_failures_total", by=0)
            self.obs_registry.inc("migrated_inserts_total", by=0)
            self.obs_registry.inc("prefix_fetch_total", by=0)
            self.obs_registry.inc("prefix_fetch_failures_total", by=0)
            if self._spec != "off":
                self.obs_registry.set_gauge(
                    "spec_accept_rate",
                    self._spec_accepted / max(self._spec_drafted, 1),
                )
                self.obs_registry.set_gauge(
                    "spec_mode_model", 1.0 if self._spec == "model" else 0.0
                )
                self.obs_registry.inc("spec_drafted_total", by=0)
                self.obs_registry.inc("spec_accepted_total", by=0)
        record = None
        if self.metrics is not None:
            watcher = getattr(self.engine, "compile_watcher", None)
            record = {
                "serve/decode_step": self._step_count,
                "serve/queue_depth": len(self._pending),
                "serve/active_slots": self.active_slots,
                "serve/batch_fill": round(batch_fill, 4),
                "serve/prefill_stall_s": round(admit_s, 6),
                "serve/prefill_stall_share": round(stall_share, 4),
                "serve/kv_pages_used": self.allocator.used_pages,
                "serve/kv_pages_free": self.allocator.free_pages,
                "serve/prefix_cache_hit_rate": round(hit_rate, 4),
                "serve/prefill_pad_share": round(pad_share, 4),
                "serve/kv_cache_bytes": self._kv_cache_bytes,
                "serve/kv_bytes_per_token": round(self._kv_bytes_per_token, 4),
                "serve/dispatches_per_round": round(dispatches_per_round, 4),
                "serve/tokens_per_dispatch": round(tokens_per_dispatch, 4),
                "serve/packed_token_utilization": round(token_utilization, 4),
                "compile/steady_state_retraces": (
                    watcher.steady_state_retraces if watcher is not None else 0
                ),
            }
            if self._spec != "off":
                record["serve/spec_drafted_total"] = self._spec_drafted
                record["serve/spec_accepted_total"] = self._spec_accepted
                record["serve/spec_accept_rate"] = round(
                    self._spec_accepted / max(self._spec_drafted, 1), 4
                )
                record["serve/spec_mode_model"] = (
                    1 if self._spec == "model" else 0
                )
        self._adapter_gauges(record)
        if record is not None:
            self.metrics.log(record)

    # -- the packed single-dispatch round ---------------------------------------

    def _step_packed(self) -> List[Completion]:
        """Sarathi-style token-budget round in ONE model dispatch: every
        decoding row's window first (1 token plain, ``spec_k+1`` when any
        row drafted — mirroring the sequential round's branch structure),
        then oldest-first prefill tokens from as many slots as the budget
        admits, padded up to the smallest warmed bucket.  Each packed token
        routes through its own slot's block table (``row_map``), so the
        forward is exactly the sequential dispatches fused.  Sampling reuses
        the sequential path's calls verbatim — same ``(uid, token_index)``
        keys, same scalar-vs-stacked key structure — so the drain is
        token-identical to the unpacked scheduler."""
        finished: List[Completion] = []
        t_step = time.monotonic()
        self._expire_deadlines(finished)
        self._admit_pass(finished)
        admit_s = time.monotonic() - t_step
        if not any(s is not None for s in self._slots):
            return finished

        t_decode = time.monotonic()
        engine = self.engine
        B = self.max_batch
        null_pos = engine.cache_size
        spec_k = engine.spec_k

        drafts = self._draft_pass() if self._spec == "ngram" else {}
        spec_mode = bool(drafts)
        S = spec_k + 1 if spec_mode else 1

        ids: List[int] = []
        poss: List[int] = []
        rows: List[int] = []
        adap: List[int] = []
        slot_off: Dict[int, int] = {}  # decoding slot -> its window's offset

        # decode/verify windows first — the budget never throttles decode
        # (ctor floor check); k_eff=0 rows ride the full window in spec mode,
        # mirroring _verify_round
        draft_mat = np.zeros((B, max(spec_k, 1)), np.int32)
        k_eff = np.zeros(B, np.int32)
        uids = np.zeros(B, np.int32)
        starts = np.zeros(B, np.int32)
        temps = np.zeros(B, np.float32)
        top_ps = np.ones(B, np.float32)
        for slot_idx, slot in enumerate(self._slots):
            if slot is None or not slot.decoding:
                continue
            slot_off[slot_idx] = len(ids)
            d = drafts.get(slot_idx, [])
            window = [int(self._tokens[slot_idx])] + [int(t) for t in d]
            window += [0] * (S - len(window))
            ids.extend(window)
            poss.extend(int(self._positions[slot_idx]) + j for j in range(S))
            rows.extend([slot_idx] * S)
            adap.extend([slot.adapter_slot] * S)
            draft_mat[slot_idx, : len(d)] = d
            k_eff[slot_idx] = len(d)
            uids[slot_idx] = slot.request.uid
            starts[slot_idx] = len(slot.tokens)
            temps[slot_idx] = slot.request.temperature
            top_ps[slot_idx] = slot.request.top_p
        n_decoding = len(slot_off)

        # oldest-first prefill from MULTIPLE slots into the leftover budget;
        # write-then-attend makes several chunks of one prompt inside one
        # dispatch correct, so a slot may clear its whole backlog here
        budget_left = engine.token_budget - len(ids)
        prefill_spans: List[tuple] = []  # (slot_idx, start, n, packed offset)
        for _, slot_idx in sorted(
            (s.seq, i)
            for i, s in enumerate(self._slots)
            if s is not None and not s.decoding and not s.migrating
        ):
            if budget_left <= 0:
                break
            slot = self._slots[slot_idx]
            req = slot.request
            start = slot.prefill_progress
            n = min(len(req.prompt) - start, budget_left)
            if n <= 0:
                continue
            prefill_spans.append((slot_idx, start, n, len(ids)))
            ids.extend(int(t) for t in req.prompt[start : start + n])
            poss.extend(range(start, start + n))
            rows.extend([slot_idx] * n)
            adap.extend([slot.adapter_slot] * n)
            budget_left -= n

        n_real = len(ids)
        if n_real == 0:
            if any(s is not None and s.migrating for s in self._slots):
                time.sleep(0.001)  # only parked handoffs: don't hot-spin
            return finished  # nothing decodable and nothing left to prefill
        bucket = next(b for b in engine.packed_buckets() if b >= n_real)
        pad = bucket - n_real
        ids.extend([0] * pad)
        poss.extend([null_pos] * pad)  # clips into the null page
        rows.extend([B] * pad)  # the all-null pad row of _ptables
        adap.extend([0] * pad)
        self._pad_tokens += pad
        self._prefill_tokens += sum(n for _, _, n, _ in prefill_spans)

        with self.tracer.span(
            "decode_step",
            step=self._step_count,
            active_slots=n_decoding,
            spec_drafted=int(k_eff.sum()),
            packed_tokens=bucket,
        ):
            logits, self._pool = engine.step_paged(
                self._ensure_pool(),
                np.asarray(ids, np.int32)[None, :],
                np.asarray(poss, np.int32)[None, :],
                self._ptables,
                np.asarray(rows, np.int32),
                adapter_idx=np.asarray(adap, np.int32),
            )
            self._step_count += 1

            # decode rows first (before any slot armed this round joins the
            # decoding set): gather each window's logits from its packed
            # offsets and reuse the sequential sampling calls unchanged
            if n_decoding:
                flat = logits[0]
                if spec_mode:
                    win_idx = np.zeros(B * S, np.int32)
                    for slot_idx, off in slot_off.items():
                        win_idx[slot_idx * S : (slot_idx + 1) * S] = off + np.arange(S)
                    win = jnp.take(flat, jnp.asarray(win_idx), axis=0).reshape(
                        B, S, flat.shape[-1]
                    )
                    accept, alt = self._spec_sample(
                        win,
                        jnp.asarray(draft_mat),
                        self.key,
                        jnp.asarray(uids),
                        jnp.asarray(starts),
                        jnp.asarray(k_eff),
                        temperature=jnp.asarray(temps),
                        top_k=self.top_k,
                        top_p=jnp.asarray(top_ps),
                    )
                    self._commit_spec_walk(
                        np.asarray(accept), np.asarray(alt), draft_mat, k_eff,
                        set(slot_off), finished,
                    )
                else:
                    sample_idx = np.zeros(B, np.int32)
                    for slot_idx, off in slot_off.items():
                        sample_idx[slot_idx] = off
                    gathered = jnp.take(flat, jnp.asarray(sample_idx), axis=0)
                    masked = [
                        s if i in slot_off else None
                        for i, s in enumerate(self._slots)
                    ]
                    next_tokens = self._sample_rows(gathered, masked).tolist()
                    for slot_idx in sorted(slot_off):
                        slot = self._slots[slot_idx]
                        if slot is None:
                            continue  # retired mid-walk (cannot happen here)
                        tok = next_tokens[slot_idx]
                        slot.tokens.append(tok)
                        slot.pos += 1
                        self._tokens[slot_idx] = tok
                        self._positions[slot_idx] = slot.pos
                        self._emit_token(slot.request.uid, tok, len(slot.tokens) - 1)
                        self._finish_if_done(slot_idx, finished)

            # prefill completions: the same per-slot scalar sample call and
            # (uid, 0) key as the sequential chunk path, so first tokens
            # match exactly; the slot joins the decode set next round
            for slot_idx, start, n, off in prefill_spans:
                slot = self._slots[slot_idx]
                if slot is None:
                    continue
                req = slot.request
                slot.prefill_progress = start + n
                L = len(req.prompt)
                if slot.prefill_progress < L:
                    continue
                first = engine._sample(
                    logits[:, off + n - 1, :],
                    self._request_key(req, 0),
                    temperature=req.temperature,
                    top_k=self.top_k,
                    top_p=req.top_p,
                )
                first_id = int(np.asarray(first)[0])
                if self.prefix_cache is not None:
                    self.prefix_cache.register(list(req.prompt), slot.pages)
                slot.decoding = True
                slot.tokens = [first_id]
                slot.pos = L
                slot.t_first = time.monotonic()
                slot.span = self.tracer.start_span(
                    "decode", trace_id=self._trace_ids.get(req.uid), uid=req.uid
                )
                self._tokens[slot_idx] = first_id
                self._positions[slot_idx] = L
                self._tables[slot_idx, : len(slot.pages)] = slot.pages
                self._emit_token(req.uid, first_id, 0)
                self._finish_if_done(slot_idx, finished)
                self._maybe_migrate(slot_idx)
        decode_s = time.monotonic() - t_decode
        self._observe("decode_step_seconds", decode_s)
        # dispatch and round tick together at round end: a concurrent
        # /healthz read between the engine call and here must never see the
        # packed invariant (dispatches == rounds) transiently violated
        self._count_dispatch(bucket, n_real)
        self._count_round()
        self._round_metrics(admit_s, decode_s, n_decoding)
        return finished

    # -- retirement (page bookkeeping) ----------------------------------------

    def _retire(
        self, slot_idx: int, reason: str, detail: Optional[str] = None
    ) -> Completion:
        slot = self._slots[slot_idx]
        completion = super()._retire(slot_idx, reason, detail)
        if slot.pages:
            # one decref per page: fresh pages drop their alloc ref, shared
            # pages drop this request's lookup ref (the prefix cache's own
            # refs keep registered pages alive for the next hit)
            self.allocator.decref(slot.pages)
            slot.pages = []
        if slot.draft_pages:
            self.allocator.decref(slot.draft_pages)
            slot.draft_pages = []
        self._tables[slot_idx, :] = 0
        self._draft_tables[slot_idx, :] = 0
        self._ptables[slot_idx, :] = 0
        self._tokens[slot_idx] = 0
        self._positions[slot_idx] = 0
        return completion

    def paging_stats(self) -> Dict[str, Any]:
        """Point-in-time pool/prefix counters for /healthz and load tools."""
        stats: Dict[str, Any] = {
            "kv_pages_used": self.allocator.used_pages,
            "kv_pages_free": self.allocator.free_pages,
            "kv_pages_peak": self.allocator.peak_used,
            "kv_dtype": self.engine.kv_dtype,
            "kv_cache_bytes": self._kv_cache_bytes,
            "kv_bytes_per_token": round(self._kv_bytes_per_token, 4),
            "kv_used_bytes": self.allocator.used_bytes,
            "prefill_pad_share": round(
                self._pad_tokens / max(self._pad_tokens + self._prefill_tokens, 1), 4
            ),
        }
        if self.prefix_cache is not None:
            stats["prefix_cache"] = self.prefix_cache.stats()
        if self._spec != "off":
            stats["spec"] = self.spec_stats()
        stats["dispatch"] = self.dispatch_stats()
        stats["disagg"] = self.disagg_stats()
        return stats

    def disagg_stats(self) -> Dict[str, Any]:
        """Cumulative disaggregation counters — the /healthz ``disagg``
        block (role + migration/prefix-fetch economics) bench.py and the
        smoke drill read."""
        return {
            "role": self.role,
            "pages_migrated": self._pages_migrated,
            "migration_bytes": self._migration_bytes,
            "migration_failures": self._migration_failures,
            "migrated_inserts": self._migrated_inserts,
            "prefix_fetches": self._prefix_fetches,
            "prefix_fetch_failures": self._prefix_fetch_failures,
        }

    def dispatch_stats(self) -> Dict[str, Any]:
        """Cumulative dispatch-economics counters — the /healthz
        ``dispatch`` block bench.py reads per-level deltas from."""
        stats: Dict[str, Any] = {
            "mode": "packed" if self._packed else "sequential",
            "rounds": self._round_total,
            "model_dispatches": self._dispatch_total,
            "dispatches_per_round": round(
                self._dispatch_total / max(self._round_total, 1), 4
            ),
            "tokens_total": self._dispatch_tokens,
            "tokens_real": self._dispatch_tokens_real,
            "tokens_per_dispatch": round(
                self._dispatch_tokens / max(self._dispatch_total, 1), 4
            ),
            "packed_token_utilization": round(
                self._dispatch_tokens_real / max(self._dispatch_tokens, 1), 4
            ),
            "admit_time_s": round(self._admit_time_s, 6),
            "decode_time_s": round(self._decode_time_s, 6),
            "prefill_stall_share": round(
                self._admit_time_s
                / max(self._admit_time_s + self._decode_time_s, 1e-9),
                4,
            ),
        }
        if self._packed:
            stats["token_budget"] = self.engine.token_budget
            stats["buckets"] = list(self.engine.packed_buckets())
        return stats

    def spec_stats(self) -> Dict[str, Any]:
        """Cumulative speculative-decoding counters — the /healthz ``spec``
        block and the source bench.py reads effective accept rates from."""
        return {
            "mode": self._spec,
            "k": self.engine.spec_k,
            "drafted": self._spec_drafted,
            "accepted": self._spec_accepted,
            "accept_rate": round(
                self._spec_accepted / max(self._spec_drafted, 1), 4
            ),
        }
