#!/usr/bin/env python
"""Render fleet health, SLO/error-budget status, and the incident timeline.

Reads the ``fleet_series.jsonl`` the FleetCollector persists (supervisor
``--fleet-persist``, or ``python -m relora_tpu.obs.fleet --persist``) and
rebuilds the in-memory SeriesStore from it, so the report works on a live
fleet's file as well as post-mortem on a copied one.  Optionally joins
additional metrics.jsonl streams (e.g. a trainer run dir) with ``--join``.

Sections:

1. fleet health — per source: last ``up`` sample, staleness, queue depth;
2. replica comparison — p95 TTFT/TPOT, error rate, token throughput,
   tokens per model dispatch, and prefill stall share per source over the
   comparison window (spot the slow, erroring, or under-packed replica);
3. SLO / error budget — burn status per objective from a fresh SLOEngine
   pass over the rebuilt store (``--slo-config`` mirrors the collector's);
4. autoscale — live replica count (current and min/max over the window)
   plus every autoscaler decision: scale-ups with the burn signals that
   drove them, scale-downs, and holds (cooldown, warming, partial burn);
5. timeline — health flips, supervisor lifecycle, autoscale actions, SLO
   burn alerts and anomalies, merged and time-ordered.

    python tools/fleet_report.py /tmp/fleet/fleet_series.jsonl
    python tools/fleet_report.py fleet.jsonl --join train=ckpts/run/metrics.jsonl
    python tools/fleet_report.py fleet.jsonl --slo-config slo.json --window-s 300
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time
from pathlib import Path
from typing import Any, Dict, List, Optional

# runnable from any cwd without an installed package
sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from relora_tpu.obs.fleet import SeriesStore, load_series_jsonl  # noqa: E402
from relora_tpu.obs.slo import SLOEngine  # noqa: E402

# replica-comparison columns: (header, series name, unit scale, format)
_COMPARE_COLUMNS = (
    ("ttft_p95_ms", "relora_serve_ttft_seconds_p95", 1e3, "{:.1f}"),
    ("tpot_p95_ms", "relora_serve_tpot_seconds_p95", 1e3, "{:.2f}"),
    ("err_rate", "error_rate", 1.0, "{:.3f}"),
    ("tok_per_s", "relora_serve_tokens_generated_total_per_s", 1.0, "{:.1f}"),
    ("spec_acc", "spec_accept_rate", 1.0, "{:.3f}"),
    ("adpt_churn", "adapter_churn", 1.0, "{:.2f}"),
    ("adpt_hit", "relora_serve_adapter_hit_rate", 1.0, "{:.3f}"),
    ("tok_disp", "tokens_per_dispatch", 1.0, "{:.1f}"),
    ("stall", "relora_serve_prefill_stall_share", 1.0, "{:.3f}"),
)

_TIMELINE_KINDS = (
    "health_flip",
    "group_health_flip",
    "slo_burn_alert",
    "series_anomaly",
    "adapter_thrash",
    "migration_failed",
)


def _mean(vals: List[float]) -> Optional[float]:
    return sum(vals) / len(vals) if vals else None


def fleet_health(store: SeriesStore, now: float, out=sys.stdout) -> None:
    out.write("== fleet health ==\n")
    sources = store.sources()
    if not sources:
        out.write("no sources in store\n")
        return
    out.write(f"{'source':<12} {'up':>4} {'age_s':>7} {'queue':>6} {'slots':>6}\n")
    for src in sources:
        up = store.latest(src, "up")
        if up is None:
            # jsonl-joined sources (trainer) have no scraped up gauge; show
            # them by their freshest sample instead of skipping the row
            newest = max(
                (store.latest(src, name) for name in store.series_names(src)),
                key=lambda s: s[0] if s else 0.0,
                default=None,
            )
            age = f"{now - newest[0]:.1f}" if newest else "?"
            out.write(f"{src:<12} {'-':>4} {age:>7} {'-':>6} {'-':>6}\n")
            continue
        t, v = up
        queue = store.latest(src, "healthz_queue_depth")
        slots = store.latest(src, "healthz_active_slots")
        out.write(
            f"{src:<12} {v:>4.0f} {now - t:>7.1f} "
            f"{'-' if queue is None else f'{queue[1]:.0f}':>6} "
            f"{'-' if slots is None else f'{slots[1]:.0f}':>6}\n"
        )


def replica_comparison(
    store: SeriesStore, now: float, window_s: float, out=sys.stdout
) -> None:
    out.write(f"\n== replica comparison (last {window_s:.0f}s, mean) ==\n")
    rows = []
    for src in store.sources():
        cells = {}
        for header, series, scale, fmt in _COMPARE_COLUMNS:
            m = _mean(store.window_values(src, series, window_s, now=now))
            cells[header] = "-" if m is None else fmt.format(m * scale)
            if header == "spec_acc" and m is not None:
                # an 0.2 accept rate is healthy for ngram and a collapse for
                # a model draft — the mode suffix keeps the column comparable
                mode = _mean(
                    store.window_values(src, "spec_mode_model", window_s, now=now)
                )
                if mode is None:
                    mode = _mean(
                        store.window_values(
                            src, "relora_serve_spec_mode_model", window_s, now=now
                        )
                    )
                if mode is not None:
                    cells[header] += ":mdl" if mode >= 0.5 else ":ngm"
        if any(v != "-" for v in cells.values()):
            rows.append((src, cells))
    if not rows:
        out.write("no serving series in window\n")
        return
    headers = [h for h, _, _, _ in _COMPARE_COLUMNS]
    out.write(f"{'source':<12} " + " ".join(f"{h:>12}" for h in headers) + "\n")
    for src, cells in rows:
        out.write(f"{src:<12} " + " ".join(f"{cells[h]:>12}" for h in headers) + "\n")


def slo_status(
    store: SeriesStore, engine: SLOEngine, now: float, out=sys.stdout
) -> None:
    out.write("\n== SLO / error budget ==\n")
    # snapshot the collector's persisted transitions BEFORE evaluating: the
    # fresh pass below records its own events into the (sink-less, in-memory)
    # store, which must not masquerade as run history
    alerts = store.events(kinds=("slo_burn_alert",))
    engine.evaluate(store, now=now)
    status = engine.status()
    if not status["objectives"]:
        out.write("no objectives evaluated (series missing from store)\n")
    else:
        out.write(
            f"{'slo':<14} {'source':<12} {'objective':>9} {'max_burn':>9} {'state':>7}\n"
        )
        for st in status["objectives"]:
            out.write(
                f"{st['slo']:<14} {st['source']:<12} {st['objective']:>9} "
                f"{st['max_burn']:>9} {st['state']:>7}\n"
            )
    # alert history as persisted by the collector — the authoritative record
    # of what actually fired during the run (the pass above only sees burn
    # still visible inside the rebuilt store's windows)
    if alerts:
        out.write(f"\nalert history ({len(alerts)} transitions):\n")
        for a in alerts:
            out.write(
                f"  {a.get('_time', 0):.2f} {a.get('state'):>5} "
                f"{a.get('slo')} source={a.get('_source')} "
                f"burn_long={a.get('burn_long')} burn_short={a.get('burn_short')}\n"
            )


def autoscale_section(
    store: SeriesStore, now: float, window_s: float, out=sys.stdout
) -> None:
    """Replica count plus the autoscaler's decision record.  Quiet (prints
    nothing) on fleets that never ran an autoscaler — the section only
    exists when there is an ``autoscaler`` source or ``autoscale_*`` events
    to show."""
    live = store.latest("autoscaler", "replicas_live")
    counts = store.window_values("autoscaler", "replicas_live", window_s, now=now)
    decisions = [
        e for e in store.events() if str(e.get("_event", "")).startswith("autoscale_")
    ]
    if live is None and not decisions:
        return
    out.write("\n== autoscale ==\n")
    if live is not None:
        lo = min(counts) if counts else live[1]
        hi = max(counts) if counts else live[1]
        out.write(
            f"replicas: {live[1]:.0f} live (age {now - live[0]:.1f}s; "
            f"window min {lo:.0f} / max {hi:.0f})\n"
        )
    ups = sum(1 for e in decisions if e.get("action") == "up")
    downs = sum(1 for e in decisions if e.get("action") == "down")
    out.write(f"decisions: {len(decisions)} recorded ({ups} up, {downs} down)\n")
    for e in decisions:
        detail = {
            k: v
            for k, v in e.items()
            if k not in ("_event", "_source", "_time", "action", "reason")
        }
        out.write(
            f"  {e.get('_time', 0):.2f} {str(e.get('_event')):<24} "
            f"{str(e.get('action', '-')):<5} {str(e.get('reason', '-')):<28}"
            + " ".join(f"{k}={v}" for k, v in sorted(detail.items()))
            + "\n"
        )


def timeline(store: SeriesStore, last: int, out=sys.stdout) -> None:
    events = [
        e
        for e in store.events()
        if e.get("_event", "").startswith(("supervisor_", "deploy_", "autoscale_"))
        or e.get("_event") in _TIMELINE_KINDS
    ]
    events.sort(key=lambda e: e.get("_time", 0.0))
    out.write(f"\n== timeline (last {last} of {len(events)} events) ==\n")
    for e in events[-last:]:
        detail = {
            k: v for k, v in e.items() if k not in ("_event", "_source", "_time")
        }
        out.write(
            f"  {e.get('_time', 0):.2f} {e.get('_event'):<22} "
            f"{str(e.get('_source')):<12} "
            + " ".join(f"{k}={v}" for k, v in detail.items())
            + "\n"
        )


def bench_freshness(bench_dir: str, out=sys.stdout) -> None:
    """Loudly flag stale/watchdog bench rounds (the same classification as
    tools/bench_gate.py): a trajectory whose newest ``BENCH_r*.json`` rounds
    are watchdog zeros (``parsed.value <= 0``), stale replays
    (``detail.stale``) or off-TPU runs carries NO fresh performance signal,
    and a fleet report that silently tabulates next to it invites reading
    dead numbers as live ones."""
    rounds = []  # (n, kind, mtime)
    for path in glob.glob(os.path.join(bench_dir, "BENCH_r[0-9]*.json")):
        try:
            with open(path) as fh:
                doc = json.load(fh)
        except (OSError, json.JSONDecodeError):
            continue
        parsed = doc.get("parsed") or {}
        detail = parsed.get("detail") if isinstance(parsed, dict) else None
        detail = detail if isinstance(detail, dict) else {}
        value = parsed.get("value") if isinstance(parsed, dict) else None
        if detail.get("stale"):
            kind = "stale_replay"
        elif not isinstance(value, (int, float)) or value <= 0:
            kind = "watchdog"
        elif "cpu" in str(detail.get("device", "")).lower():
            kind = "off_tpu"
        else:
            kind = "real"
        try:
            mtime = os.path.getmtime(path)
        except OSError:
            mtime = 0.0
        rounds.append((int(doc.get("n", 0)), kind, mtime))
    if not rounds:
        return
    rounds.sort()
    newest_n, newest_kind, _ = rounds[-1]
    real = [r for r in rounds if r[1] == "real"]
    if newest_kind == "real":
        out.write(
            f"bench trajectory: round r{newest_n} is a real on-TPU "
            f"measurement ({len(real)}/{len(rounds)} rounds real)\n\n"
        )
        return
    counts: Dict[str, int] = {}
    for _, kind, _ in rounds:
        counts[kind] = counts.get(kind, 0) + 1
    breakdown = ", ".join(f"{v} {k}" for k, v in sorted(counts.items()))
    out.write("!" * 72 + "\n")
    out.write(
        f"!!! BENCH STALENESS: newest round r{newest_n} is {newest_kind.upper()}, "
        f"not a real on-TPU measurement\n"
    )
    out.write(f"!!! rounds in {bench_dir}: {breakdown}\n")
    if real:
        real_n, _, real_mtime = real[-1]
        age = ""
        if real_mtime > 0:
            age = f", recorded {(time.time() - real_mtime) / 86400.0:.1f} days ago"
        out.write(
            f"!!! newest REAL on-TPU round: r{real_n} "
            f"({newest_n - real_n} rounds behind{age})\n"
        )
    else:
        out.write("!!! NO real on-TPU round exists in this trajectory\n")
    out.write(
        "!!! perf numbers below reflect serving telemetry only; do not read\n"
        "!!! the bench trajectory as fresh (tools/bench_gate.py --check)\n"
    )
    out.write("!" * 72 + "\n\n")


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="fleet_series.jsonl written by the FleetCollector")
    ap.add_argument(
        "--join", action="append", default=[], metavar="NAME=PATH",
        help="also ingest a metrics.jsonl under source NAME (e.g. train=...)",
    )
    ap.add_argument("--slo-config", help="JSON SLO config (default: standing objectives)")
    ap.add_argument(
        "--window-s", type=float, default=300.0,
        help="comparison window in seconds (default 300)",
    )
    ap.add_argument(
        "--events", type=int, default=40,
        help="how many trailing timeline events to print (default 40)",
    )
    ap.add_argument(
        "--json", action="store_true",
        help="emit machine-readable JSON instead of the text report",
    )
    ap.add_argument(
        "--bench-dir",
        default=str(Path(__file__).resolve().parents[1]),
        help="where BENCH_r*.json rounds live (default: repo root); stale or "
        "watchdog trajectories get a loud banner ('' disables the check)",
    )
    args = ap.parse_args(argv)

    store = SeriesStore(max_points=100_000, max_events=100_000)
    n = load_series_jsonl(store, args.path)
    for spec in args.join:
        name, _, path = spec.partition("=")
        if not path:
            ap.error(f"--join expects NAME=PATH, got {spec!r}")
        n += load_series_jsonl(store, path, source=name)
    if n == 0:
        print(f"no records loaded from {args.path}")
        return 1

    # "now" is the newest stamp in the file, not wall clock: the report must
    # give identical answers on a file copied off a dead fleet hours ago
    stamps = [e.get("_time", 0.0) for e in store.events()]
    for src in store.sources():
        for name in store.series_names(src):
            latest = store.latest(src, name)
            if latest is not None:
                stamps.append(latest[0])
    now = max(stamps) if stamps else time.time()

    engine = SLOEngine.from_config(args.slo_config)
    if args.json:
        history = store.events(kinds=("slo_burn_alert",))
        engine.evaluate(store, now=now)
        payload = {
            "loaded_records": n,
            "now": now,
            "sources": store.sources(),
            "slo": engine.status(),
            "alert_history": history,
        }
        json.dump(payload, sys.stdout, indent=2)
        sys.stdout.write("\n")
        return 0

    print(f"fleet report: {args.path}  ({n} records, now={now:.2f})\n")
    if args.bench_dir:
        bench_freshness(args.bench_dir)
    fleet_health(store, now)
    replica_comparison(store, now, args.window_s)
    slo_status(store, engine, now)
    autoscale_section(store, now, args.window_s)
    timeline(store, args.events)
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:
        import os

        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        sys.exit(0)
