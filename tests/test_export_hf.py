"""Smoke test for tools/export_hf.py (ISSUE satellite: the tool previously
had no coverage): tiny config, CPU, both dtypes, and the merged-LoRA path —
the exported state dict must be full-rank (no LoRA keys) and load back as
plain tensors."""

import json
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from relora_tpu.config.model import ModelConfig
from relora_tpu.core.relora import LoraSpec
from relora_tpu.models.llama import LlamaForCausalLM
from relora_tpu.models.params_util import init_params
from relora_tpu.train.checkpoint import save_checkpoint, wait_for_save

torch = pytest.importorskip("torch")

sys.path.insert(0, ".")
from tools.export_hf import main as export_main  # noqa: E402

TINY = ModelConfig(
    family="llama",
    vocab_size=256,
    hidden_size=64,
    intermediate_size=160,
    num_hidden_layers=2,
    num_attention_heads=4,
    max_sequence_length=64,
)


def _save_tiny_checkpoint(tmp_path, lora=None):
    model = LlamaForCausalLM(TINY, lora=lora, dtype=jnp.float32)
    params = init_params(model, jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    path = save_checkpoint(
        str(tmp_path / "ckpts"), 1, {"params": params}, {"update_step": 1}, lora_spec=lora
    )
    wait_for_save()
    cfg_path = tmp_path / "tiny_config.json"
    cfg_path.write_text(
        json.dumps(
            {
                "vocab_size": TINY.vocab_size,
                "hidden_size": TINY.hidden_size,
                "intermediate_size": TINY.intermediate_size,
                "num_hidden_layers": TINY.num_hidden_layers,
                "num_attention_heads": TINY.num_attention_heads,
                "max_sequence_length": TINY.max_sequence_length,
            }
        )
    )
    return path, str(cfg_path)


@pytest.mark.parametrize("dtype", ["f32", "bf16"])
def test_export_merged_lora_checkpoint(tmp_path, dtype):
    spec = LoraSpec(r=4, alpha=32, dropout=0.0)
    ckpt, cfg_path = _save_tiny_checkpoint(tmp_path, lora=spec)
    out = tmp_path / "export"
    export_main(
        [
            "--checkpoint", ckpt,
            "--model_config", cfg_path,
            "--out", str(out),
            "--dtype", dtype,
        ]
    )
    sd = torch.load(out / "pytorch_model.bin", weights_only=True)
    assert not any("lora" in k for k in sd)
    expected = torch.bfloat16 if dtype == "bf16" else torch.float32
    assert all(v.dtype == expected for v in sd.values())
    assert sd["model.embed_tokens.weight"].shape == (TINY.vocab_size, TINY.hidden_size)
    assert sd["model.layers.0.self_attn.q_proj.weight"].shape == (64, 64)
    hf_cfg = json.loads((out / "config.json").read_text())
    assert hf_cfg["torch_dtype"] == ("bfloat16" if dtype == "bf16" else "float32")
    assert hf_cfg["num_hidden_layers"] == TINY.num_hidden_layers


def test_export_full_rank_checkpoint(tmp_path):
    ckpt, cfg_path = _save_tiny_checkpoint(tmp_path, lora=None)
    out = tmp_path / "export"
    export_main(
        ["--checkpoint", ckpt, "--model_config", cfg_path, "--out", str(out)]
    )
    sd = torch.load(out / "pytorch_model.bin", weights_only=True)
    assert sd["lm_head.weight"].shape == (TINY.vocab_size, TINY.hidden_size)


def test_restore_serving_params_merged_and_plain(tmp_path):
    """Satellite: serve-side restore works for LoRA, full-rank, AND
    already-merged checkpoints that kept their relora_config.json sidecar."""
    from relora_tpu.core.relora import merged_params
    from relora_tpu.train.checkpoint import restore_serving_params

    spec = LoraSpec(r=4, alpha=32, dropout=0.0)
    ckpt, _ = _save_tiny_checkpoint(tmp_path, lora=spec)
    serving = restore_serving_params(ckpt)
    flat = jax.tree_util.tree_flatten_with_path(serving)[0]
    assert not any("lora" in jax.tree_util.keystr(p) for p, _ in flat)

    # already-merged tree saved WITH the sidecar: restore must pass through
    # (this used to require lora_a/lora_b leaves and KeyError without them)
    merged_dir = tmp_path / "merged"
    path2 = save_checkpoint(
        str(merged_dir), 2, {"params": serving}, {"update_step": 2}, lora_spec=spec
    )
    wait_for_save()
    again = restore_serving_params(path2)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        serving,
        again,
    )

    # full-rank checkpoint, no sidecar
    ckpt3, _ = _save_tiny_checkpoint(tmp_path / "fr", lora=None)
    plain = restore_serving_params(ckpt3)
    assert "embed_tokens" in plain
