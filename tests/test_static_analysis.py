"""Tests for relora_tpu.analysis — the RTL footgun linter.

Per rule: a bad fixture that must fire and the corrected idiom that must
stay quiet.  Plus suppression (# noqa), baseline round-trip, and the repo
self-check (the tree lints clean against the checked-in baseline, with no
stale entries).

Pure stdlib — no jax import, no devices; these run anywhere, fast.
"""

import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

from relora_tpu.analysis import (
    RULE_CATALOG,
    BaselineEntry,
    Finding,
    format_baseline_entry,
    lint_paths,
    lint_text,
    load_baseline,
)

pytestmark = pytest.mark.lint

REPO_ROOT = Path(__file__).resolve().parents[1]


def codes(src: str, *, hot: bool = False) -> list:
    return [f.code for f in lint_text(textwrap.dedent(src), force_hot=hot)]


# ---------------------------------------------------------------------------
# RTL1xx retrace hazards


def test_rtl101_branch_on_tracer_fires():
    src = """
        import jax

        @jax.jit
        def f(x):
            if x > 0:
                return x
            return -x
    """
    assert "RTL101" in codes(src)


def test_rtl101_clean_where_idiom():
    src = """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            return jnp.where(x > 0, x, -x)
    """
    assert codes(src) == []


def test_rtl101_static_shape_checks_ok():
    # shape/ndim/isinstance/None-checks on traced args are host-static
    src = """
        import jax

        @jax.jit
        def f(x, mask=None):
            if x.ndim == 2:
                x = x[None]
            if mask is None:
                return x
            if isinstance(mask, tuple):
                mask = mask[0]
            return x * mask
    """
    assert codes(src) == []


def test_rtl102_unhashable_static_arg_fires():
    src = """
        import jax

        def f(x, sizes):
            return x

        g = jax.jit(f, static_argnums=(1,))

        def caller(x):
            return g(x, [1, 2, 3])
    """
    assert "RTL102" in codes(src)


def test_rtl102_tuple_static_arg_ok():
    src = """
        import jax

        def f(x, sizes):
            return x

        g = jax.jit(f, static_argnums=(1,))

        def caller(x):
            return g(x, (1, 2, 3))
    """
    assert codes(src) == []


def test_rtl103_jit_inside_loop_fires():
    src = """
        import jax

        def run(fns, x):
            for fn in fns:
                x = jax.jit(fn)(x)
            return x
    """
    assert "RTL103" in codes(src)


def test_rtl103_jit_hoisted_ok():
    src = """
        import jax

        def run(fn, xs):
            fast = jax.jit(fn)
            for x in xs:
                x = fast(x)
            return x
    """
    assert codes(src) == []


def test_rtl104_fstring_on_tracer_fires():
    src = """
        import jax

        @jax.jit
        def f(x):
            print(f"x is {x}")
            return x
    """
    assert "RTL104" in codes(src)


def test_rtl104_debug_print_ok():
    src = """
        import jax

        @jax.jit
        def f(x):
            jax.debug.print("x is {}", x)
            return x
    """
    assert codes(src) == []


# ---------------------------------------------------------------------------
# RTL2xx host syncs (hot regions; force_hot marks the fixture hot)


def test_rtl201_item_fires_hot_only():
    src = """
        def loop(xs):
            total = 0.0
            for x in xs:
                total += x.mean().item()
            return total
    """
    assert "RTL201" in codes(src, hot=True)
    assert codes(src, hot=False) == []  # same code cold: no finding


def test_rtl202_float_on_computed_fires():
    src = """
        def loop(metrics):
            return float(metrics["loss"])
    """
    assert "RTL202" in codes(src, hot=True)


def test_rtl202_static_scalars_ok():
    src = """
        import time

        def loop(batch, dt):
            n = int(batch.size)
            t = float(time.monotonic())
            return n, t, float(dt)
    """
    assert codes(src, hot=True) == []


def test_rtl203_block_until_ready_fires():
    src = """
        import jax

        def loop(state):
            jax.block_until_ready(state.params)
    """
    assert "RTL203" in codes(src, hot=True)


def test_rtl204_np_asarray_fires_jnp_ok():
    bad = """
        import numpy as np

        def loop(x):
            return np.asarray(x)
    """
    good = """
        import jax.numpy as jnp

        def loop(x):
            return jnp.asarray(x)  # host->device: fine
    """
    assert "RTL204" in codes(bad, hot=True)
    assert codes(good, hot=True) == []


def test_hot_marker_comment_activates_rules():
    src = """
        # relora-lint: hot-path

        def loop(x):
            return x.item()
    """
    assert "RTL201" in codes(src)


# ---------------------------------------------------------------------------
# RTL3xx donation


def test_rtl301_read_after_donation_fires():
    src = """
        import jax

        def make(step):
            step_fn = jax.jit(step, donate_argnums=(0,))

            def run(state, batch):
                new_state, metrics = step_fn(state, batch)
                return new_state, state.step  # donated buffer read
            return run
    """
    assert "RTL301" in codes(src)


def test_rtl301_rebind_ok():
    src = """
        import jax

        def make(step):
            step_fn = jax.jit(step, donate_argnums=(0,))

            def run(state, batch):
                state, metrics = step_fn(state, batch)
                return state, state.step
            return run
    """
    assert codes(src) == []


def test_rtl301_loop_reuse_fires():
    # donated on iteration 1, passed again on iteration 2
    src = """
        import jax

        def make(step):
            step_fn = jax.jit(step, donate_argnums=(0,))

            def run(state, batches):
                for b in batches:
                    new_state = step_fn(state, b)
                return new_state
            return run
    """
    assert "RTL301" in codes(src)


def test_rtl301_donation_is_function_scoped():
    # two sibling functions binding the same name: one donates, one doesn't.
    # the non-donating one must not inherit the other's donate_argnums.
    src = """
        import jax

        def donating(step, state, batch):
            step = jax.jit(step, donate_argnums=0)
            new_state, m = step(state, batch)
            return new_state

        def plain(step, state, batch):
            step = jax.jit(step)
            new_state, m = step(state, batch)
            return new_state, state.step  # fine: nothing was donated
    """
    assert codes(src) == []


def test_rtl302_missing_donation_fires():
    src = """
        import jax

        def step(state, batch):
            return state

        step_fn = jax.jit(step)
    """
    assert "RTL302" in codes(src)


def test_rtl302_decorated_def_fires():
    src = """
        import jax

        @jax.jit
        def train_step(params, opt_state, batch):
            return params, opt_state
    """
    assert "RTL302" in codes(src)


def test_rtl302_with_donation_ok():
    src = """
        import functools
        import jax

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def train_step(params, opt_state, batch):
            return params, opt_state

        def step(state, batch):
            return state

        step_fn = jax.jit(step, donate_argnums=(0,))
    """
    assert codes(src) == []


# ---------------------------------------------------------------------------
# RTL4xx RNG hygiene


def test_rtl401_key_reuse_fires():
    src = """
        import jax

        def init(key):
            a = jax.random.normal(key, (4,))
            b = jax.random.normal(key, (4,))
            return a, b
    """
    assert "RTL401" in codes(src)


def test_rtl401_split_ok():
    src = """
        import jax

        def init(key):
            key, sub = jax.random.split(key)
            a = jax.random.normal(sub, (4,))
            key, sub = jax.random.split(key)
            b = jax.random.normal(sub, (4,))
            return a, b
    """
    assert codes(src) == []


def test_rtl401_exclusive_branches_ok():
    # one consumption per runtime path is fine
    src = """
        import jax

        def draw(key, uniform):
            if uniform:
                return jax.random.uniform(key, (4,))
            else:
                return jax.random.normal(key, (4,))
    """
    assert codes(src) == []


def test_rtl402_time_seed_fires():
    src = """
        import time
        import jax

        def make_key():
            return jax.random.PRNGKey(int(time.time()))
    """
    assert "RTL402" in codes(src)


def test_rtl402_config_seed_ok():
    src = """
        import jax

        def make_key(cfg):
            return jax.random.PRNGKey(cfg.seed)
    """
    assert codes(src) == []


# ---------------------------------------------------------------------------
# RTL5xx pytree / sharding


def test_rtl501_inplace_params_mutation_fires():
    src = """
        def graft(params, new_head):
            params["lm_head"] = new_head
            return params
    """
    assert "RTL501" in codes(src)


def test_rtl501_dict_mutator_fires():
    src = """
        def prune(params, name):
            params.pop(name)
            return params
    """
    assert "RTL501" in codes(src)


def test_rtl501_rebuild_or_rebind_ok():
    src = """
        def graft(params, new_head):
            return {**params, "lm_head": new_head}

        def prune(params, name):
            params = dict(params)
            params.pop(name)
            return params
    """
    assert codes(src) == []


def test_rtl502_specless_shard_map_fires():
    src = """
        from jax.experimental.shard_map import shard_map

        def wrap(f, mesh):
            return shard_map(f, mesh)
    """
    assert "RTL502" in codes(src)


def test_rtl502_explicit_specs_ok():
    src = """
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        def wrap(f, mesh):
            return shard_map(f, mesh, in_specs=(P("dp"),), out_specs=P("dp"))
    """
    assert codes(src) == []


# ---------------------------------------------------------------------------
# call graph: thread-root inference, resolution, one-level propagation


def _index(src: str):
    from relora_tpu.analysis import get_module_index
    from relora_tpu.analysis.core import FileContext

    return get_module_index(FileContext("m.py", "m.py", textwrap.dedent(src)))


def test_module_index_infers_all_root_kinds():
    src = """
        import asyncio
        import signal
        import threading

        def on_term(signum, frame):
            pass

        signal.signal(signal.SIGTERM, on_term)

        class Server:
            def __init__(self, loop):
                self._thread = threading.Thread(target=self._loop, daemon=True)
                loop.run_in_executor(None, self._scrape)

            def _loop(self):
                pass

            def _scrape(self):
                pass

            async def handle(self, request):
                pass
    """
    mi = _index(src)
    assert mi.thread_roots["Server._loop"] == "thread"
    assert mi.thread_roots["Server._scrape"] == "executor"
    assert mi.thread_roots["on_term"] == "signal"
    assert mi.thread_roots["Server.handle"] == "async"


def test_module_index_resolves_self_bare_and_qualified():
    src = """
        def helper():
            pass

        class C:
            def outer(self):
                def inner():
                    pass
                inner()
                helper()
                self.meth()

            def meth(self):
                pass
    """
    mi = _index(src)
    assert mi.resolve_local("inner", "C.outer") == "C.outer.inner"
    assert mi.resolve_local("helper", "C.outer") == "helper"
    assert mi.resolve_local("self.meth", "C.outer") == "C.meth"
    assert mi.resolve_local("C.meth", "") == "C.meth"
    assert mi.resolve_local("self.nope", "C.outer") is None


def test_module_index_reachability_is_transitive():
    src = """
        class C:
            def a(self):
                self.b()

            def b(self):
                self.c()

            def c(self):
                pass

            def d(self):
                pass
    """
    mi = _index(src)
    assert mi.reachable(["C.a"]) == {"C.a", "C.b", "C.c"}
    assert "C.d" not in mi.reachable(["C.a"])


def test_rtl2xx_propagates_to_unconditional_helper():
    # `_log` is not in the hot-prefix table, but it is called
    # unconditionally from Trainer.fit — the .item() inside it runs every
    # step and must fire
    src = """
        class Trainer:
            def fit(self, batches):
                for batch in batches:
                    loss = self.state.loss
                    self._log(loss)

            def _log(self, loss):
                return loss.item()
    """
    found = lint_text(
        textwrap.dedent(src), relpath="relora_tpu/train/trainer.py"
    )
    assert "RTL201" in [f.code for f in found]


def test_rtl2xx_no_propagation_through_conditional_call():
    # the sanctioned cadence-gating idiom: a bulk-pull helper behind an
    # `if len(pending) >= log_every` gate (possibly via a nested closure)
    # must NOT become hot
    src = """
        class Trainer:
            def fit(self, batches, log_every=32):
                pending = []

                def flush():
                    self._pull(pending)

                for batch in batches:
                    pending.append(batch)
                    if len(pending) >= log_every:
                        flush()

            def _pull(self, pending):
                return [p.item() for p in pending]
    """
    found = lint_text(
        textwrap.dedent(src), relpath="relora_tpu/train/trainer.py"
    )
    assert [f.code for f in found] == []


# ---------------------------------------------------------------------------
# RTL6xx concurrency discipline


def test_rtl601_cross_thread_write_fires():
    src = """
        import threading

        class Worker:
            def __init__(self):
                self.count = 0
                self._thread = threading.Thread(target=self._loop, daemon=True)

            def _loop(self):
                while True:
                    self.count = self.count + 1

            def reset(self):
                self.count = 0
    """
    assert "RTL601" in codes(src)


def test_rtl601_common_lock_ok():
    src = """
        import threading

        class Worker:
            def __init__(self):
                self.count = 0
                self._lock = threading.Lock()
                self._thread = threading.Thread(target=self._loop, daemon=True)

            def _loop(self):
                while True:
                    with self._lock:
                        self.count = self.count + 1

            def reset(self):
                with self._lock:
                    self.count = 0
    """
    assert "RTL601" not in codes(src)


def test_rtl601_single_writer_ok():
    # writes confined to the spawned thread (init-time writes are exempt:
    # they happen before the thread exists)
    src = """
        import threading

        class Worker:
            def __init__(self):
                self.count = 0
                self._thread = threading.Thread(target=self._loop, daemon=True)

            def _loop(self):
                while True:
                    self.count = self.count + 1

            def snapshot(self):
                return self.count
    """
    assert "RTL601" not in codes(src)


def test_rtl602_time_sleep_in_async_fires():
    src = """
        import time

        class Handler:
            async def handle(self, request):
                time.sleep(0.1)
                return request
    """
    assert "RTL602" in codes(src)


def test_rtl602_queue_get_without_timeout_fires():
    src = """
        import queue

        class Handler:
            def __init__(self):
                self._q = queue.Queue()

            async def handle(self):
                return self._q.get()
    """
    assert "RTL602" in codes(src)


def test_rtl602_engine_step_in_async_fires():
    src = """
        class Handler:
            async def handle(self, tokens):
                return self.engine.decode(tokens)
    """
    assert "RTL602" in codes(src)


def test_rtl602_blessed_idioms_ok():
    # await asyncio.sleep, a timeout-bounded get, and passing (not calling)
    # a blocking callable into run_in_executor are all fine
    src = """
        import asyncio
        import queue

        class Handler:
            def __init__(self):
                self._q = queue.Queue()

            async def handle(self, loop):
                await asyncio.sleep(0.1)
                item = self._q.get(timeout=1.0)
                return await loop.run_in_executor(None, self._q.get)
    """
    assert "RTL602" not in codes(src)


def test_rtl603_asyncio_event_set_from_thread_fires():
    src = """
        import asyncio
        import threading

        class Shutdown:
            def __init__(self):
                self._done = asyncio.Event()
                self._thread = threading.Thread(target=self._work)

            def _work(self):
                self._done.set()
    """
    assert "RTL603" in codes(src)


def test_rtl603_call_soon_threadsafe_ok():
    src = """
        import asyncio
        import threading

        class Shutdown:
            def __init__(self, loop):
                self._done = asyncio.Event()
                self._loop = loop
                self._thread = threading.Thread(target=self._work)

            def _work(self):
                self._loop.call_soon_threadsafe(self._done.set)
    """
    assert "RTL603" not in codes(src)


def test_rtl604_opposite_lock_order_fires():
    src = """
        import threading

        class Drain:
            def __init__(self):
                self._scale_lock = threading.Lock()
                self._queue_lock = threading.Lock()

            def scale_down(self):
                with self._scale_lock:
                    with self._queue_lock:
                        pass

            def drain(self):
                with self._queue_lock:
                    with self._scale_lock:
                        pass
    """
    assert "RTL604" in codes(src)


def test_rtl604_cycle_through_call_level_fires():
    # drain() acquires the queue lock while a held scale lock is one call
    # away — the cycle only exists through the call edge
    src = """
        import threading

        class Drain:
            def __init__(self):
                self._scale_lock = threading.Lock()
                self._queue_lock = threading.Lock()

            def scale_down(self):
                with self._scale_lock:
                    self._drain_locked()

            def _drain_locked(self):
                with self._queue_lock:
                    pass

            def drain(self):
                with self._queue_lock:
                    with self._scale_lock:
                        pass
    """
    assert "RTL604" in codes(src)


def test_rtl604_consistent_order_ok():
    src = """
        import threading

        class Drain:
            def __init__(self):
                self._scale_lock = threading.Lock()
                self._queue_lock = threading.Lock()

            def scale_down(self):
                with self._scale_lock:
                    with self._queue_lock:
                        pass

            def drain(self):
                with self._scale_lock:
                    with self._queue_lock:
                        pass
    """
    assert "RTL604" not in codes(src)


def test_rtl604_reentrant_same_lock_ok():
    src = """
        import threading

        class Reentrant:
            def __init__(self):
                self._lock = threading.RLock()

            def outer(self):
                with self._lock:
                    with self._lock:
                        pass
    """
    assert "RTL604" not in codes(src)


def test_rtl605_thread_target_async_def_fires():
    src = """
        import threading

        class Runner:
            def start(self):
                threading.Thread(target=self._run, daemon=True).start()

            async def _run(self):
                pass
    """
    assert "RTL605" in codes(src)


def test_rtl605_sync_target_ok():
    src = """
        import threading

        class Runner:
            def start(self):
                threading.Thread(target=self._run, daemon=True).start()

            def _run(self):
                pass
    """
    assert "RTL605" not in codes(src)


# ---------------------------------------------------------------------------
# RTL7xx fleet-plane consistency (project pass over fixture trees)


def fleet_codes(files):
    from relora_tpu.analysis import build_project_index
    from relora_tpu.analysis.rules_fleet import fleet_findings

    return [f.code for f in fleet_findings(build_project_index(files))]


PRODUCER_SRC = textwrap.dedent(
    """
    class MetricsRegistry:
        def __init__(self, namespace="relora_serve"):
            self.namespace = namespace

        def tick(self):
            self.inc("requests_total")
    """
)


def test_rtl701_seeded_typo_in_report_columns_fires():
    # the acceptance fixture: one typo'd series name in a report table must
    # fail the pass
    files = {
        "relora_tpu/serve/metrics.py": PRODUCER_SRC,
        "tools/fleet_report.py": textwrap.dedent(
            """
            _COMPARE_COLUMNS = (
                ("req", "relora_serve_requests_totl", "{:.0f}"),
            )
            """
        ),
    }
    assert "RTL701" in fleet_codes(files)


def test_rtl701_matching_producer_ok():
    files = {
        "relora_tpu/serve/metrics.py": PRODUCER_SRC,
        "tools/fleet_report.py": textwrap.dedent(
            """
            _COMPARE_COLUMNS = (
                ("req", "relora_serve_requests_total", "{:.0f}"),
            )
            """
        ),
    }
    assert "RTL701" not in fleet_codes(files)


def test_rtl701_series_kwarg_and_derivation_suffix():
    # `series=` kwargs are consumers; an `f"{name}_per_s"` store in a
    # parse_prometheus module produces the derived name iff the base exists
    collector = textwrap.dedent(
        """
        from relora_tpu.obs.parse_prometheus import parse_prometheus

        def derive(flat, values):
            for name, v in flat.items():
                values[f"{name}_per_s"] = v
        """
    )
    slo = textwrap.dedent(
        """
        def rules(SLO):
            return [SLO(name="rps", series="relora_serve_requests_total_per_s")]
        """
    )
    good = {
        "relora_tpu/serve/metrics.py": PRODUCER_SRC,
        "relora_tpu/obs/fleet.py": collector,
        "relora_tpu/obs/slo.py": slo,
    }
    assert "RTL701" not in fleet_codes(good)
    bad = dict(good)
    del bad["relora_tpu/serve/metrics.py"]  # base counter never produced
    assert "RTL701" in fleet_codes(bad)


def test_rtl702_unemitted_event_kind_fires():
    files = {
        "relora_tpu/obs/deploy.py": textwrap.dedent(
            """
            def announce(store):
                store.add_event("deploy_start", {})
            """
        ),
        "tools/fleet_report.py": 'DEPLOY_KINDS = ("deploy_start", "deploy_done")\n',
    }
    assert "RTL702" in fleet_codes(files)


def test_rtl702_emitted_kinds_ok_including_supervisor_prefix():
    # supervisor-routed kinds are consumed under a `supervisor_` prefix but
    # emitted bare through record_supervisor_event
    files = {
        "relora_tpu/obs/deploy.py": textwrap.dedent(
            """
            def announce(store):
                store.add_event("deploy_start", {})
                store.record_supervisor_event("restart", {})
            """
        ),
        "tools/fleet_report.py": (
            'DEPLOY_KINDS = ("deploy_start", "supervisor_restart")\n'
        ),
    }
    assert "RTL702" not in fleet_codes(files)


def test_rtl703_unmaterialized_delta_counter_fires():
    collector = textwrap.dedent(
        """
        from relora_tpu.obs.parse_prometheus import parse_prometheus

        def derive(flat, values):
            for name, v in flat.items():
                if name.endswith("requests_total"):
                    values["requests_per_s"] = v
        """
    )
    files = {
        "relora_tpu/obs/fleet.py": collector,
        "relora_tpu/serve/metrics.py": PRODUCER_SRC,
    }
    assert "RTL703" in fleet_codes(files)
    # materializing the counter at zero satisfies the rule
    zeroed = dict(files)
    zeroed["relora_tpu/serve/server.py"] = textwrap.dedent(
        """
        def warmup(stats):
            stats.inc("requests_total", 0)
        """
    )
    assert "RTL703" not in fleet_codes(zeroed)


def test_rtl704_fault_site_without_check_site_fires():
    files = {
        "relora_tpu/utils/boot.py": textwrap.dedent(
            """
            from relora_tpu.utils import faults

            def setup():
                faults.configure("scrape_drop", rate=0.5)
            """
        ),
    }
    assert "RTL704" in fleet_codes(files)
    checked = dict(files)
    checked["relora_tpu/obs/fleet.py"] = textwrap.dedent(
        """
        from relora_tpu.utils import faults

        def scrape(target):
            if faults.should("scrape_drop"):
                return None
            return target
        """
    )
    assert "RTL704" not in fleet_codes(checked)


def test_rtl704_env_fault_spec_is_a_consumer():
    # RELORA_TPU_FAULTS env strings (site:param=value) configure sites too
    files = {
        "tests/test_resilience.py": textwrap.dedent(
            """
            import os

            def test_preempt():
                os.environ["RELORA_TPU_FAULTS"] = "ghost_site:rate=0.5"
            """
        ),
    }
    assert "RTL704" in fleet_codes(files)


def test_rtl705_dead_event_emission_fires():
    files = {
        "relora_tpu/obs/deploy.py": textwrap.dedent(
            """
            def announce(store):
                store.add_event("mystery_event", {})
            """
        ),
    }
    assert "RTL705" in fleet_codes(files)
    consumed = dict(files)
    consumed["tools/fleet_report.py"] = 'TIMELINE_KINDS = ("mystery_event",)\n'
    assert "RTL705" not in fleet_codes(consumed)


# ---------------------------------------------------------------------------
# hotpaths drift guard: device-dispatch-shaped modules must be registered


def test_hotpaths_registry_covers_dispatch_shaped_modules():
    """A module in the serving/training/ops/obs planes that defines a
    step/decode/prefill-shaped entry point or calls jax.jit/pjit must either
    have a HOT_FUNCTIONS entry or carry the HOT_MARKER comment — otherwise
    new hot code silently escapes the RTL2xx rules."""
    import ast as ast_mod

    from relora_tpu.analysis.core import dotted_name as dn
    from relora_tpu.analysis.hotpaths import HOT_FUNCTIONS, HOT_MARKER

    shaped_names = {"step", "decode", "prefill", "decode_paged", "prefill_chunk"}
    jit_calls = {"jax.jit", "jax.pjit", "jit", "pjit"}
    missing = []
    for sub in ("serve", "train", "ops", "obs"):
        for path in sorted((REPO_ROOT / "relora_tpu" / sub).glob("*.py")):
            rel = path.relative_to(REPO_ROOT).as_posix()
            text = path.read_text()
            if rel in HOT_FUNCTIONS or HOT_MARKER in text:
                continue
            tree = ast_mod.parse(text)
            shaped = any(
                isinstance(n, (ast_mod.FunctionDef, ast_mod.AsyncFunctionDef))
                and n.name in shaped_names
                for n in ast_mod.walk(tree)
            )
            jitted = any(
                isinstance(n, ast_mod.Call) and dn(n.func) in jit_calls
                for n in ast_mod.walk(tree)
            )
            if shaped or jitted:
                missing.append(rel)
    assert missing == [], (
        f"modules with dispatch-shaped code but no hotpaths registration: "
        f"{missing} — add a HOT_FUNCTIONS entry (or the HOT_MARKER comment) "
        "in relora_tpu/analysis/hotpaths.py"
    )


# ---------------------------------------------------------------------------
# engine: catalog, suppression, baseline, CLI, repo self-check


def test_catalog_covers_all_families():
    assert len(RULE_CATALOG) >= 20
    families = {code[:4] for code in RULE_CATALOG}
    assert families == {"RTL1", "RTL2", "RTL3", "RTL4", "RTL5", "RTL6", "RTL7"}


def test_noqa_suppresses_specific_and_bare():
    src = """
        def graft(params, new_head):
            params["lm_head"] = new_head  # noqa: RTL501
            return params

        def graft2(params, new_head):
            params["lm_head"] = new_head  # noqa
            return params

        def graft3(params, new_head):
            params["lm_head"] = new_head  # noqa: RTL101
            return params
    """
    found = lint_text(textwrap.dedent(src))
    # first two suppressed; the wrong-code noqa does not suppress
    assert [f.code for f in found] == ["RTL501"]


def test_baseline_roundtrip(tmp_path):
    f = Finding("pkg/mod.py", 3, "RTL501", "msg", 'params["x"] = y')
    line = format_baseline_entry(f, "intentional: grafting owns the dict")
    p = tmp_path / "baseline.txt"
    p.write_text("# comment\n\n" + line + "\n")
    entries = load_baseline(str(p))
    assert len(entries) == 1 and entries[0].matches(f)
    # different line text (the code changed) no longer matches
    assert not entries[0].matches(
        Finding("pkg/mod.py", 3, "RTL501", "msg", 'params["y"] = y')
    )


def test_baseline_requires_justification(tmp_path):
    p = tmp_path / "baseline.txt"
    p.write_text("a.py | RTL501 | x = 1 |\n")
    with pytest.raises(ValueError):
        load_baseline(str(p))


def test_lint_paths_baseline_and_stale(tmp_path):
    mod = tmp_path / "mod.py"
    mod.write_text("def f(params, v):\n    params['k'] = v\n    return params\n")
    baseline = [
        BaselineEntry("mod.py", "RTL501", "params['k'] = v", "ok", 1),
        BaselineEntry("mod.py", "RTL101", "gone", "stale entry", 2),
    ]
    report = lint_paths([str(mod)], root=str(tmp_path), baseline=baseline)
    assert report.new == []
    assert report.baselined == 1
    assert [e.lineno for e in report.stale_baseline] == [2]


def test_cli_exit_codes(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("def f(params, v):\n    params['k'] = v\n")
    clean = tmp_path / "clean.py"
    clean.write_text("def f(params, v):\n    return {**params, 'k': v}\n")
    env_root = str(REPO_ROOT)

    r = subprocess.run(
        [sys.executable, "-m", "relora_tpu.analysis", "--no-baseline", str(bad)],
        capture_output=True,
        text=True,
        cwd=env_root,
    )
    assert r.returncode == 1
    assert "RTL501" in r.stdout

    r = subprocess.run(
        [sys.executable, "-m", "relora_tpu.analysis", "--no-baseline", str(clean)],
        capture_output=True,
        text=True,
        cwd=env_root,
    )
    assert r.returncode == 0
    assert r.stdout == ""


def test_cli_family_filter(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("def f(params, v):\n    params['k'] = v\n")

    r = subprocess.run(
        [
            sys.executable, "-m", "relora_tpu.analysis",
            "--no-baseline", "--family", "RTL5", str(bad),
        ],
        capture_output=True,
        text=True,
        cwd=str(REPO_ROOT),
    )
    assert r.returncode == 1
    assert "RTL501" in r.stdout

    r = subprocess.run(
        [
            sys.executable, "-m", "relora_tpu.analysis",
            "--no-baseline", "--family", "RTL6", str(bad),
        ],
        capture_output=True,
        text=True,
        cwd=str(REPO_ROOT),
    )
    assert r.returncode == 0


def test_cli_call_graph_dump(tmp_path):
    mod = tmp_path / "mod.py"
    mod.write_text(
        textwrap.dedent(
            """
            import threading

            class W:
                def start(self):
                    threading.Thread(target=self._loop).start()

                def _loop(self):
                    self._tick()

                def _tick(self):
                    pass
            """
        )
    )
    r = subprocess.run(
        [
            sys.executable, "-m", "relora_tpu.analysis",
            "--call-graph-dump", str(mod),
        ],
        capture_output=True,
        text=True,
        cwd=str(REPO_ROOT),
    )
    assert r.returncode == 0
    assert "root[thread] W._loop" in r.stdout
    assert "W._loop -> W._tick" in r.stdout


def test_repo_lints_clean_against_baseline():
    """The tree itself must pass: no new findings, no stale baseline rows,
    no parse errors.  This is the tier-1 lint gate."""
    report = lint_paths(
        [str(REPO_ROOT / "relora_tpu")],
        root=str(REPO_ROOT),
        baseline=str(REPO_ROOT / "tools" / "lint_baseline.txt"),
    )
    assert report.parse_errors == []
    assert [f.render() for f in report.new] == []
    assert [e.path + "|" + e.code for e in report.stale_baseline] == []
    # the linter actually ran over the package, not an empty dir
    assert report.files_scanned > 40


def test_repo_baseline_entries_are_justified():
    entries = load_baseline(str(REPO_ROOT / "tools" / "lint_baseline.txt"))
    assert entries, "baseline exists and has entries"
    for e in entries:
        assert len(e.justification) > 10, f"thin justification: {e}"
