"""Shared throughput-measurement core for bench.py and scripts/bench_sweep.py.

One implementation of the model/optimizer construction, warmup, sync, and
timed loop, so the headline bench and the lever-sweep harness cannot drift.
Throughput definition parity: tokens_in_update / update_time
(torchrun_main.py:928-931).
"""

from __future__ import annotations

import time
from typing import Optional

from relora_tpu.obs.memory import hbm_peak_gb as obs_hbm_peak_gb
from relora_tpu.obs.mfu import PEAK_FLOPS_DEFAULT
from relora_tpu.obs.mfu import peak_flops as detect_peak_flops

# kept for importers; the actual per-device table (and the
# RELORA_TPU_PEAK_FLOPS override) lives in relora_tpu.obs.mfu
PEAK_FLOPS_V5E = PEAK_FLOPS_DEFAULT


def run_throughput_bench(
    model_name: str,
    *,
    micro_batch: int = 8,
    grad_accum: int = 1,
    seq: int = 1024,
    remat: bool = True,
    remat_policy: str = "full",
    loss_impl: str = "dense",
    vocab_chunk: int = 8192,
    logits_dtype: str = "f32",
    attn: str = "auto",
    rank: Optional[int] = 128,
    quantize: Optional[str] = None,
    base_dtype: Optional[str] = None,
    lora_fused="auto",
    dropout: float = 0.1,
    warmup_steps: int = 3,
    measure_steps: int = 10,
    magnitude_reset: bool = False,
    peak_flops: Optional[float] = None,
) -> dict:
    """Build the ReLoRA train step for ``model_name`` and measure steady-state
    training throughput on the default backend.  Returns a dict with
    tokens_per_sec / mfu / step_time_s / loss / device.

    ``rank=None`` (or 0) benches the full-rank configuration (every param
    trainable).  ``magnitude_reset=True`` runs one magnitude-pruning
    optimizer reset between warmup and the timed window (proves the path
    on-chip; the 1B recipe amortizes its cost over 1000 steps, so it is
    deliberately excluded from the per-step figure).
    """
    from relora_tpu.utils.logging import enable_compile_cache

    enable_compile_cache()
    import jax
    import jax.numpy as jnp

    from relora_tpu.config.model import MODEL_ZOO
    from relora_tpu.core.optim import build_optimizer
    from relora_tpu.core.partition import partition
    from relora_tpu.core.relora import LoraSpec, trainable_param_mask
    from relora_tpu.models.llama import LlamaForCausalLM
    from relora_tpu.models.params_util import init_params
    from relora_tpu.train.state import TrainState
    from relora_tpu.train.step import make_train_step

    cfg = MODEL_ZOO[model_name]
    spec = (
        LoraSpec(
            r=rank,
            alpha=32,
            dropout=dropout,
            quantize=quantize,
            base_dtype=base_dtype,
            fused=lora_fused,
        )
        if rank
        else None
    )
    model = LlamaForCausalLM(
        cfg,
        lora=spec,
        dtype=jnp.bfloat16,
        scan_layers=True,
        remat=remat,
        remat_policy=remat_policy,
        attention_impl=attn,
        logits_dtype=jnp.bfloat16 if logits_dtype == "bf16" else jnp.float32,
    )
    sample = jnp.zeros((1, 8), jnp.int32)
    params = init_params(model, jax.random.PRNGKey(0), sample)
    mask = trainable_param_mask(params)
    tx = build_optimizer(schedule=lambda s: 1e-3)
    opt_state = jax.jit(tx.init)(partition(params, mask)[0])
    state = TrainState.create(params, opt_state)
    step = jax.jit(
        make_train_step(model, tx, mask, loss_impl=loss_impl, vocab_chunk=vocab_chunk),
        donate_argnums=0,
    )

    batch = jax.random.randint(
        jax.random.PRNGKey(1), (grad_accum, micro_batch, seq), 0, cfg.vocab_size
    )
    rng = jax.random.PRNGKey(2)

    # always at least one untimed step: primes the compile cache and binds
    # `metrics` for the pre-measure sync even when warmup_steps == 0 — the
    # result dict reports warmup_steps_effective so a --warmup 0 sweep can
    # see the floor was applied rather than misattribute the measurement
    warmup_steps_effective = max(warmup_steps, 1)
    for i in range(warmup_steps_effective):
        state, metrics = step(state, batch, jax.random.fold_in(rng, i))
    if magnitude_reset:
        from relora_tpu.core.optim import reset_optimizer_state

        reset = jax.jit(
            lambda s: s.replace(
                opt_state=reset_optimizer_state(s.opt_state, mode="magnitude", ratio=0.9)
            ),
            donate_argnums=0,
        )
        state = reset(state)
        # fence the reset's device execution out of the timed window
        jax.block_until_ready(state.opt_state)
    float(metrics["loss"])  # full sync (block_until_ready can return early
    # through the axon relay; a scalar pull cannot)

    t0 = time.perf_counter()
    for i in range(measure_steps):
        state, metrics = step(state, batch, jax.random.fold_in(rng, 100 + i))
    # the final loss depends on every preceding step's params, so this one
    # sync forces the whole chain to have executed
    final_loss = float(metrics["loss"])
    dt = time.perf_counter() - t0

    tokens_per_update = grad_accum * micro_batch * seq
    tokens_per_sec = tokens_per_update * measure_steps / dt
    # one schema for CPU and TPU: obs/memory normalizes the backends that
    # keep no allocator stats (CPU) to None instead of a raw `or {}` dance
    hbm_peak_gb = obs_hbm_peak_gb(jax.devices()[0])
    # 6*N per token fwd+bwd on the dense (equivalent) params
    n_params = cfg.num_params(include_embeddings=False) + cfg.vocab_size * cfg.hidden_size
    if peak_flops is None:
        # per-device table keyed on device_kind; RELORA_TPU_PEAK_FLOPS overrides
        peak_flops = detect_peak_flops(jax.devices()[0])
    mfu = tokens_per_sec * 6 * n_params / peak_flops
    return {
        "tokens_per_sec": round(tokens_per_sec, 1),
        "mfu": round(mfu, 4),
        "peak_flops": peak_flops,
        "step_time_s": round(dt / measure_steps, 4),
        "tokens_per_update": tokens_per_update,
        "warmup_steps_effective": warmup_steps_effective,
        "loss": final_loss,
        "hbm_peak_gb": hbm_peak_gb,
        "device": str(jax.devices()[0]),
    }
