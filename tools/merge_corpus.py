"""Merge pre-tokenized mmap corpus shards into one corpus.

The reference exposes this as MMapIndexedDatasetBuilder.merge_file_
(peft_pretraining/megatron_dataset/indexed_dataset.py:596-603), used to
combine per-worker pretokenizer outputs.  Here the same capability is a
one-shot CLI over MemmapTokenWriter.merge_file: raw ``.bin`` bytes are
streamed, never re-encoded, so merging is IO-bound.

Usage::

    python tools/merge_corpus.py --out merged shard_a shard_b shard_c

Each positional argument is a corpus prefix (``<prefix>.bin``/``.idx``).
Shards must share a dtype (the pretokenizer autoselects by vocab size, so
shards from one tokenizer always match).
"""

from __future__ import annotations

import argparse
import os
import sys
import time


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("shards", nargs="+", help="input corpus prefixes (no extension)")
    p.add_argument("--out", required=True, help="output corpus prefix")
    args = p.parse_args(argv)

    sys.path.insert(0, ".")
    from relora_tpu.data.memmap import (
        MemmapTokenDataset,
        MemmapTokenWriter,
        _read_index_arrays,
    )

    # realpath comparison: a spelling variant like ./b for b would pass a
    # string check, and the writer truncates out's .bin on open — catching
    # it after that destroys the input shard
    out_real = os.path.realpath(os.path.abspath(args.out))
    for shard in args.shards:
        if os.path.realpath(os.path.abspath(shard)) == out_real:
            p.error(f"--out must not be one of the input shards ({shard!r})")

    dtype, _, _ = _read_index_arrays(args.shards[0])
    t0 = time.time()
    with MemmapTokenWriter(args.out, dtype=dtype) as w:
        for shard in args.shards:
            w.merge_file(shard)

    merged = MemmapTokenDataset(args.out)
    print(
        f"merged {len(args.shards)} shards -> {args.out}.bin/.idx: "
        f"{len(merged):,} sequences / {merged.n_tokens:,} tokens "
        f"({dtype}) in {time.time()-t0:.1f}s"
    )


if __name__ == "__main__":
    main()
