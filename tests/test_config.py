"""Tests for the training/model config system (args_utils.py parity)."""

import os

import pytest
import yaml

from relora_tpu.config.model import MODEL_ZOO, ModelConfig, load_model_config
from relora_tpu.config.training import TrainingConfig, parse_token_count, parse_train_args


def base_cfg(**kw):
    d = dict(dataset_path="/tmp/ds", batch_size=4)
    d.update(kw)
    return TrainingConfig(**d)


def test_requires_exactly_one_data_source():
    with pytest.raises(ValueError, match="Exactly one"):
        TrainingConfig(batch_size=4).finalize()
    with pytest.raises(ValueError, match="Exactly one"):
        TrainingConfig(
            batch_size=4, dataset_path="/x", megatron_dataset_config="/y"
        ).finalize()


def test_batch_size_required():
    with pytest.raises(ValueError, match="batch_size"):
        TrainingConfig(dataset_path="/x").finalize()


def test_total_batch_derivation():
    cfg = base_cfg(gradient_accumulation=8).finalize()
    assert cfg.total_batch_size == 32
    cfg = base_cfg().finalize()
    assert cfg.total_batch_size == 4 and cfg.gradient_accumulation == 1


def test_grad_accum_for_world():
    cfg = base_cfg(total_batch_size=1024, batch_size=8).finalize()
    assert cfg.grad_accum_for(32) == 4
    with pytest.raises(ValueError):
        cfg.grad_accum_for(3)


def test_max_train_tokens_overrides_steps():
    cfg = base_cfg(total_batch_size=8, max_train_tokens="1M").finalize()
    assert cfg.num_training_steps == 1_000_000 // 8
    assert parse_token_count("2B") == 2_000_000_000
    assert parse_token_count(100) == 100
    assert parse_token_count(None) is None


def test_fp16_rejected():
    with pytest.raises(NotImplementedError):
        base_cfg(dtype="fp16").finalize()


def test_reset_modes_mutually_exclusive():
    with pytest.raises(ValueError, match="mutually exclusive"):
        base_cfg(
            reset_optimizer_on_relora=True, optimizer_magnitude_pruning=0.8
        ).finalize()
    cfg = base_cfg(
        reset_optimizer_on_relora=False, optimizer_magnitude_pruning=0.8
    ).finalize()
    assert cfg.optimizer_reset_mode == "magnitude"
    assert cfg.optimizer_reset_ratio == 0.8
    cfg = base_cfg(reset_optimizer_on_relora=True).finalize()
    assert cfg.optimizer_reset_mode == "zero"


def test_relora_without_peft_dropped():
    """Reference parity: args_utils clears relora before the (dead) promotion,
    so --relora without --use_peft trains full-rank."""
    cfg = base_cfg(relora=1000).finalize()
    assert cfg.use_peft is False and cfg.relora is None
    cfg = base_cfg(relora=1000, use_peft=True).finalize()
    assert cfg.relora == 1000
    cfg = base_cfg(use_peft=False).finalize()
    assert cfg.relora is None and cfg.lora_r is None


def test_skip_batches_parsing():
    cfg = base_cfg(skip_batches="3,7,12").finalize()
    assert cfg.skip_batches == {3, 7, 12}
    cfg = base_cfg().finalize()
    assert cfg.skip_batches == set()


def test_yaml_roundtrip(tmp_path):
    """A reference-format YAML (1B_v1.0.yaml style) loads correctly."""
    raw = {
        "dataset_path": "/tmp/ds",
        "use_peft": True,
        "lora_r": 128,
        "relora": 1000,
        "restart_warmup_steps": 100,
        "reset_optimizer_on_relora": False,
        "optimizer_magnitude_pruning": 0.8,
        "batch_size": 8,
        "total_batch_size": 1024,
        "lr": "4e-4",  # yaml may leave scientific notation as str
        "adam_beta2": 0.95,
        "scheduler": "cosine_restarts",
        "warmup_steps": 500,
        "num_training_steps": 130000,
        "dtype": "bfloat16",
    }
    p = tmp_path / "cfg.yaml"
    p.write_text(yaml.safe_dump(raw))
    cfg = TrainingConfig.from_yaml(str(p))
    assert cfg.lr == 4e-4
    assert cfg.optimizer_reset_mode == "magnitude"
    assert cfg.total_batch_size == 1024

    out = tmp_path / "resolved.yaml"
    cfg.save(str(out))
    again = yaml.safe_load(out.read_text())
    assert again["relora"] == 1000


def test_cli_parsing():
    cfg = parse_train_args(
        [
            "--dataset_path", "/tmp/ds",
            "--batch_size", "4",
            "--relora", "100",
            "--use_peft", "true",
            "--lr", "1e-3",
            "--scheduler", "cosine_restarts",
            "--cycle_length", "100",
            "--restart_warmup_steps", "10",
        ]
    )
    assert cfg.relora == 100 and cfg.lr == 1e-3


def test_cli_yaml_exclusive(tmp_path):
    p = tmp_path / "cfg.yaml"
    p.write_text(yaml.safe_dump({"dataset_path": "/tmp/ds", "batch_size": 2}))
    with pytest.raises(RuntimeError, match="not both"):
        parse_train_args(["--training_config", str(p), "--batch_size", "4"])
    cfg = parse_train_args(["--training_config", str(p)])
    assert cfg.batch_size == 2


def test_model_zoo_sizes():
    # spot-check against the reference JSON sweep
    c = MODEL_ZOO["llama_35m"]
    assert (c.hidden_size, c.intermediate_size, c.num_hidden_layers, c.num_attention_heads) == (384, 1024, 6, 8)
    c = MODEL_ZOO["llama_1b"]
    assert (c.hidden_size, c.intermediate_size, c.num_hidden_layers) == (2048, 5461, 24)
    c = MODEL_ZOO["llama_7b"]
    assert c.max_sequence_length == 2048 and c.hidden_size == 4096
    assert load_model_config("llama_250m").vocab_size == 32100
    # param count sanity: llama_250m should be ~250M incl embeddings
    n = MODEL_ZOO["llama_250m"].num_params()
    assert 200e6 < n < 300e6


def test_model_config_hf_json(tmp_path):
    p = tmp_path / "config.json"
    p.write_text(
        '{"hidden_size": 384, "intermediate_size": 1024, "num_hidden_layers": 6,'
        '"num_attention_heads": 8, "vocab_size": 32100, "max_sequence_length": 1024,'
        '"rms_norm_eps": 1e-6, "model_type": "llama"}'
    )
    c = ModelConfig.from_hf_json(str(p))
    assert c.family == "llama" and c.head_dim == 48


def test_package_import_does_not_initialize_jax():
    """Importing config/logging must not touch the XLA backend (it would break
    a later jax.distributed.initialize() on multi-host)."""
    import subprocess, sys

    code = (
        "import relora_tpu.config.training, relora_tpu.utils.logging, sys;"
        "assert 'jax' not in sys.modules or not __import__('jax')._src.xla_bridge._backends,"
        "'XLA backend initialized at import time'"
    )
    r = subprocess.run([sys.executable, "-c", code], capture_output=True, text=True, cwd="/root/repo")
    assert r.returncode == 0, r.stderr
