"""Causal attention with selectable backends.

The reference calls ``F.scaled_dot_product_attention(..., is_causal=True)``
and deliberately ignores the padding mask (modeling_llama.py:221-224,
modeling_pythia.py:262-270).  Here the same contract — causal, no padding
mask — is served by three interchangeable implementations:

- ``xla``     — ``jax.nn.dot_product_attention``: XLA fuses this into an
  efficient (flash-like) kernel on TPU; the safe default everywhere.
- ``pallas``  — the Pallas TPU flash-attention kernel
  (jax.experimental.pallas.ops.tpu.flash_attention) for long sequences;
  requires TPU and MXU-friendly head dims.
- ``naive``   — explicit softmax(QKᵀ)V in f32, the differential-testing
  oracle.

All take/return ``(batch, seq, heads, head_dim)``.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def _expand_grouped_kv(q, k, v):
    """Materialize grouped K/V up to the full query head count (for impls
    that need equal head counts), validating divisibility at the boundary."""
    n, n_kv = q.shape[2], k.shape[2]
    if n == n_kv:
        return k, v
    if n % n_kv:
        raise ValueError(f"num_heads={n} must divide by kv_heads={n_kv}")
    rep = n // n_kv
    return jnp.repeat(k, rep, axis=2), jnp.repeat(v, rep, axis=2)


def _grouped_equal_heads_call(q, k, v, equal_heads_fn) -> jax.Array:
    """Apply an equal-head-count attention kernel to grouped-query inputs
    WITHOUT materializing expanded K/V: one call per group slice, every
    slice reading the same K/V buffers.  ``g`` is a small static int, so the
    unrolled loop adds g-1 kernel launches, not g× K/V HBM."""
    n, n_kv = q.shape[2], k.shape[2]
    if n == n_kv:
        return equal_heads_fn(q, k, v)
    if n % n_kv:
        raise ValueError(f"num_heads={n} must divide by kv_heads={n_kv}")
    g = n // n_kv
    B, S, _, H = q.shape
    qg = q.reshape(B, S, n_kv, g, H)
    outs = [equal_heads_fn(qg[:, :, :, j, :], k, v) for j in range(g)]
    return jnp.stack(outs, axis=3).reshape(B, S, n, H)


def _pallas_min_seq() -> int:
    """Sequence length at/above which impl='auto' prefers the pallas flash
    kernel on TPU.  Disabled unless RELORA_TPU_PALLAS_MIN_SEQ is set: the
    only recorded A/B has XLA beating pallas by 5% at seq 1024 on the v5e
    (BASELINE.md r2), so until scripts/bench_attention.py has measured the
    crossover on-chip, auto stays on the XLA fused path and the pallas
    dispatch is explicit opt-in.  0 (or unset) disables."""
    import os

    _DISABLED = 1 << 62
    raw = os.environ.get("RELORA_TPU_PALLAS_MIN_SEQ", "")
    try:
        val = int(raw)
    except ValueError:
        return _DISABLED
    return val if val > 0 else _DISABLED


def _naive_attention(q, k, v, *, causal: bool, scale: float) -> jax.Array:
    B, S, N, H = q.shape
    n_kv = k.shape[2]
    qg = q.astype(jnp.float32).reshape(B, S, n_kv, N // n_kv, H)
    logits = (
        jnp.einsum("bqkgh,bskh->bkgqs", qg, k.astype(jnp.float32)) * scale
    )
    if causal:
        mask = jnp.tril(jnp.ones((S, k.shape[1]), dtype=bool))
        logits = jnp.where(mask[None, None, None, :, :], logits, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bkgqs,bskh->bqkgh", probs, v.astype(jnp.float32))
    return out.reshape(B, S, N, H).astype(q.dtype)


def flash_block_size(S: int, S_kv: int) -> Optional[int]:
    """Tile size for the pallas flash kernel, or None when the lengths are
    sub-tile / non-128-aligned and the kernel can't apply.  The kernel's
    _verify_block requires exact divisibility (e.g. S=768 with block 512 is
    rejected), so this picks the largest of 512/256/128 dividing both."""
    if S < 128 or S_kv < 128 or S % 128 or S_kv % 128:
        return None
    return next(b for b in (512, 256, 128) if S % b == 0 and S_kv % b == 0)


def _pallas_attention(q, k, v, *, causal: bool, scale: float) -> jax.Array:
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        BlockSizes,
        flash_attention,
    )

    blk = flash_block_size(q.shape[1], k.shape[1])
    if blk is None:
        # e.g. the (1, 8) param-init trace: XLA's fused path is fine at
        # these sizes
        return jax.nn.dot_product_attention(
            q, k, v, scale=scale, is_causal=causal
        )
    sizes = BlockSizes(
        block_q=blk,
        block_k_major=blk,
        block_k=blk,
        block_b=1,
        block_q_major_dkv=blk,
        block_k_major_dkv=blk,
        block_k_dkv=blk,
        block_q_dkv=blk,
        block_k_major_dq=blk,
        block_k_dq=blk,
        block_q_dq=blk,
    )

    def equal_heads(qq, kk, vv):
        # the pallas kernel wants (batch, heads, seq, head_dim)
        qt, kt, vt = (x.swapaxes(1, 2) for x in (qq, kk, vv))
        out = flash_attention(
            qt, kt, vt, causal=causal, sm_scale=scale, block_sizes=sizes
        )
        return out.swapaxes(1, 2)

    return _grouped_equal_heads_call(q, k, v, equal_heads)


def cached_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    positions: jax.Array,
    *,
    scale: Optional[float] = None,
) -> jax.Array:
    """Masked decode attention against a fixed-capacity KV cache.

    ``q`` is ``(B, T, N, H)`` — T is 1 for single-token decode, up to S for
    prefill — holding queries at absolute positions ``positions`` ``(B, T)``
    (or ``(1, T)``, broadcast over batch).  ``k``/``v`` are the cache buffers
    ``(B, C, N_kv, H)`` with capacity C; entry ``j`` of the cache is visible
    to the query at position ``p`` iff ``j <= p``, which is simultaneously
    the causal mask (prefill), the length mask that hides not-yet-written
    (or stale, from an evicted slot) cache tail entries (decode), and the
    pad mask for right-padded prompts.

    Math in f32 like the ``naive`` oracle: decode is memory-bound — the
    arithmetic is negligible next to streaming the cache from HBM — so
    there is no reason to give up softmax accuracy.  Grouped-query K/V
    attends without materializing the head expansion.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    B, T, N, H = q.shape
    C, n_kv = k.shape[1], k.shape[2]
    if N % n_kv:
        raise ValueError(f"num_heads={N} must divide by kv_heads={n_kv}")
    qg = q.astype(jnp.float32).reshape(B, T, n_kv, N // n_kv, H)
    logits = jnp.einsum("btkgh,bskh->bkgts", qg, k.astype(jnp.float32)) * scale
    visible = jnp.arange(C)[None, None, :] <= positions[..., None]  # (B|1, T, C)
    logits = jnp.where(
        visible[:, None, None, :, :], logits, jnp.finfo(jnp.float32).min
    )
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bkgts,bskh->btkgh", probs, v.astype(jnp.float32))
    return out.reshape(B, T, N, H).astype(q.dtype)


def gather_kv_pages(pool: jax.Array, block_tables: jax.Array) -> jax.Array:
    """Gather a per-row contiguous K/V view out of a shared page pool.

    ``pool`` is ``(num_pages, page_size, N_kv, H)`` — one buffer shared by
    every request — and ``block_tables`` is ``(B, W)`` mapping each row's
    logical page index (``position // page_size``) to a pool page.  Returns
    ``(B, W * page_size, N_kv, H)`` in logical token order.  Padded table
    entries point at the null page (paging.NULL_PAGE); whatever garbage
    lives there is masked off downstream by the ``j <= position``
    visibility rule, exactly like unwritten tail entries of the contiguous
    cache.
    """
    pages = jnp.take(pool, block_tables, axis=0)  # (B, W, page_size, N_kv, H)
    B, W, ps = pages.shape[:3]
    return pages.reshape(B, W * ps, pages.shape[3], pages.shape[4])


def paged_cached_attention(
    q: jax.Array,
    pool_k: jax.Array,
    pool_v: jax.Array,
    block_tables: jax.Array,
    positions: jax.Array,
    *,
    scale: Optional[float] = None,
) -> jax.Array:
    """``cached_attention`` against a paged K/V pool.

    The gather reconstructs each row's logical cache at full table width
    ``W * page_size`` — with ``W = cache_size / page_size`` that is exactly
    the contiguous path's contraction length ``C``, and masked entries get
    softmax probability exactly 0.0 (their f32-min logits underflow the
    shifted exp), so the result is bitwise-identical to attending the
    contiguous cache.  That equality is what lets the paged scheduler pin
    token parity against the contiguous engine.  Width-bucketing the gather
    to the pages actually used (a read-bandwidth win for short requests in
    a long-capacity pool) is future work and would trade that bitwise
    guarantee for an allclose one.
    """
    k = gather_kv_pages(pool_k, block_tables)
    v = gather_kv_pages(pool_v, block_tables)
    return cached_attention(q, k, v, positions, scale=scale)


def dot_product_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    impl: str = "auto",
    scale: Optional[float] = None,
) -> jax.Array:
    """Causal SDPA over ``(B, S, N, H)`` tensors.

    ``impl='auto'`` resolves to the XLA fused path (which beat the pallas
    kernel by 5% at seq 1024 on the v5e, BASELINE.md r2).  Setting
    ``RELORA_TPU_PALLAS_MIN_SEQ=N`` opts in to the pallas flash kernel for
    seq >= N on TPU; until the op-level A/B at 1k/4k/16k
    (scripts/bench_attention.py) has measured a crossover on-chip there is
    no default threshold.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if impl == "auto":
        impl = "xla"
        if q.shape[1] >= _pallas_min_seq() and jax.default_backend() == "tpu":
            impl = "pallas"
    if impl == "xla":
        return jax.nn.dot_product_attention(q, k, v, scale=scale, is_causal=causal)
    if impl == "pallas":
        return _pallas_attention(q, k, v, causal=causal, scale=scale)
    if impl in ("ring", "ring_zigzag", "ulysses"):
        # context parallelism: S sharded over the mesh's sequence axis
        from relora_tpu.parallel.mesh import current_mesh

        mesh = current_mesh()
        if mesh is None:
            raise RuntimeError(
                f"attention impl {impl!r} needs a mesh: call "
                "relora_tpu.parallel.mesh.set_current_mesh(mesh) first"
            )
        if impl == "ring":
            from relora_tpu.parallel.ring_attention import ring_attention

            return ring_attention(q, k, v, mesh, causal=causal, scale=scale)
        if impl == "ring_zigzag":
            # inputs travel in the persistent zigzag layout (the train step
            # permutes tokens/positions/labels consistently)
            from relora_tpu.parallel.ring_attention import ring_attention_zigzag

            if not causal:
                raise ValueError("zigzag layout only applies to causal attention")
            return ring_attention_zigzag(q, k, v, mesh, scale=scale, inputs_permuted=True)
        from relora_tpu.parallel.ulysses import ulysses_attention

        return ulysses_attention(q, k, v, mesh, causal=causal, scale=scale)
    if impl == "naive":
        return _naive_attention(q, k, v, causal=causal, scale=scale)
    raise ValueError(f"Unknown attention impl {impl!r}")
