"""Observability subsystem: span tracer, metrics registry, flight recorder,
MFU helpers, and the trace report tool.

The load-bearing test here is the golden /metrics render: ServeMetrics was
extracted into the shared ``relora_tpu.obs.metrics.MetricsRegistry``, and
the acceptance criterion is that the ``/metrics`` body is **byte-identical**
to the pre-refactor renderer.  The golden string below was captured from the
pre-extraction ``serve/admission.ServeMetrics`` — do not regenerate it from
the current code; that would defeat the pin.
"""

import json
import os
import subprocess
import sys
import threading
import time

import pytest

from relora_tpu.obs.flight import FlightRecorder, dump_on_fault
from relora_tpu.obs.metrics import LATENCY_BUCKETS, Histogram, MetricsRegistry
from relora_tpu.obs.mfu import (
    PEAK_FLOPS_DEFAULT,
    peak_flops,
    step_flops_from_cost_analysis,
)
from relora_tpu.obs.tracer import NoopTracer, Tracer, chrome_trace_events, new_trace_id

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# metrics registry: golden render (byte-identical to pre-refactor ServeMetrics)

GOLDEN_RENDER = (
    '# TYPE relora_serve_http_requests_total counter\n'
    'relora_serve_http_requests_total{route="generate"} 2\n'
    'relora_serve_http_requests_total{route="healthz"} 1\n'
    '# TYPE relora_serve_rejected_total counter\n'
    'relora_serve_rejected_total{reason="queue_full"} 1\n'
    '# TYPE relora_serve_requests_finished_total counter\n'
    'relora_serve_requests_finished_total{reason="length"} 2\n'
    '# TYPE relora_serve_tokens_generated_total counter\n'
    'relora_serve_tokens_generated_total 7\n'
    '# TYPE relora_serve_active_slots gauge\n'
    'relora_serve_active_slots 2\n'
    '# TYPE relora_serve_draining gauge\n'
    'relora_serve_draining 0\n'
    '# TYPE relora_serve_queue_depth gauge\n'
    'relora_serve_queue_depth 3\n'
    '# TYPE relora_serve_tpot_seconds histogram\n'
    'relora_serve_tpot_seconds_bucket{le="0.001"} 0\n'
    'relora_serve_tpot_seconds_bucket{le="0.0025"} 0\n'
    'relora_serve_tpot_seconds_bucket{le="0.005"} 0\n'
    'relora_serve_tpot_seconds_bucket{le="0.01"} 0\n'
    'relora_serve_tpot_seconds_bucket{le="0.025"} 1\n'
    'relora_serve_tpot_seconds_bucket{le="0.05"} 1\n'
    'relora_serve_tpot_seconds_bucket{le="0.1"} 1\n'
    'relora_serve_tpot_seconds_bucket{le="0.25"} 1\n'
    'relora_serve_tpot_seconds_bucket{le="0.5"} 1\n'
    'relora_serve_tpot_seconds_bucket{le="1"} 1\n'
    'relora_serve_tpot_seconds_bucket{le="2.5"} 1\n'
    'relora_serve_tpot_seconds_bucket{le="5"} 1\n'
    'relora_serve_tpot_seconds_bucket{le="10"} 1\n'
    'relora_serve_tpot_seconds_bucket{le="30"} 1\n'
    'relora_serve_tpot_seconds_bucket{le="+Inf"} 1\n'
    'relora_serve_tpot_seconds_sum 0.020000\n'
    'relora_serve_tpot_seconds_count 1\n'
    '# TYPE relora_serve_ttft_seconds histogram\n'
    'relora_serve_ttft_seconds_bucket{le="0.001"} 0\n'
    'relora_serve_ttft_seconds_bucket{le="0.0025"} 0\n'
    'relora_serve_ttft_seconds_bucket{le="0.005"} 1\n'
    'relora_serve_ttft_seconds_bucket{le="0.01"} 1\n'
    'relora_serve_ttft_seconds_bucket{le="0.025"} 2\n'
    'relora_serve_ttft_seconds_bucket{le="0.05"} 2\n'
    'relora_serve_ttft_seconds_bucket{le="0.1"} 2\n'
    'relora_serve_ttft_seconds_bucket{le="0.25"} 2\n'
    'relora_serve_ttft_seconds_bucket{le="0.5"} 3\n'
    'relora_serve_ttft_seconds_bucket{le="1"} 3\n'
    'relora_serve_ttft_seconds_bucket{le="2.5"} 4\n'
    'relora_serve_ttft_seconds_bucket{le="5"} 4\n'
    'relora_serve_ttft_seconds_bucket{le="10"} 4\n'
    'relora_serve_ttft_seconds_bucket{le="30"} 4\n'
    'relora_serve_ttft_seconds_bucket{le="+Inf"} 5\n'
    'relora_serve_ttft_seconds_sum 33.321000\n'
    'relora_serve_ttft_seconds_count 5\n'
)


def _populated_serve_metrics():
    # deferred import: pulls in the serve stack (jax) only for the tests
    # that pin the ServeMetrics subclass specifically
    from relora_tpu.serve.admission import ServeMetrics

    m = ServeMetrics()
    m.inc("http_requests_total", ("route", "generate"))
    m.inc("http_requests_total", ("route", "generate"))
    m.inc("http_requests_total", ("route", "healthz"))
    m.inc("tokens_generated_total", by=7)
    m.inc("rejected_total", ("reason", "queue_full"))
    m.inc("requests_finished_total", ("reason", "length"), by=2)
    m.set_gauge("draining", 0)
    m.set_gauge("queue_depth", 3)
    m.set_gauge("active_slots", 2.0)
    for v in (0.004, 0.017, 0.3, 2.0, 31.0):
        m.observe("ttft_seconds", v)
    m.observe("tpot_seconds", 0.02)
    return m


def test_serve_metrics_render_byte_identical_golden():
    assert _populated_serve_metrics().render() == GOLDEN_RENDER


def test_serve_metrics_snapshot_golden():
    assert _populated_serve_metrics().snapshot() == {
        "http_requests_total.generate": 2,
        "http_requests_total.healthz": 1,
        "rejected_total.queue_full": 1,
        "requests_finished_total.length": 2,
        "tokens_generated_total": 7,
        "draining": 0,
        "queue_depth": 3,
        "active_slots": 2.0,
        "ttft_seconds_count": 5,
        "ttft_seconds_sum": 33.321,
        "tpot_seconds_count": 1,
        "tpot_seconds_sum": 0.02,
    }


def test_registry_namespace_and_accessors():
    r = MetricsRegistry(namespace="relora_train")
    r.set_gauge("mfu", 0.42)
    r.inc("steps_total")
    r.observe("metric_pull_seconds", 0.003)
    assert "relora_train_mfu 0.42" in r.render()
    assert r.gauge_value("mfu") == 0.42
    assert r.counter_value("steps_total") == 1
    assert r.histogram("metric_pull_seconds").count == 1
    assert r.histogram("missing") is None


def test_histogram_quantile():
    h = Histogram()
    for v in (0.004, 0.004, 0.004, 0.09, 2.0):
        h.observe(v)
    # p50 of 5 samples lands in the 0.005 bucket; p95 in the 2.5 bucket
    assert h.quantile(0.5) == 0.005
    assert h.quantile(0.95) == 2.5
    assert Histogram().quantile(0.5) == 0.0
    h2 = Histogram()
    h2.observe(100.0)  # beyond the last bound -> +Inf bucket
    assert h2.quantile(0.5) == float("inf")
    assert h.bounds == LATENCY_BUCKETS


# ---------------------------------------------------------------------------
# tracer


def test_span_nesting_builds_a_tree():
    rec = FlightRecorder()
    tr = Tracer(service="t", recorder=rec)
    with tr.span("root", kind="test") as root:
        with tr.span("child_a"):
            with tr.span("grandchild"):
                pass
        with tr.span("child_b"):
            pass
    spans = {s["name"]: s for s in rec.spans()}
    assert set(spans) == {"root", "child_a", "grandchild", "child_b"}
    assert spans["root"]["parent_id"] is None
    assert spans["child_a"]["parent_id"] == spans["root"]["span_id"]
    assert spans["child_b"]["parent_id"] == spans["root"]["span_id"]
    assert spans["grandchild"]["parent_id"] == spans["child_a"]["span_id"]
    # one trace id for the whole tree; attrs and durations recorded
    assert len({s["trace_id"] for s in spans.values()}) == 1
    assert spans["root"]["attrs"] == {"kind": "test"}
    assert all(s["dur_s"] >= 0 for s in spans.values())
    assert root.t_end is not None
    assert tr.current_span() is None  # stack fully unwound


def test_span_end_is_idempotent_and_set_chains():
    rec = FlightRecorder()
    tr = Tracer(service="t", recorder=rec)
    sp = tr.start_span("manual", uid=1)
    d1 = sp.set(outcome="ok").end()
    d2 = sp.end()
    assert d1 == d2
    assert len(rec.spans()) == 1  # recorded exactly once
    assert rec.spans()[0]["attrs"] == {"uid": 1, "outcome": "ok"}


def test_cross_thread_span_with_explicit_parent():
    """The serving pattern: a root span starts on one thread, children are
    attached from another thread via explicit parent= (never the ambient
    stack, which is thread-local)."""
    rec = FlightRecorder()
    tr = Tracer(service="t", recorder=rec)
    rid = new_trace_id()
    root = tr.start_span("request", trace_id=rid, uid=7)

    def worker():
        child = tr.start_span("phase", trace_id=rid, parent=root)
        child.end()

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    root.end()
    spans = {s["name"]: s for s in rec.spans()}
    assert spans["phase"]["parent_id"] == spans["request"]["span_id"]
    assert spans["phase"]["trace_id"] == rid == spans["request"]["trace_id"]
    assert spans["phase"]["thread"] != spans["request"]["thread"]


def test_exception_inside_span_still_records_and_unwinds():
    rec = FlightRecorder()
    tr = Tracer(service="t", recorder=rec)
    with pytest.raises(ValueError):
        with tr.span("outer"):
            with tr.span("inner"):
                raise ValueError("boom")
    assert {s["name"] for s in rec.spans()} == {"outer", "inner"}
    assert tr.current_span() is None


def test_tracer_jsonl_sink(tmp_path):
    path = tmp_path / "spans.jsonl"
    tr = Tracer(service="t", recorder=FlightRecorder(), jsonl_path=str(path))
    with tr.span("a"):
        pass
    tr.event("tick")  # events go to the sink too, tagged so span readers can skip them
    with tr.span("b"):
        pass
    tr.close()
    lines = [json.loads(line) for line in path.read_text().splitlines()]
    assert [rec["name"] for rec in lines] == ["a", "tick", "b"]
    assert lines[1]["_event"] is True
    assert [rec["name"] for rec in lines if not rec.get("_event")] == ["a", "b"]
    with tr.span("after_close"):  # close() drops the sink, not the tracer
        pass
    assert len(path.read_text().splitlines()) == 3


def test_noop_tracer_is_api_compatible():
    tr = NoopTracer()
    with tr.span("x", attr=1) as sp:
        assert sp.end() == 0.0
        assert sp.set(foo="bar") is sp
    sp = tr.start_span("y")
    sp.end()
    tr.event("e")
    tr.close()
    assert tr.current_span() is None
    assert tr.enabled is False


def test_chrome_trace_export():
    rec = FlightRecorder()
    tr = Tracer(service="svc", recorder=rec)
    with tr.span("step", n=3):
        time.sleep(0.001)
    tr.event("marker", note="hi")
    events = chrome_trace_events(rec.spans(), rec.events(), pid=42)
    by_ph = {}
    for e in events:
        by_ph.setdefault(e["ph"], []).append(e)
    (x,) = by_ph["X"]
    assert x["name"] == "step" and x["cat"] == "svc" and x["pid"] == 42
    assert x["dur"] >= 1000  # microseconds
    assert x["args"]["n"] == 3
    (i,) = by_ph["i"]
    assert i["name"] == "marker" and i["args"]["note"] == "hi"
    assert by_ph["M"][0]["args"]["name"]  # thread_name metadata present


# ---------------------------------------------------------------------------
# flight recorder


def test_flight_ring_buffer_bounds_and_dump(tmp_path):
    rec = FlightRecorder(span_capacity=4, event_capacity=2)
    for i in range(7):
        rec.add_span({"name": f"s{i}", "trace_id": "t", "span_id": str(i)})
    rec.add_event({"name": "e"})
    assert [s["name"] for s in rec.spans()] == ["s3", "s4", "s5", "s6"]
    assert rec.dropped_spans == 3
    path = rec.dump(str(tmp_path / "d" / "flight.json"), reason="drill")
    payload = json.loads(open(path).read())
    assert payload["reason"] == "drill"
    assert payload["pid"] == os.getpid()
    assert payload["dropped_spans"] == 3
    assert len(payload["spans"]) == 4 and len(payload["events"]) == 1
    rec.clear()
    assert rec.spans() == [] and rec.dropped_spans == 0


def test_dump_on_fault_env_dir_and_empty_buffer(tmp_path, monkeypatch):
    from relora_tpu.obs import flight

    monkeypatch.setenv("RELORA_TPU_FLIGHT_DIR", str(tmp_path))
    flight.default_recorder().clear()
    assert dump_on_fault("nothing_recorded") is None  # empty buffer -> no file
    Tracer(service="t").start_span("s").end()  # default recorder
    path = dump_on_fault("drill")
    assert path == str(tmp_path / f"flight_drill_{os.getpid()}.json")
    assert json.loads(open(path).read())["reason"] == "drill"
    flight.default_recorder().clear()


# ---------------------------------------------------------------------------
# MFU helpers


class _FakeDevice:
    def __init__(self, kind):
        self.device_kind = kind


def test_peak_flops_table_and_env_override(monkeypatch):
    monkeypatch.delenv("RELORA_TPU_PEAK_FLOPS", raising=False)
    assert peak_flops(_FakeDevice("TPU v5e")) == 197e12
    assert peak_flops(_FakeDevice("TPU v5p chip")) == 459e12
    assert peak_flops(_FakeDevice("TPU v6e")) == 918e12
    assert peak_flops(_FakeDevice("TPU v4")) == 275e12
    assert peak_flops(_FakeDevice("NVIDIA H100 80GB")) == 989e12
    assert peak_flops(_FakeDevice("cpu")) == PEAK_FLOPS_DEFAULT
    monkeypatch.setenv("RELORA_TPU_PEAK_FLOPS", "123e12")
    assert peak_flops(_FakeDevice("TPU v5e")) == 123e12  # override wins


def test_step_flops_from_cost_analysis_shapes():
    assert step_flops_from_cost_analysis({"flops": 5.0}) == 5.0
    assert step_flops_from_cost_analysis([{"flops": 2.0}, {"flops": 3.0}]) == 5.0
    assert step_flops_from_cost_analysis(None) is None
    assert step_flops_from_cost_analysis({}) is None
    assert step_flops_from_cost_analysis([{"flops": 0.0}]) is None
    assert step_flops_from_cost_analysis([{"bytes": 1}, "junk"]) is None


def test_benchlib_peak_flops_alias():
    # importers of the old constant keep working, and it matches the table
    from relora_tpu.utils.benchlib import PEAK_FLOPS_V5E

    assert PEAK_FLOPS_V5E == PEAK_FLOPS_DEFAULT == 197e12


# ---------------------------------------------------------------------------
# trace report tool


def test_trace_report_renders_dump_and_chrome_export(tmp_path):
    rec = FlightRecorder()
    tr = Tracer(service="train", recorder=rec)
    for step in range(2):
        with tr.span("update_step", step=step):
            with tr.span("data_fetch"):
                pass
            with tr.span("dispatch", step=step):
                time.sleep(0.002)
    dump = rec.dump(str(tmp_path / "flight_manual_1.json"), reason="manual")
    chrome = tmp_path / "chrome.json"
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trace_report.py"), dump,
         "--chrome", str(chrome)],
        capture_output=True, text=True, check=True, cwd=str(tmp_path),
    ).stdout
    assert "reason=manual" in out
    assert "update_step" in out and "dispatch" in out and "data_fetch" in out
    assert "p50_ms" in out and "p95_ms" in out
    events = json.loads(chrome.read_text())["traceEvents"]
    assert any(e["ph"] == "X" and e["name"] == "dispatch" for e in events)


def test_trace_report_reads_jsonl_stream(tmp_path):
    path = tmp_path / "spans.jsonl"
    tr = Tracer(service="t", recorder=FlightRecorder(), jsonl_path=str(path))
    with tr.span("outer"):
        with tr.span("inner"):
            pass
    tr.close()
    with open(path, "a") as fh:
        fh.write('{"torn line')  # killed writer leaves a torn tail: tolerated
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trace_report.py"), str(path)],
        capture_output=True, text=True, check=True,
    ).stdout
    assert "outer" in out and "inner" in out


# ---------------------------------------------------------------------------
# MFU edge cases: the 6ND fallback path and peak-FLOPs resolution corners


def test_peak_flops_device_without_kind_and_env_precedence(monkeypatch):
    monkeypatch.delenv("RELORA_TPU_PEAK_FLOPS", raising=False)
    # a device object with no device_kind attribute at all -> default
    assert peak_flops(object()) == PEAK_FLOPS_DEFAULT
    assert peak_flops(_FakeDevice("")) == PEAK_FLOPS_DEFAULT
    assert peak_flops(_FakeDevice("made-up accelerator 9000")) == PEAK_FLOPS_DEFAULT
    # the env override wins over everything, including unknown kinds
    monkeypatch.setenv("RELORA_TPU_PEAK_FLOPS", "42e12")
    assert peak_flops(object()) == 42e12
    assert peak_flops(None) == 42e12


def test_step_flops_from_cost_analysis_hostile_inputs():
    # non-iterable / wrong-typed cost objects must signal fallback, not raise
    assert step_flops_from_cost_analysis(42) is None
    assert step_flops_from_cost_analysis("flops") is None
    assert step_flops_from_cost_analysis([{"flops": "NaN-ish"}]) is None
    assert step_flops_from_cost_analysis([None, {"flops": 7.0}]) == 7.0


def test_trainer_measure_step_flops_falls_back_to_6nd_when_lower_raises():
    """When lowering/cost_analysis blows up, _measure_step_flops returns None
    (the live-MFU gauge then uses the 6ND analytic estimate) instead of
    failing the run."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    from relora_tpu.train.trainer import Trainer

    class BadStep:
        def lower(self, *a, **k):
            raise RuntimeError("backend exploded")

    tr = Trainer.__new__(Trainer)  # no __init__: only the fields the method reads
    tr.mesh = Mesh(np.array(jax.devices()).reshape(-1), ("dp",))
    tr._train_step = BadStep()
    tr.state = {"params": np.ones((2,), np.float32)}
    assert tr._measure_step_flops(np.zeros((1, 2, 4), np.int32), jax.random.PRNGKey(0)) is None


def test_trainer_measure_step_flops_honors_live_mfu_kill_switch(monkeypatch):
    from relora_tpu.train.trainer import Trainer

    monkeypatch.setenv("RELORA_TPU_LIVE_MFU", "0")
    tr = Trainer.__new__(Trainer)  # the kill switch returns before any field use
    assert tr._measure_step_flops(None, None) is None
