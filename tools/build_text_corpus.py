"""Build a pretraining corpus (BPE tokenizer + mmap token dataset) from
local text trees.

For air-gapped environments with no HF hub access: harvests text files
(.py/.md/.rst/.txt) from the given roots, trains a byte-level BPE tokenizer
on them, and writes the token stream to the framework's mmap ``.idx``/``.bin``
format (data/memmap.py), one document per file.  The result feeds the
megatron data path (``--megatron_dataset_config``) exactly like a
pretokenized C4/Pile dump would.

Usage::

    python tools/build_text_corpus.py --out /tmp/corpus \
        --roots /opt/venv/lib/python3.12/site-packages /usr/share/doc \
        --vocab-size 32100 --max-mb 400
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

TEXT_EXT = (".py", ".md", ".rst", ".txt")


def harvest(roots, max_bytes, min_size=256, max_file=2_000_000):
    """Yield (path, text) for qualifying files, capped at max_bytes total.

    Files are shuffled (seeded) so the cap doesn't bias the corpus toward
    whichever root sorts first.
    """
    paths = []
    for root in roots:
        for dirpath, dirnames, files in os.walk(root):
            dirnames[:] = [d for d in dirnames if d not in ("__pycache__", ".git", "node_modules")]
            for f in files:
                if f.endswith(TEXT_EXT):
                    paths.append(os.path.join(dirpath, f))
    random.Random(0).shuffle(paths)
    total = 0
    for p in paths:
        try:
            size = os.path.getsize(p)
            if size < min_size or size > max_file:
                continue
            with open(p, "r", encoding="utf-8", errors="strict") as fh:
                text = fh.read()
        except (OSError, UnicodeDecodeError):
            continue
        total += len(text)
        yield p, text
        if total >= max_bytes:
            return


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", required=True, help="output prefix (writes <out>.idx/.bin + <out>.tokenizer.json)")
    ap.add_argument("--roots", nargs="+", required=True)
    ap.add_argument("--vocab-size", type=int, default=32100)
    ap.add_argument("--max-mb", type=float, default=400.0)
    args = ap.parse_args()

    from tokenizers import Tokenizer, models, pre_tokenizers, decoders, trainers

    from relora_tpu.data.memmap import MemmapTokenWriter, best_dtype

    os.makedirs(os.path.dirname(os.path.abspath(args.out)), exist_ok=True)
    max_bytes = int(args.max_mb * 1e6)

    print("pass 1: training byte-level BPE tokenizer ...", flush=True)
    tok = Tokenizer(models.BPE())
    tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
    tok.decoder = decoders.ByteLevel()
    trainer = trainers.BpeTrainer(
        vocab_size=args.vocab_size,
        special_tokens=["<pad>", "<eos>"],
        initial_alphabet=pre_tokenizers.ByteLevel.alphabet(),
    )
    tok.train_from_iterator(
        (text for _, text in harvest(args.roots, max_bytes)), trainer=trainer
    )
    tok.save(f"{args.out}.tokenizer.json")
    eos_id = tok.token_to_id("<eos>")

    print("pass 2: tokenizing into mmap dataset ...", flush=True)
    n_docs = 0
    n_tokens = 0
    with MemmapTokenWriter(args.out, dtype=best_dtype(args.vocab_size)) as w:
        batch = []

        def flush():
            nonlocal n_docs, n_tokens
            for enc in tok.encode_batch(batch):
                ids = enc.ids + [eos_id]
                w.add_document(ids)
                n_docs += 1
                n_tokens += len(ids)
            batch.clear()

        for _, text in harvest(args.roots, max_bytes):
            batch.append(text)
            if len(batch) >= 256:
                flush()
        if batch:
            flush()

    with open(f"{args.out}.meta.json", "w") as fh:
        json.dump(
            {
                "vocab_size": args.vocab_size,
                "eos_id": eos_id,
                "n_docs": n_docs,
                "n_tokens": n_tokens,
                "roots": args.roots,
                "max_mb": args.max_mb,
            },
            fh,
            indent=2,
        )
    print(f"done: {n_docs} docs, {n_tokens/1e6:.1f}M tokens -> {args.out}.idx/.bin")


if __name__ == "__main__":
    main()
