"""Test configuration: run everything on CPU with 8 virtual devices.

Multi-device sharding logic is testable without TPU hardware via XLA's host
platform device-count override — set before jax is first imported.
"""

import os

# Force, don't setdefault: the sandbox exports JAX_PLATFORMS=axon (the real
# TPU) and a sitecustomize re-asserts it, which would silently run the whole
# suite on the TPU tunnel.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: OFF by default (operator-facing writeup:
# docs/operations.md §9 "Troubleshooting").  On this jaxlib (0.4.37,
# CPU backend) executables deserialized from the persistent cache corrupt
# the heap when combined with donate_argnums — runs that resume a second
# Trainer in the same process die with "double free or corruption" / NaN
# garbage in restored state (reproducible with any cache settings; clean
# with the cache disabled).  Opt back in on a fixed jaxlib with
# RELORA_TPU_TEST_COMPILE_CACHE=1.
if os.environ.get("RELORA_TPU_TEST_COMPILE_CACHE", "0") == "1":
    jax.config.update("jax_compilation_cache_dir", "/tmp/jax_test_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
else:
    # The in-process benches (utils/benchlib.py) call enable_compile_cache(),
    # which would re-enable the persistent cache mid-suite and corrupt later
    # donate_argnums programs the same way; default its env knob off here.
    # Tests that exercise the knob monkeypatch the env var explicitly.
    os.environ.setdefault("RELORA_TPU_COMPILE_CACHE", "0")

# The trainer's static HBM plan (obs/memory.plan_for) pays a duplicate AOT
# compile of the train step — harmless in real runs, but it would double the
# compile cost of every Trainer-constructing test.  Default it off; the perf
# attribution integration test monkeypatches it back on.
os.environ.setdefault("RELORA_TPU_MEM_PLAN", "0")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs
