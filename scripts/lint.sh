#!/usr/bin/env bash
# Static-analysis gate: the RTL footgun linter over the package.
# Stdlib-only (no jax import), so it runs in any bare Python.
#
#   scripts/lint.sh            # lint relora_tpu/ against the baseline
#   scripts/lint.sh path ...   # lint specific files/dirs
set -euo pipefail
cd "$(dirname "$0")/.."

exec python -m relora_tpu.analysis "$@"
