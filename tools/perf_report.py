#!/usr/bin/env python
"""One performance-attribution report: metrics.jsonl + traces + BENCH files.

Joins the three telemetry streams the obs layer produces into the answer to
"where is the MFU going":

1. **MFU-gap waterfall** — the trainer's per-flush ``mfu_gap/*`` records:
   data_fetch / dispatch / compute / host shares of wall time (they sum to
   ~100% by construction), averaged over the run.
2. **HBM plan** — ``memory_plan`` events: the per-pytree breakdown (params /
   opt_state), XLA's static plan for the compiled train step, and the
   plan-vs-live-peak reconciliation where the backend keeps allocator stats.
3. **Compile telemetry** — ``compile`` events: per-function compile counts,
   expected vs steady-state retraces (the number that should be zero), and
   the signature diff of any retrace.
4. **Serving utilization** — ``serve/batch_fill`` and prefill-stall share
   when the run dir came from the scheduler; paged runs add KV-pool pressure
   (``serve/kv_pages_used``/``free``), prefix-cache hit rate, the
   chunked-prefill padding share, and dispatch economics (dispatches per
   round, tokens per dispatch, packed-token utilization).
5. **Span phases** — p50/p95 per phase from a ``train_spans.jsonl`` stream
   (``--traces``, or auto-detected next to the run dir).
6. **BENCH trajectory** — committed ``BENCH_*.json`` context (``--bench-dir``).

    python tools/perf_report.py ckpts/run
    python tools/perf_report.py ckpts/run --traces traces/train_spans.jsonl
    python tools/perf_report.py ckpts/run --assert-no-retraces   # CI gate
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from pathlib import Path
from typing import Any, Dict, List, Optional

# runnable from any cwd without an installed package
sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

BAR_WIDTH = 40


def load_jsonl(path: str) -> List[Dict[str, Any]]:
    records = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                continue  # torn tail line from a killed writer
    return records


def fmt_bytes(n: Optional[float]) -> str:
    if n is None:
        return "n/a"
    for unit, div in (("GB", 1e9), ("MB", 1e6), ("KB", 1e3)):
        if abs(n) >= div:
            return f"{n / div:.2f} {unit}"
    return f"{int(n)} B"


def percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def mean(vals: List[float]) -> float:
    return sum(vals) / len(vals) if vals else 0.0


def print_waterfall(records: List[Dict[str, Any]], out) -> bool:
    gaps = [r for r in records if "mfu_gap/wall_s" in r]
    if not gaps:
        out.write("\nMFU-gap waterfall: no mfu_gap records in metrics.jsonl\n")
        return False
    shares = {
        key: mean([g.get(f"mfu_gap/{key}", 0.0) for g in gaps])
        for key in ("data_fetch", "dispatch", "compute", "comms", "host")
    }
    total_wall = sum(g["mfu_gap/wall_s"] for g in gaps)
    n_steps = sum(int(g.get("mfu_gap/window_steps", 0)) for g in gaps)
    out.write(
        f"\nMFU-gap waterfall  ({len(gaps)} windows, {n_steps} steps, "
        f"{total_wall:.1f}s wall)\n"
    )
    for key, share in shares.items():
        bar = "#" * max(0, round(share * BAR_WIDTH))
        out.write(f"  {key:<12} {share * 100:6.1f}%  {bar}\n")
    out.write(f"  {'sum':<12} {sum(shares.values()) * 100:6.1f}%\n")
    return True


def print_memory(records: List[Dict[str, Any]], out) -> None:
    plans = [r for r in records if r.get("_event") == "memory_plan"]
    if not plans:
        out.write("\nHBM plan: no memory_plan events\n")
        return
    out.write("\nHBM plan\n")
    for plan in plans:
        if plan.get("source") == "pytree":
            out.write("  per-pytree (resident state):\n")
            for key in sorted(plan):
                if key.endswith("_bytes") and not key.startswith("live_"):
                    name = key[: -len("_bytes")]
                    out.write(f"    {name:<12} {fmt_bytes(plan[key]):>12}\n")
        else:
            out.write(f"  XLA static plan ({plan.get('source', '?')}):\n")
            for key in (
                "argument_bytes",
                "output_bytes",
                "temp_bytes",
                "alias_bytes",
                "generated_code_bytes",
                "plan_total_bytes",
            ):
                if key in plan:
                    name = key[: -len("_bytes")]
                    out.write(f"    {name:<16} {fmt_bytes(plan[key]):>12}\n")
            if plan.get("live_peak_bytes") is not None:
                out.write(
                    f"    live peak        {fmt_bytes(plan['live_peak_bytes']):>12}"
                    f"  (live/plan = {plan.get('live_vs_plan')})\n"
                )
            else:
                out.write("    live peak                 n/a  (backend keeps no allocator stats)\n")


def print_compiles(records: List[Dict[str, Any]], out) -> int:
    compiles = [r for r in records if r.get("_event") == "compile"]
    gaps = [r for r in records if "compile/steady_state_retraces" in r]
    retraces = [c for c in compiles if not c.get("expected")]
    n_retraces = len(retraces)
    if gaps:  # the counter in the last record is authoritative for the run
        n_retraces = max(n_retraces, int(gaps[-1]["compile/steady_state_retraces"]))
    out.write("\nCompile telemetry\n")
    if compiles:
        by_fn: Dict[str, List[Dict[str, Any]]] = {}
        for c in compiles:
            by_fn.setdefault(c.get("fn", "?"), []).append(c)
        out.write(f"  {'fn':<16} {'compiles':>8} {'expected':>9} {'total_s':>9}\n")
        for fn, evs in sorted(by_fn.items()):
            out.write(
                f"  {fn:<16} {len(evs):>8} {sum(bool(e.get('expected')) for e in evs):>9} "
                f"{sum(e.get('duration_s', 0.0) for e in evs):>9.2f}\n"
            )
        for c in retraces:
            out.write(f"  RETRACE {c.get('fn')}: {'; '.join(c.get('changed') or [])}\n")
    else:
        out.write("  no compile events recorded\n")
    out.write(f"  steady-state retraces: {n_retraces}\n")
    return n_retraces


def print_train_summary(records: List[Dict[str, Any]], out) -> None:
    steps = [r for r in records if "loss" in r and "update_step" in r]
    if not steps:
        return
    mfus = [r["mfu"] for r in steps if isinstance(r.get("mfu"), (int, float))]
    toks = [
        r["throughput_tokens"]
        for r in steps
        if isinstance(r.get("throughput_tokens"), (int, float))
    ]
    out.write(
        f"\nTraining  ({len(steps)} updates)  loss {steps[-1]['loss']:.4f}"
        f"  mean mfu {mean(mfus):.4f}  mean tok/s {mean(toks):.1f}\n"
    )


def print_serving(records: List[Dict[str, Any]], out) -> None:
    steps = [r for r in records if "serve/batch_fill" in r]
    if not steps:
        return
    fills = [r["serve/batch_fill"] for r in steps]
    stalls = [r.get("serve/prefill_stall_share", 0.0) for r in steps]
    out.write(
        f"\nServing utilization  ({len(steps)} decode steps)\n"
        f"  batch fill      mean {mean(fills) * 100:5.1f}%  min {min(fills) * 100:5.1f}%"
        f"  max {max(fills) * 100:5.1f}%\n"
        f"  prefill stall   mean {mean(stalls) * 100:5.1f}% of step time\n"
    )
    # dispatch economics: the ratios are cumulative-over-the-run gauges, so
    # the last record carries the run's answer (1.00/round = fully packed)
    disp_steps = [r for r in steps if "serve/dispatches_per_round" in r]
    if disp_steps:
        last = disp_steps[-1]
        out.write(
            f"  dispatches      {last['serve/dispatches_per_round']:.2f} per round"
            f"  {last.get('serve/tokens_per_dispatch', 0.0):.1f} tokens each"
            f"  ({last.get('serve/packed_token_utilization', 0.0) * 100:.1f}% real)\n"
        )
    _print_adapters(steps, out)
    # paged-KV pool pressure (PagedContinuousBatchingScheduler runs only)
    paged_steps = [r for r in steps if "serve/kv_pages_used" in r]
    if not paged_steps:
        return
    used = [r["serve/kv_pages_used"] for r in paged_steps]
    free = [r["serve/kv_pages_free"] for r in paged_steps]
    total = used[-1] + free[-1]
    pads = [r.get("serve/prefill_pad_share", 0.0) for r in paged_steps]
    # hit rate is cumulative: the last record is the run's rate
    hit_rate = paged_steps[-1].get("serve/prefix_cache_hit_rate", 0.0)
    out.write(
        f"  kv pages        mean {mean(used):7.1f} used  peak {max(used)} "
        f"of {total}  (min free {min(free)})\n"
        f"  prefix cache    hit rate {hit_rate * 100:5.1f}%\n"
        f"  prefill pad     {pads[-1] * 100:5.1f}% of chunked prefill tokens\n"
    )
    # pool HBM footprint (static per engine; int8 pools report ~1 byte/elem
    # of cache plus per-page scales vs 2 for bf16)
    pool = paged_steps[-1].get("serve/kv_cache_bytes")
    per_tok = paged_steps[-1].get("serve/kv_bytes_per_token")
    if pool is not None:
        out.write(
            f"  kv pool         {fmt_bytes(pool)} resident"
            f"  ({fmt_bytes(per_tok)}/token across layers)\n"
        )
    # speculative decoding (--spec runs only): counters are cumulative, so
    # the last record carries the run totals
    spec_steps = [r for r in paged_steps if "serve/spec_drafted_total" in r]
    if spec_steps:
        last = spec_steps[-1]
        out.write(
            f"  speculative     accept rate {last.get('serve/spec_accept_rate', 0.0) * 100:5.1f}%"
            f"  ({last.get('serve/spec_accepted_total', 0)}/"
            f"{last.get('serve/spec_drafted_total', 0)} drafted tokens accepted)\n"
        )


def _print_adapters(steps: List[Dict[str, Any]], out) -> None:
    """Multi-tenant adapter pressure (--adapter-dir runs only).  Evictions
    are cumulative and hit rate is lifetime, so the last record carries the
    run totals; slot occupancy is a gauge worth averaging."""
    adapter_steps = [r for r in steps if "serve/adapter_slots_used" in r]
    if not adapter_steps:
        return
    used = [r["serve/adapter_slots_used"] for r in adapter_steps]
    last = adapter_steps[-1]
    evictions = last.get("serve/adapter_evictions_total", 0)
    hit_rate = last.get("serve/adapter_hit_rate", 0.0)
    thrash = "  <- slot thrash: raise --adapter-slots" if evictions > 2 * max(used) else ""
    out.write(
        f"  adapter slots   mean {mean(used):5.1f} used  peak {max(used):.0f}\n"
        f"  adapter churn   {evictions:.0f} evictions  hit rate {hit_rate * 100:5.1f}%{thrash}\n"
    )


def print_phases(trace_path: str, out) -> None:
    spans = [s for s in load_jsonl(trace_path) if s.get("dur_s") is not None]
    if not spans:
        return
    by_name: Dict[str, List[float]] = {}
    for s in spans:
        by_name.setdefault(s.get("name", "?"), []).append(s["dur_s"])
    out.write(f"\nSpan phases  ({trace_path})\n")
    out.write(f"  {'phase':<16} {'count':>6} {'p50_ms':>9} {'p95_ms':>9}\n")
    for name, vals in sorted(by_name.items(), key=lambda kv: -sum(kv[1])):
        vals.sort()
        out.write(
            f"  {name:<16} {len(vals):>6} {percentile(vals, 0.5) * 1e3:>9.2f} "
            f"{percentile(vals, 0.95) * 1e3:>9.2f}\n"
        )


def print_bench(bench_dir: str, out) -> None:
    rounds = []
    for path in sorted(glob.glob(os.path.join(bench_dir, "BENCH_r[0-9]*.json"))):
        try:
            with open(path) as fh:
                doc = json.load(fh)
        except (OSError, json.JSONDecodeError):
            continue
        value = (doc.get("parsed") or {}).get("value")
        if value:
            rounds.append((doc.get("n"), value, (doc.get("parsed") or {}).get("detail") or {}))
    if not rounds:
        return
    out.write("\nBENCH trajectory (train tok/s)\n")
    for n, value, detail in rounds:
        mfu = detail.get("mfu")
        out.write(
            f"  round {n}: {value:,.1f} tok/s"
            + (f"  mfu {mfu:.4f}" if isinstance(mfu, (int, float)) else "")
            + ("  [stale]" if detail.get("stale") else "")
            + "\n"
        )
    obs_path = os.path.join(bench_dir, "BENCH_obs.json")
    if os.path.exists(obs_path):
        with open(obs_path) as fh:
            obs = json.load(fh)
        out.write(
            f"  obs overhead: {obs.get('value')}% of step time "
            f"(budget {((obs.get('detail') or {}).get('budget_pct'))}%)\n"
        )


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("run_dir", help="run dir containing metrics.jsonl (or the file itself)")
    ap.add_argument("--traces", help="train_spans.jsonl stream (default: autodetect)")
    ap.add_argument(
        "--bench-dir",
        default=str(Path(__file__).resolve().parents[1]),
        help="directory with BENCH_*.json (default: repo root); '' disables",
    )
    ap.add_argument(
        "--assert-no-retraces",
        action="store_true",
        help="exit 1 when any steady-state retrace was recorded (smoke/CI)",
    )
    args = ap.parse_args(argv)

    metrics_path = args.run_dir
    if os.path.isdir(metrics_path):
        metrics_path = os.path.join(metrics_path, "metrics.jsonl")
    if not os.path.exists(metrics_path):
        print(f"no metrics.jsonl at {metrics_path}", file=sys.stderr)
        return 2
    records = load_jsonl(metrics_path)
    out = sys.stdout
    out.write(f"perf attribution: {metrics_path}  ({len(records)} records)\n")

    print_train_summary(records, out)
    print_waterfall(records, out)
    print_memory(records, out)
    n_retraces = print_compiles(records, out)
    print_serving(records, out)

    trace_path = args.traces
    if trace_path is None:
        candidate = os.path.join(os.path.dirname(metrics_path), "train_spans.jsonl")
        trace_path = candidate if os.path.exists(candidate) else None
    if trace_path and os.path.exists(trace_path):
        print_phases(trace_path, out)

    if args.bench_dir and os.path.isdir(args.bench_dir):
        print_bench(args.bench_dir, out)

    if args.assert_no_retraces and n_retraces > 0:
        out.write(f"\nFAIL: {n_retraces} steady-state retraces (expected 0)\n")
        return 1
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:
        # downstream closed early (`| head`): not an error; silence the
        # interpreter's exit-time flush of the dead pipe
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        sys.exit(0)
