from relora_tpu.utils.logging import get_logger, metrics_logger, set_process_index
