"""Fleet observability plane: collector, SLO burn alerts, cross-process traces.

Layers, cheapest first:

- **Unit**: Prometheus-text parsing round-trips the repo's own exposition
  renderer; cumulative-bucket quantiles match ``Histogram.quantile``; the
  SeriesStore's retention, JSONL persistence/rotation, and torn-tail
  recovery.
- **Fake replicas**: the FleetCollector scraping scriptable ``/healthz`` +
  ``/metrics`` stubs — derived rate/error/percentile series, health-flip
  events, trainer-JSONL tailing, the ``/fleet/*`` route payloads.
- **SLO engine**: multi-window burn-rate fire -> clear lifecycle on
  synthetic series (events into store AND flight recorder), and the
  anomaly path firing exactly where a bare ``LossSpikeDetector`` fires on
  the same series.
- **Acceptance**: a real 2-replica ``serve.py --random-init`` fleet behind
  the Router with ``RELORA_TPU_TRACE_DIR`` set; the per-process span JSONLs
  merge (tools/trace_report.py) into ONE tree per request id containing the
  router's ``route`` span, the replica's ``request`` span, and model-thread
  spans — the cross-process trace-joining contract.
"""

import http.server
import json
import os
import subprocess
import sys
import threading
import time

import pytest

from relora_tpu.obs.fleet import (
    FleetCollector,
    SeriesStore,
    histogram_quantile,
    load_series_jsonl,
    parse_prometheus,
)
from relora_tpu.obs.metrics import MetricsRegistry
from relora_tpu.obs.slo import SLO, AnomalySpec, SLOEngine

pytestmark = [pytest.mark.fleet]

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- unit: exposition parsing -------------------------------------------------


def test_parse_prometheus_round_trips_own_renderer():
    """parse_prometheus inverts MetricsRegistry.render: plain and labelled
    counters (flattened to ``name.labelval``), gauges, and histograms with
    +Inf buckets, sum, and count."""
    reg = MetricsRegistry(namespace="relora_serve")
    reg.inc("requests_total", by=7)
    reg.inc("requests_finished_total", ("reason", "length"), by=9)
    reg.inc("requests_finished_total", ("reason", "error"), by=1)
    reg.set_gauge("queue_depth", 3)
    for v in (0.004, 0.004, 0.004, 0.004, 0.09):
        reg.observe("ttft_seconds", v)
    flat, hists = parse_prometheus(reg.render())
    assert flat["relora_serve_requests_total"] == 7.0
    assert flat["relora_serve_requests_finished_total.length"] == 9.0
    assert flat["relora_serve_requests_finished_total.error"] == 1.0
    assert flat["relora_serve_queue_depth"] == 3.0
    h = hists["relora_serve_ttft_seconds"]
    assert h["count"] == 5 and h["sum"] == pytest.approx(0.106)
    assert h["buckets"][-1][0] == float("inf") and h["buckets"][-1][1] == 5
    # quantile parity with the in-process Histogram on identical data
    hist = reg.histogram("ttft_seconds")
    assert histogram_quantile(h["buckets"], 0.50) == hist.quantile(0.50)
    assert histogram_quantile(h["buckets"], 0.95) == hist.quantile(0.95)


# -- unit: the series store ---------------------------------------------------


def test_series_store_retention_and_queries():
    store = SeriesStore(max_points=4)
    for i in range(10):
        store.add_samples("r0", {"up": float(i)}, t=100.0 + i, persist=False)
    pts = store.samples("r0", "up")
    assert len(pts) == 4  # ring retention
    assert [v for _, v in pts] == [6.0, 7.0, 8.0, 9.0]
    assert store.latest("r0", "up") == (109.0, 9.0)
    assert store.window_values("r0", "up", 2.5, now=109.0) == [7.0, 8.0, 9.0]
    assert store.sources() == ["r0"] and store.series_names("r0") == ["up"]


def test_series_store_persistence_rotation_and_torn_tail(tmp_path):
    """Records persist in the trainer's metrics.jsonl schema; the file
    rotates at the byte cap; reload skips a torn tail line but keeps the
    rotated predecessor's records."""
    path = str(tmp_path / "fleet_series.jsonl")
    store = SeriesStore(persist_path=path, persist_max_bytes=400)
    for i in range(12):
        store.add_samples("r0", {"up": 1.0, "queue": float(i)}, t=1000.0 + i)
    store.add_event("health_flip", "r0", t=1012.0, frm="ok", to="stuck")
    store.close()
    assert os.path.exists(path + ".1")  # rotation happened
    with open(path) as fh:
        first = json.loads(fh.readline())
    assert first["_source"] == "r0" and "_time" in first  # shared schema
    with open(path, "a") as fh:
        fh.write('{"up": 1.0, "_source": "r0", "_ti')  # torn tail
    fresh = SeriesStore()
    n = load_series_jsonl(fresh, path)
    assert n == 13  # 12 sample records + 1 event, torn line skipped
    assert len(fresh.samples("r0", "queue")) == 12  # rotated file included
    assert fresh.events(kinds=("health_flip",))[0]["to"] == "stuck"


# -- fake replicas: the collector --------------------------------------------


class _ScrapeTarget:
    """A scriptable /healthz + /metrics endpoint standing in for one
    replica (or the router): tests flip ``healthy`` and rewrite
    ``metrics_text`` between collector rounds."""

    def __init__(self):
        self.healthy = True
        self.health_payload = {"status": "ok", "queue_depth": 2, "active_slots": 1}
        self.metrics_text = ""
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                if self.path == "/healthz":
                    if outer.healthy:
                        code, payload = 200, outer.health_payload
                    else:
                        code, payload = 503, {"status": "stuck"}
                    body = json.dumps(payload).encode()
                    ctype = "application/json"
                else:
                    code, body, ctype = 200, outer.metrics_text.encode(), "text/plain"
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):
                pass

        self.httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self.httpd.server_address[1]
        self._thread = threading.Thread(target=self.httpd.serve_forever, daemon=True)
        self._thread.start()

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()
        self._thread.join(5)


def _serve_metrics_text(finished_length=0, finished_error=0, ttfts=(), spec=None):
    reg = MetricsRegistry(namespace="relora_serve")
    if finished_length:
        reg.inc("requests_finished_total", ("reason", "length"), by=finished_length)
    if finished_error:
        reg.inc("requests_finished_total", ("reason", "error"), by=finished_error)
    for v in ttfts:
        reg.observe("ttft_seconds", v)
    if spec is not None:
        drafted, accepted = spec
        reg.inc("spec_drafted_total", by=drafted)
        reg.inc("spec_accepted_total", by=accepted)
    return reg.render()


def test_collector_derives_series_and_flip_events(tmp_path):
    """Two scripted replicas: scraped gauges land verbatim, counters grow
    ``_per_s`` rate series, finish-reason counters collapse into
    ``error_rate``, histograms become p50/p95, a 503 flip emits a
    health_flip event, and an unpublished port scores down."""
    a, b = _ScrapeTarget(), _ScrapeTarget()
    try:
        a.metrics_text = _serve_metrics_text(finished_length=10, ttfts=(0.004,) * 5)
        b.metrics_text = _serve_metrics_text(finished_length=5)
        eps = {"r0": ("127.0.0.1", a.port), "r1": ("127.0.0.1", b.port),
               "r2": ("127.0.0.1", None)}
        coll = FleetCollector(lambda: eps, persist_path=str(tmp_path / "f.jsonl"))
        ups = coll.scrape_once(now=1000.0)
        assert ups == {"r0": 1.0, "r1": 1.0, "r2": 0.0}
        assert coll.store.latest("r0", "healthz_queue_depth")[1] == 2.0
        assert coll.store.latest("r0", "relora_serve_ttft_seconds_p95")[1] > 0

        # round 2: r0 progressed (+10 done, +2 error), r1 went unhealthy
        a.metrics_text = _serve_metrics_text(
            finished_length=20, finished_error=2, ttfts=(0.004,) * 5
        )
        b.healthy = False
        coll.scrape_once(now=1002.0)
        per_s = coll.store.latest("r0", "relora_serve_requests_finished_total.length_per_s")
        assert per_s[1] == pytest.approx(5.0)  # +10 over 2s
        assert coll.store.latest("r0", "error_rate")[1] == pytest.approx(2.0 / 12.0)
        assert coll.store.latest("r1", "up")[1] == 0.0
        flips = coll.store.events(kinds=("health_flip",))
        assert [(e["_source"], e["frm"], e["to"]) for e in flips] == [
            ("r1", "ok", "stuck")
        ]

        # the collector's own exposition + the mounted /fleet/* routes
        rendered = coll.render_metrics()
        assert "relora_fleet_scrape_rounds_total 2" in rendered
        assert "relora_fleet_source_r1_up 0" in rendered
        status, ctype, body = coll.handle_fleet_route("/fleet/metrics")
        assert status == 200 and ctype.startswith("text/plain")
        status, ctype, body = coll.handle_fleet_route("/fleet/series?source=r0&series=up")
        payload = json.loads(body)
        assert [v for _, v in payload["sources"]["r0"]["up"]] == [1.0, 1.0]
        assert coll.handle_fleet_route("/not/fleet") is None
        coll.store.close()
    finally:
        a.close()
        b.close()


def test_collector_derives_spec_accept_rate(tmp_path):
    """Speculative counters collapse into a per-replica ``spec_accept_rate``
    over each scrape window's counter deltas — and a window with no new
    drafts reads 0.0 instead of dividing by zero or replaying stale state."""
    a = _ScrapeTarget()
    try:
        a.metrics_text = _serve_metrics_text(finished_length=1, spec=(100, 40))
        coll = FleetCollector(
            lambda: {"r0": ("127.0.0.1", a.port)},
            persist_path=str(tmp_path / "f.jsonl"),
        )
        coll.scrape_once(now=1000.0)
        assert coll.store.latest("r0", "spec_accept_rate")[1] == pytest.approx(0.4)
        # next window: +100 drafted, +50 accepted -> 0.5 for the window
        a.metrics_text = _serve_metrics_text(finished_length=1, spec=(200, 90))
        coll.scrape_once(now=1002.0)
        assert coll.store.latest("r0", "spec_accept_rate")[1] == pytest.approx(0.5)
        # idle window: counters unchanged, rate is 0, no blow-up
        coll.scrape_once(now=1004.0)
        assert coll.store.latest("r0", "spec_accept_rate")[1] == 0.0
        coll.store.close()
    finally:
        a.close()


def test_collector_tails_trainer_jsonl_with_torn_tail(tmp_path):
    """The trainer's metrics.jsonl joins the store by tailing: complete new
    lines land each round, a torn tail is deferred to the next round, and
    records keep their own _time."""
    path = str(tmp_path / "metrics.jsonl")
    with open(path, "w") as fh:
        fh.write(json.dumps({"loss": 2.5, "mfu": 0.31, "_step": 1, "_time": 50.0}) + "\n")
        fh.write('{"loss": 2.4, "_step": 2')  # torn: writer mid-line
    coll = FleetCollector(lambda: {}, jsonl_sources={"train": path})
    coll.scrape_once(now=1000.0)
    assert [v for _, v in coll.store.samples("train", "loss")] == [2.5]
    assert coll.store.latest("train", "mfu") == (50.0, 0.31)
    with open(path, "a") as fh:
        fh.write(', "_time": 51.0}\n')  # the torn line completes
    coll.scrape_once(now=1001.0)
    assert [v for _, v in coll.store.samples("train", "loss")] == [2.5, 2.4]


# -- the SLO engine -----------------------------------------------------------


def test_slo_burn_alert_fires_and_clears():
    """Google-SRE shape on a synthetic availability series: an outage deep
    enough to burn both windows fires once; recovery of the SHORT window
    clears it (the long window still remembers the outage — that must not
    hold the alert open).  Transitions land in the store and the flight
    recorder."""
    from relora_tpu.obs.flight import default_recorder

    store = SeriesStore()
    slo = SLO(
        name="availability", series="up", threshold=1.0, bad_when="lt",
        objective=0.9, windows=((30.0, 5.0, 2.0),),
    )
    engine = SLOEngine([slo])
    flight_before = len(default_recorder().events())
    transitions = []
    for i in range(60):
        t = 1000.0 + i
        up = 0.0 if 20 <= i < 35 else 1.0  # 15s outage
        store.add_samples("r0", {"up": up}, t=t, persist=False)
        for tr in engine.evaluate(store, now=t):
            # a returned dict IS a transition; its post-transition state
            # ("firing" / "ok") tells which edge it was
            transitions.append((i, "fire" if tr["state"] == "firing" else "clear"))
    states = [s for _, s in transitions]
    assert states == ["fire", "clear"]
    fire_i = transitions[0][0]
    clear_i = transitions[1][0]
    assert 20 <= fire_i < 35  # fired during the outage
    assert clear_i >= 35  # cleared only after recovery
    stored = store.events(kinds=("slo_burn_alert",))
    assert [e["state"] for e in stored] == ["fire", "clear"]
    assert stored[0]["burn_long"] >= 2.0 and stored[0]["burn_short"] >= 2.0
    flight = default_recorder().events()[flight_before:]
    assert [e["name"] for e in flight if e.get("name") == "slo_burn_alert"]
    assert engine.active_alerts() == []
    assert engine.status()["history"][0]["state"] == "cleared"


def test_slo_engine_anomaly_parity_with_loss_spike_detector():
    """The SLO engine's anomaly path IS LossSpikeDetector per (source,
    series): on an identical loss series both fire at the same index, and
    the engine emits a ``series_anomaly`` event with the detector's median
    context."""
    from relora_tpu.train.resilience import LossSpikeDetector

    series = [2.0 + 0.01 * (i % 5) for i in range(40)]
    for i in range(40, 44):
        series.append(9.0)  # sustained spike: fires after patience=3

    det = LossSpikeDetector(threshold=4.0, window=16, min_history=8, patience=3)
    direct_fire = None
    for i, v in enumerate(series):
        if det.update(i, v) is not None:
            direct_fire = i
            break
    assert direct_fire is not None

    store = SeriesStore()
    spec = AnomalySpec(
        series="loss", source="train", threshold=4.0, window=16,
        min_history=8, patience=3,
    )
    engine = SLOEngine([], anomalies=[spec])
    engine_fire = None
    for i, v in enumerate(series):
        store.add_samples("train", {"loss": v}, t=1000.0 + i, persist=False)
        fired = engine.evaluate(store, now=1000.0 + i)
        if fired and engine_fire is None:
            engine_fire = i
            detail = fired[0]
    assert engine_fire == direct_fire
    events = store.events(kinds=("series_anomaly",))
    assert events and events[0]["series"] == "loss"
    assert events[0]["median"] == pytest.approx(
        sorted(series[:16])[8], abs=0.1
    ) or events[0]["median"] < 3.0  # median context from the detector


# -- acceptance: cross-process trace joining ----------------------------------


@pytest.mark.serve
def test_merged_trace_one_tree_per_request(tmp_path, monkeypatch):
    """A single request through the router produces, after merging the
    router's and replicas' span JSONLs, ONE tree per request id holding the
    router's ``route`` span, a replica's ``request`` span, and spans
    recorded on the replica's model thread — all under the request-id trace
    id (the PR's pinned acceptance criterion)."""
    import asyncio

    from relora_tpu.serve.router import Router
    from relora_tpu.serve.supervisor import ReplicaSupervisor

    trace_dir = tmp_path / "traces"
    trace_dir.mkdir()
    # children inherit os.environ; the in-process Router reads it at
    # construction — set it before either exists
    monkeypatch.setenv("RELORA_TPU_TRACE_DIR", str(trace_dir))

    sup = ReplicaSupervisor(
        [
            sys.executable, os.path.join(ROOT, "serve.py"),
            "--model_config", "llama_9m", "--random-init",
            "--max-batch", "4", "--max-queue", "16", "--no-warmup",
        ],
        2,
        str(tmp_path / "fleet"),
        backoff_base_s=0.1, backoff_jitter=0.0, poll_interval_s=0.05,
    )
    router = Router(
        sup.endpoints, port=0, probe_interval_s=0.1,
        retry_backoff_s=0.02, failure_threshold=2, cooldown_s=0.2,
    )
    rt = threading.Thread(target=lambda: asyncio.run(router.serve_forever()), daemon=True)
    sup.start()
    rt.start()
    rids = []
    try:
        assert router.started.wait(10)
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            if sum(st.healthy for st in router.replicas.values()) >= 2:
                break
            time.sleep(0.05)
        else:
            pytest.fail("fleet never became healthy")

        import http.client

        for i in range(3):  # a few requests so both replicas likely serve
            conn = http.client.HTTPConnection("127.0.0.1", router.port, timeout=60)
            conn.request(
                "POST", "/v1/generate",
                json.dumps({"prompt": [1, 2, 3], "max_new_tokens": 4}),
                {"Content-Type": "application/json"},
            )
            resp = conn.getresponse()
            assert resp.status == 200
            rid = resp.getheader("X-Request-Id")
            assert rid
            rids.append(rid)
            resp.read()
            conn.close()
    finally:
        router.begin_shutdown()
        rt.join(10)
        sup.stop()  # SIGTERM -> replicas drain, flushing their span sinks

    stream_files = sorted(str(p) for p in trace_dir.glob("*_spans_*.jsonl"))
    router_files = [p for p in stream_files if "router_spans" in p]
    serve_files = [p for p in stream_files if "serve_spans" in p]
    assert len(router_files) == 1 and len(serve_files) >= 2

    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import trace_report
    finally:
        sys.path.pop(0)
    streams = []
    for path in stream_files:
        spans, events, _ = trace_report.load(path)
        streams.append((os.path.basename(path), spans, events))
    spans, events = trace_report.merge_streams(streams)

    for rid in rids:
        tree = [s for s in spans if s.get("trace_id") == rid]
        services = {s["service"] for s in tree}
        assert services == {"router", "serve"}, (rid, services)
        names = {(s["service"], s["name"]) for s in tree}
        assert ("router", "route") in names
        assert ("serve", "request") in names
        model_spans = [
            s for s in tree if s["service"] == "serve" and s["thread"] == "serve-model"
        ]
        assert model_spans, f"no model-thread spans under {rid}"
        # wall-clock realignment: the router's root must start before any
        # replica work on the same request
        route = next(s for s in tree if s["name"] == "route")
        assert all(s["t_start"] >= route["t_start"] - 0.05 for s in tree)

    # the merged Chrome export groups spans by source process
    chrome_path = str(tmp_path / "merged_chrome.json")
    rc = trace_report.main([*stream_files, "--chrome", chrome_path])
    assert rc == 0
    chrome = json.load(open(chrome_path))["traceEvents"]
    proc_names = {e["args"]["name"] for e in chrome if e.get("name") == "process_name"}
    assert len(proc_names) == len(stream_files)
