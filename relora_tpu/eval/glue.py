"""GLUE fine-tuning of pretrained checkpoints — the run_glue.py engine.

Capability parity with the reference's HF-Trainer-based harness
(run_glue.py:209-623): task→sentence-keys map (:57-67), tokenize+pad,
fine-tune ``LlamaForSequenceClassification`` (regression when the task is
stsb), and compute the standard GLUE metrics.  The reference delegates the
loop to transformers.Trainer and the metrics to ``evaluate``; here the loop
is a small jitted train step (same machinery as pretraining) and the metrics
are computed directly (accuracy / F1 / Matthews / Pearson / Spearman) so no
extra dependencies are needed.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Callable, Dict, Iterator, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from relora_tpu.config.model import ModelConfig
from relora_tpu.core.relora import LoraSpec
from relora_tpu.models.llama import LlamaForSequenceClassification
from relora_tpu.models.params_util import init_params
from relora_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# parity: run_glue.py:57-67
TASK_TO_KEYS: Dict[str, Tuple[str, Optional[str]]] = {
    "cola": ("sentence", None),
    "mnli": ("premise", "hypothesis"),
    "mrpc": ("sentence1", "sentence2"),
    "qnli": ("question", "sentence"),
    "qqp": ("question1", "question2"),
    "rte": ("sentence1", "sentence2"),
    "sst2": ("sentence", None),
    "stsb": ("sentence1", "sentence2"),
    "wnli": ("sentence1", "sentence2"),
}

TASK_NUM_LABELS = {
    "cola": 2, "mnli": 3, "mrpc": 2, "qnli": 2, "qqp": 2,
    "rte": 2, "sst2": 2, "stsb": 1, "wnli": 2,
}


# ---------------------------------------------------------------------------
# metrics (no `evaluate` dependency)
# ---------------------------------------------------------------------------


def accuracy(preds: np.ndarray, labels: np.ndarray) -> float:
    return float((preds == labels).mean())


def f1_binary(preds: np.ndarray, labels: np.ndarray) -> float:
    tp = float(((preds == 1) & (labels == 1)).sum())
    fp = float(((preds == 1) & (labels == 0)).sum())
    fn = float(((preds == 0) & (labels == 1)).sum())
    if tp == 0:
        return 0.0
    precision = tp / (tp + fp)
    recall = tp / (tp + fn)
    return 2 * precision * recall / (precision + recall)


def matthews_corr(preds: np.ndarray, labels: np.ndarray) -> float:
    tp = float(((preds == 1) & (labels == 1)).sum())
    tn = float(((preds == 0) & (labels == 0)).sum())
    fp = float(((preds == 1) & (labels == 0)).sum())
    fn = float(((preds == 0) & (labels == 1)).sum())
    denom = np.sqrt((tp + fp) * (tp + fn) * (tn + fp) * (tn + fn))
    return float((tp * tn - fp * fn) / denom) if denom > 0 else 0.0


def pearson_corr(a: np.ndarray, b: np.ndarray) -> float:
    a = a.astype(np.float64) - a.mean()
    b = b.astype(np.float64) - b.mean()
    denom = np.sqrt((a * a).sum() * (b * b).sum())
    return float((a * b).sum() / denom) if denom > 0 else 0.0


def spearman_corr(a: np.ndarray, b: np.ndarray) -> float:
    rank = lambda x: np.argsort(np.argsort(x)).astype(np.float64)
    return pearson_corr(rank(a), rank(b))


def task_metrics(
    task: str, preds: np.ndarray, labels: np.ndarray, num_labels: Optional[int] = None
) -> Dict[str, float]:
    """The metric set evaluate.load("glue", task) would report
    (parity: run_glue.py:496-501).  ``num_labels == 1`` marks a custom
    regression task (float-typed labels, the reference's dtype inference):
    those report pearson/spearman like stsb."""
    if task == "stsb" or num_labels == 1:
        return {
            "pearson": pearson_corr(preds, labels),
            "spearmanr": spearman_corr(preds, labels),
        }
    if task == "cola":
        return {"matthews_correlation": matthews_corr(preds, labels)}
    out = {"accuracy": accuracy(preds, labels)}
    # pair tasks report accuracy + F1 (GLUE's mrpc/qqp set; the local
    # pair-shaped surrogates locpair/locnsp follow the same convention)
    if task in ("mrpc", "qqp", "locpair", "locnsp"):
        out["f1"] = f1_binary(preds, labels)
    return out


# ---------------------------------------------------------------------------
# fine-tuning engine
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class GlueConfig:
    task: str = "sst2"
    lr: float = 2e-5
    batch_size: int = 32
    num_epochs: int = 3
    max_length: int = 128
    weight_decay: float = 0.01
    warmup_ratio: float = 0.06
    seed: int = 0
    use_lora: bool = False
    lora_r: int = 8
    # custom (non-GLUE) datasets: explicit label count; None = from the task
    # table (parity: num_labels inference, run_glue.py:392-411)
    num_labels: Optional[int] = None


def classification_loss(logits: jax.Array, labels: jax.Array, num_labels: int) -> jax.Array:
    """CE for classification, MSE for regression (parity:
    modeling_llama.py: regression when num_labels == 1)."""
    if num_labels == 1:
        return jnp.mean(jnp.square(logits[:, 0] - labels.astype(jnp.float32)))
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=-1))


def finetune(
    model_cfg: ModelConfig,
    gcfg: GlueConfig,
    train_batches: Callable[[], Iterator[Tuple[np.ndarray, np.ndarray]]],
    eval_batches: Callable[[], Iterator[Tuple[np.ndarray, np.ndarray]]],
    steps_per_epoch: int,
    pad_token_id: int = 0,
    pretrained_backbone=None,
    predict_batches: Optional[Callable[[], Iterator[np.ndarray]]] = None,
    do_train: bool = True,
    do_eval: bool = True,
):
    """Fine-tune and return ``(metrics, predictions)``.

    ``train_batches``/``eval_batches`` yield (input_ids, labels) numpy pairs;
    ``predict_batches`` (if given) yields unlabeled input_ids and produces
    test-set predictions (parity: do_predict, run_glue.py:594-614).
    ``pretrained_backbone`` is a causal-LM param tree (ours) whose base
    weights are grafted under the classifier's ``model`` subtree — how a
    ReLoRA-pretrained checkpoint is evaluated downstream.
    """
    num_labels = gcfg.num_labels or TASK_NUM_LABELS[gcfg.task]
    lora = LoraSpec(r=gcfg.lora_r, alpha=2 * gcfg.lora_r, dropout=0.1) if gcfg.use_lora else None
    model = LlamaForSequenceClassification(
        model_cfg,
        num_labels=num_labels,
        pad_token_id=pad_token_id,
        lora=lora,
        dtype=jnp.float32,
    )
    sample = jnp.zeros((2, 8), jnp.int32)
    params = init_params(model, jax.random.PRNGKey(gcfg.seed), sample)

    if pretrained_backbone is not None:
        from relora_tpu.models.hf_compat import graft_base_weights

        backbone = {k: v for k, v in pretrained_backbone.items() if k != "lm_head"}
        params = {**params, "model": graft_base_weights(params["model"], backbone)}
        logger.info("grafted pretrained backbone into the classifier")

    total_steps = steps_per_epoch * gcfg.num_epochs
    schedule = optax.linear_schedule(0.0, gcfg.lr, max(1, int(total_steps * gcfg.warmup_ratio)))
    decay = optax.linear_schedule(gcfg.lr, 0.0, max(1, total_steps - int(total_steps * gcfg.warmup_ratio)))
    lr_fn = optax.join_schedules([schedule, decay], [int(total_steps * gcfg.warmup_ratio)])
    tx = optax.adamw(lr_fn, weight_decay=gcfg.weight_decay)
    opt_state = tx.init(params)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def train_step(params, opt_state, ids, labels, rng):
        def loss_fn(p):
            logits = model.apply({"params": p}, ids, deterministic=False, rngs={"dropout": rng})
            return classification_loss(logits, labels, num_labels)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    @jax.jit
    def predict(params, ids):
        return model.apply({"params": params}, ids, deterministic=True)

    rng = jax.random.PRNGKey(gcfg.seed + 1)
    step = 0
    if do_train:
        for epoch in range(gcfg.num_epochs):
            for ids, labels in train_batches():
                params, opt_state, loss = train_step(
                    params, opt_state, jnp.asarray(ids), jnp.asarray(labels),
                    jax.random.fold_in(rng, step),
                )
                step += 1
            logger.info(f"epoch {epoch}: last train loss {float(loss):.4f}")

    def logits_to_preds(logits):
        if num_labels == 1:
            return np.asarray(logits)[:, 0]
        return np.argmax(np.asarray(logits), axis=-1)

    metrics: Dict[str, float] = {}
    if do_eval:
        preds, labels_all = [], []
        for ids, labels in eval_batches():
            preds.append(logits_to_preds(predict(params, jnp.asarray(ids))))
            labels_all.append(labels)
        preds = np.concatenate(preds)
        labels_all = np.concatenate(labels_all)
        metrics = task_metrics(gcfg.task, preds, labels_all, num_labels=num_labels)
        logger.info(f"{gcfg.task}: {metrics}")

    predictions = None
    if predict_batches is not None:
        predictions = np.concatenate(
            [logits_to_preds(predict(params, jnp.asarray(ids))) for ids in predict_batches()]
        )
    return metrics, predictions
