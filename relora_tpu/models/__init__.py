from relora_tpu.models.lora import LoRALinear
from relora_tpu.models.llama import LlamaForCausalLM
