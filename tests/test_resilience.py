"""Fault-tolerance subsystem: preemption-safe checkpointing, save retries
with integrity fallback, and automatic loss-spike rollback.

Every failure mode here is injected through relora_tpu.utils.faults, so the
recovery paths run deterministically under tier-1 instead of being
discovered in production.  The acceptance tests mirror the operational
drills in docs/operations.md: SIGTERM mid-run -> emergency checkpoint ->
bit-exact resume, and a poisoned-data loss spike -> rollback + automatic
skip_batches extension -> run completes without manual intervention.
"""

import json
import math
import os
import signal
import time

import jax
import numpy as np
import pytest

from relora_tpu.config.model import ModelConfig
from relora_tpu.config.training import TrainingConfig
from relora_tpu.train import checkpoint as ckpt
from relora_tpu.train.resilience import LossSpikeDetector, PreemptionGuard
from relora_tpu.utils import faults

TINY = ModelConfig(
    vocab_size=128,
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=2,
    num_attention_heads=2,
    max_sequence_length=32,
)


@pytest.fixture(autouse=True)
def _disarm_faults():
    faults.reset()
    yield
    faults.reset()


# ---------------------------------------------------------------------------
# LossSpikeDetector


def feed(det, losses, start=1):
    events = []
    for i, loss in enumerate(losses):
        ev = det.update(start + i, loss)
        if ev is not None:
            events.append(ev)
    return events


def test_detector_flags_sustained_spike():
    det = LossSpikeDetector(threshold=4.0, min_history=8, patience=3)
    base = [2.0 + 0.01 * ((i * 7) % 5) for i in range(20)]
    events = feed(det, base + [9.0, 9.5, 9.2])
    assert len(events) == 1
    ev = events[0]
    assert ev.first_step == 21 and ev.last_step == 23
    assert ev.loss == 9.2
    assert 1.9 < ev.median < 2.1


def test_detector_tolerates_single_blip_and_keeps_baseline_clean():
    det = LossSpikeDetector(threshold=4.0, min_history=8, patience=3)
    base = [2.0 + 0.01 * (i % 4) for i in range(16)]
    # isolated outliers never reach patience; they also must not enter the
    # window and drag the median up
    assert feed(det, base + [9.0, 2.0, 9.0, 2.01, 9.0, 2.02]) == []
    assert det.last_median < 2.2


def test_detector_nan_counts_as_outlier():
    det = LossSpikeDetector(threshold=4.0, min_history=4, patience=2)
    events = feed(det, [2.0, 2.01, 2.0, 2.02, 2.0, float("nan"), float("inf")])
    assert len(events) == 1
    assert events[0].first_step == 6 and events[0].last_step == 7
    assert not math.isfinite(events[0].loss)


def test_detector_reset_streak_keeps_window():
    det = LossSpikeDetector(threshold=4.0, min_history=4, patience=2)
    feed(det, [2.0, 2.01, 2.0, 2.02, 2.0])
    assert det.update(6, 9.0) is None  # streak 1
    det.reset_streak()
    assert det.update(7, 9.0) is None  # streak restarts at 1, not 2
    assert det.last_median < 2.1  # baseline survived the reset


def test_detector_validation():
    with pytest.raises(ValueError):
        LossSpikeDetector(threshold=0.0)
    with pytest.raises(ValueError):
        LossSpikeDetector(threshold=1.0, patience=0)
    with pytest.raises(ValueError):
        LossSpikeDetector(threshold=1.0, min_history=2)


def test_training_config_validates_spike_fields(tmp_path):
    kw = dict(dataset_path="/synthetic", save_dir=str(tmp_path))
    with pytest.raises(ValueError):
        TrainingConfig(**kw, spike_threshold=-1.0).finalize()
    with pytest.raises(ValueError):
        TrainingConfig(**kw, spike_threshold=3.0, spike_min_history=2).finalize()
    with pytest.raises(ValueError):
        TrainingConfig(**kw, save_retries=-1).finalize()


# ---------------------------------------------------------------------------
# PreemptionGuard


def test_preemption_guard_flags_sigterm_and_restores_handlers():
    prev = signal.getsignal(signal.SIGTERM)
    with PreemptionGuard() as guard:
        assert not guard.requested
        os.kill(os.getpid(), signal.SIGTERM)
        time.sleep(0.05)  # let the interpreter run the Python-level handler
        assert guard.requested and guard.signum == signal.SIGTERM
    assert signal.getsignal(signal.SIGTERM) == prev


def test_preemption_guard_second_sigint_escalates():
    with PreemptionGuard() as guard:
        os.kill(os.getpid(), signal.SIGINT)
        time.sleep(0.05)
        assert guard.requested
        with pytest.raises(KeyboardInterrupt):
            os.kill(os.getpid(), signal.SIGINT)
            time.sleep(0.5)


def test_preemption_guard_disabled_is_inert():
    prev = signal.getsignal(signal.SIGTERM)
    with PreemptionGuard(enabled=False):
        assert signal.getsignal(signal.SIGTERM) == prev


# ---------------------------------------------------------------------------
# faults harness


@pytest.mark.faults
def test_faults_env_parsing():
    faults.configure_from_env("ckpt_save:times=2;loss:steps=3-5,delta=1.5;preempt:at=7")
    assert faults.active("ckpt_save") and faults.active("preempt")
    assert faults.perturb("loss", 1.0, step=4) == 2.5
    assert faults.perturb("loss", 1.0, step=6) == 1.0
    faults.configure("nan_grads", steps=[9, 2])
    assert faults.nan_grad_steps() == (2, 9)


@pytest.mark.faults
def test_faults_maybe_fail_counts_down():
    faults.configure("ckpt_save", times=2)
    for _ in range(2):
        with pytest.raises(OSError):
            faults.maybe_fail("ckpt_save")
    faults.maybe_fail("ckpt_save")  # third call passes
    assert faults.fire_count("ckpt_save") == 2


# ---------------------------------------------------------------------------
# save retries + integrity fallback (checkpoint layer)


def _make_state(devices):
    from jax.sharding import NamedSharding, PartitionSpec as P

    from relora_tpu.parallel.mesh import MeshSpec, make_mesh
    from relora_tpu.train.state import TrainState

    mesh = make_mesh(MeshSpec(data=1, fsdp=8))
    sharding = NamedSharding(mesh, P("fsdp", None))
    params = {
        "layer": {
            "kernel": jax.device_put(
                jax.numpy.arange(64.0, dtype=jax.numpy.float32).reshape(8, 8),
                sharding,
            ),
            "bias": jax.numpy.ones((8,), jax.numpy.float32),
        }
    }
    opt_state = {"mu": jax.tree_util.tree_map(jax.numpy.zeros_like, params)}
    return TrainState.create(params, opt_state)


@pytest.mark.faults
def test_save_retry_recovers_from_transient_io_error(tmp_path, devices):
    state = _make_state(devices)
    faults.configure("ckpt_save", times=2)
    path = ckpt.save_checkpoint(
        str(tmp_path), 4, state, {"update_step": 4}, retries=3, retry_backoff=0.01
    )
    ckpt.wait_for_save()
    assert faults.fire_count("ckpt_save") == 2  # failed twice, then stuck
    ok, reason = ckpt.verify_checkpoint(path)
    assert ok, reason
    ts, found = ckpt.get_last_checkpoint(str(tmp_path))
    assert found == path and ts["update_step"] == 4


@pytest.mark.faults
def test_save_retries_exhausted_falls_back_to_previous(tmp_path, devices):
    state = _make_state(devices)
    ckpt.save_checkpoint(str(tmp_path), 3, state, {"update_step": 3})
    ckpt.wait_for_save()

    faults.configure("ckpt_save", times=10)
    with pytest.raises(OSError):
        ckpt.save_checkpoint(
            str(tmp_path), 6, state, {"update_step": 6}, retries=1, retry_backoff=0.01
        )
    faults.reset()
    # the failed save never becomes a resume candidate
    ts, path = ckpt.get_last_checkpoint(str(tmp_path))
    assert ts["update_step"] == 3 and path.endswith("model_3")


# ---------------------------------------------------------------------------
# Trainer-level acceptance drills (real training on the tiny model)


class FakeTokens:
    def __init__(self, n=512, seq=16, vocab=128, seed=0):
        rs = np.random.RandomState(seed)
        rows = []
        for _ in range(n):
            start = rs.randint(vocab)
            rows.append([(start + j) % vocab for j in range(seq)])
        self.arr = np.asarray(rows, dtype=np.int32)

    def __len__(self):
        return len(self.arr)

    def __getitem__(self, idx):
        return {"input_ids": self.arr[idx]}


def make_cfg(tmp_path, **kw):
    base = dict(
        dataset_path="/synthetic",
        batch_size=4,
        total_batch_size=8,
        max_length=16,
        lr=5e-3,
        scheduler="cosine_restarts",
        warmup_steps=2,
        restart_warmup_steps=2,
        num_training_steps=16,
        cycle_length=8,
        relora=8,
        use_peft=True,
        lora_r=4,
        save_dir=str(tmp_path / "ckpt"),
        save_every=8,
        eval_every=100,
        seed=0,
        dp_size=2,
    )
    base.update(kw)
    return TrainingConfig(**base).finalize()


def make_train_factory(cfg, trainer, data):
    from relora_tpu.data.hf_pipeline import TokenBatchIterator

    def train_factory():
        return iter(
            TokenBatchIterator(
                data,
                microbatch=cfg.batch_size * trainer.n_batch_shards,
                grad_accum=trainer.grad_accum,
                skip_updates=trainer.update_step,
            )
        )

    return train_factory


def read_events(save_dir):
    events = []
    with open(os.path.join(save_dir, "metrics.jsonl")) as f:
        for line in f:
            rec = json.loads(line)
            if "_event" in rec:
                events.append(rec)
    return events


@pytest.mark.faults
def test_sigterm_emergency_checkpoint_and_bitexact_resume(tmp_path):
    """SIGTERM mid-loop commits an emergency checkpoint; a resumed run
    continues with bit-exact counters (incl. the NaN-skip counter) and
    reaches bit-exact final params vs an uninterrupted run."""
    from relora_tpu.train.trainer import Trainer

    data = FakeTokens(n=1024)

    # reference: uninterrupted 16 steps with one injected NaN-grad update
    # (nan_abort_fraction raised: 1 skip of 16 would trip the 5% abort)
    faults.configure("nan_grads", steps=[2])
    cfg_a = make_cfg(tmp_path / "a", save_every=100, nan_abort_fraction=0.5)
    tr_a = Trainer(cfg_a, model_cfg=TINY)
    res_a = tr_a.fit(make_train_factory(cfg_a, tr_a, data)(), None)
    assert res_a["n_skipped"] == 1 and not res_a["preempted"]

    # interrupted run: a real SIGTERM delivered at the update-5 boundary
    faults.reset()
    faults.configure("nan_grads", steps=[2])
    faults.configure("preempt", at=5)
    cfg_b = make_cfg(tmp_path / "b", save_every=100, nan_abort_fraction=0.5)
    tr_b1 = Trainer(cfg_b, model_cfg=TINY)
    res_b1 = tr_b1.fit(make_train_factory(cfg_b, tr_b1, data)(), None)
    assert res_b1["preempted"] is True
    stop = res_b1["update_step"]
    # signal delivery lands at the armed boundary or (rarely) one later
    assert 5 <= stop <= 6

    emergency = os.path.join(cfg_b.save_dir, f"model_{stop}")
    assert os.path.isdir(os.path.join(emergency, ckpt.STATE_SUBDIR))
    ok, reason = ckpt.verify_checkpoint(emergency, check_arrays=True)
    assert ok, reason
    kinds = [e["_event"] for e in read_events(cfg_b.save_dir)]
    assert "preemption" in kinds and "emergency_checkpoint" in kinds

    # resume: counters restore bit-exact, run finishes identically to A
    faults.reset()
    faults.configure("nan_grads", steps=[2])  # same compiled step as A/B1
    cfg_b2 = make_cfg(
        tmp_path / "b", save_every=100, autoresume=True, nan_abort_fraction=0.5
    )
    tr_b2 = Trainer(cfg_b2, model_cfg=TINY)
    assert tr_b2.update_step == stop
    assert int(tr_b2.state.n_skipped) == 1  # NaN counter survived
    assert tr_b2.tokens_seen == stop * cfg_b.total_batch_size * 16
    res_b2 = tr_b2.fit(make_train_factory(cfg_b2, tr_b2, data)(), None)

    assert res_b2["update_step"] == res_a["update_step"] == 16
    assert res_b2["tokens_seen"] == res_a["tokens_seen"]
    assert res_b2["n_skipped"] == res_a["n_skipped"]
    for la, lb in zip(
        jax.tree_util.tree_leaves(tr_a.state.params),
        jax.tree_util.tree_leaves(tr_b2.state.params),
    ):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


@pytest.mark.faults
def test_loss_spike_rolls_back_and_auto_extends_skip(tmp_path):
    """An injected loss spike triggers automatic rollback to the last good
    checkpoint and auto-extends skip_batches over the poisoned window; the
    run then completes WITHOUT any manual skip_batches."""
    from relora_tpu.train.trainer import Trainer

    data = FakeTokens(n=1024)
    faults.configure("loss", steps=range(9, 12), delta=8.0)
    cfg = make_cfg(
        tmp_path,
        num_training_steps=16,
        save_every=4,
        relora=None,
        use_peft=False,
        scheduler="cosine",
        cycle_length=16,
        spike_threshold=4.0,
        spike_window=8,
        spike_min_history=4,
        spike_patience=3,
    )
    trainer = Trainer(cfg, model_cfg=TINY)
    factory = make_train_factory(cfg, trainer, data)
    result = trainer.fit(factory(), None, train_iter_factory=factory)

    assert result["update_step"] == 16 and not result["aborted"]
    assert result["n_rollbacks"] == 1
    # logged window [9, 11] maps to pre-increment skip indices 8..11(+margin)
    assert {8, 9, 10, 11} <= cfg.skip_batches

    events = read_events(cfg.save_dir)
    by_kind = {}
    for e in events:
        by_kind.setdefault(e["_event"], []).append(e)
    assert by_kind["loss_spike"][0]["first_step"] == 9
    assert by_kind["loss_spike"][0]["last_step"] == 11
    assert by_kind["rollback"][0]["target"].endswith("model_8")
    skipped_at = [e["_step"] for e in by_kind["batch_skipped"]]
    assert skipped_at == [8, 9, 10, 11]

    # recovery state survives a process restart: the final checkpoint records
    # the blacklist and the rollback count
    with open(os.path.join(cfg.save_dir, "model_16", ckpt.TRAINING_STATE_FILE)) as f:
        ts = json.load(f)
    assert ts["n_spike_rollbacks"] == 1
    assert set(ts["skip_batches"]) >= {8, 9, 10, 11}


def test_resume_with_changed_batch_size_rejected(tmp_path):
    """The data rewind assumes a fixed batch size; resuming with a different
    one must fail loudly instead of silently de-aligning the stream."""
    from relora_tpu.train.trainer import Trainer

    data = FakeTokens(n=512)
    cfg = make_cfg(tmp_path, num_training_steps=8, save_every=8)
    trainer = Trainer(cfg, model_cfg=TINY)
    trainer.fit(make_train_factory(cfg, trainer, data)(), None)

    cfg2 = make_cfg(tmp_path, num_training_steps=16, batch_size=2, autoresume=True)
    with pytest.raises(RuntimeError, match="batch size"):
        Trainer(cfg2, model_cfg=TINY)


@pytest.mark.faults
def test_sigterm_flight_dump_and_span_tree(tmp_path, monkeypatch):
    """The crash flight recorder drill: a real SIGTERM mid-loop makes the
    PreemptionGuard handler dump the span ring buffer, and the dump holds a
    complete, well-nested trace of the update loop that trace_report can
    render."""
    import glob
    import subprocess
    import sys

    from relora_tpu.obs import flight
    from relora_tpu.train.trainer import Trainer

    monkeypatch.setenv("RELORA_TPU_FLIGHT_DIR", str(tmp_path))
    # the recorder is process-wide: start the drill from a clean buffer so
    # spans from earlier tests in this process can't leak into the dump
    flight.default_recorder().clear()

    data = FakeTokens(n=512)
    cfg = make_cfg(tmp_path, num_training_steps=16, save_every=100)
    trainer = Trainer(cfg, model_cfg=TINY)
    faults.configure("preempt", at=4)
    res = trainer.fit(make_train_factory(cfg, trainer, data)(), None)
    assert res["preempted"] is True

    dumps = glob.glob(str(tmp_path / f"flight_sigterm_{os.getpid()}.json"))
    assert len(dumps) == 1, dumps
    with open(dumps[0]) as f:
        payload = json.load(f)
    assert payload["reason"] == "sigterm"
    assert payload["pid"] == os.getpid()

    spans = payload["spans"]
    train_spans = [s for s in spans if s["service"] == "train"]
    assert train_spans, "no trainer spans in the dump"
    # one training run = one trace id across every span
    assert len({s["trace_id"] for s in train_spans}) == 1
    steps = [s for s in train_spans if s["name"] == "update_step"]
    assert len(steps) >= 3  # preempted at update 4
    by_parent = {}
    for s in train_spans:
        by_parent.setdefault(s["parent_id"], []).append(s["name"])
    # every completed update_step parents its phases
    last = steps[-1]
    assert {"data_fetch", "dispatch"} <= set(by_parent[last["span_id"]])
    assert any("metric_pull" in kids for kids in by_parent.values())

    # the report tool renders the dump end to end
    out = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(os.path.dirname(__file__)),
                      "tools", "trace_report.py"),
         dumps[0]],
        capture_output=True, text=True, check=True,
    ).stdout
    assert "reason=sigterm" in out
    assert "update_step" in out and "dispatch" in out

    flight.default_recorder().clear()
